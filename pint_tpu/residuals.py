"""Residuals: phase and time residuals, chi-square.

Reference: src/pint/residuals.py (Residuals.calc_phase_resids,
calc_time_resids, rms_weighted, chi2). Phase arithmetic stays in
double-double until the fractional part is extracted; everything after
(means, chi2) is f64.
"""

from __future__ import annotations

import warnings
from typing import Optional

import numpy as np

__all__ = ["Residuals"]


class Residuals:
    """Timing residuals of `toas` under `model`.

    track_mode: "nearest" assigns each TOA to the nearest integer pulse;
    "use_pulse_numbers" uses -pn flags (reference: track_mode).
    """

    def __init__(self, toas, model, track_mode: Optional[str] = None,
                 subtract_mean: Optional[bool] = None,
                 use_weighted_mean: bool = True):
        self.toas = toas
        self.model = model
        if track_mode is None:
            track_mode = ("use_pulse_numbers"
                          if toas.get_pulse_numbers() is not None
                          else "nearest")
        self.track_mode = track_mode
        if subtract_mean is None:
            # with an explicit PhaseOffset the fitted PHOFF replaces
            # the implicit mean removal (reference: Residuals defaults
            # subtract_mean off when PHOFF is in the model — otherwise
            # the mean subtraction deletes exactly the signal PHOFF
            # measures and it always fits to zero)
            subtract_mean = "PhaseOffset" not in model.components
        self.subtract_mean = subtract_mean
        self.use_weighted_mean = use_weighted_mean
        self._phase_resids = None
        self._time_resids = None

    # -- lazy computation ---------------------------------------------

    def calc_phase_resids(self) -> np.ndarray:
        """Residual phase [turns], mean-subtracted (f64)."""
        ph = self.model.phase(self.toas, abs_phase=True)
        if self.track_mode == "use_pulse_numbers":
            pn = self.toas.get_pulse_numbers()
            if pn is None:
                raise ValueError("track_mode=use_pulse_numbers but no "
                                 "-pn flags on these TOAs")
            full = (np.asarray(ph.int) - pn) + np.asarray(ph.frac)
        elif self.track_mode == "nearest":
            full = np.asarray(ph.frac)
        else:
            raise ValueError(f"unknown track_mode {self.track_mode!r}")
        # per-TOA phase adjustments from tim-file PHASE commands
        # (flag -padd, turns; reference: Residuals applies padd in
        # calc_phase_resids — a phase command inserts whole/fractional
        # turns into the residual, not a time shift)
        padd = np.array(self.toas.get_flag_value("padd", 0.0, float))
        if np.any(padd != 0.0):
            full = full + padd
        if self.subtract_mean:
            full = full - self._mean(full)
        return full

    def _mean(self, x):
        if not self.use_weighted_mean:
            return x.mean()
        err = self.toas.get_errors()
        if np.any(err == 0):
            return x.mean()
        w = 1.0 / err ** 2
        return np.sum(x * w) / np.sum(w)

    @property
    def phase_resids(self):
        if self._phase_resids is None:
            self._phase_resids = self.calc_phase_resids()
        return self._phase_resids

    def calc_time_resids(self) -> np.ndarray:
        """Residuals in seconds: phase / F0 (reference uses the 'modelF0'
        calctype by default — same thing)."""
        return self.phase_resids / self.model.F0.value

    @property
    def time_resids(self):
        if self._time_resids is None:
            self._time_resids = self.calc_time_resids()
        return self._time_resids

    # -- summary stats -------------------------------------------------

    @property
    def resids_us(self):
        return self.time_resids * 1e6

    def rms_weighted(self) -> float:
        """Weighted RMS [s] (reference: Residuals.rms_weighted)."""
        err_s = self.toas.get_errors() * 1e-6
        if np.any(err_s == 0):
            return float(np.sqrt(np.mean(self.time_resids ** 2)))
        w = 1.0 / err_s ** 2
        r = self.time_resids
        wmean = np.sum(r * w) / np.sum(w)
        return float(np.sqrt(np.sum(w * (r - wmean) ** 2) / np.sum(w)))

    def rms(self) -> float:
        return float(np.sqrt(np.mean(self.time_resids ** 2)))

    @property
    def chi2(self) -> float:
        """chi2 of the residuals. With correlated-noise components this
        is the basis-marginalized GLS chi2 r^T C^-1 r (reference:
        Residuals.calc_chi2 defers to the GLS solve the same way);
        otherwise the white chi2 against scaled TOA errors."""
        if getattr(self.model, "has_correlated_errors", False):
            from pint_tpu.gls import gls_chi2

            return gls_chi2(self.model, self.toas,
                            resids=self.time_resids)
        err_s = self._scaled_errors_s()
        return float(np.sum((self.time_resids / err_s) ** 2))

    def _scaled_errors_s(self):
        scaled = None
        if hasattr(self.model, "scaled_toa_uncertainty"):
            try:
                scaled = self.model.scaled_toa_uncertainty(self.toas)
            except Exception:
                scaled = None
        if scaled is not None:
            return np.asarray(scaled)
        return self.toas.get_errors() * 1e-6

    def ecorr_average(self, use_noise_model: bool = True,
                      max_gap_days: float = 0.5) -> dict:
        """Epoch-averaged residuals (reference:
        Residuals.ecorr_average): weighted average of the residuals
        within each ECORR epoch (or, without an ECORR model /
        use_noise_model=False, within gap-separated observing epochs),
        the standard whitened view for plotting dense TOA sets.

        Returns dict of arrays over epochs: mjds (weighted-mean
        epoch), time_resids [s], errors [s] (1/sqrt(sum w) plus the
        epoch's fully-correlated ECORR variance), freqs (weighted
        mean), indices (list of TOA index arrays), n (counts)."""
        err_s = self._scaled_errors_s()
        if np.any(err_s == 0):
            raise ValueError(
                "ecorr_average needs nonzero TOA uncertainties "
                "(weighted averaging is undefined at zero error)")
        w = 1.0 / err_s ** 2
        mjds = np.asarray(self.toas.get_mjds())
        freqs = np.asarray(self.toas.get_freqs())
        r = self.time_resids

        seg = None
        if use_noise_model and hasattr(self.model,
                                       "noise_model_ecorr_segments"):
            seg = self.model.noise_model_ecorr_segments(self.toas)
            if seg is None and "EcorrNoise" in getattr(
                    self.model, "components", {}):
                warnings.warn(
                    "model has ECORR but its epochs overlap (dense-"
                    "basis fallback); epoch-averaged errors will NOT "
                    "include the correlated term", stacklevel=2)
        if seg is not None:
            eid, jvar, _ = seg
            eid = np.asarray(eid)
            jvar = np.asarray(jvar)
        else:
            # gap clustering on sorted MJDs — the same primitive the
            # ECORR quantization basis uses
            from pint_tpu.models.noise import quantization_buckets

            buckets = quantization_buckets(mjds, dt_days=max_gap_days,
                                           nmin=1)
            eid = np.empty(len(mjds), np.int64)
            for k, b in enumerate(buckets):
                eid[b] = k
            jvar = np.zeros(len(buckets))

        out = {"mjds": [], "time_resids": [], "errors": [],
               "freqs": [], "indices": [], "n": []}

        def emit(idx, evar):
            wk = w[idx]
            wsum = wk.sum()
            out["mjds"].append(np.sum(mjds[idx] * wk) / wsum)
            out["time_resids"].append(np.sum(r[idx] * wk) / wsum)
            out["errors"].append(np.sqrt(1.0 / wsum + evar))
            out["freqs"].append(np.sum(freqs[idx] * wk) / wsum)
            out["indices"].append(idx)
            out["n"].append(len(idx))

        no_epoch = len(jvar) - 1 if seg is not None else None
        for k in np.unique(eid):
            idx = np.flatnonzero(eid == k)
            if k == no_epoch:
                # TOAs outside every ECORR epoch are NOT jointly
                # correlated: they stay unaveraged (reference
                # behavior)
                for i in idx:
                    emit(np.array([i]), 0.0)
            else:
                emit(idx, float(jvar[k]))
        order = np.argsort(np.asarray(out["mjds"]))
        return {k: (np.asarray(v)[order] if k != "indices"
                    else [v[i] for i in order])
                for k, v in out.items()}

    @property
    def dof(self) -> int:
        return self.toas.ntoas - len(self.model.free_params) - 1

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof


_WIDEBAND_REEXPORTS = ("WidebandTOAResiduals", "CombinedResiduals",
                       "DMResiduals")


def __getattr__(name):
    """Reference-path re-exports: the reference exposes the wideband
    residual classes from pint.residuals; they live in pint_tpu.wideband
    (lazy here — a top-level import would be circular)."""
    if name in _WIDEBAND_REEXPORTS:
        from pint_tpu import wideband

        return getattr(wideband, name)
    raise AttributeError(name)


def __dir__():
    return sorted(list(globals()) + list(_WIDEBAND_REEXPORTS))
