"""Analysis utilities: F-test, DMX parsing/statistics, weighted stats.

Reference: src/pint/utils.py (FTest, dmxparse, weighted_mean,
split_prefixed_name, taylor_horner, taylor_horner_deriv — the
latter three live in
pint_tpu.models.parameter / pint_tpu.ops.taylor here and are
re-exported for API familiarity).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.models.parameter import split_prefixed_name  # noqa: F401
from pint_tpu.ops.taylor import (  # noqa: F401
    taylor_horner,
    taylor_horner_deriv,
)

__all__ = ["FTest", "weighted_mean", "dmxparse",
           "get_highest_density_range",
           "split_prefixed_name", "taylor_horner", "taylor_horner_deriv",
           "format_uncertainty", "dmx_ranges", "add_dmx_ranges",
           "wavex_setup", "dmwavex_setup",
           "akaike_information_criterion",
           "bayesian_information_criterion", "PosVel"]


def get_highest_density_range(mjds, ndays: float = 7.0):
    """(start, end) MJD of the ``ndays``-wide window holding the most
    TOAs (reference: utils.get_highest_density_range — used to pick a
    TZR region). Sliding-window count over sorted epochs; ties go to
    the earliest window."""
    m = np.sort(np.asarray(mjds, dtype=np.float64))
    if m.size == 0:
        raise ValueError("no MJDs given")
    counts = np.searchsorted(m, m + float(ndays), side="right") \
        - np.arange(m.size)
    k = int(np.argmax(counts))
    return float(m[k]), float(m[k] + float(ndays))


def FTest(chi2_1: float, dof_1: int, chi2_2: float, dof_2: int) -> float:
    """F-test probability that the chi2 improvement from model 1 to the
    (larger) model 2 arises by chance (reference: utils.FTest). Small
    values favor keeping model 2's extra parameters."""
    from scipy.stats import f as fdist

    delta_chi2 = chi2_1 - chi2_2
    delta_dof = dof_1 - dof_2
    if delta_dof <= 0 or dof_2 <= 0:
        raise ValueError("model 2 must have more free parameters")
    if delta_chi2 <= 0:
        return 1.0
    F = (delta_chi2 / delta_dof) / (chi2_2 / dof_2)
    return float(fdist.sf(F, delta_dof, dof_2))


def weighted_mean(arr, sigma, axis=None):
    """(mean, stderr) with 1/sigma^2 weights (reference:
    utils.weighted_mean)."""
    arr = np.asarray(arr, dtype=np.float64)
    w = 1.0 / np.asarray(sigma, dtype=np.float64) ** 2
    wsum = np.sum(w, axis=axis)
    mean = np.sum(arr * w, axis=axis) / wsum
    return mean, np.sqrt(1.0 / wsum)


def dmxparse(fitter) -> dict:
    """Collect DMX windows from a fitted model: per-window value,
    (covariance-corrected) uncertainty, epoch range and center
    (reference: utils.dmxparse). Returns dict of arrays:
    dmxs, dmx_verrs, dmxeps (centers), r1s, r2s, bins."""
    model = fitter.model
    comp = model.components.get("DispersionDMX")
    if comp is None or not comp.dmx_ids:
        raise ValueError("model has no DMX windows")
    names = ["Offset"] + list(model.free_params)
    cov = fitter.parameter_covariance_matrix
    dmxs, verrs, eps, r1s, r2s, bins = [], [], [], [], [], []
    # mean-subtraction covariance correction (reference dmxparse):
    # var(DMX_i - <DMX>) needs the full DMX block of the covariance
    free_dmx = [f"DMX_{istr}" for _, istr in comp.dmx_ids
                if not comp.params[f"DMX_{istr}"].frozen]
    idx = [names.index(nm) for nm in free_dmx] \
        if cov is not None and all(nm in names for nm in free_dmx) \
        else []
    sub = cov[np.ix_(idx, idx)] if idx else None
    mean_var = float(np.mean(sub)) if sub is not None and len(idx) \
        else 0.0
    k = 0
    for _, istr in comp.dmx_ids:
        p = comp.params[f"DMX_{istr}"]
        r1 = comp.params[f"DMXR1_{istr}"].value
        r2 = comp.params[f"DMXR2_{istr}"].value
        dmxs.append(p.value)
        r1s.append(r1)
        r2s.append(r2)
        eps.append(0.5 * (r1 + r2))
        bins.append(istr)
        if not p.frozen and sub is not None and k < len(idx):
            var = sub[k, k] - 2.0 * float(np.mean(sub[k])) + mean_var
            verrs.append(np.sqrt(max(var, 0.0)))
            k += 1
        else:
            verrs.append(p.uncertainty if p.uncertainty else 0.0)
    return {"dmxs": np.array(dmxs), "dmx_verrs": np.array(verrs),
            "dmxeps": np.array(eps), "r1s": np.array(r1s),
            "r2s": np.array(r2s), "bins": bins,
            "mean_dmx": float(np.mean(dmxs))}


def format_uncertainty(value: float, unc: Optional[float],
                       sig_digits: int = 2) -> str:
    """Compact parenthesized-uncertainty notation used in pulsar
    publication tables: 1.234567(89) means 1.234567 +- 0.000089
    (reference: pintpublish's table formatting). With no uncertainty,
    plain repr of the value."""
    if unc is None or not np.isfinite(unc) or unc <= 0:
        return repr(float(value))
    exp = int(np.floor(np.log10(unc)))
    # decimals so the uncertainty shows sig_digits digits
    dec = max(0, sig_digits - 1 - exp)
    udigits = int(round(unc * 10 ** dec))
    if udigits >= 10 ** sig_digits:  # rounding bumped a digit
        udigits //= 10
        dec -= 1
        if dec < 0:
            dec = 0
            udigits = int(round(unc))
    if dec == 0:
        return f"{value:.0f}({udigits})"
    return f"{value:.{dec}f}({udigits})"


def dmx_ranges(toas, max_window_days: float = 14.0,
               min_gap_days: float = 0.1):
    """Auto-generate DMX windows from TOA epochs: cluster MJDs into
    groups no wider than ``max_window_days``, one (r1, r2) window per
    group padded by ``min_gap_days`` (reference: utils.dmx_ranges)."""
    mjds = np.sort(np.unique(np.asarray(toas.get_mjds())))
    if len(mjds) == 0:
        return []
    clusters = []
    start = prev = mjds[0]
    for m in mjds[1:]:
        if m - start > max_window_days:
            clusters.append((start, prev))
            start = m
        prev = m
    clusters.append((start, prev))
    # pad, but never past the midpoint to the neighboring cluster —
    # densely sampled data would otherwise get overlapping windows
    # (a TOA in two windows makes two degenerate DMX columns)
    ranges = []
    for i, (c1, c2) in enumerate(clusters):
        lo = c1 - min_gap_days
        hi = c2 + min_gap_days
        if i > 0:
            lo = max(lo, 0.5 * (clusters[i - 1][1] + c1))
        if i < len(clusters) - 1:
            hi = min(hi, 0.5 * (c2 + clusters[i + 1][0]))
        ranges.append((lo, hi))
    return ranges


def add_dmx_ranges(model, toas, max_window_days: float = 14.0,
                   frozen: bool = False) -> int:
    """Attach auto-generated DMX windows to the model's DispersionDMX
    component (created if absent); returns the number of windows."""
    from pint_tpu.models.dispersion import DispersionDMX

    comp = model.components.get("DispersionDMX")
    if comp is None:
        comp = DispersionDMX()
        model.add_component(comp, setup=False)
    # one past the highest existing index: the count would collide
    # with (and overwrite) existing windows when indices have gaps
    start = max((i for i, _ in comp.dmx_ids), default=0)
    ranges = dmx_ranges(toas, max_window_days=max_window_days)
    for k, (r1, r2) in enumerate(ranges):
        comp.add_dmx_range(start + k + 1, r1, r2, value=0.0,
                           frozen=frozen)
    comp.setup()
    model.invalidate_cache()
    return len(ranges)


def wavex_setup(model, t_span_days: float, n_freqs: int,
                frozen: bool = False) -> list:
    """Attach a WaveX component with harmonically spaced frequencies
    k/T, k=1..n (reference: utils.wavex_setup). Returns the
    frequencies in 1/day."""
    from pint_tpu.models.components_extra import WaveX

    comp = model.components.get("WaveX")
    if comp is None:
        comp = WaveX()
        model.add_component(comp, setup=False)
    freqs = [k / t_span_days for k in range(1, n_freqs + 1)]
    for f in freqs:
        comp.add_wavex_component(f, frozen=frozen)
    comp.setup()
    model.invalidate_cache()
    return freqs


def dmwavex_setup(model, t_span_days: float, n_freqs: int,
                  frozen: bool = False) -> list:
    """Attach a DMWaveX component with frequencies k/T (reference:
    utils.dmwavex_setup)."""
    from pint_tpu.models.components_extra import DMWaveX

    comp = model.components.get("DMWaveX")
    if comp is None:
        comp = DMWaveX()
        model.add_component(comp, setup=False)
    freqs = [k / t_span_days for k in range(1, n_freqs + 1)]
    for f in freqs:
        comp.add_dmwavex_component(f, frozen=frozen)
    comp.setup()
    model.invalidate_cache()
    return freqs


def akaike_information_criterion(fitter) -> float:
    """AIC = 2k + chi2 for the fitted model (Gaussian likelihood up to
    a constant; reference: utils.akaike_information_criterion)."""
    k = len(fitter.model.free_params)
    return 2.0 * k + float(fitter.resids.chi2)


def bayesian_information_criterion(fitter) -> float:
    """BIC = k ln N + chi2 (reference: utils.bic)."""
    k = len(fitter.model.free_params)
    n = fitter.toas.ntoas
    return k * float(np.log(n)) + float(fitter.resids.chi2)


class PosVel:
    """Minimal 6-vector position/velocity with frame bookkeeping
    (reference: utils.PosVel): supports +/- chaining with
    origin/destination checking, dot products, and numpy access."""

    def __init__(self, pos, vel, origin=None, obj=None):
        self.pos = np.asarray(pos, dtype=np.float64)
        self.vel = np.asarray(vel, dtype=np.float64)
        self.origin = origin
        self.obj = obj

    def __add__(self, other: "PosVel") -> "PosVel":
        if self.obj is not None and other.origin is not None and \
                self.obj != other.origin:
            raise ValueError(
                f"cannot chain {self.origin}->{self.obj} with "
                f"{other.origin}->{other.obj}")
        return PosVel(self.pos + other.pos, self.vel + other.vel,
                      origin=self.origin, obj=other.obj)

    def __sub__(self, other: "PosVel") -> "PosVel":
        if self.origin is not None and other.origin is not None and \
                self.origin != other.origin:
            raise ValueError("subtraction needs a common origin")
        return PosVel(self.pos - other.pos, self.vel - other.vel,
                      origin=other.obj, obj=self.obj)

    def __neg__(self) -> "PosVel":
        return PosVel(-self.pos, -self.vel, origin=self.obj,
                      obj=self.origin)

    def __repr__(self):
        return (f"PosVel({self.origin or '?'} -> {self.obj or '?'}, "
                f"|r|={np.linalg.norm(self.pos, axis=-1)!r})")
