"""Analysis utilities: F-test, DMX parsing/statistics, weighted stats.

Reference: src/pint/utils.py (FTest, dmxparse, weighted_mean,
split_prefixed_name, taylor_horner — the latter two live in
pint_tpu.models.parameter / pint_tpu.ops.taylor here and are
re-exported for API familiarity).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.models.parameter import split_prefixed_name  # noqa: F401
from pint_tpu.ops.taylor import taylor_horner  # noqa: F401

__all__ = ["FTest", "weighted_mean", "dmxparse",
           "split_prefixed_name", "taylor_horner"]


def FTest(chi2_1: float, dof_1: int, chi2_2: float, dof_2: int) -> float:
    """F-test probability that the chi2 improvement from model 1 to the
    (larger) model 2 arises by chance (reference: utils.FTest). Small
    values favor keeping model 2's extra parameters."""
    from scipy.stats import f as fdist

    delta_chi2 = chi2_1 - chi2_2
    delta_dof = dof_1 - dof_2
    if delta_dof <= 0 or dof_2 <= 0:
        raise ValueError("model 2 must have more free parameters")
    if delta_chi2 <= 0:
        return 1.0
    F = (delta_chi2 / delta_dof) / (chi2_2 / dof_2)
    return float(fdist.sf(F, delta_dof, dof_2))


def weighted_mean(arr, sigma, axis=None):
    """(mean, stderr) with 1/sigma^2 weights (reference:
    utils.weighted_mean)."""
    arr = np.asarray(arr, dtype=np.float64)
    w = 1.0 / np.asarray(sigma, dtype=np.float64) ** 2
    wsum = np.sum(w, axis=axis)
    mean = np.sum(arr * w, axis=axis) / wsum
    return mean, np.sqrt(1.0 / wsum)


def dmxparse(fitter) -> dict:
    """Collect DMX windows from a fitted model: per-window value,
    (covariance-corrected) uncertainty, epoch range and center
    (reference: utils.dmxparse). Returns dict of arrays:
    dmxs, dmx_verrs, dmxeps (centers), r1s, r2s, bins."""
    model = fitter.model
    comp = model.components.get("DispersionDMX")
    if comp is None or not comp.dmx_ids:
        raise ValueError("model has no DMX windows")
    names = ["Offset"] + list(model.free_params)
    cov = fitter.parameter_covariance_matrix
    dmxs, verrs, eps, r1s, r2s, bins = [], [], [], [], [], []
    # mean-subtraction covariance correction (reference dmxparse):
    # var(DMX_i - <DMX>) needs the full DMX block of the covariance
    free_dmx = [f"DMX_{istr}" for _, istr in comp.dmx_ids
                if not comp.params[f"DMX_{istr}"].frozen]
    idx = [names.index(nm) for nm in free_dmx] \
        if cov is not None and all(nm in names for nm in free_dmx) \
        else []
    sub = cov[np.ix_(idx, idx)] if idx else None
    mean_var = float(np.mean(sub)) if sub is not None and len(idx) \
        else 0.0
    k = 0
    for _, istr in comp.dmx_ids:
        p = comp.params[f"DMX_{istr}"]
        r1 = comp.params[f"DMXR1_{istr}"].value
        r2 = comp.params[f"DMXR2_{istr}"].value
        dmxs.append(p.value)
        r1s.append(r1)
        r2s.append(r2)
        eps.append(0.5 * (r1 + r2))
        bins.append(istr)
        if not p.frozen and sub is not None and k < len(idx):
            var = sub[k, k] - 2.0 * float(np.mean(sub[k])) + mean_var
            verrs.append(np.sqrt(max(var, 0.0)))
            k += 1
        else:
            verrs.append(p.uncertainty if p.uncertainty else 0.0)
    return {"dmxs": np.array(dmxs), "dmx_verrs": np.array(verrs),
            "dmxeps": np.array(eps), "r1s": np.array(r1s),
            "r2s": np.array(r2s), "bins": bins,
            "mean_dmx": float(np.mean(dmxs))}
