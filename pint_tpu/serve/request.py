"""Typed serve requests and their result futures.

The serving layer (SURVEY.md north star: "serving heavy traffic")
turns the library's one-model-one-call entry points into queued,
coalescable work items. Three request kinds exist, matching the three
hot read paths of a timing service:

- ``FitStepRequest``: one linearized GLS fit iteration (the unit
  ``parallel.fit_step`` computes and ``parallel.pta`` batches);
- ``ResidualsRequest``: residuals + whitened chi2 at the current
  parameter point (rides the SAME batched solve — its chi2 is the
  bases-only-marginalized ``chi2r`` output of ``pta._solve_one``, the
  quantity ``Residuals.chi2`` reports);
- ``PhasePredictRequest``: absolute-phase prediction from a polyco
  segment (``polycos.PolycoEntry``) at arbitrary MJDs — the
  phase-ephemeris read path (fold-mode observing, online dedispersion);
- ``PosteriorRequest`` (ISSUE 9): a posterior-sampling run over the
  pulsar's linearized GLS posterior — the whole-chain-on-device
  stretch-move kernel of ``pint_tpu.sampling.serve_kernel``, batched
  across pulsars by walker/step shape class, dispatched as chunked
  supervised ``lax.scan`` programs with journalable per-chunk
  progress.

Every request carries an optional relative deadline and owns a
``ServeFuture``; the scheduler resolves the future when the request's
batch completes (or fails it with ``DeadlineExceeded`` /
``ServeOverload``).
"""

from __future__ import annotations

import concurrent.futures
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ServeFuture", "DeadlineExceeded", "ServeOverload",
           "TenantOverQuota", "ShutdownShed", "EngineKilled",
           "StateMissing",
           "FitStepRequest", "ResidualsRequest", "PhasePredictRequest",
           "PosteriorRequest", "AppendTOAsRequest", "GWBRequest",
           "FitStepResult",
           "ResidualsResult", "PhasePredictResult", "PosteriorResult",
           "AppendResult", "GWBResult"]


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before its batch dispatched
    (expired in queue, shed by the deadline-aware admission policy,
    or dead on arrival at dispatch time)."""


class ServeOverload(RuntimeError):
    """Admission queue at capacity — backpressure signal to the
    caller (shed load or retry later; the queue cap is
    ``config.serve_queue_cap``)."""


class TenantOverQuota(ServeOverload):
    """The submitting tenant's token bucket is drained
    (``config.tenant_qps`` / ``$PINT_TPU_TENANT_QPS``): this tenant
    is bursting past its quota and is shed WITHOUT touching shared
    capacity — other tenants keep being admitted."""


class ShutdownShed(ServeOverload):
    """The engine is draining for shutdown and the bounded drain
    timeout elapsed before this request dispatched — shed with an
    explicit label instead of dying silently with the process."""


class EngineKilled(RuntimeError):
    """The engine was killed (injected ``kill_restart`` fault — the
    simulated SIGKILL of the restart-recovery harness): in-flight
    futures die unresolved exactly as a real process death would
    leave them; the journal's unacknowledged entries are what a
    restarted engine replays."""


class ServeFuture(concurrent.futures.Future):
    """The request's result future. On a synchronous (non-threaded)
    engine, ``result()`` pumps the engine's queue first so a plain
    submit-then-result sequence completes without a background
    thread; on a started engine the inherited blocking wait applies.
    """

    _sync_engine = None  # set by ServeEngine.submit when not threaded

    def result(self, timeout: Optional[float] = None):
        if self._sync_engine is not None and not self.done():
            self._sync_engine.flush()
        return super().result(timeout)


class Request:
    """Base serve request: deadline bookkeeping + future plumbing.

    ``deadline_s`` is RELATIVE (seconds from submission); the engine
    stamps the absolute expiry at admission. ``None`` = no deadline.

    ``tenant`` feeds the admission controller's per-tenant token
    buckets (None = the anonymous default tenant). ``rid`` +
    ``payload`` make a request journalable: ``payload`` is an opaque
    JSON-able description sufficient for the CALLER's replay factory
    to rebuild the request after a crash (the journal stores it
    verbatim; requests without one are served but never journaled —
    an in-memory object cannot be replayed into a fresh process).
    """

    kind = "?"

    def __init__(self, deadline_s: Optional[float] = None,
                 tenant: Optional[str] = None,
                 rid: Optional[str] = None,
                 payload: Optional[dict] = None):
        self.deadline_s = deadline_s
        self.tenant = tenant
        self.rid = rid
        self.payload = payload
        self.future = ServeFuture()
        self.admitted_at: Optional[float] = None  # time.monotonic()
        self.expires_at: Optional[float] = None

    def expired(self, now: float) -> bool:
        return self.expires_at is not None and now > self.expires_at


@dataclass
class FitStepResult:
    """One GLS correction, aligned with ``names`` (same contract as
    ``parallel.pta.fit_pta``: dparams is the correction to ADD, an
    implicit leading "Offset" unless the model carries PHOFF)."""

    names: List[str]
    dparams: np.ndarray
    cov: np.ndarray
    chi2: float       # linearized post-fit chi2
    chi2r: float      # chi2 at the current point (bases marginalized)

    def errors(self) -> Dict[str, float]:
        sig = np.sqrt(np.diag(self.cov))
        return {n: float(s) for n, s in zip(self.names, sig)
                if n != "Offset"}


@dataclass
class ResidualsResult:
    """Residuals at the current point plus the whitened chi2 the
    batched solve produced (= ``Residuals.chi2`` semantics)."""

    time_resids: np.ndarray   # [s]
    chi2: float

    @property
    def rms_us(self) -> float:
        return float(np.sqrt(np.mean(self.time_resids ** 2))) * 1e6


@dataclass
class PosteriorResult:
    """One pulsar's sampled linearized posterior: the thinned chain
    in PHYSICAL parameter units using the ``dparams`` convention of
    ``parallel.pta._solve_one`` (each sample is the correction to ADD
    to the current parameter values), aligned with ``names``."""

    names: List[str]
    chain: np.ndarray            # (S, W, p) thinned samples
    lnprob: np.ndarray           # (S, W)
    acceptance_fraction: float
    nsteps: int                  # un-thinned chain length actually run

    def flat(self, discard: int = 0) -> np.ndarray:
        """(S*W, p) flattened post-burn samples."""
        return self.chain[discard:].reshape(-1, self.chain.shape[-1])

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-parameter posterior median/std of the correction."""
        flat = self.flat(discard=self.chain.shape[0] // 3)
        med = np.median(flat, axis=0)
        std = np.std(flat, axis=0)
        return {n: {"median": float(m), "std": float(s)}
                for n, m, s in zip(self.names, med, std)}


@dataclass
class PhasePredictResult:
    """Absolute phase split (int turns, frac turns) at the request's
    MJDs — same split as ``PolycoEntry.abs_phase``."""

    phase_int: np.ndarray
    phase_frac: np.ndarray


class _GLSRequest(Request):
    """Shared plumbing for the two request kinds that ride the batched
    GLS solve. Accepts either (toas, model) — assembled at dispatch —
    or a prebuilt ``parallel.pta.PulsarProblem`` (the serving-state
    form: a service holding hot pulsar states assembles once and
    re-solves on every poll, so admission stays O(1))."""

    def __init__(self, toas=None, model=None, problem=None,
                 track_mode=None, deadline_s: Optional[float] = None,
                 **kw):
        super().__init__(deadline_s=deadline_s, **kw)
        if problem is None and (toas is None or model is None):
            raise ValueError(
                f"{type(self).__name__} needs (toas, model) or a "
                f"prebuilt PulsarProblem")
        self.toas = toas
        self.model = model
        self.track_mode = track_mode
        self.problem = problem

    def ensure_problem(self):
        """Assemble (or return the cached) linearized problem."""
        if self.problem is None:
            from pint_tpu.parallel.pta import build_problem

            self.problem = build_problem(self.toas, self.model,
                                         track_mode=self.track_mode)
        return self.problem

    @property
    def sizes(self):
        """(ntoa, nparam, nbasis) — the shape-class inputs, read off
        the assembled problem (assembling it first if needed: any
        size heuristic computed without assembly could drift from
        build_problem's real shapes and misclassify the request)."""
        pr = self.ensure_problem()
        return (pr.M.shape[0], pr.M.shape[1], pr.F.shape[1])


class FitStepRequest(_GLSRequest):
    kind = "fit_step"


class ResidualsRequest(_GLSRequest):
    kind = "residuals"


class PosteriorRequest(_GLSRequest):
    """Sample the pulsar's linearized timing posterior (ISSUE 9).

    Rides the same assembled ``PulsarProblem`` as the GLS kinds; the
    served work is a whole-chain-on-device stretch-move ensemble run
    (``sampling.serve_kernel``). ``seed`` anchors the positional PRNG
    stream — a request's chain depends only on its own seed, never on
    its batch position, so a coalesced batch slot is bit-identical to
    the direct ``sample_problems`` path at the same shape class.
    ``nsteps`` is a RUNTIME budget (requests with different chain
    lengths share one compiled shape class); ``nwalkers``/``thin``
    are part of the shape class."""

    kind = "posterior"

    def __init__(self, toas=None, model=None, problem=None,
                 nwalkers: int = 32, nsteps: int = 500,
                 seed: int = 0, thin: int = 1, **kw):
        super().__init__(toas=toas, model=model, problem=problem,
                         **kw)
        self.nwalkers = int(nwalkers)
        self.nsteps = int(nsteps)
        self.seed = int(seed)
        self.thin = max(1, int(thin))
        if self.nwalkers < 2 or self.nwalkers % 2:
            raise ValueError("nwalkers must be even and >= 2")
        if self.nsteps < 1 or self.nsteps >= 2 ** 31:
            # upper bound: the kernel's positional PRNG offset is an
            # int32 — past 2^31 fold_in streams would wrap and repeat
            raise ValueError("nsteps must be in [1, 2^31)")
        if self.nsteps % self.thin:
            raise ValueError("nsteps must be a multiple of thin")

    def ensure_problem(self):
        """The walker-count guard lives here, not in the kernel: the
        serve kernel's padded batch traces ndim, so
        ``build_stretch_chunk`` cannot check it — and an
        under-walkered stretch-move ensemble is confined to the
        affine hull of its start positions (dimensions beyond
        nwalkers-1 are silently never explored)."""
        pr = super().ensure_problem()
        if self.nwalkers < 2 * pr.M.shape[1]:
            raise ValueError(
                f"nwalkers={self.nwalkers} < 2*ndim"
                f"={2 * pr.M.shape[1]}: need an even nwalkers >= "
                "2*ndim for ensemble moves")
        return pr

    @property
    def walker_steps(self) -> int:
        """Total walker-updates this chain costs — the kind-local
        'rows' unit the capacity router learns posterior service
        rates in."""
        return self.nsteps * self.nwalkers


class StateMissing(RuntimeError):
    """An ``AppendTOAsRequest`` with ``cold=False`` named a pulsar
    state the engine does not hold (process restart lost the
    in-memory accumulator store, or the key was never cold-built):
    the caller must re-submit a cold build — silently rebuilding
    from only the appended rows would serve a fit of the tail of the
    data as if it covered all of it."""


@dataclass
class AppendResult:
    """One pulsar's re-converged incremental fit: ``dparams`` is the
    TOTAL correction to ADD to the model at the state's linearization
    point theta_0 (the ``parallel.pta`` convention), reflecting every
    TOA accumulated into the state INCLUDING this request's batch.
    ``chi2r`` is the bases-marginalized chi2 of the combined set at
    theta_0 (``Residuals.chi2`` semantics)."""

    names: List[str]
    dparams: np.ndarray
    cov: np.ndarray
    chi2: float          # linearized post-fit chi2, combined set
    chi2r: float         # chi2 at theta_0, combined set
    ntoa_total: int      # TOAs accumulated in the state after this
    cold: bool           # True when this request cold-built the state
    cg_iters: int

    def errors(self) -> Dict[str, float]:
        sig = np.sqrt(np.diag(self.cov))
        return {n: float(s) for n, s in zip(self.names, sig)
                if n != "Offset"}


class AppendTOAsRequest(_GLSRequest):
    """Append a batch of TOAs to a pulsar's cached accumulated normal
    equations and re-converge in O(new TOAs) (ISSUE 12).

    ``state_key`` names the per-pulsar accumulator state the engine
    holds (``ServeEngine.append_store``). The FIRST request for a key
    is the cold build: ``toas`` is the full initial dataset,
    accumulated chunk-free into a fresh state whose noise-basis span
    is recorded. Subsequent requests carry ONLY the new TOAs: their
    rows are assembled at admission (O(new) host work — design
    matrix, residuals, and the noise basis evaluated on the COLD
    span's Fourier frequencies via the ``tspan`` override, so the
    columns align with the cached Gram), the device work is a rank
    update + preconditioned-CG re-solve of the small accumulated
    system, and the result is the total correction at the state's
    linearization point theta_0.

    Contract: the served model stays AT theta_0 (the linearized-
    serving convention PosteriorRequest also uses) — apply the
    returned ``dparams`` to a COPY if you want parameter values.
    Cold is EXPLICIT: only ``cold=True`` creates (or REBUILDS —
    that is how you re-linearize after a parameter/hyperparameter
    change) a state, and a warm append against a missing state
    fails with ``StateMissing`` instead of silently promoting
    itself to a cold build — otherwise a small append racing an
    in-flight cold build could install a tail-only state as if it
    covered the full dataset. ECORR models are rejected (appended
    epochs would grow the basis rank and break the fixed shape
    classes); wideband TOAs are rejected like every serve GLS kind.
    States are in-memory: after a process restart the first request
    per key must be cold."""

    kind = "append"

    def __init__(self, state_key: str, toas=None, model=None,
                 cold: Optional[bool] = None, **kw):
        super().__init__(toas=toas, model=model, **kw)
        self.state_key = str(state_key)
        self.cold = cold
        self._store = None   # bound by the engine at admission

    def bind_store(self, store):
        self._store = store

    def ensure_problem(self):
        """Assemble ONLY this request's rows, basis-aligned with the
        cached state (tspan pinned to the cold span). Raises
        ``StateMissing`` for a warm append with no cached state and
        ``ValueError`` for ECORR/wideband/shape-mismatched models."""
        if self.problem is not None:
            return self.problem
        from pint_tpu.serve.append import build_append_rows

        entry = None
        if self._store is not None:
            entry = self._store.get(self.state_key)
        cold = self.cold
        if cold is None:
            # never auto-promote to cold: an unspecified-cold append
            # is a WARM append, and a missing state is an error — a
            # tail batch must not masquerade as the full dataset
            # (e.g. racing an in-flight cold build, or after a
            # process restart lost the store)
            cold = False
        if not cold and entry is None:
            raise StateMissing(
                f"append state {self.state_key!r} not found (process "
                f"restart, or never cold-built?); submit a cold "
                f"build (cold=True with the full dataset) first")
        self.cold = bool(cold)
        tspan = None if cold or entry is None else entry.tspan
        tref = None if cold or entry is None else entry.tref
        self.problem = build_append_rows(
            self.toas, self.model, tspan=tspan, tref=tref,
            track_mode=self.track_mode)
        if entry is not None and not cold:
            entry.check_compatible(self.problem)
        return self.problem


@dataclass
class GWBResult:
    """One array's swept GWB detection grid: ``logL[k]`` is the
    Hellings–Downs cross-correlated marginal log-likelihood at
    ``(log10A[k], gamma[k])`` (``pta.gwb.GWBLikelihood`` semantics —
    the improper-prior constant is dropped, so COMPARE values across
    the grid, don't read them absolutely)."""

    logL: np.ndarray             # (npts,)
    log10A: np.ndarray           # (npts,) the grid actually swept
    gamma: np.ndarray            # (npts,)
    npulsars: int
    nfreq: int

    def best(self) -> Dict[str, float]:
        """The grid's maximum-likelihood point."""
        k = int(np.argmax(self.logL))
        return {"log10A": float(self.log10A[k]),
                "gamma": float(self.gamma[k]),
                "logL": float(self.logL[k])}


class GWBRequest(Request):
    """Sweep the array-level GWB likelihood over a hyperparameter
    grid (ISSUE 17).

    Carries a whole pulsar ARRAY (``pairs`` of (toas, model), prebuilt
    ``PulsarProblem``s, or a prebuilt ``pta.gwb.GWBLikelihood`` — the
    serving-state form: a service holding a hot array builds the
    likelihood once, blocks and all, and re-sweeps per request). The
    served work is the chunked outer Schur sweep
    (``pta.gwb.gwb_sweep_driver``): each chunk of
    ``config.gwb_chunk()`` grid points is one supervised dispatch, so
    the chunk boundary is the failover/deadline boundary and journal
    progress is acked per chunk — NOT AOT-exported and NOT donated
    (the blocks are long-lived request state, exactly the posterior
    chains' rationale). ``log10A``/``gamma`` are RUNTIME grids
    (requests with different grids share a compiled shape class);
    the shape class is (npulsars, basis size, chunk)."""

    kind = "gwb"

    def __init__(self, pairs=None, problems=None, likelihood=None,
                 log10A=None, gamma=None, nfreq: int = 10,
                 positions=None, gamma_matrix=None, track_mode=None,
                 **kw):
        super().__init__(**kw)
        if likelihood is None and pairs is None and problems is None:
            raise ValueError(
                "GWBRequest needs pairs, problems, or a prebuilt "
                "GWBLikelihood")
        self.pairs = pairs
        self.problems = problems
        self.likelihood = likelihood
        self.positions = positions
        self.gamma_matrix = gamma_matrix
        self.nfreq = int(nfreq)
        self.track_mode = track_mode
        self.log10A = np.atleast_1d(
            np.asarray(log10A, np.float64)).ravel()
        self.gamma = np.atleast_1d(
            np.asarray(gamma, np.float64)).ravel()
        if self.log10A.shape != self.gamma.shape:
            raise ValueError(
                f"log10A grid ({self.log10A.shape}) and gamma grid "
                f"({self.gamma.shape}) must have the same length")
        if len(self.log10A) < 1:
            raise ValueError("GWBRequest needs a non-empty grid")

    def ensure_likelihood(self, mesh=None, axis: str = "pulsar",
                          supervisor=None):
        """Build (or return the cached) array likelihood. The
        engine's mesh threads through so the inner block assembly is
        sharded over the pulsar axis."""
        if self.likelihood is None:
            from pint_tpu.pta.gwb import GWBLikelihood

            self.likelihood = GWBLikelihood(
                pairs=self.pairs, problems=self.problems,
                positions=self.positions,
                gamma_matrix=self.gamma_matrix, nfreq=self.nfreq,
                mesh=mesh, axis=axis, supervisor=supervisor,
                track_mode=self.track_mode)
        return self.likelihood

    @property
    def npoints(self) -> int:
        """Grid points this sweep costs — the kind-local 'rows' unit
        the capacity router learns GWB service rates in."""
        return len(self.log10A)

    @property
    def sizes(self):
        """(npulsars, basis columns) — the shape-class inputs, read
        off the assembled likelihood."""
        lk = self.ensure_likelihood()
        return (lk.npulsars, lk.m)


class PhasePredictRequest(Request):
    """Evaluate one polyco segment's absolute phase at ``mjds``.

    The entry is host-fit once (``Polycos.generate_polycos``) and then
    served read-only; the per-request device work is the padded,
    vmapped polynomial evaluation in ``serve.bucket``."""

    kind = "phase"

    def __init__(self, entry, mjds, deadline_s: Optional[float] = None,
                 **kw):
        super().__init__(deadline_s=deadline_s, **kw)
        self.entry = entry
        self.mjds = np.atleast_1d(np.asarray(mjds, np.float64))

    @property
    def sizes(self):
        """(nmjd, ncoeff) — the phase shape-class inputs."""
        return (len(self.mjds), len(np.asarray(self.entry.coeffs)))
