"""Breaker-aware capacity routing: host CPU and accelerator as
CONCURRENT pools with learned service rates.

Before ISSUE 8 the host path existed only as the dispatch
supervisor's failover target: every batch first tried the device,
and a dead backend cost each dispatch a watchdog deadline before the
host solved it. This module promotes the host to a first-class
capacity pool:

- **N named pools** (ISSUE 19; classically two): "device" (the
  engine's jitted / AOT-restored bucket executables on the default
  backend) and "host" (the numpy mirrors — ``pta_solve_np`` /
  ``PolycoEntry.abs_phase`` — running pinned, hang-free, on the
  caller's CPU) are structural; ``$PINT_TPU_POOLS`` adds further
  device-class pools, each with its own breaker, rates and
  counters. In a pipelined drain, units routed to different pools
  genuinely execute concurrently.
- **learned service rates**: every completed dispatch feeds an EWMA
  of rows/s per (pool, kind). Rows are KIND-LOCAL units (padded
  TOA/MJD rows for gls/phase, walker-steps for posterior chains), so
  backlogs are tracked and costed per kind — a queued posterior unit
  is priced at the posterior rate in every completion-time and
  admission-wait estimate (ISSUE 9 satellite), never at the GLS
  rate. Routing predicts each pool's completion time as the per-kind
  backlog cost + this batch / rate and picks the cheaper pool. Cold start is deliberately conservative:
  until the HOST rate has been observed (a breaker demotion served
  there, or ``seed_rate`` taught it explicitly), everything routes
  to the device — the router never guesses the host faster on no
  evidence, so a fault-free deployment behaves exactly like the
  pre-router engine.
- **breaker-aware demotion**: an OPEN device breaker
  (``runtime.breaker``, consulted through the supervisor's
  ``pool_health`` surface) demotes the device pool instead of
  stopping the world — batches route straight to the host pool,
  counted as ``demotions``, without each paying the watchdog-timeout
  + failover dance first. When the breaker closes (half-open probe
  recovery), the device pool rejoins automatically.

Every decision is visible: ``snapshot()`` is the ``router`` block of
``ServeMetrics.snapshot()`` (per-pool dispatch/request/row shares,
learned rates, demotion count).
"""

from __future__ import annotations

from typing import Dict, Optional

from pint_tpu.runtime import locks

__all__ = ["CapacityRouter"]

# EWMA smoothing for learned rates: ~5-dispatch memory — fast enough
# to track a warming cache, slow enough not to thrash on one outlier
_EWMA_ALPHA = 0.3
# rows/s assumed for a pool that has never been observed; the device
# prior is high on purpose (routing away from the device requires
# EVIDENCE, not a guess)
_DEVICE_PRIOR = 1e9


class _Pool:
    """One capacity pool's accounting. ISSUE 11: the monotonic
    counters (dispatches/requests/rows/demotions) are bound children
    of the registry's ``pint_tpu_router_*_total`` metrics labelled
    (scope, pool) and read back through ``__getattr__``; the learned
    EWMA rates and in-flight backlog mirror into gauges. Routing
    logic keeps its local ``rates``/``inflight_kind`` dicts — the
    registry is the observability plane, not the decision state."""

    _COUNTERS = ("dispatches", "requests", "rows", "demotions")

    __slots__ = ("name", "rates", "inflight_rows", "inflight_kind",
                 "_c", "_g_rate", "_g_inflight", "_scope")

    def __init__(self, name: str, scope: str = ""):
        from pint_tpu.obs import metrics as om

        self.name = name
        self._scope = scope
        self._c = {
            cn: om.counter(
                f"pint_tpu_router_{cn}_total",
                f"capacity-router {cn} per pool"
            ).child(scope=scope, pool=name)
            for cn in self._COUNTERS}
        self._g_rate = om.gauge(
            "pint_tpu_router_rate_rows_per_s",
            "learned EWMA service rate per (pool, kind)")
        self._g_inflight = om.gauge(
            "pint_tpu_router_inflight_rows",
            "in-flight kind-local rows per pool"
        ).child(scope=scope, pool=name)
        self.rates: Dict[str, float] = {}   # kind -> EWMA rows/s
        self.inflight_rows = 0
        self.inflight_kind: Dict[str, int] = {}  # kind -> rows

    def __getattr__(self, name):
        # __slots__ class: _c exists once __init__ ran; counter
        # names read through the registry children
        if name in _Pool._COUNTERS:
            return int(object.__getattribute__(self, "_c")[name]
                       .value())
        raise AttributeError(name)

    def bump(self, counter: str, n: int = 1):
        self._c[counter].inc(n)

    def rate(self, kind: str) -> Optional[float]:
        return self.rates.get(kind)

    def observe(self, kind: str, rows: int, wall_s: float):
        if wall_s <= 0.0:
            return
        r = max(1.0, rows) / wall_s
        prev = self.rates.get(kind)
        self.rates[kind] = r if prev is None else \
            (1.0 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * r
        self._g_rate.set(self.rates[kind], scope=self._scope,
                         pool=self.name, kind=kind)

    def snapshot(self) -> dict:
        return {
            "dispatches": self.dispatches,
            "requests": self.requests,
            "rows": self.rows,
            "inflight_rows": self.inflight_rows,
            "demotions": self.demotions,
            "rows_per_s": {k: round(v, 1)
                           for k, v in sorted(self.rates.items())},
        }

    def add_inflight(self, kind: str, rows: int):
        self.inflight_rows += rows
        self.inflight_kind[kind] = \
            self.inflight_kind.get(kind, 0) + rows
        self._g_inflight.set(self.inflight_rows)

    def sub_inflight(self, kind: str, rows: int):
        self.inflight_rows = max(0, self.inflight_rows - rows)
        self.inflight_kind[kind] = max(
            0, self.inflight_kind.get(kind, 0) - rows)
        self._g_inflight.set(self.inflight_rows)


class CapacityRouter:
    """Routes sealed shape-class units to a capacity pool.

    ``supervisor`` provides the ``pool_health`` surface (breaker
    state). One router per engine — its shares are that deployment's
    accounting, like the engine's compile counts.

    ``pools`` (ISSUE 19) generalizes the capacity layer to N NAMED
    pools (default ``config.pool_spec()``, i.e. the classic
    ``("device", "host")`` pair): "device" and "host" stay
    structural — the engine's jitted executables and the always-
    available numpy mirrors — and every extra name is an additional
    device-class pool with its own process-global ``runtime.breaker``
    instance (keyed ``pool:<name>`` through the supervisor's
    ``pool_health`` surface), its own learned EWMA rates, and its own
    G13 registry counters. An OPEN breaker demotes ONLY its pool;
    host demotion-of-last-resort happens only when every device-class
    pool is open. With the default spec the routing decisions are
    bit-identical to the two-pool router."""

    def __init__(self, supervisor=None, pools=None):
        from pint_tpu import config
        from pint_tpu.obs import metrics as om

        self.supervisor = supervisor
        self.scope = om.new_scope("router")
        if pools is None:
            pools = config.pool_spec() or ("device", "host")
        # stable routing order: device first (ties prefer it, the
        # two-pool behavior), extra device-class pools in spec
        # order, host last (the failover pool never wins a tie)
        names = ["device"]
        names += [n for n in pools if n not in ("device", "host")]
        names.append("host")
        self._order = tuple(names)
        self._extra = tuple(n for n in self._order
                            if n not in ("device", "host"))
        self.pools = {n: _Pool(n, scope=self.scope)
                      for n in self._order}
        self._lock = locks.make_lock("serve.router")

    # -- routing -------------------------------------------------------

    def _open_pools(self) -> dict:
        """Breaker-open flags per device-class pool (host is never
        open — definitionally closed). One ``pool_health`` read per
        routing decision, never a probe."""
        if self.supervisor is None:
            return {}
        try:
            h = self.supervisor.pool_health(pools=self._extra)
            return {n: bool(h.get(n, {}).get("open", False))
                    for n in self._order if n != "host"}
        except Exception:
            return {}

    def _device_open(self) -> bool:
        return self._open_pools().get("device", False)

    def pick(self, kind: str, rows: int) -> str:
        """Choose the pool for one sealed unit of ``rows`` padded
        rows. A breaker-open device-class pool is demoted outright
        (only when EVERY device-class pool is open does the unit
        route straight to host, counted as a demotion); otherwise
        the pool with the smaller predicted completion time wins,
        with device-class pools preferred until the host has a
        LEARNED rate."""
        with self._lock:
            host = self.pools["host"]
            open_map = self._open_pools()
            live = [n for n in self._order
                    if n != "host" and not open_map.get(n, False)]
            if not live:
                host.bump("demotions")
                return "host"

            def backlog_s(p, r_kind):
                # per-kind backlog costing (each kind at its own
                # learned rate; unlearned kinds free — consistent
                # with predicted_wait_s)
                t = 0.0
                for k, v in p.inflight_kind.items():
                    r = r_kind if k == kind else p.rate(k)
                    if r:
                        t += v / r
                return t

            best, best_t = None, None
            for n in live:
                p = self.pools[n]
                r = p.rate(kind) or _DEVICE_PRIOR
                t = backlog_s(p, r) + rows / r
                if best_t is None or t < best_t:
                    best, best_t = n, t
            hr = host.rate(kind)
            if hr is None:
                # cold host: routing away from the device classes
                # requires evidence, never a guess
                return best
            t_host = backlog_s(host, hr) + rows / hr
            return best if best_t <= t_host else "host"

    def _best_rate(self, kind: str) -> Optional[float]:
        rates = [p.rate(kind) for p in self.pools.values()]
        rates = [r for r in rates if r]
        return max(rates) if rates else None

    def predicted_wait_s(self, rows: int, kind: str = "gls",
                         ahead_by_kind: Optional[Dict[str, int]]
                         = None) -> float:
        """Admission-policy estimate: how long ``rows`` rows of
        ``kind`` would wait given the current backlog, PER-KIND
        (ISSUE 9 satellite): each kind's backlog — in-flight plus the
        caller-supplied queued-ahead ``ahead_by_kind`` — is costed at
        ITS OWN best learned (pool, kind) rate, so a posterior chain
        queued ahead is priced at the posterior rate, never the
        ~1000x faster GLS rate (which would admit a doomed long chain
        against a deadline it provably cannot make). Rows are
        kind-local units (padded TOA/MJD rows for gls/phase,
        walker-steps for posterior) — which is exactly why rates and
        backlogs must never mix across kinds. A kind with no learned
        rate contributes 0 (never doomed on no evidence); if the
        NEWCOMER's own kind is unlearned the whole estimate is 0."""
        with self._lock:
            own = self._best_rate(kind)
            if own is None:
                return 0.0
            backlog: Dict[str, int] = {}
            for p in self.pools.values():
                for k, v in p.inflight_kind.items():
                    backlog[k] = backlog.get(k, 0) + v
            for k, v in (ahead_by_kind or {}).items():
                backlog[k] = backlog.get(k, 0) + v
            t = rows / own
            for k, v in backlog.items():
                r = self._best_rate(k)
                if r:
                    t += v / r
            return t

    # -- accounting ----------------------------------------------------

    def issued(self, pool: str, nreq: int, rows: int,
               kind: str = "gls"):
        with self._lock:
            p = self.pools[pool]
            p.bump("dispatches")
            p.bump("requests", nreq)
            p.bump("rows", rows)
            p.add_inflight(kind, rows)

    def finished(self, pool: str, kind: str, rows: int,
                 wall_s: float, used_pool: Optional[str] = None):
        """Complete one dispatch issued to ``pool``. ``used_pool``
        names the pool that ACTUALLY produced the result; a rate is
        observed only when the result came from the pool it was
        issued to. A device-issued dispatch that failed over to the
        host ("host-failover") teaches NOBODY: its wall includes the
        watchdog deadline it first burned, a corrupt sample for
        either pool — the failover stays visible in the supervisor
        counters, and repeated failures trip the breaker whose OPEN
        state is what routes (and teaches) the host pool."""
        with self._lock:
            self.pools[pool].sub_inflight(kind, rows)
            if used_pool is None:
                used_pool = pool
            if used_pool == pool:
                self.pools[pool].observe(kind, rows, wall_s)

    def seed_rate(self, pool: str, kind: str, rows_per_s: float):
        """Directly set a pool's learned rate (tests, and the bench's
        host-probe warmup)."""
        with self._lock:
            p = self.pools[pool]
            p.rates[kind] = float(rows_per_s)
            p._g_rate.set(p.rates[kind], scope=self.scope,
                          pool=pool, kind=kind)

    def snapshot(self) -> dict:
        with self._lock:
            out = {name: p.snapshot()
                   for name, p in self.pools.items()}
        total = sum(p["dispatches"] for p in out.values())
        for p in out.values():
            p["share"] = round(p["dispatches"] / total, 4) \
                if total else 0.0
        return out

    def health_block(self) -> dict:
        """The /healthz ``pools`` block (ISSUE 19 satellite): per
        pool, the breaker state (through the supervisor's
        ``pool_health`` surface), the learned EWMA rates, and the
        in-flight depth. Engine-lock-free by construction — the only
        locks touched are the router's own leaf lock and the
        per-breaker locks, so the fleet front (and any scrape) can
        read it while the engine lock is held (the G16 SCRAPE_ROOTS
        contract tests/test_metrics.py asserts)."""
        try:
            health = self.supervisor.pool_health(pools=self._extra) \
                if self.supervisor is not None else {}
        except Exception:
            health = {}
        with self._lock:
            out = {}
            for name, p in self.pools.items():
                h = dict(health.get(name, {}))
                h["rows_per_s"] = {k: round(v, 1)
                                   for k, v in sorted(
                                       p.rates.items())}
                h["inflight_rows"] = p.inflight_rows
                out[name] = h
        return out
