"""Serve fleet: journal-replicated multi-worker failover (ISSUE 19).

One ``ServeEngine`` is one worker: one admission queue, one dispatch
serializer, one supervisor. A deployment that loses that process
loses every queued request until a restart replays the journal. This
module turns the journal into the fleet's REPLICATED LOG and makes
worker death a first-class serving event with a bounded blast
radius — lose a worker, lose 1/N of in-flight capacity and ZERO
accepted requests:

- ``WorkerLease``: each worker registers in the shared journal with a
  ``lease`` record and renews it with periodic ``heartbeat`` records
  (``$PINT_TPU_FLEET_HEARTBEAT_S``). Liveness is a JOURNAL fact, not
  an in-memory one — a worker partitioned from the journal looks
  exactly like a dead one, which is the only safe reading.
- ``FleetFront``: N workers over ONE journal and ONE AOT store.
  Submits round-robin across live workers; every journaled admit
  carries its owner (``worker=``). The front's expiry sweep compares
  each live worker's newest heartbeat against the lease TTL
  (``$PINT_TPU_FLEET_LEASE_TTL_S``); a missed lease FENCES the worker
  (``ServeEngine.kill`` — a fenced engine can never dispatch again,
  so the split-brain worker whose beats stopped reaching the journal
  cannot double-serve) and re-homes its unacknowledged admits onto a
  survivor: ``rehome`` records move ownership in the log, the
  survivor replays them through the normal replay path (bit-identical
  results — same kernels, same shape classes), and each survivor
  future's result is copied into the ORIGINAL caller's future, so
  every submitted request still resolves to exactly one
  ``serve.terminal``. Chunked kinds (posterior/GWB/append) re-home at
  their journaled chunk boundary exactly like a restart replay.
- AOT reuse: workers share one ``$PINT_TPU_AOT_DIR``, so a re-homed
  shape class that any worker ever exported restores on the survivor
  without a cold serve-kernel compile (tests/test_serve_restart.py).

Failure-injection kinds (``runtime.faults``): ``worker_kill`` at key
``fleet.worker/<id>`` kills that worker mid-burst; ``lease_expire``
at key ``fleet.lease/<id>`` forces that worker's lease to read as
expired at the next sweep without killing the engine first — the
fence in the sweep is what keeps the transfer safe.

Scope note (honest naming): ``FleetFront`` runs its N workers
in-process — the demo/bench/chaos surface. True cross-process fleets
run one ``pint_serve --worker-id`` per process over the same
``$PINT_TPU_JOURNAL``; the journal protocol (lease / heartbeat /
admit-with-owner / rehome) is identical, the front is then whatever
spawned the workers. Only requests WITH a journal payload get the
re-home guarantee: an in-memory-only request cannot be rebuilt on a
survivor (same contract as restart replay).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from pint_tpu import obs
from pint_tpu.runtime import faults, locks
from pint_tpu.serve.request import EngineKilled
from pint_tpu.serve.scheduler import ServeEngine

__all__ = ["WorkerLease", "FleetWorker", "FleetFront"]


class WorkerLease:
    """One worker's liveness in the shared journal: a ``lease``
    record at construction, ``heartbeat`` records on every
    ``beat()``. ``start()`` runs beats on a daemon thread at the
    configured cadence; tests drive ``beat()`` manually for
    determinism."""

    def __init__(self, journal, worker_id: str,
                 heartbeat_s: Optional[float] = None):
        from pint_tpu import config
        from pint_tpu.obs import metrics as om

        self.journal = journal
        self.worker_id = worker_id
        self.heartbeat_s = config.fleet_heartbeat_s() \
            if heartbeat_s is None else float(heartbeat_s)
        self._c_beats = om.counter(
            "pint_tpu_fleet_heartbeats_total",
            "fleet worker lease heartbeats written"
        ).child(scope=om.new_scope("fleet"), worker=worker_id)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.journal.lease(worker_id)
        self._c_beats.inc()  # the lease record is the first beat

    def beat(self):
        self.journal.heartbeat(self.worker_id)
        self._c_beats.inc()

    def start(self):
        if self._thread is not None:
            return self
        self._stop_evt.clear()

        def _loop():
            while not self._stop_evt.wait(self.heartbeat_s):
                self.beat()

        self._thread = threading.Thread(
            target=_loop, name=f"pint-lease-{self.worker_id}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None


class FleetWorker:
    """One fleet member: a ``ServeEngine`` plus its journal lease."""

    def __init__(self, worker_id: str, engine: ServeEngine,
                 lease: WorkerLease):
        self.worker_id = worker_id
        self.engine = engine
        self.lease = lease


def _copy_result(src_fut, dst_fut):
    """Resolve the original caller's future with the survivor's
    replayed result (or exception). Guarded: the original may have
    resolved already (a kill that raced an in-flight collect)."""

    def _done(f):
        if dst_fut.done():
            return
        e = f.exception()
        try:
            if e is not None:
                dst_fut.set_exception(e)
            else:
                dst_fut.set_result(f.result())
        except Exception:
            pass  # lost the resolve race — the earlier result stands

    src_fut.add_done_callback(_done)


class _FleetMetricsView:
    """Duck-typed ``ServeMetrics`` facade over the fleet — what the
    pint_serve stats path (``metrics.snapshot()``), the session
    snapshot, and the restart bookkeeping (``restart_info``) call,
    so ``--fleet`` drops into the daemon without a second code path.
    The top-level snapshot is the FIRST worker's (the stable key
    set every consumer expects) with fleet-wide totals overriding
    the throughput counters and per-worker detail alongside."""

    def __init__(self, front: "FleetFront"):
        self._front = front

    @property
    def restart_info(self):
        w = next(iter(self._front.workers.values()))
        return w.engine.metrics.restart_info

    def snapshot(self) -> dict:
        front = self._front
        per = {wid: w.engine.metrics.snapshot()
               for wid, w in front.workers.items()}
        snap = dict(next(iter(per.values())))
        for key in ("submitted", "completed", "queue_depth"):
            vals = [p.get(key) for p in per.values()
                    if p.get(key) is not None]
            if vals:
                snap[key] = sum(vals)
        snap["fleet"] = front.snapshot()
        snap["workers"] = per
        return snap

    def report(self) -> str:
        return "\n".join(
            f"[{wid}] {w.engine.metrics.report()}"
            for wid, w in self._front.workers.items())


class FleetFront:
    """N workers, one journal, one admission front.

    ``factory(payload)`` is the replay factory re-homing rebuilds
    requests with (same contract as ``ServeEngine.replay``).
    ``journal`` is the shared replicated log — a path (the front
    constructs and owns the ``RequestJournal``) or a prebuilt one.
    Workers run THREADED (``ServeEngine.start``): a synchronous
    future pumping a dead worker's queue would raise instead of
    waiting out a re-home.
    """

    # registry counter names (G13 vocabulary: mutate via .inc() only)
    _COUNTERS = ("rehomed", "lease_expiries", "worker_kills")

    def __init__(self, factory: Callable[[dict], object],
                 n: Optional[int] = None,
                 journal=None,
                 aot_dir: Optional[str] = None,
                 lease_ttl_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None,
                 start: bool = True,
                 engine_kwargs: Optional[dict] = None,
                 pools: Optional[Tuple[str, ...]] = None):
        from pint_tpu import config
        from pint_tpu.obs import metrics as om

        if journal is None:
            journal = config.journal_path()
        if journal is None:
            raise ValueError(
                "FleetFront needs a journal (path or RequestJournal) "
                "— the shared journal IS the fleet's replicated log")
        self._journal_owned = isinstance(journal, str)
        if isinstance(journal, str):
            from pint_tpu.serve.journal import RequestJournal

            journal = RequestJournal(journal)
        self.journal = journal
        self.factory = factory
        self.lease_ttl_s = config.fleet_lease_ttl_s() \
            if lease_ttl_s is None else float(lease_ttl_s)
        n = config.fleet_workers() if n is None else max(1, int(n))
        self._scope = om.new_scope("fleet")
        self._c = {
            name: om.counter(
                f"pint_tpu_fleet_{name}_total",
                f"fleet {name.replace('_', ' ')}"
            ).child(scope=self._scope)
            for name in self._COUNTERS}
        # fleet bookkeeping lock: a LEAF lock (never engine-marked —
        # submits must not fsync/dispatch under it; pick/track take
        # it briefly, the actual engine submit runs outside)
        self._lock = locks.make_lock("serve.fleet")
        self._rr = 0
        self._state: Dict[str, str] = {}    # live | dead | rehomed
        self._inflight: Dict[str, object] = {}  # rid -> original req
        self.workers: Dict[str, FleetWorker] = {}
        kw = dict(engine_kwargs or {})
        kw.setdefault("aot_dir", aot_dir)
        for i in range(n):
            wid = f"w{i}"
            eng = ServeEngine(journal=self.journal, worker_id=wid,
                              pools=pools, **kw)
            lease = WorkerLease(self.journal, wid,
                                heartbeat_s=heartbeat_s)
            self.workers[wid] = FleetWorker(wid, eng, lease)
            self._state[wid] = "live"
        self._sweep_stop = threading.Event()
        self._sweeper: Optional[threading.Thread] = None
        self.metrics = _FleetMetricsView(self)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self, sweep_s: Optional[float] = None):
        """Start every worker loop + lease heartbeat and the expiry
        sweeper (cadence defaults to half the heartbeat interval, so
        an expiry is noticed within ~TTL + heartbeat/2)."""
        for w in self.workers.values():
            w.engine.start()
            w.lease.start()
        if self._sweeper is None:
            if sweep_s is None:
                sweep_s = min(w.lease.heartbeat_s
                              for w in self.workers.values()) / 2.0
            self._sweep_stop.clear()

            def _loop():
                while not self._sweep_stop.wait(sweep_s):
                    try:
                        self.sweep()
                    except Exception:
                        pass  # the sweeper must outlive a bad sweep

            self._sweeper = threading.Thread(
                target=_loop, name="pint-fleet-sweep", daemon=True)
            self._sweeper.start()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None):
        """Stop the sweeper, leases, then every LIVE worker (bounded
        drain semantics per ``ServeEngine.stop``); close the journal
        if the front constructed it."""
        self._sweep_stop.set()
        t = self._sweeper
        if t is not None:
            t.join(timeout=10.0)
            self._sweeper = None
        for w in self.workers.values():
            w.lease.stop()
        for wid, w in self.workers.items():
            if self._state.get(wid) == "live":
                try:
                    w.engine.stop(drain=drain, timeout=timeout)
                except Exception:
                    pass
        if self._journal_owned:
            self.journal.close()

    # -- submission ----------------------------------------------------

    def _live_locked(self) -> List[str]:
        return [wid for wid, st in self._state.items()
                if st == "live"]

    def _pick_live(self) -> Optional[FleetWorker]:
        with self._lock:
            live = self._live_locked()
            if not live:
                return None
            wid = live[self._rr % len(live)]
            self._rr += 1
            return self.workers[wid]

    def _track(self, req):
        rid = getattr(req, "rid", None)
        if rid is None or getattr(req, "payload", None) is None:
            return  # unjournalable: no re-home guarantee

        with self._lock:
            self._inflight[rid] = req

        def _done(_f, rid=rid):
            with self._lock:
                self._inflight.pop(rid, None)

        req.future.add_done_callback(_done)

    def _poll_faults(self):
        plan = faults.active_plan()
        if plan is None:
            return
        with self._lock:
            live = self._live_locked()
        for wid in live:
            if plan.faults_for(f"fleet.worker/{wid}",
                               kinds=("worker_kill",)):
                self.kill_worker(wid)

    def submit(self, req):
        """Admit one request through a live worker. A worker that
        died between pick and submit is fenced and the next live one
        tried; with zero live workers the fleet is down and the
        submit raises ``EngineKilled`` (the caller's restart/retry
        signal, same as the single-engine contract)."""
        self._poll_faults()
        for _ in range(max(1, len(self.workers))):
            w = self._pick_live()
            if w is None:
                break
            try:
                fut = w.engine.submit(req)
            except EngineKilled:
                self._fence(w.worker_id, reason="submit_raced_kill")
                continue
            self._track(req)
            return fut
        raise EngineKilled("no live workers in the fleet")

    # -- failure handling ----------------------------------------------

    def _fence(self, wid: str, reason: str = "lease_expired"):
        """live -> dead: stop the lease, kill the engine (it can
        never dispatch again), leave its journal entries for the
        re-home pass. Idempotent."""
        with self._lock:
            if self._state.get(wid) != "live":
                return
            self._state[wid] = "dead"
        w = self.workers[wid]
        w.lease.stop()
        try:
            w.engine.kill()
        except Exception:
            pass
        obs.flight_dump(f"fleet_fence:{wid}", worker=wid,
                        fence_reason=reason)

    def kill_worker(self, wid: str):
        """The worker_kill fault (simulated worker SIGKILL): fence
        immediately — its heartbeats stop with it, and the normal
        sweep re-homes its unacked admits."""
        with self._lock:
            was_live = self._state.get(wid) == "live"
        if not was_live:
            return
        self._c["worker_kills"].inc()
        self._fence(wid, reason="worker_kill")

    def sweep(self, now: Optional[float] = None):
        """The liveness sweep: fence any live worker whose newest
        journal heartbeat is older than the lease TTL (or whose
        lease an injected ``lease_expire`` fault forces to read
        expired), then re-home every dead worker's unacknowledged
        admits onto a survivor. Returns the number of requests
        re-homed this pass. Safe to call from any thread; re-homing
        is serialized by worker state (dead -> rehomed exactly
        once)."""
        self._poll_faults()
        plan = faults.active_plan()
        beats = self.journal.workers()
        if now is None:
            now = time.time()
        with self._lock:
            live = self._live_locked()
        for wid in live:
            forced = plan is not None and plan.faults_for(
                f"fleet.lease/{wid}", kinds=("lease_expire",))
            stale = (now - beats.get(wid, 0.0)) > self.lease_ttl_s
            if forced or stale:
                self._c["lease_expiries"].inc()
                self._fence(wid, reason="lease_expire"
                            if forced else "heartbeat_stale")
        return self._rehome_dead()

    def _rehome_dead(self) -> int:
        moved = 0
        with self._lock:
            dead = [wid for wid, st in self._state.items()
                    if st == "dead"]
        for wid in dead:
            moved += self._rehome_one(wid)
        return moved

    def _rehome_one(self, wid: str) -> int:
        recs = self.journal.unacknowledged(owner=wid)
        survivor = self._pick_live()
        if survivor is None:
            return 0  # fleet-wide outage: stays dead, retried later
        with self._lock:
            if self._state.get(wid) != "dead":
                return 0
            self._state[wid] = "rehomed"
        try:
            with obs.span("fleet.rehome", worker=wid,
                          survivor=survivor.worker_id, n=len(recs)):
                for rec in recs:
                    self.journal.rehome(rec["rid"],
                                        survivor.worker_id)
                futs = survivor.engine.replay(self.factory,
                                              records=recs)
        except EngineKilled:
            # the survivor died under us: revert for the next sweep
            self._fence(survivor.worker_id,
                        reason="rehome_target_died")
            with self._lock:
                self._state[wid] = "dead"
            return 0
        for rec, fut in zip(recs, futs):
            with self._lock:
                orig = self._inflight.get(rec["rid"])
            if orig is not None and orig.future is not fut:
                # never pump the corpse: the original future must
                # wait for the survivor, not flush the dead engine
                orig.future._sync_engine = None
                _copy_result(fut, orig.future)
        self._c["rehomed"].inc(len(recs))
        return len(recs)

    # -- introspection -------------------------------------------------

    def live_workers(self) -> List[str]:
        with self._lock:
            return self._live_locked()

    def health_blocks(self) -> Dict[str, dict]:
        """Per-worker router pools block for /healthz — breaker
        state, learned EWMA rate, in-flight depth per capacity pool.
        Router leaf-lock reads only, NEVER an engine lock (the
        scrape-isolation contract, G16 part 2)."""
        return {wid: w.engine.router.health_block()
                for wid, w in self.workers.items()}

    def snapshot(self) -> dict:
        with self._lock:
            states = dict(self._state)
            inflight = len(self._inflight)
        out = {
            "workers": states,
            "live": [w for w, s in states.items() if s == "live"],
            "inflight_tracked": inflight,
            "lease_ttl_s": self.lease_ttl_s,
            "journal": self.journal.counts(),
            "counters": {name: int(c.value())
                         for name, c in self._c.items()},
        }
        out["engines"] = {
            wid: {"dead": bool(w.engine._dead),
                  "pools": w.engine.router.health_block()}
            for wid, w in self.workers.items()}
        return out
