"""Admission control: per-tenant token-bucket quotas + deadline-aware
load shedding + in-queue deadline expiry.

The serve layer's overload story before ISSUE 8 was binary: the queue
cap rejected everything past capacity and deadlines were only checked
at drain time — a million-user burst either hard-failed or silently
aged requests past their deadlines while they sat queued. This module
makes every shed decision EXPLICIT, LABELED, and policy-driven:

- **per-tenant token buckets** (``config.tenant_qps`` /
  ``$PINT_TPU_TENANT_QPS``, burst ``$PINT_TPU_TENANT_BURST``): each
  tenant refills at the configured rate; a drained bucket sheds with
  ``TenantOverQuota`` without touching shared capacity — one bursting
  tenant cannot starve the rest. Rate 0 (default) disables the
  bookkeeping entirely.
- **deadline-aware shedding** (``config.shed_policy``,
  ``$PINT_TPU_SHED_POLICY``): at capacity, shed the request that will
  miss its deadline ANYWAY — a queued request whose remaining budget
  is smaller than the router-predicted wait (or the newcomer itself,
  by the same test) — and never one that can still make it. Only when
  nobody is provably doomed does the submit degrade to plain
  backpressure rejection ("reject" restores the pre-ISSUE-8
  behavior unconditionally).
- **in-queue expiry** (the ``shed_expired`` counter): requests whose
  deadline passes while still queued are failed with
  ``DeadlineExceeded`` at the next admission or drain touch, not
  discovered dispatch-time after the batch already padded around
  them.

Fault hooks (``runtime.faults``, new kinds): an active plan's
``overload`` rule makes matching admissions see exhausted capacity
(exercising the shed policy without a real burst); ``tenant_burst``
drains the matching tenant's bucket on demand. Both are consumed
HERE, at admission — the dispatch supervisor never sees them.

Counters live on the controller and are embedded in
``ServeMetrics.snapshot()`` as the ``admission`` block — a shed
request is always visible in the artifact, never a silent drop.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

from pint_tpu.runtime import faults, locks

__all__ = ["TokenBucket", "AdmissionController"]

# shed-burst flight trigger (ISSUE 10): >= _BURST_N sheds inside
# _BURST_WINDOW_S dumps the tracer ring to $PINT_TPU_FLIGHT_DIR —
# a sustained shed storm is an incident, a lone deadline miss is not
_BURST_N = 16
_BURST_WINDOW_S = 5.0


class TokenBucket:
    """The classic token bucket: ``rate`` tokens/s refill up to
    ``burst`` capacity; ``take`` consumes one if available. Time is
    injected (monotonic seconds) so tests are deterministic."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._last = None  # first take() anchors the clock

    def take(self, now: float) -> bool:
        if self._last is None:
            self._last = now
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def drain(self):
        """Empty the bucket (the ``tenant_burst`` fault hook)."""
        self.tokens = 0.0


class AdmissionController:
    """Admission policy + shed accounting for one ServeEngine.

    The engine calls ``check_quota`` before classifying (a
    quota-shed request must not pay GLS assembly), and
    ``shed_decision`` when the queue is at capacity. Thread-safe: the
    engine may call from its submit path and its drain loop
    concurrently."""

    def __init__(self, tenant_qps: Optional[float] = None,
                 tenant_burst: Optional[float] = None,
                 policy: Optional[str] = None):
        from pint_tpu import config

        self.tenant_qps = config.tenant_qps() \
            if tenant_qps is None else max(0.0, float(tenant_qps))
        self.tenant_burst = (config.tenant_burst()
                             if tenant_burst is None
                             else max(1.0, float(tenant_burst)))
        self.policy = config.shed_policy() if policy is None \
            else str(policy)
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = locks.make_lock("serve.admission")
        # shed accounting (the admission block of the metrics
        # snapshot): every decision that drops a request lands in
        # exactly one of these. ISSUE 11: the counters are bound
        # children of the process metric registry
        # (pint_tpu_admission_*_total, scope-labelled) and the
        # attribute reads below are derived views — mutation goes
        # through bump() only (graftlint G13).
        from pint_tpu.obs import metrics as om

        self.scope = om.new_scope("adm")
        self._c = {
            name: om.counter(
                f"pint_tpu_admission_{name}_total",
                f"admission {name.replace('_', ' ')}"
            ).child(scope=self.scope)
            for name in self._COUNTERS}
        # per-tenant admit/shed accounting as a labelled counter
        self._tenant_counter = om.counter(
            "pint_tpu_admission_tenant_total",
            "per-tenant admission outcomes")
        self._tenant_names: set = set()
        # aggregate shed stream, labelled by kind — fed by note_shed
        # (called next to every shed counter bump); the shed-rate
        # SLO's numerator
        self._shed_total = om.counter(
            "pint_tpu_serve_shed_total",
            "sheds by kind (quota/deadline/expired/overload)")
        # recent shed stamps for the burst detector (bounded deque —
        # the detector needs only the last _BURST_N arrivals)
        self._shed_times: collections.deque = collections.deque(
            maxlen=_BURST_N)

    _COUNTERS = ("shed_expired", "shed_deadline", "shed_quota",
                 "shed_overload", "shed_shutdown",
                 "injected_overload", "shed_bursts")

    def __getattr__(self, name):
        c = self.__dict__.get("_c")
        if c is not None and name in type(self)._COUNTERS:
            return int(c[name].value())
        raise AttributeError(name)

    def bump(self, name: str, n: int = 1):
        """The ONE mutation surface for the admission counters
        (graftlint G13 flags ad-hoc attr increments in this layer)."""
        self._c[name].inc(n)

    @property
    def tenants(self) -> Dict[str, dict]:
        """Derived per-tenant view of the labelled registry counter
        (snapshot-compatible with the pre-ISSUE-11 dict)."""
        with self._lock:
            names = sorted(self._tenant_names)
        return {name: {
            "admitted": int(self._tenant_counter.value(
                scope=self.scope, tenant=name, outcome="admitted")),
            "shed": int(self._tenant_counter.value(
                scope=self.scope, tenant=name, outcome="shed")),
        } for name in names}

    def note_shed(self, kind: str):
        """Record one shed for the burst detector; a burst (>=
        ``_BURST_N`` sheds inside ``_BURST_WINDOW_S``) triggers a
        flight-recorder dump (rate-limited by the recorder itself).
        Called next to every shed counter bump — quota, deadline,
        expiry, overload. Several of those call sites hold the
        ENGINE lock (submit's shed paths, the expiry sweeps), and a
        shed storm is exactly when stalling admission behind a disk
        fsync would hurt most — so the dump itself runs on a
        detached daemon thread (bounded: one per burst trigger,
        which the recorder rate-limits to one per 10 s per reason)."""
        now = time.monotonic()
        self._shed_total.inc(scope=self.scope, kind=kind)
        with self._lock:
            self._shed_times.append(now)
            burst = (len(self._shed_times) == _BURST_N
                     and now - self._shed_times[0] <= _BURST_WINDOW_S)
            if burst:
                self._c["shed_bursts"].inc()
                self._shed_times.clear()
        if burst:
            from pint_tpu import obs

            obs.event("serve.shed_burst", kind=kind, n=_BURST_N,
                      window_s=_BURST_WINDOW_S)

            def dump():
                obs.flight_dump("shed_burst", last_kind=kind,
                                admission=self.snapshot())

            threading.Thread(target=dump, daemon=True,
                             name="pint-shed-burst-dump").start()

    # -- per-tenant quotas ---------------------------------------------

    def _note_tenant(self, name: str, outcome: str):
        """One tenant admission outcome into the labelled registry
        counter (the ``tenants`` property is its derived view).
        Caller holds ``self._lock`` (for the name set only — the
        counter has its own lock)."""
        self._tenant_names.add(name)
        self._tenant_counter.inc(scope=self.scope, tenant=name,
                                 outcome=outcome)

    def check_quota(self, tenant: Optional[str],
                    now: Optional[float] = None) -> bool:
        """True = within quota (token consumed). Also consumes the
        fault plan's ``tenant_burst`` rules: a matching rule drains
        the tenant's bucket first, so the NEXT take fails
        deterministically."""
        name = tenant or "default"
        plan = faults.active_plan()
        burst_hit = False
        if plan is not None:
            burst_hit = bool(plan.faults_for(
                f"serve.admit/{name}", kinds=("tenant_burst",)))
        if self.tenant_qps <= 0.0 and not burst_hit:
            return True
        with self._lock:
            b = self._buckets.get(name)
            if b is None:
                b = self._buckets[name] = TokenBucket(
                    max(self.tenant_qps, 0.0), self.tenant_burst)
            if burst_hit:
                b.drain()
            ok = b.take(time.monotonic() if now is None else now)
            if ok:
                self._note_tenant(name, "admitted")
            else:
                self._note_tenant(name, "shed")
                self._c["shed_quota"].inc()
        if not ok:
            self.note_shed("quota")
        return ok

    # -- capacity / shedding -------------------------------------------

    def capacity_exhausted(self, queued: int, cap: int) -> bool:
        """Queue-full test, including the fault plan's ``overload``
        rules (an injected overload makes THIS admission see a full
        queue regardless of the real depth)."""
        plan = faults.active_plan()
        if plan is not None and plan.faults_for(
                "serve.admit/capacity", kinds=("overload",)):
            self._c["injected_overload"].inc()
            return True
        return queued >= cap

    def shed_decision(self, newcomer, queued_waits,
                      newcomer_wait_s: float, now: float):
        """At-capacity policy decision. Returns one of

        - ``("victim", req)``: shed the queued ``req`` — it cannot
          make its deadline anyway — and admit the newcomer;
        - ``("newcomer", None)``: the newcomer itself cannot make its
          deadline; shed it (its future is failed, nothing raised);
        - ``("reject", None)``: nobody is provably doomed —
          backpressure-reject the newcomer (``ServeOverload``).

        ``queued_waits`` is ``[(req, predicted_wait_s)]`` with each
        wait computed POSITION-AWARE by the engine (only rows ahead
        of the candidate count — one prefix-sum pass, so the
        at-capacity decision stays O(n) under the engine lock);
        ``newcomer_wait_s`` is the same estimate for the newcomer.
        "Doomed" = remaining deadline budget < predicted wait. The
        policy NEVER sheds a request that can still make its
        deadline."""
        if self.policy == "reject":
            return ("reject", None)
        for r, wait in queued_waits:
            if r.expires_at is None:
                continue
            if r.expires_at - now < wait:
                return ("victim", r)
        if newcomer.deadline_s is not None and \
                float(newcomer.deadline_s) < newcomer_wait_s:
            return ("newcomer", None)
        return ("reject", None)

    # -- reporting -----------------------------------------------------

    def snapshot(self) -> dict:
        # no self._lock here: every field is a registry read with
        # its own metric lock (the tenants property takes self._lock
        # for the name set) — a snapshot must never serialize behind
        # the admission hot path
        return {
            "policy": self.policy,
            "tenant_qps": self.tenant_qps,
            "shed_expired": self.shed_expired,
            "shed_deadline": self.shed_deadline,
            "shed_quota": self.shed_quota,
            "shed_overload": self.shed_overload,
            "shed_shutdown": self.shed_shutdown,
            "shed_bursts": self.shed_bursts,
            "injected_overload": self.injected_overload,
            "tenants": {k: dict(v)
                        for k, v in sorted(self.tenants.items())},
        }

    @property
    def total_shed(self) -> int:
        return (self.shed_expired + self.shed_deadline +
                self.shed_quota + self.shed_overload +
                self.shed_shutdown)
