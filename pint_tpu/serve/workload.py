"""The ONE synthetic mixed-shape serve workload builder.

Used by both ``bench_serve.py`` (sequential-vs-coalesced throughput
artifact) and ``scripts/pint_serve.py --demo`` (the daemon demo) —
previously two near-identical copies that could drift apart, flagged
in the PR-3 review. The workload: small simulated pulsars across a
few TOA-count classes (so several shape buckets are exercised), a
mod-7 sprinkle of polyco phase reads and a mod-3 sprinkle of
residual requests between the fit steps.

Two consumption modes:

- ``prebuild=True`` (bench): assemble each pulsar's linearized
  ``PulsarProblem`` once and share it across request objects — the
  serving-state hot path, so the measured loop is dispatch work, not
  model assembly;
- ``prebuild=False`` (demo daemon): requests carry (toas, model) and
  assemble at dispatch, exercising the admission-side path too.
"""

from __future__ import annotations

import io
import warnings
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["BENCH_SIZES", "DEMO_SIZES", "synth_pulsar",
           "demo_polyco_entry", "build_workload"]

# six pulsars over three TOA buckets (64/128/256) — the committed
# bench_serve artifact's shape mix (ARCHITECTURE.md "Serving layer")
BENCH_SIZES: Tuple[int, ...] = (50, 60, 100, 120, 200, 180)
# the demo daemon's smaller three-class mix
DEMO_SIZES: Tuple[int, ...] = (50, 100, 200)


def synth_pulsar(k: int, ntoa: int, base: int = 1300):
    """One simulated white-noise pulsar (model, toas), deterministic
    per (k, ntoa, base); F0 perturbed so a fit step has real work."""
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = (f"PSR J{base + k}\nRAJ 12:0{k % 10}:00.0 1\n"
           f"DECJ 30:0{k % 10}:00.0 1\nF0 {150.0 + 31.0 * k} 1\n"
           f"F1 -1e-15 1\nPEPOCH 55000\nPOSEPOCH 55000\n"
           f"DM {10 + k} 1\nTZRMJD 55000.1\nTZRSITE @\n"
           f"TZRFRQ 1400\nUNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_uniform(
            54000, 56000, ntoa, m, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(k))
    m.F0.add_delta(1e-10)
    m.invalidate_cache(params_only=True)
    return m, t


def demo_polyco_entry(psrname: str = "DEMO"):
    """The fixed polyco segment every phase read in the workload
    evaluates (host oracle: ``PolycoEntry.abs_phase``)."""
    from pint_tpu.polycos import PolycoEntry

    return PolycoEntry(
        psrname=psrname, tmid=55000.0, rphase_int=1e9,
        rphase_frac=0.25, f0=200.0, obs="@", span_min=60.0,
        coeffs=np.array([0.02, 1e-3, -2e-5, 1e-7]))


def build_workload(nreq: int,
                   sizes: Optional[Sequence[int]] = None,
                   base: int = 1300, prebuild: bool = True,
                   with_kinds: bool = False,
                   entry_name: str = "BENCH"):
    """Return ``fresh()``, a zero-arg builder of the request list.

    Request objects are single-shot (their future resolves once), so
    callers rebuild the list per pass while the expensive parts (the
    pulsars, the prebuilt problems, the polyco entry) are shared.
    ``with_kinds`` yields (kind, request) tuples (the demo daemon's
    form) instead of bare requests.
    """
    from pint_tpu.serve import (
        FitStepRequest,
        PhasePredictRequest,
        ResidualsRequest,
    )

    sizes = tuple(BENCH_SIZES if sizes is None else sizes)
    pulsars = [synth_pulsar(k, ntoa, base=base)
               for k, ntoa in enumerate(sizes)]
    problems = None
    if prebuild:
        from pint_tpu.parallel.pta import build_problem

        problems = [build_problem(t, m) for m, t in pulsars]
    entry = demo_polyco_entry(entry_name)

    def fresh():
        reqs = []
        for i in range(nreq):
            j = i % len(pulsars)
            if i % 7 == 6:
                mjds = 55000.0 + np.linspace(-0.01, 0.01, 24)
                kind, rq = "phase", PhasePredictRequest(entry, mjds)
            elif i % 3 == 2:
                kind = "residuals"
                rq = ResidualsRequest(problem=problems[j]) if prebuild \
                    else ResidualsRequest(*reversed(pulsars[j]))
            else:
                kind = "fit_step"
                rq = FitStepRequest(problem=problems[j]) if prebuild \
                    else FitStepRequest(*reversed(pulsars[j]))
            reqs.append((kind, rq) if with_kinds else rq)
        return reqs

    return fresh
