"""Crash-safe restart: append-only request journal, AOT-exported
bucket executables, serve-state snapshot.

A process restart used to cost the full trace+compile+first-run of
every shape class (~32 s measured for the bench mix) AND silently
forgot every queued request. This module makes restart a first-class
serving event:

- ``RequestJournal``: an append-only JSONL journal. Every journalable
  admission (a request carrying a ``payload`` — an opaque JSON-able
  description the caller's replay factory can rebuild from) is
  recorded BEFORE dispatch and acknowledged with a status label
  (served / shed:* / failed) on completion; each line is flushed and
  fsynced so a SIGKILL loses at most the line being written. A cold
  restart reads the journal and replays exactly the entries without
  an ack (``ServeEngine.replay``).
- ``AotStore``: ``jax.export`` StableHLO artifacts of the engine's
  bucket executables, one file per (kind, shape-class) keyed by a
  manifest that records platform / jax version / x64 / donation —
  artifacts from a foreign configuration are skipped, never
  mis-served. Export happens right after a class's first successful
  device dispatch (crash-safe: the artifact exists as soon as the
  compile it replaces does); restore deserializes and PRIMES each
  artifact at engine construction — the XLA binary compile of the
  restored module (seeded by the feature-keyed persistent jit cache)
  is paid at restore time, so the first served request compiles
  NOTHING (Sanitizer ``_cache_size``-asserted in
  tests/test_serve_restart.py). Priming runs through the dispatch
  supervisor: restoring against a wedged backend degrades to a cold
  engine instead of hanging init.
- ``save_state``/``load_state``: the serve-state snapshot
  (``state.json`` in the AOT dir): metrics snapshot + shape-class
  manifest + shutdown reason, written on ``ServeEngine.stop`` so the
  restarted process can label itself warm/cold honestly in the
  ``restart`` block of its artifacts.

The LAPACK note: on this jax/CPU build a deserialized module whose
program carries LAPACK custom calls (the GLS solve's cholesky)
SEGFAULTS if invoked before the in-process FFI handlers are
registered; ``AotStore.restore_all`` therefore runs a tiny
registration warmup through a throwaway jit before the first
restored call. The warmup is supervised like any other dispatch.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from pint_tpu.runtime import locks
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["RequestJournal", "AotStore", "save_state", "load_state"]


# ------------------------------------------------------------------
# request journal
# ------------------------------------------------------------------


class RequestJournal:
    """Append-only JSONL request journal.

    Line forms::

        {"op": "admit", "rid": ..., "payload": {...}, "tenant": ...}
        {"op": "ack",   "rid": ..., "status": "served" | "shed:..." |
                                              "failed" | "replayed"}

    ``unacknowledged()`` returns admit records with no terminal ack,
    in admit order — the replay set. "replayed" is a progress marker
    (the restarted engine re-admitted the entry), not a terminal
    status; a crash DURING replay leaves the entry replayable again.

    **Fleet ownership protocol** (ISSUE 19): the journal doubles as
    the fleet's replicated log. Admit records may carry a
    ``"worker"`` owner; ``lease``/``heartbeat`` records register a
    worker and renew its lease (``workers()`` reads the newest
    heartbeat per worker); a ``rehome`` record transfers an admit's
    ownership to a survivor (applied at scan time, so
    ``unacknowledged(owner=...)`` — the per-worker replay set —
    always reflects the LAST recorded owner and a re-homed entry is
    never replayed twice by two workers)::

        {"op": "lease",     "worker": W, "t": ...}
        {"op": "heartbeat", "worker": W, "t": ...}
        {"op": "rehome",    "rid": ..., "worker": W}

    **Torn-record hardening** (ISSUE 19 satellite): a crash
    mid-append leaves a partial last line, and records interleaved
    around a ``compact()`` can leave stale bytes; every scan
    warn-and-skips any unparseable (or non-object) record — counted
    once per distinct record in ``pint_tpu_journal_torn_records`` —
    and NEVER raises: a damaged journal degrades to a smaller replay
    set, not a dead restart path.

    Long-running chunked work (a posterior chain) additionally writes
    ``progress`` lines between its chunk dispatches — non-terminal
    marks recording how far a request got before a crash. They are
    informational (replay restarts the chain from scratch — chunk
    results are not persisted) and are dropped by compaction.

    **Compaction** (ISSUE 9 satellite): an append-only journal on a
    long-lived deployment grows without bound even though the replay
    set stays tiny. ``compact()`` rewrites the file to exactly the
    unacknowledged admit records (original lines verbatim, admit
    order preserved) via atomic tmp + fsync + rename — a crash
    mid-compaction leaves the previous journal intact, and replay
    after compaction is bit-identical to replay before it
    (tests/test_serve_restart.py). Auto-triggered after an append
    pushes the file past ``config.journal_compact_bytes()``
    ($PINT_TPU_JOURNAL_COMPACT_BYTES, 0 disables).
    """

    _TERMINAL = ("served", "failed", "shed")

    def __init__(self, path: str,
                 compact_bytes: Optional[int] = None):
        from pint_tpu.obs import metrics as om

        self.path = path
        self._lock = locks.make_lock("serve.journal")
        self._fh = None
        # ISSUE 11: compaction count rides the metric registry (the
        # counts() dict reads it back — derived view, G13-clean)
        _scope = om.new_scope("journal")
        self._c_compactions = om.counter(
            "pint_tpu_journal_compactions_total",
            "journal auto/explicit compactions"
        ).child(scope=_scope)
        # ISSUE 19 satellite: unparseable records warn-and-skip at
        # scan, counted once per distinct damaged line (scans repeat;
        # the damage does not)
        self._c_torn = om.counter(
            "pint_tpu_journal_torn_records",
            "unparseable journal records skipped at scan"
        ).child(scope=_scope)
        self._torn_seen: set = set()
        if compact_bytes is None:
            from pint_tpu import config

            compact_bytes = config.journal_compact_bytes()
        self._compact_bytes = max(0, int(compact_bytes))
        self._next_compact = self._compact_bytes
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        # a crash mid-write leaves a torn tail line WITHOUT a
        # newline; appending straight onto it would concatenate the
        # next record into the unparseable tail and lose BOTH
        torn = False
        try:
            with open(path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() > 0:
                    fh.seek(-1, os.SEEK_END)
                    torn = fh.read(1) != b"\n"
        except OSError:
            pass
        self._fh = open(path, "a", encoding="utf-8")
        if torn:
            self._fh.write("\n")
            self._fh.flush()
        self._bytes = self._fh.tell()

    # -- writes --------------------------------------------------------

    def _append(self, rec: dict):
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._fh is None or self._fh.closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._bytes += len(line) + 1
            if self._compact_bytes and self._bytes > self._next_compact:
                self._compact_locked()

    def admit(self, rid: str, payload: dict,
              tenant: Optional[str] = None,
              deadline_s: Optional[float] = None,
              worker: Optional[str] = None):
        rec = {"op": "admit", "rid": rid, "payload": payload}
        if tenant is not None:
            rec["tenant"] = tenant
        if deadline_s is not None:
            rec["deadline_s"] = deadline_s
        if worker is not None:
            rec["worker"] = worker
        self._append(rec)

    def ack(self, rid: str, status: str):
        self._append({"op": "ack", "rid": rid, "status": status})

    # -- fleet ownership (ISSUE 19) ------------------------------------

    def lease(self, worker: str):
        """Register ``worker`` as a fleet member (first heartbeat)."""
        self._append({"op": "lease", "worker": worker,
                      "t": time.time()})

    def heartbeat(self, worker: str):
        """Renew ``worker``'s lease. The fleet front's expiry sweep
        compares the newest heartbeat per worker against the lease
        TTL — a worker whose beats stop (killed OR partitioned from
        the journal) reads as expired and its unacked admits are
        re-homed."""
        self._append({"op": "heartbeat", "worker": worker,
                      "t": time.time()})

    def rehome(self, rid: str, worker: str):
        """Transfer ownership of one admit to ``worker``. Applied at
        scan time (last rehome wins), so the per-owner replay set
        moves with the record and survives compaction."""
        self._append({"op": "rehome", "rid": rid, "worker": worker})

    def progress(self, rid: str, steps: int):
        """Non-terminal progress mark for chunked work (a posterior
        chain records steps completed after every chunk dispatch):
        visible in a post-crash journal scan, dropped by compaction,
        ignored by the replay-set computation."""
        self._append({"op": "progress", "rid": rid,
                      "steps": int(steps)})

    # -- compaction ----------------------------------------------------

    def compact(self):
        """Rewrite the journal to exactly its unacknowledged admit
        records (atomic tmp + fsync + rename; original admit lines
        preserved verbatim and in order, so replay after compaction
        is bit-identical to replay before it)."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self):
        keep = self.unacknowledged_unlocked()
        # fleet liveness survives compaction: one heartbeat record
        # per leased worker at its newest recorded time (ISSUE 19 —
        # compacting mid-fleet must not make every worker read as
        # never-leased / instantly-expired)
        _, _, beats = self._scan()
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for rec in keep:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
            for w in sorted(beats):
                fh.write(json.dumps(
                    {"op": "heartbeat", "worker": w, "t": beats[w]},
                    sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        reopen = self._fh is not None and not self._fh.closed
        if reopen:
            self._fh.close()
        self._fh = open(self.path, "a", encoding="utf-8")
        self._bytes = self._fh.tell()
        if not reopen:
            # compacting a closed journal leaves it closed
            self._fh.close()
        self._c_compactions.inc()
        # hysteresis: when the LIVE unacknowledged set itself exceeds
        # the threshold, compaction cannot shrink below it — without
        # a backoff every subsequent append would re-scan and rewrite
        # the whole file under the lock (O(file) per append during
        # exactly the backed-up outage this journal exists for). The
        # next auto-trigger waits for the file to double instead.
        if self._compact_bytes:
            self._next_compact = max(self._compact_bytes,
                                     2 * self._bytes)

    @property
    def compactions(self) -> int:
        return int(self._c_compactions.value())

    def close(self):
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()

    # -- reads ---------------------------------------------------------

    def _torn_locked(self, line: str):
        """Count one unparseable record, once per distinct line —
        scans repeat every restart/compaction; the damage does not.
        Warn-and-skip, NEVER raise (ISSUE 19 satellite)."""
        h = hashlib.sha256(line.encode("utf-8", "replace")).digest()
        if h in self._torn_seen:
            return
        self._torn_seen.add(h)
        self._c_torn.inc()
        _log().warning("journal %s: skipping torn/unparseable "
                       "record (%d bytes)", self.path, len(line))

    def _scan(self) -> Tuple[List[dict], Dict[str, str],
                             Dict[str, float]]:
        """One pass over the file: (admits with ownership rehomes
        applied, terminal acks by rid, newest heartbeat per worker).
        Callers hold ``self._lock`` (scan races auto-compaction's
        rewrite+rename otherwise)."""
        admits: List[dict] = []
        acks: Dict[str, str] = {}
        beats: Dict[str, float] = {}
        rehomes: Dict[str, str] = {}
        try:
            with open(self.path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        self._torn_locked(line)
                        continue
                    if not isinstance(rec, dict):
                        # parses but is not a record (a bare scalar
                        # from interleaved torn writes)
                        self._torn_locked(line)
                        continue
                    op = rec.get("op")
                    if op == "admit":
                        admits.append(rec)
                    elif op == "ack":
                        st = str(rec.get("status", ""))
                        if st.split(":", 1)[0] in self._TERMINAL:
                            acks[rec.get("rid")] = st
                    elif op in ("lease", "heartbeat"):
                        w = rec.get("worker")
                        if w is not None:
                            try:
                                t = float(rec.get("t", 0.0))
                            except (TypeError, ValueError):
                                t = 0.0
                            beats[w] = max(beats.get(w, 0.0), t)
                    elif op == "rehome":
                        rid, w = rec.get("rid"), rec.get("worker")
                        if rid is not None and w is not None:
                            rehomes[rid] = w
        except OSError:
            pass
        if rehomes:
            # last recorded owner wins; applied to a COPY so the
            # verbatim admit line is what compaction re-serializes
            # only when ownership did not move
            admits = [
                dict(rec, worker=rehomes[rec.get("rid")])
                if rec.get("rid") in rehomes else rec
                for rec in admits]
        return admits, acks, beats

    def unacknowledged_unlocked(
            self, owner: Optional[str] = None) -> List[dict]:
        admits, acks, _ = self._scan()
        seen = set()
        out = []
        for rec in admits:
            rid = rec.get("rid")
            if rid in acks or rid in seen:
                continue
            seen.add(rid)
            if owner is not None and rec.get("worker") != owner:
                continue
            out.append(rec)
        return out

    def unacknowledged(self,
                       owner: Optional[str] = None) -> List[dict]:
        # under the lock so a concurrent auto-compaction's
        # rewrite+rename never races the scan. ``owner`` filters to
        # one worker's replay set (fleet re-home path).
        with self._lock:
            return self.unacknowledged_unlocked(owner)

    def workers(self) -> Dict[str, float]:
        """Newest heartbeat time per leased worker."""
        with self._lock:
            _, _, beats = self._scan()
            return beats

    def counts(self) -> dict:
        with self._lock:
            admits, acks, beats = self._scan()
            unacked = len(self.unacknowledged_unlocked())
            return {"admitted": len(admits), "acked": len(acks),
                    "unacknowledged": unacked,
                    "compactions": self.compactions,
                    "torn": int(self._c_torn.value()),
                    "workers": len(beats),
                    "bytes": self._bytes}


# ------------------------------------------------------------------
# AOT executable store
# ------------------------------------------------------------------


def _fingerprint() -> dict:
    """The configuration an artifact is only valid under."""
    import jax

    return {"jax": jax.__version__,
            "platform": jax.default_backend(),
            "x64": bool(jax.config.jax_enable_x64)}


def _key_str(kind: str, full_key: tuple) -> str:
    return kind + "/" + "/".join(str(x) for x in full_key)


class AotStore:
    """Serialized-executable store for one engine's bucket kernels.

    ``save(kind, full_key, jit_fn, avals)`` exports the jitted kernel
    at the class's exact avals and writes artifact + manifest
    atomically; ``restore_all(supervisor)`` deserializes every
    manifest entry matching the current configuration, wraps each in
    a fresh ``jax.jit`` (so repeat dispatches reuse one compiled
    module) and primes it with masking-safe zero batches so no
    compile is left for the first real request. Restored callables
    are fetched with ``get``."""

    _COUNTERS = ("exported", "export_errors", "restore_errors",
                 "hits", "misses")

    def __init__(self, dirpath: str, donation: bool = False):
        from pint_tpu.obs import metrics as om

        self.dir = dirpath
        self.donation = bool(donation)
        os.makedirs(dirpath, exist_ok=True)
        self._manifest_path = os.path.join(dirpath, "manifest.json")
        self._restored: Dict[str, Callable] = {}
        self._saved: set = set()
        self._lock = locks.make_lock("serve.aot_store")
        # ISSUE 11: registry-backed counters (scope-labelled), read
        # back via __getattr__ — snapshot() stays a derived view;
        # hits/misses count restored-executable lookups at dispatch
        # time (the warm-restart effectiveness gauge)
        self._scope = om.new_scope("aot")
        self._c = {
            name: om.counter(
                f"pint_tpu_aot_{name}_total",
                f"AOT store {name.replace('_', ' ')}"
            ).child(scope=self._scope)
            for name in self._COUNTERS}
        self._g_restored = om.gauge(
            "pint_tpu_aot_restored",
            "restored executables held").child(scope=self._scope)
        self.restored = 0

    def __getattr__(self, name):
        c = self.__dict__.get("_c")
        if c is not None and name in type(self)._COUNTERS:
            return int(c[name].value())
        raise AttributeError(name)

    # -- manifest ------------------------------------------------------

    def _read_manifest(self) -> dict:
        try:
            with open(self._manifest_path, encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return {}

    def _write_manifest(self, manifest: dict):
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._manifest_path)

    # -- export --------------------------------------------------------

    def has(self, kind: str, full_key: tuple) -> bool:
        ks = _key_str(kind, full_key)
        with self._lock:
            return ks in self._saved or ks in self._restored

    def save(self, kind: str, full_key: tuple, jit_fn, avals):
        """Export one compiled class (trace at ``avals`` — abstract
        ShapeDtypeStructs, no device work) and persist it. Failures
        are counted, never raised: AOT is an optimization, losing an
        artifact must not fail the dispatch that just succeeded."""
        ks = _key_str(kind, full_key)
        with self._lock:
            if ks in self._saved or ks in self._restored:
                return
            self._saved.add(ks)  # one attempt per key, even on error
        try:
            from jax import export as jexport

            exp = jexport.export(jit_fn)(*avals)
            blob = exp.serialize()
            fname = hashlib.sha256(
                (ks + json.dumps(_fingerprint(), sort_keys=True)
                 ).encode()).hexdigest()[:16] + ".bin"
            tmp = os.path.join(self.dir, fname + ".tmp")
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, os.path.join(self.dir, fname))
            with self._lock:
                manifest = self._read_manifest()
                manifest[ks] = {
                    "kind": kind,
                    "key": list(full_key),
                    "file": fname,
                    "avals": [[list(a.shape), str(a.dtype)]
                              for a in avals],
                    "donation": self.donation,
                    **_fingerprint(),
                }
                self._write_manifest(manifest)
            self._c["exported"].inc()
        except Exception as e:
            self._c["export_errors"].inc()
            _log().warning("AOT export of %s failed: %r", ks, e)

    # -- restore -------------------------------------------------------

    def restore_all(self, supervisor=None) -> int:
        """Deserialize + prime every compatible artifact. Returns the
        number restored. Priming (and the LAPACK FFI registration
        warmup) runs through ``supervisor.dispatch`` so a wedged
        backend degrades to a cold engine rather than hanging
        construction; any per-artifact failure skips that artifact.
        """
        import numpy as np

        manifest = self._read_manifest()
        if not manifest:
            return 0
        fp = _fingerprint()
        compatible = {
            ks: ent for ks, ent in manifest.items()
            if all(ent.get(k) == v for k, v in fp.items())
            and bool(ent.get("donation", False)) == self.donation}
        if not compatible:
            return 0
        import jax
        import jax.numpy as jnp
        from jax import export as jexport

        def _primed():
            # LAPACK FFI registration warmup: a restored module's
            # custom calls (the GLS cholesky) segfault on this build
            # unless the in-process handlers registered first — one
            # tiny host cholesky does that. Then prime each restored
            # module with a masking-safe zero batch (valid/pvalid all
            # zero = the padded-slot path the kernels are built for)
            # so its XLA binary compile happens NOW, not on the first
            # served request.
            np.asarray(jax.jit(jnp.linalg.cholesky)(jnp.eye(2)))  # graftlint: allow G6 -- registration warmup inside the supervised restore dispatch
            restored = {}
            for ks, ent in compatible.items():
                try:
                    with open(os.path.join(self.dir, ent["file"]),
                              "rb") as fh:
                        exp = jexport.deserialize(fh.read())
                    fn = jax.jit(exp.call)
                    zeros = tuple(
                        jnp.zeros(tuple(shape), dtype=dtype)
                        for shape, dtype in ent["avals"])
                    out = fn(*zeros)  # graftlint: allow G6 -- priming inside the supervised restore dispatch
                    jax.tree_util.tree_map(np.asarray, out)
                    restored[ks] = fn
                except Exception as e:
                    self._c["restore_errors"].inc()
                    _log().warning("AOT restore of %s failed: %r",
                                   ks, e)
            return restored

        try:
            if supervisor is not None:
                from pint_tpu import obs

                with obs.span("serve.aot_restore",
                              n=len(compatible)):
                    restored = supervisor.dispatch(
                        _primed, key="serve.aot_restore",
                        fallback=lambda: {})
            else:
                restored = _primed()
        except Exception as e:
            self._c["restore_errors"].inc()
            _log().warning("AOT restore pass failed: %r", e)
            restored = {}
        with self._lock:
            self._restored.update(restored)
            self.restored = len(self._restored)
            self._g_restored.set(self.restored)
        # ISSUE 15: restored executables are COMPILES this process
        # never paid for — the ledger records them with
        # aot_restored=True (key spelled as the scheduler's dispatch
        # key, so a later first_call merges into the same entry)
        try:
            from pint_tpu.obs import perf as _perf

            for ks in restored:
                _perf.note_compile(f"serve.{ks}",
                                   backend=fp.get("platform"),
                                   kind="aot", aot_restored=True)
        except Exception:
            pass
        return self.restored

    def get(self, kind: str, full_key: tuple) -> Optional[Callable]:
        with self._lock:
            fn = self._restored.get(_key_str(kind, full_key))
        # restore hit/miss accounting (ISSUE 11): a dispatch-time
        # lookup that finds a restored executable is a warm-restart
        # win; a miss is a class this process compiled itself
        self._c["hits" if fn is not None else "misses"].inc()
        return fn

    def snapshot(self) -> dict:
        with self._lock:
            restored = self.restored
        return {"dir": self.dir,
                "restored": restored,
                "exported": self.exported,
                "export_errors": self.export_errors,
                "restore_errors": self.restore_errors,
                "hits": self.hits,
                "misses": self.misses}


# ------------------------------------------------------------------
# serve-state snapshot
# ------------------------------------------------------------------


def save_state(dirpath: str, snapshot: dict,
               reason: str = "shutdown"):
    """Write the serve-state snapshot (``state.json`` in the AOT
    dir): the engine metrics snapshot + shutdown reason. Atomic, so
    a crash mid-write leaves the previous snapshot intact."""
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, "state.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"reason": reason, "metrics": snapshot}, fh,
                  indent=1, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def load_state(dirpath: str) -> Optional[dict]:
    try:
        with open(os.path.join(dirpath, "state.json"),
                  encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _log():
    from pint_tpu.logging import log

    return log
