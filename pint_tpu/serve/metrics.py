"""Serving metrics: per-bucket counters surfaced through the existing
profiling layer.

``profiling.Scoreboard`` already accumulates named wall-clock phases
process-wide; the serve layer feeds it (``serve.assemble`` /
``serve.dispatch`` annotations ride ``profiling.annotate``, so they
show up in device traces too) and adds the serving-specific view a
scoreboard cannot express: queue depth, batch occupancy, padded-waste
fraction, per-bucket latency quantiles, compile counts.

Everything here is host bookkeeping — a few dict updates per BATCH,
not per TOA — so it stays on unconditionally (same design stance as
``FitStats``).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

__all__ = ["BucketStats", "ServeMetrics", "percentile"]

# per-bucket latency reservoir cap: enough for stable p99 at serving
# rates while bounding memory on a long-lived engine (newest kept —
# serving cares about current behavior, not the cold start)
_LAT_CAP = 4096


def percentile(sorted_xs: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (no numpy
    dependency on the hot path; empty -> nan)."""
    if not sorted_xs:
        return float("nan")
    k = min(len(sorted_xs) - 1,
            max(0, int(round(q / 100.0 * (len(sorted_xs) - 1)))))
    return sorted_xs[k]


def _pct_ms(sorted_xs: List[float], q: float) -> Optional[float]:
    """percentile in ms for a JSON snapshot: None (valid JSON null)
    when there are no samples — json.dumps would otherwise emit the
    bare NaN token, which strict parsers reject."""
    if not sorted_xs:
        return None
    return round(percentile(sorted_xs, q) * 1e3, 3)


class BucketStats:
    """Counters for one shape class (one executable) — registry-
    backed (ISSUE 11): each stat is a bound child of the
    ``pint_tpu_serve_bucket_*_total`` counters labelled
    (scope, cls), read back through ``__getattr__`` so the snapshot
    stays a derived view. The latency reservoir is per-sample state,
    not a counter, and stays local."""

    _COUNTERS = ("requests", "batches", "slots", "rows_real",
                 "rows_padded")

    def __init__(self, scope: str = "", cls: str = ""):
        from pint_tpu.obs import metrics as om

        self._c = {
            name: om.counter(
                f"pint_tpu_serve_bucket_{name}_total",
                f"per-shape-class {name.replace('_', ' ')}"
            ).child(scope=scope, cls=cls)
            for name in self._COUNTERS}
        self.latencies_s: List[float] = []  # admit -> future resolved

    def __getattr__(self, name):
        c = self.__dict__.get("_c")
        if c is not None and name in type(self)._COUNTERS:
            return int(c[name].value())
        raise AttributeError(name)

    def record(self, nreal: int, pb: int, rows_real: int,
               rows_padded: int, lats: List[float]):
        self._c["requests"].inc(nreal)
        self._c["batches"].inc()
        self._c["slots"].inc(pb)
        self._c["rows_real"].inc(rows_real)
        self._c["rows_padded"].inc(rows_padded)
        self.latencies_s.extend(lats)
        if len(self.latencies_s) > _LAT_CAP:
            del self.latencies_s[:-_LAT_CAP]

    @property
    def occupancy(self) -> float:
        """Mean fraction of batch slots holding real requests."""
        return self.requests / self.slots if self.slots else 0.0

    @property
    def padded_waste(self) -> float:
        """Fraction of dispatched rows that were padding."""
        tot = self.rows_padded
        return 1.0 - self.rows_real / tot if tot else 0.0

    def snapshot(self) -> dict:
        lats = sorted(self.latencies_s)
        return {
            "requests": self.requests, "batches": self.batches,
            "occupancy": round(self.occupancy, 4),
            "padded_waste": round(self.padded_waste, 4),
            "p50_ms": _pct_ms(lats, 50),
            "p99_ms": _pct_ms(lats, 99),
        }


class ServeMetrics:
    """Engine-wide serving counters + the per-bucket table.

    ``cache`` is the engine's ExecutableCache — compile counts are
    read from it live so the metrics can never disagree with the
    thing that actually compiled."""

    def __init__(self, cache=None, supervisor=None,
                 pipeline_depth: int = 1, donation: bool = False,
                 admission=None, router=None):
        self.cache = cache
        self.supervisor = supervisor
        self.pipeline_depth = pipeline_depth   # configured in-flight cap
        self.donation = donation               # buffer donation on?
        # ISSUE 8 observability: the admission controller's shed
        # counters, the capacity router's per-pool shares, and the
        # engine's restart provenance ride every snapshot — a shed,
        # rerouted or replayed request is always visible in the
        # artifact, never a silent drop
        self.admission = admission
        self.router = router
        self.append_store = None   # wired by the engine (ISSUE 12)
        self.restart_info: dict = {}
        # log-bucketed latency histograms per (pool, kind, class) x
        # (queue_wait | dispatch_wall | e2e) — fixed power-of-two
        # buckets, O(1) memory, p50/p90/p99/max without per-sample
        # storage (ISSUE 10; the scheduler records into it at every
        # dispatch finish). The per-bucket reservoir above remains
        # the exact-quantile view of RECENT traffic; this is the
        # unbounded-horizon tail view the artifacts embed. ISSUE 11:
        # rows are SHARED with the registry's
        # pint_tpu_serve_latency_seconds histogram and the engine
        # counters are bound registry children (scope-labelled), so
        # snapshot() is a derived view of the metrics plane.
        from pint_tpu.obs import HistogramSet
        from pint_tpu.obs import metrics as om

        self.scope = om.new_scope("serve")
        hist = om.histogram(
            "pint_tpu_serve_latency_seconds",
            "serve latency per (pool, kind, class) x "
            "(queue_wait|dispatch_wall|e2e)")
        scope = self.scope
        self.latency = HistogramSet(
            row_factory=lambda key, metric: hist.row(
                scope=scope, pool=str(key[0]), kind=str(key[1]),
                cls=str(key[2]) if len(key) > 2 else "",
                metric=metric))
        self._c = {
            name: om.counter(
                f"pint_tpu_serve_{name}_total",
                f"serve engine {name.replace('_', ' ')}"
            ).child(scope=scope)
            for name in self._COUNTERS}
        self._g_queue = om.gauge("pint_tpu_serve_queue_depth",
                                 "admitted-and-undispatched "
                                 "requests").child(scope=scope)
        self._g_queue_max = om.gauge(
            "pint_tpu_serve_max_queue_depth",
            "peak queue depth").child(scope=scope)
        self.max_queue_depth = 0
        self._queue_depth = 0
        self.buckets: Dict[tuple, BucketStats] = {}

    # "attempts" counts every submit() entry BEFORE any shed
    # decision (ISSUE 11 review): quota and overload sheds never
    # reach the `submitted` counter, so a shed-rate SLO with
    # `submitted` as denominator would be blind to a pure-shed
    # storm — attempts is the honest denominator
    _COUNTERS = ("attempts", "submitted", "completed", "rejected",
                 "deadline_missed", "fallback_single", "failed")

    def __getattr__(self, name):
        c = self.__dict__.get("_c")
        if c is not None and name in type(self)._COUNTERS:
            return int(c[name].value())
        raise AttributeError(name)

    def bump(self, name: str, n: int = 1):
        """The ONE mutation surface for the engine counters
        (graftlint G13 flags ad-hoc attr increments in the serve
        layer)."""
        self._c[name].inc(n)

    # -- gauges --------------------------------------------------------

    def queue_depth(self, depth: Optional[int] = None) -> int:
        if depth is not None:
            self._queue_depth = depth
            self._g_queue.set(depth)
            if depth > self.max_queue_depth:
                self.max_queue_depth = depth
                self._g_queue_max.set(depth)
        return self._queue_depth

    def bucket(self, key) -> BucketStats:
        if key not in self.buckets:
            self.buckets[key] = BucketStats(
                scope=self.scope, cls=self._fmt_key(key))
        return self.buckets[key]

    @property
    def compile_count(self) -> int:
        return self.cache.compile_count if self.cache else 0

    @property
    def bucket_count(self) -> int:
        """Distinct shape classes admitted — the bound the executable
        count must respect."""
        return len(self.buckets)

    # -- reporting -----------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able state of the engine (the daemon prints this on
        shutdown; bench_serve embeds it in its artifact)."""
        all_lats = sorted(
            x for b in self.buckets.values() for x in b.latencies_s)
        slots = sum(b.slots for b in self.buckets.values())
        reqs = sum(b.requests for b in self.buckets.values())
        rows_r = sum(b.rows_real for b in self.buckets.values())
        rows_p = sum(b.rows_padded for b in self.buckets.values())
        out = {
            "attempts": self.attempts,
            "submitted": self.submitted, "completed": self.completed,
            "rejected": self.rejected,
            "deadline_missed": self.deadline_missed,
            "fallback_single": self.fallback_single,
            "failed": self.failed,
            "queue_depth": self._queue_depth,
            "max_queue_depth": self.max_queue_depth,
            "compile_count": self.compile_count,
            "bucket_count": self.bucket_count,
            "batch_occupancy": round(reqs / slots, 4) if slots else 0.0,
            "padded_waste": round(1.0 - rows_r / rows_p, 4)
            if rows_p else 0.0,
            "p50_ms": _pct_ms(all_lats, 50),
            "p99_ms": _pct_ms(all_lats, 99),
            "per_bucket": {self._fmt_key(k): b.snapshot()
                           for k, b in sorted(self.buckets.items(),
                                              key=lambda kv: str(kv[0]))},
        }
        # the pipeline/donation configuration rides the snapshot so
        # an artifact can say how a number was produced (the
        # dispatch_overhead observability contract, ISSUE 7)
        out["pipeline_depth"] = self.pipeline_depth
        out["donation"] = bool(self.donation)
        # ISSUE 10: latency histograms + tracer/flight state — the
        # `latency` and `obs` blocks every serve artifact carries
        out["latency"] = self.latency.snapshot()
        from pint_tpu import obs

        out["obs"] = obs.status()
        # ISSUE 15: the annotate()/phase scoreboard is registry-
        # backed now — its rows (serve.assemble, serve.dispatch)
        # ride the snapshot instead of living in a report-only dict
        try:
            from pint_tpu.profiling import scoreboard

            sb = scoreboard.snapshot()
            if sb:
                out["scoreboard"] = sb
        except Exception:
            pass
        # ISSUE 11: the SLO watchdog's burn state rides the snapshot
        # when armed ($PINT_TPU_SLO) — absent otherwise, keeping the
        # pre-metrics-plane snapshot shape bit-compatible
        from pint_tpu.obs import slo as _slo

        slo_state = _slo.status()
        if slo_state is not None:
            out["slo"] = slo_state
        # ISSUE 14: the numerical-health verdict block when the
        # monitor is armed ($PINT_TPU_HEALTH / $PINT_TPU_SHADOW_RATE)
        # — absent otherwise, keeping pre-health snapshots
        # bit-compatible (the slo-block convention)
        from pint_tpu.obs import health as _hmon

        health_state = _hmon.status()
        if health_state is not None:
            out["health"] = health_state
        if self.admission is not None:
            out["admission"] = self.admission.snapshot()
        if self.append_store is not None:
            # ISSUE 12: per-pulsar append-state accounting (cold
            # builds vs rank updates — the warm/cold serving mix)
            out["append"] = self.append_store.snapshot()
        if self.router is not None:
            out["router"] = self.router.snapshot()
        if self.restart_info:
            rs = dict(self.restart_info)
            aot = getattr(self.cache, "aot", None)
            if aot is not None:
                rs["aot"] = aot.snapshot()  # live, not ctor-time
            out["restart"] = rs
        if self.supervisor is not None:
            # the dispatch-supervisor counters (timeouts, retries,
            # breaker state, failovers; max_inflight = the pipelining
            # actually achieved): a degraded run must be LABELED in
            # the artifact, never silently slow
            out["dispatch"] = self.supervisor.snapshot()
        return out

    @staticmethod
    def _fmt_key(key) -> str:
        return "/".join(str(x) for x in key)

    def report(self) -> str:
        """Human-readable table (mirrors Scoreboard.report's shape)."""
        s = self.snapshot()
        lines = [
            f"serve: {s['completed']}/{s['submitted']} completed, "
            f"{s['rejected']} rejected, {s['deadline_missed']} missed "
            f"deadline, {s['fallback_single']} single-fallback, "
            f"{s['failed']} failed",
            f"executables: {s['compile_count']} "
            f"(shape classes: {s['bucket_count']}), occupancy "
            f"{s['batch_occupancy']:.2f}, padded waste "
            f"{s['padded_waste']:.2f}, p50 {s['p50_ms']} ms, "
            f"p99 {s['p99_ms']} ms",
            f"{'bucket':<28} {'reqs':>6} {'batch':>6} {'occ':>6} "
            f"{'waste':>6} {'p50ms':>8} {'p99ms':>8}",
        ]
        adm = s.get("admission")
        if adm and (adm.get("shed_expired") or adm.get("shed_quota")
                    or adm.get("shed_deadline")
                    or adm.get("shed_shutdown")):
            lines.insert(1, (
                f"SHED: {adm['shed_expired']} expired in queue, "
                f"{adm['shed_deadline']} deadline-doomed, "
                f"{adm['shed_quota']} over tenant quota, "
                f"{adm['shed_shutdown']} at shutdown "
                f"(policy {adm['policy']})"))
        rt = s.get("router")
        if rt and rt.get("host", {}).get("dispatches"):
            lines.insert(1, (
                f"pools: device {rt['device']['dispatches']} "
                f"dispatches ({rt['device']['share']:.0%}), host "
                f"{rt['host']['dispatches']} "
                f"({rt['host']['share']:.0%}, "
                f"{rt['host']['demotions']} breaker demotions)"))
        rs = s.get("restart")
        if rs and (rs.get("warm") or rs.get("replayed")):
            lines.insert(1, (
                f"restart: warm={rs.get('warm')} "
                f"aot_restored={rs.get('aot', {}).get('restored', 0)} "
                f"replayed={rs.get('replayed', 0)}"))
        disp = s.get("dispatch")
        if disp and (disp.get("timeouts") or disp.get("failovers")
                     or disp.get("retries")
                     or disp.get("breaker_rejections")):
            states = ", ".join(
                f"{b}:{v['state']}"
                for b, v in sorted(disp.get("breakers", {}).items()))
            lines.insert(2, (
                f"DEGRADED dispatch: {disp.get('failovers', 0)} "
                f"failovers, {disp.get('timeouts', 0)} timeouts, "
                f"{disp.get('retries', 0)} retries, "
                f"{disp.get('breaker_rejections', 0)} breaker "
                f"rejections ({states})"))
        for k, b in s["per_bucket"].items():
            lines.append(
                f"{k:<28} {b['requests']:>6} {b['batches']:>6} "
                f"{b['occupancy']:>6.2f} {b['padded_waste']:>6.2f} "
                f"{b['p50_ms']:>8} {b['p99_ms']:>8}")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps(self.snapshot())
