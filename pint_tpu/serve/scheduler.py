"""Continuous-batching request scheduler: admission pipeline ->
open shape-class buckets -> routed, padded, vmapped dispatches.

The serving loop of an inference stack, applied to timing — rebuilt
(ISSUE 8) from drain-the-queue rounds into CONTINUOUS BATCHING:
requests are admitted in-flight into *open* shape-class buckets
between drain windows. A bucket seals (becomes a dispatch unit) when
it fills to ``max_batch`` or its coalescing window expires; sealed
units dispatch while new arrivals keep landing in freshly opened
buckets — admission never stops for a drain. A burst of K compatible
requests still pays one dispatch RTT instead of K (0.1-0.25 s each
over the axon tunnel); compiles stay bounded by the shape-class
count, never the request count.

Admission pipeline (``serve.admission``), in order:

1. **tenant quota**: per-tenant token buckets
   (``$PINT_TPU_TENANT_QPS`` / ``_BURST``) shed a bursting tenant
   with ``TenantOverQuota`` before any assembly work is spent;
2. **classification**: the request is assembled and assigned its
   shape class (unchanged from the coalescing engine);
3. **in-queue expiry**: requests whose deadline passed while queued
   are failed with ``DeadlineExceeded`` NOW (the ``shed_expired``
   counter), not discovered at dispatch;
4. **capacity + shed policy** (``$PINT_TPU_SHED_POLICY``): at
   capacity, the deadline-aware policy sheds the request that will
   miss its deadline anyway — a doomed queued victim, or the doomed
   newcomer itself — and never one that can still make it; with no
   provably-doomed request the submit is backpressure-rejected
   (``ServeOverload``), exactly the pre-ISSUE-8 behavior.

Dispatch routing (``serve.router``): every sealed unit is placed by
the breaker-aware capacity router — host CPU and the accelerator are
CONCURRENT pools with learned per-pool service rates; an OPEN device
breaker demotes the device pool (units route straight to the host
mirrors as planned capacity, pinned and hang-free) instead of every
dispatch paying the watchdog-timeout-then-failover dance.

Crash-safe restart (``serve.journal``): with a journal, every
payload-carrying admission is recorded before dispatch and
acknowledged on completion; with an AOT dir, each shape class is
exported after its first compile and restored+primed at engine
construction, so a restarted engine serves its first request with
zero new serve-kernel compiles and ``replay()`` re-submits exactly
the unacknowledged journal entries. ``stop(timeout=...)`` drains
gracefully: queued work keeps dispatching until the bound, the
remainder is shed with an explicit ``ShutdownShed`` per request, and
the serve-state snapshot is written.

Every device dispatch still routes through the engine's
``runtime.DispatchSupervisor`` (watchdog deadline, circuit breaker,
host failover), and every shed/quota/reroute/replay decision is
LABELED in the metrics snapshot (``admission``/``router``/``restart``
blocks) — degraded serving is visible, never silent.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid
from typing import List, Optional, Tuple

import numpy as np

from pint_tpu import obs
from pint_tpu.fitter import Fitter
from pint_tpu.profiling import annotate
from pint_tpu.runtime import faults, locks
from pint_tpu.serve.admission import AdmissionController
from pint_tpu.serve.bucket import (
    ExecutableCache,
    append_shape_class,
    gls_shape_class,
    gwb_shape_class,
    pad_dim,
    phase_shape_class,
    posterior_shape_class,
    pow2_ceil,
)
from pint_tpu.serve.metrics import ServeMetrics
from pint_tpu.serve.request import (
    AppendResult,
    AppendTOAsRequest,
    DeadlineExceeded,
    EngineKilled,
    FitStepRequest,
    FitStepResult,
    GWBRequest,
    GWBResult,
    PhasePredictRequest,
    PhasePredictResult,
    PosteriorRequest,
    PosteriorResult,
    ResidualsRequest,
    ResidualsResult,
    ServeOverload,
    ShutdownShed,
    TenantOverQuota,
)
from pint_tpu.serve.router import CapacityRouter

__all__ = ["ServeEngine", "ServeGLSFitter"]


class _OpenBucket:
    """One open shape-class bucket: requests accumulate here between
    seal events (full batch / window expiry / explicit flush)."""

    __slots__ = ("key", "reqs", "opened_at", "fallback")

    def __init__(self, key, opened_at: float, fallback: bool):
        self.key = key
        self.reqs: List = []
        self.opened_at = opened_at
        self.fallback = fallback


class ServeEngine:
    """The serving engine: admission pipeline, open buckets,
    capacity router, executable cache, journal, metrics. One engine
    per served deployment; its compile accounting
    (``metrics.compile_count``) is self-contained.

    ``mesh`` optionally shards every dispatch's batch axis over the
    named mesh ``axis`` (the ``parallel.pta`` pulsar axis): batch
    slots then pad to a mesh multiple so XLA GSPMD never sees a
    ragged shard. ``aot_dir``/``journal`` arm the crash-safe restart
    path (defaults from ``$PINT_TPU_AOT_DIR`` / ``$PINT_TPU_JOURNAL``).
    """

    def __init__(self, window_s: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 bucket_edges: Optional[Tuple[int, ...]] = None,
                 mesh=None, axis: str = "pulsar",
                 pipeline_depth: Optional[int] = None,
                 tenant_qps: Optional[float] = None,
                 tenant_burst: Optional[float] = None,
                 shed_policy: Optional[str] = None,
                 aot_dir: Optional[str] = None,
                 journal=None,
                 worker_id: Optional[str] = None,
                 pools: Optional[Tuple[str, ...]] = None):
        from pint_tpu import config
        from pint_tpu.runtime import DispatchSupervisor

        self.window_s = config.serve_window_s() \
            if window_s is None else float(window_s)
        self.max_batch = config.serve_max_batch() \
            if max_batch is None else int(max_batch)
        self.queue_cap = config.serve_queue_cap() \
            if queue_cap is None else int(queue_cap)
        self.bucket_edges = tuple(sorted(
            config.serve_bucket_edges() if bucket_edges is None
            else bucket_edges))
        self.mesh = mesh
        self.axis = axis
        # pipelined drain (ISSUE 7): keep up to this many sealed
        # units IN FLIGHT while draining — unit k+1 is issued on the
        # supervisor's async pipeline while unit k executes. 1 = the
        # classic synchronous drain.
        self.pipeline_depth = max(1, config.serve_pipeline_depth()
                                  if pipeline_depth is None
                                  else int(pipeline_depth))
        # engine-owned dispatch supervisor: its counters (timeouts,
        # failovers, retries) are this deployment's — self-contained
        # like the compile accounting — while breaker state stays
        # process-global (backend health is a process fact)
        self.supervisor = DispatchSupervisor()
        self.admission = AdmissionController(
            tenant_qps=tenant_qps, tenant_burst=tenant_burst,
            policy=shed_policy)
        # fleet identity (ISSUE 19): stamped onto every journaled
        # admit so the fleet front can re-home exactly this worker's
        # unacked set when its lease expires; None = classic
        # single-worker engine, admits carry no owner.
        self.worker_id = worker_id
        self.router = CapacityRouter(supervisor=self.supervisor,
                                     pools=pools)
        if aot_dir is None:
            aot_dir = config.aot_dir()
        self.cache = ExecutableCache(mesh=mesh, axis=axis,
                                     supervisor=self.supervisor,
                                     aot_dir=aot_dir)
        # journal: a path (str), a prebuilt RequestJournal, or None
        # (default $PINT_TPU_JOURNAL). A prebuilt journal is NOT
        # owned: a fleet shares one journal across workers, and one
        # worker's stop() must not close it under the others.
        if journal is None:
            journal = config.journal_path()
        self._journal_owned = journal is None or isinstance(journal,
                                                            str)
        if isinstance(journal, str):
            from pint_tpu.serve.journal import RequestJournal

            journal = RequestJournal(journal)
        self.journal = journal
        self.metrics = ServeMetrics(self.cache,
                                    supervisor=self.supervisor,
                                    pipeline_depth=self.pipeline_depth,
                                    donation=self.cache.donation,
                                    admission=self.admission,
                                    router=self.router)
        self.metrics.restart_info = self._restart_info(aot_dir)
        # per-pulsar cached accumulated normal equations (ISSUE 12):
        # the AppendTOAsRequest state registry — in-memory, delta
        # commits under its own lock at collect time
        from pint_tpu.serve.append import AppendStore

        self.append_store = AppendStore()
        self.metrics.append_store = self.append_store
        self._open: dict = {}                  # key -> _OpenBucket
        self._ready: collections.deque = collections.deque()
        self._pool_last_collect: dict = {}     # pool -> last collect t
        self._nqueued = 0
        self._earliest_expiry: Optional[float] = None
        self._dead = False
        self._drain_stop_at: Optional[float] = None  # shutdown bound
        # the ENGINE lock (admission-critical): every submitter
        # serializes on it, so a supervised dispatch / journal fsync
        # / host solve under it stalls admission — engine=True arms
        # the runtime.locks dispatch-clear check, and G16 part 3 bans
        # it statically (analysis/lock_registry.py ENGINE_LOCKS)
        self._lock = locks.make_rlock("serve.engine", engine=True)
        self._cv = locks.make_condition(self._lock)
        # the dispatch SERIALIZER: sealed units issue/collect while
        # holding it BY DESIGN (one drain at a time; _cv is released
        # per iteration so admission keeps flowing) — deliberately
        # NOT engine-marked and exempt from G16 part 3
        self._dispatch_lock = locks.make_lock("serve.dispatch")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ISSUE 11: arm the SLO burn-rate watchdog when $PINT_TPU_SLO
        # is set (a no-op otherwise — no thread, no ring); it samples
        # the process metric registry this engine now writes through
        from pint_tpu.obs import slo as _slo

        _slo.maybe_start()

    def _restart_info(self, aot_dir) -> dict:
        info = {"warm": False, "replayed": 0}
        if self.cache.aot is not None:
            info["aot"] = self.cache.aot.snapshot()
            info["warm"] = self.cache.aot.restored > 0
            from pint_tpu.serve.journal import load_state

            prior = load_state(aot_dir)
            if prior is not None:
                info["prior_shutdown"] = prior.get("reason")
        if self.journal is not None:
            info["journal"] = self.journal.counts()
        return info

    # -- admission -----------------------------------------------------

    def submit(self, req):
        """Run one request through the admission pipeline; returns
        its ServeFuture. Raises ``TenantOverQuota`` when the tenant's
        token bucket is drained and ``ServeOverload`` when capacity
        is exhausted and the shed policy found nobody provably doomed
        (backpressure — nothing is partially accepted). A
        deadline-doomed newcomer is NOT raised: its future is failed
        with ``DeadlineExceeded`` and returned (a labeled shed
        response, not a transport error).

        Tracing (ISSUE 10): every submit opens the request's ROOT
        span ("serve.request", a fresh trace id) before any
        admission decision, and the request resolves to exactly one
        terminal event (served / shed:* / failed) — either here on a
        raise-path shed, or from the future's done callback. Queue
        wait, dispatch and ack spans attach under this root as the
        request moves through the engine."""
        if self._dead:
            raise EngineKilled(
                "engine was killed (kill_restart); restart and "
                "replay the journal")
        # every live submit is an ATTEMPT, counted before any shed
        # decision — the shed-rate SLO's denominator (quota/overload
        # sheds never reach `submitted`)
        self.metrics.bump("attempts")
        osp = obs.open_root("serve.request", label="req",
                            kind=req.kind,
                            tenant=req.tenant or "default",
                            rid=req.rid)
        req._osp = osp
        if osp.ctx is not None:
            self._wire_terminal_span(req, osp)
        now = time.monotonic()
        # 1. tenant quota — before classification, so a shed tenant
        # never costs GLS assembly work
        if not self.admission.check_quota(req.tenant, now=now):
            osp.event("serve.terminal", status="shed:quota")
            osp.end(status="shed:quota")
            raise TenantOverQuota(
                f"tenant {req.tenant or 'default'!r} is over its "
                f"{self.admission.tenant_qps}/s quota; shed")
        # 2. classification (assembles GLS problems — outside any
        # lock; the request object is single-submitter by contract)
        try:
            key, fb = self._class_of(req)
        except Exception as e:
            self.metrics.bump("submitted")
            self.metrics.bump("failed")
            req.future.set_exception(e)
            return req.future
        with self._cv:
            now = time.monotonic()
            # 3. in-queue expiry sweep (amortized: no-op until the
            # earliest queued deadline has actually passed)
            self._expire_locked(now)
            # 4. capacity + shed policy
            if self.admission.capacity_exhausted(self._nqueued,
                                                 self.queue_cap):
                verdict, victim = self.admission.shed_decision(
                    req, self._queued_waits_locked(),
                    self._predicted_wait_locked(req), now)
                if verdict == "victim":
                    self._remove_queued_locked(victim)
                    self.admission.bump("shed_deadline")
                    self.admission.note_shed("deadline")
                    victim.future.set_exception(DeadlineExceeded(
                        f"{victim.kind} request shed at admission: "
                        f"predicted wait exceeds its remaining "
                        f"{victim.deadline_s}s deadline (doomed "
                        f"anyway; capacity given to a request that "
                        f"can still make it)"))
                elif verdict == "newcomer":
                    self.admission.bump("shed_deadline")
                    self.admission.note_shed("deadline")
                    self.metrics.bump("submitted")
                    req.future.set_exception(DeadlineExceeded(
                        f"{req.kind} request shed at admission: "
                        f"predicted wait exceeds its {req.deadline_s}"
                        f"s deadline (would miss anyway)"))
                    return req.future
                else:
                    self.metrics.bump("rejected")
                    self.admission.bump("shed_overload")
                    self.admission.note_shed("overload")
                    osp.event("serve.terminal",
                              status="shed:overload")
                    osp.end(status="shed:overload")
                    raise ServeOverload(
                        f"admission queue full ({self.queue_cap}); "
                        f"shed load or raise "
                        f"PINT_TPU_SERVE_QUEUE_CAP")
            # admitted: stamp, journal, place into its open bucket
            req.admitted_at = now
            osp.event("serve.admit", queued=self._nqueued + 1)
            if req.deadline_s is not None:
                req.expires_at = now + float(req.deadline_s)
                if self._earliest_expiry is None or \
                        req.expires_at < self._earliest_expiry:
                    self._earliest_expiry = req.expires_at
            if self._thread is None:
                # synchronous mode: result() pumps the queue itself
                req.future._sync_engine = self
            b = self._open.get(key)
            if b is None:
                b = self._open[key] = _OpenBucket(key, now, fb)
            b.reqs.append(req)
            self._nqueued += 1
            if len(b.reqs) >= self.max_batch:
                self._seal_locked(key)
            self.metrics.bump("submitted")
            self.metrics.queue_depth(self._nqueued)
            self._cv.notify()
        # journal OUTSIDE the engine lock: the per-admit fsync must
        # not serialize other submitters or the drain loop's seal/
        # expire work behind disk latency. The request may even
        # complete before the admit line lands (threaded drain) —
        # the ack callback fires immediately on a done future and
        # the journal scan matches admit/ack lines in any order.
        self._journal_admit(req)
        return req.future

    @staticmethod
    def _terminal_status(fut) -> str:
        """Classify a RESOLVED future into its terminal trace label —
        the same taxonomy the journal ack uses."""
        try:
            fut.result(timeout=0)
            return "served"
        except DeadlineExceeded:
            return "shed:deadline"
        except ShutdownShed:
            return "shed:shutdown"
        except TenantOverQuota:
            return "shed:quota"
        except ServeOverload:
            return "shed:overload"
        except EngineKilled:
            return "killed"
        except Exception:
            return "failed"

    def _wire_terminal_span(self, req, osp):
        """Close the request's root span with its terminal status
        when the future resolves — every admitted request ends in
        exactly one of served / shed:* / failed / killed (the
        zero-orphan contract the chaos oracle asserts)."""

        def _terminal(fut, osp=osp):
            status = self._terminal_status(fut)
            osp.event("serve.terminal", status=status)
            osp.end(status=status)

        req.future.add_done_callback(_terminal)

    def _journal_admit(self, req):
        if self.journal is None or req.payload is None:
            return
        if req.rid is None:
            req.rid = uuid.uuid4().hex
        # a replayed entry already HAS its admit line (plus the
        # "replayed" progress mark) — writing another would grow the
        # journal by the full payload and double-count `admitted`
        # on every restart; only its terminal ack below is owed
        if not getattr(req, "_journal_replayed", False):
            self.journal.admit(req.rid, req.payload,
                               tenant=req.tenant,
                               deadline_s=req.deadline_s,
                               worker=self.worker_id)
        journal = self.journal

        osp = getattr(req, "_osp", None)

        def _ack(fut, rid=req.rid):
            # the ONE exception->status classifier (shared with the
            # trace terminal event, so journal and trace vocabularies
            # can never drift). "killed" is deliberately NOT acked:
            # the kill_restart contract is that journal entries stay
            # unacknowledged — a killed engine's work must replay
            st = self._terminal_status(fut)
            if st == "killed":
                return
            journal.ack(rid, st)
            if osp is not None:
                osp.event("serve.journal_ack", status=st)

        req.future.add_done_callback(_ack)

    def replay(self, factory, owner: Optional[str] = None,
               records: Optional[List[dict]] = None) -> List:
        """Re-submit every unacknowledged journal entry (crash
        recovery): ``factory(payload)`` rebuilds the request from
        the journaled payload. Returns the new futures, in journal
        order. Each entry gets a non-terminal "replayed" progress
        mark; its terminal ack lands when the replayed future
        resolves — a crash DURING replay leaves it replayable.

        ``owner`` scopes the replay set to one worker's admits (the
        fleet re-home path — a survivor must NOT replay its own
        in-flight entries); ``records`` replays an explicit
        already-scanned set instead (the fleet front scans once,
        writes the ``rehome`` marks, then hands the records here)."""
        if self.journal is None:
            return []
        if records is None:
            records = self.journal.unacknowledged(owner=owner)
        futs = []
        for rec in records:
            req = factory(rec["payload"])
            req.rid = rec["rid"]
            if req.payload is None:
                req.payload = rec["payload"]
            req._journal_replayed = True
            self.journal.ack(rec["rid"], "replayed")
            futs.append(self.submit(req))
        self.metrics.restart_info["replayed"] = self.metrics.restart_info.get("replayed", 0) + len(futs)  # graftlint: allow G13 -- restart_info is the labeled restart-summary dict on the snapshot surface, not registry counter state; it accumulates because a fleet re-home may call replay() several times on one survivor
        return futs

    # -- queue bookkeeping (all under self._lock) ----------------------

    def _queued_requests_locked(self):
        for b in self._open.values():
            yield from b.reqs
        for _, grp in self._ready:
            yield from grp

    def _remove_queued_locked(self, req):
        for key, b in list(self._open.items()):
            if req in b.reqs:
                b.reqs.remove(req)
                self._nqueued -= 1
                if not b.reqs:
                    del self._open[key]
                return
        for unit in self._ready:
            if req in unit[1]:
                unit[1].remove(req)
                self._nqueued -= 1
                return

    @staticmethod
    def _kind_of(req) -> str:
        if isinstance(req, PhasePredictRequest):
            return "phase"
        if isinstance(req, PosteriorRequest):
            return "posterior"
        if isinstance(req, AppendTOAsRequest):
            return "append"
        if isinstance(req, GWBRequest):
            return "gwb"
        return "gls"

    def _predicted_wait_locked(self, req) -> float:
        """Admission-policy wait estimate for a NEWCOMER: every
        already-sealed unit dispatches before it, plus the router's
        in-flight backlog, each KIND costed at its own learned
        (pool, kind) rate (0.0 — never doomed — until the newcomer's
        own kind has an observed rate; ISSUE 9 satellite: a queued
        posterior chain is priced at the posterior rate, so a heavy
        chain ahead dooms a tight-deadline newcomer honestly, and a
        GLS-speed estimate never admits a long chain against a
        deadline it cannot make). Open-bucket rows are excluded:
        their seal order vs the newcomer's own bucket is not
        knowable, and overestimating the wait would shed a request
        that could still make its deadline."""
        ahead: dict = {}
        for _, grp in self._ready:
            for r in grp:
                k = self._kind_of(r)
                ahead[k] = ahead.get(k, 0) + self._rows_of(r)
        return self.router.predicted_wait_s(
            self._rows_of(req), kind=self._kind_of(req),
            ahead_by_kind=ahead)

    def _queued_waits_locked(self):
        """``[(req, predicted_wait_s)]`` for every queued request,
        ONE O(n) prefix-sum pass in dispatch order. A queued
        candidate's wait counts only rows AHEAD of it — sealed units
        dispatch in deque order, batch-mates ride the same vmapped
        dispatch, and rows queued BEHIND a candidate must not count
        (the inflated wait would shed a head-of-queue request that
        was about to be served on time). The prefix sum is PER KIND
        (rows are kind-local units — walker-steps for posterior —
        and the router costs each kind at its own rate). Open-bucket
        requests dispatch after every sealed unit; other open
        buckets are excluded, same never-overestimate rule as
        above."""
        out = []
        ahead: dict = {}
        for _, grp in self._ready:
            for r in grp:
                out.append((r, self.router.predicted_wait_s(
                    self._rows_of(r), kind=self._kind_of(r),
                    ahead_by_kind=dict(ahead))))
            for r in grp:
                k = self._kind_of(r)
                ahead[k] = ahead.get(k, 0) + self._rows_of(r)
        for b in self._open.values():
            for r in b.reqs:
                out.append((r, self.router.predicted_wait_s(
                    self._rows_of(r), kind=self._kind_of(r),
                    ahead_by_kind=dict(ahead))))
        return out

    def _expire_locked(self, now: float):
        """Fail every queued request whose deadline has passed
        (satellite: deadlines used to be checked only at
        drain/dispatch time — a doomed request could sit in the queue
        consuming capacity long after its caller gave up). Amortized:
        skips entirely until the earliest queued expiry is due."""
        if self._earliest_expiry is None or now < self._earliest_expiry:
            return
        earliest = None

        def sweep(reqs: List) -> List:
            nonlocal earliest
            live = []
            for r in reqs:
                if r.expired(now):
                    self._nqueued -= 1
                    self.metrics.bump("deadline_missed")
                    self.admission.bump("shed_expired")
                    self.admission.note_shed("expired")
                    r.future.set_exception(DeadlineExceeded(
                        f"{r.kind} request missed its "
                        f"{r.deadline_s}s deadline in queue"))
                else:
                    if r.expires_at is not None and \
                            (earliest is None
                             or r.expires_at < earliest):
                        earliest = r.expires_at
                    live.append(r)
            return live

        for key, b in list(self._open.items()):
            b.reqs[:] = sweep(b.reqs)
            if not b.reqs:
                del self._open[key]
        for unit in list(self._ready):
            unit[1][:] = sweep(unit[1])
            if not unit[1]:
                self._ready.remove(unit)
        self._earliest_expiry = earliest
        self.metrics.queue_depth(self._nqueued)

    def _seal_locked(self, key):
        """Seal one open bucket into a ready dispatch unit."""
        b = self._open.pop(key)
        if not b.reqs:
            return
        if b.fallback:
            self.metrics.bump("fallback_single", len(b.reqs))
        obs.event("serve.seal",
                  cls=ServeMetrics._fmt_key(key), n=len(b.reqs))
        self._ready.append((key, b.reqs))
        self._cv.notify_all()

    # -- draining ------------------------------------------------------

    def flush(self):
        """Seal every open bucket and drain every sealed unit (new
        requests admitted DURING the drain are drained too). Safe
        from any thread; dispatches are serialized."""
        while True:
            with self._cv:
                if self._dead:
                    raise EngineKilled(
                        "engine was killed (kill_restart); restart "
                        "and replay the journal")
                self._expire_locked(time.monotonic())
                for key in list(self._open):
                    self._seal_locked(key)
                if not self._ready:
                    return
            self._drain_ready()

    def _drain_ready(self, stop_at: Optional[float] = None):
        """Dispatch sealed units with a sliding window of
        ``pipeline_depth`` in flight; collection stays in issue order
        so result scattering (and the per-bucket metrics) are
        deterministic. A mid-pipeline backend death drains cleanly:
        every issued dispatch carries its own depth-scaled watchdog
        deadline and host fallback, so collecting the window always
        terminates — zero hung futures (tests/test_runtime_faults).
        ``stop_at`` bounds a shutdown drain (units are not popped
        past it). An injected ``kill_restart`` fault aborts the drain
        like a SIGKILL: already-issued work is abandoned, futures die
        unresolved, journal entries stay unacknowledged."""
        sync = self.pipeline_depth <= 1
        pending: collections.deque = collections.deque()
        with self._dispatch_lock:
            # a fleet worker_kill (ServeEngine.kill) latches _dead
            # under this lock between drains — a dead engine must
            # never dispatch again (its queued work re-homes)
            if self._dead:
                raise EngineKilled(
                    "engine was killed; queued work stays "
                    "unacknowledged in the journal")
            while True:
                with self._cv:
                    if not self._ready:
                        break
                    # re-read the shutdown bound every iteration: a
                    # stop(timeout=...) that lands while this drain
                    # is already running must still bound it — the
                    # call-time stop_at alone would let a large
                    # backlog drain unboundedly past the contract
                    bound = stop_at
                    live = self._drain_stop_at
                    if live is not None and \
                            (bound is None or live < bound):
                        bound = live
                    if bound is not None and \
                            time.monotonic() > bound:
                        break
                    key, grp = self._ready.popleft()
                    self._nqueued -= len(grp)
                    self.metrics.queue_depth(self._nqueued)
                plan = faults.active_plan()
                if plan is not None and plan.faults_for(
                        "serve.drain", kinds=("kill_restart",)):
                    self._dead = True
                    raise EngineKilled(
                        "injected kill_restart: engine died "
                        "mid-drain (simulated SIGKILL — journal "
                        "entries stay unacknowledged)")
                # dispatch-time expiry: a unit may have aged between
                # seal and pop (the legacy drain-time deadline check)
                now = time.monotonic()
                live = []
                for r in grp:
                    if r.expired(now):
                        self.metrics.bump("deadline_missed")
                        self.admission.bump("shed_expired")
                        self.admission.note_shed("expired")
                        r.future.set_exception(DeadlineExceeded(
                            f"{r.kind} request missed its "
                            f"{r.deadline_s}s deadline in queue"))
                    else:
                        live.append(r)
                if not live:
                    continue
                state = self._dispatch_begin(key, live, sync=sync)
                if sync:
                    self._dispatch_finish(*state)
                    continue
                pending.append(state)
                if len(pending) >= self.pipeline_depth:
                    self._dispatch_finish(*pending.popleft())
            while pending:
                self._dispatch_finish(*pending.popleft())

    def _class_of(self, r):
        """(shape-class key, is_fallback). GLS requests are assembled
        here (the class must reflect the REAL problem shapes, and
        assembly has to happen before dispatch anyway); the assembled
        problem is cached on the request."""
        if isinstance(r, PhasePredictRequest):
            n, k = r.sizes
            key = phase_shape_class(n, k, self.bucket_edges)
            if key is None:
                return ("phase", pow2_ceil(n), pad_dim(k, 4)), True
            return key, False
        if isinstance(r, AppendTOAsRequest):
            # bind the engine's state store BEFORE assembly: a warm
            # append's rows must be built on the cold span's Fourier
            # frequencies (the tspan override), which only the store
            # knows
            r.bind_store(self.append_store)
            with annotate("serve.assemble"):
                pr = r.ensure_problem()
            n, p = pr.M.shape
            q = pr.F.shape[1]
            key = append_shape_class(n, p, q, self.bucket_edges)
            if key is None:
                return ("append", pow2_ceil(n), pad_dim(p),
                        pad_dim(q)), True
            return key, False
        if isinstance(r, GWBRequest):
            from pint_tpu import config

            # assembly here builds the whole array likelihood (the
            # per-pulsar blocks stay lazy — they assemble as ONE
            # supervised dispatch at issue time); the engine's mesh
            # and supervisor thread through so block assembly shards
            # over the pulsar axis and counts against this
            # deployment's dispatch counters
            with annotate("serve.assemble"):
                lk = r.ensure_likelihood(mesh=self.mesh,
                                         axis=self.axis,
                                         supervisor=self.supervisor)
            return gwb_shape_class(lk.npulsars, lk.m,
                                   config.gwb_chunk()), False
        with annotate("serve.assemble"):
            pr = r.ensure_problem()
        n, p = pr.M.shape
        q = pr.F.shape[1]
        if isinstance(r, PosteriorRequest):
            from pint_tpu import config

            K = config.chain_chunk_steps(r.nsteps, thin=r.thin)
            key = posterior_shape_class(n, p, q, r.nwalkers, K,
                                        r.thin, self.bucket_edges)
            if key is None:
                return ("posterior", pow2_ceil(n), pad_dim(p),
                        pad_dim(q), r.nwalkers, K, r.thin), True
            return key, False
        key = gls_shape_class(n, p, q, self.bucket_edges)
        if key is None:
            return ("gls", pow2_ceil(n), pad_dim(p), pad_dim(q)), True
        return key, False

    def _batch_pad(self, P: int) -> int:
        """Pad the batch axis to a power of two (a mesh multiple of
        one when sharding) so batch sizes, like TOA counts, land on a
        bounded set of compiled shapes."""
        Pb = pow2_ceil(P)
        if self.mesh is not None:
            m = self.mesh.shape[self.axis]
            Pb = m * pow2_ceil(-(-P // m))
        return Pb

    def _dispatch_begin(self, key, grp: List, sync: bool = False):
        """Route one sealed unit to a capacity pool and issue its
        call (async on the supervisor's pipeline mode unless
        ``sync``). Returns the state tuple ``_dispatch_finish``
        consumes; an assembly/issue failure rides along as the
        collect slot and fails the group at finish time, so begin
        never throws into the drain loop.

        Tracing: the unit gets its own trace ("serve.unit" root
        carrying the member rids), the router verdict is a
        "serve.route" child event, and the issue half runs inside a
        "serve.issue" child span — so the supervised dispatch
        (issued here under pipelining) parents under it. Each member
        request additionally gets a retroactive "serve.queue" span
        (admission -> issue) under its OWN root, tagged with the
        unit's trace id, linking the two stories."""
        Pb = self._batch_pad(len(grp))
        full_key = key + (Pb,)
        t0 = time.monotonic()
        kind = key[0] if key[0] in ("phase", "posterior",
                                    "append", "gwb") else "gls"
        rows = self._unit_rows(key, grp, Pb)
        pool = self.router.pick(kind, rows)
        self.router.issued(pool, len(grp), rows, kind=kind)
        cls = ServeMetrics._fmt_key(key)
        usp = obs.open_root(
            "serve.unit", label="unit", kind=kind, cls=cls,
            pool=pool, n=len(grp),
            rids=[r.rid for r in grp if r.rid is not None])
        usp.event("serve.route", pool=pool, rows=rows)
        if usp.ctx is not None:
            tracer = obs.get_tracer()
            t0_trace = tracer.monotonic_us(t0)
            for r in grp:
                rosp = getattr(r, "_osp", None)
                if rosp is not None and rosp.ctx is not None and \
                        r.admitted_at is not None:
                    tracer.record_span(
                        "serve.queue",
                        tracer.monotonic_us(r.admitted_at),
                        t0_trace, parent=rosp.ctx,
                        unit=usp.trace_id)
        info: dict = {}
        try:
            with obs.span("serve.issue", parent=usp.ctx, pool=pool):
                if key[0] == "phase":
                    _, nb, kb = key
                    collect = self.cache.phase_begin(
                        full_key, grp, nb, kb, Pb, sync=sync,
                        pool=pool, info=info)
                elif key[0] == "append":
                    _, nb, pb, qb = key
                    entries = self._append_entries(grp)
                    info["append_entries"] = entries
                    collect = self.cache.append_begin(
                        full_key, grp, shape=(Pb, nb, pb, qb),
                        entries=entries, sync=sync, pool=pool,
                        info=info)
                elif key[0] == "posterior":
                    _, nb, pb, qb = key[:4]
                    collect = self.cache.posterior_begin(
                        full_key, grp, shape=(Pb, nb, pb, qb),
                        sync=sync, pool=pool, info=info,
                        progress=self._posterior_progress(grp))
                elif key[0] == "gwb":
                    collect = self.cache.gwb_begin(
                        full_key, grp, sync=sync, pool=pool,
                        info=info,
                        progress=self._gwb_progress(grp))
                else:
                    _, nb, pb, qb = key
                    collect = self.cache.gls_begin(
                        full_key, [r.problem for r in grp],
                        shape=(Pb, nb, pb, qb), sync=sync, pool=pool,
                        info=info)
        except Exception as e:
            collect = e
        return key, full_key, grp, Pb, t0, collect, pool, info, usp

    def _append_entries(self, grp: List):
        """Per-request cached state entries at ISSUE time (None =
        cold slot, starts from the zero state). Two same-key
        requests in one unit both read the pre-batch state — the
        kernel returns additive DELTAS, so both land at commit and
        each response reflects the data up to its own rows."""
        entries = []
        for r in grp:
            e = None
            if not r.cold:
                e = self.append_store.get(r.state_key)
            entries.append(e)
        return entries

    def _append_finish(self, key, grp: List, out, info: dict):
        """Commit the append deltas to the state store and scatter
        results. A slot whose CG/basis solve failed (ok False) fails
        its future WITHOUT committing — the state stays exactly as
        before, so the caller can retry or cold-rebuild."""
        (cm_used, dSig, db, du, dscal, dparams, cov, chi2, chi2r,
         ok, iters) = out
        entries = info.get("append_entries") or [None] * len(grp)
        for k, r in enumerate(grp):
            pr = r.problem
            p = pr.M.shape[1]
            if not bool(ok[k]):
                r.future.set_exception(ValueError(
                    f"append solve for state {r.state_key!r} failed "
                    f"(singular/degenerate combined system); state "
                    f"NOT updated"))
                continue
            try:
                entry = self.append_store.commit(
                    r.state_key, pr, key[2], key[3],
                    cold=entries[k] is None, cm_used=cm_used[k],
                    dSig=dSig[k], db=db[k], du=du[k],
                    dscal=dscal[k], nrows=pr.M.shape[0])
            except Exception as e:
                r.future.set_exception(e)
                continue
            r.future.set_result(AppendResult(
                names=pr.names, dparams=dparams[k][:p],
                cov=cov[k][:p, :p], chi2=float(chi2[k]),
                chi2r=float(chi2r[k]), ntoa_total=entry.ntoa,
                cold=entries[k] is None, cg_iters=int(iters[k])))

    def _unit_rows(self, key, grp: List, Pb: int) -> int:
        """Kind-local work units one sealed unit dispatches (feeds
        the router's per-kind rate learning, so it must count the
        PADDED work the device really executes — under the batch
        vmap the budget mask lowers to a select, so every slot runs
        every chunk's K steps)."""
        if key[0] == "posterior":
            W, K = key[4], key[5]
            kmax = max((r.nsteps for r in grp), default=0)
            return Pb * W * max(1, -(-kmax // K)) * K
        if key[0] == "gwb":
            # each request sweeps its OWN chunked grid (batch slots
            # never pad: coalescing is admission-only), so the
            # executed work is the sum of per-request padded points
            K = key[3]
            return sum(max(1, -(-r.npoints // K)) * K for r in grp)
        return Pb * key[1]

    def _posterior_progress(self, grp: List):
        """Per-chunk progress hook for a posterior unit: journals a
        non-terminal progress ack per journalable request after
        every chunk dispatch, so a crash mid-chain is visible in the
        journal (the replay restarts the chain; the marks label how
        far the dead run got)."""
        if self.journal is None:
            return None
        journal = self.journal

        def progress(done_steps):
            for k, r in enumerate(grp):
                if r.rid is not None and r.payload is not None:
                    journal.progress(r.rid, int(done_steps[k]))

        return progress

    def _gwb_progress(self, grp: List):
        """Per-chunk journal progress for a GWB unit (the posterior
        convention): one non-terminal ack per journalable request
        after each of ITS sweep chunks, so a crash mid-sweep is
        visible in the journal (the replay restarts the sweep; the
        marks label how far the dead run got)."""
        if self.journal is None:
            return None
        journal = self.journal

        def progress(k, done_points):
            r = grp[k]
            if r.rid is not None and r.payload is not None:
                journal.progress(r.rid, int(done_points))

        return progress

    def _dispatch_finish(self, key, full_key, grp, Pb, t0, collect,
                         pool, info, usp):
        """Collect one issued dispatch and scatter results to the
        group's futures (the wait rides the supervisor's depth-scaled
        watchdog, so this always terminates). Feeds the router's
        rate learning with the pool that ACTUALLY served — and the
        latency histograms (queue wait / dispatch wall / e2e per
        (pool, kind, class), ISSUE 10) with every member request."""
        kind = key[0] if key[0] in ("phase", "posterior",
                                    "append", "gwb") else "gls"
        rows = self._unit_rows(key, grp, Pb)
        try:
            if isinstance(collect, Exception):
                raise collect
            with annotate("serve.dispatch"), \
                    obs.span("serve.collect", parent=usp.ctx,
                             pool=pool):
                out = collect()
                self._observe_unit_health(kind, key, out, pool,
                                          info)
            if key[0] == "phase":
                pi, pf = out
                for k, r in enumerate(grp):
                    n = len(r.mjds)
                    r.future.set_result(PhasePredictResult(
                        phase_int=pi[k][:n], phase_frac=pf[k][:n]))
            elif key[0] == "posterior":
                chain, lnp, acc, rows_done = out
                for k, r in enumerate(grp):
                    pr = r.problem
                    p = pr.M.shape[1]
                    nrows = int(rows_done[k])
                    # OWNED copies: a view slice would pin the whole
                    # padded (Pb, S, W, pb) batch buffer for as long
                    # as any client holds its result
                    r.future.set_result(PosteriorResult(
                        names=pr.names,
                        chain=np.ascontiguousarray(
                            chain[k, :nrows, :, :p]),
                        lnprob=lnp[k, :nrows].copy(),
                        acceptance_fraction=float(acc[k])
                        / max(1, r.walker_steps),
                        nsteps=r.nsteps))
            elif key[0] == "append":
                self._append_finish(key, grp, out, info)
            elif key[0] == "gwb":
                for k, r in enumerate(grp):
                    # the driver's concatenate already owns its
                    # buffer; ascontiguousarray keeps the no-view
                    # promise if that ever changes
                    r.future.set_result(GWBResult(
                        logL=np.ascontiguousarray(out[k]),
                        log10A=r.log10A.copy(),
                        gamma=r.gamma.copy(),
                        npulsars=r.likelihood.npulsars,
                        nfreq=r.likelihood.nfreq))
            else:
                dparams, cov, chi2, chi2r = out
                for k, r in enumerate(grp):
                    pr = r.problem
                    p = pr.M.shape[1]
                    if isinstance(r, ResidualsRequest):
                        res = ResidualsResult(time_resids=pr.r,
                                              chi2=float(chi2r[k]))
                    else:
                        res = FitStepResult(
                            names=pr.names, dparams=dparams[k][:p],
                            cov=cov[k][:p, :p], chi2=float(chi2[k]),
                            chi2r=float(chi2r[k]))
                    r.future.set_result(res)
        except Exception as e:
            self.router.finished(pool, kind, rows, 0.0,
                                 used_pool="error")
            usp.end(status="failed",
                    error=f"{type(e).__name__}: {e}")
            for r in grp:
                if not r.future.done():
                    self.metrics.bump("failed")
                    r.future.set_exception(e)
            return
        done = time.monotonic()
        usp.end(status="ok",
                used_pool=info.get("used_pool", pool))
        # rate-learning wall: a pipelined collect's issue-to-collect
        # span includes time spent queued behind other in-flight
        # dispatches (up to pipeline_depth x the true service time —
        # the same corruption the supervisor excludes from RTT
        # drift). The inter-completion gap since the pool's previous
        # collect is the honest throughput sample under pipelining;
        # a collect after idle (gap would span the idle period)
        # falls back to its own issue-to-collect wall.
        last = self._pool_last_collect.get(pool)
        wall = done - t0 if last is None or last <= t0 \
            else done - last
        self._pool_last_collect[pool] = done
        self.router.finished(pool, kind, rows, wall,
                             used_pool=info.get("used_pool", pool))
        lats = [done - (r.admitted_at or t0) for r in grp]
        nb = key[1]
        rows_real = sum(self._rows_of(r) for r in grp)
        self.metrics.bucket(full_key).record(
            len(grp), Pb, rows_real, Pb * nb, lats)
        # log-bucketed latency histograms, keyed (pool, kind, class):
        # one dispatch-wall sample per unit, one queue-wait + e2e
        # sample per member request (ISSUE 10 — the `latency` block
        # of every serve snapshot/artifact)
        hkey = (info.get("used_pool", pool), kind,
                ServeMetrics._fmt_key(key))
        self.metrics.latency.record(hkey, "dispatch_wall", done - t0)
        for r in grp:
            adm = r.admitted_at or t0
            self.metrics.latency.record(hkey, "queue_wait",
                                        max(0.0, t0 - adm))
            self.metrics.latency.record(hkey, "e2e", done - adm)
        self.metrics.bump("completed", len(grp))

    @staticmethod
    def _observe_unit_health(kind, key, out, pool, info):
        """Numerical-health tap for one collected serve unit (ISSUE
        14): every signal here is ALREADY in the collected outputs —
        zero extra dispatches — and the math lives in
        ``HealthMonitor.observe`` (graftlint G14), not here. A no-op
        branch when $PINT_TPU_HEALTH is unset. GUARDED: collect()
        already produced valid results when this runs, so an
        instrumentation bug must degrade to a missed observation,
        never fail the unit's futures (the supervisor's shadow hook
        makes the same promise)."""
        try:
            ServeEngine._observe_unit_health_inner(
                kind, key, out, pool, info)
        except Exception:
            pass

    @staticmethod
    def _observe_unit_health_inner(kind, key, out, pool, info):
        from pint_tpu.obs import health as _health

        mon = _health.get_monitor()
        if not mon.enabled:
            return
        used = info.get("used_pool", pool)
        if kind == "posterior":
            # lnpost, not values: -inf walkers are legal (zero-
            # probability start positions), only NaN/+inf is garbage
            mon.observe("serve.posterior", {"lnpost": out[1]},
                        pool=used, key=str(key))
        elif kind == "append":
            # the append CG's effort vs the runtime budget the
            # bucket kernel ACTUALLY ran (threaded through info by
            # append_begin — never recomputed here); the worst slot
            # of the batch is the one a budget-exhaustion incident
            # cares about
            mon.observe("serve.append",
                        {"values": [out[5], out[7]],
                         "cg_iters": int(np.max(out[10])),
                         "cg_budget": info.get("append_cg_budget"),
                         "ok": bool(np.all(out[9]))},
                        pool=used, key=str(key))
        elif kind == "phase":
            mon.observe("serve.phase", {"values": list(out)},
                        pool=used, key=str(key))
        elif kind == "gwb":
            # every swept logL value: nonfinite anywhere in the grid
            # is the garbage signal (a -inf grid point would mean a
            # non-PD outer Schur system, not a low-probability one)
            mon.observe("serve.gwb",
                        {"values": [np.concatenate(
                            [np.ravel(o) for o in out])]},
                        pool=used, key=str(key))
        else:
            dparams, cov, chi2, chi2r = out
            mon.observe("serve.gls", {"values": [dparams, chi2]},
                        pool=used, key=str(key))

    @staticmethod
    def _rows_of(r) -> int:
        """KIND-LOCAL work units (must match what the router's rate
        for that kind was learned in): TOA/MJD rows for gls/phase,
        total walker-steps for a posterior chain."""
        if isinstance(r, PhasePredictRequest):
            return len(r.mjds)
        if isinstance(r, PosteriorRequest):
            return r.walker_steps
        if isinstance(r, GWBRequest):
            return r.npoints
        return r.problem.M.shape[0]

    # -- threaded serving loop ----------------------------------------

    def start(self):
        """Run the continuous-batching loop in a daemon thread.
        Futures then resolve asynchronously;
        ``ServeFuture.result(timeout)`` is the blocking wait."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="pint-serve", daemon=True)
        self._thread.start()
        return self

    def kill(self):
        """Simulated SIGKILL for the fleet chaos path (worker_kill):
        latch the engine dead WITHOUT draining. Queued work is NOT
        failed — futures stay unresolved exactly as a real process
        death leaves them, journal entries stay unacknowledged, and
        the fleet front re-homes them onto a survivor (the original
        caller's future is then resolved with the survivor's
        bit-identical result). The shared journal is deliberately
        NOT closed and no state snapshot is written: both belong to
        the fleet, not the corpse. Blocks at most one in-flight
        drain unit (the kill lands at the next drain boundary, like
        the injected kill_restart fault)."""
        self._stop.set()
        with self._dispatch_lock:
            self._dead = True
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=60.0)
            self._thread = None

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None):
        """Stop the loop. ``drain=True`` (default) keeps dispatching
        what is queued so no accepted request is silently dropped;
        ``timeout`` bounds that drain — work still queued at the
        deadline is shed with an explicit ``ShutdownShed`` per
        request (the graceful-shutdown contract: labeled, never
        silent, never unbounded). Writes the serve-state snapshot
        and closes the journal."""
        stop_at = None if timeout is None \
            else time.monotonic() + max(0.0, timeout)
        # the loop's own final drain (it seals + drains on stop)
        # must honor the same bound, or it drains unboundedly before
        # this thread ever reaches the shed step
        self._drain_stop_at = stop_at
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=60.0)
            self._thread = None
        try:
            if drain and not self._dead:
                if stop_at is None:
                    self.flush()
                else:
                    while time.monotonic() <= stop_at:
                        with self._cv:
                            for key in list(self._open):
                                self._seal_locked(key)
                            if not self._ready:
                                break
                        self._drain_ready(stop_at=stop_at)
                    self._shed_remaining()
        finally:
            self._persist_state("shutdown")

    def _shed_remaining(self):
        """Fail everything still queued after a bounded shutdown
        drain — each future gets a labeled ShutdownShed (the daemon
        turns these into explicit shed response lines)."""
        with self._cv:
            reqs = list(self._queued_requests_locked())
            self._open.clear()
            self._ready.clear()
            self._nqueued = 0
            self.metrics.queue_depth(0)
        if reqs:
            # shutdown-drain flight dump (ISSUE 10): the bounded
            # drain expired with work still queued — the post-mortem
            # pairing of the journal's unserved set with what the
            # engine was doing when the clock ran out
            obs.flight_dump("shutdown_shed", shed=len(reqs),
                            admission=self.admission.snapshot())
        for r in reqs:
            self.admission.bump("shed_shutdown")
            if not r.future.done():
                r.future.set_exception(ShutdownShed(
                    f"{r.kind} request shed: engine shut down "
                    f"before it dispatched (bounded drain timeout)"))

    def _persist_state(self, reason: str):
        if self.cache.aot is not None:
            from pint_tpu.serve.journal import save_state

            try:
                save_state(self.cache.aot.dir,
                           self.metrics.snapshot(), reason=reason)
            except Exception:
                pass
        if self.journal is not None and self._journal_owned:
            self.journal.close()

    def _loop(self):
        while True:
            with self._cv:
                while not self._open and not self._ready and \
                        not self._stop.is_set():
                    self._cv.wait(timeout=0.25)
                if self._stop.is_set():
                    stop_at = self._drain_stop_at
                    if (not self._open and not self._ready) or \
                            (stop_at is not None
                             and time.monotonic() > stop_at):
                        # drained clean, or the bounded shutdown
                        # window is spent — stop() owns the labeled
                        # shed of whatever remains; spinning here
                        # would just burn the join timeout
                        return
            # continuous batching: hold open buckets for their
            # coalescing window (a full bucket seals itself at
            # admission), then seal and dispatch — new requests keep
            # being admitted into fresh open buckets while sealed
            # units are in flight
            while not self._stop.is_set():
                with self._cv:
                    self._expire_locked(time.monotonic())
                    if self._ready:
                        break
                    if not self._open:
                        break
                    now = time.monotonic()
                    due = [key for key, b in self._open.items()
                           if now >= b.opened_at + self.window_s]
                    if due:
                        for key in due:
                            self._seal_locked(key)
                        break
                time.sleep(min(1e-3, max(self.window_s, 1e-4)))
            if self._stop.is_set():
                with self._cv:
                    for key in list(self._open):
                        self._seal_locked(key)
            try:
                self._drain_ready(stop_at=self._drain_stop_at)
            except EngineKilled:
                return
            except BaseException as e:
                # unhandled engine exception: dump the black box
                # before the drain thread dies — the one trigger
                # where the trace is ALL the evidence there will be
                obs.flight_dump("engine_exception",
                                error=f"{type(e).__name__}: {e}")
                raise


class ServeGLSFitter(Fitter):
    """Iterated-GLS fitter routed through a ServeEngine — the
    ``Fitter.auto(serve=engine)`` path. Each iteration submits one
    FitStepRequest and applies the returned correction, exactly the
    ``fit_pta`` update loop but with the solve coalesced against
    whatever else the engine is serving. The final chi2 is the
    bases-marginalized chi2 at the fitted point (``Residuals.chi2``
    semantics)."""

    def __init__(self, toas, model, engine: ServeEngine,
                 residuals=None, track_mode=None):
        super().__init__(toas, model, residuals=residuals,
                         track_mode=track_mode)
        self.engine = engine

    def fit_toas(self, maxiter: int = 4,
                 timeout: Optional[float] = None):
        from pint_tpu.residuals import Residuals

        t0 = time.perf_counter()
        res = None
        for _ in range(max(1, maxiter)):
            fut = self.engine.submit(FitStepRequest(
                self.toas, self.model, track_mode=self.track_mode))
            res = fut.result(timeout=timeout)
            self.update_model(np.asarray(res.dparams), res.names)
        # one more pass at the fitted point: uncertainties + chi2
        fut = self.engine.submit(FitStepRequest(
            self.toas, self.model, track_mode=self.track_mode))
        res = fut.result(timeout=timeout)
        self.set_uncertainties(np.asarray(res.cov), res.names)
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        self.converged = True
        chi2 = res.chi2r
        self._record_stats(chi2, max(1, maxiter) + 1, t0)
        return chi2
