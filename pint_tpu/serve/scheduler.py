"""Coalescing request scheduler: admission queue -> shape-class
groups -> one padded vmapped dispatch per group.

The serving loop of an inference stack, applied to timing: requests
admitted within a coalescing window (``config.serve_window_s``) are
grouped by compatible shape class (``serve.bucket``) and solved in
ONE device call per group via the ``parallel.pta`` batch kernel, so a
burst of K compatible requests pays one dispatch RTT instead of K
(over the axon tunnel that is 0.1-0.25 s EACH — see
``config.dispatch_rtt_ms``). Compiles are bounded by the shape-class
count, never the request count.

Operation modes:

- synchronous (default): ``submit()`` queues; ``flush()`` — called
  explicitly, or implicitly by ``ServeFuture.result()`` — drains
  everything pending. Deterministic; what the tests and bench drive.
- threaded: ``start()`` runs a daemon loop that waits for the first
  request, sleeps out the coalescing window to let a batch
  accumulate, then drains. The stdin daemon
  (``scripts/pint_serve.py``) uses this.

Backpressure: the admission queue is capped
(``config.serve_queue_cap``); a full queue rejects the submit with
``ServeOverload`` — shedding at admission is the only honest
overload response when every accepted request carries a deadline.
Expired requests are failed with ``DeadlineExceeded`` at drain time,
before any device work is spent on them. A request whose shape fits
no configured bucket is NOT rejected: it falls back to the next
power-of-two shape class (counted in ``metrics.fallback_single`` —
graceful, still shape-quantized), and fallback requests landing on
the SAME class coalesce into one shared padded dispatch.

Every device dispatch routes through the engine's
``runtime.DispatchSupervisor`` (watchdog deadline, circuit breaker,
host numpy/polyco failover): a wedged backend degrades a batch to
the host path — counted, never hung — so every admitted future
always completes.

Pipelined drain (ISSUE 7): with ``pipeline_depth`` > 1 (default 2,
``$PINT_TPU_SERVE_PIPELINE``) a drain pass keeps that many
shape-class dispatches in flight at once — batch k+1 is issued on
the supervisor's async pipeline (``dispatch_async``) while batch k
executes, with explicit result collection only at scatter time
(double-buffering on jax's async dispatch). Each in-flight dispatch
carries its own depth-scaled watchdog deadline and host fallback, so
a mid-pipeline backend death still drains every admitted future to
labeled host failover — zero hung futures.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from pint_tpu.fitter import Fitter
from pint_tpu.profiling import annotate
from pint_tpu.serve.bucket import (
    ExecutableCache,
    gls_shape_class,
    pad_dim,
    phase_shape_class,
    pow2_ceil,
)
from pint_tpu.serve.metrics import ServeMetrics
from pint_tpu.serve.request import (
    DeadlineExceeded,
    FitStepRequest,
    FitStepResult,
    PhasePredictRequest,
    PhasePredictResult,
    ResidualsRequest,
    ResidualsResult,
    ServeOverload,
)

__all__ = ["ServeEngine", "ServeGLSFitter"]


class ServeEngine:
    """The serving engine: queue, coalescer, executable cache,
    metrics. One engine per served deployment; its compile accounting
    (``metrics.compile_count``) is self-contained.

    ``mesh`` optionally shards every dispatch's batch axis over the
    named mesh ``axis`` (the ``parallel.pta`` pulsar axis): batch
    slots then pad to a mesh multiple so XLA GSPMD never sees a
    ragged shard."""

    def __init__(self, window_s: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 bucket_edges: Optional[Tuple[int, ...]] = None,
                 mesh=None, axis: str = "pulsar",
                 pipeline_depth: Optional[int] = None):
        from pint_tpu import config
        from pint_tpu.runtime import DispatchSupervisor

        self.window_s = config.serve_window_s() \
            if window_s is None else float(window_s)
        self.max_batch = config.serve_max_batch() \
            if max_batch is None else int(max_batch)
        self.queue_cap = config.serve_queue_cap() \
            if queue_cap is None else int(queue_cap)
        self.bucket_edges = tuple(sorted(
            config.serve_bucket_edges() if bucket_edges is None
            else bucket_edges))
        self.mesh = mesh
        self.axis = axis
        # pipelined drain (ISSUE 7): keep up to this many shape-class
        # dispatches IN FLIGHT during one drain pass — batch k+1 is
        # issued on the supervisor's async pipeline while batch k
        # executes, and results are collected in issue order. 1 = the
        # classic synchronous drain.
        self.pipeline_depth = max(1, config.serve_pipeline_depth()
                                  if pipeline_depth is None
                                  else int(pipeline_depth))
        # engine-owned dispatch supervisor: its counters (timeouts,
        # failovers, retries) are this deployment's — self-contained
        # like the compile accounting — while breaker state stays
        # process-global (backend health is a process fact)
        self.supervisor = DispatchSupervisor()
        self.cache = ExecutableCache(mesh=mesh, axis=axis,
                                     supervisor=self.supervisor)
        self.metrics = ServeMetrics(self.cache,
                                    supervisor=self.supervisor,
                                    pipeline_depth=self.pipeline_depth,
                                    donation=self.cache.donation)
        self._queue: collections.deque = collections.deque()
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._dispatch_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- admission -----------------------------------------------------

    def submit(self, req):
        """Admit a request; returns its ServeFuture. Raises
        ServeOverload when the queue is at capacity (backpressure —
        nothing is partially accepted)."""
        with self._cv:
            if len(self._queue) >= self.queue_cap:
                self.metrics.rejected += 1
                raise ServeOverload(
                    f"admission queue full ({self.queue_cap}); "
                    f"shed load or raise PINT_TPU_SERVE_QUEUE_CAP")
            now = time.monotonic()
            req.admitted_at = now
            if req.deadline_s is not None:
                req.expires_at = now + float(req.deadline_s)
            if self._thread is None:
                # synchronous mode: result() pumps the queue itself
                req.future._sync_engine = self
            self._queue.append(req)
            self.metrics.submitted += 1
            self.metrics.queue_depth(len(self._queue))
            self._cv.notify()
        return req.future

    # -- draining ------------------------------------------------------

    def flush(self):
        """Drain every currently-queued request (grouping, batching
        and dispatching as one coalesced pass). Safe from any thread;
        dispatches are serialized."""
        while True:
            with self._cv:
                if not self._queue:
                    return
                batch = list(self._queue)
                self._queue.clear()
                self.metrics.queue_depth(0)
            with self._dispatch_lock:
                self._process(batch)

    def _process(self, reqs: List):
        now = time.monotonic()
        live = []
        for r in reqs:
            if r.expired(now):
                self.metrics.deadline_missed += 1
                r.future.set_exception(DeadlineExceeded(
                    f"{r.kind} request missed its "
                    f"{r.deadline_s}s deadline in queue"))
            else:
                live.append(r)
        groups: dict = {}
        fallbacks = []
        for r in live:
            try:
                key, fb = self._class_of(r)
            except Exception as e:
                self.metrics.failed += 1
                r.future.set_exception(e)
                continue
            if fb:
                fallbacks.append((key, r))
            else:
                groups.setdefault(key, []).append(r)
        units: List[Tuple] = []
        for key, grp in groups.items():
            for i in range(0, len(grp), self.max_batch):
                units.append((key, grp[i:i + self.max_batch]))
        # oversize requests (no configured bucket) still coalesce:
        # the fallback shape class IS a shape class, so requests that
        # land on the same power-of-two dims share one padded
        # dispatch instead of going one-at-a-time (compile count
        # stays <= bucket count + oversize classes either way)
        fb_groups: dict = {}
        for key, r in fallbacks:
            fb_groups.setdefault(key, []).append(r)
        for key, grp in fb_groups.items():
            self.metrics.fallback_single += len(grp)
            for i in range(0, len(grp), self.max_batch):
                units.append((key, grp[i:i + self.max_batch]))
        if self.pipeline_depth <= 1 or len(units) <= 1:
            for key, grp in units:
                self._dispatch(key, grp)
            return
        # pipelined drain: a sliding window of pipeline_depth
        # in-flight dispatches; collection stays in issue order so
        # result scattering (and the per-bucket metrics) are
        # deterministic. A mid-pipeline backend death drains cleanly:
        # every issued dispatch carries its own depth-scaled watchdog
        # deadline and host fallback, so collecting the window always
        # terminates — zero hung futures (tests/test_runtime_faults).
        pending: collections.deque = collections.deque()
        for key, grp in units:
            pending.append(self._dispatch_begin(key, grp))
            if len(pending) >= self.pipeline_depth:
                self._dispatch_finish(*pending.popleft())
        while pending:
            self._dispatch_finish(*pending.popleft())

    def _class_of(self, r):
        """(shape-class key, is_fallback). GLS requests are assembled
        here (the class must reflect the REAL problem shapes, and
        assembly has to happen before dispatch anyway); the assembled
        problem is cached on the request."""
        if isinstance(r, PhasePredictRequest):
            n, k = r.sizes
            key = phase_shape_class(n, k, self.bucket_edges)
            if key is None:
                return ("phase", pow2_ceil(n), pad_dim(k, 4)), True
            return key, False
        with annotate("serve.assemble"):
            pr = r.ensure_problem()
        n, p = pr.M.shape
        q = pr.F.shape[1]
        key = gls_shape_class(n, p, q, self.bucket_edges)
        if key is None:
            return ("gls", pow2_ceil(n), pad_dim(p), pad_dim(q)), True
        return key, False

    def _batch_pad(self, P: int) -> int:
        """Pad the batch axis to a power of two (a mesh multiple of
        one when sharding) so batch sizes, like TOA counts, land on a
        bounded set of compiled shapes."""
        Pb = pow2_ceil(P)
        if self.mesh is not None:
            m = self.mesh.shape[self.axis]
            Pb = m * pow2_ceil(-(-P // m))
        return Pb

    def _dispatch(self, key, grp: List):
        """One synchronous device call for one shape-class group;
        scatter results to the group's futures. A dispatch failure
        fails exactly this group's futures — the engine keeps
        serving."""
        self._dispatch_finish(*self._dispatch_begin(key, grp,
                                                    sync=True))

    def _dispatch_begin(self, key, grp: List, sync: bool = False):
        """Issue one shape-class group's device call (async on the
        supervisor's pipeline mode unless ``sync``). Returns the
        state tuple ``_dispatch_finish`` consumes; an assembly/issue
        failure rides along as the collect slot and fails the group
        at finish time, so begin never throws into the drain loop."""
        Pb = self._batch_pad(len(grp))
        full_key = key + (Pb,)
        t0 = time.monotonic()
        try:
            if key[0] == "phase":
                _, nb, kb = key
                collect = self.cache.phase_begin(
                    full_key, grp, nb, kb, Pb, sync=sync)
            else:
                _, nb, pb, qb = key
                collect = self.cache.gls_begin(
                    full_key, [r.problem for r in grp],
                    shape=(Pb, nb, pb, qb), sync=sync)
        except Exception as e:
            collect = e
        return key, full_key, grp, Pb, t0, collect

    def _dispatch_finish(self, key, full_key, grp, Pb, t0, collect):
        """Collect one issued dispatch and scatter results to the
        group's futures (the wait rides the supervisor's depth-scaled
        watchdog, so this always terminates)."""
        try:
            if isinstance(collect, Exception):
                raise collect
            with annotate("serve.dispatch"):
                out = collect()
            if key[0] == "phase":
                pi, pf = out
                for k, r in enumerate(grp):
                    n = len(r.mjds)
                    r.future.set_result(PhasePredictResult(
                        phase_int=pi[k][:n], phase_frac=pf[k][:n]))
            else:
                dparams, cov, chi2, chi2r = out
                for k, r in enumerate(grp):
                    pr = r.problem
                    p = pr.M.shape[1]
                    if isinstance(r, ResidualsRequest):
                        res = ResidualsResult(time_resids=pr.r,
                                              chi2=float(chi2r[k]))
                    else:
                        res = FitStepResult(
                            names=pr.names, dparams=dparams[k][:p],
                            cov=cov[k][:p, :p], chi2=float(chi2[k]),
                            chi2r=float(chi2r[k]))
                    r.future.set_result(res)
        except Exception as e:
            for r in grp:
                if not r.future.done():
                    self.metrics.failed += 1
                    r.future.set_exception(e)
            return
        done = time.monotonic()
        lats = [done - (r.admitted_at or t0) for r in grp]
        nb = key[1]
        rows_real = sum(self._rows_of(r) for r in grp)
        self.metrics.bucket(full_key).record(
            len(grp), Pb, rows_real, Pb * nb, lats)
        self.metrics.completed += len(grp)

    @staticmethod
    def _rows_of(r) -> int:
        if isinstance(r, PhasePredictRequest):
            return len(r.mjds)
        return r.problem.M.shape[0]

    # -- threaded serving loop ----------------------------------------

    def start(self):
        """Run the coalescing loop in a daemon thread. Futures then
        resolve asynchronously; ``ServeFuture.result(timeout)`` is
        the blocking wait."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="pint-serve", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True):
        """Stop the loop; by default drain what is still queued so no
        accepted request is silently dropped."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=60.0)
            self._thread = None
        if drain:
            self.flush()

    def _loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(timeout=0.25)
                if self._stop.is_set() and not self._queue:
                    return
            # first request seen: sleep out the coalescing window so
            # a burst lands in one batch, but dispatch immediately
            # once a full batch is waiting
            deadline = time.monotonic() + self.window_s
            while time.monotonic() < deadline:
                with self._lock:
                    if len(self._queue) >= self.max_batch or \
                            self._stop.is_set():
                        break
                time.sleep(min(1e-3, max(self.window_s, 1e-4)))
            self.flush()


class ServeGLSFitter(Fitter):
    """Iterated-GLS fitter routed through a ServeEngine — the
    ``Fitter.auto(serve=engine)`` path. Each iteration submits one
    FitStepRequest and applies the returned correction, exactly the
    ``fit_pta`` update loop but with the solve coalesced against
    whatever else the engine is serving. The final chi2 is the
    bases-marginalized chi2 at the fitted point (``Residuals.chi2``
    semantics)."""

    def __init__(self, toas, model, engine: ServeEngine,
                 residuals=None, track_mode=None):
        super().__init__(toas, model, residuals=residuals,
                         track_mode=track_mode)
        self.engine = engine

    def fit_toas(self, maxiter: int = 4,
                 timeout: Optional[float] = None):
        from pint_tpu.residuals import Residuals

        t0 = time.perf_counter()
        res = None
        for _ in range(max(1, maxiter)):
            fut = self.engine.submit(FitStepRequest(
                self.toas, self.model, track_mode=self.track_mode))
            res = fut.result(timeout=timeout)
            self.update_model(np.asarray(res.dparams), res.names)
        # one more pass at the fitted point: uncertainties + chi2
        fut = self.engine.submit(FitStepRequest(
            self.toas, self.model, track_mode=self.track_mode))
        res = fut.result(timeout=timeout)
        self.set_uncertainties(np.asarray(res.cov), res.names)
        self.resids = Residuals(self.toas, self.model,
                                track_mode=self.track_mode)
        self.converged = True
        chi2 = res.chi2r
        self._record_stats(chi2, max(1, maxiter) + 1, t0)
        return chi2
