"""Shape-class bucketing + the bounded executable cache.

The continuous-batching insight from inference serving applied to
timing: XLA compiles one executable per input SHAPE, so a naive
serving loop compiles once per distinct request (unbounded, and each
compile is multi-second — multi-minute over the axon tunnel). Here
every request is padded to a shape CLASS:

- the TOA/MJD axis pads to a power-of-two bucket edge
  (``config.serve_bucket_edges``, default 64..16384);
- the parameter and noise-basis axes pad to multiples of 8 (padded
  columns are identity-pinned / unit-prior, exactly the
  ``parallel.pta`` masking contract);
- the batch (request) axis pads to a power of two up to
  ``config.serve_max_batch`` (and to a mesh multiple when the engine
  shards the batch axis over a device mesh).

Total executables are then bounded by the product of the (few) bucket
counts — never by the request count. ``ExecutableCache`` owns fresh
jitted kernels (so its compile accounting is per-engine, not
process-global) and tracks every shape class it has dispatched.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pint_tpu.parallel.pta import _solve_one, pta_solve_np, \
    stack_problems

__all__ = ["bucket_for", "pad_dim", "pow2_ceil", "ExecutableCache",
           "gls_shape_class", "phase_shape_class",
           "posterior_shape_class", "append_shape_class",
           "gwb_shape_class"]


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (max(1, int(n)) - 1).bit_length()


def bucket_for(n: int, edges: Tuple[int, ...]) -> Optional[int]:
    """Smallest bucket edge >= n, or None when n exceeds every edge
    (the scheduler's single-request fallback case)."""
    for e in edges:
        if n <= e:
            return e
    return None


def pad_dim(d: int, multiple: int = 8) -> int:
    """Pad a (parameter / basis) axis to a multiple; 0 stays 0 so
    white-noise models don't drag a dead basis block through the
    solve."""
    if d == 0:
        return 0
    return ((d + multiple - 1) // multiple) * multiple


def gls_shape_class(n: int, p: int, q: int, edges: Tuple[int, ...]):
    """(kind, N_bucket, p_pad, q_pad) for a fit/residuals request —
    or None when the TOA count exceeds every bucket edge. Fit and
    residual requests share classes: the solve kernel is
    structure-agnostic (it consumes padded matrices), so the
    component-structure part of the serve cache key collapses to the
    request kind class; the structure-sensitive compiles (the phase
    chain per model) stay cached in the model layer
    (``TimingModel._get_compiled``)."""
    nb = bucket_for(n, edges)
    if nb is None:
        return None
    return ("gls", nb, pad_dim(p), pad_dim(q))


def phase_shape_class(nmjd: int, ncoeff: int, edges: Tuple[int, ...]):
    """(kind, N_bucket, k_pad) for a phase-prediction request."""
    nb = bucket_for(nmjd, edges)
    if nb is None:
        return None
    return ("phase", nb, pad_dim(ncoeff, 4))


def posterior_shape_class(n: int, p: int, q: int, W: int, K: int,
                          thin: int, edges: Tuple[int, ...]):
    """(kind, N_bucket, p_pad, q_pad, W, K, thin) for a posterior
    request — or None when the TOA count exceeds every bucket edge.
    The problem axes bucket like GLS classes (same padded masking);
    the WALKER count and the chunked-scan length K ride in the key
    EXACTLY (not padded): both are compile-time constants of the
    chain program, W is pinned by the request (padding it would
    change the PRNG stream and break bit-equality with the direct
    ``sample_problems`` path), and K is already quantized by
    ``config.chain_chunk_steps`` — the actual per-request ``nsteps``
    is a runtime budget, so distinct chain lengths share a class."""
    nb = bucket_for(n, edges)
    if nb is None:
        return None
    return ("posterior", nb, pad_dim(p), pad_dim(q), int(W), int(K),
            int(thin))


def append_shape_class(n: int, p: int, q: int,
                       edges: Tuple[int, ...]):
    """(kind, N_bucket, p_pad, q_pad) for an append request — the
    NEW-row count buckets like a GLS TOA axis (the accumulated state
    is already padded to (p_pad, q_pad), so state and rows share one
    class), or None when the batch exceeds every edge (the cold-build
    fallback-single case)."""
    nb = bucket_for(n, edges)
    if nb is None:
        return None
    return ("append", nb, pad_dim(p), pad_dim(q))


def gwb_shape_class(P: int, m: int, K: int):
    """(kind, npulsars, basis columns, chunk) for a GWB sweep
    request. EXACT, never None: the compiled programs are keyed on
    the array size, the common-basis column count and the sweep
    chunk — the hyperparameter GRIDS are runtime args (distinct
    grids share a class), and there is no TOA axis to bucket (the
    per-pulsar blocks are request state, assembled once)."""
    return ("gwb", int(P), int(m), int(K))


def _phase_eval_one(coeffs, tmid, rphase_int, rphase_frac, f0, mjds,
                    valid):
    """One polyco segment's absolute phase at padded MJDs (device
    mirror of ``polycos.PolycoEntry.abs_phase``). Horner from the
    highest coefficient — the same evaluation order as
    np.polynomial.polynomial.polyval, but XLA may fuse the
    multiply-add into an FMA, so the host oracle agrees only to ~1
    ulp of fractional phase (~1e-16 turn, orders below the 10 ps
    oracle budget); zero-padded high coefficients contribute exact
    zeros. Padded MJD slots carry dt=0 and are zeroed by ``valid``
    on the way out."""
    import jax.numpy as jnp

    dt = (mjds - tmid) * 1440.0
    poly = jnp.zeros_like(dt)
    for i in range(coeffs.shape[0] - 1, -1, -1):
        poly = poly * dt + coeffs[i]
    spin = 60.0 * f0 * dt
    spin_i = jnp.floor(spin)
    frac = rphase_frac + (spin - spin_i) + poly
    carry = jnp.floor(frac)
    return (rphase_int + spin_i + carry) * valid, \
        (frac - carry) * valid


class ExecutableCache:
    """Per-engine compiled-executable registry.

    One fresh ``jax.jit`` wrapper per kernel kind (NOT the module
    globals), so jit-cache growth is attributable to THIS engine: a
    compile happens exactly when a shape class first dispatches, and
    ``compile_count`` == the number of distinct classes seen. With a
    ``mesh``, batch-axis inputs are placed block-sharded over
    ``axis`` before the call (input shardings are part of XLA's cache
    key, so a mesh engine and a local engine never share entries —
    which is why each engine owns its wrappers)."""

    def __init__(self, mesh=None, axis: str = "pulsar",
                 supervisor=None, aot_dir=None):
        import jax

        from pint_tpu.config import donation_enabled
        from pint_tpu.runtime import get_supervisor

        self.mesh = mesh
        self.axis = axis
        # serve-kernel donation is scoped to ACCELERATOR backends:
        # the engine's pipelined drain executes these kernels from
        # concurrent worker threads, and XLA:CPU's donation aliasing
        # showed a rare buffer-reuse race under that concurrency
        # (a real batch slot reading back another dispatch's memory
        # — caught by the mid-pipeline fault test under load). On
        # CPU donation buys nothing anyway (host memory, no HBM
        # round-trip); on TPU per-device streams serialize execution
        # and donation is the HBM win ISSUE 7 targets. The device
        # fitter's loop donation is unaffected: its dispatches are
        # strictly sequential, with the CPU equality oracle in
        # tests/test_device_fitter.py.
        self.donation = donation_enabled() and \
            jax.default_backend() != "cpu"
        if self.donation:
            # alias-exact buffer donation (ISSUE 7): the GLS batch's
            # pvalid (P, p) aliases the dparams output, the phase
            # batch's mjds/valid (P, nb) alias the (pi, pf) outputs —
            # XLA writes the results INTO the input buffers instead
            # of allocating + copying fresh HBM each dispatch. Only
            # exactly-aliasable positions are donated (an unusable
            # donation warns per call). Every donated array is
            # rebuilt per dispatch inside the run closure, so no
            # caller ever reads a donated buffer (graftlint G11).
            self._gls = jax.jit(jax.vmap(_solve_one),
                                donate_argnums=(6,))
            self._phase = jax.jit(jax.vmap(_phase_eval_one),
                                  donate_argnums=(5, 6))
        else:
            self._gls = jax.jit(jax.vmap(_solve_one))
            self._phase = jax.jit(jax.vmap(_phase_eval_one))
        # append rank-update kernel (ISSUE 12): one jitted vmapped
        # wrapper, built lazily on the first append class (XLA caches
        # per padded shape). NOT donated: the state arrays are read
        # back as deltas for the host-side store commit.
        self._append = None
        # posterior chain kernels (ISSUE 9): one jitted vmapped slot
        # kernel per (W, K, thin) walker/step class — W and K are
        # compile-time constants of the scan program, so unlike the
        # structure-agnostic GLS kernel the wrapper itself is
        # class-keyed. NOT donated: each chunk re-feeds the carried
        # (pos, lp) pair it just read back for the host-side chunk
        # loop and journaled progress, so no argument position is
        # safely alias-exact across the whole chunked run.
        self._posterior: dict = {}
        # every dispatch routes through the runtime supervisor:
        # watchdog deadline + host failover (numpy mirror for GLS,
        # PolycoEntry.abs_phase for phase) so a wedged backend can
        # never hang a serve batch — only slow it down, labeled
        self.supervisor = supervisor or get_supervisor()
        self.keys: set = set()
        # AOT warm restart (ISSUE 8): with an aot_dir, every shape
        # class exports a jax.export artifact right after its first
        # successful device dispatch, and a fresh engine restores +
        # primes them at construction so its first request compiles
        # nothing. Disabled under a mesh: exported modules carry no
        # sharding annotations, and restoring one against sharded
        # inputs would silently gather.
        self.aot = None
        if aot_dir and mesh is None:
            from pint_tpu.serve.journal import AotStore

            self.aot = AotStore(aot_dir, donation=self.donation)
            self.aot.restore_all(supervisor=self.supervisor)
        # ISSUE 11: pull-gauges into the metric registry — compile
        # count and jit-cache entries per engine cache, evaluated at
        # scrape time through a weakref (a dead engine's gauge just
        # stops producing samples, it can never keep the cache alive)
        import weakref

        from pint_tpu.obs import metrics as om

        ref = weakref.ref(self)
        scope = om.new_scope("cache")
        om.gauge("pint_tpu_jit_cache_size",
                 "live jit-cache entries per engine executable "
                 "cache").set_fn(
            lambda: (lambda c: c.jit_cache_size()
                     if c is not None else None)(ref()),
            scope=scope)
        om.gauge("pint_tpu_serve_compile_count",
                 "distinct shape classes compiled per engine"
                 ).set_fn(
            lambda: (lambda c: c.compile_count
                     if c is not None else None)(ref()),
            scope=scope)

    @property
    def compile_count(self) -> int:
        """Distinct shape classes dispatched == executables built.
        Cross-checkable against the jit wrappers' own cache sizes
        (tests do)."""
        return len(self.keys)

    def jit_cache_size(self) -> Optional[int]:
        """Sum of the underlying jit caches' entry counts, when the
        running jax exposes it (None otherwise)."""
        try:
            return int(self._gls._cache_size()) + \
                int(self._phase._cache_size()) + \
                (int(self._append._cache_size())
                 if self._append is not None else 0) + \
                sum(int(fn._cache_size())
                    for fn in self._posterior.values())
        except AttributeError:
            return None

    def _place(self, arrs: dict) -> dict:
        import jax
        import jax.numpy as jnp

        if self.mesh is None:
            return {k: jnp.asarray(v) for k, v in arrs.items()}
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = {}
        for k, v in arrs.items():
            v = jnp.asarray(v)
            sh = NamedSharding(
                self.mesh, P(self.axis, *([None] * (v.ndim - 1))))
            out[k] = jax.device_put(v, sh)
        return out

    def _issue(self, run, host, dispatch_key, class_key, sync: bool,
               pool: str = "device", info: Optional[dict] = None,
               export_cb=None, restored: bool = False,
               ledger_cb=None):
        """Shared issue/collect plumbing: ``sync`` runs the
        supervised dispatch inline (the classic drain); otherwise the
        dispatch is ISSUED on the supervisor's pipeline mode
        (``dispatch_async``) and the returned zero-arg ``collect``
        blocks on its DispatchFuture — batch k+1's device work then
        overlaps batch k's result read. The class key is recorded at
        collect time, only on a real (non-failed-over) device
        dispatch; ``export_cb`` (the AOT export of a freshly
        compiled class) fires on the same condition.

        ``pool`` is the capacity router's verdict: "host" runs the
        numpy mirror as a PINNED supervised dispatch — hang-free by
        construction, bypassing the device breaker entirely (a
        routed host solve is planned capacity, not a failover).
        ``info`` (when given) is filled with the pool that actually
        produced the result, for the router's rate learning."""
        from pint_tpu import obs

        if info is None:
            info = {}
        info.setdefault("pool", pool)

        if pool == "host":
            if sync:
                def collect():
                    with obs.span("serve.pool.host",
                                  key=dispatch_key):
                        out = self.supervisor.dispatch(
                            host, key=dispatch_key, pinned=True)
                    info["used_pool"] = "host"
                    return out
            else:
                with obs.span("serve.pool.host.issue",
                              key=dispatch_key):
                    fut = self.supervisor.dispatch_async(
                        host, key=dispatch_key, pinned=True)

                def collect():
                    out = fut.result()
                    info["used_pool"] = "host"
                    return out

            return collect

        fell_over = []

        def host_counted():
            fell_over.append(True)
            return host()

        def _record():
            if fell_over:
                info["used_pool"] = "host-failover"
                return
            info["used_pool"] = "device"
            if not restored:
                self.keys.add(class_key)
                if export_cb is not None:
                    export_cb()
                if ledger_cb is not None:
                    # ISSUE 15: enrich this class's compile-ledger
                    # entry (the supervisor's first_call already
                    # recorded the wall) with XLA cost analysis.
                    # The probe itself runs on a BACKGROUND thread
                    # (defer_cost): lower().compile() re-pays the
                    # in-process compile, which must never land on
                    # a serve dispatch path; the ledger dedups per
                    # key either way
                    ledger_cb()

        if sync:
            # LAZY: the dispatch runs inside collect, so the
            # caller's annotate("serve.dispatch") region wraps the
            # real device work in sync mode too (an eager dispatch
            # here would leave the profiler attributing ~0 ms)
            def collect():
                with obs.span("serve.pool.device",
                              key=dispatch_key):
                    out = self.supervisor.dispatch(
                        run, key=dispatch_key,
                        fallback=host_counted)
                _record()
                return out
        else:
            with obs.span("serve.pool.device.issue",
                          key=dispatch_key):
                fut = self.supervisor.dispatch_async(
                    run, key=dispatch_key, fallback=host_counted)

            def collect():
                out = fut.result()
                _record()
                return out

        return collect

    def gls_begin(self, key, problems, shape, sync: bool = False,
                  pool: str = "device", info: Optional[dict] = None):
        """Pad ``problems`` to the class shape (``parallel.pta``
        masking) and issue the batch as one SUPERVISED dispatch
        (runtime watchdog; host ``pta_solve_np`` failover). Returns a
        zero-arg ``collect`` whose call yields host arrays (dparams,
        cov, chi2, chi2r), each (P, ...). The class key is recorded
        only on success, so a failed dispatch cannot inflate
        ``compile_count`` past the classes actually built — and a
        failed-over (host-solved) dispatch does not record one
        either: no executable was built for it. ``pool="host"``
        (the capacity router's demotion/steering verdict) runs the
        numpy mirror as planned capacity instead."""
        stacked = stack_problems(problems, shape=shape)
        restored = None
        if pool == "device" and self.aot is not None:
            restored = self.aot.get("gls", key)

        def run():
            # place + dispatch + host read on the guarded worker so
            # the deadline covers completion, not just enqueue; the
            # placed arrays are fresh per call, so the donated
            # pvalid buffer is never observable afterwards. A
            # restored (AOT) class calls its deserialized executable
            # instead of the jit wrapper — same program, zero
            # in-process trace/compile.
            st = self._place(stacked)
            fn = restored if restored is not None else self._gls
            out = fn(st["M"], st["F"], st["phi"], st["r"], st["nvec"], st["valid"], st["pvalid"])  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            hs = tuple(np.asarray(o) for o in out)
            if self.donation:
                # OWNED arrays: dparams aliases the donated pvalid
                # buffer — a zero-copy view escaping the closure
                # would dangle once XLA reuses the memory (runtime
                # G11). Copy only actual views; an accelerator D2H
                # read is already a fresh owned buffer.
                hs = tuple(h if h.flags.owndata else h.copy()
                           for h in hs)
            return hs

        dispatch_key = f"serve.gls/{'/'.join(str(x) for x in key)}"
        # first-compile-only work stays OFF the per-dispatch path:
        # avals/callbacks are built only while this class still owes
        # its AOT export or its ledger cost entry
        need_export = self.aot is not None and restored is None and \
            pool == "device" and not self.aot.has("gls", key)
        # pool-gated like need_export: ledger_cb can only FIRE on
        # a real device dispatch (_record's device branch), and the
        # deferred probe lowers the DEVICE jit — neither belongs to
        # a host-pool (demoted/steered) dispatch
        need_ledger = restored is None and pool == "device" and \
            key not in self.keys
        export_cb = ledger_cb = None
        if need_export or need_ledger:
            import jax

            avals = tuple(jax.ShapeDtypeStruct(stacked[n].shape,
                                               stacked[n].dtype)
                          for n in ("M", "F", "phi", "r", "nvec",
                                    "valid", "pvalid"))
            if need_export:
                export_cb = lambda: self.aot.save(  # noqa: E731
                    "gls", key, self._gls, avals)
            if need_ledger:
                from pint_tpu.obs import perf as _perf

                # defer_cost: the probe re-pays the in-process
                # compile (lower().compile() is NOT a cache hit of
                # the jit call) — it runs on a background thread,
                # never on the serve dispatch path
                ledger_cb = lambda: _perf.note_compile(  # noqa: E731
                    dispatch_key, kind="serve.gls",
                    jitted=self._gls, args=avals, defer_cost=True)

        return self._issue(
            run, lambda: pta_solve_np(stacked),
            dispatch_key, key, sync,
            pool=pool, info=info, export_cb=export_cb,
            restored=restored is not None, ledger_cb=ledger_cb)

    def gls(self, key, problems, shape):
        """Synchronous ``gls_begin`` + collect (the non-pipelined
        drain and every pre-pipeline caller)."""
        return self.gls_begin(key, problems, shape, sync=True)()

    def phase_begin(self, key, requests, nb: int, kb: int, Pb: int,
                    sync: bool = False, pool: str = "device",
                    info: Optional[dict] = None):
        """Pad phase requests to (Pb, nb) MJDs x kb coefficients and
        issue the batch as one supervised dispatch (host failover:
        per-entry ``PolycoEntry.abs_phase``; key recorded on a real
        device dispatch only, as in ``gls_begin``). Returns the
        zero-arg ``collect``. ``pool``/``info`` as in ``gls_begin``.
        """
        coeffs = np.zeros((Pb, kb))
        tmid = np.zeros(Pb)
        rpi = np.zeros(Pb)
        rpf = np.zeros(Pb)
        f0 = np.zeros(Pb)
        mjds = np.zeros((Pb, nb))
        valid = np.zeros((Pb, nb))
        for k, rq in enumerate(requests):
            e = rq.entry
            c = np.asarray(e.coeffs, np.float64)
            coeffs[k, :len(c)] = c
            tmid[k] = e.tmid
            rpi[k] = e.rphase_int
            rpf[k] = e.rphase_frac
            f0[k] = e.f0
            m = rq.mjds
            mjds[k, :len(m)] = m
            mjds[k, len(m):] = e.tmid  # dt = 0 on padded slots
            valid[k, :len(m)] = 1.0

        restored = None
        if pool == "device" and self.aot is not None:
            restored = self.aot.get("phase", key)

        def run():
            # placed arrays are fresh per call: the donated
            # mjds/valid buffers are never observable afterwards
            arrs = self._place({"coeffs": coeffs, "tmid": tmid,
                                "rpi": rpi, "rpf": rpf, "f0": f0,
                                "mjds": mjds, "valid": valid})
            fn = restored if restored is not None else self._phase
            pi, pf = fn(arrs["coeffs"], arrs["tmid"], arrs["rpi"], arrs["rpf"], arrs["f0"], arrs["mjds"], arrs["valid"])  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            hi, hf = np.asarray(pi), np.asarray(pf)
            if self.donation:
                # owned arrays: (pi, pf) alias the donated
                # mjds/valid buffers (see the gls run above)
                hi = hi if hi.flags.owndata else hi.copy()
                hf = hf if hf.flags.owndata else hf.copy()
            return hi, hf

        def host():
            pi = np.zeros((Pb, nb))
            pf = np.zeros((Pb, nb))
            for k, rq in enumerate(requests):
                n = len(rq.mjds)
                hi, hf = rq.entry.abs_phase(rq.mjds)
                pi[k, :n] = hi
                pf[k, :n] = hf
            return pi, pf

        dispatch_key = f"serve.phase/{'/'.join(str(x) for x in key)}"
        # first-compile-only work off the per-dispatch path + the
        # deferred cost probe — see gls_begin
        need_export = self.aot is not None and restored is None and \
            pool == "device" and not self.aot.has("phase", key)
        need_ledger = restored is None and pool == "device" and \
            key not in self.keys
        export_cb = ledger_cb = None
        if need_export or need_ledger:
            import jax

            avals = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                          for a in (coeffs, tmid, rpi, rpf, f0,
                                    mjds, valid))
            if need_export:
                export_cb = lambda: self.aot.save(  # noqa: E731
                    "phase", key, self._phase, avals)
            if need_ledger:
                from pint_tpu.obs import perf as _perf

                ledger_cb = lambda: _perf.note_compile(  # noqa: E731
                    dispatch_key, kind="serve.phase",
                    jitted=self._phase, args=avals,
                    defer_cost=True)

        return self._issue(
            run, host,
            dispatch_key, key, sync,
            pool=pool, info=info, export_cb=export_cb,
            restored=restored is not None, ledger_cb=ledger_cb)

    def phase(self, key, requests, nb: int, kb: int, Pb: int):
        """Synchronous ``phase_begin`` + collect."""
        return self.phase_begin(key, requests, nb, kb, Pb,
                                sync=True)()

    def append_begin(self, key, requests, shape, entries,
                     sync: bool = False, pool: str = "device",
                     info: Optional[dict] = None):
        """Pad the append batch to its class shape and issue ONE
        supervised dispatch of the vmapped rank-update + CG-resolve
        slot kernel (``serve.append._append_slot``). ``entries`` is
        the per-request list of cached ``AppendStateEntry`` (None
        for cold slots — they start from the zero state). The kernel
        is PURE: it returns per-slot state DELTAS; the scheduler
        commits them to the store at collect time. Not AOT-exported:
        like the posterior kernel there is no LAPACK-heavy retrace
        to amortize, and a restored executable could not resurrect
        the in-memory state store anyway. Host failover: the numpy
        mirror ``append_slot_np`` per slot."""
        import jax

        from pint_tpu.serve.append import append_slot_np

        Pb, nb, pb, qb = shape
        P = pb + qb
        cm = np.ones((Pb, pb))
        Sig = np.zeros((Pb, P, P))
        bb = np.zeros((Pb, P))
        uu = np.zeros((Pb, P))
        scal = np.zeros((Pb, 8))
        M = np.zeros((Pb, nb, pb))
        F = np.zeros((Pb, nb, qb))
        phi = np.ones((Pb, qb))
        r0 = np.zeros((Pb, nb))
        nvec = np.ones((Pb, nb))
        valid = np.zeros((Pb, nb))
        pvalid = np.zeros((Pb, pb))
        submean = np.zeros(Pb)
        coldf = np.zeros(Pb)
        budget = np.int32(8 * (pb + 1))
        if info is not None:
            # the health tap thresholds CG effort against the budget
            # THE KERNEL ACTUALLY RAN — threaded, never recomputed
            # (the StreamingGLS.default_budget single-source rule)
            info["append_cg_budget"] = int(budget)
        for k, r in enumerate(requests):
            pr = r.problem
            n, p = pr.M.shape
            q = pr.F.shape[1]
            M[k, :n, :p] = pr.M
            F[k, :n, :q] = pr.F
            phi[k, :q] = pr.phi
            r0[k, :n] = pr.r
            nvec[k, :n] = pr.nvec
            valid[k, :n] = 1.0
            pvalid[k, :p] = 1.0
            submean[k] = 1.0 if pr.submean else 0.0
            e = entries[k]
            if e is None:
                coldf[k] = 1.0
            else:
                cm[k] = e.cm
                Sig[k] = e.Sig
                bb[k] = e.b
                uu[k] = e.u
                scal[k] = e.scal
                phi[k] = e.stacked_phi()
        arrs = {"cm": cm, "Sig": Sig, "b": bb, "u": uu, "scal": scal,
                "M": M, "F": F, "phi": phi, "r0": r0, "nvec": nvec,
                "valid": valid, "pvalid": pvalid, "submean": submean,
                "cold": coldf}
        if self._append is None:
            from pint_tpu.serve.append import append_kernel

            self._append = append_kernel()
        fn = self._append
        names = ("cm", "Sig", "b", "u", "scal", "M", "F", "phi",
                 "r0", "nvec", "valid", "pvalid", "submean", "cold")

        def run():
            st = self._place(arrs)
            out = fn(*(st[n_] for n_ in names), jax.numpy.asarray(budget), jax.numpy.asarray(1e-13))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            return tuple(np.asarray(o) for o in out)

        def host():
            outs = [append_slot_np(
                cm[k], Sig[k], bb[k], uu[k], scal[k], M[k], F[k],
                phi[k], r0[k], nvec[k], valid[k], pvalid[k],
                submean[k], coldf[k], budget=int(budget))
                for k in range(Pb)]
            return tuple(np.stack([np.asarray(o[j]) for o in outs])
                         for j in range(11))

        return self._issue(
            run, host,
            f"serve.append/{'/'.join(str(x) for x in key)}", key,
            sync, pool=pool, info=info)

    def _posterior_kernel(self, W: int, K: int, thin: int):
        import jax

        from pint_tpu.sampling.serve_kernel import make_posterior_slot

        ck = (W, K, thin)
        if ck not in self._posterior:
            self._posterior[ck] = jax.jit(jax.vmap(
                make_posterior_slot(W, K, thin=thin),
                in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                         None, None)))
        return self._posterior[ck]

    def posterior_begin(self, key, requests, shape,
                        sync: bool = False, pool: str = "device",
                        info: Optional[dict] = None, progress=None):
        """Pad the requests' problems to the class shape and run the
        whole-chain posterior kernel as CHUNKED supervised dispatches
        (``sampling.posterior_chunk_driver``): each chunk of K scan
        steps is its own deadline-bounded dispatch with a pinned-host
        failover, so long chains never turn one watchdog window into
        an unbounded hang and shutdown drains stay bounded by a
        chunk. ``progress`` (per-slot steps completed) fires after
        every chunk — the scheduler journals it as non-terminal
        progress acks. Returns the zero-arg ``collect`` yielding
        (chain, lnprob, naccept, rows_done) host arrays.

        Not AOT-exported: the chain program embeds the request
        class's (W, K, thin) and recompiles in seconds from the
        feature-keyed persistent jit cache — unlike the GLS/phase
        kernels there is no LAPACK-heavy multi-second retrace to
        amortize at restart, and a restored chain could not resume
        mid-run anyway (chunk state is not persisted; replay restarts
        the chain, which the journal's progress marks label
        honestly). The batch axis is likewise not mesh-sharded:
        posterior batches are small (few pulsars) while the per-slot
        scan is deep — the parallelism is inside the slot, not across
        it."""
        _, nb, pb, qb, W, K, thin = key[:7]
        stacked = stack_problems([r.problem for r in requests],
                                 shape=shape)
        # padded batch slots run a zero-step budget (their chunk
        # work is masked off in-kernel, same convention as the
        # all-padded GLS slot solving the identity system)
        npad = shape[0] - len(requests)
        seeds = [r.seed for r in requests] + [0] * npad
        nsteps = [r.nsteps for r in requests] + [0] * npad
        fnv = self._posterior_kernel(W, K, thin)
        if info is None:
            info = {}

        from pint_tpu.sampling.serve_kernel import (
            posterior_chunk_driver,
        )

        inner = posterior_chunk_driver(
            fnv, stacked, seeds, nsteps, W, K, thin,
            self.supervisor,
            "serve.posterior/" + "/".join(str(x) for x in key),
            pool=pool, sync=sync, info=info, progress=progress)

        def collect():
            out = inner()
            if info.get("used_pool") == "device":
                # compile accounting parity with gls/phase: the class
                # is recorded only when a real device dispatch built
                # (or reused) its executable
                self.keys.add(key)
            return out

        return collect

    def gwb_begin(self, key, requests, sync: bool = False,
                  pool: str = "device",
                  info: Optional[dict] = None, progress=None):
        """Sweep each request's (log10A, gamma) grid through the
        array-likelihood chunk driver (``pta.gwb.gwb_sweep_driver``):
        every chunk of K grid points is its own supervised,
        deadline-bounded dispatch with the numpy outer mirror as host
        failover, so the chunk boundary is the failover/drain
        boundary. ``progress(k, points_done)`` fires after each of
        request k's chunks — the scheduler journals it as
        non-terminal progress acks (the posterior convention).
        Returns the zero-arg ``collect`` yielding one logL host
        array per request.

        Batch coalescing here is ADMISSION coalescing only: each
        request owns its array (its own blocks, Gamma and basis), so
        same-class requests ride one sealed unit but sweep as
        separate chunked dispatches — under ``sync=False`` every
        request's chunk 0 is issued on the supervisor's pipeline, so
        the unit still overlaps device work. Not AOT-exported and
        not donated: the assembled blocks are long-lived request
        state read back by every chunk (the posterior kernel's
        rationale, verbatim)."""
        from pint_tpu.pta.gwb import gwb_sweep_driver

        K = key[3]
        if info is None:
            info = {}
        infos = [dict() for _ in requests]
        tag = "serve.gwb/" + "/".join(str(x) for x in key)
        collects = []
        for k, r in enumerate(requests):
            prog = None if progress is None else \
                (lambda done, k=k: progress(k, done))
            collects.append(gwb_sweep_driver(
                r.likelihood, r.log10A, r.gamma, K,
                supervisor=self.supervisor, key_tag=tag,
                pool=pool, sync=sync, info=infos[k],
                progress=prog))

        def collect():
            outs = [np.asarray(c()) for c in collects]
            pools = [i.get("used_pool") for i in infos]
            if "host-failover" in pools:
                info["used_pool"] = "host-failover"
            elif pools and all(p == "host" for p in pools):
                info["used_pool"] = "host"
            else:
                info["used_pool"] = "device"
                self.keys.add(key)
            return outs

        return collect
