"""Shape-class bucketing + the bounded executable cache.

The continuous-batching insight from inference serving applied to
timing: XLA compiles one executable per input SHAPE, so a naive
serving loop compiles once per distinct request (unbounded, and each
compile is multi-second — multi-minute over the axon tunnel). Here
every request is padded to a shape CLASS:

- the TOA/MJD axis pads to a power-of-two bucket edge
  (``config.serve_bucket_edges``, default 64..16384);
- the parameter and noise-basis axes pad to multiples of 8 (padded
  columns are identity-pinned / unit-prior, exactly the
  ``parallel.pta`` masking contract);
- the batch (request) axis pads to a power of two up to
  ``config.serve_max_batch`` (and to a mesh multiple when the engine
  shards the batch axis over a device mesh).

Total executables are then bounded by the product of the (few) bucket
counts — never by the request count. ``ExecutableCache`` owns fresh
jitted kernels (so its compile accounting is per-engine, not
process-global) and tracks every shape class it has dispatched.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pint_tpu.parallel.pta import _solve_one, pta_solve_np, \
    stack_problems

__all__ = ["bucket_for", "pad_dim", "pow2_ceil", "ExecutableCache",
           "gls_shape_class", "phase_shape_class"]


def pow2_ceil(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (max(1, int(n)) - 1).bit_length()


def bucket_for(n: int, edges: Tuple[int, ...]) -> Optional[int]:
    """Smallest bucket edge >= n, or None when n exceeds every edge
    (the scheduler's single-request fallback case)."""
    for e in edges:
        if n <= e:
            return e
    return None


def pad_dim(d: int, multiple: int = 8) -> int:
    """Pad a (parameter / basis) axis to a multiple; 0 stays 0 so
    white-noise models don't drag a dead basis block through the
    solve."""
    if d == 0:
        return 0
    return ((d + multiple - 1) // multiple) * multiple


def gls_shape_class(n: int, p: int, q: int, edges: Tuple[int, ...]):
    """(kind, N_bucket, p_pad, q_pad) for a fit/residuals request —
    or None when the TOA count exceeds every bucket edge. Fit and
    residual requests share classes: the solve kernel is
    structure-agnostic (it consumes padded matrices), so the
    component-structure part of the serve cache key collapses to the
    request kind class; the structure-sensitive compiles (the phase
    chain per model) stay cached in the model layer
    (``TimingModel._get_compiled``)."""
    nb = bucket_for(n, edges)
    if nb is None:
        return None
    return ("gls", nb, pad_dim(p), pad_dim(q))


def phase_shape_class(nmjd: int, ncoeff: int, edges: Tuple[int, ...]):
    """(kind, N_bucket, k_pad) for a phase-prediction request."""
    nb = bucket_for(nmjd, edges)
    if nb is None:
        return None
    return ("phase", nb, pad_dim(ncoeff, 4))


def _phase_eval_one(coeffs, tmid, rphase_int, rphase_frac, f0, mjds,
                    valid):
    """One polyco segment's absolute phase at padded MJDs (device
    mirror of ``polycos.PolycoEntry.abs_phase``). Horner from the
    highest coefficient — the same evaluation order as
    np.polynomial.polynomial.polyval, but XLA may fuse the
    multiply-add into an FMA, so the host oracle agrees only to ~1
    ulp of fractional phase (~1e-16 turn, orders below the 10 ps
    oracle budget); zero-padded high coefficients contribute exact
    zeros. Padded MJD slots carry dt=0 and are zeroed by ``valid``
    on the way out."""
    import jax.numpy as jnp

    dt = (mjds - tmid) * 1440.0
    poly = jnp.zeros_like(dt)
    for i in range(coeffs.shape[0] - 1, -1, -1):
        poly = poly * dt + coeffs[i]
    spin = 60.0 * f0 * dt
    spin_i = jnp.floor(spin)
    frac = rphase_frac + (spin - spin_i) + poly
    carry = jnp.floor(frac)
    return (rphase_int + spin_i + carry) * valid, \
        (frac - carry) * valid


class ExecutableCache:
    """Per-engine compiled-executable registry.

    One fresh ``jax.jit`` wrapper per kernel kind (NOT the module
    globals), so jit-cache growth is attributable to THIS engine: a
    compile happens exactly when a shape class first dispatches, and
    ``compile_count`` == the number of distinct classes seen. With a
    ``mesh``, batch-axis inputs are placed block-sharded over
    ``axis`` before the call (input shardings are part of XLA's cache
    key, so a mesh engine and a local engine never share entries —
    which is why each engine owns its wrappers)."""

    def __init__(self, mesh=None, axis: str = "pulsar",
                 supervisor=None):
        import jax

        from pint_tpu.runtime import get_supervisor

        self.mesh = mesh
        self.axis = axis
        self._gls = jax.jit(jax.vmap(_solve_one))
        self._phase = jax.jit(jax.vmap(_phase_eval_one))
        # every dispatch routes through the runtime supervisor:
        # watchdog deadline + host failover (numpy mirror for GLS,
        # PolycoEntry.abs_phase for phase) so a wedged backend can
        # never hang a serve batch — only slow it down, labeled
        self.supervisor = supervisor or get_supervisor()
        self.keys: set = set()

    @property
    def compile_count(self) -> int:
        """Distinct shape classes dispatched == executables built.
        Cross-checkable against the jit wrappers' own cache sizes
        (tests do)."""
        return len(self.keys)

    def jit_cache_size(self) -> Optional[int]:
        """Sum of the underlying jit caches' entry counts, when the
        running jax exposes it (None otherwise)."""
        try:
            return int(self._gls._cache_size()) + \
                int(self._phase._cache_size())
        except AttributeError:
            return None

    def _place(self, arrs: dict) -> dict:
        import jax
        import jax.numpy as jnp

        if self.mesh is None:
            return {k: jnp.asarray(v) for k, v in arrs.items()}
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = {}
        for k, v in arrs.items():
            v = jnp.asarray(v)
            sh = NamedSharding(
                self.mesh, P(self.axis, *([None] * (v.ndim - 1))))
            out[k] = jax.device_put(v, sh)
        return out

    def gls(self, key, problems, shape):
        """Pad ``problems`` to the class shape (``parallel.pta``
        masking) and solve the batch in one SUPERVISED dispatch
        (runtime watchdog; host ``pta_solve_np`` failover). Returns
        host arrays (dparams, cov, chi2, chi2r), each (P, ...). The
        class key is recorded only on success, so a failed dispatch
        cannot inflate ``compile_count`` past the classes actually
        built — and a failed-over (host-solved) dispatch does not
        record one either: no executable was built for it."""
        stacked = stack_problems(problems, shape=shape)

        def run():
            # place + dispatch + host read on the guarded worker so
            # the deadline covers completion, not just enqueue
            st = self._place(stacked)
            out = self._gls(st["M"], st["F"], st["phi"], st["r"], st["nvec"], st["valid"], st["pvalid"])  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            return tuple(np.asarray(o) for o in out)

        fell_over = []

        def host():
            fell_over.append(True)
            return pta_solve_np(stacked)

        host_out = self.supervisor.dispatch(
            run, key=f"serve.gls/{'/'.join(str(x) for x in key)}",
            fallback=host)
        if not fell_over:
            self.keys.add(key)
        return host_out

    def phase(self, key, requests, nb: int, kb: int, Pb: int):
        """Pad phase requests to (Pb, nb) MJDs x kb coefficients and
        evaluate the batch in one supervised dispatch (host failover:
        per-entry ``PolycoEntry.abs_phase``; key recorded on a real
        device dispatch only, as in ``gls``)."""
        coeffs = np.zeros((Pb, kb))
        tmid = np.zeros(Pb)
        rpi = np.zeros(Pb)
        rpf = np.zeros(Pb)
        f0 = np.zeros(Pb)
        mjds = np.zeros((Pb, nb))
        valid = np.zeros((Pb, nb))
        for k, rq in enumerate(requests):
            e = rq.entry
            c = np.asarray(e.coeffs, np.float64)
            coeffs[k, :len(c)] = c
            tmid[k] = e.tmid
            rpi[k] = e.rphase_int
            rpf[k] = e.rphase_frac
            f0[k] = e.f0
            m = rq.mjds
            mjds[k, :len(m)] = m
            mjds[k, len(m):] = e.tmid  # dt = 0 on padded slots
            valid[k, :len(m)] = 1.0

        def run():
            arrs = self._place({"coeffs": coeffs, "tmid": tmid,
                                "rpi": rpi, "rpf": rpf, "f0": f0,
                                "mjds": mjds, "valid": valid})
            pi, pf = self._phase(arrs["coeffs"], arrs["tmid"], arrs["rpi"], arrs["rpf"], arrs["f0"], arrs["mjds"], arrs["valid"])  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            return np.asarray(pi), np.asarray(pf)

        fell_over = []

        def host():
            fell_over.append(True)
            pi = np.zeros((Pb, nb))
            pf = np.zeros((Pb, nb))
            for k, rq in enumerate(requests):
                n = len(rq.mjds)
                hi, hf = rq.entry.abs_phase(rq.mjds)
                pi[k, :n] = hi
                pf[k, :n] = hf
            return pi, pf

        pi, pf = self.supervisor.dispatch(
            run, key=f"serve.phase/{'/'.join(str(x) for x in key)}",
            fallback=host)
        if not fell_over:
            self.keys.add(key)
        return pi, pf
