"""Shape-bucketed batch serving layer.

The ROADMAP north star is a system serving heavy traffic; the library
half of that is here. The moving parts (one module each):

- ``serve.request``: typed requests (fit step / residuals / phase
  prediction / posterior sampling / array GWB sweeps) with deadlines
  and result futures;
- ``serve.bucket``: power-of-two shape-class bucketing + the bounded
  executable cache (compiles scale with bucket count, not traffic);
- ``serve.scheduler``: the coalescing ServeEngine (admission queue,
  window batching, backpressure, single-request fallback) and the
  ``Fitter.auto(serve=...)``-routed fitter;
- ``serve.metrics``: per-bucket occupancy / waste / latency /
  compile counters, fed through the profiling hooks (plus the
  engine's runtime dispatch-supervisor counters — timeouts,
  failovers, breaker state — so degraded serving is labeled);
- ``serve.workload``: the ONE synthetic mixed-shape workload
  builder shared by bench_serve.py and the demo daemon;
- ``serve.admission`` (ISSUE 8): per-tenant token-bucket quotas,
  deadline-aware load shedding, in-queue deadline expiry — every
  shed labeled;
- ``serve.router`` (ISSUE 8): breaker-aware capacity routing over
  host CPU + accelerator as CONCURRENT pools with learned service
  rates (an open breaker demotes the device pool, it does not stop
  the world);
- ``serve.journal`` (ISSUE 8): crash-safe restart — append-only
  request journal with replay, jax.export AOT bucket executables
  (warm restart serves its first request with zero new compiles),
  serve-state snapshot;
- ``serve.fleet`` (ISSUE 19): N workers over one journal-as-
  replicated-log — worker leases with journal heartbeats,
  missed-lease fencing, and re-homing of a dead worker's
  unacknowledged admits onto survivors (lose a worker, lose 1/N
  capacity and zero accepted requests).

Every device dispatch routes through the engine's
``pint_tpu.runtime.DispatchSupervisor`` (watchdog deadline, circuit
breaker, host failover) — a wedged backend degrades a batch to the
host path instead of hanging it.

Entry points: ``scripts/pint_serve.py`` (stdin JSONL daemon) and
``bench_serve.py`` (sequential-vs-coalesced throughput artifact).
"""

from pint_tpu.serve.request import (  # noqa: F401
    AppendResult,
    AppendTOAsRequest,
    DeadlineExceeded,
    EngineKilled,
    FitStepRequest,
    FitStepResult,
    GWBRequest,
    GWBResult,
    PhasePredictRequest,
    PhasePredictResult,
    PosteriorRequest,
    PosteriorResult,
    ResidualsRequest,
    ResidualsResult,
    ServeFuture,
    ServeOverload,
    ShutdownShed,
    StateMissing,
    TenantOverQuota,
)
from pint_tpu.serve.append import (  # noqa: F401
    AppendStore,
    build_append_rows,
)
from pint_tpu.serve.scheduler import (  # noqa: F401
    ServeEngine,
    ServeGLSFitter,
)
from pint_tpu.serve.metrics import ServeMetrics  # noqa: F401
from pint_tpu.serve.bucket import (  # noqa: F401
    ExecutableCache,
    bucket_for,
    pow2_ceil,
)
from pint_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    TokenBucket,
)
from pint_tpu.serve.router import CapacityRouter  # noqa: F401
from pint_tpu.serve.journal import (  # noqa: F401
    AotStore,
    RequestJournal,
)
from pint_tpu.serve.fleet import (  # noqa: F401
    FleetFront,
    FleetWorker,
    WorkerLease,
)
