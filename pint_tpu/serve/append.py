"""Per-pulsar cached accumulated normal equations + the append rank
update (ISSUE 12): the serve-side half of the matrix-free streaming
GLS.

The online-timing workload (ROADMAP item 2b): live telescopes stream
TOAs into a persistent per-pulsar fit state. A cold build accumulates
the full dataset once; every subsequent ``AppendTOAsRequest`` ships
ONLY the new rows — assembled at admission in O(new TOAs), with the
noise basis evaluated on the COLD span's Fourier frequencies (the
``tspan`` override) so its columns align with the cached Gram — and
the device work is a rank UPDATE of the small (p+q)^2 accumulated
system plus the same preconditioned-CG finalize the streaming fitter
uses (``parallel.streaming._cg_schur``). Re-convergence is O(new
TOAs) host work + O((p+q)^2) device work, never a cold refit.

Concurrency contract: the append kernel is PURE — it returns the new
rows' DELTA contributions, and the engine applies them to the store
under a lock at collect time. Deltas are additive because the column
scale ``cm`` is FROZEN at cold build (appended rows reuse it; the
f64 exponent headroom over the cold column maxima is enormous), so
two same-key requests batched together each see the pre-batch state
and both deltas land — each response reflects the data up to and
including its own rows.

States are in-memory (the store is not journaled): after a process
restart the first request per key must be a cold build
(``StateMissing`` otherwise — a replayed append must never
masquerade as a full fit).
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.parallel.pta import PulsarProblem
from pint_tpu.runtime import locks
from pint_tpu.parallel.streaming import _cg_schur, cg_solve_np

__all__ = ["AppendProblem", "AppendStore", "AppendStateEntry",
           "build_append_rows", "append_slot_np"]


class AppendProblem(PulsarProblem):
    """One append batch's assembled rows: like ``PulsarProblem`` but
    ``r`` is NOT mean-subtracted (the mean correction is applied at
    solve time from accumulated scalars, over the COMBINED set) and
    the basis span / mean-subtraction flag ride along."""

    def __init__(self, *a, tspan: float = 0.0, tref: float = 0.0,
                 submean: bool = True, **kw):
        super().__init__(*a, **kw)
        self.tspan = float(tspan)
        self.tref = float(tref)     # cold first-TOA day (basis epoch)
        self.submean = bool(submean)


def build_append_rows(toas, model, tspan: Optional[float] = None,
                      tref: Optional[float] = None,
                      track_mode=None) -> AppendProblem:
    """Assemble ONE batch of rows for the append path (O(batch) host
    work). ``tspan``/``tref`` pin the Fourier fundamental and the
    basis epoch to the cold build's (None = derive from these TOAs —
    the cold build). Rejects wideband TOAs and ECORR models
    (appended epochs would grow the basis rank past the fixed shape
    class)."""
    from pint_tpu.residuals import Residuals
    from pint_tpu.wideband import has_wideband_dm

    if has_wideband_dm(toas):
        raise ValueError("AppendTOAsRequest cannot serve wideband "
                         "TOAs (no stacked [time; DM] append system)")
    pairs = model.noise_model_basis_weight_pairs(toas, tspan=tspan,
                                                 tref_day=tref)
    if any("Ecorr" in name for name, _, _ in pairs):
        raise ValueError(
            "AppendTOAsRequest cannot serve ECORR models: appended "
            "epochs grow the quantization-basis rank, which would "
            "break the cached accumulated system's fixed shape; use "
            "the streaming fitter (cold) for ECORR models")
    res = Residuals(toas, model, track_mode=track_mode,
                    subtract_mean=False)
    M, names, _ = model.designmatrix(toas, incoffset=True)
    nvec = model.scaled_toa_uncertainty(toas) ** 2
    if pairs:
        F = np.concatenate([f for _, f, _ in pairs], axis=1)
        phi = np.concatenate([p for _, _, p in pairs])
    else:
        F = np.zeros((toas.ntoas, 0))
        phi = np.ones(0)
    if tspan is None:
        from pint_tpu.models.noise import _tdb_seconds

        t = _tdb_seconds(toas)
        tspan = float(t.max() - t.min()) if len(t) > 1 else 1.0
    if tref is None:
        tref = float(toas.tdb_day.min())
    return AppendProblem(
        np.asarray(M), np.asarray(res.time_resids), nvec, F, phi,
        names, model=model, toas=toas, tspan=tspan, tref=tref,
        submean="PhaseOffset" not in model.components)


# ----------------------------------------------------------- kernel


def _append_slot(cm, Sig, b, u, scal, M, F, phi, r0, nvec, valid,
                 pvalid, submean, cold, budget, tol):
    """One padded batch slot's rank update + re-solve (pure,
    vmappable): fold the new rows' Gram/moment contributions into
    the slot's accumulated state, then CG-solve the COMBINED system
    via the same Jacobi-preconditioned Schur operator the streaming
    fitter uses. Returns the DELTAS (additive; the engine owns the
    store mutation) plus the solve outputs. ``cold`` slots derive
    their frozen column scale from their own rows; warm slots reuse
    the state's. ``submean``/``cold`` are per-slot runtime flags so
    PHOFF and cold/warm requests share one compiled class."""
    p = M.shape[1]
    Mm = M * pvalid[None, :]
    w = valid / nvec
    colmax = jnp.max(jnp.abs(Mm) * valid[:, None], axis=0)
    cm_used = jnp.where(cold > 0.5,
                        jnp.where(colmax == 0, 1.0, colmax), cm)
    cm_used = jnp.where(cm_used == 0, 1.0, cm_used)
    big = jnp.concatenate([Mm / cm_used[None, :],
                           F * valid[:, None]], axis=1)
    bigw = big * w[:, None]
    dSig = big.T @ bigw
    db = bigw.T @ r0
    du = bigw.T @ valid
    dscal = jnp.zeros_like(scal)
    dscal = dscal.at[0].set(jnp.sum(w * r0 * r0))
    dscal = dscal.at[1].set(jnp.sum(w * r0))
    dscal = dscal.at[2].set(jnp.sum(w))
    Sig2 = Sig + dSig
    b2 = b + db
    u2 = u + du
    scal2 = scal + dscal
    sw = scal2[2]
    swr0 = scal2[1]
    mu = submean * swr0 / jnp.where(sw > 0, sw, 1.0)
    bfin = b2 - mu * u2
    rCr = scal2[0] - 2.0 * mu * swr0 + mu * mu * sw
    q = F.shape[1]
    prior = jnp.concatenate([jnp.zeros(p), 1.0 / phi]) if q else \
        jnp.zeros(p)
    Sigma = Sig2 + jnp.diag(prior)
    colvalid = jnp.concatenate([pvalid, jnp.ones(q)])
    Sigma = Sigma * jnp.outer(colvalid, colvalid) + \
        jnp.diag(1.0 - colvalid)
    bfin = bfin * colvalid
    dp, cov, chi2, chi2r, _, ok, iters, _resid = _cg_schur(
        Sigma, bfin, rCr, cm_used, budget, tol)
    return (cm_used, dSig, db, du, dscal, dp * pvalid, cov, chi2,
            chi2r, ok, iters)


def append_slot_np(cm, Sig, b, u, scal, M, F, phi, r0, nvec, valid,
                   pvalid, submean, cold, budget=None, tol=1e-13):
    """Numpy mirror of ``_append_slot`` — the capacity router's host
    pool and the supervisor's failover path (identical algebra)."""
    p = M.shape[1]
    Mm = M * pvalid[None, :]
    w = valid / nvec
    colmax = np.max(np.abs(Mm) * valid[:, None], axis=0) \
        if M.shape[0] else np.zeros(p)
    cm_used = np.where(cold > 0.5,
                       np.where(colmax == 0, 1.0, colmax), cm)
    cm_used = np.where(cm_used == 0, 1.0, cm_used)
    big = np.concatenate([Mm / cm_used[None, :],
                          F * valid[:, None]], axis=1)
    bigw = big * w[:, None]
    dSig = big.T @ bigw
    db = bigw.T @ r0
    du = bigw.T @ valid
    dscal = np.zeros_like(scal)
    dscal[0] = np.sum(w * r0 * r0)
    dscal[1] = np.sum(w * r0)
    dscal[2] = np.sum(w)
    Sig2 = Sig + dSig
    b2 = b + db
    u2 = u + du
    scal2 = scal + dscal
    sw, swr0 = scal2[2], scal2[1]
    mu = float(submean) * swr0 / (sw if sw > 0 else 1.0)
    bfin = b2 - mu * u2
    rCr = scal2[0] - 2.0 * mu * swr0 + mu * mu * sw
    q = F.shape[1]
    prior = np.concatenate([np.zeros(p), 1.0 / phi]) if q else \
        np.zeros(p)
    Sigma = Sig2 + np.diag(prior)
    colvalid = np.concatenate([pvalid, np.ones(q)])
    Sigma = Sigma * np.outer(colvalid, colvalid) + \
        np.diag(1.0 - colvalid)
    bfin = bfin * colvalid
    dp, cov, chi2, chi2r, _, ok, iters, _resid = cg_solve_np(
        Sigma, bfin, float(rCr), cm_used, budget=budget, tol=tol)
    return (cm_used, dSig, db, du, dscal, dp * pvalid, cov, chi2,
            chi2r, ok, iters)


# ------------------------------------------------------------ store


class AppendStateEntry:
    """One pulsar's accumulated normal equations at its linearization
    point theta_0, padded to its shape class's (pb, qb). All arrays
    host numpy; mutation only through ``AppendStore.commit``."""

    __slots__ = ("key", "names", "p", "q", "pb", "qb", "cm", "Sig",
                 "b", "u", "scal", "phi", "tspan", "tref", "submean",
                 "ntoa", "updates")

    def __init__(self, key: str, names: List[str], p: int, q: int,
                 pb: int, qb: int, phi: np.ndarray, tspan: float,
                 tref: float, submean: bool):
        P = pb + qb
        self.key = key
        self.names = list(names)
        self.p = p
        self.q = q
        self.pb = pb
        self.qb = qb
        self.cm = np.ones(pb)
        self.Sig = np.zeros((P, P))
        self.b = np.zeros(P)
        self.u = np.zeros(P)
        self.scal = np.zeros(8)
        self.phi = np.asarray(phi, np.float64)
        self.tspan = float(tspan)
        self.tref = float(tref)
        self.submean = bool(submean)
        self.ntoa = 0
        self.updates = 0

    def check_compatible(self, problem):
        from pint_tpu.serve.bucket import pad_dim

        if list(problem.names) != self.names:
            raise ValueError(
                f"append state {self.key!r} was built for params "
                f"{self.names}; this request's model has "
                f"{list(problem.names)} — re-submit a cold build")
        if pad_dim(problem.M.shape[1]) != self.pb or \
                pad_dim(problem.F.shape[1]) != self.qb:
            raise ValueError(
                f"append state {self.key!r} shape class changed; "
                f"re-submit a cold build")
        if problem.phi.shape[0] != self.q or (
                self.q and not np.allclose(problem.phi,
                                           self.phi[:self.q])):
            raise ValueError(
                f"append state {self.key!r}: noise hyperparameters "
                f"changed since the cold build — re-linearize with a "
                f"cold build")

    def stacked_phi(self) -> np.ndarray:
        out = np.ones(self.qb)
        out[:self.q] = self.phi[:self.q]
        return out


class AppendStore:
    """The engine's per-pulsar state registry. Reads at dispatch
    time, delta commits at collect time, both under one lock; the
    counters are registry-backed (graftlint G13)."""

    def __init__(self):
        import weakref

        from pint_tpu.obs import metrics as om

        self._lock = locks.make_lock("serve.append_store")
        self._states: dict = {}
        scope = om.new_scope("append")
        self._c_cold = om.counter(
            "pint_tpu_append_cold_builds_total",
            "append-state cold builds").child(scope=scope)
        self._c_upd = om.counter(
            "pint_tpu_append_rank_updates_total",
            "append-state rank updates").child(scope=scope)
        # weakref pull-fn (the bucket.py gauge pattern): the registry
        # is process-global and outlives the engine — a strong `self`
        # capture would pin every per-pulsar (P,P) state past
        # shutdown; a dead store's gauge just stops producing
        ref = weakref.ref(self)
        om.gauge("pint_tpu_append_states",
                 "live per-pulsar append states").set_fn(
            lambda: (lambda s: float(len(s._states))
                     if s is not None else None)(ref()),
            scope=scope)

    def get(self, key: str) -> Optional[AppendStateEntry]:
        with self._lock:
            return self._states.get(key)

    def commit(self, key: str, problem, pb: int, qb: int, cold: bool,
               cm_used, dSig, db, du, dscal, nrows: int
               ) -> AppendStateEntry:
        """Apply one slot's deltas. A cold commit (RE)CREATES the
        entry from zero — that is the explicit re-linearization path
        (changed parameters/hyperparameters, or a fresh dataset);
        the previous state, if any, is replaced wholesale. Two cold
        builds racing in one batch therefore resolve last-wins —
        each is a complete dataset by the explicit-cold contract, so
        either outcome is internally consistent."""
        with self._lock:
            entry = self._states.get(key)
            if cold:
                entry = AppendStateEntry(
                    key, problem.names, problem.M.shape[1],
                    problem.F.shape[1], pb, qb, problem.phi,
                    problem.tspan, problem.tref, problem.submean)
                entry.cm = np.asarray(cm_used, np.float64).copy()
                self._states[key] = entry
                self._c_cold.inc()
            else:
                if entry is None:
                    from pint_tpu.serve.request import StateMissing

                    raise StateMissing(
                        f"append state {key!r} vanished before "
                        f"collect (restart?)")
                self._c_upd.inc()
            entry.Sig += np.asarray(dSig)
            entry.b += np.asarray(db)
            entry.u += np.asarray(du)
            entry.scal += np.asarray(dscal)
            entry.ntoa += int(nrows)
            entry.updates += 1
            return entry

    def drop(self, key: str):
        with self._lock:
            self._states.pop(key, None)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "states": len(self._states),
                "cold_builds": int(self._c_cold.value()),
                "rank_updates": int(self._c_upd.value()),
                "ntoa_total": int(sum(e.ntoa
                                      for e in self._states.values())),
            }


def append_kernel():
    """The jitted vmapped slot kernel (one wrapper; XLA caches one
    executable per padded shape class)."""
    return jax.jit(jax.vmap(
        _append_slot,
        in_axes=(0,) * 14 + (None, None)))
