"""Energy-dependent pulse-profile templates.

Reference: src/pint/templates/lceprimitives.py + lcenorms.py — there,
each primitive/norm object carries per-parameter slopes in
x = log10(E/E0) evaluated per photon through python class machinery.
TPU-first redesign: ONE flat theta holds the base template parameters
plus d(param)/dx slopes, and the pdf evaluates every photon's
(phase, energy) pair in a single fused XLA program:

    logits_e = logits + x * dlogits     -> softmax_e (per photon)
    loc_k(E) = loc_k + x * dloc_k
    w_k(E)   = exp(log w_k + x * dlogw_k)
    f(phi, E) = p0(E) + sum_k p_k(E) prim_k(phi; loc_k(E), w_k(E))

Each primitive pdf is normalized for every width, and the softmax
normalizations sum to 1 at every energy, so f(.|E) is a proper
conditional density — matching the reference's convention.

theta layout (m primitives, all single-shape):
    [logits (m+1) | locs (m) | log_w (m) | dlogits (m+1) | dloc (m) |
     dlogw (m)]
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.templates import (LCGaussian, LCLorentzian, LCTemplate,
                                LCVonMises)

__all__ = ["LCEnergyTemplate", "LCEnergyFitter"]

_E_PRIMS = (LCGaussian, LCVonMises, LCLorentzian)


def _prim_pdf_vec(prim, phi, loc, width):
    """Primitive pdf with PER-PHOTON loc/width arrays. The von Mises
    and Lorentzian base pdfs are purely elementwise and broadcast
    per-photon shapes as-is (one source of truth for each
    normalization convention); only the Gaussian needs a variant — its
    base pdf's wrapped-copies axis assumes a scalar width."""
    if isinstance(prim, LCGaussian):
        ns = jnp.arange(-3.0, 4.0)
        z = (phi[:, None] - loc[:, None] + ns[None, :]) \
            / width[:, None]
        return jnp.sum(jnp.exp(-0.5 * z * z), axis=-1) / (
            width * jnp.sqrt(2 * jnp.pi))
    return prim.pdf(phi, loc, (width,))


class LCEnergyTemplate:
    """Template whose normalizations, peak locations, and widths vary
    linearly in x = log10(E/E0) (reference: lceprimitives'
    'slope' parameterization)."""

    def __init__(self, template: LCTemplate, e0_kev: float = 1.0,
                 dlogits=None, dloc=None, dlogw=None):
        for p in template.primitives:
            if not isinstance(p, _E_PRIMS):
                raise ValueError(
                    f"energy-dependent templates support "
                    f"{[c.name for c in _E_PRIMS]}; got {p.name}")
        self.primitives = list(template.primitives)
        m = len(self.primitives)
        self.e0_kev = float(e0_kev)
        base = np.asarray(template.theta, dtype=np.float64)

        def slopes(v, n, name):
            if v is None:
                return np.zeros(n)
            v = np.asarray(v, dtype=np.float64)
            if v.shape != (n,):
                raise ValueError(
                    f"{name} needs shape ({n},), got {v.shape} — a "
                    "wrong length would silently shift every slope "
                    "slice in theta")
            return v

        self.theta = np.concatenate([
            base,
            slopes(dlogits, m + 1, "dlogits"),
            slopes(dloc, m, "dloc"),
            slopes(dlogw, m, "dlogw")])

    @property
    def m(self) -> int:
        return len(self.primitives)

    def _pdf_fn(self):
        prims = list(self.primitives)
        m = len(prims)
        e0 = self.e0_kev

        def pdf(theta, phi, energy_kev):
            x = jnp.log10(energy_kev / e0)
            logits = theta[:m + 1]
            locs = theta[m + 1:2 * m + 1]
            logw = theta[2 * m + 1:3 * m + 1]
            dlogits = theta[3 * m + 1:4 * m + 2]
            dloc = theta[4 * m + 2:5 * m + 2]
            dlogw = theta[5 * m + 2:6 * m + 2]
            p = jax.nn.softmax(logits[None, :]
                               + x[:, None] * dlogits[None, :],
                               axis=-1)              # (N, m+1)
            val = p[:, 0]
            for k, prim in enumerate(prims):
                loc_e = locs[k] + x * dloc[k]
                w_e = jnp.exp(logw[k] + x * dlogw[k])
                val = val + p[:, k + 1] * _prim_pdf_vec(
                    prim, phi, loc_e, w_e)
            return val

        return pdf

    def __call__(self, phases, energies_kev, theta=None) -> np.ndarray:
        theta = self.theta if theta is None else theta
        return np.asarray(self._pdf_fn()(
            jnp.asarray(theta), jnp.asarray(phases),
            jnp.asarray(energies_kev)))

    def base_template(self) -> LCTemplate:
        """The energy-independent template at E = E0."""
        m = self.m
        t = LCTemplate.__new__(LCTemplate)
        t.primitives = list(self.primitives)
        t._shape_sizes = [1] * m
        t.theta = np.asarray(self.theta[:3 * m + 1]).copy()
        return t

    def random(self, n: int, energies_kev,
               rng: Optional[np.random.Generator] = None) -> np.ndarray:
        """Draw photon phases given per-photon energies (inverse-cdf
        on a fine grid — exact enough for tests/simulation)."""
        rng = rng or np.random.default_rng()
        energies_kev = np.asarray(energies_kev, dtype=np.float64)
        if energies_kev.shape != (n,):
            raise ValueError(
                f"energies_kev must have shape ({n},) matching n; "
                f"got {energies_kev.shape}")
        grid = np.linspace(0.0, 1.0, 2049)
        centers = 0.5 * (grid[:-1] + grid[1:])
        pdf = self._pdf_fn()
        # vectorized: the full (N, G) pdf matrix in one device call
        vals = np.asarray(jax.vmap(
            lambda c: pdf(jnp.asarray(self.theta),
                          jnp.full(energies_kev.shape, c),
                          jnp.asarray(energies_kev)),
            out_axes=1)(jnp.asarray(centers)))
        cdf = np.cumsum(vals, axis=1)
        cdf /= cdf[:, -1:]
        u = rng.uniform(size=n)
        # per-row inverse cdf without a python loop: rows are monotone
        idx = (cdf < u[:, None]).sum(axis=1)
        return centers[np.clip(idx, 0, len(centers) - 1)]

    def __str__(self):
        m = self.m
        lines = [f"LCEnergyTemplate (E0 = {self.e0_kev} keV)"]
        lines.append(str(self.base_template()))
        lines.append("slopes per decade of energy:")
        lines.append(f"  dloc  {np.round(self.theta[4*m+2:5*m+2], 4)}")
        lines.append(f"  dlogw {np.round(self.theta[5*m+2:6*m+2], 4)}")
        return "\n".join(lines)


class LCEnergyFitter:
    """Unbinned weighted ML over (phase, energy) photon pairs
    (reference: lcfitters with energy-dependent primitives)."""

    def __init__(self, template: LCEnergyTemplate, phases,
                 energies_kev, weights=None):
        self.template = template
        self.phases = jnp.asarray(np.mod(phases, 1.0))
        self.energies = jnp.asarray(np.asarray(energies_kev,
                                               dtype=np.float64))
        self.weights = (jnp.ones_like(self.phases) if weights is None
                        else jnp.asarray(weights))
        pdf = template._pdf_fn()

        def nll(theta):
            f = pdf(theta, self.phases, self.energies)
            return -jnp.sum(jnp.log(self.weights * f
                                    + (1.0 - self.weights)))

        self._nll = jax.jit(nll)
        self._valgrad = jax.jit(jax.value_and_grad(nll))

    def loglikelihood(self, theta=None) -> float:
        theta = self.template.theta if theta is None else theta
        return -float(self._nll(jnp.asarray(theta)))

    def fit(self, maxiter: int = 500) -> dict:
        from scipy.optimize import minimize

        def f(x):
            v, g = self._valgrad(jnp.asarray(x))
            return float(v), np.asarray(g, dtype=np.float64)

        res = minimize(f, np.asarray(self.template.theta), jac=True,
                       method="BFGS",
                       options={"maxiter": maxiter, "gtol": 1e-6})
        self.template.theta = np.asarray(res.x)
        gnorm = float(np.linalg.norm(res.jac))
        return {"loglikelihood": -float(res.fun),
                "iterations": int(res.nit),
                "grad_norm": gnorm,
                "success": bool(res.success)
                or gnorm < 1e-4 * max(1.0, abs(float(res.fun)))}
