"""Photon pulse-profile templates + maximum-likelihood fitting.

Reference: src/pint/templates/ (lcprimitives.py LCGaussian/...,
lctemplate.py LCTemplate, lcfitters.py LCFitter, lcnorm.py NormAngles)
— ~4k LoC of numpy class machinery there. TPU-first redesign: a
template is a pure function of a flat parameter vector; the unbinned
weighted photon log-likelihood and its gradient are one jitted XLA
reduction over the photon axis, and the ML fit is gradient-based
L-BFGS over that kernel (the reference uses scipy simplex/L-BFGS with
per-primitive gradient bookkeeping).

Parameterization (one flat f64 vector ``theta``):
    theta = [logits (m+1,) | locs (m,) | log_shapes (sum n_shape,)]
softmax(logits) -> [background, norm_1..norm_m]: normalizations are
positive and sum to 1 with the background taking the remainder, so no
constrained optimizer is needed (the reference's NormAngles spherical
parameterization solves the same problem; softmax is the standard
unconstrained simplex map and is smooth for autodiff). Shape
parameters (widths) live in log space so they stay positive.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LCPrimitive", "LCGaussian", "LCGaussian2", "LCVonMises",
           "LCLorentzian", "LCLorentzian2", "LCTopHat",
           "LCSkewGaussian", "LCEmpiricalFourier", "LCKernelDensity",
           "LCTemplate", "LCFitter", "GaussianPrior",
           "read_template", "write_template", "make_template"]


class LCPrimitive:
    """One peak shape: a normalized pdf on phase [0,1) with a location
    and ``n_shape`` positive shape parameters (reference:
    lcprimitives.LCPrimitive)."""

    name = "prim"
    n_shape = 1

    @staticmethod
    def pdf(phi, loc, shape):  # pragma: no cover - abstract
        """shape is a (n_shape,) slice of exp(log_shapes)."""
        raise NotImplementedError

    @classmethod
    def fwhm(cls, shape) -> float:
        """Full width at half max in phase units (reference:
        LCPrimitive.fwhm); default assumes shape[0] is a Gaussian-like
        sigma."""
        return float(2.0 * math.sqrt(2.0 * math.log(2.0)) * shape[0])


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian peak (reference: lcprimitives.LCGaussian).
    shape[0] = sigma in phase units; wrapping summed over +-3 turns."""

    name = "gaussian"

    @staticmethod
    def pdf(phi, loc, shape):
        width = shape[0]
        d = phi - loc
        ns = jnp.arange(-3.0, 4.0)
        z = (d[..., None] + ns) / width
        g = jnp.exp(-0.5 * z * z)
        return jnp.sum(g, axis=-1) / (width * jnp.sqrt(2 * jnp.pi))


class LCGaussian2(LCPrimitive):
    """Two-sided (asymmetric) wrapped Gaussian: sigma_left below the
    peak, sigma_right above, continuous at the peak with overall unit
    normalization 2/(sl+sr) scaling (reference:
    lcprimitives.LCGaussian2)."""

    name = "gaussian2"
    n_shape = 2

    @staticmethod
    def pdf(phi, loc, shape):
        sl, sr = shape[0], shape[1]
        d = phi - loc
        ns = jnp.arange(-3.0, 4.0)
        dn = d[..., None] + ns
        sig = jnp.where(dn < 0, sl, sr)
        g = jnp.exp(-0.5 * (dn / sig) ** 2)
        norm = jnp.sqrt(2 * jnp.pi) * 0.5 * (sl + sr)
        return jnp.sum(g, axis=-1) / norm

    @classmethod
    def fwhm(cls, shape) -> float:
        k = 2.0 * math.sqrt(2.0 * math.log(2.0))
        return float(0.5 * k * (shape[0] + shape[1]))


class LCVonMises(LCPrimitive):
    """Von Mises peak: exp(kappa cos 2pi(phi-loc)) / I0(kappa), with
    kappa = 1/(2 pi width)^2 matching the reference's width convention
    (reference: lcprimitives.LCVonMises)."""

    name = "vonmises"

    @staticmethod
    def pdf(phi, loc, shape):
        width = shape[0]
        kappa = 1.0 / (2.0 * jnp.pi * width) ** 2
        val = jnp.exp(kappa * (jnp.cos(2 * jnp.pi * (phi - loc)) - 1.0))
        norm = jax.scipy.special.i0e(kappa)  # e^-k I0(k): overflow-safe
        return val / norm


class LCLorentzian(LCPrimitive):
    """Wrapped Lorentzian (wrapped-Cauchy closed form), width = HWHM in
    phase units (reference: lcprimitives.LCLorentzian)."""

    name = "lorentzian"

    @staticmethod
    def pdf(phi, loc, shape):
        width = shape[0]
        rho = jnp.exp(-2.0 * jnp.pi * width)
        c = jnp.cos(2.0 * jnp.pi * (phi - loc))
        return (1.0 - rho ** 2) / (1.0 + rho ** 2 - 2.0 * rho * c)

    @classmethod
    def fwhm(cls, shape) -> float:
        return float(2.0 * shape[0])


class LCLorentzian2(LCPrimitive):
    """Two-sided wrapped Lorentzian: HWHM gamma_left below the peak,
    gamma_right above (reference: lcprimitives.LCLorentzian2). Built
    from two half wrapped-Cauchy lobes, each lobe weighted so the
    composite is continuous at the peak and integrates to 1."""

    name = "lorentzian2"
    n_shape = 2

    @staticmethod
    def pdf(phi, loc, shape):
        gl, gr = shape[0], shape[1]

        def half(width, c):
            rho = jnp.exp(-2.0 * jnp.pi * width)
            val = (1.0 - rho ** 2) / (1.0 + rho ** 2 - 2.0 * rho * c)
            peak = (1.0 + rho) / (1.0 - rho)   # value at phase == loc
            return val, peak

        # signed phase distance in (-0.5, 0.5]
        d = jnp.mod(phi - loc + 0.5, 1.0) - 0.5
        c = jnp.cos(2.0 * jnp.pi * d)
        vl, pl = half(gl, c)
        vr, pr = half(gr, c)
        # scale each lobe to a common peak height, then normalize:
        # each full wrapped-Cauchy integrates to 1, so each half-lobe
        # (scaled by s) integrates to s/2.
        sl = 1.0 / pl
        sr = 1.0 / pr
        val = jnp.where(d < 0, sl * vl, sr * vr)
        return val / (0.5 * (sl + sr))

    @classmethod
    def fwhm(cls, shape) -> float:
        return float(shape[0] + shape[1])


class LCTopHat(LCPrimitive):
    """Smoothed top hat: product of two logistic edges of 1% of the
    width, full width = shape[0] in phase (reference:
    lcprimitives.LCTopHat — exact box there; smoothed here so the ML
    fit stays differentiable)."""

    name = "tophat"

    @staticmethod
    def pdf(phi, loc, shape):
        width = shape[0]
        k = 100.0 / width  # edge sharpness: 1% of the width
        d = jnp.mod(phi - loc + 0.5, 1.0) - 0.5
        box = jax.nn.sigmoid(k * (d + width / 2)) * \
            jax.nn.sigmoid(-k * (d - width / 2))
        # normalization of the product of sigmoids ~ width for k*w >> 1
        return box / width

    @classmethod
    def fwhm(cls, shape) -> float:
        return float(shape[0])


class LCSkewGaussian(LCPrimitive):
    """Wrapped skew-normal peak (reference: the lcprimitives skew
    family): pdf = 2/sigma phi(z) Phi(alpha z), z = d/sigma. Shape
    params ride the template's log transform (positive), so the SIGNED
    skewness alpha is stored as shape[1] = exp(alpha): shape[1] = 1 is
    symmetric, >1 skews the tail to later phase, <1 to earlier."""

    name = "skewgaussian"
    n_shape = 2

    @staticmethod
    def pdf(phi, loc, shape):
        sigma = shape[0]
        alpha = jnp.log(shape[1])
        d = phi - loc
        ns = jnp.arange(-3.0, 4.0)
        z = (d[..., None] + ns) / sigma
        g = jnp.exp(-0.5 * z * z) / (sigma * jnp.sqrt(2 * jnp.pi))
        cdf = 0.5 * (1.0 + jax.scipy.special.erf(
            alpha * z / jnp.sqrt(2.0)))
        return jnp.sum(2.0 * g * cdf, axis=-1)

    @classmethod
    def fwhm(cls, shape) -> float:
        # Gaussian-equivalent width of the skew-normal
        a = math.log(float(shape[1]))
        dlt = a / math.sqrt(1 + a * a)
        sd = float(shape[0]) * math.sqrt(1 - 2 * dlt * dlt / math.pi)
        return 2.0 * math.sqrt(2.0 * math.log(2.0)) * sd


_PRIM_TYPES = {c.name: c for c in
               (LCGaussian, LCGaussian2, LCVonMises, LCLorentzian,
                LCLorentzian2, LCTopHat, LCSkewGaussian)}


class LCEmpiricalFourier:
    """Empirical template as a truncated Fourier series measured from
    photon phases (reference: lcprimitives/lctemplate empirical
    Fourier machinery): pdf(phi) = max(1 + Σ_k a_k cos 2πkφ +
    b_k sin 2πkφ, eps), renormalized after the positivity clip.
    A fixed (measured, not ML-fit) profile for phase-folding /
    weighted-H workflows; use LCTemplate+LCFitter for parametric
    fits."""

    def __init__(self, coeffs_cos, coeffs_sin):
        self.a = np.asarray(coeffs_cos, np.float64)
        self.b = np.asarray(coeffs_sin, np.float64)
        if self.a.shape != self.b.shape:
            raise ValueError("cos/sin coefficient shapes differ")
        self._norm = self._compute_norm()

    @classmethod
    def from_phases(cls, phases, weights=None, nharm: int = 20):
        """Measure the harmonic coefficients from (weighted) photon
        phases: a_k = 2<w cos 2πkφ>/<w>, b_k likewise (the empirical
        characteristic function)."""
        ph = np.mod(np.asarray(phases, np.float64), 1.0)
        w = np.ones_like(ph) if weights is None else \
            np.asarray(weights, np.float64)
        k = np.arange(1, nharm + 1)
        arg = 2 * np.pi * ph[:, None] * k[None, :]
        wsum = w.sum()
        a = 2.0 * (w[:, None] * np.cos(arg)).sum(0) / wsum
        b = 2.0 * (w[:, None] * np.sin(arg)).sum(0) / wsum
        return cls(a, b)

    def _raw(self, phi):
        phi = np.mod(np.asarray(phi, np.float64), 1.0)
        k = np.arange(1, len(self.a) + 1)
        arg = 2 * np.pi * phi[..., None] * k
        return (1.0 + (self.a * np.cos(arg)).sum(-1)
                + (self.b * np.sin(arg)).sum(-1))

    def _compute_norm(self) -> float:
        xs = np.linspace(0.0, 1.0, 4096, endpoint=False)
        return float(np.mean(np.maximum(self._raw(xs), 1e-6)))

    def __call__(self, phases) -> np.ndarray:
        return np.maximum(self._raw(phases), 1e-6) / self._norm


class LCKernelDensity:
    """Empirical template as a wrapped-Gaussian kernel density of the
    photon phases (reference: lcprimitives.LCKernelDensity). Bandwidth
    defaults to the circular Silverman rule; evaluation is gridded +
    interpolated so calling with millions of photons stays cheap."""

    def __init__(self, phases, weights=None, bw: float = None,
                 ngrid: int = 1024):
        ph = np.mod(np.asarray(phases, np.float64), 1.0)
        w = np.ones_like(ph) if weights is None else \
            np.asarray(weights, np.float64)
        if bw is None:
            # circular dispersion -> Silverman-style bandwidth, scaled
            # DOWN 3x: pulse profiles are multimodal (narrow peaks on
            # a broad background), where the global Silverman rule
            # oversmooths by roughly the peak width; pass bw= to
            # control it exactly
            C = np.average(np.cos(2 * np.pi * ph), weights=w)
            S = np.average(np.sin(2 * np.pi * ph), weights=w)
            R = np.hypot(C, S)
            sigma_c = np.sqrt(max(-2.0 * np.log(max(R, 1e-12)),
                                  1e-4)) / (2 * np.pi)
            neff = w.sum() ** 2 / (w ** 2).sum()
            bw = 1.06 * sigma_c * neff ** (-0.2) / 3.0
        self.bw = float(max(bw, 2.0 / ngrid))
        # bin CENTERS: anchoring at left edges would rotate the whole
        # density by -0.5/ngrid (a systematic phase bias)
        grid = (np.arange(ngrid) + 0.5) / ngrid
        # O(N + ngrid log ngrid): histogram the weighted phases onto
        # the grid (bin width 1/ngrid << bw, negligible smearing) and
        # circular-convolve with the wrapped-Gaussian kernel by FFT —
        # construction stays cheap at millions of photons. The kernel
        # is indexed by center-to-center offsets, so it is the same
        # circular-distance array either way.
        hist, _ = np.histogram(ph, bins=ngrid, range=(0.0, 1.0),
                               weights=w)
        off = np.arange(ngrid) / ngrid
        dcirc = np.minimum(off, 1.0 - off)
        kern = np.exp(-0.5 * (dcirc / self.bw) ** 2)
        dens = np.real(np.fft.ifft(np.fft.fft(hist)
                                   * np.fft.fft(kern)))
        self._grid = grid
        self._dens = np.maximum(dens, 0.0) / np.mean(
            np.maximum(dens, 0.0))

    def __call__(self, phases) -> np.ndarray:
        ph = np.mod(np.asarray(phases, np.float64), 1.0)
        # circular interpolation: pad both ends with the wrapped
        # neighbors (grid runs 0.5/G .. 1-0.5/G)
        xp = np.concatenate([[self._grid[-1] - 1.0], self._grid,
                             [self._grid[0] + 1.0]])
        fp = np.concatenate([[self._dens[-1]], self._dens,
                             [self._dens[0]]])
        return np.interp(ph, xp, fp)


class LCTemplate:
    """Weighted sum of primitives + uniform background (reference:
    lctemplate.LCTemplate). Holds primitive *types*; all numeric state
    lives in the flat theta vector so the pdf is a pure function."""

    def __init__(self, primitives: Sequence[LCPrimitive],
                 norms: Sequence[float], locs: Sequence[float],
                 widths):
        self.primitives = list(primitives)
        m = len(self.primitives)
        shapes = [np.atleast_1d(np.asarray(w, dtype=np.float64))
                  for w in widths]
        for p, s in zip(self.primitives, shapes):
            if s.shape != (p.n_shape,):
                raise ValueError(
                    f"{p.name} needs {p.n_shape} shape params, "
                    f"got {s.shape}")
        assert len(norms) == len(locs) == m
        self._shape_sizes = [p.n_shape for p in self.primitives]
        self.theta = self.pack(np.asarray(norms, dtype=np.float64),
                               np.asarray(locs, dtype=np.float64),
                               shapes)

    # ---- flat parameter vector ------------------------------------

    @staticmethod
    def pack(norms, locs, shapes: List[np.ndarray]) -> np.ndarray:
        bg = 1.0 - np.sum(norms)
        if bg <= 0:
            raise ValueError("norms must sum to < 1")
        logits = np.log(np.concatenate([[bg], norms]))
        return np.concatenate([logits, locs,
                               np.log(np.concatenate(shapes))])

    def unpack(self, theta):
        m = len(self.primitives)
        p = jax.nn.softmax(jnp.asarray(theta[:m + 1]))
        locs = jnp.mod(jnp.asarray(theta[m + 1:2 * m + 1]), 1.0)
        flat = jnp.exp(jnp.asarray(theta[2 * m + 1:]))
        shapes, off = [], 0
        for n in self._shape_sizes:
            shapes.append(flat[off:off + n])
            off += n
        return p[1:], locs, shapes

    # ---- evaluation ------------------------------------------------

    def _pdf_fn(self):
        prim_pdfs = [p.pdf for p in self.primitives]
        sizes = list(self._shape_sizes)
        m = len(prim_pdfs)

        def pdf(theta, phi):
            p = jax.nn.softmax(theta[:m + 1])
            locs = theta[m + 1:2 * m + 1]
            flat = jnp.exp(theta[2 * m + 1:])
            val = p[0] * jnp.ones_like(phi)
            off = 0
            for k, f in enumerate(prim_pdfs):
                val = val + p[k + 1] * f(phi, locs[k],
                                         flat[off:off + sizes[k]])
                off += sizes[k]
            return val

        return pdf

    def __call__(self, phases, theta=None) -> np.ndarray:
        theta = self.theta if theta is None else theta
        return np.asarray(self._pdf_fn()(jnp.asarray(theta),
                                         jnp.asarray(phases)))

    @property
    def norms(self) -> np.ndarray:
        return np.asarray(self.unpack(self.theta)[0])

    @property
    def locs(self) -> np.ndarray:
        return np.asarray(self.unpack(self.theta)[1])

    @property
    def widths(self) -> List[np.ndarray]:
        return [np.asarray(s) for s in self.unpack(self.theta)[2]]

    # ---- profile statistics (reference: LCTemplate delta/Delta) ----

    def fwhms(self) -> List[float]:
        return [p.fwhm(s) for p, s in
                zip(self.primitives, self.widths)]

    def delta(self) -> Optional[float]:
        """Phase of the highest-amplitude peak (reference:
        LCTemplate.delta: radio-to-peak offset)."""
        if not self.primitives:
            return None
        k = int(np.argmax(self.norms))
        return float(self.locs[k])

    def Delta(self) -> Optional[float]:
        """Separation of the two strongest peaks in phase (reference:
        LCTemplate.Delta)."""
        if len(self.primitives) < 2:
            return None
        order = np.argsort(self.norms)[::-1]
        a, b = self.locs[order[0]], self.locs[order[1]]
        d = abs(a - b)
        return float(min(d, 1.0 - d))

    def param_mask(self, free_norms=True, free_locs=True,
                   free_widths=True, prims=None) -> np.ndarray:
        """Boolean mask over theta selecting FREE entries, for
        LCFitter's free= argument (reference: the LCNorm/LCPrimitive
        free arrays). ``prims`` restricts to a subset of primitive
        indices; note norms live on a softmax simplex, so freeing any
        norm also frees the background logit (the simplex has one
        redundant direction — holding the rest fixed keeps their
        RATIOS fixed, the natural analog of the reference's fixed
        norms)."""
        m = len(self.primitives)
        sel = list(range(m)) if prims is None else list(prims)
        mask = np.zeros(len(np.asarray(self.theta)), bool)
        if free_norms:
            mask[0] = True
            for k in sel:
                mask[1 + k] = True
        if free_locs:
            for k in sel:
                mask[m + 1 + k] = True
        if free_widths:
            off = 2 * m + 1
            for k, nsh in enumerate(self._shape_sizes):
                if k in sel:
                    mask[off:off + nsh] = True
                off += nsh
        return mask

    def rotate(self, dphi: float):
        """Shift every peak location by dphi (mod 1), in place
        (reference: LCTemplate.rotate)."""
        m = len(self.primitives)
        th = np.asarray(self.theta).copy()
        th[m + 1:2 * m + 1] = np.mod(th[m + 1:2 * m + 1] + dphi, 1.0)
        self.theta = th

    def integrate(self, ph1: float, ph2: float, n: int = 2001) -> float:
        """Trapezoid integral of the pdf on [ph1, ph2] (reference:
        LCTemplate.integrate); used for binned likelihoods."""
        xs = np.linspace(ph1, ph2, n)
        return float(np.trapezoid(self(xs), xs))

    def random(self, n: int,
               rng: Optional[np.random.Generator] = None) -> np.ndarray:
        """Draw n photon phases from the template (for simulation
        tests; reference: LCTemplate.random)."""
        rng = rng or np.random.default_rng()
        norms = self.norms
        locs = self.locs
        shapes = self.widths
        bg = 1.0 - norms.sum()
        comp = rng.choice(len(norms) + 1, size=n,
                          p=np.concatenate([[bg], norms]))
        out = rng.uniform(size=n)  # background
        for k, prim in enumerate(self.primitives):
            idx = comp == k + 1
            nk = int(idx.sum())
            if nk == 0:
                continue
            s = shapes[k]
            if isinstance(prim, LCSkewGaussian):
                # skew-normal draw: z = d*|z0| + sqrt(1-d^2)*z1 with
                # d = alpha/sqrt(1+alpha^2) (Azzalini representation)
                alpha = np.log(s[1])
                dlt = alpha / np.sqrt(1 + alpha * alpha)
                z0 = np.abs(rng.normal(size=nk))
                z1 = rng.normal(size=nk)
                draw = locs[k] + s[0] * (dlt * z0
                                         + np.sqrt(1 - dlt ** 2) * z1)
            elif isinstance(prim, LCGaussian):
                draw = rng.normal(locs[k], s[0], size=nk)
            elif isinstance(prim, LCGaussian2):
                side = rng.uniform(size=nk) < s[0] / (s[0] + s[1])
                mag = np.abs(rng.normal(0.0, 1.0, size=nk))
                draw = locs[k] + np.where(side, -mag * s[0], mag * s[1])
            elif isinstance(prim, LCVonMises):
                kappa = 1.0 / (2 * np.pi * s[0]) ** 2
                draw = locs[k] + rng.vonmises(0.0, kappa, size=nk) / (
                    2 * np.pi)
            elif isinstance(prim, LCTopHat):
                draw = locs[k] + s[0] * (rng.uniform(size=nk) - 0.5)
            elif isinstance(prim, LCLorentzian2):
                side = rng.uniform(size=nk) < s[0] / (s[0] + s[1])
                mag = np.abs(np.tan(np.pi * (rng.uniform(size=nk)
                                             - 0.5)))
                draw = locs[k] + np.where(side, -mag * s[0],
                                          mag * s[1])
            else:  # Lorentzian: Cauchy with HWHM already in phase
                draw = locs[k] + s[0] * np.tan(
                    np.pi * (rng.uniform(size=nk) - 0.5))
            out[idx] = draw
        return np.mod(out, 1.0)

    def __str__(self):
        lines = []
        for p, nrm, loc, sh in zip(self.primitives, self.norms,
                                   self.locs, self.widths):
            ss = " ".join(f"{x:.6g}" for x in sh)
            lines.append(f"{p.name:<12} norm={nrm:.4f} loc={loc:.4f} "
                         f"shape=[{ss}]")
        lines.append(f"background   {1.0 - self.norms.sum():.4f}")
        return "\n".join(lines)


def make_template(spec: Sequence[Tuple[str, float, float, object]]
                  ) -> LCTemplate:
    """Build from (name, norm, loc, width-or-widths) rows; names are
    the primitive ``name`` attributes ('gaussian', 'vonmises', ...)."""
    prims, norms, locs, widths = [], [], [], []
    for name, nrm, loc, w in spec:
        try:
            prims.append(_PRIM_TYPES[name]())
        except KeyError:
            raise ValueError(f"unknown primitive {name!r}; know "
                             f"{sorted(_PRIM_TYPES)}") from None
        norms.append(nrm)
        locs.append(loc)
        widths.append(w)
    return LCTemplate(prims, norms, locs, widths)


# ---- template file I/O (reference: lcprimitives prim_io /
# lctemplate.prim_io read/write of .gauss profile files) -------------

def write_template(template: LCTemplate, path: str):
    """Plain-text profile file: one primitive per line,
    ``name norm loc shape...``, '#' comments."""
    with open(path, "w") as fh:
        fh.write("# pint_tpu pulse-profile template\n")
        fh.write("# name norm loc shape_params...\n")
        for p, nrm, loc, sh in zip(template.primitives, template.norms,
                                   template.locs, template.widths):
            ss = " ".join(repr(float(x)) for x in sh)
            fh.write(f"{p.name} {float(nrm)!r} {float(loc)!r} {ss}\n")


def read_template(path: str) -> LCTemplate:
    spec = []
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            toks = line.split()
            name = toks[0].lower()
            vals = [float(t) for t in toks[1:]]
            if len(vals) < 3:
                raise ValueError(f"bad template line: {line!r}")
            spec.append((name, vals[0], vals[1],
                         vals[2] if len(vals) == 3 else vals[2:]))
    if not spec:
        raise ValueError(f"no primitives found in {path}")
    return make_template(spec)


class GaussianPrior:
    """Gaussian penalty on selected theta entries (reference:
    lcfitters' location/width priors keeping peaks from wandering)."""

    def __init__(self, indices, means, sigmas):
        self.indices = jnp.asarray(np.asarray(indices, dtype=np.int64))
        self.means = jnp.asarray(np.asarray(means, dtype=np.float64))
        self.sigmas = jnp.asarray(np.asarray(sigmas, dtype=np.float64))

    def nll(self, theta):
        z = (theta[self.indices] - self.means) / self.sigmas
        return 0.5 * jnp.sum(z * z)


class LCFitter:
    """Unbinned weighted ML template fitter (reference:
    lcfitters.LCFitter). loglikelihood = sum_i log(w_i f(phi_i) +
    (1-w_i)); the photon-axis reduction is one jitted XLA program and
    the optimizer is host L-BFGS-B over the device value-and-grad."""

    def __init__(self, template: LCTemplate, phases,
                 weights=None, prior: Optional[GaussianPrior] = None):
        self.template = template
        self.phases = jnp.asarray(np.mod(phases, 1.0))
        self.weights = (jnp.ones_like(self.phases) if weights is None
                        else jnp.asarray(weights))
        pdf = template._pdf_fn()

        def nll(theta):
            f = pdf(theta, self.phases)
            val = -jnp.sum(jnp.log(self.weights * f
                                   + (1.0 - self.weights)))
            if prior is not None:
                val = val + prior.nll(theta)
            return val

        self._nll = jax.jit(nll)
        self._valgrad = jax.jit(jax.value_and_grad(nll))
        self._hess = jax.jit(jax.hessian(nll))

    def loglikelihood(self, theta=None) -> float:
        theta = self.template.theta if theta is None else theta
        return -float(self._nll(jnp.asarray(theta)))

    def fit(self, maxiter: int = 500, compute_errors: bool = True,
            free=None) -> dict:
        """ML fit; updates the template's theta in place. With
        compute_errors, invert the exact autodiff Hessian at the
        optimum for the theta covariance (reference: LCFitter's
        hess_errors). ``free`` is a boolean theta mask (see
        LCTemplate.param_mask) — fixed entries are held at their
        current values (reference: the free/fixed machinery on LCNorm
        and each LCPrimitive)."""
        from scipy.optimize import minimize

        theta0 = np.asarray(self.template.theta, np.float64)
        free = np.ones(len(theta0), bool) if free is None \
            else np.asarray(free, bool)
        base = jnp.asarray(theta0)
        fidx = jnp.asarray(np.nonzero(free)[0])

        def f(x):
            full = base.at[fidx].set(jnp.asarray(x))
            v, g = self._valgrad(full)
            return float(v), np.asarray(g, dtype=np.float64)[free]

        # dense BFGS: theta is tiny (3m+1) and scipy 1.17's L-BFGS-B
        # line search stalls on the phase-periodic landscape
        res = minimize(f, theta0[free], jac=True, method="BFGS",
                       options={"maxiter": maxiter, "gtol": 1e-6})
        theta = theta0.copy()
        theta[free] = np.asarray(res.x)
        self.template.theta = theta
        gnorm = float(np.linalg.norm(res.jac))
        # BFGS often ends with "precision loss" right at the optimum;
        # a small gradient relative to |logL| is convergence
        out = {"loglikelihood": -float(res.fun),
               "iterations": int(res.nit),
               "grad_norm": gnorm,
               "success": bool(res.success)
               or gnorm < 1e-4 * max(1.0, abs(float(res.fun)))}
        if compute_errors:
            H = np.asarray(self._hess(jnp.asarray(theta)))
            Hf = H[np.ix_(free, free)]
            err = np.zeros(len(theta))
            try:
                cov = np.linalg.inv(Hf)
                err[free] = np.sqrt(np.maximum(np.diag(cov), 0.0))
            except np.linalg.LinAlgError:
                cov = None
                err[free] = np.nan
            out["theta_cov"] = cov  # free-subset covariance
            out["theta_err"] = err  # full-length, 0 at fixed entries
        return out

    # ---- binned fit (reference: LCFitter chi-squared path) ---------

    def fit_binned(self, nbins: int = 64, maxiter: int = 500) -> dict:
        """Weighted binned Poisson-chi2 fit: faster for huge photon
        sets; bins the weighted phase histogram once on the host, then
        minimizes chi2 against bin-center pdf values."""
        from scipy.optimize import minimize

        w = np.asarray(self.weights)
        ph = np.asarray(self.phases)
        hist, edges = np.histogram(ph, bins=nbins, range=(0.0, 1.0),
                                   weights=w)
        var, _ = np.histogram(ph, bins=nbins, range=(0.0, 1.0),
                              weights=w * w)
        centers = 0.5 * (edges[:-1] + edges[1:])
        wsum = w.sum()
        pdf = self.template._pdf_fn()
        cj = jnp.asarray(centers)
        hj = jnp.asarray(hist)
        vj = jnp.asarray(np.maximum(var, 1e-12))

        def chi2(theta):
            mu = pdf(theta, cj) * (wsum / nbins)
            return jnp.sum((hj - mu) ** 2 / vj)

        vg = jax.jit(jax.value_and_grad(chi2))

        def f(x):
            v, g = vg(jnp.asarray(x))
            return float(v), np.asarray(g, dtype=np.float64)

        res = minimize(f, np.asarray(self.template.theta), jac=True,
                       method="BFGS",
                       options={"maxiter": maxiter, "gtol": 1e-6})
        self.template.theta = np.asarray(res.x)
        gnorm = float(np.linalg.norm(res.jac))
        return {"chi2": float(res.fun), "nbins": nbins,
                "iterations": int(res.nit),
                "success": bool(res.success)
                or gnorm < 1e-4 * max(1.0, abs(float(res.fun)))}

    def __str__(self):
        return (f"LCFitter: {len(np.asarray(self.phases))} photons, "
                f"logL={self.loglikelihood():.2f}\n"
                f"{self.template}")
