"""Photon pulse-profile templates + maximum-likelihood fitting.

Reference: src/pint/templates/ (lcprimitives.py LCGaussian/...,
lctemplate.py LCTemplate, lcfitters.py LCFitter) — ~4k LoC of numpy
class machinery there. TPU-first redesign: a template is a pure
function of a flat parameter vector; the unbinned weighted photon
log-likelihood and its gradient are one jitted XLA reduction over the
photon axis, and the ML fit is gradient-based (the reference uses
scipy simplex/L-BFGS per-primitive bookkeeping).

Parameterization (one flat f64 vector `theta`):
    theta = [logits (m+1,) | locs (m,) | log_widths (m,)]
softmax(logits) -> [background, norm_1..norm_m]: normalizations are
positive and sum to 1 with the background taking the remainder, so no
constrained optimizer is needed.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LCPrimitive", "LCGaussian", "LCVonMises", "LCLorentzian",
           "LCTemplate", "LCFitter"]


class LCPrimitive:
    """One peak shape: a normalized pdf on phase [0,1) with a location
    and a width parameter (reference: lcprimitives.LCPrimitive)."""

    name = "prim"

    @staticmethod
    def pdf(phi, loc, width):  # pragma: no cover - abstract
        raise NotImplementedError


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian peak (reference: lcprimitives.LCGaussian).
    width = sigma in phase units; wrapping summed over +-3 turns."""

    name = "gaussian"

    @staticmethod
    def pdf(phi, loc, width):
        d = phi - loc
        ns = jnp.arange(-3.0, 4.0)
        z = (d[..., None] + ns) / width[..., None]
        g = jnp.exp(-0.5 * z * z)
        return jnp.sum(g, axis=-1) / (width * jnp.sqrt(2 * jnp.pi))


class LCVonMises(LCPrimitive):
    """Von Mises peak: exp(kappa cos 2pi(phi-loc)) / I0(kappa), with
    kappa = 1/(2 pi width)^2 matching the reference's width convention
    (reference: lcprimitives.LCVonMises)."""

    name = "vonmises"

    @staticmethod
    def pdf(phi, loc, width):
        kappa = 1.0 / (2.0 * jnp.pi * width) ** 2
        val = jnp.exp(kappa * (jnp.cos(2 * jnp.pi * (phi - loc)) - 1.0))
        norm = jax.scipy.special.i0e(kappa)  # e^-k I0(k): overflow-safe
        return val / norm


class LCLorentzian(LCPrimitive):
    """Wrapped Lorentzian (wrapped-Cauchy closed form), width = HWHM in
    phase units (reference: lcprimitives.LCLorentzian)."""

    name = "lorentzian"

    @staticmethod
    def pdf(phi, loc, width):
        rho = jnp.exp(-2.0 * jnp.pi * width)
        c = jnp.cos(2.0 * jnp.pi * (phi - loc))
        return (1.0 - rho ** 2) / (1.0 + rho ** 2 - 2.0 * rho * c)


_PRIM_TYPES = {c.name: c for c in (LCGaussian, LCVonMises, LCLorentzian)}


class LCTemplate:
    """Weighted sum of primitives + uniform background (reference:
    lctemplate.LCTemplate). Holds primitive *types*; all numeric state
    lives in the flat theta vector so the pdf is a pure function."""

    def __init__(self, primitives: Sequence[LCPrimitive],
                 norms: Sequence[float], locs: Sequence[float],
                 widths: Sequence[float]):
        self.primitives = list(primitives)
        m = len(self.primitives)
        assert len(norms) == len(locs) == len(widths) == m
        self.theta = self.pack(np.asarray(norms, dtype=np.float64),
                               np.asarray(locs, dtype=np.float64),
                               np.asarray(widths, dtype=np.float64))

    # ---- flat parameter vector ------------------------------------

    @staticmethod
    def pack(norms, locs, widths) -> np.ndarray:
        bg = 1.0 - np.sum(norms)
        if bg <= 0:
            raise ValueError("norms must sum to < 1")
        logits = np.log(np.concatenate([[bg], norms]))
        return np.concatenate([logits, locs, np.log(widths)])

    def unpack(self, theta) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        m = len(self.primitives)
        p = jax.nn.softmax(jnp.asarray(theta[:m + 1]))
        locs = jnp.mod(jnp.asarray(theta[m + 1:2 * m + 1]), 1.0)
        widths = jnp.exp(jnp.asarray(theta[2 * m + 1:]))
        return p[1:], locs, widths

    # ---- evaluation ------------------------------------------------

    def _pdf_fn(self):
        prim_pdfs = [p.pdf for p in self.primitives]
        m = len(prim_pdfs)

        def pdf(theta, phi):
            p = jax.nn.softmax(theta[:m + 1])
            locs = theta[m + 1:2 * m + 1]
            widths = jnp.exp(theta[2 * m + 1:])
            val = p[0] * jnp.ones_like(phi)
            for k, f in enumerate(prim_pdfs):
                val = val + p[k + 1] * f(phi, locs[k], widths[k])
            return val

        return pdf

    def __call__(self, phases, theta=None) -> np.ndarray:
        theta = self.theta if theta is None else theta
        return np.asarray(self._pdf_fn()(jnp.asarray(theta),
                                         jnp.asarray(phases)))

    @property
    def norms(self) -> np.ndarray:
        return np.asarray(self.unpack(self.theta)[0])

    @property
    def locs(self) -> np.ndarray:
        return np.asarray(self.unpack(self.theta)[1])

    @property
    def widths(self) -> np.ndarray:
        return np.asarray(self.unpack(self.theta)[2])

    def random(self, n: int,
               rng: Optional[np.random.Generator] = None) -> np.ndarray:
        """Draw n photon phases from the template (for simulation
        tests; reference: LCTemplate.random)."""
        rng = rng or np.random.default_rng()
        norms, locs, widths = (np.asarray(x) for x in
                               self.unpack(self.theta))
        bg = 1.0 - norms.sum()
        comp = rng.choice(len(norms) + 1, size=n,
                          p=np.concatenate([[bg], norms]))
        out = rng.uniform(size=n)  # background
        for k, prim in enumerate(self.primitives):
            idx = comp == k + 1
            nk = int(idx.sum())
            if nk == 0:
                continue
            if isinstance(prim, LCGaussian):
                draw = rng.normal(locs[k], widths[k], size=nk)
            elif isinstance(prim, LCVonMises):
                kappa = 1.0 / (2 * np.pi * widths[k]) ** 2
                draw = locs[k] + rng.vonmises(0.0, kappa, size=nk) / (
                    2 * np.pi)
            else:  # Lorentzian
                draw = locs[k] + widths[k] * np.tan(
                    np.pi * (rng.uniform(size=nk) - 0.5)) / (2 * np.pi)
            out[idx] = draw
        return np.mod(out, 1.0)


@partial(jax.jit, static_argnames=("pdf_id",))
def _nll_cached(theta, phases, weights, pdf_id):  # pragma: no cover
    raise RuntimeError("placeholder; replaced per-template below")


class LCFitter:
    """Unbinned weighted ML template fitter (reference:
    lcfitters.LCFitter). loglikelihood = sum_i log(w_i f(phi_i) +
    (1-w_i)); optimization is jitted gradient descent with backtracking
    (no scipy dependency on the device path)."""

    def __init__(self, template: LCTemplate, phases,
                 weights=None):
        self.template = template
        self.phases = jnp.asarray(np.mod(phases, 1.0))
        self.weights = (jnp.ones_like(self.phases) if weights is None
                        else jnp.asarray(weights))
        pdf = template._pdf_fn()

        def nll(theta):
            f = pdf(theta, self.phases)
            return -jnp.sum(jnp.log(self.weights * f
                                    + (1.0 - self.weights)))

        self._nll = jax.jit(nll)
        self._valgrad = jax.jit(jax.value_and_grad(nll))

    def loglikelihood(self, theta=None) -> float:
        theta = self.template.theta if theta is None else theta
        return -float(self._nll(jnp.asarray(theta)))

    def fit(self, maxiter: int = 500) -> dict:
        """ML fit: host L-BFGS-B over the jitted device
        value-and-grad (the reduction over the photon axis is the hot
        part and runs as one XLA program per evaluation); updates the
        template's theta in place."""
        from scipy.optimize import minimize

        def f(x):
            v, g = self._valgrad(jnp.asarray(x))
            return float(v), np.asarray(g, dtype=np.float64)

        res = minimize(f, np.asarray(self.template.theta), jac=True,
                       method="L-BFGS-B",
                       options={"maxiter": maxiter})
        self.template.theta = np.asarray(res.x)
        return {"loglikelihood": -float(res.fun),
                "iterations": int(res.nit),
                "grad_norm": float(np.linalg.norm(res.jac)),
                "success": bool(res.success)}
