"""Fake-TOA simulation.

Reference: src/pint/simulation.py (make_fake_toas_uniform,
zero_residuals, make_fake_toas_fromtim). TOAs are Newton-iterated onto
integer model phase (2–3 passes through the full jitted forward model),
then optionally perturbed by a white-noise draw.
"""

from __future__ import annotations

import warnings
from typing import Optional

import numpy as np

from pint_tpu.ops import dd_np
from pint_tpu.residuals import Residuals
from pint_tpu.toa import TOAs, get_TOAs_array

SECS_PER_DAY = 86400.0


def zero_residuals(toas: TOAs, model, maxiter: int = 4,
                   tol_s: float = 1e-10) -> TOAs:
    """Shift TOA MJDs until model residual phase is integer (reference:
    simulation.zero_residuals Newton loop)."""
    t = toas
    for _ in range(maxiter):
        r = Residuals(t, model, track_mode="nearest",
                      subtract_mean=False).time_resids
        if np.max(np.abs(r)) < tol_s:
            break
        day = t.mjd_day
        frac = dd_np.sub(t.mjd_frac,
                         dd_np.div_f(dd_np.dd(np.asarray(r)), SECS_PER_DAY))
        t = _rebuild(t, day, frac)
    return t


def _rebuild(t: TOAs, day, frac) -> TOAs:
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        new = get_TOAs_array((day, frac), obs=t.obs, freqs=t.freq_mhz,
                             errors=t.error_us, flags=t.flags,
                             ephem=t.ephem, planets=t.planets)
    new.names = list(t.names)
    return new


def correlated_noise_draw(toas: TOAs, model,
                          rng: Optional[np.random.Generator] = None
                          ) -> np.ndarray:
    """One realization [s] of the model's correlated-noise processes:
    delta = F @ (sqrt(phi) * z), z ~ N(0,1) per basis column (reference:
    simulation.add_correlated_noise over the noise-model bases)."""
    rng = rng or np.random.default_rng()
    F = model.noise_model_designmatrix(toas)
    if F is None:
        return np.zeros(toas.ntoas)
    phi = model.noise_model_basis_weight(toas)
    return F @ (np.sqrt(phi) * rng.standard_normal(F.shape[1]))


def _noise_draw_s(t: TOAs, model, rng, white: bool,
                  correlated: bool) -> np.ndarray:
    """Noise draw [s]: white at the EFAC/EQUAD-scaled sigma when
    ``white``, plus a correlated-basis draw when ``correlated``."""
    noise_s = np.zeros(t.ntoas)
    if white:
        sigma = model.scaled_toa_uncertainty(t) if model.noise_components \
            else t.error_us * 1e-6
        noise_s = rng.standard_normal(t.ntoas) * sigma
    if correlated:
        noise_s = noise_s + correlated_noise_draw(t, model, rng)
    return noise_s


def make_fake_toas_uniform(startMJD: float, endMJD: float, ntoas: int,
                           model, error_us: float = 1.0, obs: str = "gbt",
                           freq_mhz: float = 1400.0, add_noise: bool = False,
                           add_correlated_noise: bool = False,
                           rng: Optional[np.random.Generator] = None,
                           name: str = "fake", flags=None) -> TOAs:
    """Evenly spaced synthetic TOAs landing on integer model phase
    (reference: make_fake_toas_uniform)."""
    return make_fake_toas_fromMJDs(
        np.linspace(float(startMJD), float(endMJD), int(ntoas)), model,
        error_us=error_us, obs=obs, freq_mhz=freq_mhz,
        add_noise=add_noise, add_correlated_noise=add_correlated_noise,
        rng=rng, name=name, flags=flags)


def make_fake_toas_fromMJDs(mjds, model, error_us=1.0, obs: str = "gbt",
                            freq_mhz=1400.0, add_noise: bool = False,
                            add_correlated_noise: bool = False,
                            rng: Optional[np.random.Generator] = None,
                            name: str = "fake", flags=None) -> TOAs:
    """Synthetic TOAs at the given MJDs, landing on integer model phase
    (reference: make_fake_toas_fromMJDs). ``freq_mhz``/``error_us`` may
    be scalars or per-TOA arrays. ``flags``: per-TOA flag dicts (or one
    dict applied to all) — set them HERE, not after the fact, so
    flag-selected noise models (EFAC/EQUAD/ECORR maskParameters) apply
    to the simulated noise draw too."""
    mjds = np.atleast_1d(np.asarray(mjds, dtype=np.float64))
    if isinstance(flags, dict):
        flags = [dict(flags) for _ in range(mjds.shape[0])]
    elif flags is not None and len(flags) != mjds.shape[0]:
        raise ValueError(
            f"flags has {len(flags)} entries for {mjds.shape[0]} "
            f"TOAs (pass one dict to apply the same flags to all)")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t = get_TOAs_array(
            mjds, obs=obs, freqs=freq_mhz, errors=error_us,
            ephem=model.EPHEM.value, flags=flags,
            planets=bool(model.PLANET_SHAPIRO.value))
    t.names = [f"{name}{i}" for i in range(t.ntoas)]
    t = zero_residuals(t, model)
    if add_noise or add_correlated_noise:
        rng = rng or np.random.default_rng()
        noise_s = _noise_draw_s(t, model, rng, add_noise,
                                add_correlated_noise)
        frac = dd_np.add(t.mjd_frac,
                         dd_np.div_f(dd_np.dd(noise_s), SECS_PER_DAY))
        t = _rebuild(t, t.mjd_day, frac)
    return t


def make_fake_toas_fromtim(timfile, model, add_noise=False,
                           add_correlated_noise=False, rng=None):
    """Replace the TOAs of an existing tim file with model-aligned fakes
    (reference: make_fake_toas_fromtim)."""
    from pint_tpu.toa import get_TOAs

    t = get_TOAs(timfile, model=model)
    t = zero_residuals(t, model)
    if add_noise or add_correlated_noise:
        rng = rng or np.random.default_rng()
        noise_s = _noise_draw_s(t, model, rng, add_noise,
                                add_correlated_noise)
        frac = dd_np.add(t.mjd_frac,
                         dd_np.div_f(dd_np.dd(noise_s), SECS_PER_DAY))
        t = _rebuild(t, t.mjd_day, frac)
    return t


def calculate_random_models(fitter, toas, Nmodels: int = 100,
                            rng: Optional[np.random.Generator] = None):
    """Draw parameter vectors from the post-fit covariance and return the
    per-draw residual curves [s] (reference:
    simulation.calculate_random_models)."""
    rng = rng or np.random.default_rng()
    cov = fitter.parameter_covariance_matrix
    if cov is None:
        raise ValueError("fit first: no covariance available")
    names = [n for n in ["Offset"] + fitter.model.free_params
             if n != "Offset"]
    # covariance includes the Offset column when fitted with incoffset
    full_names = ["Offset"] + names if cov.shape[0] == len(names) + 1 \
        else names
    draws = rng.multivariate_normal(
        np.zeros(cov.shape[0]), cov, size=Nmodels)
    out = np.empty((Nmodels, toas.ntoas))
    import copy

    for k in range(Nmodels):
        m = copy.deepcopy(fitter.model)
        for name, dx in zip(full_names, draws[k]):
            if name == "Offset":
                continue
            m.get_param(name).add_delta(float(dx))
        m.invalidate_cache(params_only=True)
        out[k] = Residuals(toas, m, subtract_mean=False).time_resids
    return out
