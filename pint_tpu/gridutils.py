"""Chi-squared grids over frozen parameter pairs.

Reference: src/pint/gridutils.py (grid_chisq, grid_chisq_derived) — the
reference's ONLY intra-process parallelism, a ProcessPoolExecutor
refitting the model at every grid node. TPU-first redesign: freeze the
gridded parameters, build the fused fit step over the remaining free
parameters once, and vmap it over all grid nodes — the whole grid
(every node running `maxiter` full phase-chain + GLS refit iterations)
is ONE jitted device call.
"""

from __future__ import annotations

import copy
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["grid_chisq", "grid_chisq_derived"]


def _build_grid_eval(model, toas, parnames: Sequence[str],
                     maxiter: int):
    """(eval_fn, node_builder): eval_fn maps a (G,) vector of gridded-
    parameter values to the refit chi2; vmap-ready."""
    from pint_tpu.parallel.fit_step import build_fit_step

    m = copy.deepcopy(model)
    for name in parnames:
        p = m.get_param(name)
        if p.value is None:
            raise ValueError(f"{name} has no value to grid around")
        p.frozen = True
    m.invalidate_cache()
    # an empty remaining-free set is fine: the implicit Offset column is
    # always profiled, so the step still returns a meaningful chi2
    step_fn, args, names = build_fit_step(m, toas)
    noff = 1 if names and names[0] == "Offset" else 0
    th0 = args[0]
    _, frozen_names, _, _, fh0, fl0 = m._pack()
    gidx = jnp.asarray([frozen_names.index(nm) for nm in parnames])
    # grid values are absolute: zero the dd low part too, else a fitted
    # parameter's residual lo (~eps*value, e.g. ~0.1 sigma for F0)
    # silently shifts every node off its nominal coordinate
    fl_z = jnp.asarray(fl0).at[gidx].set(0.0)

    def eval_node(gvals):
        fh = jnp.asarray(fh0).at[gidx].set(gvals)
        th = th0

        def one_iter(th):
            # out[:4] rather than a fixed unpack: with
            # $PINT_TPU_HEALTH armed the step carries its in-trace
            # health vector as a fifth output (ISSUE 14)
            dparams, cov, chi2, r = step_fn(
                th, args[1], fh, fl_z, *args[4:])[:4]
            # drop the Offset column when present; the rest align
            # with th (PHOFF models have no implicit offset column)
            return th + dparams[noff:], chi2

        for _ in range(maxiter):
            th, _ = one_iter(th)
        _, chi2 = one_iter(th)  # chi2 at the refit point
        return chi2

    return eval_node, names


def grid_chisq(model, toas, parnames: Sequence[str],
               parvalues: Sequence[np.ndarray], maxiter: int = 2
               ) -> np.ndarray:
    """chi2 over the outer-product grid of ``parvalues`` with the
    parameters in ``parnames`` held fixed at each node and every other
    free parameter refit (reference: gridutils.grid_chisq; the
    ProcessPoolExecutor is replaced by one vmapped device call).

    Returns an array of shape (len(parvalues[0]), len(parvalues[1]),
    ...) matching np.meshgrid(..., indexing='ij').
    """
    if len(parnames) != len(parvalues):
        raise ValueError("parnames and parvalues must pair up")
    grids = [np.asarray(v, dtype=np.float64) for v in parvalues]
    mesh = np.meshgrid(*grids, indexing="ij")
    nodes = np.stack([g.ravel() for g in mesh], axis=1)  # (S, G)
    eval_node, _ = _build_grid_eval(model, toas, parnames, maxiter)
    chi2 = jax.jit(jax.vmap(eval_node))(jnp.asarray(nodes))
    return np.asarray(chi2).reshape(mesh[0].shape)


def grid_chisq_derived(model, toas, parnames: Sequence[str],
                       parfuncs: Sequence[Callable],
                       gridvalues: Sequence[np.ndarray],
                       maxiter: int = 2
                       ) -> Tuple[np.ndarray, list]:
    """Grid over derived quantities: ``parfuncs[k](*grid_coords)``
    gives the value of ``parnames[k]`` at each node (reference:
    gridutils.grid_chisq_derived). Returns (chi2, [param value arrays])."""
    if not (len(parnames) == len(parfuncs) == len(gridvalues)):
        raise ValueError("parnames, parfuncs, gridvalues must pair up")
    grids = [np.asarray(v, dtype=np.float64) for v in gridvalues]
    mesh = np.meshgrid(*grids, indexing="ij")
    pvals = [np.asarray(f(*mesh), dtype=np.float64) for f in parfuncs]
    nodes = np.stack([v.ravel() for v in pvals], axis=1)
    eval_node, _ = _build_grid_eval(model, toas, parnames, maxiter)
    chi2 = jax.jit(jax.vmap(eval_node))(jnp.asarray(nodes))
    return np.asarray(chi2).reshape(mesh[0].shape), pvals
