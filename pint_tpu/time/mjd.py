"""High-precision MJD handling: decimal-string ↔ double-double, and the
"pulsar MJD" convention.

TOA files carry MJDs as decimal strings with up to ~19 significant digits
— far beyond f64. The reference routes these through ``np.longdouble``
(src/pint/pulsar_mjd.py); here each MJD becomes a host dd pair
(day-integer, day-fraction) that is exact to <1 ps.

The "pulsar_mjd" convention (reference: PulsarMJD astropy Time format):
observatory UTC MJDs count 86400 s/day even on leap-second days; the day
fraction is elapsed-seconds/86400 regardless. We keep TOAs in that
convention and convert to TT/TDB seconds via the leap table.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.ops import dd_np


def parse_mjd_string(s: str):
    """Parse a decimal MJD string exactly into (int_day: float, frac: dd).

    The integer day is exact in f64; the fraction is parsed as an integer
    scaled by a power of ten using two f64 legs (front/back 15-digit
    chunks), keeping <1e-19 day (≈ 10 ps) precision.
    """
    s = s.strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    if "." in s:
        ip, fp = s.split(".", 1)
    else:
        ip, fp = s, ""
    if (not ip and not fp) or (ip and not ip.isdigit()) or \
            (fp and not fp.isdigit()) or len(ip) > 18:
        # isdigit() also rejects int()-tolerated junk like '1_5' or '+5'
        raise ValueError(f"bad MJD string {s!r}")
    day = float(int(ip)) if ip else 0.0
    # fraction digits → dd via chunked base-10 accumulation
    frac = dd_np.dd(0.0)
    fp = fp[:30]
    if fp:
        a = fp[:15]
        b = fp[15:30]
        frac = dd_np.div(dd_np.dd(float(int(a))), dd_np.dd(10.0 ** len(a)))
        if b:
            # divide by 10^len(b) then 10^15: both divisors exact in
            # f64 (10^k exact only to k=22), keeping the native C++
            # kernel bit-identical
            fb = dd_np.div(dd_np.dd(float(int(b))),
                           dd_np.dd(10.0 ** len(b)))
            fb = dd_np.div(fb, dd_np.dd(10.0 ** 15))
            frac = dd_np.add(frac, fb)
    if neg:
        return -day, dd_np.neg(frac)
    return day, frac


def parse_mjd_strings(strings, use_native: bool = True):
    """Vector parse → (int_days f64 array, frac dd pair of arrays).
    Large batches go through the native C++ kernel when available
    (bit-identical results; pint_tpu/native/mjdparse.cpp)."""
    if use_native and len(strings) >= 256:
        from pint_tpu.native import mjdparse_native

        out = mjdparse_native(strings)
        if out is not None:
            return out
    days = np.empty(len(strings))
    fhi = np.empty(len(strings))
    flo = np.empty(len(strings))
    for i, s in enumerate(strings):
        d, f = parse_mjd_string(s)
        days[i] = d
        fhi[i] = f[0]
        flo[i] = f[1]
    return days, (fhi, flo)


def mjd_to_str(day: float, frac, ndigits: int = 16) -> str:
    """Format (int_day, frac dd) back to a decimal MJD string, exact to
    ndigits of fraction (round-trip partner of parse_mjd_string)."""
    fhi = float(np.asarray(frac[0]))
    flo = float(np.asarray(frac[1]))
    day = int(day)
    # normalize frac into [0, 1)
    total = fhi + flo
    if total < 0:
        borrow = int(np.ceil(-total))
        day -= borrow
        fhi += borrow
    elif total >= 1.0:
        carry = int(np.floor(total))
        day += carry
        fhi -= carry
    # digit-by-digit extraction in dd
    f = dd_np.dd(fhi, flo)
    digits = []
    for _ in range(ndigits):
        f = dd_np.mul_f(f, 10.0)
        d = int(np.floor(f[0] + f[1]))
        d = min(max(d, 0), 9)
        digits.append(str(d))
        f = dd_np.sub_f(f, float(d))
    return f"{day}.{''.join(digits)}"


# MJD of the civil epoch 1970-01-01 (Unix day 0)
_MJD_UNIX_EPOCH = 40587


def mjd_to_calendar(days):
    """EXACT MJD -> civil (UTC) proleptic-Gregorian calendar:
    returns (year, month, day_of_month, day_of_year) int64 arrays
    for integer MJDs (ISSUE 10 satellite — the pintk day-of-year
    axis used a Julian-year 365.25 d approximation that drifted
    ~0.75 d within a year and fabricated day-366 artifacts at
    non-leap year boundaries).

    Fully VECTORIZED integer arithmetic (the civil_from_days
    algorithm: 400-year eras of exactly 146097 days, year-of-era
    recovered by correcting for the 4/100/400 leap rules, months
    counted from March so the leap day lands last) — O(N) numpy
    ops, no per-element datetime calls, exact for all
    representable MJDs. Oracle: datetime itself, in
    tests/test_obs.py::test_mjd_to_calendar_exact."""
    days = np.atleast_1d(np.asarray(days))
    z = np.floor(days).astype(np.int64) - _MJD_UNIX_EPOCH + 719468
    era = np.floor_divide(z, 146097)
    doe = z - era * 146097                              # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524
           - doe // 146096) // 365                      # [0, 399]
    y = yoe + era * 400                                 # March-based
    doy_mar = doe - (365 * yoe + yoe // 4 - yoe // 100)  # [0, 365]
    mp = (5 * doy_mar + 2) // 153                       # [0, 11]
    dom = doy_mar - (153 * mp + 2) // 5 + 1             # [1, 31]
    month = mp + np.where(mp < 10, 3, -9)               # [1, 12]
    year = y + (month <= 2)
    # day-of-year: the same algebra inverted for Jan 1 of `year`
    # (days_from_civil(year, 1, 1)), so the leap rules can never
    # disagree with the conversion above
    yj = year - 1                                       # Jan -> m<=2
    era_j = np.floor_divide(yj, 400)
    yoe_j = yj - era_j * 400
    doy_jan1 = (153 * 10 + 2) // 5                      # Jan 1, March-based
    doe_j = yoe_j * 365 + yoe_j // 4 - yoe_j // 100 + doy_jan1
    jan1_z = era_j * 146097 + doe_j - 719468
    doy = z - 719468 - jan1_z + 1
    return year, month, dom, doy


def mjd_dd_to_seconds(day, frac, epoch_day: float):
    """(day + frac − epoch_day) in SI seconds as a dd pair (86400 s/day,
    pulsar-MJD convention — caller handles scale offsets separately)."""
    ddays = dd_np.add_f(frac, np.asarray(day, np.float64) - epoch_day)
    return dd_np.mul_f(ddays, 86400.0)
