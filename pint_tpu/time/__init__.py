"""Time scales, Earth orientation, and high-precision MJD handling.

This package replaces what the reference gets from astropy.time + PyERFA
(C) — see SURVEY.md §2b: UTC/TAI/TT/TDB scale chains, the "pulsar MJD"
convention, Earth rotation (ERA/GMST), precession-nutation, and
ITRF→GCRS observatory position/velocity
(reference: src/pint/pulsar_mjd.py, src/pint/erfautils.py).

Everything here is host-side numpy (IEEE f64 + double-double pairs);
results are packed into device arrays once per dataset (the host/device
cut described in ARCHITECTURE.md).
"""

from pint_tpu.time.leapseconds import tai_minus_utc, leap_table  # noqa: F401
from pint_tpu.time.mjd import (  # noqa: F401
    parse_mjd_string,
    mjd_to_str,
    mjd_dd_to_seconds,
)
from pint_tpu.time.scales import (  # noqa: F401
    utc_mjd_to_tt_mjd,
    tt_mjd_to_tdb_mjd,
    tdb_minus_tt_seconds,
)
from pint_tpu.time.frames import (  # noqa: F401
    earth_rotation_angle,
    gmst06,
    obliquity06,
    nutation00b_truncated,
    precession_matrix,
    itrf_to_gcrs_posvel,
    icrs_to_ecliptic_matrix,
)
