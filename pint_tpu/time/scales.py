"""UTC → TAI → TT → TDB scale conversions on dd MJDs.

Replaces astropy.time scale chains + ERFA ``dtdb``
(reference: src/pint/toa.py TOAs.compute_TDBs; SURVEY.md Appendix A.3).

TDB−TT uses a truncated Fairhead–Bretagnon analytic series (36 leading
terms of the ERFA/FB1990 expansion). Truncation error vs the full ~800-term
series is a few hundred ns worst-case — adequate for bring-up and fully
self-consistent for the simulate→fit oracle; the term table is data, so
extending it later is mechanical. The additional topocentric term
−(v_⊕·r_obs)/c² (~2 µs diurnal) is applied in the TOA pipeline where the
observatory GCRS vectors are available.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.ops import dd_np
from pint_tpu.time.leapseconds import tai_minus_utc

TT_MINUS_TAI = 32.184  # seconds, exact
SECS_PER_DAY = 86400.0
MJD_J2000 = 51544.5  # TT

# Fairhead & Bretagnon 1990 leading terms: (amplitude [s],
# frequency [rad / Julian millennium], phase [rad]); t in TT millennia
# since J2000. Constant-in-t group:
_FB_T0 = np.array([
    (1.656674564e-3, 6283.075849991, 6.240054195),
    (2.2417471e-5, 5753.384884897, 4.296977442),
    (1.3839792e-5, 12566.151699983, 6.196904410),
    (4.770086e-6, 529.690965095, 0.444401603),
    (4.676740e-6, 6069.776754553, 4.021195093),
    (2.256707e-6, 213.299095438, 5.543113262),
    (1.694205e-6, -3.523118349, 5.025132748),
    (1.554905e-6, 77713.771467920, 5.198467090),
    (1.276839e-6, 7860.419392439, 5.988822341),
    (1.193379e-6, 5223.693919802, 3.649823730),
    (1.115322e-6, 3930.209696220, 1.422745069),
    (0.794185e-6, 11506.769769794, 2.322313077),
    (0.600309e-6, 1577.343542448, 2.678271909),
    (0.496817e-6, 6208.294251424, 5.696701824),
    (0.486306e-6, 5884.926846583, 0.520007179),
    (0.468597e-6, 6244.942814354, 5.866398759),
    (0.447061e-6, 26.298319800, 3.615796498),
    (0.435206e-6, -398.149003408, 4.349338347),
    (0.432392e-6, 74.781598567, 2.435898309),
    (0.375510e-6, 5507.553238667, 4.103476804),
    (0.243085e-6, -775.522611324, 3.651837925),
    (0.230685e-6, 5856.477659115, 4.773852582),
    (0.203747e-6, 12036.460734888, 4.333987818),
    (0.173435e-6, 18849.227549974, 6.153743485),
    (0.159080e-6, 10977.078804699, 1.890075226),
    (0.143935e-6, -796.298006816, 5.957517795),
    (0.137927e-6, 11790.629088659, 1.135934669),
    (0.119979e-6, 38.133035638, 4.551585768),
    (0.118971e-6, 5486.777843175, 1.914547226),
    (0.116120e-6, 1059.381930189, 0.873504123),
])
# t^1 group:
_FB_T1 = np.array([
    (102.156724e-6, 6283.075849991, 4.249032005),
    (1.706807e-6, 12566.151699983, 4.205904248),
    (0.269668e-6, 213.299095438, 3.400290479),
    (0.265919e-6, 529.690965095, 5.836047367),
    (0.210568e-6, -3.523118349, 6.262738348),
    (0.077996e-6, 5223.693919802, 4.670344204),
])


def utc_mjd_to_tt_mjd(day, frac):
    """Pulsar-MJD UTC (int day f64, frac dd) → TT as one dd MJD.

    TT = UTC + (TAI−UTC)(utc day) + 32.184 s. The pulsar-MJD convention
    makes the day fraction elapsed/86400 even on 86401-s days, so the
    offset addition is uniform (this is precisely why the convention
    exists — reference: src/pint/pulsar_mjd.py).
    """
    day = np.asarray(day, np.float64)
    off = tai_minus_utc(day) + TT_MINUS_TAI  # seconds
    mjd = dd_np.add_f(frac, day)
    return dd_np.add(mjd, dd_np.div_f(dd_np.dd(off), SECS_PER_DAY))


def tt_mjd_to_utc_mjd(day, frac):
    """TT (f64 day, f64 frac) -> pulsar-MJD UTC (day, frac), both f64
    pairs normalized to frac in [0, 1). Inverse of utc_mjd_to_tt_mjd.

    The leap table must be evaluated at the UTC day the answer lands
    on, which is itself the answer — a fixed point of the staircase
    map d -> day + floor(frac - off(d)). Two iterations reach it
    everywhere except inside an inserted leap second (23:59:60.x has
    no pulsar-MJD preimage; the iteration 2-cycles across the step):
    those instants alias to the start of the following day, matching
    the convention's elapsed/86400 aliasing, as does an exact
    post-step midnight that lands one ulp short (the bug the
    precision-fuzz leap sweep caught: the old two-pass returned a UTC
    a full second late there)."""
    day = np.asarray(day, np.float64)
    frac = np.asarray(frac, np.float64)

    def off_of(d):
        return (tai_minus_utc(d) + TT_MINUS_TAI) / SECS_PER_DAY

    d1 = day + np.floor(frac - off_of(day))
    d2 = day + np.floor(frac - off_of(d1))
    d3 = day + np.floor(frac - off_of(d2))
    # converged lanes have d3 == d2; 2-cycling lanes (inside a leap
    # second) take the later day — both are just the max
    day_utc = np.maximum(d2, d3)
    f = frac - off_of(day_utc) - (day_utc - day)
    f = np.clip(f, 0.0, np.nextafter(1.0, 0.0))
    return day_utc, f


def tdb_minus_tt_seconds(tt_mjd_f64):
    """Truncated Fairhead–Bretagnon TDB−TT [s] at TT MJD(s) (f64 is ample:
    the series slope is ~1e-7 s/s, so µs-level argument error is harmless).
    """
    t = (np.asarray(tt_mjd_f64, np.float64) - MJD_J2000) / 365250.0
    w = np.zeros_like(t)
    for A, om, ph in _FB_T0:
        w = w + A * np.sin(om * t + ph)
    for A, om, ph in _FB_T1:
        w = w + t * (A * np.sin(om * t + ph))
    return w


def tt_mjd_to_tdb_mjd(tt_mjd):
    """TT dd MJD → TDB dd MJD (geocentric term only)."""
    dtdb = tdb_minus_tt_seconds(dd_np.to_f64(tt_mjd))
    return dd_np.add(tt_mjd, dd_np.div_f(dd_np.dd(dtdb), SECS_PER_DAY))
