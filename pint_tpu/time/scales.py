"""UTC → TAI → TT → TDB scale conversions on dd MJDs.

Replaces astropy.time scale chains + ERFA ``dtdb``
(reference: src/pint/toa.py TOAs.compute_TDBs; SURVEY.md Appendix A.3).

TDB−TT uses a truncated Fairhead–Bretagnon analytic series: 60 t^0
terms, 16 t^1 terms, 6 t^2 terms and the leading t^3 term of the
FB1990 expansion (the published constants, embedded as data). Honest
truncation estimate vs the full ~790-term series: the largest omitted
t^0 amplitude is ~0.028 µs and the omitted tail RSSes to ~0.1 µs
worst-case (the full table cannot be re-derived offline; the table is
data, so extending further stays mechanical). Independent-method
cross-check: tests/test_time_truth.py integrates the defining
relativistic rate with the in-repo ephemeris and agrees to <5 µs over
12 yr — limited by the Keplerian ephemeris's missing indirect
planetary perturbations of Earth's orbit, not by this series. The
additional topocentric term −(v_⊕·r_obs)/c² (~2 µs diurnal) is
applied in the TOA pipeline where the observatory GCRS vectors are
available.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.ops import dd_np
from pint_tpu.time.leapseconds import tai_minus_utc

TT_MINUS_TAI = 32.184  # seconds, exact
SECS_PER_DAY = 86400.0
MJD_J2000 = 51544.5  # TT

# Fairhead & Bretagnon 1990 leading terms: (amplitude [s],
# frequency [rad / Julian millennium], phase [rad]); t in TT millennia
# since J2000. Constant-in-t group:
_FB_T0 = np.array([
    (1.656674564e-3, 6283.075849991, 6.240054195),
    (2.2417471e-5, 5753.384884897, 4.296977442),
    (1.3839792e-5, 12566.151699983, 6.196904410),
    (4.770086e-6, 529.690965095, 0.444401603),
    (4.676740e-6, 6069.776754553, 4.021195093),
    (2.256707e-6, 213.299095438, 5.543113262),
    (1.694205e-6, -3.523118349, 5.025132748),
    (1.554905e-6, 77713.771467920, 5.198467090),
    (1.276839e-6, 7860.419392439, 5.988822341),
    (1.193379e-6, 5223.693919802, 3.649823730),
    (1.115322e-6, 3930.209696220, 1.422745069),
    (0.794185e-6, 11506.769769794, 2.322313077),
    (0.600309e-6, 1577.343542448, 2.678271909),
    (0.496817e-6, 6208.294251424, 5.696701824),
    (0.486306e-6, 5884.926846583, 0.520007179),
    (0.468597e-6, 6244.942814354, 5.866398759),
    (0.447061e-6, 26.298319800, 3.615796498),
    (0.435206e-6, -398.149003408, 4.349338347),
    (0.432392e-6, 74.781598567, 2.435898309),
    (0.375510e-6, 5507.553238667, 4.103476804),
    (0.243085e-6, -775.522611324, 3.651837925),
    (0.230685e-6, 5856.477659115, 4.773852582),
    (0.203747e-6, 12036.460734888, 4.333987818),
    (0.173435e-6, 18849.227549974, 6.153743485),
    (0.159080e-6, 10977.078804699, 1.890075226),
    (0.143935e-6, -796.298006816, 5.957517795),
    (0.137927e-6, 11790.629088659, 1.135934669),
    (0.119979e-6, 38.133035638, 4.551585768),
    (0.118971e-6, 5486.777843175, 1.914547226),
    (0.116120e-6, 1059.381930189, 0.873504123),
    # terms 31-60 of the published t^0 table (round-5 extension;
    # amplitudes 0.028-0.102 us)
    (0.101868e-6, -5573.142801634, 5.984503847),
    (0.098358e-6, 2352.866153772, 6.145309371),
    (0.080164e-6, 206.185548437, 2.095377709),
    (0.079645e-6, 4694.002954708, 2.949233637),
    (0.075019e-6, 2942.463423292, 4.980931759),
    (0.064397e-6, 5746.271337896, 1.280308748),
    (0.063814e-6, 5760.498431898, 4.167901731),
    (0.062617e-6, 20.775395492, 2.654394814),
    (0.058844e-6, 426.598190876, 4.839650148),
    (0.054139e-6, 17260.154654690, 3.411091093),
    (0.048373e-6, 155.420399434, 2.251573730),
    (0.048042e-6, 2146.165416475, 1.495846011),
    (0.046551e-6, -0.980321068, 0.921573539),
    (0.042732e-6, 632.783739313, 5.720622217),
    (0.042560e-6, 161000.685737473, 1.270837679),
    (0.042411e-6, 6275.962302991, 2.869567043),
    (0.040759e-6, 12352.852604545, 3.981496998),
    (0.040480e-6, 15720.838784878, 2.546610123),
    (0.040184e-6, -7.113547001, 3.565975565),
    (0.036955e-6, 3154.687084896, 5.071801441),
    (0.036564e-6, 5088.628839767, 3.324679049),
    (0.036507e-6, 801.820931124, 6.248866009),
    (0.034867e-6, 522.577418094, 5.210064075),
    (0.033529e-6, 9437.762934887, 2.404714239),
    (0.033477e-6, 6062.663207553, 4.144987272),
    (0.032438e-6, 6076.890301554, 0.749317412),
    (0.032423e-6, 8827.390269875, 5.541473556),
    (0.030215e-6, 7084.896781115, 3.389610345),
    (0.029247e-6, -71430.695617928, 4.183178762),
    (0.028244e-6, -6286.598968340, 5.069663519),
])
# t^1 group (16 leading terms):
_FB_T1 = np.array([
    (102.156724e-6, 6283.075849991, 4.249032005),
    (1.706807e-6, 12566.151699983, 4.205904248),
    (0.269668e-6, 213.299095438, 3.400290479),
    (0.265919e-6, 529.690965095, 5.836047367),
    (0.210568e-6, -3.523118349, 6.262738348),
    (0.077996e-6, 5223.693919802, 4.670344204),
    (0.059641e-6, 26.298319800, 1.083044735),
    (0.054764e-6, 1577.343542448, 4.534800170),
    (0.034420e-6, -398.149003408, 5.980077351),
    (0.033595e-6, 5507.553238667, 5.980162321),
    (0.032088e-6, 18849.227549974, 5.869584648),
    (0.029198e-6, 5856.477659115, 0.313144238),
    (0.027764e-6, 155.420399434, 0.419288904),
    (0.025190e-6, 5746.271337896, 2.776244623),
    (0.024976e-6, 5760.498431898, 2.689294301),
    (0.022997e-6, -796.298006816, 1.255488919),
])
# t^2 group:
_FB_T2 = np.array([
    (4.322990e-6, 6283.075849991, 2.642893748),
    (0.406495e-6, 0.0, 4.712388980),
    (0.122605e-6, 12566.151699983, 2.438140634),
    (0.019476e-6, 213.299095438, 1.642186981),
    (0.016916e-6, 529.690965095, 4.510959344),
    (0.013374e-6, -3.523118349, 1.502210314),
])
# t^3 leading term:
_FB_T3 = np.array([
    (0.143388e-6, 6283.075849991, 1.131453581),
])


def utc_mjd_to_tt_mjd(day, frac):
    """Pulsar-MJD UTC (int day f64, frac dd) → TT as one dd MJD.

    TT = UTC + (TAI−UTC)(utc day) + 32.184 s. The pulsar-MJD convention
    makes the day fraction elapsed/86400 even on 86401-s days, so the
    offset addition is uniform (this is precisely why the convention
    exists — reference: src/pint/pulsar_mjd.py).
    """
    day = np.asarray(day, np.float64)
    off = tai_minus_utc(day) + TT_MINUS_TAI  # seconds
    mjd = dd_np.add_f(frac, day)
    return dd_np.add(mjd, dd_np.div_f(dd_np.dd(off), SECS_PER_DAY))


def tt_mjd_to_utc_mjd(day, frac):
    """TT (f64 day, f64 frac) -> pulsar-MJD UTC (day, frac), both f64
    pairs normalized to frac in [0, 1). Inverse of utc_mjd_to_tt_mjd.

    The leap table must be evaluated at the UTC day the answer lands
    on, which is itself the answer — a fixed point of the staircase
    map d -> day + floor(frac - off(d)). Two iterations reach it
    everywhere except inside an inserted leap second (23:59:60.x has
    no pulsar-MJD preimage; the iteration 2-cycles across the step):
    those instants alias to the start of the following day, matching
    the convention's elapsed/86400 aliasing, as does an exact
    post-step midnight that lands one ulp short (the bug the
    precision-fuzz leap sweep caught: the old two-pass returned a UTC
    a full second late there)."""
    day = np.asarray(day, np.float64)
    frac = np.asarray(frac, np.float64)

    def off_of(d):
        return (tai_minus_utc(d) + TT_MINUS_TAI) / SECS_PER_DAY

    d1 = day + np.floor(frac - off_of(day))
    d2 = day + np.floor(frac - off_of(d1))
    d3 = day + np.floor(frac - off_of(d2))
    # converged lanes have d3 == d2; 2-cycling lanes (inside a leap
    # second) take the later day — both are just the max
    day_utc = np.maximum(d2, d3)
    f = frac - off_of(day_utc) - (day_utc - day)
    f = np.clip(f, 0.0, np.nextafter(1.0, 0.0))
    return day_utc, f


def tdb_minus_tt_seconds(tt_mjd_f64):
    """Truncated Fairhead–Bretagnon TDB−TT [s] at TT MJD(s) (f64 is ample:
    the series slope is ~1e-7 s/s, so µs-level argument error is harmless).
    w = Σ_k t^k Σ_i A_ki sin(ω_ki t + φ_ki), t in TT millennia.
    """
    t = (np.asarray(tt_mjd_f64, np.float64) - MJD_J2000) / 365250.0
    w = np.zeros_like(t)
    tk = np.ones_like(t)
    for table in (_FB_T0, _FB_T1, _FB_T2, _FB_T3):
        g = np.zeros_like(t)
        for A, om, ph in table:
            g = g + A * np.sin(om * t + ph)
        w = w + tk * g
        tk = tk * t
    return w


def tt_mjd_to_tdb_mjd(tt_mjd):
    """TT dd MJD → TDB dd MJD (geocentric term only)."""
    dtdb = tdb_minus_tt_seconds(dd_np.to_f64(tt_mjd))
    return dd_np.add(tt_mjd, dd_np.div_f(dd_np.dd(dtdb), SECS_PER_DAY))
