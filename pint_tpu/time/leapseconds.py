"""Leap-second (TAI−UTC) table, embedded — this build environment has no
network and no astropy/erfa to consult (reference equivalent: ERFA ``dat``
via astropy.time; SURVEY.md Appendix A.2).

TAI−UTC = 10 s at 1972-01-01, +1 s after each listed UTC day; 37 s from
2017-01-01 onward (no leap second scheduled through 2026).
"""

from __future__ import annotations

import numpy as np

# MJD of 00:00 UTC on the day AFTER each leap second (i.e., the instant the
# new offset takes effect), and the TAI-UTC value from that instant.
_LEAP_MJDS = [
    (41317.0, 10.0),  # 1972-01-01 baseline
    (41499.0, 11.0),  # 1972-07-01
    (41683.0, 12.0),  # 1973-01-01
    (42048.0, 13.0),  # 1974-01-01
    (42413.0, 14.0),  # 1975-01-01
    (42778.0, 15.0),  # 1976-01-01
    (43144.0, 16.0),  # 1977-01-01
    (43509.0, 17.0),  # 1978-01-01
    (43874.0, 18.0),  # 1979-01-01
    (44239.0, 19.0),  # 1980-01-01
    (44786.0, 20.0),  # 1981-07-01
    (45151.0, 21.0),  # 1982-07-01
    (45516.0, 22.0),  # 1983-07-01
    (46247.0, 23.0),  # 1985-07-01
    (47161.0, 24.0),  # 1988-01-01
    (47892.0, 25.0),  # 1990-01-01
    (48257.0, 26.0),  # 1991-01-01
    (48804.0, 27.0),  # 1992-07-01
    (49169.0, 28.0),  # 1993-07-01
    (49534.0, 29.0),  # 1994-07-01
    (50083.0, 30.0),  # 1996-01-01
    (50630.0, 31.0),  # 1997-07-01
    (51179.0, 32.0),  # 1999-01-01
    (53736.0, 33.0),  # 2006-01-01
    (54832.0, 34.0),  # 2009-01-01
    (56109.0, 35.0),  # 2012-07-01
    (57204.0, 36.0),  # 2015-07-01
    (57754.0, 37.0),  # 2017-01-01
]

_MJDS = np.array([m for m, _ in _LEAP_MJDS])
_OFFS = np.array([o for _, o in _LEAP_MJDS])


def leap_table():
    """(effective_mjd_utc, tai_minus_utc_seconds) arrays."""
    return _MJDS.copy(), _OFFS.copy()


def tai_minus_utc(mjd_utc):
    """TAI−UTC in seconds for UTC MJD(s); 10 s before 1972 is extended
    backwards (pre-1972 rubber-second UTC is out of scope, as in the
    reference's pulsar use)."""
    mjd_utc = np.asarray(mjd_utc, dtype=np.float64)
    idx = np.searchsorted(_MJDS, mjd_utc, side="right") - 1
    idx = np.clip(idx, 0, len(_OFFS) - 1)
    return _OFFS[idx]


def is_leap_second_day(mjd_int):
    """True for UTC days that contain a leap second (86401 s) — the day
    *before* each entry above (after the 1972 baseline)."""
    mjd_int = np.asarray(mjd_int)
    return np.isin(mjd_int + 1, _MJDS[1:].astype(np.int64))
