"""Earth-orientation-parameter (EOP) table loading.

The reference gets dUT1/polar motion from IERS tables that astropy
downloads at runtime (reference: src/pint/erfautils.py consuming
astropy.utils.iers). This build is zero-egress, so EOP arrives the same
way clock corrections do: from a local mirror directory
($PINT_TPU_CLOCK_DIR, see observatory/global_clock_corrections) that
the operator syncs out-of-band. Two formats:

- IERS ``finals2000A.all`` / ``finals.all`` fixed-width records (the
  file astropy's IERS-A machinery consumes): MJD at columns 8-15,
  polar motion x/y [arcsec] at 19-27 / 38-46, UT1-UTC [s] at 59-68.
- A plain whitespace table ``# MJD xp_arcsec yp_arcsec dut1_s`` for
  hand-maintained mirrors.

``install_eop`` feeds the parsed table into time.frames.set_eop, after
which itrf_to_gcrs_posvel applies UT1 = UTC + dUT1 and polar motion.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

__all__ = ["parse_finals2000a", "parse_plain_eop", "load_eop_file",
           "find_eop_file", "install_eop"]

_FINALS_NAMES = ("finals2000A.all", "finals.all", "finals2000A.data",
                 "finals.data", "eop.dat")


def parse_finals2000a(text: str):
    """Parse IERS finals2000A fixed-width records →
    (mjd, xp_arcsec, yp_arcsec, dut1_s) arrays. Records without a
    UT1-UTC value (future epochs beyond prediction) are dropped."""
    mjd, xp, yp, dut1 = [], [], [], []
    for line in text.splitlines():
        if len(line) < 68:
            continue
        try:
            m = float(line[7:15])
            x = float(line[18:27])
            y = float(line[37:46])
            d = float(line[58:68])
        except ValueError:
            continue
        # sanity windows: |PM| < 1 arcsec, |dUT1| < 0.9 s by definition
        if not (0 < m < 1e5 and abs(x) < 2 and abs(y) < 2
                and abs(d) < 1.0):
            continue
        mjd.append(m)
        xp.append(x)
        yp.append(y)
        dut1.append(d)
    return (np.asarray(mjd), np.asarray(xp), np.asarray(yp),
            np.asarray(dut1))


def parse_plain_eop(text: str):
    """Parse the plain-table format: ``MJD xp_arcsec yp_arcsec dut1_s``
    per line, ``#`` comments."""
    mjd, xp, yp, dut1 = [], [], [], []
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        if len(parts) < 4:
            continue
        try:
            vals = [float(v) for v in parts[:4]]
        except ValueError:
            continue
        mjd.append(vals[0])
        xp.append(vals[1])
        yp.append(vals[2])
        dut1.append(vals[3])
    return (np.asarray(mjd), np.asarray(xp), np.asarray(yp),
            np.asarray(dut1))


def load_eop_file(path: str):
    """(mjd, xp_arcsec, yp_arcsec, dut1_s) from either supported
    format (finals fixed-width tried first — its lines are full-width
    so plain parsing of one would not yield 4 clean floats)."""
    with open(path) as f:
        text = f.read()
    out = parse_finals2000a(text)
    if len(out[0]) == 0:
        out = parse_plain_eop(text)
    if len(out[0]) == 0:
        raise ValueError(f"no EOP records parsed from {path}")
    return out


def find_eop_file(mirror_dir: Optional[str] = None) -> Optional[str]:
    """Locate an EOP table in the clock-mirror directory (searched at
    the top level and under ``T2runtime/earth/``, where tempo2-style
    mirrors keep orientation data)."""
    if mirror_dir is None:
        from pint_tpu.observatory.global_clock_corrections import \
            clock_mirror

        mirror_dir = clock_mirror()
    if not mirror_dir:
        return None
    for sub in ("", "earth", os.path.join("T2runtime", "earth")):
        d = os.path.join(mirror_dir, sub) if sub else mirror_dir
        if not os.path.isdir(d):
            continue
        for name in _FINALS_NAMES:
            p = os.path.join(d, name)
            if os.path.isfile(p):
                return p
    return None


def install_eop(path: Optional[str] = None) -> Tuple[int, str]:
    """Load an EOP table (explicit path, else the mirror search) and
    install it via frames.set_eop. Returns (n_records, path)."""
    from pint_tpu.time import frames

    if path is None:
        path = find_eop_file()
        if path is None:
            raise FileNotFoundError(
                "no EOP table found: set $PINT_TPU_CLOCK_DIR at a "
                "mirror containing finals2000A.all (or pass a path)")
    mjd, xp, yp, dut1 = load_eop_file(path)
    frames.set_eop(mjd, dut1, xp_arcsec=xp, yp_arcsec=yp)
    return len(mjd), path
