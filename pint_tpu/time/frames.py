"""Earth rotation and celestial frames — the ERFA replacement layer.

Replaces the PyERFA calls the reference makes through
src/pint/erfautils.py (gcrs_posvel_from_itrf: pnm06a/era00/sp00/pom00)
with an equinox-based chain:

    GCRS = P(t) · N(t) · R3(−GAST) · W · ITRF

- P: IAU-2006-compatible precession (Capitaine polynomials for ζ, z, θ);
- N: IAU2000B nutation, 31 leading lunisolar terms with t-dependent
  and out-of-phase coefficients + the fixed planetary bias (~1-2 mas
  worst-case vs the full 77-term table → ≲6 cm on the
  geocenter-to-site vector ≈ 0.2 ns of Roemer — error budget in
  ARCHITECTURE.md);
- GAST = GMST(ERA) + Δψ cos ε (equation of the equinoxes, leading term);
- W: polar motion, identity by default (no IERS tables offline; ~0.3″
  ≈ 9 m ≈ 30 ns — irrelevant for self-consistent fixtures, hook provided
  for real-data use);
- UT1 ≈ UTC (|ΔUT1| < 0.9 s ≈ ≤40 cm of site position; same hook).

All host-side numpy f64; angles in radians, times as TT/UT1 MJD f64
(sub-second argument errors are harmless here — rates are ≤ 7.3e-5 rad/s
and position enters delays divided by c).
"""

from __future__ import annotations

import numpy as np

ASEC2RAD = np.pi / (180.0 * 3600.0)
TURNAS = 1296000.0  # arcsec per turn
MJD_J2000 = 51544.5
OMEGA_EARTH = 2 * np.pi * 1.00273781191135448 / 86400.0  # rad/s (ERA rate)


def _jc(tt_mjd):
    """Julian centuries TT since J2000."""
    return (np.asarray(tt_mjd, np.float64) - MJD_J2000) / 36525.0


def earth_rotation_angle(ut1_mjd):
    """ERA(UT1), IAU 2000 (reference ERFA era00). Radians in [0, 2π)."""
    t = np.asarray(ut1_mjd, np.float64) - MJD_J2000
    # split t to keep the fast term accurate: ERA/2π = 0.779057… + t
    # + 0.00273781…·t (mod 1); the integer part of t drops out.
    era = 2 * np.pi * (
        (t % 1.0 + 0.7790572732640 + 0.00273781191135448 * t) % 1.0)
    return era % (2 * np.pi)


def gmst06(ut1_mjd, tt_mjd):
    """GMST consistent with IAU 2006 precession (reference ERFA gmst06):
    GMST = ERA + polynomial(t_TT)."""
    t = _jc(tt_mjd)
    poly = (0.014506 + 4612.156534 * t + 1.3915817 * t * t
            - 0.00000044 * t**3 - 0.000029956 * t**4) * ASEC2RAD
    return (earth_rotation_angle(ut1_mjd) + poly) % (2 * np.pi)


def obliquity06(tt_mjd):
    """Mean obliquity of the ecliptic, IAU 2006 (arcsec poly → rad)."""
    t = _jc(tt_mjd)
    eps = (84381.406 - 46.836769 * t - 0.0001831 * t * t
           + 0.00200340 * t**3)
    return eps * ASEC2RAD


# IAU 2000B lunisolar nutation, leading 31 terms of the published
# 77-term table (McCarthy & Luzum 2003): per row the Delaunay-argument
# multipliers (l, l', F, D, Om) and the coefficients
#   Δψ: ps·sin(arg) + pst·t·sin(arg) + pc·cos(arg)
#   Δε: ec·cos(arg) + ect·t·cos(arg) + es·sin(arg)
# in arcsec (pst/ect per Julian century). Terms 32-77 have amplitudes
# <0.8 mas each (omitted tail RSS ~1-2 mas ≈ <0.1 ns of Roemer on the
# site vector — error budget in ARCHITECTURE.md); the table is data,
# further extension stays mechanical.
_NUT_TERMS = np.array([
    # l  l'  F   D  Om     ps         pst        pc         ec         ect        es
    (0, 0, 0, 0, 1, -17.2064161, -0.0174666, 0.0033386, 9.2052331, 0.0009086, 0.0015377),
    (0, 0, 2, -2, 2, -1.3170906, -0.0001675, -0.0013696, 0.5730336, -0.0003015, -0.0004587),
    (0, 0, 2, 0, 2, -0.2276413, -0.0000234, 0.0002796, 0.0978459, -0.0000485, 0.0001374),
    (0, 0, 0, 0, 2, 0.2074554, 0.0000207, -0.0000698, -0.0897492, 0.0000470, -0.0000291),
    (0, 1, 0, 0, 0, 0.1475877, -0.0003633, 0.0011817, 0.0073871, -0.0000184, -0.0001924),
    (0, 1, 2, -2, 2, -0.0516821, 0.0001226, -0.0000524, 0.0224386, -0.0000677, -0.0000174),
    (1, 0, 0, 0, 0, 0.0711159, 0.0000073, -0.0000872, -0.0006750, 0.0, 0.0000358),
    (0, 0, 2, 0, 1, -0.0387298, -0.0000367, 0.0000380, 0.0200728, 0.0000018, 0.0000318),
    (1, 0, 2, 0, 2, -0.0301461, -0.0000036, 0.0000816, 0.0129025, -0.0000063, 0.0000367),
    (0, -1, 2, -2, 2, 0.0215829, -0.0000494, 0.0000111, -0.0095929, 0.0000299, 0.0000132),
    (0, 0, 2, -2, 1, 0.0128227, 0.0000137, 0.0000181, -0.0068982, -0.0000009, 0.0000039),
    (-1, 0, 2, 0, 2, 0.0123457, 0.0000011, 0.0000019, -0.0053311, 0.0000032, -0.0000004),
    (-1, 0, 0, 2, 0, 0.0156994, 0.0000010, -0.0000168, -0.0000127, 0.0, 0.0000082),
    (1, 0, 0, 0, 1, 0.0063110, 0.0000063, 0.0000027, -0.0033228, 0.0, -0.0000009),
    (-1, 0, 0, 0, 1, -0.0057976, -0.0000063, -0.0000189, 0.0031429, 0.0, -0.0000075),
    (-1, 0, 2, 2, 2, -0.0059641, -0.0000011, 0.0000149, 0.0025543, -0.0000011, 0.0000066),
    (1, 0, 2, 0, 1, -0.0051613, -0.0000042, 0.0000129, 0.0026366, 0.0, 0.0000078),
    (-2, 0, 2, 0, 1, 0.0045893, 0.0000050, 0.0000031, -0.0024236, -0.0000010, 0.0000020),
    (0, 0, 0, 2, 0, 0.0063384, 0.0000011, -0.0000150, -0.0001220, 0.0, 0.0000029),
    (0, 0, 2, 2, 2, -0.0038571, -0.0000001, 0.0000158, 0.0016452, -0.0000011, 0.0000068),
    (0, -2, 2, -2, 2, 0.0032481, 0.0, 0.0, -0.0013870, 0.0, 0.0),
    (-2, 0, 0, 2, 0, -0.0047722, 0.0, -0.0000018, 0.0000477, 0.0, -0.0000025),
    (2, 0, 2, 0, 2, -0.0031046, -0.0000001, 0.0000131, 0.0013238, -0.0000011, 0.0000059),
    (1, 0, 2, -2, 2, 0.0028593, 0.0, -0.0000001, -0.0012338, 0.0000010, -0.0000003),
    (-1, 0, 2, 0, 1, 0.0020441, 0.0000021, 0.0000010, -0.0010758, 0.0, -0.0000003),
    (2, 0, 0, 0, 0, 0.0029243, 0.0, -0.0000074, -0.0000609, 0.0, 0.0000013),
    (0, 0, 2, 0, 0, 0.0025887, 0.0, -0.0000066, -0.0000550, 0.0, 0.0000011),
    (0, 1, 0, 0, 1, -0.0014053, -0.0000025, 0.0000079, 0.0008551, -0.0000002, -0.0000045),
    (-1, 0, 0, 2, 1, 0.0015164, 0.0000010, 0.0000011, -0.0008001, 0.0, -0.0000001),
    (0, 2, 2, -2, 2, -0.0015794, 0.0000072, -0.0000016, 0.0006850, -0.0000042, -0.0000005),
    (0, 0, -2, 2, 0, 0.0021783, 0.0, 0.0000013, -0.0000167, 0.0, 0.0000013),
])

# IAU2000B fixed planetary-nutation bias (arcsec): the model's account
# of the planetary terms it omits relative to IAU2000A.
_NUT_PLANETARY_PSI = -0.000135
_NUT_PLANETARY_EPS = 0.000388


def _fundamental_args(t):
    """Delaunay arguments (rad); t in Julian centuries TT (IERS 2003)."""
    l = (134.96340251 + 477198.8675605 * t) * np.pi / 180.0   # noqa: E741
    lp = (357.52910918 + 35999.0502911 * t) * np.pi / 180.0
    F = (93.27209062 + 483202.0174577 * t) * np.pi / 180.0
    D = (297.85019547 + 445267.1114469 * t) * np.pi / 180.0
    Om = (125.04455501 - 1934.1362891 * t) * np.pi / 180.0
    return l, lp, F, D, Om


def nutation00b_truncated(tt_mjd):
    """(Δψ, Δε) in radians: 31-term IAU2000B lunisolar series with
    the t-dependent and out-of-phase coefficients, plus the model's
    fixed planetary bias. Truncation vs the full 77-term table is
    ~1-2 mas (see _NUT_TERMS comment); vs IAU2000A the 2000B model
    itself is ~1 mas 1995-2050."""
    t = _jc(tt_mjd)
    l, lp, F, D, Om = _fundamental_args(t)
    dpsi = np.full_like(t, _NUT_PLANETARY_PSI)
    deps = np.full_like(t, _NUT_PLANETARY_EPS)
    for cl, clp, cF, cD, cOm, ps, pst, pc, ec, ect, es in _NUT_TERMS:
        arg = cl * l + clp * lp + cF * F + cD * D + cOm * Om
        s, c = np.sin(arg), np.cos(arg)
        dpsi = dpsi + (ps + pst * t) * s + pc * c
        deps = deps + (ec + ect * t) * c + es * s
    return dpsi * ASEC2RAD, deps * ASEC2RAD


def _R1(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([o, z, z], -1),
        np.stack([z, c, s], -1),
        np.stack([z, -s, c], -1),
    ], -2)


def _R2(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([c, z, -s], -1),
        np.stack([z, o, z], -1),
        np.stack([s, z, c], -1),
    ], -2)


def _R3(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([c, s, z], -1),
        np.stack([-s, c, z], -1),
        np.stack([z, z, o], -1),
    ], -2)


def precession_matrix(tt_mjd):
    """Mean-of-J2000 ← mean-of-date rotation, Capitaine/IAU-2006-compatible
    equatorial precession angles ζ, z, θ:
        v_J2000 = R3(ζ) R2(−θ) R3(z) · v_date  (transpose of the classic
        date←J2000 matrix R3(−z) R2(θ) R3(−ζ)).
    """
    t = _jc(tt_mjd)
    zeta = (2.650545 + 2306.083227 * t + 0.2988499 * t**2
            + 0.01801828 * t**3) * ASEC2RAD
    z = (-2.650545 + 2306.077181 * t + 1.0927348 * t**2
         + 0.01826837 * t**3) * ASEC2RAD
    theta = (2004.191903 * t - 0.4294934 * t**2
             - 0.04182264 * t**3) * ASEC2RAD
    # date ← J2000 is R3(-z) R2(theta) R3(-zeta); we return its transpose
    m = _R3(-z) @ _R2(theta) @ _R3(-zeta)
    return np.swapaxes(m, -1, -2)


def nutation_matrix(tt_mjd):
    """Mean-of-date ← true-of-date: N^T = [R1(−ε−Δε) R3(−Δψ) R1(ε)]^T …
    returned as true→mean transpose so GCRS chain composes as P·N·R3(−GAST).
    """
    eps = obliquity06(tt_mjd)
    dpsi, deps = nutation00b_truncated(tt_mjd)
    n = _R1(-(eps + deps)) @ _R3(-dpsi) @ _R1(eps)  # true ← mean
    return np.swapaxes(n, -1, -2)  # mean ← true


def gast06(ut1_mjd, tt_mjd):
    eps = obliquity06(tt_mjd)
    dpsi, _ = nutation00b_truncated(tt_mjd)
    return (gmst06(ut1_mjd, tt_mjd) + dpsi * np.cos(eps)) % (2 * np.pi)


# ------------------------------------------------ EOP (IERS) hooks
# The reference gets dUT1/polar motion from downloaded IERS tables via
# astropy; offline they default to zero. set_eop installs a table (the
# same pluggable pattern as clock files): UT1 = UTC + interp(dut1), and
# polar motion rotates the ITRF vector before the Earth-rotation chain.

_EOP = None  # (mjd, dut1_s, xp_rad, yp_rad) arrays or None


def set_eop(mjd, dut1_s, xp_arcsec=None, yp_arcsec=None):
    """Install an Earth-orientation table (reference analog: the IERS-A
    table astropy downloads). Linear interpolation; outside the table
    range the edge values hold."""
    mjd = np.asarray(mjd, np.float64)
    global _EOP
    _EOP = (
        mjd,
        np.asarray(dut1_s, np.float64),
        np.asarray(xp_arcsec, np.float64) * ASEC2RAD
        if xp_arcsec is not None else np.zeros_like(mjd),
        np.asarray(yp_arcsec, np.float64) * ASEC2RAD
        if yp_arcsec is not None else np.zeros_like(mjd),
    )


def clear_eop():
    global _EOP
    _EOP = None


def _eop_at(utc_mjd):
    """(dut1_s, xp_rad, yp_rad) at the given UTC epochs."""
    if _EOP is None:
        z = np.zeros_like(np.asarray(utc_mjd, np.float64))
        return z, z, z
    mjd, dut1, xp, yp = _EOP
    u = np.asarray(utc_mjd, np.float64)
    return (np.interp(u, mjd, dut1), np.interp(u, mjd, xp),
            np.interp(u, mjd, yp))


def itrf_to_gcrs_posvel(itrf_xyz_m, utc_mjd, tt_mjd):
    """Observatory ITRF (x,y,z) [m] → GCRS position [m] and velocity [m/s]
    at the given epochs (reference: src/pint/erfautils.py
    gcrs_posvel_from_itrf). UT1 = UTC + dUT1 and polar motion from the
    installed EOP table (zero without one — ≤40 cm / ≤1.3 ns Roemer).

    itrf_xyz_m: (3,) site vector. utc/tt_mjd: (N,) epochs.
    Returns pos (N,3), vel (N,3).
    """
    itrf = np.asarray(itrf_xyz_m, np.float64)
    utc_mjd = np.atleast_1d(np.asarray(utc_mjd, np.float64))
    tt_mjd = np.atleast_1d(np.asarray(tt_mjd, np.float64))
    dut1, xp, yp = _eop_at(utc_mjd)
    ut1_mjd = utc_mjd + dut1 / 86400.0
    # compute the nutation series once — shared by GAST and the N matrix
    eps = obliquity06(tt_mjd)
    dpsi, deps = nutation00b_truncated(tt_mjd)
    gast = (gmst06(ut1_mjd, tt_mjd) + dpsi * np.cos(eps)) % (2 * np.pi)
    # true-of-date equatorial coords of the site
    cg, sg = np.cos(gast), np.sin(gast)
    x, y, z = itrf
    if _EOP is not None:
        # small-angle polar motion ITRS→TIRS, W ≈ R2(xp) R1(yp)
        # dropping the tiny s' term: r_TIRS = (x − xp z, y + yp z,
        # z + xp x − yp y)
        x, y, z = (x - xp * z,
                   y + yp * z,
                   z + xp * itrf[0] - yp * itrf[1])
    tod_pos = np.stack([cg * x - sg * y, sg * x + cg * y,
                        np.broadcast_to(z, cg.shape)], -1)
    # velocity: d/dt R3(−GAST) — Earth rotation dominates (precession
    # rates are ~1e-12 rad/s, negligible vs 7.3e-5)
    tod_vel = OMEGA_EARTH * np.stack(
        [-sg * x - cg * y, cg * x - sg * y, np.zeros_like(cg)], -1)
    n_true_from_mean = _R1(-(eps + deps)) @ _R3(-dpsi) @ _R1(eps)
    pn = precession_matrix(tt_mjd) @ np.swapaxes(n_true_from_mean, -1, -2)
    pos = np.einsum("...ij,...j->...i", pn, tod_pos)
    vel = np.einsum("...ij,...j->...i", pn, tod_vel)
    return pos, vel


def icrs_to_ecliptic_matrix(obliquity_arcsec: float = 84381.406):
    """Rotation ecliptic ← ICRS/equatorial (IERS2010 obliquity default;
    reference: src/pint/pulsar_ecliptic.py PulsarEcliptic + ecliptic.dat).
    """
    return _R1(np.float64(obliquity_arcsec * ASEC2RAD))
