"""Headless model/TOA facade backing the interactive fitter GUI
(reference: src/pint/pintk/pulsar.py Pulsar). Every piece of GUI
behavior — fit, selection, per-TOA delete, jumping, pulse-number
tracking, undo, random-model draws — lives here so it is fully
scriptable and testable without a display; the Tk widgets in
``pint_tpu.pintk.plk`` are a thin view over this class.
"""

from __future__ import annotations

import copy
import io
import warnings
from typing import List, Optional

import numpy as np

__all__ = ["Pulsar"]

# flag used to mark GUI-created jumps on TOAs (reference pintk uses
# -gui_jump flags + JUMP maskParameters the same way)
GUI_JUMP_FLAG = "gui_jump"


class Pulsar:
    """One loaded pulsar: model + TOAs + fit state.

    Parameters
    ----------
    parfile, timfile:
        paths (or file-like) understood by get_model / get_TOAs.
    fitter:
        'auto', 'wls', 'gls', 'downhill', 'downhill_gls'.
    """

    def __init__(self, parfile, timfile, fitter: str = "auto",
                 ephem: Optional[str] = None):
        from pint_tpu.models import get_model
        from pint_tpu.toa import get_TOAs

        self.parfile = parfile
        self.timfile = timfile
        self.fitter_name = fitter
        self.model = get_model(parfile)
        self.all_toas = get_TOAs(
            timfile, model=self.model,
            ephem=ephem or self.model.EPHEM.value,
            planets=bool(self.model.PLANET_SHAPIRO.value))
        self.prefit_model = copy.deepcopy(self.model)
        self.selected = np.zeros(self.all_toas.ntoas, dtype=bool)
        self.fitted = False
        self.fit_results = None
        self.track_mode = None  # None -> nearest; or "use_pulse_numbers"
        self._undo_stack: List[dict] = []
        self._fitter_obj = None

    # ------------------------------------------------------ residuals

    @property
    def name(self) -> str:
        return self.model.name or (self.model.PSR.value or "?")

    def _residuals(self, model) -> "np.ndarray":
        from pint_tpu.residuals import Residuals

        return Residuals(self.all_toas, model,
                         track_mode=self.track_mode or "nearest")

    @property
    def prefit_resids(self):
        return self._residuals(self.prefit_model)

    @property
    def postfit_resids(self):
        if not self.fitted:
            raise ValueError("no fit performed yet")
        return self._residuals(self.model)

    # ------------------------------------------------------ selection

    def select(self, mask):
        """Replace the selection with a boolean mask or index list."""
        mask = np.asarray(mask)
        if mask.dtype != bool:
            m = np.zeros(self.all_toas.ntoas, dtype=bool)
            m[mask] = True
            mask = m
        if mask.shape != (self.all_toas.ntoas,):
            raise ValueError("selection mask has wrong length")
        self.selected = mask

    def select_mjd_range(self, mjd1: float, mjd2: float):
        mjds = np.asarray(self.all_toas.get_mjds())
        self.select((mjds >= mjd1) & (mjds <= mjd2))

    def clear_selection(self):
        self.selected = np.zeros(self.all_toas.ntoas, dtype=bool)

    # ------------------------------------------------------- snapshot

    def _push_undo(self):
        self._undo_stack.append({
            "model": copy.deepcopy(self.model),
            "prefit_model": copy.deepcopy(self.prefit_model),
            "toas": self.all_toas.select(
                np.ones(self.all_toas.ntoas, dtype=bool)),
            "selected": self.selected.copy(),
            "fitted": self.fitted,
        })

    def undo(self) -> bool:
        """Revert the last mutating operation; False if nothing to
        undo."""
        if not self._undo_stack:
            return False
        st = self._undo_stack.pop()
        self.model = st["model"]
        self.prefit_model = st["prefit_model"]
        self.all_toas = st["toas"]
        self.selected = st["selected"]
        self.fitted = st["fitted"]
        self._fitter_obj = None
        return True

    # ------------------------------------------------------ mutations

    def delete_TOAs(self, mask=None):
        """Drop the masked (default: selected) TOAs."""
        mask = self.selected if mask is None else np.asarray(mask)
        if not mask.any():
            return 0
        self._push_undo()
        self.all_toas = self.all_toas.select(~mask)
        self.selected = np.zeros(self.all_toas.ntoas, dtype=bool)
        self._fitter_obj = None
        return int(mask.sum())

    def _jump_component(self):
        import pint_tpu.models.jump  # register PhaseJump # noqa: F401

        return self.model.get_or_create_component("PhaseJump")

    def jump_selection(self, mask=None) -> Optional[str]:
        """JUMP the masked (default selected) TOAs: tag them with a
        -gui_jump flag and add a matching free JUMP maskParameter
        (reference: pintk Pulsar.add_jump)."""
        mask = self.selected if mask is None else np.asarray(mask)
        if not mask.any():
            return None
        self._push_undo()
        comp = self._jump_component()
        existing = [int(self.all_toas.flags[i].get(GUI_JUMP_FLAG, 0))
                    for i in range(self.all_toas.ntoas)]
        jump_id = max(existing, default=0) + 1
        for i in np.flatnonzero(mask):
            self.all_toas.flags[i][GUI_JUMP_FLAG] = str(jump_id)
        self.all_toas._touch()
        p = comp.add_jump(key=f"-{GUI_JUMP_FLAG}",
                          key_value=(str(jump_id),), value=0.0,
                          frozen=False)
        comp.setup()
        self.model.invalidate_cache()
        self._fitter_obj = None
        return p.name

    def unjump_selection(self, mask=None) -> int:
        """Remove GUI jumps covering the masked TOAs."""
        mask = self.selected if mask is None else np.asarray(mask)
        ids = {self.all_toas.flags[i].get(GUI_JUMP_FLAG)
               for i in np.flatnonzero(mask)}
        ids.discard(None)
        if not ids:
            return 0
        self._push_undo()
        comp = self.model.components.get("PhaseJump")
        removed = 0
        for i in range(self.all_toas.ntoas):
            if self.all_toas.flags[i].get(GUI_JUMP_FLAG) in ids:
                del self.all_toas.flags[i][GUI_JUMP_FLAG]
        self.all_toas._touch()
        if comp is not None:
            for nm in list(comp.params):
                p = comp.params[nm]
                if nm.startswith("JUMP") and \
                        getattr(p, "key", None) == f"-{GUI_JUMP_FLAG}" \
                        and p.key_value and p.key_value[0] in ids:
                    comp.remove_param(nm)
                    removed += 1
            comp.setup()
        self.model.invalidate_cache()
        self._fitter_obj = None
        return removed

    # -------------------------------------------------- pulse numbers

    def compute_pulse_numbers(self):
        """Freeze the current model's phase assignment into -pn flags
        and track them in subsequent fits."""
        self.all_toas.compute_pulse_numbers(self.model)
        self.track_mode = "use_pulse_numbers"

    def reset_pulse_numbers(self):
        for f in self.all_toas.flags:
            f.pop("pn", None)
        self.all_toas._touch()
        self.track_mode = None

    # ------------------------------------------------- fit-param box

    def fittable_params(self) -> list:
        """Parameter names the fit checkbox column offers (reference:
        pintk's fitbox): every value-carrying numeric parameter kind
        that the fitters can take a derivative against."""
        from pint_tpu.models.parameter import (AngleParameter,
                                               MJDParameter,
                                               floatParameter)

        out = []
        for nm in self.model.params:
            p = self.model.get_param(nm)
            if getattr(p, "value", None) is None:
                continue
            if isinstance(p, (floatParameter, MJDParameter,
                              AngleParameter)):
                out.append(nm)
        return out

    def set_fit_params(self, names) -> None:
        """Freeze/unfreeze so that exactly ``names`` are free
        (reference: the pintk fitbox apply path). Names that are not
        fittable raise (a silently-ignored name would freeze
        everything and fail far from the cause); the structure change
        drops compiled fits and the cached fitter."""
        names = set(names)
        fittable = self.fittable_params()
        unknown = names - set(fittable)
        if unknown:
            raise KeyError(
                f"not fittable parameter(s): {sorted(unknown)}")
        for nm in fittable:
            p = self.model.get_param(nm)
            p.frozen = nm not in names
        self.model.invalidate_cache()
        self._fitter_obj = None  # stale structure (like delete/jump)

    # ----------------------------------------------------- TOA info

    def toa_info(self, index: int) -> dict:
        """Everything the plk click-info popup shows for one TOA
        (reference: plk's per-point info): MJD, freq, error, obs,
        flags, pre/post-fit residual, and its serial index. Reuses
        the Residuals most recently computed by plot_data (every GUI
        redraw refreshes it), so a click-info popup doesn't pay an
        O(N) model evaluation for one scalar."""
        t = self.all_toas
        i = int(index)
        res = getattr(self, "_last_resids", None)
        if res is None or len(res.time_resids) != t.ntoas:
            res = (self.postfit_resids if self.fitted
                   else self.prefit_resids)
        return {
            "index": i,
            "mjd": float(np.asarray(t.get_mjds())[i]),
            "freq_mhz": float(np.asarray(t.get_freqs())[i]),
            "error_us": float(np.asarray(t.get_errors())[i]),
            "obs": t.get_obss()[i],
            "name": t.names[i] if getattr(t, "names", None) else "",
            "flags": dict(t.flags[i]),
            "resid_us": float(res.time_resids[i] * 1e6),
            "selected": bool(self.selected[i]),
        }

    # ------------------------------------------------------------ fit

    def _make_fitter(self):
        from pint_tpu.fitter import (DownhillWLSFitter, Fitter,
                                     WLSFitter)
        from pint_tpu.gls import DownhillGLSFitter, GLSFitter

        kinds = {"wls": WLSFitter, "gls": GLSFitter,
                 "downhill": DownhillWLSFitter,
                 "downhill_gls": DownhillGLSFitter}
        if self.fitter_name == "auto":
            return Fitter.auto(self.all_toas, self.model)
        return kinds[self.fitter_name](self.all_toas, self.model)

    def fit(self, maxiter: int = 5):
        """Fit the current model to the current TOAs (reference: pintk
        Pulsar.fit). Keeps the pre-fit model for plotting."""
        self._push_undo()
        self.prefit_model = copy.deepcopy(self.model)
        f = self._make_fitter()
        self.fit_results = f.fit_toas(maxiter=maxiter)
        self.model = f.model
        self._fitter_obj = f
        self.fitted = True
        return self.fit_results

    @property
    def fitter(self):
        if self._fitter_obj is None:
            raise ValueError("no fit performed yet")
        return self._fitter_obj

    def random_models(self, n: int = 10,
                      rng: Optional[np.random.Generator] = None):
        """Residual curves for n draws from the post-fit covariance
        (the pintk random-models overlay)."""
        from pint_tpu.simulation import calculate_random_models

        return calculate_random_models(self.fitter, self.all_toas,
                                       Nmodels=n, rng=rng)

    # ---------------------------------------------------- plot export

    def plot_data(self, postfit: bool = True) -> dict:
        """Everything the plk plot needs, as plain arrays: mjds,
        residuals (us), errors (us), freqs, obs, orbital phase (if
        binary), selection mask."""
        res = (self.postfit_resids if postfit and self.fitted
               else self.prefit_resids)
        self._last_resids = res  # reused by toa_info (O(1) popup)
        mjds = np.asarray(self.all_toas.get_mjds())
        data = {
            "mjds": mjds,
            "resids_us": res.time_resids * 1e6,
            "errors_us": np.asarray(self.all_toas.get_errors()),
            "freqs": np.asarray(self.all_toas.get_freqs()),
            "obs": list(self.all_toas.get_obss()),
            "selected": self.selected.copy(),
            "rms_us": res.rms_weighted() * 1e6,
            "chi2": float(res.chi2),
        }
        def _opt(nm):
            try:
                return self.model.get_param(nm).value
            except KeyError:
                return None

        pb = _opt("PB")
        t0 = _opt("TASC")
        if t0 is None:
            t0 = _opt("T0")
        if pb and t0:
            data["orbital_phase"] = np.mod((mjds - t0) / pb, 1.0)
        # solar elongation [deg] (reference plk axis): angle between
        # the observatory->Sun and observatory->pulsar directions
        sun = getattr(self.all_toas, "obs_sun_pos", None)
        if sun is not None:
            sun = np.asarray(sun)
            try:  # _host_psr_dir owns the astrometry dispatch
                n = self.model._host_psr_dir(self.all_toas)
            except (KeyError, ValueError):
                n = None  # no astrometry component: no elongation
            if n is not None:
                cosd = np.sum(sun * n, axis=-1) / \
                    np.linalg.norm(sun, axis=-1)
                data["elongation"] = np.degrees(
                    np.arccos(np.clip(cosd, -1.0, 1.0)))
        return data

    # -------------------------------------------------------- file IO

    def write_par(self, path):
        with open(path, "w") as fh:
            fh.write(self.model.as_parfile())

    def write_tim(self, path):
        self.all_toas.write_TOA_file(path)

    def update_model_from_text(self, text: str):
        """Replace the model from edited par text (the ParWidget apply
        path). The live TOAs are re-barycentered in place when EPHEM
        changes, and planet positions are (re)computed when either
        EPHEM or PLANET_SHAPIRO changes."""
        from pint_tpu.models import get_model

        self._push_undo()
        old_ephem = self.model.EPHEM.value
        old_planets = bool(self.model.PLANET_SHAPIRO.value)
        self.model = get_model(io.StringIO(text))
        self.prefit_model = copy.deepcopy(self.model)
        new_planets = bool(self.model.PLANET_SHAPIRO.value)
        ephem_changed = self.model.EPHEM.value != old_ephem
        if ephem_changed or new_planets != old_planets:
            # recompute on the TOAs we HAVE (not the on-disk tim:
            # that would resurrect deleted TOAs and drop jump flags);
            # the TDB chain only depends on the ephemeris, so a pure
            # PLANET_SHAPIRO toggle skips straight to posvels
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                if ephem_changed:
                    self.all_toas.compute_TDBs(
                        ephem=self.model.EPHEM.value)
                self.all_toas.compute_posvels(
                    ephem=self.model.EPHEM.value,
                    planets=new_planets)
        self.fitted = False
        self._fitter_obj = None
