"""Fit-parameter checkbox column (reference: src/pint/pintk/plk.py's
fitbox): toggle which parameters the next fit frees. All logic lives
in the Pulsar facade (fittable_params / set_fit_params); this widget
is a thin Tk shell of checkbuttons."""

from __future__ import annotations

__all__ = ["FitboxWidget"]


class FitboxWidget:
    """Tk shell: one checkbutton per fittable parameter."""

    def __init__(self, master, pulsar, on_apply=None):
        import tkinter as tk

        self._tk = tk
        self.pulsar = pulsar
        self._on_apply = on_apply
        self.frame = tk.Frame(master)
        tk.Button(self.frame, text="Apply fit params",
                  command=self.apply).pack(side=tk.TOP, fill=tk.X)
        canvas = tk.Canvas(self.frame, width=160)
        bar = tk.Scrollbar(self.frame, orient="vertical",
                           command=canvas.yview)
        self._inner = tk.Frame(canvas)
        self._inner.bind("<Configure>", lambda e: canvas.configure(
            scrollregion=canvas.bbox("all")))
        canvas.create_window((0, 0), window=self._inner, anchor="nw")
        canvas.configure(yscrollcommand=bar.set)
        canvas.pack(side="left", fill="both", expand=True)
        bar.pack(side="right", fill="y")
        self._vars = {}
        self.refresh()

    def refresh(self):
        """Rebuild the checkbutton set from the CURRENT model —
        must run after anything that adds/frees parameters (GUI
        jumps, par edits), or Apply would re-freeze them: the facade
        freezes every fittable param not listed."""
        for w in self._inner.winfo_children():
            w.destroy()
        self._vars = {}
        free = set(self.pulsar.model.free_params)
        for nm in self.pulsar.fittable_params():
            v = self._tk.BooleanVar(value=nm in free)
            self._tk.Checkbutton(self._inner, text=nm, variable=v,
                                 anchor="w").pack(fill="x")
            self._vars[nm] = v

    def apply(self):
        names = [nm for nm, v in self._vars.items() if v.get()]
        self.pulsar.set_fit_params(names)
        if self._on_apply:
            self._on_apply()
