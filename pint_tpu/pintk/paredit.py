"""Par-file editor pane (reference: src/pint/pintk/paredit.py
ParWidget): edit the model as text, apply, write out. The text-side
logic (ParEditState) is headless-testable."""

from __future__ import annotations

__all__ = ["ParEditState", "ParWidget"]


class ParEditState:
    def __init__(self, pulsar):
        self.pulsar = pulsar

    def current_text(self) -> str:
        return self.pulsar.model.as_parfile()

    def apply(self, text: str):
        """Apply edited par text to the pulsar (rebuilds the model;
        raises on a malformed par so the GUI can show the error)."""
        self.pulsar.update_model_from_text(text)

    def write(self, path: str):
        self.pulsar.write_par(path)


class ParWidget:
    """Tk shell over ParEditState (requires a display)."""

    def __init__(self, master, pulsar, on_apply=None):
        import tkinter as tk
        from tkinter import filedialog, messagebox, scrolledtext

        self.state = ParEditState(pulsar)
        self._on_apply = on_apply
        self.frame = tk.Frame(master)
        bar = tk.Frame(self.frame)
        bar.pack(side=tk.TOP, fill=tk.X)
        tk.Button(bar, text="Apply", command=self.apply).pack(
            side=tk.LEFT)
        tk.Button(bar, text="Reset", command=self.reset).pack(
            side=tk.LEFT)
        tk.Button(bar, text="Write par...", command=self.write).pack(
            side=tk.LEFT)
        self.text = scrolledtext.ScrolledText(self.frame, width=60)
        self.text.pack(side=tk.TOP, fill=tk.BOTH, expand=1)
        self._tk = tk
        self._filedialog = filedialog
        self._messagebox = messagebox
        self.reset()

    def reset(self):
        self.text.delete("1.0", self._tk.END)
        self.text.insert(self._tk.END, self.state.current_text())

    def apply(self):
        try:
            self.state.apply(self.text.get("1.0", self._tk.END))
        except Exception as e:  # surface parse errors to the user
            self._messagebox.showerror("par error", str(e))
            return
        if self._on_apply:
            self._on_apply()

    def write(self):
        path = self._filedialog.asksaveasfilename(
            defaultextension=".par")
        if path:
            self.state.write(path)
