"""Point-coloring modes for the plk residual plot (reference:
src/pint/pintk/colormodes.py). Each mode maps the Pulsar plot_data
dict to per-point colors; pure functions so they're testable headless.
"""

from __future__ import annotations

import numpy as np

__all__ = ["COLOR_MODES", "point_colors"]

_DEFAULT = "#2c7fb8"
_SELECTED = "#e34a33"
_CYCLE = ["#2c7fb8", "#e34a33", "#31a354", "#756bb1", "#ff7f00",
          "#a6761d", "#e7298a", "#666666"]


def _mode_default(data):
    c = np.array([_DEFAULT] * len(data["mjds"]), dtype=object)
    c[data["selected"]] = _SELECTED
    return list(c)


def _mode_freq(data):
    """Blue->red across the observing band (log spacing)."""
    f = np.asarray(data["freqs"], dtype=float)
    finite = np.isfinite(f)
    lo = np.log10(f[finite].min()) if finite.any() else 0.0
    hi = np.log10(f[finite].max()) if finite.any() else 1.0
    span = (hi - lo) or 1.0
    out = []
    for fi in f:
        if not np.isfinite(fi):
            out.append("#666666")
            continue
        x = (np.log10(fi) - lo) / span
        r = int(255 * x)
        b = int(255 * (1 - x))
        out.append(f"#{r:02x}40{b:02x}")
    return out


def _mode_obs(data):
    sites = sorted(set(data["obs"]))
    cmap = {s: _CYCLE[i % len(_CYCLE)] for i, s in enumerate(sites)}
    return [cmap[o] for o in data["obs"]]


def _mode_jump(data):
    """Color by GUI jump id (0 = unjumped)."""
    ids = data.get("jump_ids")
    if ids is None:
        return _mode_default(data)
    out = []
    for j in ids:
        out.append("#bbbbbb" if j == 0 else _CYCLE[j % len(_CYCLE)])
    return out


COLOR_MODES = {
    "default": _mode_default,
    "frequency": _mode_freq,
    "observatory": _mode_obs,
    "jump": _mode_jump,
}


def point_colors(mode: str, data) -> list:
    try:
        return COLOR_MODES[mode](data)
    except KeyError:
        raise ValueError(f"unknown color mode {mode!r}; know "
                         f"{sorted(COLOR_MODES)}") from None
