"""Tim-file editor pane (reference: src/pint/pintk/timedit.py
TimWidget)."""

from __future__ import annotations

__all__ = ["TimEditState", "TimWidget"]


class TimEditState:
    def __init__(self, pulsar):
        self.pulsar = pulsar

    def current_text(self) -> str:
        import io
        import os
        import tempfile

        # round-trip through the writer so edits start from the
        # canonical serialization
        fd, path = tempfile.mkstemp(suffix=".tim")
        os.close(fd)
        try:
            self.pulsar.write_tim(path)
            with open(path) as fh:
                return fh.read()
        finally:
            os.unlink(path)

    def apply(self, text: str):
        """Reload TOAs from edited tim text."""
        import io

        from pint_tpu.toa import get_TOAs

        import numpy as np

        p = self.pulsar
        p._push_undo()
        p.all_toas = get_TOAs(
            io.StringIO(text), model=p.model,
            ephem=p.model.EPHEM.value,
            planets=bool(p.model.PLANET_SHAPIRO.value))
        p.selected = np.zeros(p.all_toas.ntoas, dtype=bool)
        p.fitted = False
        p._fitter_obj = None

    def write(self, path: str):
        self.pulsar.write_tim(path)


class TimWidget:
    """Tk shell over TimEditState (requires a display)."""

    def __init__(self, master, pulsar, on_apply=None):
        import tkinter as tk
        from tkinter import filedialog, messagebox, scrolledtext

        self.state = TimEditState(pulsar)
        self._on_apply = on_apply
        self.frame = tk.Frame(master)
        bar = tk.Frame(self.frame)
        bar.pack(side=tk.TOP, fill=tk.X)
        tk.Button(bar, text="Apply", command=self.apply).pack(
            side=tk.LEFT)
        tk.Button(bar, text="Reset", command=self.reset).pack(
            side=tk.LEFT)
        tk.Button(bar, text="Write tim...", command=self.write).pack(
            side=tk.LEFT)
        self.text = scrolledtext.ScrolledText(self.frame, width=60)
        self.text.pack(side=tk.TOP, fill=tk.BOTH, expand=1)
        self._tk = tk
        self._filedialog = filedialog
        self._messagebox = messagebox
        self.reset()

    def reset(self):
        self.text.delete("1.0", self._tk.END)
        self.text.insert(self._tk.END, self.state.current_text())

    def apply(self):
        try:
            self.state.apply(self.text.get("1.0", self._tk.END))
        except Exception as e:
            self._messagebox.showerror("tim error", str(e))
            return
        if self._on_apply:
            self._on_apply()

    def write(self):
        path = self._filedialog.asksaveasfilename(
            defaultextension=".tim")
        if path:
            self.state.write(path)
