"""Interactive fitting GUI (reference: src/pint/pintk/: the `pintk`
script with PlkWidget + par/tim editors over a Pulsar facade).

Architecture: ALL behavior lives in headless classes —
:class:`pint_tpu.pintk.pulsar.Pulsar` (fit/select/delete/jump/undo),
:class:`pint_tpu.pintk.plk.PlkState` (axes/colors/box-select),
``ParEditState``/``TimEditState`` — and the Tk widgets are thin shells,
so the whole GUI logic runs under pytest without a display and the
same facade is scriptable from notebooks.
"""

from __future__ import annotations

import argparse
import sys

from pint_tpu.pintk.pulsar import Pulsar  # noqa: F401

__all__ = ["Pulsar", "main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pintk", description="Interactive timing-model fitter")
    p.add_argument("parfile")
    p.add_argument("timfile")
    p.add_argument("--fitter", default="auto",
                   choices=["auto", "wls", "gls", "downhill",
                            "downhill_gls"])
    args = p.parse_args(argv)

    try:
        import tkinter as tk
    except ImportError as e:  # pragma: no cover - env without Tk
        raise SystemExit(f"pintk needs tkinter: {e}")

    from pint_tpu.pintk.fitbox import FitboxWidget
    from pint_tpu.pintk.paredit import ParWidget
    from pint_tpu.pintk.plk import PlkWidget
    from pint_tpu.pintk.timedit import TimWidget

    pulsar = Pulsar(args.parfile, args.timfile, fitter=args.fitter)

    root = tk.Tk()
    root.title(f"pintk: {pulsar.name}")
    plk = PlkWidget(root, pulsar)
    plk.frame.pack(side=tk.LEFT, fill=tk.BOTH, expand=1)

    fitbox = FitboxWidget(root, pulsar, on_apply=plk.update_plot)
    fitbox.frame.pack(side=tk.LEFT, fill=tk.Y)
    # GUI jumps / par edits can add or free parameters; the fitbox
    # must rebuild its checkbutton set or Apply would re-freeze them
    plk.on_model_change = fitbox.refresh

    def _applied():
        plk.update_plot()
        fitbox.refresh()

    side = tk.Frame(root)
    side.pack(side=tk.RIGHT, fill=tk.BOTH)
    par = ParWidget(side, pulsar, on_apply=_applied)
    par.frame.pack(side=tk.TOP, fill=tk.BOTH, expand=1)
    tim = TimWidget(side, pulsar, on_apply=_applied)
    tim.frame.pack(side=tk.BOTTOM, fill=tk.BOTH, expand=1)

    root.mainloop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
