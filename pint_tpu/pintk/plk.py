"""plk-style residual-plot widget (reference: src/pint/pintk/plk.py
PlkWidget): matplotlib canvas embedded in Tk with rectangle selection,
fit/undo/delete/jump buttons, axis choices, and color modes.

All plotting state transforms live on PlkState (headless-testable);
the Tk widget is a thin shell so the module imports fine without a
display (tkinter is only touched inside PlkWidget.__init__).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pint_tpu.pintk.colormodes import point_colors

__all__ = ["PlkState", "PlkWidget", "XAXIS_CHOICES", "YAXIS_CHOICES"]

XAXIS_CHOICES = ["mjd", "orbital_phase", "serial", "frequency"]
YAXIS_CHOICES = ["residual", "residual_phase"]


class PlkState:
    """Pure plotting state: which axes, color mode, and the derived
    arrays for the current Pulsar."""

    def __init__(self, pulsar):
        self.pulsar = pulsar
        self.xaxis = "mjd"
        self.yaxis = "residual"
        self.color_mode = "default"
        self.show_prefit = False

    # -------------------------------------------------------- arrays

    def _jump_ids(self):
        from pint_tpu.pintk.pulsar import GUI_JUMP_FLAG

        return [int(f.get(GUI_JUMP_FLAG, 0))
                for f in self.pulsar.all_toas.flags]

    def xy(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, dict]:
        """(x, y, yerr, data) for the current axis selection."""
        data = self.pulsar.plot_data(postfit=not self.show_prefit
                                     and self.pulsar.fitted)
        data["jump_ids"] = self._jump_ids()
        if self.xaxis == "mjd":
            x = data["mjds"]
        elif self.xaxis == "orbital_phase":
            x = data.get("orbital_phase")
            if x is None:
                raise ValueError("model has no binary: no orbital "
                                 "phase axis")
        elif self.xaxis == "serial":
            x = np.arange(len(data["mjds"]), dtype=float)
        elif self.xaxis == "frequency":
            x = data["freqs"]
        else:
            raise ValueError(f"unknown x axis {self.xaxis!r}")
        y = data["resids_us"]
        yerr = data["errors_us"]
        if self.yaxis == "residual_phase":
            f0 = self.pulsar.model.F0.value
            y = y * 1e-6 * f0
            yerr = yerr * 1e-6 * f0
        return np.asarray(x, dtype=float), np.asarray(y), \
            np.asarray(yerr), data

    def colors(self, data) -> list:
        return point_colors(self.color_mode, data)

    def select_rectangle(self, x1, x2, y1=None, y2=None,
                         extend: bool = False) -> int:
        """Box selection in current axis coordinates; returns the
        number of selected points."""
        x, y, _, _ = self.xy()
        lo, hi = min(x1, x2), max(x1, x2)
        m = (x >= lo) & (x <= hi)
        if y1 is not None and y2 is not None:
            ylo, yhi = min(y1, y2), max(y1, y2)
            m &= (y >= ylo) & (y <= yhi)
        if extend:
            m |= self.pulsar.selected
        self.pulsar.select(m)
        return int(m.sum())

    def title(self, data: Optional[dict] = None) -> str:
        if data is None:
            data = self.pulsar.plot_data(postfit=self.pulsar.fitted
                                         and not self.show_prefit)
        kind = "post-fit" if self.pulsar.fitted and \
            not self.show_prefit else "pre-fit"
        return (f"{self.pulsar.name}  {kind}  "
                f"wrms={data['rms_us']:.3f} us  "
                f"chi2={data['chi2']:.2f}")


class PlkWidget:
    """Tk shell over PlkState (requires a display)."""

    def __init__(self, master, pulsar):
        import tkinter as tk

        from matplotlib.backends.backend_tkagg import (
            FigureCanvasTkAgg, NavigationToolbar2Tk)
        from matplotlib.figure import Figure
        from matplotlib.widgets import RectangleSelector

        self.state = PlkState(pulsar)
        self.frame = tk.Frame(master)
        top = tk.Frame(self.frame)
        top.pack(side=tk.TOP, fill=tk.X)

        tk.Button(top, text="Fit", command=self.fit).pack(
            side=tk.LEFT)
        tk.Button(top, text="Undo", command=self.undo).pack(
            side=tk.LEFT)
        tk.Button(top, text="Delete", command=self.delete).pack(
            side=tk.LEFT)
        tk.Button(top, text="Jump", command=self.jump).pack(
            side=tk.LEFT)
        tk.Button(top, text="Unjump", command=self.unjump).pack(
            side=tk.LEFT)
        tk.Button(top, text="Pulse numbers",
                  command=self.track_pn).pack(side=tk.LEFT)
        tk.Button(top, text="Random models",
                  command=self.random_models).pack(side=tk.LEFT)

        self.xvar = tk.StringVar(value=self.state.xaxis)
        tk.OptionMenu(top, self.xvar, *XAXIS_CHOICES,
                      command=self.set_xaxis).pack(side=tk.LEFT)
        self.cvar = tk.StringVar(value=self.state.color_mode)
        from pint_tpu.pintk.colormodes import COLOR_MODES

        tk.OptionMenu(top, self.cvar, *COLOR_MODES,
                      command=self.set_color_mode).pack(side=tk.LEFT)

        self.fig = Figure(figsize=(9, 5))
        self.ax = self.fig.add_subplot(111)
        self.canvas = FigureCanvasTkAgg(self.fig, master=self.frame)
        self.canvas.get_tk_widget().pack(side=tk.TOP, fill=tk.BOTH,
                                         expand=1)
        NavigationToolbar2Tk(self.canvas, self.frame)
        self.selector = RectangleSelector(self.ax, self._on_select,
                                          useblit=True, button=[1])
        self._random_curves = None
        self.update_plot()

    # ------------------------------------------------------- actions

    def _on_select(self, eclick, erelease):
        self.state.select_rectangle(eclick.xdata, erelease.xdata,
                                    eclick.ydata, erelease.ydata,
                                    extend=eclick.key == "shift")
        self.update_plot()

    def fit(self):
        self.state.pulsar.fit()
        self._random_curves = None
        self.update_plot()

    def undo(self):
        self.state.pulsar.undo()
        self._random_curves = None  # TOA count may have changed
        self.update_plot()

    def delete(self):
        self.state.pulsar.delete_TOAs()
        self._random_curves = None
        self.update_plot()

    def jump(self):
        self.state.pulsar.jump_selection()
        self.update_plot()

    def unjump(self):
        self.state.pulsar.unjump_selection()
        self.update_plot()

    def track_pn(self):
        self.state.pulsar.compute_pulse_numbers()
        self.update_plot()

    def random_models(self):
        self._random_curves = self.state.pulsar.random_models(n=10)
        self.update_plot()

    def set_xaxis(self, value):
        self.state.xaxis = value
        self.update_plot()

    def set_color_mode(self, value):
        self.state.color_mode = value
        self.update_plot()

    # ---------------------------------------------------------- draw

    def update_plot(self):
        x, y, yerr, data = self.state.xy()
        self.ax.clear()
        colors = self.state.colors(data)
        self.ax.errorbar(x, y, yerr=yerr, fmt="none", ecolor="#bbbbbb",
                         zorder=1)
        self.ax.scatter(x, y, c=colors, s=12, zorder=2)
        sel = data["selected"]
        if sel.any():
            self.ax.scatter(x[sel], y[sel], facecolors="none",
                            edgecolors="#e34a33", s=60, zorder=3)
        if self._random_curves is not None and \
                self.state.xaxis == "mjd":
            for curve in self._random_curves:
                if len(curve) != len(x):  # TOAs changed under us
                    self._random_curves = None
                    break
                self.ax.plot(x, np.asarray(curve) * 1e6,
                             color="#31a354", alpha=0.3, zorder=0)
        self.ax.set_xlabel(self.state.xaxis)
        self.ax.set_ylabel("residual (us)"
                           if self.state.yaxis == "residual"
                           else "residual (turns)")
        self.ax.set_title(self.state.title(data))
        self.ax.grid(alpha=0.2)
        self.canvas.draw_idle()
