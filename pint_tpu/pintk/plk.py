"""plk-style residual-plot widget (reference: src/pint/pintk/plk.py
PlkWidget): matplotlib canvas embedded in Tk with rectangle selection,
fit/undo/delete/jump buttons, axis choices, and color modes.

All plotting state transforms live on PlkState (headless-testable);
the Tk widget is a thin shell so the module imports fine without a
display (tkinter is only touched inside PlkWidget.__init__).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pint_tpu.pintk.colormodes import point_colors

__all__ = ["PlkState", "PlkWidget", "XAXIS_CHOICES", "YAXIS_CHOICES"]

XAXIS_CHOICES = ["mjd", "year", "day_of_year", "orbital_phase",
                 "serial", "frequency", "toa_error", "elongation"]
YAXIS_CHOICES = ["residual", "residual_phase"]


class PlkState:
    """Pure plotting state: which axes, color mode, and the derived
    arrays for the current Pulsar."""

    def __init__(self, pulsar):
        self.pulsar = pulsar
        self.xaxis = "mjd"
        self.yaxis = "residual"
        self.color_mode = "default"
        self.show_prefit = False
        # view-limit state (zoom): None = autoscale to the data. A
        # stack of previous views backs zoom_out, like the
        # reference's plk zoom history.
        self.xlim: Optional[Tuple[float, float]] = None
        self.ylim: Optional[Tuple[float, float]] = None
        self._view_stack: list = []
        # random-models overlay curves (aligned with the current TOA
        # set; invalidated by any TOA-count or fit change)
        self.random_curves: Optional[list] = None

    # -------------------------------------------------------- arrays

    def _jump_ids(self):
        from pint_tpu.pintk.pulsar import GUI_JUMP_FLAG

        return [int(f.get(GUI_JUMP_FLAG, 0))
                for f in self.pulsar.all_toas.flags]

    def xy(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, dict]:
        """(x, y, yerr, data) for the current axis selection."""
        data = self.pulsar.plot_data(postfit=not self.show_prefit
                                     and self.pulsar.fitted)
        data["jump_ids"] = self._jump_ids()
        if self.xaxis == "mjd":
            x = data["mjds"]
        elif self.xaxis == "year":
            # Julian-epoch year (reference plk "year" axis)
            x = 2000.0 + (data["mjds"] - 51544.5) / 365.25
        elif self.xaxis == "day_of_year":
            # EXACT civil (UTC) day-of-year via the calendar
            # conversion in pint_tpu.time.mjd (ISSUE 10 satellite:
            # the old Julian-year 365.25 d approximation drifted up
            # to ~0.75 d within a year and produced day-366
            # artifacts at non-leap year boundaries). Jan 1 00:00 ->
            # 1.0, fractional day rides the MJD fraction.
            from pint_tpu.time.mjd import mjd_to_calendar

            mjds = data["mjds"]
            _, _, _, doy = mjd_to_calendar(mjds)
            x = doy + (mjds - np.floor(mjds))
        elif self.xaxis == "orbital_phase":
            x = data.get("orbital_phase")
            if x is None:
                raise ValueError("model has no binary: no orbital "
                                 "phase axis")
        elif self.xaxis == "serial":
            x = np.arange(len(data["mjds"]), dtype=float)
        elif self.xaxis == "frequency":
            x = data["freqs"]
        elif self.xaxis == "toa_error":
            x = data["errors_us"]
        elif self.xaxis == "elongation":
            x = data.get("elongation")
            if x is None:
                raise ValueError("no solar-elongation data (TOAs "
                                 "lack Sun positions)")
        else:
            raise ValueError(f"unknown x axis {self.xaxis!r}")
        y = data["resids_us"]
        yerr = data["errors_us"]
        if self.yaxis == "residual_phase":
            f0 = self.pulsar.model.F0.value
            y = y * 1e-6 * f0
            yerr = yerr * 1e-6 * f0
        out = (np.asarray(x, dtype=float), np.asarray(y),
               np.asarray(yerr), data)
        self._last_xy = out[:2]  # reused by nearest_point (O(1) pick)
        return out

    def colors(self, data) -> list:
        return point_colors(self.color_mode, data)

    def select_rectangle(self, x1, x2, y1=None, y2=None,
                         extend: bool = False) -> int:
        """Box selection in current axis coordinates; returns the
        number of selected points."""
        x, y, _, _ = self.xy()
        lo, hi = min(x1, x2), max(x1, x2)
        m = (x >= lo) & (x <= hi)
        if y1 is not None and y2 is not None:
            ylo, yhi = min(y1, y2), max(y1, y2)
            m &= (y >= ylo) & (y <= yhi)
        if extend:
            m |= self.pulsar.selected
        self.pulsar.select(m)
        return int(m.sum())

    def zoom_rectangle(self, x1, x2, y1=None, y2=None) -> None:
        """Zoom to a box in current axis coordinates (reference: plk
        right-drag zoom). The previous view is pushed so zoom_out
        steps back through the history. Zero-area boxes (a plain
        click: RectangleSelector fires on release even without a
        drag) are ignored — they would blank the plot and pollute
        the history."""
        if x1 == x2 or (y1 is not None and y2 is not None
                        and y1 == y2):
            return
        self._view_stack.append((self.xlim, self.ylim))
        self.xlim = (min(x1, x2), max(x1, x2))
        if y1 is not None and y2 is not None:
            self.ylim = (min(y1, y2), max(y1, y2))

    def zoom_out(self) -> None:
        """Step back one zoom level (autoscale when the history is
        empty)."""
        if self._view_stack:
            self.xlim, self.ylim = self._view_stack.pop()
        else:
            self.xlim = self.ylim = None

    def reset_view(self) -> None:
        self.xlim = self.ylim = None
        self._view_stack.clear()

    def set_axis(self, xaxis: Optional[str] = None,
                 yaxis: Optional[str] = None) -> None:
        """Change plot axes AND reset the view: zoom limits are in
        axis units, so keeping them across an axis switch would show
        an empty plot (mjd limits applied to a 0-1 orbital phase)."""
        if xaxis is not None:
            self.xaxis = xaxis
        if yaxis is not None:
            self.yaxis = yaxis
        self.reset_view()

    def visible_mask(self) -> np.ndarray:
        """Boolean mask of points inside the current view limits —
        lets selection operations act on what the user sees."""
        x, y, _, _ = self.xy()
        m = np.ones(len(x), dtype=bool)
        if self.xlim is not None:
            m &= (x >= self.xlim[0]) & (x <= self.xlim[1])
        if self.ylim is not None:
            m &= (y >= self.ylim[0]) & (y <= self.ylim[1])
        return m

    def compute_random_models(self, n: int = 10, rng=None) -> list:
        """Fit-covariance draw curves for the overlay, computed
        through the Pulsar facade and cached on the state (the Tk
        widget is a pure view). Requires a completed fit."""
        self.random_curves = self.pulsar.random_models(n=n, rng=rng)
        return self.random_curves

    def clear_random_models(self) -> None:
        self.random_curves = None

    def overlay_arrays(self, x: np.ndarray) -> list:
        """Random-model curves as (x, y_us) pairs aligned with the
        current plot arrays; silently drops (and clears) the overlay
        when the TOA set changed under it."""
        if self.random_curves is None:
            return []
        out = []
        for curve in self.random_curves:
            if len(curve) != len(x):
                self.random_curves = None
                return []
            out.append((x, np.asarray(curve) * 1e6))
        return out

    def nearest_point(self, x, y=None,
                      max_frac: float = 0.02) -> Optional[int]:
        """Index of the plotted point nearest (x, y) in the current
        axis coordinates, or None if nothing is within ``max_frac``
        of the VISIBLE span (a click on empty space selects nothing,
        and a zoomed view picks what's under the cursor, not an
        off-screen point). Reuses the arrays of the last xy() call —
        update_plot just computed them — so a pick costs no model
        evaluation."""
        cached = getattr(self, "_last_xy", None)
        if cached is None or \
                len(cached[0]) != self.pulsar.all_toas.ntoas:
            self.xy()  # none cached / stale after a TOA edit
            cached = self._last_xy
        px, py = cached
        # normalize by (and restrict the pick to) the current view
        if self.xlim is not None:
            sx = self.xlim[1] - self.xlim[0] or 1.0
        else:
            sx = np.ptp(px) or 1.0
        if y is not None and self.ylim is not None:
            sy = self.ylim[1] - self.ylim[0] or 1.0
        else:
            sy = np.ptp(py) or 1.0
        vis = np.ones(len(px), dtype=bool)
        if self.xlim is not None:
            vis &= (px >= self.xlim[0]) & (px <= self.xlim[1])
        if self.ylim is not None:
            vis &= (py >= self.ylim[0]) & (py <= self.ylim[1])
        if not vis.any():
            return None
        d2 = ((px - x) / sx) ** 2
        if y is not None:
            d2 = d2 + ((py - y) / sy) ** 2
        d2 = np.where(vis, d2, np.inf)
        i = int(np.argmin(d2))
        return i if float(np.sqrt(d2[i])) <= max_frac else None

    def title(self, data: Optional[dict] = None) -> str:
        if data is None:
            data = self.pulsar.plot_data(postfit=self.pulsar.fitted
                                         and not self.show_prefit)
        kind = "post-fit" if self.pulsar.fitted and \
            not self.show_prefit else "pre-fit"
        return (f"{self.pulsar.name}  {kind}  "
                f"wrms={data['rms_us']:.3f} us  "
                f"chi2={data['chi2']:.2f}")


class PlkWidget:
    """Tk shell over PlkState (requires a display). Set
    ``on_model_change`` to be notified after actions that can change
    the model's parameter structure (fit/jump/unjump/undo) — the
    fitbox refreshes its checkbuttons from it."""

    on_model_change = None

    def __init__(self, master, pulsar):
        import tkinter as tk

        from matplotlib.backends.backend_tkagg import (
            FigureCanvasTkAgg, NavigationToolbar2Tk)
        from matplotlib.figure import Figure
        from matplotlib.widgets import RectangleSelector

        self.state = PlkState(pulsar)
        self.frame = tk.Frame(master)
        top = tk.Frame(self.frame)
        top.pack(side=tk.TOP, fill=tk.X)

        tk.Button(top, text="Fit", command=self.fit).pack(
            side=tk.LEFT)
        tk.Button(top, text="Undo", command=self.undo).pack(
            side=tk.LEFT)
        tk.Button(top, text="Delete", command=self.delete).pack(
            side=tk.LEFT)
        tk.Button(top, text="Jump", command=self.jump).pack(
            side=tk.LEFT)
        tk.Button(top, text="Unjump", command=self.unjump).pack(
            side=tk.LEFT)
        tk.Button(top, text="Pulse numbers",
                  command=self.track_pn).pack(side=tk.LEFT)
        tk.Button(top, text="Random models",
                  command=self.random_models).pack(side=tk.LEFT)

        self.xvar = tk.StringVar(value=self.state.xaxis)
        tk.OptionMenu(top, self.xvar, *XAXIS_CHOICES,
                      command=self.set_xaxis).pack(side=tk.LEFT)
        self.cvar = tk.StringVar(value=self.state.color_mode)
        from pint_tpu.pintk.colormodes import COLOR_MODES

        tk.OptionMenu(top, self.cvar, *COLOR_MODES,
                      command=self.set_color_mode).pack(side=tk.LEFT)

        self.fig = Figure(figsize=(9, 5))
        self.ax = self.fig.add_subplot(111)
        self.canvas = FigureCanvasTkAgg(self.fig, master=self.frame)
        self.canvas.get_tk_widget().pack(side=tk.TOP, fill=tk.BOTH,
                                         expand=1)
        # middle-click a point -> per-TOA info popup (reference: the
        # plk click-info behavior); all content comes from the
        # headless Pulsar.toa_info
        self.canvas.mpl_connect("button_press_event", self._on_click)
        NavigationToolbar2Tk(self.canvas, self.frame)
        # left-drag: box selection; right-drag: zoom (reference plk
        # bindings); both are thin event shims over PlkState
        self.selector = RectangleSelector(self.ax, self._on_select,
                                          useblit=True, button=[1])
        self.zoomer = RectangleSelector(self.ax, self._on_zoom,
                                        useblit=True, button=[3])
        tk.Button(top, text="Zoom out",
                  command=self.zoom_out).pack(side=tk.LEFT)
        self.update_plot()

    # ------------------------------------------------------- actions

    def _on_select(self, eclick, erelease):
        self.state.select_rectangle(eclick.xdata, erelease.xdata,
                                    eclick.ydata, erelease.ydata,
                                    extend=eclick.key == "shift")
        self.update_plot()

    def _on_click(self, event):
        if event.button != 2 or event.inaxes is not self.ax \
                or event.xdata is None:
            return
        idx = self.state.nearest_point(event.xdata, event.ydata)
        if idx is None:
            return
        info = self.state.pulsar.toa_info(idx)
        import tkinter.messagebox as mb

        lines = [f"TOA #{info['index']}  {info['name']}",
                 f"MJD {info['mjd']:.8f}",
                 f"freq {info['freq_mhz']:.3f} MHz",
                 f"resid {info['resid_us']:.3f} us "
                 f"+- {info['error_us']:.3f}",
                 f"obs {info['obs']}"]
        lines += [f"-{k} {v}" for k, v in
                  sorted(info["flags"].items())]
        mb.showinfo("TOA info", "\n".join(lines))

    def _on_zoom(self, eclick, erelease):
        self.state.zoom_rectangle(eclick.xdata, erelease.xdata,
                                  eclick.ydata, erelease.ydata)
        self.update_plot()

    def zoom_out(self):
        self.state.zoom_out()
        self.update_plot()

    def _model_changed(self):
        if self.on_model_change:
            self.on_model_change()

    def fit(self):
        self.state.pulsar.fit()
        self.state.clear_random_models()
        self.update_plot()
        self._model_changed()

    def undo(self):
        self.state.pulsar.undo()
        self.state.clear_random_models()  # TOA count may have changed
        self.update_plot()
        self._model_changed()

    def delete(self):
        self.state.pulsar.delete_TOAs()
        self.state.clear_random_models()
        self.update_plot()

    def jump(self):
        self.state.pulsar.jump_selection()
        self.update_plot()
        self._model_changed()  # may have added a free JUMP param

    def unjump(self):
        self.state.pulsar.unjump_selection()
        self.update_plot()
        self._model_changed()

    def track_pn(self):
        self.state.pulsar.compute_pulse_numbers()
        self.update_plot()

    def random_models(self):
        self.state.compute_random_models(n=10)
        self.update_plot()

    def set_xaxis(self, value):
        self.state.set_axis(xaxis=value)  # resets zoom (axis units)
        self.update_plot()

    def set_color_mode(self, value):
        self.state.color_mode = value
        self.update_plot()

    # ---------------------------------------------------------- draw

    def update_plot(self):
        x, y, yerr, data = self.state.xy()
        self.ax.clear()
        colors = self.state.colors(data)
        self.ax.errorbar(x, y, yerr=yerr, fmt="none", ecolor="#bbbbbb",
                         zorder=1)
        self.ax.scatter(x, y, c=colors, s=12, zorder=2)
        sel = data["selected"]
        if sel.any():
            self.ax.scatter(x[sel], y[sel], facecolors="none",
                            edgecolors="#e34a33", s=60, zorder=3)
        if self.state.xaxis == "mjd":
            for cx, cy in self.state.overlay_arrays(x):
                self.ax.plot(cx, cy, color="#31a354", alpha=0.3,
                             zorder=0)
        if self.state.xlim is not None:
            self.ax.set_xlim(*self.state.xlim)
        if self.state.ylim is not None:
            self.ax.set_ylim(*self.state.ylim)
        self.ax.set_xlabel(self.state.xaxis)
        self.ax.set_ylabel("residual (us)"
                           if self.state.yaxis == "residual"
                           else "residual (turns)")
        self.ax.set_title(self.state.title(data))
        self.ax.grid(alpha=0.2)
        self.canvas.draw_idle()
