"""Minimal FITS binary-table I/O (host side).

The reference reads mission event files through astropy.io.fits
(src/pint/event_toas.py load_fits_TOAs); astropy does not exist in this
image, so this module implements the small slice of the FITS standard
the photon pipeline needs: header parsing, BINTABLE column decode
(big-endian scalar columns), and writing a compliant single-extension
event table (used both by tests and by the photonphase CLI to write
PULSE_PHASE back).
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["FitsHDU", "read_fits", "read_events_fits",
           "write_events_fits"]

BLOCK = 2880
CARD = 80

# TFORM letter -> numpy big-endian dtype
_TFORM_DTYPES = {
    "L": "u1", "B": "u1", "I": ">i2", "J": ">i4", "K": ">i8",
    "E": ">f4", "D": ">f8",
}


class FitsHDU:
    """One header-data unit: header dict + (for BINTABLE) column data."""

    def __init__(self, header: Dict[str, object],
                 data: Optional[Dict[str, np.ndarray]] = None):
        self.header = header
        self.data = data or {}

    @property
    def name(self) -> str:
        return str(self.header.get("EXTNAME", ""))


def _parse_card(card: bytes) -> Optional[Tuple[str, object]]:
    key = card[:8].decode("ascii", "replace").strip()
    if key in ("", "COMMENT", "HISTORY", "END"):
        return None
    if card[8:10] != b"= ":
        return None
    raw = card[10:].decode("ascii", "replace")
    # strip inline comment (outside quoted strings)
    if raw.lstrip().startswith("'"):
        s = raw.lstrip()[1:]
        out, i = [], 0
        while i < len(s):
            if s[i] == "'":
                if i + 1 < len(s) and s[i + 1] == "'":
                    out.append("'")
                    i += 2
                    continue
                break
            out.append(s[i])
            i += 1
        return key, "".join(out).rstrip()
    val = raw.split("/")[0].strip()
    if val in ("T", "F"):
        return key, val == "T"
    try:
        return key, int(val)
    except ValueError:
        pass
    try:
        return key, float(val)
    except ValueError:
        return key, val


def _read_header(f) -> Optional[Dict[str, object]]:
    header: Dict[str, object] = {}
    while True:
        block = f.read(BLOCK)
        if len(block) < BLOCK:
            return None if not header else header
        for i in range(0, BLOCK, CARD):
            card = block[i:i + CARD]
            if card[:3] == b"END":
                return header
            kv = _parse_card(card)
            if kv:
                header[kv[0]] = kv[1]


def _parse_tform(tform: str) -> Tuple[int, str]:
    """'1D' -> (1, 'D'); 'E' -> (1, 'E'); '10A' -> (10, 'A')."""
    i = 0
    while i < len(tform) and tform[i].isdigit():
        i += 1
    repeat = int(tform[:i]) if i else 1
    return repeat, tform[i:i + 1]


def _read_bintable(f, header) -> Dict[str, np.ndarray]:
    nrow = int(header["NAXIS2"])
    rowbytes = int(header["NAXIS1"])
    nfield = int(header["TFIELDS"])
    raw = f.read(nrow * rowbytes)
    pad = (-(nrow * rowbytes)) % BLOCK
    f.read(pad)
    cols: Dict[str, np.ndarray] = {}
    offset = 0
    for k in range(1, nfield + 1):
        name = str(header.get(f"TTYPE{k}", f"COL{k}")).strip()
        repeat, letter = _parse_tform(str(header[f"TFORM{k}"]).strip())
        if letter == "A":
            arr = np.frombuffer(
                raw, dtype=f"S{repeat}", count=nrow,
                offset=offset).astype(str) if nrow else np.array([])
            width = repeat
        else:
            dt = np.dtype(_TFORM_DTYPES[letter])
            width = dt.itemsize * repeat
            # strided view over rows
            full = np.frombuffer(raw, dtype=np.uint8).reshape(
                nrow, rowbytes) if nrow else np.zeros((0, rowbytes),
                                                      np.uint8)
            sub = full[:, offset:offset + width].copy()
            arr = sub.view(dt).reshape(nrow, repeat)
            if repeat == 1:
                arr = arr[:, 0]
            arr = arr.astype(dt.newbyteorder("="))
        cols[name] = arr
        offset += width
    return cols


def read_fits(path_or_bytes) -> List[FitsHDU]:
    """Parse all HDUs; BINTABLE extensions get decoded column data."""
    if isinstance(path_or_bytes, (bytes, bytearray)):
        f = io.BytesIO(path_or_bytes)
    else:
        f = open(path_or_bytes, "rb")
    try:
        hdus: List[FitsHDU] = []
        while True:
            header = _read_header(f)
            if header is None:
                break
            data: Dict[str, np.ndarray] = {}
            naxis = int(header.get("NAXIS", 0))
            if header.get("XTENSION", "").strip() == "BINTABLE":
                data = _read_bintable(f, header)
            elif naxis > 0:
                nbytes = abs(int(header.get("BITPIX", 8))) // 8
                for i in range(1, naxis + 1):
                    nbytes *= int(header[f"NAXIS{i}"])
                f.read(nbytes + ((-nbytes) % BLOCK))
            hdus.append(FitsHDU(header, data))
        return hdus
    finally:
        f.close()


def read_events_fits(path) -> Tuple[Dict[str, np.ndarray],
                                    Dict[str, object]]:
    """(columns, header) of the EVENTS extension (first BINTABLE named
    EVENTS, else the first BINTABLE)."""
    hdus = read_fits(path)
    tables = [h for h in hdus if h.data]
    if not tables:
        raise ValueError(f"no binary-table extension in {path}")
    for h in tables:
        if h.name.upper() == "EVENTS":
            return h.data, h.header
    return tables[0].data, tables[0].header


# ------------------------------------------------------------- writing


def _card(key: str, value, comment: str = "") -> bytes:
    if isinstance(value, bool):
        v = "T" if value else "F"
        s = f"{key:<8}= {v:>20}"
    elif isinstance(value, (int, np.integer)):
        s = f"{key:<8}= {value:>20d}"
    elif isinstance(value, (float, np.floating)):
        s = f"{key:<8}= {value:>20.15G}"
    else:
        s = f"{key:<8}= '{value}'"
    if comment:
        s += f" / {comment}"
    return s[:CARD].ljust(CARD).encode("ascii")


def _pad_block(b: bytes, fill: bytes = b"\x00") -> bytes:
    return b + fill * ((-len(b)) % BLOCK)


def write_events_fits(path, columns: Dict[str, np.ndarray],
                      header_extra: Optional[Dict[str, object]] = None,
                      extname: str = "EVENTS") -> None:
    """Write a minimal standard-compliant FITS file with an empty
    primary HDU and one BINTABLE of the given scalar columns (float64 ->
    D, float32 -> E, int -> J)."""
    names = list(columns)
    n = len(next(iter(columns.values()))) if names else 0
    enc = []
    for nm in names:
        a = np.asarray(columns[nm])
        if a.dtype.kind == "f" and a.dtype.itemsize == 4:
            enc.append((nm, "E", a.astype(">f4")))
        elif a.dtype.kind == "f":
            enc.append((nm, "D", a.astype(">f8")))
        else:
            enc.append((nm, "J", a.astype(">i4")))
    rowbytes = sum(a.dtype.itemsize for _, _, a in enc)

    primary = [_card("SIMPLE", True), _card("BITPIX", 8),
               _card("NAXIS", 0), _card("EXTEND", True),
               b"END".ljust(CARD)]
    out = _pad_block(b"".join(primary), b" ")

    cards = [_card("XTENSION", "BINTABLE"), _card("BITPIX", 8),
             _card("NAXIS", 2), _card("NAXIS1", rowbytes),
             _card("NAXIS2", n), _card("PCOUNT", 0), _card("GCOUNT", 1),
             _card("TFIELDS", len(enc)), _card("EXTNAME", extname)]
    for k, (nm, letter, _) in enumerate(enc, start=1):
        cards.append(_card(f"TTYPE{k}", nm))
        cards.append(_card(f"TFORM{k}", letter))
    for k, v in (header_extra or {}).items():
        cards.append(_card(k, v))
    cards.append(b"END".ljust(CARD))
    out += _pad_block(b"".join(cards), b" ")

    rec = np.zeros(n, dtype=[(nm, a.dtype) for nm, _, a in enc])
    for nm, _, a in enc:
        rec[nm] = a
    out += _pad_block(rec.tobytes())
    with open(path, "wb") as f:
        f.write(out)
