"""TEMPO/TEMPO2/PINT ``.par`` file tokenizer.

Format (reference: src/pint/models/model_builder.py parse_parfile;
SURVEY.md Appendix A.7): one parameter per line,

    KEY  value  [fit-flag]  [uncertainty]

whitespace separated. Mask parameters carry extra key tokens before the
value (``JUMP -fe L-wide 0.000216 1 0.000002`` or
``JUMP MJD 55000 55100 ...``). Duplicate keys are legal and meaningful
(one line per JUMP/EFAC instance), so parsing preserves every line in
order rather than collapsing to a dict of scalars.

This module only tokenizes; semantic interpretation (units, component
routing, prefix/mask expansion) lives in ``pint_tpu.models.model_builder``.
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field
from typing import List, Union


@dataclass
class ParfileLine:
    """One non-comment par line: the key plus its raw tokens."""

    key: str
    tokens: List[str] = field(default_factory=list)
    raw: str = ""


# Comment markers accepted by TEMPO-family tools.
_COMMENT_PREFIXES = ("#", "C ", "c ")


def resolve_source(source, kind: str = "par"):
    """Shared path-vs-literal resolution for par/tim inputs.

    Returns (lines, base_dir) — base_dir is the containing directory for
    file inputs (INCLUDE resolution), '.' otherwise.
    """
    import os

    if hasattr(source, "read"):
        return source.read().splitlines(), "."
    text = str(source)
    if os.path.exists(text):
        with open(text, "r") as f:
            return (f.read().splitlines(),
                    os.path.dirname(os.path.abspath(text)))
    # Not an existing file: literal content. A data line always contains
    # whitespace or a newline; a mistyped path contains neither, so fail
    # with the clearer file error in that case.
    if "\n" in text or " " in text or "\t" in text:
        return text.splitlines(), "."
    raise FileNotFoundError(f"no such {kind} file: {text!r}")


def _iter_lines(source) -> "list[str]":
    return resolve_source(source, kind="par")[0]


def parse_parfile(source: Union[str, io.IOBase]) -> List[ParfileLine]:
    """Tokenize a par file (path, file object, or literal content string).

    Returns the ordered list of lines; keys are upper-cased (par files are
    case-insensitive in keys, case-preserving in values).
    """
    out: List[ParfileLine] = []
    for raw in _iter_lines(source):
        line = raw.strip()
        if not line or line.startswith(_COMMENT_PREFIXES) or line == "C":
            continue
        parts = line.split()
        key = parts[0].upper()
        out.append(ParfileLine(key=key, tokens=parts[1:], raw=raw))
    return out


def parfile_dict(lines: List[ParfileLine]) -> "dict[str, list[list[str]]]":
    """key → list of token lists (one entry per occurrence, in file order)."""
    d: "dict[str, list[list[str]]]" = {}
    for ln in lines:
        d.setdefault(ln.key, []).append(ln.tokens)
    return d
