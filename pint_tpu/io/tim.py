"""``.tim`` TOA-file parser/writer (TEMPO2 "FORMAT 1" plus the TEMPO
Princeton, Parkes and ITOA column formats — ITOA goes beyond the
reference, whose parse_TOA_line raises "not implemented" there).

Reference behavior: src/pint/toa.py (.tim parsing in get_TOAs / TOA
class). Key property preserved here: **the MJD never passes through a
single float64** — it stays a decimal string until
``pint_tpu.time.mjd.parse_mjd_string`` splits it exactly into
(int day, double-double fraction).

Supported commands: FORMAT, MODE, INCLUDE, C/CC/# comments, SKIP/NOSKIP,
END, TIME (accumulated offset, seconds), PHASE (accumulated turns →
``-padd`` flag, applied by Residuals), EFAC/EQUAD (scoped error
scaling), EMIN/EMAX/FMIN/FMAX (cuts on the scaled error / frequency),
JUMP (toggle pairs → ``-tim_jump N`` flag, mirroring the reference's
jump-flag behavior), TRACK/INFO (ignored).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class TimTOA:
    """One parsed TOA line, host-side."""

    mjd_str: str  # full-precision decimal string, scale = site clock (UTC)
    freq_mhz: float
    error_us: float
    obs: str
    name: str = ""
    flags: Dict[str, str] = field(default_factory=dict)


_COMMANDS = {
    "FORMAT", "MODE", "INCLUDE", "SKIP", "NOSKIP", "END", "TIME",
    "EFAC", "EQUAD", "EMIN", "EMAX", "FMIN", "FMAX", "JUMP", "PHASE",
    "TRACK", "INFO",
}


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def _parse_format1_line(parts: List[str]) -> Optional[TimTOA]:
    # name freq mjd error site [-flag value]...
    if len(parts) < 5:
        return None
    name, freq, mjd, err, site = parts[:5]
    if not (_is_number(freq) and _is_number(mjd) and _is_number(err)):
        return None
    flags: Dict[str, str] = {}
    i = 5
    while i < len(parts):
        tok = parts[i]
        if tok.startswith("-") and not _is_number(tok):
            key = tok[1:]
            nxt = parts[i + 1] if i + 1 < len(parts) else None
            # a following token that itself looks like a flag means this
            # flag is value-less
            if nxt is not None and not (nxt.startswith("-")
                                        and not _is_number(nxt)):
                flags[key] = nxt
                i += 2
            else:
                flags[key] = ""
                i += 1
        else:
            i += 1  # stray token; tolerated like the reference
    return TimTOA(mjd_str=mjd, freq_mhz=float(freq), error_us=float(err),
                  obs=site, name=name, flags=flags)


def _parse_princeton_line(line: str) -> Optional[TimTOA]:
    """TEMPO Princeton format: observatory code in column 0, then
    fixed columns — name(2:15) freq(15:24) MJD(24:44) err(44:53)
    dmcorr(68:78). Parsed leniently by token position within slices.
    """
    if len(line) < 44:
        return None
    obs = line[0]
    name = line[1:15].strip()
    freq = line[15:24].strip()
    mjd = line[24:44].strip().replace(" ", "")
    err = line[44:53].strip()
    if not (freq and mjd and err):
        return None
    if not (_is_number(freq) and _is_number(mjd) and _is_number(err)):
        return None
    return TimTOA(mjd_str=mjd, freq_mhz=float(freq), error_us=float(err),
                  obs=obs, name=name)


def _parse_itoa_line(line: str) -> Optional[TimTOA]:
    """ITOA column format (detected by the TOA decimal point at
    column 15): name(1:2), blanks(3:9), MJD(10:28), error-us(29:34),
    freq-MHz(35:45), DM correction pc/cm^3 (46:55, recorded as the
    ``ddm`` flag), 2-char observatory code(58:59). Goes beyond the
    reference here: its parse_TOA_line raises 'not implemented' on
    ITOA lines."""
    if len(line) < 59 or line[14:15] != ".":
        return None
    if line[2:9].strip():  # cols 3-9 must be blank in ITOA
        return None
    name = line[0:2].strip()
    mjd = line[9:28].strip().replace(" ", "")
    err = line[28:34].strip()
    freq = line[34:45].strip()
    ddm = line[45:55].strip()
    obs = line[57:59].strip()
    if not (mjd and err and freq and obs):
        return None
    if not (_is_number(mjd) and _is_number(err) and _is_number(freq)):
        return None
    toa = TimTOA(mjd_str=mjd, freq_mhz=float(freq),
                 error_us=float(err), obs=obs, name=name)
    if ddm and _is_number(ddm) and float(ddm) != 0.0:
        toa.flags["ddm"] = ddm
    return toa


def _parse_parkes_line(line: str) -> Optional[TimTOA]:
    """TEMPO Parkes column format (detected by a blank first column
    and a decimal point at column 41): name(1:25), freq-MHz(25:34),
    MJD(34:55), phase offset(55:63), error-us(63:71), 1-char
    observatory(79). The MJD field is already one decimal string."""
    if len(line) < 80 or not line.startswith(" ") \
            or line[41:42] != ".":
        return None
    name = line[1:25].strip()
    freq = line[25:34].strip()
    mjd = line[34:55].strip().replace(" ", "")
    err = line[63:71].strip()
    obs = line[79:80].strip()
    if not (freq and mjd and err and obs):
        return None
    if not (_is_number(freq) and _is_number(mjd) and _is_number(err)):
        return None
    phoff = line[55:63].strip()
    if phoff and _is_number(phoff) and float(phoff) != 0.0:
        # a phase offset shifts the TOA by phoff*P0, which a parser
        # cannot apply (it needs the model's period). The reference
        # raises for exactly this reason — silent mis-timing otherwise
        raise ValueError(
            f"nonzero phase offset {phoff} in Parkes-format TOA line "
            f"is not supported (matches the reference): {line!r}")
    return TimTOA(mjd_str=mjd, freq_mhz=float(freq),
                  error_us=float(err), obs=obs, name=name)


def parse_tim(source, _depth: int = 0,
              _jump_base: int = 0) -> List[TimTOA]:
    """Parse a .tim file (path, file object, or literal multi-line string).

    INCLUDE is followed relative to the including file's directory.
    """
    state = _fresh_state()
    state["jump_count"] = _jump_base
    return _parse_tim_stream(source, state, _depth=_depth)


def _fresh_state() -> dict:
    """Command state of the expanded line stream. ONE dict is shared
    by the whole INCLUDE tree: every command (FORMAT, TIME, PHASE,
    EFAC/EQUAD, EMIN/EMAX/FMIN/FMAX, SKIP, JUMP toggling) is a
    property of the linear stream exactly as in the reference's
    single loop — a command inside an INCLUDEd file stays in force
    after the include returns."""
    return {
        "skipping": False,
        "fmt": "Unknown",  # FORMAT 1 switches later lines to TEMPO2
        "time_offset_s": 0.0,
        "phase_turns": 0.0,
        "efac": 1.0,
        "equad_us": 0.0,
        "emin_us": None, "emax_us": None,
        "fmin_mhz": None, "fmax_mhz": None,
        "jump_active": False,
        # jump ids number ACROSS include boundaries: physically
        # distinct JUMP blocks must not share a -tim_jump id (that
        # would merge them into one fitted parameter)
        "jump_count": 0,
        "ended": False,  # END terminates the WHOLE stream, not just
        # the file it appears in (an END inside an include stops the
        # includer too)
    }


def _parse_tim_stream(source, st: dict, _depth: int = 0):
    """parse_tim worker: one file/stream of the INCLUDE tree, sharing
    the command state ``st`` (see _fresh_state).

    **EMIN/EMAX cut ordering (intentional, ISSUE 10 satellite)**:
    the error cuts are applied to the SCALED uncertainty — after the
    scoped EFAC multiply and EQUAD quadrature add — not to the raw
    column value. Rationale: the cut then sees exactly the
    uncertainty the fit will see, so "drop TOAs worse than X" means
    what it says under any in-file rescaling. TEMPO-parity caveat:
    classic TEMPO applies EMIN/EMAX to the RAW quoted error before
    its own scaling, so a .tim file combining EFAC/EQUAD with
    EMIN/EMAX can select a (slightly) different TOA subset here than
    under TEMPO — files that keep the cuts ahead of any EFAC/EQUAD
    command in the stream are unaffected (the scale factors are
    still 1 when the cut state is set, and both orderings see raw ==
    scaled for TOAs parsed before the first scaling command).
    FMIN/FMAX have no such subtlety (frequency is never rescaled)."""
    from pint_tpu.io.par import resolve_source

    lines, base_dir = resolve_source(source, kind="tim")

    toas: List[TimTOA] = []

    for raw in lines:
        line = raw.rstrip("\n")
        stripped = line.strip()
        if not stripped:
            continue
        if stripped.startswith(("#", "C ", "CC ")) or stripped in ("C", "CC"):
            continue
        parts = stripped.split()
        head = parts[0].upper()

        # inside SKIP...NOSKIP, commands are inert too (only NOSKIP exits)
        if st["skipping"] and head != "NOSKIP":
            continue

        if head in _COMMANDS:
            if head == "SKIP":
                st["skipping"] = True
            elif head == "NOSKIP":
                st["skipping"] = False
            elif head == "END":
                st["ended"] = True
                break
            elif head == "INCLUDE" and len(parts) > 1:
                if _depth > 10:
                    raise RecursionError("INCLUDE nesting too deep")
                inc = parts[1]
                if not os.path.isabs(inc):
                    inc = os.path.join(base_dir, inc)
                toas.extend(_parse_tim_stream(inc, st,
                                              _depth=_depth + 1))
                if st["ended"]:
                    break
            elif head == "TIME" and len(parts) > 1:
                st["time_offset_s"] += float(parts[1])
            elif head == "PHASE" and len(parts) > 1:
                # accumulated phase offset [turns] applied to later
                # TOAs via the -padd flag, which Residuals adds to
                # the phase residual (reference: PHASE command ->
                # padd flag -> calc_phase_resids)
                st["phase_turns"] += float(parts[1])
            elif head == "EFAC" and len(parts) > 1:
                st["efac"] = float(parts[1])
            elif head == "EQUAD" and len(parts) > 1:
                st["equad_us"] = float(parts[1])
            elif head == "EMIN" and len(parts) > 1:
                st["emin_us"] = float(parts[1])
            elif head == "EMAX" and len(parts) > 1:
                st["emax_us"] = float(parts[1])
            elif head == "FMIN" and len(parts) > 1:
                st["fmin_mhz"] = float(parts[1])
            elif head == "FMAX" and len(parts) > 1:
                st["fmax_mhz"] = float(parts[1])
            elif head == "JUMP":
                st["jump_active"] = not st["jump_active"]
                if st["jump_active"]:
                    st["jump_count"] += 1
            elif head == "FORMAT" and len(parts) > 1:
                st["fmt"] = "Tempo2" if parts[1] == "1" else "Unknown"
            # MODE/TRACK/INFO: recorded implicitly or ignored
            continue

        # per-line format detection (the reference's _toa_format):
        # after a FORMAT 1 command every line is TEMPO2-tokenized;
        # otherwise the Parkes column signature is checked FIRST (a
        # Parkes line tokenizes numerically and would be swallowed by
        # the free-form parser), then free-form/Princeton, then ITOA
        # (detected by its TOA decimal point in column 15, index 14)
        if st["fmt"] == "Tempo2":
            toa = _parse_format1_line(parts)
        elif line.startswith(" ") and line[41:42] == ".":
            toa = _parse_parkes_line(line)
        else:
            toa = None
            itoa_sig = line[14:15] == "." and not line[2:9].strip()
            if itoa_sig:
                # ITOA column signature, checked before free-form: a
                # real ITOA line tokenizes numerically and the
                # free-form parser would mis-assign its fields. On a
                # near-miss (signature matches but the columns don't
                # parse as ITOA) fall THROUGH to free-form — e.g. a
                # short-name free-form line whose frequency decimal
                # point happens to land in column 15.
                toa = _parse_itoa_line(line)
                fell_through = toa is None
            else:
                fell_through = False
            if toa is None:
                toa = _parse_format1_line(parts)
            if toa is None:
                toa = _parse_princeton_line(line)
            if toa is not None and fell_through:
                # ITOA-signature line swallowed by a fallback parser:
                # only accept it when the resulting MJD is plausible.
                # A truncated/misaligned ITOA line tokenizes
                # numerically with SWAPPED fields (verified, ADVICE
                # round 5: a 57-char ITOA-like line free-form-parsed
                # with mjd='5.00', freq=50123.88) — an implausible
                # MJD is that swap, not a real TOA, and must fail at
                # the parse site instead of poisoning the dataset.
                try:
                    mjd_f = float(toa.mjd_str)
                except ValueError:
                    mjd_f = float("nan")
                if not (15000.0 <= mjd_f <= 100000.0):
                    raise ValueError(
                        f"ambiguous ITOA-like line (free-form "
                        f"fallback produced implausible MJD "
                        f"{toa.mjd_str!r} — truncated or misaligned "
                        f"ITOA columns?): {line!r}")
        if toa is None:
            raise ValueError(f"unparseable TOA line: {line!r}")
        if st["time_offset_s"] != 0.0:
            toa.flags["to"] = repr(st["time_offset_s"])
        if st["phase_turns"] != 0.0:
            toa.flags["padd"] = repr(st["phase_turns"])
        if st["efac"] != 1.0:
            toa.error_us *= st["efac"]
        if st["equad_us"] != 0.0:
            toa.error_us = (toa.error_us ** 2
                            + st["equad_us"] ** 2) ** 0.5
        # EMIN/EMAX/FMIN/FMAX cuts apply to the SCALED error, after
        # the scoped EFAC/EQUAD (reference command semantics: the cut
        # sees what the fit would see)
        if st["emin_us"] is not None and toa.error_us < st["emin_us"]:
            continue
        if st["emax_us"] is not None and toa.error_us > st["emax_us"]:
            continue
        if st["fmin_mhz"] is not None \
                and toa.freq_mhz < st["fmin_mhz"]:
            continue
        if st["fmax_mhz"] is not None \
                and toa.freq_mhz > st["fmax_mhz"]:
            continue
        if st["jump_active"]:
            toa.flags.setdefault("tim_jump", str(st["jump_count"]))
        toas.append(toa)
    return toas


def write_tim(path_or_file, toas: List[TimTOA], comment: str = "") -> None:
    """Write TOAs in TEMPO2 FORMAT 1 (round-trips through parse_tim)."""
    own = not hasattr(path_or_file, "write")
    f = open(path_or_file, "w") if own else path_or_file
    try:
        f.write("FORMAT 1\n")
        if comment:
            for c in comment.splitlines():
                f.write(f"C {c}\n")
        for t in toas:
            name = t.name or "unk"
            flags = "".join(
                f" -{k} {v}" for k, v in sorted(t.flags.items()) if v != ""
            )
            f.write(
                f"{name} {t.freq_mhz:.6f} {t.mjd_str} "
                f"{t.error_us:.3f} {t.obs}{flags}\n"
            )
    finally:
        if own:
            f.close()
