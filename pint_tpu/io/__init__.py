"""Host-side file ingestion: .par and .tim microformats.

Pure Python, no device code — parsing happens once, on the host, and
produces plain data that the model/TOA layers turn into device arrays
(reference: src/pint/models/model_builder.py parse_parfile,
src/pint/toa.py .tim parsing).
"""

from pint_tpu.io.par import parse_parfile, ParfileLine
from pint_tpu.io.tim import parse_tim, write_tim, TimTOA

__all__ = [
    "parse_parfile",
    "ParfileLine",
    "parse_tim",
    "write_tim",
    "TimTOA",
]
