"""Wideband TOA support: per-TOA DM measurements and joint residuals.

Reference: src/pint/residuals.py (WidebandTOAResiduals, DMResiduals,
CombinedResiduals) and the ``-pp_dm``/``-pp_dme`` tim-file flag
convention (SURVEY.md Appendix A.7: wideband TOAs carry the measured DM
channel and its uncertainty as flags).

The wideband fitter (pint_tpu.wideband_fitter.WidebandTOAFitter) stacks
[time-residual; DM-residual] vectors and the corresponding
block-diagonal design matrix, then reuses the GLS kernel unchanged.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["get_wideband_dm", "has_wideband_dm", "DMResiduals",
           "CombinedResiduals", "WidebandTOAResiduals"]


def get_wideband_dm(toas) -> Tuple[np.ndarray, np.ndarray]:
    """(dm, dm_error) [pc/cm^3] from -pp_dm/-pp_dme flags; raises when
    any TOA lacks the DM channel (reference: TOAs.get_dms /
    WidebandTOAResiduals input contract)."""
    dm = toas.get_flag_value("pp_dm", as_type=float)
    dme = toas.get_flag_value("pp_dme", as_type=float)
    if any(v is None for v in dm):
        missing = sum(1 for v in dm if v is None)
        raise ValueError(
            f"{missing}/{toas.ntoas} TOAs lack -pp_dm wideband flags")
    if any(v is None for v in dme):
        missing = sum(1 for v in dme if v is None)
        raise ValueError(
            f"{missing}/{toas.ntoas} TOAs have -pp_dm but no -pp_dme "
            "uncertainty flag")
    return (np.array(dm, dtype=np.float64),
            np.array(dme, dtype=np.float64))


def has_wideband_dm(toas) -> bool:
    return all(v is not None
               for v in toas.get_flag_value("pp_dm"))


class DMResiduals:
    """DM-channel residuals: measured DM (flags) minus model DM value at
    each TOA (reference: residuals.DMResiduals)."""

    def __init__(self, toas, model, subtract_mean: bool = False):
        self.toas = toas
        self.model = model
        self.subtract_mean = subtract_mean
        self._resids: Optional[np.ndarray] = None

    def model_dm(self) -> np.ndarray:
        """Model DM at each TOA [pc/cm^3], aggregated over every
        component with a DM contribution (DM polynomial, DMX, DMJUMP
        with the reference's -DMJUMP model-side sign, solar wind,
        DMWaveX) via the single traced dm function
        (TimingModel.build_dm_fn)."""
        return self.model.total_dm(self.toas)

    def calc_resids(self) -> np.ndarray:
        measured, _ = get_wideband_dm(self.toas)
        r = measured - self.model_dm()
        if self.subtract_mean:
            err = self.dm_errors
            w = 1.0 / err ** 2
            r = r - np.sum(r * w) / np.sum(w)
        return r

    @property
    def resids(self) -> np.ndarray:
        if self._resids is None:
            self._resids = self.calc_resids()
        return self._resids

    @property
    def dm_errors(self) -> np.ndarray:
        """Scaled (DMEFAC/DMEQUAD) DM uncertainties."""
        return self.model.scaled_dm_uncertainty(self.toas)

    @property
    def chi2(self) -> float:
        return float(np.sum((self.resids / self.dm_errors) ** 2))


class CombinedResiduals:
    """Stack of heterogeneous residual channels with a combined chi2
    (reference: residuals.CombinedResiduals)."""

    def __init__(self, residual_objs):
        self.residual_objs = list(residual_objs)

    @property
    def chi2(self) -> float:
        return float(sum(r.chi2 for r in self.residual_objs))

    @property
    def resids(self) -> np.ndarray:
        parts = []
        for r in self.residual_objs:
            v = getattr(r, "time_resids", None)
            parts.append(np.asarray(v if v is not None else r.resids))
        return np.concatenate(parts)


class WidebandTOAResiduals(CombinedResiduals):
    """Joint TOA + DM residuals of a wideband data set (reference:
    residuals.WidebandTOAResiduals): .toa is the phase/time channel,
    .dm the DM-measurement channel."""

    def __init__(self, toas, model, subtract_mean=None,
                 track_mode=None):
        from pint_tpu.residuals import Residuals

        self.toas = toas
        self.model = model
        self.toa = Residuals(toas, model, subtract_mean=subtract_mean,
                             track_mode=track_mode)
        self.dm = DMResiduals(toas, model)
        super().__init__([self.toa, self.dm])

    @property
    def dof(self) -> int:
        return 2 * self.toas.ntoas - len(self.model.free_params) - 1

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof


