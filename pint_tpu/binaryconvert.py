"""Binary-model parameterization conversion.

Reference: src/pint/binaryconvert.py (convert_binary). Supported
conversions mirror the reference's core set:

    ELL1  <-> DD / DDS / DDH / BT      (EPS1/EPS2/TASC <-> ECC/OM/T0)
    ELL1  <-> ELL1H                    (M2/SINI <-> H3/STIG)
    DD    <-> DDS                      (SINI <-> SHAPMAX)
    DD    <-> DDH                      (M2/SINI <-> H3/STIG)

The converted model is a new TimingModel sharing every non-binary
component; uncertainties are propagated to first order where the map
is nonlinear (ECC/OM from EPS1/EPS2).
"""

from __future__ import annotations

import copy

import numpy as np

from pint_tpu.models.timing_model import TimingModel

__all__ = ["convert_binary"]

TSUN = 4.925490947e-6
SECS_PER_DAY = 86400.0


def _binary_component(model: TimingModel):
    for name, comp in model.components.items():
        if name.startswith("Binary"):
            return name, comp
    raise ValueError("model has no binary component")


def _get(comp, name, default=None):
    p = comp.params.get(name)
    return p.value if p is not None and p.value is not None else default


def _mean_motion(comp):
    """Orbital angular frequency [rad/day] from PB or FB0."""
    pb = _get(comp, "PB")
    if pb is not None:
        return 2.0 * np.pi / pb
    fb0 = _get(comp, "FB0")
    if fb0 is None:
        raise ValueError("binary model has neither PB nor FB0")
    return 2.0 * np.pi * fb0 * SECS_PER_DAY


def _h3stig_from_m2sini(m2, sini):
    cosi = np.sqrt(1.0 - sini ** 2)
    stig = sini / (1.0 + cosi)
    h3 = TSUN * m2 * stig ** 3
    return h3, stig


def _m2sini_from_h3stig(h3, stig):
    sini = 2.0 * stig / (1.0 + stig ** 2)
    m2 = h3 / (TSUN * stig ** 3)
    return m2, sini


def convert_binary(model: TimingModel, target: str) -> TimingModel:
    """Return a copy of ``model`` with its binary component converted
    to the ``target`` parameterization (reference:
    binaryconvert.convert_binary)."""
    from pint_tpu.models.timing_model import component_types

    by_upper = {c[len("Binary"):].upper(): c for c in component_types
                if c.startswith("Binary")}
    cls_name = by_upper.get(target.upper())
    if cls_name is None:
        raise ValueError(f"unknown binary model {target!r}")
    src_name, src = _binary_component(model)
    if src_name == cls_name:
        return copy.deepcopy(model)
    if src_name == "BinaryDDGR":
        raise ValueError(
            "cannot convert from DDGR: its post-Keplerian parameters "
            "are mass-derived, not explicit — evaluate them and build "
            "a DD model directly if needed")

    new = copy.deepcopy(model)
    new.remove_component(src_name)
    dst = component_types[cls_name]()
    new.add_component(dst, setup=False)

    # ---- shared Keplerian/secular/Shapiro params pass through -------
    for name in ("PB", "PBDOT", "A1", "A1DOT", "M2", "SINI", "GAMMA",
                 "ECC", "EDOT", "OM", "OMDOT", "T0", "TASC", "EPS1",
                 "EPS2", "EPS1DOT", "EPS2DOT", "H3", "H4", "STIG",
                 "SHAPMAX", "DR", "DTH", "A0", "B0", "KIN", "KOM",
                 "MTOT", "XOMDOT", "XPBDOT", "LNEDOT"):
        if name in src.params and name in dst.params:
            sp = src.params[name]
            dp = dst.params[name]
            dp.value = sp.value
            dp.frozen = sp.frozen
            dp.uncertainty = sp.uncertainty
            if sp._dd is not None:
                dp.set_dd(sp._dd)
    # FB series passes through when both sides support it
    for name in getattr(src, "fb_terms", []):
        if name in src.params:
            sp = src.params[name]
            dst.add_fb_term(int(name[2:]), value=sp.value,
                            frozen=sp.frozen)

    src_is_ell1 = "EPS1" in src.params
    dst_is_ell1 = "EPS1" in dst.params

    RAD_PER_S_TO_DEG_PER_YR = np.degrees(1.0) * 86400.0 * 365.25

    if src_is_ell1 and not dst_is_ell1:
        # ELL1 -> eccentric: ECC/OM/T0 from EPS1/EPS2/TASC
        eps1 = _get(src, "EPS1", 0.0)
        eps2 = _get(src, "EPS2", 0.0)
        ecc = float(np.hypot(eps1, eps2))
        om = float(np.arctan2(eps1, eps2)) % (2.0 * np.pi)
        nb = _mean_motion(src)  # rad/day
        tasc = _get(src, "TASC")
        dst.params["ECC"].value = ecc
        dst.params["OM"].value = np.degrees(om)
        dst.params["T0"].value = tasc + om / nb
        # secular drifts: eps1 = e sin w, eps2 = e cos w =>
        # edot = (eps1 d1 + eps2 d2)/e, wdot = (d1 eps2 - d2 eps1)/e^2
        d1 = _get(src, "EPS1DOT", 0.0)
        d2 = _get(src, "EPS2DOT", 0.0)
        if (d1 or d2) and ecc > 0:
            if "EDOT" in dst.params:
                dst.params["EDOT"].value = (eps1 * d1 + eps2 * d2) / ecc
            if "OMDOT" in dst.params:
                dst.params["OMDOT"].value = float(
                    (d1 * eps2 - d2 * eps1) / ecc ** 2
                    * RAD_PER_S_TO_DEG_PER_YR)
        # first-order uncertainty propagation
        s1 = src.params["EPS1"].uncertainty
        s2 = src.params["EPS2"].uncertainty
        if s1 is not None and s2 is not None and ecc > 0:
            decc = np.hypot(eps1 * s1, eps2 * s2) / ecc
            dom = np.hypot(eps2 * s1, eps1 * s2) / ecc ** 2
            dst.params["ECC"].uncertainty = float(decc)
            dst.params["OM"].uncertainty = float(np.degrees(dom))
            dst.params["T0"].uncertainty = float(dom / nb)
        for nm in ("ECC", "OM", "T0"):
            dst.params[nm].frozen = src.params["EPS1"].frozen
    elif dst_is_ell1 and not src_is_ell1:
        # eccentric -> ELL1 (valid for small e)
        ecc = _get(src, "ECC", 0.0)
        om = np.radians(_get(src, "OM", 0.0))
        t0 = _get(src, "T0")
        nb = _mean_motion(src)
        if ecc > 0.01:
            import warnings

            warnings.warn(f"ELL1 conversion at e={ecc:.3g} > 0.01: "
                          "O(e^2) timing errors may be significant")
        dst.params["EPS1"].value = float(ecc * np.sin(om))
        dst.params["EPS2"].value = float(ecc * np.cos(om))
        dst.params["TASC"].value = t0 - om / nb
        edot = _get(src, "EDOT", 0.0)
        omdot = _get(src, "OMDOT", 0.0) / RAD_PER_S_TO_DEG_PER_YR
        if (edot or omdot):
            d1 = edot * np.sin(om) + ecc * np.cos(om) * omdot
            d2 = edot * np.cos(om) - ecc * np.sin(om) * omdot
            if "EPS1DOT" in dst.params:
                dst.params["EPS1DOT"].value = float(d1)
                dst.params["EPS2DOT"].value = float(d2)
            elif "LNEDOT" in dst.params and ecc > 0:
                # ELL1k: exact rotation + log-eccentricity rate
                dst.params["OMDOT"].value = _get(src, "OMDOT", 0.0)
                dst.params["LNEDOT"].value = float(edot / ecc)
        se = src.params["ECC"].uncertainty
        so = src.params["OM"].uncertainty
        if se is not None and so is not None:
            so_r = np.radians(so)
            dst.params["EPS1"].uncertainty = float(np.hypot(
                np.sin(om) * se, ecc * np.cos(om) * so_r))
            dst.params["EPS2"].uncertainty = float(np.hypot(
                np.cos(om) * se, ecc * np.sin(om) * so_r))
            dst.params["TASC"].uncertainty = float(so_r / nb)
        for nm in ("EPS1", "EPS2", "TASC"):
            dst.params[nm].frozen = src.params["ECC"].frozen

    if src_is_ell1 and dst_is_ell1:
        # within the ELL1 family: map linear eps drifts <-> ELL1k's
        # exact (OMDOT, LNEDOT) rotation parameters
        eps1 = _get(src, "EPS1", 0.0)
        eps2 = _get(src, "EPS2", 0.0)
        ecc2 = eps1 ** 2 + eps2 ** 2
        d1 = _get(src, "EPS1DOT", 0.0)
        d2 = _get(src, "EPS2DOT", 0.0)
        if (d1 or d2) and ecc2 > 0 and "LNEDOT" in dst.params:
            dst.params["OMDOT"].value = float(
                (d1 * eps2 - d2 * eps1) / ecc2
                * RAD_PER_S_TO_DEG_PER_YR)
            dst.params["LNEDOT"].value = float(
                (eps1 * d1 + eps2 * d2) / ecc2)
        if "LNEDOT" in src.params and "EPS1DOT" in dst.params:
            omdot = _get(src, "OMDOT", 0.0) / RAD_PER_S_TO_DEG_PER_YR
            lnedot = _get(src, "LNEDOT", 0.0)
            if omdot or lnedot:
                dst.params["EPS1DOT"].value = float(
                    lnedot * eps1 + eps2 * omdot)
                dst.params["EPS2DOT"].value = float(
                    lnedot * eps2 - eps1 * omdot)

    # ---- Shapiro reparameterizations --------------------------------
    if "H3" in dst.params and "H3" not in src.params:
        m2, sini = _get(src, "M2"), _get(src, "SINI")
        if "SHAPMAX" in src.params and _get(src, "SHAPMAX") is not None:
            sini = 1.0 - np.exp(-_get(src, "SHAPMAX"))
        if m2 is not None and sini is not None:
            h3, stig = _h3stig_from_m2sini(m2, sini)
            dst.params["H3"].value = float(h3)
            dst.params["STIG"].value = float(stig)
            dst.params["H3"].frozen = src.params["M2"].frozen
            dst.params["STIG"].frozen = src.params["M2"].frozen
    if "M2" in dst.params and "M2" not in src.params:
        h3, stig = _get(src, "H3"), _get(src, "STIG")
        if stig is None and h3 and _get(src, "H4") is not None:
            # orthometric ratio: STIG = H4/H3 (Freire & Wex 2010)
            stig = _get(src, "H4") / h3
        if h3 is not None and stig is not None:
            m2, sini = _m2sini_from_h3stig(h3, stig)
            dst.params["M2"].value = float(m2)
            if "SINI" in dst.params:
                dst.params["SINI"].value = float(sini)
                dst.params["SINI"].frozen = src.params["H3"].frozen
            elif "SHAPMAX" in dst.params:
                dst.params["SHAPMAX"].value = float(-np.log(1.0 - sini))
                dst.params["SHAPMAX"].frozen = src.params["H3"].frozen
            dst.params["M2"].frozen = src.params["H3"].frozen
    if "SINI" in dst.params and _get(dst, "SINI") is None and \
            "KIN" in src.params and _get(src, "KIN") is not None:
        dst.params["SINI"].value = float(np.sin(np.radians(
            _get(src, "KIN"))))
        dst.params["SINI"].frozen = src.params["KIN"].frozen
    if "SHAPMAX" in dst.params and "SINI" in src.params and \
            _get(src, "SINI") is not None:
        dst.params["SHAPMAX"].value = float(
            -np.log(1.0 - _get(src, "SINI")))
        dst.params["SHAPMAX"].frozen = src.params["SINI"].frozen
    if "SINI" in dst.params and "SHAPMAX" in src.params and \
            _get(src, "SHAPMAX") is not None:
        dst.params["SINI"].value = float(
            1.0 - np.exp(-_get(src, "SHAPMAX")))
        dst.params["SINI"].frozen = src.params["SHAPMAX"].frozen

    dst.setup()
    dst.validate()
    new.invalidate_cache()
    return new
