"""Phase jumps: per-TOA-subset constant offsets (JUMP mask parameters).

Reference: src/pint/models/jump.py (PhaseJump). JUMP values are in
seconds; the phase contribution is −JUMP·F0 on the selected TOAs
(matching the reference's jump_phase sign convention: a positive JUMP
makes the selected TOAs arrive "later").
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.parameter import maskParameter
from pint_tpu.models.timing_model import PhaseComponent
from pint_tpu.ops.dd import DD


class PhaseJump(PhaseComponent):
    """Per-TOA-subset constant offsets (reference:
    src/pint/models/jump.py PhaseJump): each JUMPn maskParameter is
    seconds on its selected TOAs; phase contribution is −JUMP·F0
    (the reference's jump_phase sign convention)."""

    category = "phase_jump"

    def __init__(self):
        super().__init__()
        self.jumps: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"JUMP*": parse_unit("s")}

    def add_jump(self, index=None, key=None, key_value=(), value=0.0,
                 frozen=True, uncertainty=None):
        if index is None:
            # one past the highest used index — the count would land
            # on an existing slot when indices are non-contiguous
            index = max((self.params[n].index for n in self.jumps),
                        default=0) + 1
        p = maskParameter("JUMP", index=index, key=key,
                          key_value=key_value, value=value, frozen=frozen,
                          uncertainty=uncertainty, units="s")
        self.add_param(p)
        self.jumps.append(p.name)
        return p

    def tim_jumps_to_params(self, toas) -> list:
        """Create one free JUMP parameter per distinct ``-tim_jump``
        flag value found on the TOAs (the flags the tim parser writes
        for JUMP/JUMP blocks), skipping ids already covered by an
        existing -tim_jump JUMP parameter (reference:
        PhaseJump.jump_flags_to_params). Returns the new parameters."""
        ids = sorted({f["tim_jump"] for f in toas.flags
                      if "tim_jump" in f}, key=str)
        covered = {p.key_value[0] for p in self.get_jump_param_objects()
                   if getattr(p, "key", None) == "-tim_jump"
                   and p.key_value}
        new = []
        for jid in ids:
            if str(jid) in covered:
                continue
            new.append(self.add_jump(key="-tim_jump",
                                     key_value=(str(jid),),
                                     value=0.0, frozen=False))
        if new:
            self.setup()
            parent = getattr(self, "_parent", None)
            if parent is not None:
                parent.invalidate_cache()
        return new

    def setup(self):
        self.jumps = sorted(
            (n for n in self.params if n.startswith("JUMP")),
            key=lambda n: self.params[n].index)

    def get_jump_param_objects(self):
        return [self.params[n] for n in self.jumps]

    def prepare(self, toas, batch, cache, prefix=""):
        for name in self.jumps:
            cache[f"mask_{name}"] = self.params[name].select_mask(
                toas).astype(np.float64)

    def phase(self, pv, batch, cache, ctx, tb):
        total = jnp.zeros_like(batch.freq_mhz)
        f0 = pv["F0"].hi + pv["F0"].lo
        for name in self.jumps:
            total = total + (pv[name].hi + pv[name].lo) * \
                cache[f"mask_{name}"]
        ph = -total * f0
        return DD(ph, jnp.zeros_like(ph))

    def linear_design_names(self):
        return [nm for nm in self.jumps if not self.params[nm].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(phase)/d(JUMPi) = -F0 * mask_i (mirrors phase above; F0
        at the current value — an exact partial)."""
        f0 = pv["F0"].hi + pv["F0"].lo
        return {nm: ("phase",
                     -f0 * jnp.asarray(cache[f"mask_{nm}"]))
                for nm in self.jumps if not self.params[nm].frozen}
