"""Solar-system Shapiro delay: Sun always, planets when PLANET_SHAPIRO.

Reference: src/pint/models/solar_system_shapiro.py (SolarSystemShapiro,
ss_obj_shapiro_delay): Δ = −2·T_obj·ln(r − r·n̂) + const, maximal when
the pulsar passes behind the body. The additive constant (the reference
normalizes by r to keep the log argument dimensionless) is absorbed by
the phase offset and irrelevant to fits.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.timing_model import DelayComponent

# GM_body/c^3 [s] (reference: _ss_mass_sec table)
T_OBJ_S = {
    "sun": 4.925490947e-6,
    "jupiter": 4.70255e-9,
    "saturn": 1.40797e-9,
    "venus": 1.2061e-11,
    "uranus": 2.1501e-10,
    "neptune": 2.5356e-10,
}
# order matches pint_tpu.toa.PLANETS stacking
PLANET_ORDER = ("jupiter", "saturn", "venus", "uranus", "neptune")


def shapiro_delay(obj_pos_ls, psr_dir, t_obj_s):
    """obj_pos_ls: obs→body (.., 3) lt-s; psr_dir: unit SSB→pulsar."""
    r = jnp.sqrt(jnp.sum(obj_pos_ls * obj_pos_ls, axis=-1))
    rcos = jnp.sum(obj_pos_ls * psr_dir, axis=-1)
    return -2.0 * t_obj_s * jnp.log(r - rcos)


class SolarSystemShapiro(DelayComponent):
    """Sun (and optionally planet) Shapiro delay (reference:
    src/pint/models/solar_system_shapiro.py
    SolarSystemShapiro.solar_system_shapiro_delay): −2 T_obj
    ln(r − r·n̂) per body; PLANET_SHAPIRO gates the planet terms as a
    trace static (it is in the compile key)."""

    category = "solar_system_shapiro"

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        n = ctx["psr_dir"]
        total = shapiro_delay(batch.obs_sun_pos, n, T_OBJ_S["sun"])
        # planet positions present in the batch ⇔ PLANET_SHAPIRO was on
        # at ingestion; the model flag decides statically at trace time
        if (self._parent is not None
                and bool(self._parent.PLANET_SHAPIRO.value)
                and batch.obs_planet_pos.shape[0] == len(PLANET_ORDER)):
            for i, name in enumerate(PLANET_ORDER):
                total = total + shapiro_delay(
                    batch.obs_planet_pos[i], n, T_OBJ_S[name])
        return total
