"""TCB <-> TDB par-file conversion.

Reference: src/pint/models/tcb_conversion.py (convert_tcb_tdb) +
scripts/tcb2tdb.py. TCB ticks faster than TDB by the IAU 1991/2006
defining constant L_B; the conversion rescales every dimensionful
parameter by the appropriate power of IFTE_K = 1/(1 - L_B) and maps
epochs through the fixed point T0 (MJD 43144.0003725, the 1977 TAI
origin where TCB = TDB):

    (t_TDB - T0) = (t_TCB - T0) / IFTE_K
    value_TDB    = value_TCB * IFTE_K^n

with n the parameter's effective time dimension (frequency-like: +1
per 1/s; interval-like: -1; see _TIME_DIM). This is the linear-drift
part of the transformation only — exactly what the reference applies —
so converted models are equivalent to ~L_B * (periodic TDB-TCB terms),
well below timing noise.
"""

from __future__ import annotations

import copy
import re
import warnings

from pint_tpu.ops import dd_np

__all__ = ["convert_tcb_tdb", "IFTE_K", "L_B", "T0_MJD"]

L_B = 1.550519768e-8  # IAU 2006 defining constant
IFTE_K = 1.0 / (1.0 - L_B)
T0_MJD = 43144.0003725  # TCB = TDB fixed point

# effective time dimension n: value_TDB = value_TCB * IFTE_K^n
_TIME_DIM = {
    "DM": -1,           # measured dispersion delay is an interval
    "NE_SW": -1,
    "CM": -1,
    "PX": 1,            # ~1/distance, distance in light-time
    "PMRA": 1, "PMDEC": 1, "PMELONG": 1, "PMELAT": 1,  # per time
    "PB": -1,
    "A1": -1,
    "GAMMA": -1,
    "M2": -1,           # masses enter as G m / c^3 [s]
    "MTOT": -1,
    "H3": -1,
    "OMDOT": 1,
    "EDOT": 1,
    "EPS1DOT": 1,
    "EPS2DOT": 1,
    "LNEDOT": 1,
    "WAVE_OM": 1,
    # dimensionless / angle / ratio parameters (listed so the
    # completeness check below knows they are intentionally unscaled)
    "OM": 0, "ECC": 0, "SINI": 0, "EPS1": 0, "EPS2": 0, "A1DOT": 0,
    "PBDOT": 0, "XPBDOT": 0, "STIG": 0, "KIN": 0, "KOM": 0,
    "XOMDOT": 1, "SHAPMAX": 0, "DR": 0, "DTH": 0, "A0": -1, "B0": -1,
    "EFAC": 0, "DMEFAC": 0, "TNCHROMIDX": 0, "SWM": 0,
    "RAJ": 0, "DECJ": 0, "ELONG": 0, "ELAT": 0,  # angles
    "TZRFRQ": 0,  # observing frequency: a label, not a TCB interval
}
_EPOCH_NAMES = ("PEPOCH", "POSEPOCH", "DMEPOCH", "CMEPOCH", "T0",
                "TASC", "TZRMJD", "WXEPOCH", "DMWXEPOCH", "CMWXEPOCH",
                "START", "FINISH")
# prefixed families: (regex, time dimension or callable(index) or
# "epoch")
_PREFIX_DIMS = [
    (re.compile(r"^F(\d+)$"), lambda n: n + 1),
    (re.compile(r"^DM(\d+)$"), lambda n: n - 1),
    (re.compile(r"^CM(\d+)$"), lambda n: n - 1),
    (re.compile(r"^FB(\d+)$"), lambda n: n + 1),
    (re.compile(r"^(GLEP_|DMXR1_|DMXR2_|CMXR1_|CMXR2_|PWEP_|PWSTART_"
                r"|PWSTOP_|SWXR1_|SWXR2_)\d+$"), "epoch"),
    (re.compile(r"^GLF0_\d+$"), 1),
    (re.compile(r"^GLF1_\d+$"), 2),
    (re.compile(r"^GLF2_\d+$"), 3),
    (re.compile(r"^GLF0D_\d+$"), 1),
    (re.compile(r"^GLTD_\d+$"), -1),
    (re.compile(r"^GLPH_\d+$"), 0),
    (re.compile(r"^PWF0_\d+$"), 1),
    (re.compile(r"^PWF1_\d+$"), 2),
    (re.compile(r"^PWF2_\d+$"), 3),
    (re.compile(r"^PWPH_\d+$"), 0),
    (re.compile(r"^DMX_\d+$"), -1),
    (re.compile(r"^CMX_\d+$"), -1),
    (re.compile(r"^SWXDM_\d+$"), -1),
    (re.compile(r"^(WX|DMWX|CMWX)FREQ_\d+$"), 1),
    (re.compile(r"^WX(SIN|COS)_\d+$"), -1),
    (re.compile(r"^DMWX(SIN|COS)_\d+$"), -1),
    (re.compile(r"^CMWX(SIN|COS)_\d+$"), -1),
    (re.compile(r"^FD\d+$"), -1),
    (re.compile(r"^FD\d*JUMP\d+$"), -1),
    (re.compile(r"^FDJUMP\d+$"), -1),
    (re.compile(r"^JUMP\d+$"), -1),
    (re.compile(r"^DMJUMP\d+$"), -1),
    (re.compile(r"^(EQUAD|ECORR)\d+$"), -1),
    (re.compile(r"^(EFAC|DMEFAC|TNEQ|DMEQUAD)\d+$"), 0),
    (re.compile(r"^WAVE\d+$"), -1),
]


def _time_dim(name: str):
    """Time dimension n, the string 'epoch', or None (unclassified)."""
    if name in _EPOCH_NAMES:
        return "epoch"
    if name in _TIME_DIM:
        return _TIME_DIM[name]
    for rx, dim in _PREFIX_DIMS:
        m = rx.match(name)
        if m:
            if dim == "epoch":
                return "epoch"
            return dim(int(m.group(1))) if callable(dim) else dim
    return None


def _map_epoch_dd(p, K_dd_inv):
    """mjd -> T0 + (mjd - T0) * K_dd_inv in dd arithmetic (keeps the
    sub-f64 epoch residue MJDParameter maintains)."""
    t0 = dd_np.dd(T0_MJD)
    x = dd_np.sub(p.dd, t0)
    x = dd_np.mul(x, K_dd_inv)
    new = dd_np.add(x, t0)
    p.set_dd((float(new[0]), float(new[1])))


def convert_tcb_tdb(model, backwards: bool = False):
    """Return a copy of ``model`` converted TCB->TDB (or TDB->TCB with
    ``backwards``); reference: tcb_conversion.convert_tcb_tdb. Every
    dimensionful parameter — including prefix/mask family members —
    is scaled; unclassified dimensionful-looking parameters trigger a
    warning rather than silent half-conversion."""
    units = (model.UNITS.value or "TDB").upper()
    src, dst = ("TDB", "TCB") if backwards else ("TCB", "TDB")
    if units != src:
        raise ValueError(f"model UNITS is {units}, expected {src}")
    K = 1.0 / IFTE_K if backwards else IFTE_K
    # exact dd factors: (1 - L_B) is exactly 1 + (-L_B) in dd
    one_minus = dd_np.add_f(dd_np.dd(1.0), -L_B)
    inv_one_minus = dd_np.div(dd_np.dd(1.0), one_minus)
    # K_dd multiplies values of positive time dimension; K_dd_inv maps
    # epochs/intervals (forward: intervals shrink by (1-L_B))
    if backwards:
        K_dd, K_dd_inv = one_minus, inv_one_minus
    else:
        K_dd, K_dd_inv = inv_one_minus, one_minus
    new = copy.deepcopy(model)
    unclassified = []
    for comp in new.components.values():
        for name, p in comp.params.items():
            if p.value is None or isinstance(p.value, bool) or \
                    not isinstance(p.value, (int, float)):
                continue
            n = _time_dim(name)
            if n == "epoch":
                _map_epoch_dd(p, K_dd_inv)
                continue
            if n is None:
                unclassified.append(name)
                continue
            if n:
                # scale in dd so long-precision values (F0 given to 20
                # digits) keep their sub-ulp residue
                f = K_dd_inv if n < 0 else K_dd
                scaled = p.dd
                for _ in range(abs(n)):
                    scaled = dd_np.mul(scaled, f)
                p.set_dd((float(scaled[0]), float(scaled[1])))
                if p.uncertainty is not None:
                    p.uncertainty = p.uncertainty * K ** n
    if unclassified:
        skipped = [nm for nm in unclassified
                   if nm not in ("NTOA", "CHI2", "SIFUNC")]
        if skipped:
            warnings.warn(
                "TCB conversion left these parameters unscaled "
                f"(unknown time dimension): {sorted(set(skipped))}")
    new.UNITS.value = dst
    new.invalidate_cache()
    return new
