"""Model builder: .par file → TimingModel.

Reference: src/pint/models/model_builder.py (ModelBuilder, get_model,
get_model_and_toas, parse_parfile). Routing: each registered Component
class contributes its parameter names/aliases to an index; prefixed
families (F2.., DMX_0001, GL*_n) and mask families (JUMP, EFAC...) are
recognized by pattern and materialized on their owning component.
"""

from __future__ import annotations

import re
import warnings
from typing import Dict, List, Optional

from pint_tpu.io.par import ParfileLine, parse_parfile
from pint_tpu.models.parameter import (
    maskParameter,
    prefixParameter,
    split_prefixed_name,
)
from pint_tpu.models.timing_model import (
    Component,
    MiscParams,
    TimingModel,
    component_types,
)

# components always present (reference: ModelBuilder default components)
DEFAULT_COMPONENTS = ["Spindown"]

# par key → (component class name, method) for prefix families
_F_RE = re.compile(r"^F(\d+)$")
_DM_RE = re.compile(r"^DM(\d+)$")
_DMX_RE = re.compile(r"^(DMX_|DMXR1_|DMXR2_)(\d+)$")
_BTX_RE = re.compile(r"^(T0X_|A1X_|XR1_|XR2_)(\d+)$")
_FB_RE = re.compile(r"^FB(\d+)$")

# mask-parameter families → owning component class (extended as the
# component zoo grows; reference: maskParameter registry)
MASK_FAMILIES: Dict[str, str] = {
    "JUMP": "PhaseJump",
    "DMJUMP": "DispersionJump",
    "EFAC": "ScaleToaError",
    "T2EFAC": "ScaleToaError",
    "EQUAD": "ScaleToaError",
    "T2EQUAD": "ScaleToaError",
    "TNEQ": "ScaleToaError",
    "ECORR": "EcorrNoise",
    "TNECORR": "EcorrNoise",
    "DMEFAC": "ScaleDmError",
    "DMEQUAD": "ScaleDmError",
    "FDJUMP": "FDJump",
}
# arbitrary-order FD jumps (FD1JUMP, FD2JUMP, ...) route via regex
_FDJUMP_RE = re.compile(r"^FD(\d+)JUMP$")
# canonical mask param name per alias
MASK_CANONICAL = {"T2EFAC": "EFAC", "T2EQUAD": "EQUAD", "TNECORR": "ECORR"}

# canonical units per mask family (the par-file convention; kept in
# sync with the components' own add_noise_param declarations and
# checked by the build-time unit discipline)
MASK_UNITS = {"EFAC": "", "EQUAD": "us", "TNEQ": "log10(s)",
              "ECORR": "us", "DMEFAC": "", "DMEQUAD": "pc cm^-3",
              "JUMP": "s", "DMJUMP": "pc cm^-3", "FDJUMP": "s"}

BINARY_COMPONENT_PREFIX = "Binary"


def _build_param_index():
    """name/alias → component class name, from registry templates.
    Prefixed-family members beyond the template's first instance
    (GLEP_2, FD3, WXFREQ_0002...) resolve via their prefix."""
    idx: Dict[str, str] = {}
    for cls_name, cls in component_types.items():
        try:
            tmpl = cls()
        except Exception:
            continue
        for pname, p in tmpl.params.items():
            idx.setdefault(pname, cls_name)
            for a in p.aliases:
                idx.setdefault(a, cls_name)
            prefix = getattr(p, "prefix", None)
            if prefix is None:
                try:
                    prefix, _, _ = split_prefixed_name(pname)
                except ValueError:
                    prefix = None
            if prefix:
                idx.setdefault(prefix, cls_name)
                idx.setdefault(prefix.rstrip("_"), cls_name)
    return idx


def guess_binary_model(keys) -> str:
    """Pick the native binary model implied by a parameter-name set
    (reference: model_builder guess_binary_model; used for TEMPO2
    "BINARY T2" par files, where the T2 superset model dispatches on
    which parameters appear). ``keys``: iterable of UPPERCASE par
    keys. Order matters — the most specific signature wins."""
    keys = set(keys)
    if "KIN" in keys or "KOM" in keys:
        return "DDK"
    if "EPS1" in keys or "EPS2" in keys or "TASC" in keys:
        if "LNEDOT" in keys:
            return "ELL1k"
        return "ELL1H" if "H3" in keys else "ELL1"
    if "MTOT" in keys:
        return "DDGR"
    if "SHAPMAX" in keys:
        return "DDS"
    if "H3" in keys and "STIG" in keys:
        return "DDH"
    if keys & {"SINI", "M2", "OMDOT", "GAMMA"}:
        return "DD"
    return "BT"


class T2BinaryWarning(UserWarning):
    """BINARY T2 par file loaded via guess_binary_model."""


class UnknownParameterWarning(UserWarning):
    pass


class ModelBuilder:
    """One-shot builder; call with parsed par lines."""

    def __init__(self):
        # importing the component modules populates the registry
        import pint_tpu.models.absolute_phase  # noqa: F401
        import pint_tpu.models.astrometry  # noqa: F401
        import pint_tpu.models.dispersion  # noqa: F401
        import pint_tpu.models.jump  # noqa: F401
        import pint_tpu.models.phase_offset  # noqa: F401
        import pint_tpu.models.solar_system_shapiro  # noqa: F401
        import pint_tpu.models.spindown  # noqa: F401
        try:  # optional layers, registered when present
            import pint_tpu.models.noise  # noqa: F401
        except ImportError:
            pass
        try:
            import pint_tpu.models.binary  # noqa: F401
        except ImportError:
            pass
        try:
            import pint_tpu.models.components_extra  # noqa: F401
            import pint_tpu.models.components_tail  # noqa: F401
        except ImportError:
            pass
        self.param_index = _build_param_index()

    def __call__(self, lines: List[ParfileLine], name="") -> TimingModel:
        comps: Dict[str, Component] = {}
        unknown: List[str] = []
        binary_name: Optional[str] = None
        mask_counters: Dict[str, int] = {}

        def get_comp(cls_name: str) -> Component:
            if cls_name not in comps:
                comps[cls_name] = component_types[cls_name]()
            return comps[cls_name]

        for cls_name in DEFAULT_COMPONENTS:
            get_comp(cls_name)

        # BINARY first, regardless of line order: binary parameters
        # (T0, TASC, PB...) exist on several Binary* classes and must
        # route to the instance the BINARY line selects
        for ln in lines:
            if ln.key == "BINARY" and ln.tokens:
                binary_name = ln.tokens[0]
                if binary_name.upper() == "T2":
                    # TEMPO2's generic dispatcher model: choose the
                    # native family from the parameter signature
                    # (reference: guess_binary_model)
                    binary_name = guess_binary_model(
                        {x.key.upper() for x in lines})
                    if binary_name == "DDK":
                        # T2 KIN/KOM are IAU-convention; the DDK
                        # kernel uses DT92 (KIN -> 180-KIN,
                        # KOM -> 90-KOM; same mapping as
                        # t2binary2pint) — loading the raw values
                        # would silently corrupt the Kopeikin terms
                        for x in lines:
                            k = x.key.upper()
                            if k in ("KIN", "KOM") and x.tokens:
                                ref = 180.0 if k == "KIN" else 90.0
                                x.tokens[0] = repr(
                                    ref - float(x.tokens[0]))
                    warnings.warn(
                        f"BINARY T2 interpreted as {binary_name!r} via "
                        f"guess_binary_model"
                        + (" (KIN/KOM converted IAU->DT92)"
                           if binary_name == "DDK" else ""),
                        T2BinaryWarning, stacklevel=2)
                # case-insensitive: the conventional par name for e.g.
                # BinaryELL1k is "ELL1k"
                by_upper = {c.upper(): c for c in component_types}
                # underscore-insensitive: par "BT_piecewise" names
                # class BinaryBTPiecewise
                want = (BINARY_COMPONENT_PREFIX + binary_name).upper()
                cls_name = by_upper.get(want) or by_upper.get(
                    want.replace("_", ""))
                if cls_name is None:
                    raise NotImplementedError(
                        f"binary model {binary_name!r} is not implemented "
                        f"(known: {sorted(c for c in component_types if c.startswith('Binary'))})")
                get_comp(cls_name)

        for ln in lines:
            key, toks = ln.key, ln.tokens
            if key == "BINARY":
                continue
            if key == "UNITS":
                # TCB is accepted here; get_model converts to TDB after
                # the build (reference: allow_tcb conversion path)
                get_comp("MiscParams").UNITS.value = toks[0] if toks else "TDB"
                continue

            # 1a. exact/alias match against already-instantiated
            # components (binary params must land on the selected model)
            matched = False
            for comp in comps.values():
                try:
                    p = _param_by_name_or_alias(comp, key)
                except KeyError:
                    continue
                p.from_tokens(toks)
                matched = True
                break
            if matched:
                continue

            # 1b. exact/alias match against the registry index
            cls_name = self.param_index.get(key)
            if cls_name is not None:
                if cls_name.startswith(BINARY_COMPONENT_PREFIX) \
                        and any(type(c).__name__.startswith(
                            BINARY_COMPONENT_PREFIX)
                            for c in comps.values()):
                    # a binary param the SELECTED model doesn't carry
                    # (e.g. SINI in a DDK par — DDK derives the
                    # inclination from KIN; reference warns the same
                    # way) must never instantiate a second binary
                    warnings.warn(
                        f"{key} is not used by the selected binary "
                        f"model; ignoring it",
                        UnknownParameterWarning, stacklevel=2)
                    unknown.append(key)
                    continue
                comp = get_comp(cls_name)
                p = _param_by_name_or_alias(comp, key)
                p.from_tokens(toks)
                continue

            # 1c. FB orbital-frequency series → the active binary
            m = _FB_RE.match(key)
            if m:
                binary = [c for c in comps.values()
                          if type(c).__name__.startswith("Binary")]
                if binary:
                    p = binary[0].add_fb_term(int(m.group(1)))
                    p.from_tokens(toks)
                    continue

            # 1d. BT_piecewise pieces → the active binary
            m = _BTX_RE.match(key)
            if m:
                binary = [c for c in comps.values()
                          if hasattr(c, "add_piece_param")]
                if binary:
                    p = binary[0].add_piece_param(
                        m.group(1), int(m.group(2)),
                        index_str=m.group(2))
                    p.from_tokens(toks)
                    continue

            # 2. prefix families
            m = _F_RE.match(key)
            if m:
                comp = get_comp("Spindown")
                p = comp.add_f_term(int(m.group(1)))
                p.from_tokens(toks)
                continue
            m = _DM_RE.match(key)
            if m:
                comp = get_comp("DispersionDM")
                p = comp.add_dm_term(int(m.group(1)))
                p.from_tokens(toks)
                continue
            m = _DMX_RE.match(key)
            if m:
                comp = get_comp("DispersionDMX")
                p = prefixParameter(name=key, units="pc cm^-3"
                                    if m.group(1) == "DMX_" else "MJD")
                comp.add_param(p)
                p.from_tokens(toks)
                continue

            # 3. mask families (one param instance per line)
            if key in MASK_FAMILIES or _FDJUMP_RE.match(key):
                cls_name = MASK_FAMILIES.get(key, "FDJump")
                if cls_name not in component_types:
                    unknown.append(key)
                    continue
                comp = get_comp(cls_name)
                canonical = MASK_CANONICAL.get(key, key)
                mask_counters[canonical] = mask_counters.get(canonical, 0) + 1
                p = maskParameter(
                    canonical, index=mask_counters[canonical],
                    units=MASK_UNITS.get(
                        canonical,
                        "s" if _FDJUMP_RE.match(key) else ""))
                comp.add_param(p)
                p.from_tokens(toks)
                continue

            # 4. generic prefixed names owned by an existing family
            #    (GLF0_2, WAVE3, WXFREQ_0002 ... route via their prefix;
            #    the new member clones the template member's class so
            #    pair-valued families stay pair-valued)
            try:
                prefix, _, _ = split_prefixed_name(key)
                owner = self.param_index.get(prefix.rstrip("_")) or \
                    self.param_index.get(prefix)
                if owner:
                    comp = get_comp(owner)
                    tmpl_member = next(
                        (q for qn, q in comp.params.items()
                         if qn != key and qn.startswith(prefix)
                         and qn[len(prefix):].isdigit()), None)
                    from pint_tpu.models.parameter import pairParameter

                    if isinstance(tmpl_member, pairParameter):
                        p = pairParameter(key,
                                          units=tmpl_member.units)
                    else:
                        p = prefixParameter(
                            name=key,
                            units=getattr(tmpl_member, "units", ""))
                    comp.add_param(p)
                    p.from_tokens(toks)
                    continue
            except ValueError:
                pass

            unknown.append(key)

        # Shared astrometry params (PX/POSEPOCH) index to the equatorial
        # template; if the par is actually ecliptic, migrate them.
        if "AstrometryEquatorial" in comps and "AstrometryEcliptic" in comps:
            eq, ec = comps["AstrometryEquatorial"], comps["AstrometryEcliptic"]
            if eq.RAJ.value is None and ec.ELONG.value is not None:
                for nm in ("PX", "POSEPOCH"):
                    if eq.params[nm].value is not None:
                        ec.params[nm] = eq.params[nm]
                del comps["AstrometryEquatorial"]
            elif ec.ELONG.value is None and eq.RAJ.value is not None:
                for nm in ("PX", "POSEPOCH"):
                    if ec.params[nm].value is not None:
                        eq.params[nm] = ec.params[nm]
                del comps["AstrometryEcliptic"]

        # implied components (reference: ModelBuilder._get_components)
        if any(c in comps for c in ("AstrometryEquatorial",
                                    "AstrometryEcliptic")):
            get_comp("SolarSystemShapiro")

        model = TimingModel(list(comps.values()), name=name)
        if binary_name:
            model.BINARY = binary_name
        if unknown:
            warnings.warn(
                f"ignoring unrecognized par parameters: {sorted(set(unknown))}",
                UnknownParameterWarning, stacklevel=2)
        model.unknown_params = sorted(set(unknown))
        for c in model.components.values():
            c.setup()
        model.validate()
        return model


def _param_by_name_or_alias(comp: Component, key: str):
    if key in comp.params:
        return comp.params[key]
    for p in comp.params.values():
        if key in p.aliases:
            return p
    raise KeyError(key)


def get_model(parfile, name="", allow_tcb=True) -> TimingModel:
    """Build a TimingModel from a par file path/handle/string
    (reference: get_model). UNITS TCB models are converted to TDB via
    the IFTE_K linear scaling (reference: allow_tcb; pass
    allow_tcb=False to refuse instead)."""
    lines = parse_parfile(parfile)
    model = ModelBuilder()(lines, name=name)
    psr = model.PSR.value
    if psr and not model.name:
        model.name = psr
    if (model.UNITS.value or "TDB").upper() == "TCB":
        if not allow_tcb:
            raise ValueError("UNITS TCB refused (allow_tcb=False)")
        from pint_tpu.models.tcb_conversion import convert_tcb_tdb

        warnings.warn(
            "par file is in TCB units: converted to TDB with the "
            "IFTE_K linear scaling (periodic TDB-TCB terms ~ns are "
            "not applied)")
        model = convert_tcb_tdb(model)
    return model


def get_model_and_toas(parfile, timfile, **kw):
    """(model, toas) in one call (reference: get_model_and_toas)."""
    from pint_tpu.toa import get_TOAs

    model = get_model(parfile)
    toas = get_TOAs(timfile, model=model, **kw)
    return model, toas
