"""Explicit fittable overall phase offset PHOFF.

Reference: src/pint/models/phase_offset.py (PhaseOffset) — replaces the
implicit "Offset" design-matrix column when present; residual phase gets
−PHOFF (turns).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.parameter import floatParameter
from pint_tpu.models.timing_model import PhaseComponent
from pint_tpu.ops.dd import DD


class PhaseOffset(PhaseComponent):
    """Fittable overall phase offset (reference:
    src/pint/models/phase_offset.py PhaseOffset): contributes −PHOFF
    turns to every non-TZR phase and REPLACES the implicit "Offset"
    design-matrix column and the implicit residual mean subtraction
    (step consumers must check names[0] == "Offset")."""

    category = "phase_offset"

    # the TZR phase must NOT include PHOFF (reference: PhaseOffset —
    # the offset shifts observed phases relative to the TZR anchor):
    # a constant applied to BOTH the main rows and the TZR row cancels
    # identically in the anchored difference, making PHOFF inert and
    # its design column zero (a singular normal matrix when free) —
    # the bug the production-config component sweep caught.
    apply_to_tzr = False

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("PHOFF", units="turn", value=0.0))

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"PHOFF": parse_unit("turn")}

    def phase(self, pv, batch, cache, ctx, tb):
        off = -(pv["PHOFF"].hi + pv["PHOFF"].lo)
        ph = off * jnp.ones_like(batch.freq_mhz)
        return DD(ph, jnp.zeros_like(ph))

    def linear_design_names(self):
        return [] if self.PHOFF.frozen else ["PHOFF"]

    def linear_design_local(self, pv, batch, cache, ctx):
        if self.PHOFF.frozen:
            return {}
        return {"PHOFF": ("phase", -jnp.ones_like(batch.freq_mhz))}
