"""Additional timing-model components: glitches, harmonic whitening
(Wave / WaveX / DMWaveX), frequency-dependent profile delays (FD), and
solar-wind dispersion.

Reference: src/pint/models/glitch.py (Glitch), wave.py (Wave),
wavex.py (WaveX, DMWaveX), frequency_dependent.py (FD),
solar_wind_dispersion.py (SolarWindDispersion).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.dispersion import DMconst
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    pairParameter,
    prefixParameter,
    split_prefixed_name,
)
from pint_tpu.models.timing_model import (
    DelayComponent,
    PhaseComponent,
    frozen_trace_value,
)
from pint_tpu.ops.dd import DD

SECS_PER_DAY = 86400.0
AU_M = 1.495978707e11
PC_M = 3.0856775814913673e16
C_M_S = 299792458.0


def _val(pv, name, default=0.0):
    p = pv.get(name)
    return (p.hi + p.lo) if p is not None else default


_GL_UNITS = {"GLEP_": "MJD", "GLPH_": "turn", "GLTD_": "d",
             "GLF0_": "Hz", "GLF1_": "Hz/s", "GLF2_": "Hz/s^2",
             "GLF0D_": "Hz"}


class Glitch(PhaseComponent):
    """Sudden spin-up events with exponential recovery (reference:
    glitch.Glitch). Per glitch index n: GLEP_n (epoch), GLPH_n (phase
    step), GLF0_n/GLF1_n/GLF2_n (frequency-derivative steps),
    GLF0D_n + GLTD_n (decaying frequency step, timescale in days).

    phase(t>=GLEP) = GLPH + GLF0 dt + GLF1 dt^2/2 + GLF2 dt^3/6
                     + GLF0D tau (1 - exp(-dt/tau))
    """

    category = "glitch"
    register = True

    PREFIXES = ("GLEP_", "GLPH_", "GLF0_", "GLF1_", "GLF2_",
                "GLF0D_", "GLTD_")

    def __init__(self):
        super().__init__()
        # first-glitch templates: route GL*_n par keys to this component
        for pre in self.PREFIXES:
            self.add_param(prefixParameter(
                prefix=pre, index=1, index_str="1",
                units=_GL_UNITS[pre]))
        self.glitch_ids: list = []

    def add_glitch(self, index, epoch, ph=0.0, f0=0.0, f1=0.0, f2=0.0,
                   f0d=0.0, td=0.0, frozen=True):
        for pre, val in (("GLEP_", epoch), ("GLPH_", ph), ("GLF0_", f0),
                         ("GLF1_", f1), ("GLF2_", f2), ("GLF0D_", f0d),
                         ("GLTD_", td)):
            self.add_param(prefixParameter(
                prefix=pre, index=index, index_str=str(index), value=val,
                frozen=frozen if pre != "GLEP_" else True,
                units=_GL_UNITS[pre]))
        self.setup()

    def setup(self):
        ids = set()
        for name, p in self.params.items():
            for pre in self.PREFIXES:
                if name.startswith(pre) and p.value is not None:
                    ids.add(int(name[len(pre):]))
        self.glitch_ids = sorted(ids)
        # every glitch needs its epoch; default missing sub-params to 0
        for i in self.glitch_ids:
            for pre in self.PREFIXES:
                nm = f"{pre}{i}"
                if nm not in self.params:
                    self.add_param(prefixParameter(
                        prefix=pre, index=i, index_str=str(i),
                        value=0.0, units=_GL_UNITS[pre]))
                elif self.params[nm].value is None and pre != "GLEP_":
                    self.params[nm].value = 0.0

    def validate(self):
        for i in self.glitch_ids:
            if self.params[f"GLEP_{i}"].value in (None, 0.0):
                raise ValueError(f"glitch {i} needs GLEP_{i}")

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {pre + "*": parse_unit(_GL_UNITS[pre])
                for pre in self.PREFIXES}


    def phase(self, pv, batch, cache, ctx, tb):
        ref = self._parent.ref_day
        total = jnp.zeros_like(batch.freq_mhz)
        tb_f = tb.hi + tb.lo
        for i in self.glitch_ids:
            ep = _val(pv, f"GLEP_{i}")
            dt = tb_f - (ep - ref) * SECS_PER_DAY
            on = dt >= 0.0
            dtc = jnp.where(on, dt, 0.0)
            tau = _val(pv, f"GLTD_{i}") * SECS_PER_DAY
            # branch-free decaying term; tau=0 means no decay component
            has_tau = tau > 0
            tau_safe = jnp.where(has_tau, tau, 1.0)
            decay = jnp.where(
                has_tau,
                _val(pv, f"GLF0D_{i}") * tau_safe *
                (1.0 - jnp.exp(-dtc / tau_safe)),
                0.0)
            ph = (_val(pv, f"GLPH_{i}")
                  + _val(pv, f"GLF0_{i}") * dtc
                  + _val(pv, f"GLF1_{i}") * dtc * dtc / 2.0
                  + _val(pv, f"GLF2_{i}") * dtc ** 3 / 6.0
                  + decay)
            total = total + jnp.where(on, ph, 0.0)
        return DD(total, jnp.zeros_like(total))

    _LD_PREFIXES = ("GLPH_", "GLF0_", "GLF1_", "GLF2_", "GLF0D_")

    def linear_design_names(self):
        # GLEP/GLTD enter nonlinearly and stay on AD when free; the
        # amplitude-like pieces are linear given the CURRENT epoch/tau
        return [f"{pre}{i}" for i in self.glitch_ids
                for pre in self._LD_PREFIXES
                if not self.params[f"{pre}{i}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """Exact partials of the glitch phase wrt its amplitude
        pieces: mask, mask*dt, mask*dt^2/2, mask*dt^3/6,
        mask*tau*(1-exp(-dt/tau)) — mirrors phase() above."""
        names = set(self.linear_design_names())
        if not names:
            return {}
        ref = self._parent.ref_day
        tb_dd = ctx["tb"]
        tb_f = tb_dd.hi + tb_dd.lo
        out = {}
        for i in self.glitch_ids:
            ep = _val(pv, f"GLEP_{i}")
            dt = tb_f - (ep - ref) * SECS_PER_DAY
            on = (dt >= 0.0).astype(tb_f.dtype)
            dtc = jnp.where(dt >= 0.0, dt, 0.0)
            if f"GLPH_{i}" in names:
                out[f"GLPH_{i}"] = ("phase", on)
            if f"GLF0_{i}" in names:
                out[f"GLF0_{i}"] = ("phase", on * dtc)
            if f"GLF1_{i}" in names:
                out[f"GLF1_{i}"] = ("phase", on * dtc * dtc / 2.0)
            if f"GLF2_{i}" in names:
                out[f"GLF2_{i}"] = ("phase", on * dtc ** 3 / 6.0)
            if f"GLF0D_{i}" in names:
                tau = _val(pv, f"GLTD_{i}") * SECS_PER_DAY
                has_tau = tau > 0
                tau_safe = jnp.where(has_tau, tau, 1.0)
                g = jnp.where(has_tau,
                              tau_safe * (1.0 - jnp.exp(-dtc / tau_safe)),
                              0.0)
                out[f"GLF0D_{i}"] = ("phase", on * g)
        return out


class Wave(PhaseComponent):
    """Legacy TEMPO sinusoid whitening (reference: wave.Wave):
    WAVEOM [rad/day], WAVEEPOCH [MJD], WAVEn = (sin, cos) amplitude
    pair [s]. The summed time offset w(t) enters as phase -F0 w(t)
    (same sign convention as JUMP: positive offset = later arrival).

    Wave amplitudes are host-static here (frozen; pairParameter is not
    device-traced) — use WaveX for fittable harmonic terms.
    """

    category = "wave"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("WAVE_OM", units="rad/d",
                                      aliases=["WAVEOM"]))
        self.add_param(MJDParameter("WAVEEPOCH"))
        self.add_param(pairParameter("WAVE1", units="s"))
        self.wave_ids: list = []

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("WAVE") and name[4:].isdigit():
                ids.append(int(name[4:]))
        self.wave_ids = sorted(ids)

    def validate(self):
        if self.wave_ids and self.WAVE_OM.value is None:
            raise ValueError("WAVE terms require WAVE_OM")

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        out = {"WAVE_OM": parse_unit("rad/d"),
               "WAVEEPOCH": parse_unit("d"),
               "WAVE*": parse_unit("s")}
        return out


    def prepare(self, toas, batch, cache, prefix=""):
        if not self.wave_ids or self.WAVE_OM.value is None:
            return
        epoch = self.WAVEEPOCH.value
        if epoch is None:
            epoch = self._parent.PEPOCH.value
        t = toas.tdb_day + toas.tdb_frac[0] + toas.tdb_frac[1] - epoch
        om = self.WAVE_OM.value
        w = np.zeros(toas.ntoas)
        for k in self.wave_ids:
            a, b = self.params[f"WAVE{k}"].value
            w += a * np.sin(k * om * t) + b * np.cos(k * om * t)
        cache["wave_offset"] = w

    def phase(self, pv, batch, cache, ctx, tb):
        if "wave_offset" not in cache:
            z = jnp.zeros_like(batch.freq_mhz)
            return DD(z, z)
        f0 = pv["F0"].hi + pv["F0"].lo
        ph = -jnp.asarray(cache["wave_offset"]) * f0
        return DD(ph, jnp.zeros_like(ph))


class WaveX(DelayComponent):
    """Explicit-frequency Fourier delays, the modern deterministic
    red-noise surrogate (reference: wavex.WaveX): per index n,
    WXFREQ_000n [1/d], WXSIN_000n / WXCOS_000n [s];
    delay = sum WXSIN sin(2 pi f t) + WXCOS cos(2 pi f t), t from
    WXEPOCH (or PEPOCH). Frequencies are fixed; amplitudes fittable."""

    category = "wavex"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("WXEPOCH"))
        self.add_param(prefixParameter(prefix="WXFREQ_", index=1,
                                       index_str="0001", units="1/d"))
        self.add_param(prefixParameter(prefix="WXSIN_", index=1,
                                       index_str="0001", units="s"))
        self.add_param(prefixParameter(prefix="WXCOS_", index=1,
                                       index_str="0001", units="s"))
        self.wavex_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"WXEPOCH": parse_unit("d"),
                "WXFREQ_*": parse_unit("1/d"),
                "WXSIN_*": parse_unit("s"),
                "WXCOS_*": parse_unit("s")}

    def add_wavex_component(self, freq_per_day, index=None, wxsin=0.0,
                            wxcos=0.0, frozen=False):
        # next slot = one past the highest USED index, not the count:
        # with non-contiguous indices (e.g. 0001+0003) the count would
        # land on and overwrite an existing slot
        if index is None:
            index = (max((i for i, _ in self.wavex_ids), default=0)
                     + 1)
        istr = f"{index:04d}"
        for pre, val, frz in (("WXFREQ_", freq_per_day, True),
                              ("WXSIN_", wxsin, frozen),
                              ("WXCOS_", wxcos, frozen)):
            if f"{pre}{istr}" in self.params:
                p = self.params[f"{pre}{istr}"]
                p.value = val
                p.frozen = frz
            else:
                self.add_param(prefixParameter(
                    prefix=pre, index=index, index_str=istr, value=val,
                    frozen=frz,
                    units="1/d" if pre == "WXFREQ_" else "s"))
        self.setup()
        return index

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("WXFREQ_"):
                _, istr, idx = split_prefixed_name(name)
                if self.params[name].value is not None:
                    ids.append((idx, istr))
        self.wavex_ids = sorted(ids)

    def validate(self):
        for idx, istr in self.wavex_ids:
            for pre in ("WXSIN_", "WXCOS_"):
                if f"{pre}{istr}" not in self.params:
                    raise ValueError(f"WXFREQ_{istr} missing {pre}{istr}")

    def _epoch(self):
        # trace constant: legal only while frozen (compile-keyed) —
        # a free epoch would go silently stale (graftflow G10)
        return frozen_trace_value(self.WXEPOCH, self._parent.PEPOCH)

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.wavex_ids:
            return jnp.zeros_like(batch.freq_mhz)
        ref = self._parent.ref_day
        tb = ctx.get("tb_days")
        if tb is None:
            tb = (batch.tdb_day - ref) + batch.tdb_frac.hi \
                + batch.tdb_frac.lo
            ctx["tb_days"] = tb
        t = tb - (self._epoch() - ref)  # days
        total = jnp.zeros_like(batch.freq_mhz)
        for idx, istr in self.wavex_ids:
            arg = 2.0 * jnp.pi * _val(pv, f"WXFREQ_{istr}") * t
            total = total + _val(pv, f"WXSIN_{istr}") * jnp.sin(arg) \
                + _val(pv, f"WXCOS_{istr}") * jnp.cos(arg)
        return total

    def linear_design_names(self):
        return [f"{pre}{istr}" for _, istr in self.wavex_ids
                for pre in ("WXSIN_", "WXCOS_")
                if not self.params[f"{pre}{istr}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(WXSIN/WXCOS) = sin/cos(2 pi f t) (exact partial
        at the current WXFREQ values)."""
        if not self.wavex_ids:
            return {}
        ref = self._parent.ref_day
        tb = (batch.tdb_day - ref) + batch.tdb_frac.hi \
            + batch.tdb_frac.lo
        t = tb - (self._epoch() - ref)
        out = {}
        for idx, istr in self.wavex_ids:
            arg = 2.0 * jnp.pi * _val(pv, f"WXFREQ_{istr}") * t
            if not self.params[f"WXSIN_{istr}"].frozen:
                out[f"WXSIN_{istr}"] = ("pre_delay", jnp.sin(arg))
            if not self.params[f"WXCOS_{istr}"].frozen:
                out[f"WXCOS_{istr}"] = ("pre_delay", jnp.cos(arg))
        return out


class DMWaveX(DelayComponent):
    """Fourier DM variations (reference: wavex.DMWaveX): DMWXFREQ_000n
    [1/d], DMWXSIN/DMWXCOS [pc/cm^3]; delay = K DM(t)/nu^2."""

    category = "dispersion"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("DMWXEPOCH"))
        self.add_param(prefixParameter(prefix="DMWXFREQ_", index=1,
                                       index_str="0001", units="1/d"))
        self.add_param(prefixParameter(prefix="DMWXSIN_", index=1,
                                       index_str="0001",
                                       units="pc cm^-3"))
        self.add_param(prefixParameter(prefix="DMWXCOS_", index=1,
                                       index_str="0001",
                                       units="pc cm^-3"))
        self.dmwavex_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"DMWXEPOCH": parse_unit("d"),
                "DMWXFREQ_*": parse_unit("1/d"),
                "DMWXSIN_*": parse_unit("pc cm^-3"),
                "DMWXCOS_*": parse_unit("pc cm^-3")}

    def add_dmwavex_component(self, freq_per_day, index=None,
                              dmwxsin=0.0, dmwxcos=0.0, frozen=False):
        """Fill or create one Fourier slot; next index is one past the
        highest existing slot (mirrors WaveX.add_wavex_component)."""
        if index is None:
            highest = [split_prefixed_name(nm)[2]
                       for nm in self.params
                       if nm.startswith("DMWXFREQ_")
                       and self.params[nm].value is not None]
            index = (max(highest) if highest else 0) + 1
        istr = f"{index:04d}"
        for pre, val, frz in (("DMWXFREQ_", freq_per_day, True),
                              ("DMWXSIN_", dmwxsin, frozen),
                              ("DMWXCOS_", dmwxcos, frozen)):
            name = f"{pre}{istr}"
            if name in self.params:
                p = self.params[name]
                p.value = val
                p.frozen = frz
            else:
                self.add_param(prefixParameter(
                    prefix=pre, index=index, index_str=istr, value=val,
                    frozen=frz, units=self.params[f"{pre}0001"].units))
        self.setup()
        return index

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("DMWXFREQ_"):
                _, istr, idx = split_prefixed_name(name)
                if self.params[name].value is not None:
                    ids.append((idx, istr))
        self.dmwavex_ids = sorted(ids)

    def dm_value_device(self, pv, batch, cache, ctx):
        if not self.dmwavex_ids:
            return jnp.zeros_like(batch.freq_mhz)
        ref = self._parent.ref_day
        epoch = frozen_trace_value(self.DMWXEPOCH,
                                   self._parent.PEPOCH)
        t = (batch.tdb_day - ref) + batch.tdb_frac.hi \
            + batch.tdb_frac.lo - (epoch - ref)
        dm = jnp.zeros_like(batch.freq_mhz)
        for idx, istr in self.dmwavex_ids:
            arg = 2.0 * jnp.pi * _val(pv, f"DMWXFREQ_{istr}") * t
            dm = dm + _val(pv, f"DMWXSIN_{istr}") * jnp.sin(arg) \
                + _val(pv, f"DMWXCOS_{istr}") * jnp.cos(arg)
        return dm

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.dmwavex_ids:
            return jnp.zeros_like(batch.freq_mhz)
        bf = ctx.get("bfreq", batch.freq_mhz)
        return DMconst * self.dm_value_device(pv, batch, cache, ctx) \
            / (bf * bf)

    def linear_design_names(self):
        return [f"{pre}{istr}" for _, istr in self.dmwavex_ids
                for pre in ("DMWXSIN_", "DMWXCOS_")
                if not self.params[f"{pre}{istr}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(DMWXSIN/COS) = DMconst sin/cos(arg) / nu^2."""
        if not self.dmwavex_ids:
            return {}
        ref = self._parent.ref_day
        epoch = frozen_trace_value(self.DMWXEPOCH,
                                   self._parent.PEPOCH)
        t = (batch.tdb_day - ref) + batch.tdb_frac.hi \
            + batch.tdb_frac.lo - (epoch - ref)
        bf = ctx.get("bfreq", batch.freq_mhz)
        inv2 = DMconst / (bf * bf)
        out = {}
        for idx, istr in self.dmwavex_ids:
            arg = 2.0 * jnp.pi * _val(pv, f"DMWXFREQ_{istr}") * t
            if not self.params[f"DMWXSIN_{istr}"].frozen:
                out[f"DMWXSIN_{istr}"] = ("pre_delay",
                                          inv2 * jnp.sin(arg))
            if not self.params[f"DMWXCOS_{istr}"].frozen:
                out[f"DMWXCOS_{istr}"] = ("pre_delay",
                                          inv2 * jnp.cos(arg))
        return out


class FD(DelayComponent):
    """Frequency-dependent profile-evolution delay (reference:
    frequency_dependent.FD): delay = sum_i FDi ln(nu/1 GHz)^i."""

    category = "frequency_dependent"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter(prefix="FD", index=1,
                                       index_str="1", units="s"))
        self.fd_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"FD*": parse_unit("s")}

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("FD") and name[2:].isdigit() and \
                    self.params[name].value is not None:
                ids.append(int(name[2:]))
        self.fd_ids = sorted(ids)

    def validate(self):
        # the Horner chain assigns exponent by position: indices must
        # be 1..n with no gaps (reference: FD.validate raises likewise)
        if self.fd_ids and self.fd_ids != list(
                range(1, len(self.fd_ids) + 1)):
            raise ValueError(
                f"FD indices must be sequential from 1, got {self.fd_ids}")

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.fd_ids:
            return jnp.zeros_like(batch.freq_mhz)
        bf = ctx.get("bfreq", batch.freq_mhz)
        logf = jnp.log(bf / 1000.0)  # nu in MHz; reference: ln(nu/GHz)
        total = jnp.zeros_like(bf)
        # Horner over ln(nu/GHz), i >= 1
        for i in reversed(self.fd_ids):
            total = (total + _val(pv, f"FD{i}")) * logf
        # TOAs at infinite frequency (barycentred data) see no FD delay
        return jnp.where(jnp.isfinite(bf), total, 0.0)

    def linear_design_names(self):
        return [f"FD{i}" for i in self.fd_ids
                if not self.params[f"FD{i}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(FDi) = ln(nu/GHz)^i (0 at infinite freq)."""
        bf = ctx.get("bfreq", batch.freq_mhz)
        fin = jnp.isfinite(bf)
        logf = jnp.log(jnp.where(fin, bf, 1000.0) / 1000.0)
        return {f"FD{i}": ("pre_delay",
                           jnp.where(fin, logf ** i, 0.0))
                for i in self.fd_ids
                if not self.params[f"FD{i}"].frozen}


class SolarWindDispersion(DelayComponent):
    """Solar-wind dispersion (reference:
    solar_wind_dispersion.SolarWindDispersion): electron density
    n_e(r) = NE_SW (1 AU/r)^2 integrated along the line of sight gives
    DM_sw = NE_SW AU^2 (pi - rho)/(r sin rho), rho = observer-frame
    angle between the Sun and pulsar directions (rho -> 0: pulsar
    behind the Sun, delay spikes at solar conjunction — SURVEY.md A.4
    oracle)."""

    category = "solar_wind"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("NE_SW", units="cm^-3", value=0.0,
                                      aliases=["NE1AU", "SOLARN0"]))
        self.add_param(floatParameter("SWM", units="", value=0.0))
        self.add_param(floatParameter("SWP", units="", value=2.0,
                                      description="radial density "
                                      "power-law index (SWM 1)"))

    def param_dimensions(self):
        from pint_tpu.units import DIMENSIONLESS, parse_unit

        return {"NE_SW": parse_unit("cm^-3"), "SWM": DIMENSIONLESS,
                "SWP": DIMENSIONLESS}

    def validate(self):
        if self.SWM.value not in (None, 0.0, 0, 1.0, 1):
            raise NotImplementedError("SWM must be 0 or 1")
        if int(self.SWM.value or 0) == 1 and \
                (self.SWP.value is None or self.SWP.value <= 1.0):
            raise ValueError("SWM 1 needs SWP > 1 (the line-of-sight "
                             "integral diverges otherwise)")

    # 64-node Gauss-Legendre rule for the SWM-1 line-of-sight integral:
    # differentiable in BOTH the elongation and the power-law index
    # (jacfwd-able — a betainc/gamma closed form would not give d/dSWP)
    _GL = np.polynomial.legendre.leggauss(64)

    def _cosq_integral(self, phi0, q):
        """∫_{phi0}^{pi/2} cos^q(phi) dphi by fixed quadrature; phi0
        per TOA, q traced scalar (> -1)."""
        nodes = jnp.asarray(self._GL[0], phi0.dtype)
        wts = jnp.asarray(self._GL[1], phi0.dtype)
        half = (jnp.pi / 2 - phi0) / 2.0
        mid = (jnp.pi / 2 + phi0) / 2.0
        phi = mid[:, None] + half[:, None] * nodes[None, :]
        c = jnp.clip(jnp.cos(phi), 1e-12, 1.0)
        return half * jnp.sum(wts[None, :] * c ** q, axis=-1)

    def _geom(self, pv, batch, ctx):
        """Line-of-sight geometry factor: dm = NE_SW * _geom (the
        NE_SW partial, shared by delay and linear_design_local)."""
        n = ctx["psr_dir"]  # (N,3) unit observer->pulsar
        s = batch.obs_sun_pos  # (N,3) observer->Sun, lt-s
        r_lts = jnp.sqrt(jnp.sum(s * s, axis=-1))
        cosr = jnp.sum(s * n, axis=-1) / r_lts
        rho = jnp.arccos(jnp.clip(cosr, -1.0, 1.0))
        r_m = r_lts * C_M_S
        sinr = jnp.maximum(jnp.sin(rho), 1e-9)
        # SWM is a model-structure switch baked into the trace:
        # frozen-guarded read (graftflow G10) — a free SWM would flip
        # geometry without retracing
        if int(frozen_trace_value(self.SWM) or 0) == 1:
            # n_e = NE_SW (AU/r)^SWP: DM = NE_SW AU^p b^{1-p}
            #   ∫_{rho-pi/2}^{pi/2} cos^{p-2} dphi, b = r sin(rho)
            # (You et al. 2007 geometry; reference: SWM 1 branch of
            # solar_wind_dispersion.py). p = 2 reduces exactly to the
            # SWM-0 closed form below.
            p = _val(pv, "SWP")
            b_m = r_m * sinr
            F = self._cosq_integral(rho - jnp.pi / 2.0, p - 2.0)
            # (AU/b)^p * b / pc keeps every intermediate O(1): the
            # naive AU^p overflows f32 range for SWP >= ~3.45 in the
            # f32 Jacobian re-trace
            return (AU_M / b_m) ** p * (b_m / PC_M) * F
        # SWM 0: n_e = NE_SW (AU/r)^2 closed form
        # DM in pc/cm^3: NE_SW [cm^-3] * AU^2[m^2]/pc[m] * geom [1/m]
        return (AU_M * AU_M / PC_M) * (jnp.pi - rho) / (r_m * sinr)

    def dm_value_device(self, pv, batch, cache, ctx):
        return _val(pv, "NE_SW") * self._geom(pv, batch, ctx)

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        bf = ctx.get("bfreq", batch.freq_mhz)
        return DMconst * self.dm_value_device(pv, batch, cache, ctx) \
            / (bf * bf)

    def linear_design_names(self):
        return [] if self.NE_SW.frozen else ["NE_SW"]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(NE_SW) = DMconst * geom / nu^2 (exact at the
        current SWP/astrometry; a free SWP stays on AD)."""
        if self.NE_SW.frozen:
            return {}
        bf = ctx.get("bfreq", batch.freq_mhz)
        return {"NE_SW": ("pre_delay",
                          DMconst * self._geom(pv, batch, ctx)
                          / (bf * bf))}
