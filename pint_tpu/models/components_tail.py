"""Component-zoo tail: troposphere, chromatic variation (CM/CMX/
CMWaveX), tabulated phase (IFUNC), piecewise spindown, piecewise solar
wind (SWX), and per-system frequency-dependent jumps (FDJump).

Reference: src/pint/models/troposphere_delay.py (TroposphereDelay),
chromatic_model.py (ChromaticCM, ChromaticCMX), wavex.py (CMWaveX),
ifunc.py (IFunc), piecewise.py (PiecewiseSpindown),
solar_wind_dispersion.py (SolarWindDispersionX), fdjump.py (FDJump).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.components_extra import (
    AU_M,
    C_M_S,
    PC_M,
    SECS_PER_DAY,
    _val,
)
from pint_tpu.models.dispersion import DMconst
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    intParameter,
    maskParameter,
    pairParameter,
    prefixParameter,
    split_prefixed_name,
)
from pint_tpu.models.timing_model import (
    DelayComponent,
    PhaseComponent,
    frozen_trace_value,
)
from pint_tpu.ops.dd import DD


def chromatic_index(parent, default: float = 4.0) -> float:
    """The model's chromatic spectral index alpha (TNCHROMIDX on the
    ChromaticCM component), shared by CMX/CMWaveX/PLChromNoise.

    Host-side by design: the sharers read alpha as a trace constant,
    which is only sound while TNCHROMIDX is frozen (frozen device
    params are part of the compile key, so a value change re-keys the
    trace). A FREE TNCHROMIDX would go stale here without retracing —
    ChromaticCM itself reads it from the traced pv and tolerates
    fitting, but the sharers cannot, so refuse loudly (graftlint G1
    finding, 2026-08-02)."""
    if parent is not None and "ChromaticCM" in parent.components:
        p = parent.components["ChromaticCM"].TNCHROMIDX
        if not p.frozen:
            raise ValueError(
                "TNCHROMIDX is free, but ChromaticCMX/CMWaveX/"
                "PLChromNoise share it as a trace constant — fitting "
                "the chromatic index is only supported with "
                "ChromaticCM alone; freeze TNCHROMIDX")
        v = p.value
        if v is not None:
            # frozen => host data covered by the compile key
            return float(v)  # graftlint: allow G1 -- frozen static
    return default


def chromatic_scale(batch, ctx, alpha):
    """Per-TOA chromatic factor DMconst nu^-alpha 1000^(alpha-2)
    (0 at infinite frequency) — the single implementation behind
    ChromaticCM/CMX/CMWaveX delays AND their closed-form design
    columns (the 1-GHz referencing convention lives here once)."""
    bf = ctx.get("bfreq", batch.freq_mhz)
    fin = jnp.isfinite(bf)
    out = DMconst * jnp.where(fin, bf, 1000.0) ** -alpha \
        * (1000.0 ** (alpha - 2.0))
    return jnp.where(fin, out, 0.0)


def solar_wind_geometry_host(toas, psr_dir) -> np.ndarray:
    """Host-side solar-wind line-of-sight DM geometry factor [pc/cm^3
    per cm^-3 of NE_SW]: (AU^2/pc)(pi - rho)/(r sin rho) with rho the
    observer-frame Sun-pulsar elongation (shared by SWX and PLSWNoise;
    device twin: SolarWindDispersion.dm_value_device)."""
    s = np.asarray(toas.obs_sun_pos)
    r_lts = np.linalg.norm(s, axis=-1)
    cosr = np.sum(s * psr_dir, axis=-1) / r_lts
    rho = np.arccos(np.clip(cosr, -1.0, 1.0))
    r_m = r_lts * C_M_S
    return (AU_M * AU_M / PC_M) * (np.pi - rho) / (
        r_m * np.maximum(np.sin(rho), 1e-9))


# --------------------------------------------------------- troposphere


class TroposphereDelay(DelayComponent):
    """Tropospheric propagation delay: zenith hydrostatic delay from a
    standard atmosphere at the site, mapped to the line-of-sight
    elevation with the Niell (1996) mapping functions (reference:
    troposphere_delay.TroposphereDelay, which uses the same NMF + a
    Davis et al. 1985 zenith delay).

    Host precompute (prepare): per-TOA geocentric zenith unit vector in
    GCRS (geocentric rather than geodetic zenith: the <=0.2 deg
    difference changes the mapping negligibly), site latitude/height,
    zenith delays, and day-of-year for the seasonal NMF term. Device:
    elevation = asin(zenith . psr_dir) and the mapping-function
    evaluation, so the delay responds to astrometry under jacfwd.

    CORRECT_TROPOSPHERE (bool) gates the component like the reference.
    """

    category = "troposphere"
    register = True

    # Niell 1996 hydrostatic mapping coefficients at |lat| = 15..75 deg
    _LAT_GRID = np.array([15.0, 30.0, 45.0, 60.0, 75.0])
    _H_AVG = np.array([
        [1.2769934e-3, 1.2683230e-3, 1.2465397e-3, 1.2196049e-3,
         1.2045996e-3],
        [2.9153695e-3, 2.9152299e-3, 2.9288445e-3, 2.9022565e-3,
         2.9024912e-3],
        [62.610505e-3, 62.837393e-3, 63.721774e-3, 63.824265e-3,
         64.258455e-3]])
    _H_AMP = np.array([
        [0.0, 1.2709626e-5, 2.6523662e-5, 3.4000452e-5, 4.1202191e-5],
        [0.0, 2.1414979e-5, 3.0160779e-5, 7.2562722e-5, 11.723375e-5],
        [0.0, 9.0128400e-5, 4.3497037e-5, 84.795348e-5, 170.37206e-5]])
    _H_HT = (2.53e-5, 5.49e-3, 1.14e-3)
    _W = np.array([
        [5.8021897e-4, 5.6794847e-4, 5.8118019e-4, 5.9727542e-4,
         6.1641693e-4],
        [1.4275268e-3, 1.5138625e-3, 1.4572752e-3, 1.5007428e-3,
         1.7599082e-3],
        [4.3472961e-2, 4.6729510e-2, 4.3908931e-2, 4.4626982e-2,
         5.4736038e-2]])

    def __init__(self):
        super().__init__()
        from pint_tpu.models.parameter import boolParameter

        self.add_param(boolParameter("CORRECT_TROPOSPHERE", value=True))

    def prepare(self, toas, batch, cache, prefix=""):
        from pint_tpu.observatory import get_observatory

        n = toas.ntoas
        zen = np.zeros((n, 3))
        mask = np.zeros(n)
        lat = np.zeros(n)
        zhd = np.zeros(n)  # zenith hydrostatic delay [s]
        h_km = np.zeros(n)
        utc = toas.get_mjds()
        tdb = toas.tdb_day + toas.tdb_frac[0] + toas.tdb_frac[1]
        for site in set(toas.obs):
            m = np.array([o == site for o in toas.obs])
            obs = get_observatory(site)
            xyz = getattr(obs, "itrf_xyz_m", None)
            if xyz is None:
                continue  # barycenter/geocenter: no troposphere
            p, _ = obs.gcrs_posvel(utc[m], tdb[m])
            r = np.linalg.norm(p, axis=-1, keepdims=True)
            zen[m] = p / r
            mask[m] = 1.0
            rho = np.hypot(xyz[0], xyz[1])
            glat = np.arctan2(xyz[2], rho)  # geocentric ~ geodetic here
            h_m = np.linalg.norm(xyz) - 6371000.0
            lat[m] = glat
            h_km[m] = max(h_m, 0.0) / 1000.0
            # standard atmosphere: P [hPa] at height, Davis et al. ZHD
            p_hpa = 1013.25 * (1.0 - 2.2557e-5 * h_m) ** 5.2568
            zhd_m = 0.0022768 * p_hpa / (
                1.0 - 0.00266 * np.cos(2.0 * glat)
                - 0.00028 * h_m / 1000.0)
            zhd[m] = zhd_m / C_M_S
        cache["tropo_zen"] = zen
        cache["tropo_mask"] = mask
        cache["tropo_lat"] = lat
        cache["tropo_zhd"] = zhd
        cache["tropo_h_km"] = h_km
        # day of year from MJD (MJD 51544 = 2000-01-01)
        doy = np.mod(utc - 51544.0, 365.25)
        cache["tropo_doy"] = doy

    @staticmethod
    def _nmf(sin_el, a, b, c):
        top = 1.0 + a / (1.0 + b / (1.0 + c))
        bot = sin_el + a / (sin_el + b / (sin_el + c))
        return top / bot

    def _interp_coeff(self, table, abslat_deg):
        """Piecewise-linear lat interpolation of an NMF coefficient
        row (host grid, device latitude)."""
        dt = jnp.asarray(abslat_deg).dtype
        return jnp.interp(abslat_deg, jnp.asarray(self._LAT_GRID, dt),
                          jnp.asarray(table, dt))

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.CORRECT_TROPOSPHERE.value:
            return jnp.zeros_like(batch.freq_mhz)
        zen = cache["tropo_zen"]
        mask = cache["tropo_mask"]
        ndir = ctx["psr_dir"]
        sin_el = jnp.clip(jnp.sum(zen * ndir, axis=-1), 0.05, 1.0)
        lat = cache["tropo_lat"]
        abslat = jnp.abs(lat) * 180.0 / jnp.pi
        doy = cache["tropo_doy"]
        # southern-hemisphere seasonal phase shifts by half a year
        phase = 2.0 * jnp.pi * (doy - 28.0) / 365.25
        phase = jnp.where(lat < 0, phase + jnp.pi, phase)
        cosph = jnp.cos(phase)
        a = self._interp_coeff(self._H_AVG[0], abslat) \
            - self._interp_coeff(self._H_AMP[0], abslat) * cosph
        b = self._interp_coeff(self._H_AVG[1], abslat) \
            - self._interp_coeff(self._H_AMP[1], abslat) * cosph
        c = self._interp_coeff(self._H_AVG[2], abslat) \
            - self._interp_coeff(self._H_AMP[2], abslat) * cosph
        m_h = self._nmf(sin_el, a, b, c)
        aht, bht, cht = self._H_HT
        dm_ht = (1.0 / sin_el - self._nmf(sin_el, aht, bht, cht)) \
            * cache["tropo_h_km"]
        return mask * cache["tropo_zhd"] * (m_h + dm_ht)


# ----------------------------------------------------------- chromatic


class ChromaticCM(DelayComponent):
    """Generalized chromatic delay (reference: chromatic_model.
    ChromaticCM): delay = DMconst * CM(t) / nu^TNCHROMIDX with nu in
    MHz and CM a Taylor series (CM, CM1, ...) about CMEPOCH."""

    category = "chromatic"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("CM", units="pc cm^-3 MHz^(a-2)",
                                      value=0.0))
        self.add_param(prefixParameter(prefix="CM", index=1,
                                       index_str="1",
                                       units="pc cm^-3 MHz^(a-2)/s"))
        self.add_param(MJDParameter("CMEPOCH"))
        self.add_param(floatParameter("TNCHROMIDX", units="", value=4.0,
                                      aliases=["CMIDX"]))
        self.cm_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import DIMENSIONLESS, parse_unit

        # CM's dimension depends on the chromatic index alpha
        # (pc cm^-3 MHz^(alpha-2)) — outside the rational-exponent
        # algebra, so the slot is declared exempt (callable -> None)
        # rather than left silently unspecified
        def cm_dim(name):
            return None

        return {"CM": cm_dim, "CM*": cm_dim,
                "CMEPOCH": parse_unit("d"),
                "TNCHROMIDX": DIMENSIONLESS}

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("CM") and name[2:].isdigit() and \
                    self.params[name].value is not None:
                ids.append(int(name[2:]))
        self.cm_ids = sorted(ids)

    def _epoch(self):
        # trace constant: legal only while frozen (compile-keyed) —
        # a free epoch would go silently stale (graftflow G10)
        return frozen_trace_value(self.CMEPOCH, self._parent.PEPOCH)

    def cm_value_device(self, pv, batch, cache, ctx):
        ref = self._parent.ref_day
        tb = ctx.get("tb_days")
        if tb is None:
            tb = (batch.tdb_day - ref) + batch.tdb_frac.hi \
                + batch.tdb_frac.lo
            ctx["tb_days"] = tb
        dt = (tb - (self._epoch() - ref)) * SECS_PER_DAY
        cm = _val(pv, "CM") * jnp.ones_like(dt)
        for i in self.cm_ids:  # true i! even when the series has gaps
            cm = cm + _val(pv, f"CM{i}") * dt ** i / math.factorial(i)
        return cm

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        bf = ctx.get("bfreq", batch.freq_mhz)
        alpha = _val(pv, "TNCHROMIDX", 4.0)
        cm = self.cm_value_device(pv, batch, cache, ctx)
        out = DMconst * cm * bf ** -alpha * (1000.0 ** (alpha - 2.0))
        # convention: CM is referenced to 1 GHz for alpha != 2 (the
        # 1000^(alpha-2) factor makes alpha=2 coincide with DM in the
        # usual MHz convention)
        return jnp.where(jnp.isfinite(bf), out, 0.0)

    def _chrom_scale(self, pv, batch, ctx):
        """chromatic_scale at the current (possibly traced)
        TNCHROMIDX."""
        return chromatic_scale(batch, ctx, _val(pv, "TNCHROMIDX", 4.0))

    def linear_design_names(self):
        out = [] if self.CM.frozen else ["CM"]
        out += [f"CM{i}" for i in self.cm_ids
                if not self.params[f"CM{i}"].frozen]
        if out and not self.CMEPOCH.frozen:
            return []  # dt pivots on a fitted CMEPOCH: stay on AD
        return out

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(CMk) = chrom_scale * dt^k/k! (mirrors
        cm_value_device; TNCHROMIDX itself stays on AD when free)."""
        names = set(self.linear_design_names())
        if not names:
            return {}
        sc = self._chrom_scale(pv, batch, ctx)
        out = {}
        if "CM" in names:
            out["CM"] = ("pre_delay", sc)
        if any(nm != "CM" for nm in names):
            ref = self._parent.ref_day
            tb = (batch.tdb_day - ref) + batch.tdb_frac.hi \
                + batch.tdb_frac.lo
            dt = (tb - (self._epoch() - ref)) * SECS_PER_DAY
            for i in self.cm_ids:
                if f"CM{i}" in names:
                    out[f"CM{i}"] = ("pre_delay",
                                     sc * dt ** i / math.factorial(i))
        return out


class ChromaticCMX(DelayComponent):
    """Piecewise-constant chromatic variation over MJD windows:
    CMX_0001/CMXR1_0001/CMXR2_0001 (reference: chromatic_model.
    ChromaticCMX)."""

    category = "chromatic_cmx"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter(prefix="CMX_", index=1,
                                       index_str="0001",
                                       units="pc cm^-3 MHz^(a-2)"))
        self.add_param(prefixParameter(prefix="CMXR1_", index=1,
                                       index_str="0001", units="MJD"))
        self.add_param(prefixParameter(prefix="CMXR2_", index=1,
                                       index_str="0001", units="MJD"))
        self.cmx_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        # CMX_ shares CM's alpha-dependent dimension (see
        # ChromaticCM.param_dimensions): declared exempt explicitly
        return {"CMX_*": lambda name: None,
                "CMXR1_*": parse_unit("d"),
                "CMXR2_*": parse_unit("d")}

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("CMX_"):
                _, istr, idx = split_prefixed_name(name)
                if self.params[name].value is not None:
                    ids.append((idx, istr))
        self.cmx_ids = sorted(ids)

    def validate(self):
        for idx, istr in self.cmx_ids:
            for pre in ("CMXR1_", "CMXR2_"):
                if f"{pre}{istr}" not in self.params or \
                        self.params[f"{pre}{istr}"].value is None:
                    raise ValueError(f"CMX_{istr} missing {pre}{istr}")

    def prepare(self, toas, batch, cache, prefix=""):
        if not self.cmx_ids:
            return
        mjd = toas.get_mjds()
        cols = []
        for idx, istr in self.cmx_ids:
            r1 = self.params[f"CMXR1_{istr}"].value
            r2 = self.params[f"CMXR2_{istr}"].value
            cols.append(((mjd >= r1) & (mjd <= r2)).astype(np.float64))
        cache["cmx_masks"] = np.stack(cols, axis=-1)

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.cmx_ids:
            return jnp.zeros_like(batch.freq_mhz)
        alpha = chromatic_index(self._parent)
        vals = jnp.stack([_val(pv, f"CMX_{istr}")
                          for _, istr in self.cmx_ids])
        cm = cache["cmx_masks"] @ vals
        bf = ctx.get("bfreq", batch.freq_mhz)
        out = DMconst * cm * bf ** -alpha * (1000.0 ** (alpha - 2.0))
        return jnp.where(jnp.isfinite(bf), out, 0.0)

    def _chrom_scale(self, batch, ctx):
        return chromatic_scale(batch, ctx,
                               chromatic_index(self._parent))

    def linear_design_names(self):
        return [f"CMX_{istr}" for _, istr in self.cmx_ids
                if not self.params[f"CMX_{istr}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(CMX_i) = chrom_scale * window_mask_i."""
        if not self.cmx_ids:
            return {}
        sc = self._chrom_scale(batch, ctx)
        masks = cache["cmx_masks"]
        return {f"CMX_{istr}": ("pre_delay",
                                sc * masks[:, col].astype(sc.dtype))
                for col, (_, istr) in enumerate(self.cmx_ids)
                if not self.params[f"CMX_{istr}"].frozen}


class CMWaveX(DelayComponent):
    """Fourier chromatic variations (reference: wavex.CMWaveX):
    CMWXFREQ_000n [1/d], CMWXSIN_/CMWXCOS_ [pc cm^-3 MHz^(a-2)]."""

    category = "cmwavex"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("CMWXEPOCH"))
        for pre in ("CMWXFREQ_", "CMWXSIN_", "CMWXCOS_"):
            self.add_param(prefixParameter(
                prefix=pre, index=1, index_str="0001",
                units="1/d" if pre == "CMWXFREQ_" else
                "pc cm^-3 MHz^(a-2)"))
        self.cmwx_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        # SIN/COS amplitudes share CM's alpha-dependent dimension
        # (see ChromaticCM.param_dimensions): declared exempt
        return {"CMWXEPOCH": parse_unit("d"),
                "CMWXFREQ_*": parse_unit("1/d"),
                "CMWXSIN_*": lambda name: None,
                "CMWXCOS_*": lambda name: None}

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("CMWXFREQ_"):
                _, istr, idx = split_prefixed_name(name)
                if self.params[name].value is not None:
                    ids.append((idx, istr))
        self.cmwx_ids = sorted(ids)

    def _epoch(self):
        # trace constant: legal only while frozen (compile-keyed) —
        # a free epoch would go silently stale (graftflow G10)
        return frozen_trace_value(self.CMWXEPOCH,
                                  self._parent.PEPOCH)

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.cmwx_ids:
            return jnp.zeros_like(batch.freq_mhz)
        alpha = chromatic_index(self._parent)
        ref = self._parent.ref_day
        tb = ctx.get("tb_days")
        if tb is None:
            tb = (batch.tdb_day - ref) + batch.tdb_frac.hi \
                + batch.tdb_frac.lo
            ctx["tb_days"] = tb
        t = tb - (self._epoch() - ref)  # days
        cm = jnp.zeros_like(batch.freq_mhz)
        for idx, istr in self.cmwx_ids:
            arg = 2.0 * jnp.pi * _val(pv, f"CMWXFREQ_{istr}") * t
            cm = cm + _val(pv, f"CMWXSIN_{istr}") * jnp.sin(arg) \
                + _val(pv, f"CMWXCOS_{istr}") * jnp.cos(arg)
        bf = ctx.get("bfreq", batch.freq_mhz)
        out = DMconst * cm * bf ** -alpha * (1000.0 ** (alpha - 2.0))
        return jnp.where(jnp.isfinite(bf), out, 0.0)

    def linear_design_names(self):
        return [f"{pre}{istr}" for _, istr in self.cmwx_ids
                for pre in ("CMWXSIN_", "CMWXCOS_")
                if not self.params[f"{pre}{istr}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(CMWXSIN/COS) = chrom_scale * sin/cos(arg)."""
        if not self.cmwx_ids:
            return {}
        sc = chromatic_scale(batch, ctx, chromatic_index(self._parent))
        ref = self._parent.ref_day
        tb = (batch.tdb_day - ref) + batch.tdb_frac.hi \
            + batch.tdb_frac.lo
        t = tb - (self._epoch() - ref)
        out = {}
        for idx, istr in self.cmwx_ids:
            arg = 2.0 * jnp.pi * _val(pv, f"CMWXFREQ_{istr}") * t
            if not self.params[f"CMWXSIN_{istr}"].frozen:
                out[f"CMWXSIN_{istr}"] = ("pre_delay",
                                          sc * jnp.sin(arg))
            if not self.params[f"CMWXCOS_{istr}"].frozen:
                out[f"CMWXCOS_{istr}"] = ("pre_delay",
                                          sc * jnp.cos(arg))
        return out


# ---------------------------------------------------- tabulated phase


class IFunc(PhaseComponent):
    """Tabulated phase offsets (reference: ifunc.IFunc): IFUNC<n> lines
    carry (MJD, value-seconds) pairs; SIFUNC selects interpolation
    (2 = linear, 0 = constant/nearest). phase += F0 * f(t). Values are
    host-side table data (not fittable), matching their whitening use.
    """

    category = "ifunc"
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(intParameter("SIFUNC", value=2))
        self.add_param(pairParameter("IFUNC1", units="MJD s"))
        self.ifunc_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"IFUNC*": parse_unit("MJD s")}

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("IFUNC") and name[5:].isdigit():
                p = self.params[name]
                if p.value is not None and tuple(p.value) != (0.0, 0.0):
                    ids.append(int(name[5:]))
        self.ifunc_ids = sorted(ids)

    def validate(self):
        if self.SIFUNC.value not in (None, 0, 2):
            raise ValueError(
                f"SIFUNC {self.SIFUNC.value}: only 0 (constant) and "
                "2 (linear) are implemented (as in the reference)")

    def prepare(self, toas, batch, cache, prefix=""):
        if not self.ifunc_ids:
            return
        pts = np.array([self.params[f"IFUNC{i}"].value
                        for i in self.ifunc_ids])
        order = np.argsort(pts[:, 0])
        t_k, v_k = pts[order, 0], pts[order, 1]
        mjd = toas.get_mjds()
        mode = self.SIFUNC.value
        mode = 2 if mode is None else int(mode)  # NOT `or`: 0 is valid
        if mode == 2:
            off = np.interp(mjd, t_k, v_k)
        else:  # mode 0: nearest tabulated value
            idx = np.abs(mjd[:, None] - t_k[None, :]).argmin(axis=1)
            off = v_k[idx]
        cache["ifunc_offset_s"] = off

    def phase(self, pv, batch, cache, ctx, tb):
        if not self.ifunc_ids:
            z = jnp.zeros_like(batch.freq_mhz)
            return DD(z, z)
        f0 = _val(pv, "F0")
        ph = f0 * cache["ifunc_offset_s"]
        return DD(ph, jnp.zeros_like(ph))


# ------------------------------------------------- piecewise spindown


class PiecewiseSpindown(PhaseComponent):
    """Piecewise spin solutions over MJD ranges (reference:
    piecewise.PiecewiseSpindown): within [PWSTART_n, PWSTOP_n], extra
    phase = PWPH_n + PWF0_n dt + PWF1_n dt^2/2 + PWF2_n dt^3/6 with dt
    from PWEP_n."""

    category = "piecewise_spindown"
    register = True

    PREFIXES = ("PWEP_", "PWSTART_", "PWSTOP_", "PWPH_", "PWF0_",
                "PWF1_", "PWF2_")

    def __init__(self):
        super().__init__()
        for pre in self.PREFIXES:
            self.add_param(prefixParameter(
                prefix=pre, index=1, index_str="1",
                units={"PWEP_": "MJD", "PWSTART_": "MJD",
                       "PWSTOP_": "MJD", "PWPH_": "turn",
                       "PWF0_": "Hz", "PWF1_": "Hz/s",
                       "PWF2_": "Hz/s^2"}[pre]))
        self.pw_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        d, hz, s = (parse_unit("d"), parse_unit("Hz"),
                    parse_unit("s"))
        return {"PWEP_*": d, "PWSTART_*": d, "PWSTOP_*": d,
                "PWPH_*": parse_unit("turn"), "PWF0_*": hz,
                "PWF1_*": hz / s, "PWF2_*": hz / s ** 2}

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("PWEP_"):
                _, istr, idx = split_prefixed_name(name)
                if self.params[name].value is not None:
                    ids.append((idx, istr))
        self.pw_ids = sorted(ids)

    def validate(self):
        for idx, istr in self.pw_ids:
            for pre in ("PWSTART_", "PWSTOP_"):
                if self.params.get(f"{pre}{istr}") is None or \
                        self.params[f"{pre}{istr}"].value is None:
                    raise ValueError(f"PWEP_{istr} missing {pre}{istr}")

    def prepare(self, toas, batch, cache, prefix=""):
        if not self.pw_ids:
            return
        mjd = toas.get_mjds()
        cols = []
        for idx, istr in self.pw_ids:
            r1 = self.params[f"PWSTART_{istr}"].value
            r2 = self.params[f"PWSTOP_{istr}"].value
            cols.append(((mjd >= r1) & (mjd <= r2)).astype(np.float64))
        cache["pw_masks"] = np.stack(cols, axis=-1)

    def phase(self, pv, batch, cache, ctx, tb):
        z = jnp.zeros_like(batch.freq_mhz)
        if not self.pw_ids:
            return DD(z, z)
        ref = self._parent.ref_day
        total = z
        for k, (idx, istr) in enumerate(self.pw_ids):
            ep = pv[f"PWEP_{istr}"]
            dt = (tb.hi + tb.lo) - ((ep.hi + ep.lo) - ref) * SECS_PER_DAY
            ph = _val(pv, f"PWPH_{istr}") \
                + _val(pv, f"PWF0_{istr}") * dt \
                + _val(pv, f"PWF1_{istr}") * dt * dt / 2.0 \
                + _val(pv, f"PWF2_{istr}") * dt ** 3 / 6.0
            total = total + cache["pw_masks"][:, k] * ph
        return DD(total, z)

    _LD_PW = ("PWPH_", "PWF0_", "PWF1_", "PWF2_")

    def linear_design_names(self):
        # PWEP_ (the piece epoch) pivots its dt: pieces with a fitted
        # epoch keep ALL their params on AD
        out = []
        for idx, istr in self.pw_ids:
            if not self.params[f"PWEP_{istr}"].frozen:
                continue
            out += [f"{pre}{istr}" for pre in self._LD_PW
                    if f"{pre}{istr}" in self.params
                    and not self.params[f"{pre}{istr}"].frozen]
        return out

    def linear_design_local(self, pv, batch, cache, ctx):
        """Exact partials of the piecewise spin phase: mask,
        mask*dt, mask*dt^2/2, mask*dt^3/6 per piece (mirrors phase)."""
        names = set(self.linear_design_names())
        if not names:
            return {}
        ref = self._parent.ref_day
        tb = ctx["tb"]
        tb_f = tb.hi + tb.lo
        out = {}
        for k, (idx, istr) in enumerate(self.pw_ids):
            if not any(f"{pre}{istr}" in names for pre in self._LD_PW):
                continue
            ep = pv[f"PWEP_{istr}"]
            dt = tb_f - ((ep.hi + ep.lo) - ref) * SECS_PER_DAY
            m = cache["pw_masks"][:, k].astype(tb_f.dtype)
            for pre, g in (("PWPH_", m), ("PWF0_", m * dt),
                           ("PWF1_", m * dt * dt / 2.0),
                           ("PWF2_", m * dt ** 3 / 6.0)):
                if f"{pre}{istr}" in names:
                    out[f"{pre}{istr}"] = ("phase", g)
        return out


# ------------------------------------------------- piecewise solar wind


class SolarWindDispersionX(DelayComponent):
    """Piecewise solar-wind amplitude over MJD windows (reference:
    solar_wind_dispersion.SolarWindDispersionX): SWXDM_0001 with
    SWXR1_/SWXR2_ bounds. SWXDM is the window's solar-wind DM scale;
    the per-TOA DM is SWXDM times the line-of-sight geometry factor
    normalized to its maximum within the window (so SWXDM reads as the
    max DM contribution in that window; the geometry is precomputed at
    the nominal astrometry — its dependence on sky-position updates is
    second order)."""

    category = "solar_windx"
    register = True

    def __init__(self):
        super().__init__()
        for pre, unit in (("SWXDM_", "pc cm^-3"), ("SWXR1_", "MJD"),
                          ("SWXR2_", "MJD")):
            self.add_param(prefixParameter(prefix=pre, index=1,
                                           index_str="0001", units=unit))
        self.swx_ids: list = []

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"SWXDM_*": parse_unit("pc cm^-3"),
                "SWXR1_*": parse_unit("d"),
                "SWXR2_*": parse_unit("d")}

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("SWXDM_"):
                _, istr, idx = split_prefixed_name(name)
                if self.params[name].value is not None:
                    ids.append((idx, istr))
        self.swx_ids = sorted(ids)

    def validate(self):
        for idx, istr in self.swx_ids:
            for pre in ("SWXR1_", "SWXR2_"):
                if self.params.get(f"{pre}{istr}") is None or \
                        self.params[f"{pre}{istr}"].value is None:
                    raise ValueError(f"SWXDM_{istr} missing {pre}{istr}")

    def prepare(self, toas, batch, cache, prefix=""):
        if not self.swx_ids:
            return
        # host geometry at nominal astrometry (see class docstring)
        geom = solar_wind_geometry_host(
            toas, self._parent._host_psr_dir(toas))
        mjd = toas.get_mjds()
        cols = []
        for idx, istr in self.swx_ids:
            r1 = self.params[f"SWXR1_{istr}"].value
            r2 = self.params[f"SWXR2_{istr}"].value
            m = (mjd >= r1) & (mjd <= r2)
            gmax = geom[m].max() if np.any(m) else 1.0
            cols.append(np.where(m, geom / gmax, 0.0))
        cache["swx_cols"] = np.stack(cols, axis=-1)

    def dm_value_device(self, pv, batch, cache, ctx):
        """SWX DM contribution [pc/cm^3] — also feeds the wideband DM
        channel via TimingModel.dm_total_device (reference: SWX
        dm_value summed into total DM). No ctx dependence: the
        geometry columns are host-precomputed at nominal astrometry
        (class docstring), so the sparse DM-row Jacobian needs no
        astrometry coupling for SWX."""
        if not self.swx_ids:
            return jnp.zeros_like(batch.freq_mhz)
        vals = jnp.stack([_val(pv, f"SWXDM_{istr}")
                          for _, istr in self.swx_ids])
        return cache["swx_cols"] @ vals

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.swx_ids:
            return jnp.zeros_like(batch.freq_mhz)
        dm = self.dm_value_device(pv, batch, cache, ctx)
        bf = ctx.get("bfreq", batch.freq_mhz)
        return DMconst * dm / (bf * bf)

    def linear_design_names(self):
        return [f"SWXDM_{istr}" for _, istr in self.swx_ids
                if not self.params[f"SWXDM_{istr}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(SWXDM_i) = DMconst * geom_col_i / nu^2 (the
        precomputed normalized-geometry window columns)."""
        if not self.swx_ids:
            return {}
        bf = ctx.get("bfreq", batch.freq_mhz)
        inv2 = DMconst / (bf * bf)
        cols = cache["swx_cols"]
        return {f"SWXDM_{istr}": ("pre_delay",
                                  inv2 * cols[:, c].astype(bf.dtype))
                for c, (_, istr) in enumerate(self.swx_ids)
                if not self.params[f"SWXDM_{istr}"].frozen}


# ----------------------------------------------------------- FD jumps


class FDJump(DelayComponent):
    """Per-system frequency-dependent delays (reference: fdjump.FDJump):
    ``FD1JUMP -fe Rcvr_800 1e-5 1`` applies FD-order-1 terms to the
    selected TOAs only; plain ``FDJUMP`` lines are order 1. delay =
    sum_jumps value * ln(nu/GHz)^order * mask."""

    category = "fdjump"
    register = True

    def __init__(self):
        super().__init__()
        self.fdjumps: list = []  # (order, param name)

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        # FD{n}JUMP{i} names don't fit the numeric-suffix star
        # convention — enumerate the materialized family instead
        s = parse_unit("s")
        return {name: s for name in self.params if "JUMP" in name}

    def add_fdjump(self, order, key, key_value, value=0.0, frozen=True,
                   index=None):
        base = "FDJUMP" if order == 1 else f"FD{order}JUMP"
        idx = index or (sum(1 for o, _ in self.fdjumps if o == order)
                        + 1)
        p = maskParameter(base, index=idx, key=key, key_value=key_value,
                          value=value, frozen=frozen, units="s")
        self.add_param(p)
        self.setup()
        return p

    def setup(self):
        self.fdjumps = []
        for name in self.params:
            if name.startswith("FDJUMP"):
                self.fdjumps.append((1, name))
            elif name.startswith("FD") and "JUMP" in name:
                order = int(name[2:name.index("JUMP")])
                self.fdjumps.append((order, name))

    def prepare(self, toas, batch, cache, prefix=""):
        for order, name in self.fdjumps:
            cache[f"mask_{name}"] = self.params[
                name].select_mask(toas).astype(np.float64)

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        z = jnp.zeros_like(batch.freq_mhz)
        if not self.fdjumps:
            return z
        bf = ctx.get("bfreq", batch.freq_mhz)
        logf = jnp.log(bf / 1000.0)
        total = z
        for order, name in self.fdjumps:
            if name in pv:
                total = total + _val(pv, name) * logf ** order * \
                    cache[f"mask_{name}"]
        return jnp.where(jnp.isfinite(bf), total, 0.0)

    def linear_design_names(self):
        return [name for _, name in self.fdjumps
                if not self.params[name].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(FDnJUMPi) = ln(nu/GHz)^n * mask_i."""
        if not self.fdjumps:
            return {}
        bf = ctx.get("bfreq", batch.freq_mhz)
        fin = jnp.isfinite(bf)
        logf = jnp.log(jnp.where(fin, bf, 1000.0) / 1000.0)
        return {name: ("pre_delay", jnp.where(
                    fin, logf ** order * cache[f"mask_{name}"], 0.0))
                for order, name in self.fdjumps
                if not self.params[name].frozen}
