"""Absolute phase anchor: TZRMJD/TZRSITE/TZRFRQ.

Reference: src/pint/models/absolute_phase.py (AbsPhase): a one-TOA
internal TOAs set at the TZR point defines phase zero; TimingModel
subtracts phase(TZR) from every phase when abs_phase=True. The TZR
mini-batch itself is built host-side in TimingModel._make_tzr_toas and
lives in the evaluation cache — this component only declares the
parameters.
"""

from __future__ import annotations

from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    strParameter,
)
from pint_tpu.models.timing_model import PhaseComponent
from pint_tpu.ops.dd import DD
import jax.numpy as jnp


class AbsPhase(PhaseComponent):
    """Absolute-phase anchor parameters (reference:
    src/pint/models/absolute_phase.py AbsPhase): declares
    TZRMJD/TZRSITE/TZRFRQ; the TZR mini-batch is built host-side in
    TimingModel._make_tzr_toas and the phase subtraction happens in
    the compiled phase chain, so this component's device phase is
    identically zero."""

    category = "phase_offset"

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        return {"TZRMJD": parse_unit("d"), "TZRFRQ": parse_unit("MHz")}

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter(
            "TZRMJD", description="zero-phase reference TOA"))
        self.add_param(strParameter("TZRSITE", value="ssb"))
        self.add_param(floatParameter("TZRFRQ", units="MHz", value=None,
                                      frozen=True))

    def validate(self):
        if self.TZRMJD.value is None:
            raise ValueError("AbsPhase requires TZRMJD")

    def phase(self, pv, batch, cache, ctx, tb):
        z = jnp.zeros_like(batch.freq_mhz)
        return DD(z, z)
