"""Timing-model layer (reference: src/pint/models/__init__.py).

Importing this package registers the core component zoo and exposes the
builder entry points.
"""

from pint_tpu.models.timing_model import (  # noqa: F401
    Component,
    DelayComponent,
    PhaseComponent,
    TimingModel,
    component_types,
)
from pint_tpu.models import absolute_phase  # noqa: F401
from pint_tpu.models import astrometry  # noqa: F401
from pint_tpu.models import dispersion  # noqa: F401
from pint_tpu.models import jump  # noqa: F401
from pint_tpu.models import phase_offset  # noqa: F401
from pint_tpu.models import solar_system_shapiro  # noqa: F401
from pint_tpu.models import spindown  # noqa: F401
from pint_tpu.models.model_builder import (  # noqa: F401
    ModelBuilder,
    get_model,
    get_model_and_toas,
)

__all__ = [
    "Component",
    "DelayComponent",
    "PhaseComponent",
    "TimingModel",
    "component_types",
    "ModelBuilder",
    "get_model",
    "get_model_and_toas",
]
