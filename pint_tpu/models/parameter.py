"""Typed timing-model parameters.

Reference: src/pint/models/parameter.py (Parameter and its zoo:
floatParameter, MJDParameter, AngleParameter, strParameter,
boolParameter, intParameter, maskParameter, prefixParameter).

Design change vs the reference: no astropy — each parameter carries a
static unit *tag* (string) and stores its value as a plain float in its
declared unit; angle parameters store radians and parse/format
sexagesimal; MJD and high-precision float parameters additionally keep a
host double-double (hi, lo) pair so values parsed from 19-digit par
strings never lose bits. The device sees only (hi, lo) vectors — unit
discipline is enforced on the host at build time, costing nothing under
jit (SURVEY.md §5 "race detection" note).
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

from pint_tpu.ops import dd_np

__all__ = [
    "Parameter", "floatParameter", "MJDParameter", "AngleParameter",
    "strParameter", "boolParameter", "intParameter", "maskParameter",
    "prefixParameter", "pairParameter", "funcParameter",
    "split_prefixed_name",
]


_PREFIX_RE = re.compile(r"^([A-Za-z0-9]+_|[A-Za-z]+)(\d+)$")


def split_prefixed_name(name: str) -> Tuple[str, str, int]:
    """'F12' → ('F', '12', 12); 'DMX_0001' → ('DMX_', '0001', 1)
    (reference: src/pint/utils.py split_prefixed_name)."""
    m = _PREFIX_RE.match(name)
    if not m:
        raise ValueError(f"{name!r} is not a prefixed parameter name")
    return m.group(1), m.group(2), int(m.group(2))


def parse_float_dd(s: str):
    """Parse a decimal-string float into a host dd pair, exactly.

    Splits mantissa digits into two 16-digit legs so e.g.
    '61.485476554373152396' keeps all bits (f64 alone drops ~5 digits).
    """
    s = s.strip().lower().replace("d", "e")
    m = re.match(r"^([+-]?)(\d*)\.?(\d*)(?:e([+-]?\d+))?$", s)
    if not m or not (m.group(2) or m.group(3)):
        raise ValueError(f"bad float literal {s!r}")
    sign = -1.0 if m.group(1) == "-" else 1.0
    ip, fp = m.group(2) or "", m.group(3) or ""
    exp = int(m.group(4) or 0) - len(fp)
    digits = (ip + fp).lstrip("0") or "0"
    # value = digits * 10^exp, accumulated in 16-digit legs (three legs
    # cover 48 significant digits — beyond dd's ~32 — so formatted dd
    # values round-trip bit-exactly including the hi+lo f64 rounding)
    val = dd_np.dd(0.0)
    pos = 0
    for leg in range(3):
        chunk = digits[pos:pos + 16]
        if not chunk:
            break
        val = dd_np.add(
            val,
            dd_np.mul(dd_np.dd(float(int(chunk))),
                      _pow10_dd(exp + len(digits) - pos - len(chunk))))
        pos += 16
    return (sign * val[0], sign * val[1])


def _pow10_dd(n: int):
    """10^n as a dd pair (exact for |n| <= 22, accurate beyond)."""
    if 0 <= n <= 22:
        return dd_np.dd(10.0 ** n)
    if -22 <= n < 0:
        return dd_np.div(dd_np.dd(1.0), dd_np.dd(10.0 ** (-n)))
    half = n // 2
    return dd_np.mul(_pow10_dd(half), _pow10_dd(n - half))


class Parameter:
    """Base parameter: name, unit tag, value, frozen flag, uncertainty."""

    par_dtype = float

    def __init__(self, name: str, value=None, units: str = "",
                 description: str = "", frozen: bool = True,
                 aliases: Optional[List[str]] = None, uncertainty=None,
                 **kw):
        self.name = name
        self.units = units
        self.description = description
        self.frozen = frozen
        self.aliases = list(aliases or [])
        self.uncertainty = uncertainty
        self.prior = None  # None == improper flat (see prior_logpdf)
        self._dd = None
        self.value = value

    # -- Bayesian hooks ------------------------------------------------
    # (reference: Parameter.prior_pdf in src/pint/models/parameter.py)

    def prior_logpdf(self, x=None):
        """log prior density at x (default: the current value). A None
        prior is the improper flat prior: logpdf 0 everywhere."""
        v = self.value if x is None else x
        if self.prior is None:
            return 0.0
        return self.prior.logpdf(v)

    def prior_pdf(self, x=None):
        return float(np.exp(self.prior_logpdf(x)))

    # -- value handling ------------------------------------------------

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        if v is not None and isinstance(v, str):
            v = self._parse_value(v)
        self._value = v
        if not isinstance(v, (int, float, np.floating)) or \
                isinstance(self, (strParameter, boolParameter)):
            self._dd = None
        elif self._dd is None or dd_np.to_f64(self._dd) != v:
            # two_sum of a non-finite value yields (nan, nan)
            self._dd = (dd_np.dd(float(v)) if np.isfinite(v)
                        else (float(v), 0.0))

    @property
    def quantity(self):  # PINT-compat alias
        return self._value

    @property
    def dd(self):
        """(hi, lo) host dd pair of the value (floats only)."""
        if self._dd is None:
            raise TypeError(f"{self.name} has no numeric dd value")
        return self._dd

    def set_dd(self, pair):
        self._dd = (float(pair[0]), float(pair[1]))
        self._value = self._dd[0] + self._dd[1]

    def add_delta(self, delta: float):
        """value += delta in dd (fit updates keep sub-f64 residue)."""
        self.set_dd(dd_np.add_f(self.dd, float(delta)))

    def _parse_value(self, tok: str):
        return float(tok.lower().replace("d", "e"))

    def _format_value(self) -> str:
        if self._dd is not None and self._dd[1] != 0.0:
            return dd_np_repr(self._dd)
        v = self._value
        return repr(float(v)) if isinstance(v, (float, np.floating)) \
            else str(v)

    def _format_uncertainty(self) -> str:
        """Uncertainty in the same units _format_value displays."""
        if self.uncertainty is None:
            return "-"
        return f"{self.uncertainty:.3g}"

    # -- par-file I/O --------------------------------------------------

    def from_tokens(self, tokens: List[str]):
        """Parse 'value [fit] [uncertainty]' par tokens."""
        if not tokens:
            raise ValueError(f"{self.name}: empty par line")
        self.value = tokens[0]
        if self.par_dtype is float and len(tokens[0]) > 17:
            try:
                self.set_dd(parse_float_dd(tokens[0]))
            except ValueError:
                pass
        if len(tokens) > 1 and tokens[1] in ("0", "1"):
            self.frozen = tokens[1] == "0"
            if len(tokens) > 2:
                self.uncertainty = self._parse_unc(tokens[2])
        elif len(tokens) > 1:
            # "KEY value uncertainty" (no fit flag) is legal
            try:
                self.uncertainty = self._parse_unc(tokens[1])
            except ValueError:
                pass

    def _parse_unc(self, tok: str) -> float:
        return abs(float(tok.lower().replace("d", "e")))

    def as_parfile_line(self) -> str:
        if self._value is None:
            return ""
        line = f"{self.name:<15} {self._format_value():>25}"
        if not self.frozen:
            line += " 1"
            if self.uncertainty is not None:
                line += f" {self.uncertainty:.8g}"
        return line + "\n"

    def __repr__(self):
        tag = "" if self.frozen else " (free)"
        return (f"<{type(self).__name__} {self.name}="
                f"{self._value!r} {self.units}{tag}>")


def dd_np_repr(pair) -> str:
    """Format a dd pair with enough digits to round-trip (~31 sig figs),
    via integer-scaled decimal reconstruction."""
    hi, lo = pair
    v = hi + lo
    if v == 0.0 or not np.isfinite(v):
        # plain-float repr: numpy-2 scalar reprs ('np.float64(inf)')
        # would not survive a par-file round trip
        return repr(float(hi))
    # Decimal digits: print hi+lo by accumulating decimal remainders
    from decimal import Decimal, getcontext
    getcontext().prec = 50
    return str((Decimal(hi) + Decimal(lo)).normalize())


class floatParameter(Parameter):
    """Plain float with a unit tag; optionally long-precision (dd) when
    parsed from >17-digit strings (F0 and friends)."""


class intParameter(Parameter):
    par_dtype = int

    def _parse_value(self, tok):
        return int(float(tok))


class boolParameter(Parameter):
    par_dtype = bool

    def _parse_value(self, tok):
        return tok.strip().upper() in ("1", "Y", "YES", "T", "TRUE")

    def _format_value(self):
        return "Y" if self._value else "N"


class strParameter(Parameter):
    par_dtype = str

    def _parse_value(self, tok):
        return tok


class MJDParameter(Parameter):
    """Epoch parameter (PEPOCH, T0, TASC, TZRMJD...): value is MJD;
    internally an exact (day, frac) split via dd."""

    def __init__(self, name, units: str = "MJD", **kw):
        super().__init__(name, units=units, **kw)

    def _parse_value(self, tok):
        from pint_tpu.time.mjd import parse_mjd_string

        day, frac = parse_mjd_string(tok)
        self._dd = dd_np.add_f(frac, day)
        return self._dd[0] + self._dd[1]

    @property
    def day_frac(self):
        """(int day f64, frac dd pair), exact."""
        d = np.round(self._dd[0])
        return d, dd_np.add_f(dd_np.dd(self._dd[0] - d, self._dd[1]), 0.0)

    @Parameter.value.setter  # type: ignore[misc]
    def value(self, v):
        if isinstance(v, str):
            v = self._parse_value(v)
        elif v is not None:
            self._dd = dd_np.dd(float(v))
            v = float(v)
        self._value = v

    def _format_value(self):
        from pint_tpu.time.mjd import mjd_to_str

        d, frac = self.day_frac
        return mjd_to_str(d, frac)


class AngleParameter(Parameter):
    """Angle stored in **radians**; par I/O in the declared unit:
    'H:M:S' (RAJ), 'D:M:S' (DECJ), or 'deg' (ELONG/ELAT).

    Reference: AngleParameter with astropy Angle; uncertainties here are
    reported in the same sexagesimal seconds as the reference par files.
    """

    def __init__(self, name, value=None, units="deg", **kw):
        super().__init__(name, value=value, units=units, **kw)

    def _parse_value(self, tok):
        if ":" in tok:
            parts = [float(p) for p in tok.split(":")]
            while len(parts) < 3:
                parts.append(0.0)
            sign = -1.0 if tok.strip().startswith("-") else 1.0
            mag = abs(parts[0]) + parts[1] / 60.0 + parts[2] / 3600.0
            if self.units == "H:M:S":
                return sign * mag * (np.pi / 12.0)
            return sign * mag * (np.pi / 180.0)
        v = float(tok)
        if self.units == "H:M:S":
            return v * (np.pi / 12.0)
        return v * (np.pi / 180.0)

    def _parse_unc(self, tok):
        # par-file uncertainties on sexagesimal angles are in seconds of
        # the respective unit (s of RA, arcsec of DEC)
        v = abs(float(tok))
        if self.units == "H:M:S":
            return v / 3600.0 * (np.pi / 12.0)
        if self.units == "D:M:S":
            return v / 3600.0 * (np.pi / 180.0)
        return v * (np.pi / 180.0)

    def _format_value(self):
        rad = self._value
        if self.units == "H:M:S":
            tot = rad * (12.0 / np.pi)
            unit_s = 3600.0
        elif self.units == "D:M:S":
            tot = rad * (180.0 / np.pi)
            unit_s = 3600.0
        else:
            return f"{rad * (180.0 / np.pi):.15f}"
        sign = "-" if tot < 0 else ""
        tot = abs(tot)
        h = int(tot)
        m = int((tot - h) * 60.0)
        s = (tot - h - m / 60.0) * unit_s
        if s >= 59.999999999995:  # carry
            s = 0.0
            m += 1
            if m == 60:
                m = 0
                h += 1
        return f"{sign}{h:02d}:{m:02d}:{s:.11f}"

    def _format_uncertainty(self):
        """Sexagesimal seconds (of RA hour / of arc), matching
        _parse_unc and the par-file convention."""
        if self.uncertainty is None:
            return "-"
        if self.units == "H:M:S":
            return f"{self.uncertainty * (12.0 / np.pi) * 3600.0:.3g}"
        if self.units == "D:M:S":
            return f"{self.uncertainty * (180.0 / np.pi) * 3600.0:.3g}"
        return f"{self.uncertainty * (180.0 / np.pi):.3g}"


class funcParameter(Parameter):
    """Read-only parameter derived from other model parameters
    (reference: funcParameter): ``func`` maps the values of ``params``
    (looked up on the attached model) to this parameter's value.
    Never fittable; excluded from par files."""

    def __init__(self, name, func, params, units: str = "",
                 description: str = "", **kw):
        self._func = func
        self._source_params = tuple(params)
        self._model = None
        super().__init__(name, value=None, units=units,
                         description=description, frozen=True, **kw)
        self._value = None

    @property
    def quantity(self):
        # keep the PINT-compat alias pointing at the derived value
        # (the inherited property reads _value, which is always None)
        return self.value

    @quantity.setter
    def quantity(self, v):
        if v is not None:
            raise AttributeError(
                f"{self.name} is derived ({self._source_params}); "
                "set its source parameters instead")

    def attach(self, model):
        self._model = model
        return self

    @property
    def value(self):
        if self._model is None:
            return None
        vals = []
        for nm in self._source_params:
            p = self._model.get_param(nm)
            if p.value is None:
                return None
            vals.append(p.value)
        return self._func(*vals)

    @value.setter
    def value(self, v):
        if v is not None:
            raise AttributeError(
                f"{self.name} is derived ({self._source_params}); "
                "set its source parameters instead")

    def as_parfile_line(self):
        return ""  # derived: never written


class maskParameter(floatParameter):
    """Parameter applying to a TOA subset selected by flag/MJD/freq/tel
    (reference: maskParameter; e.g. ``JUMP -fe L-wide 0.000216 1``).

    ``key`` is '-flagname' or one of 'mjd', 'freq', 'tel', 'name';
    ``key_value`` the matching value(s). Instances are numbered:
    JUMP1, JUMP2, ... with ``prefix`` = 'JUMP'.
    """

    def __init__(self, name, index=1, key=None, key_value=(), **kw):
        self.prefix = name
        self.index = index
        self.key = key
        self.key_value = list(key_value)
        super().__init__(f"{name}{index}", **kw)

    def from_tokens(self, tokens):
        """Parse '[-flag value | mjd a b | freq a b | tel t] value [fit]
        [unc]' — the mask key tokens precede the value."""
        toks = list(tokens)
        if not toks:
            raise ValueError(f"{self.name}: empty mask par line")
        k = toks[0].lower()
        if toks[0].startswith("-"):
            self.key = toks[0]
            self.key_value = [toks[1]]
            toks = toks[2:]
        elif k in ("mjd", "freq"):
            self.key = k
            self.key_value = [float(toks[1]), float(toks[2])]
            toks = toks[3:]
        elif k in ("tel", "name"):
            self.key = k
            self.key_value = [toks[1]]
            toks = toks[2:]
        super().from_tokens(toks)

    def select_mask(self, toas) -> np.ndarray:
        """Boolean (N,) mask of TOAs this parameter applies to
        (reference: src/pint/toa_select.py TOASelect)."""
        n = toas.ntoas
        if self.key is None:
            return np.ones(n, dtype=bool)
        if self.key.startswith("-"):
            flag = self.key[1:]
            want = str(self.key_value[0])
            return np.array(
                [f.get(flag) == want for f in toas.flags])
        if self.key == "mjd":
            m = toas.get_mjds()
            lo, hi = self.key_value
            return (m >= lo) & (m <= hi)
        if self.key == "freq":
            lo, hi = self.key_value
            return (toas.freq_mhz >= lo) & (toas.freq_mhz <= hi)
        if self.key in ("tel", "name"):
            want = str(self.key_value[0]).lower()
            if self.key == "tel":
                from pint_tpu.observatory import get_observatory

                want_site = get_observatory(want).name
                return np.array([o == want_site for o in toas.obs])
            return np.array([nm == want for nm in toas.names])
        raise ValueError(f"unknown mask key {self.key!r}")

    def as_parfile_line(self):
        if self._value is None:
            return ""
        if self.key is None:
            keypart = ""
        elif self.key.startswith("-"):
            keypart = f"{self.key} {self.key_value[0]} "
        else:
            keypart = f"{self.key.upper()} " + " ".join(
                str(v) for v in self.key_value) + " "
        line = f"{self.prefix:<8} {keypart}{self._format_value()}"
        if not self.frozen:
            line += " 1"
            if self.uncertainty is not None:
                line += f" {self.uncertainty:.8g}"
        return line + "\n"


class prefixParameter(floatParameter):
    """One member of an indexed family (F2.., DMX_0001, GLF0_1...).

    ``prefix`` includes any trailing underscore ('DMX_'); the par name is
    prefix+index with the original zero padding preserved.
    """

    def __init__(self, name=None, prefix=None, index=0, index_str=None,
                 **kw):
        if name is not None and prefix is None:
            prefix, index_str, index = split_prefixed_name(name)
        self.prefix = prefix
        self.index = index
        self.index_str = index_str if index_str is not None else str(index)
        super().__init__(f"{prefix}{self.index_str}", **kw)


class pairParameter(Parameter):
    """Two-float parameter (reference: pairParameter, used by IFUNC/WAVE
    entries ``WAVE1 a b``)."""

    def __init__(self, name, value=(0.0, 0.0), **kw):
        super().__init__(name, value=None, **kw)
        self._value = tuple(float(v) for v in value)

    def from_tokens(self, tokens):
        self._value = (float(tokens[0]), float(tokens[1]))

    def _format_value(self):
        return f"{self._value[0]!r} {self._value[1]!r}"

    def as_parfile_line(self):
        return f"{self.name:<15} {self._format_value()}\n"
