"""Spindown: rotational phase Σ Fᵢ·dtⁱ⁺¹/(i+1)!.

Reference: src/pint/models/spindown.py (Spindown.spindown_phase,
F0..Fn prefix parameters, PEPOCH). The F0·dt product runs in
double-double — 1e10 turns must stay good to <1e-9 turns — via
dd_taylor_horner with DD coefficients (each Fi arrives as a DD scalar
from the packed parameter vector, so 19-digit par values keep all bits).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    prefixParameter,
    split_prefixed_name,
)
from pint_tpu.models.timing_model import SECS_PER_DAY, PhaseComponent
from pint_tpu.ops.dd import DD, dd_mul_f, dd_sub, dd_sub_f
from pint_tpu.ops.taylor import dd_taylor_horner


class Spindown(PhaseComponent):
    """Rotational phase Σ Fᵢ·dtⁱ⁺¹/(i+1)! (reference:
    src/pint/models/spindown.py Spindown.spindown_phase; F0..Fn
    prefix family, PEPOCH). The F0·dt product runs in double-double
    via dd_taylor_horner so 19-digit par values keep all bits."""

    category = "spindown"

    def __init__(self):
        super().__init__()
        f0 = self.add_param(floatParameter(
            "F0", units="Hz", frozen=True,
            description="spin frequency"))
        f1 = self.add_param(floatParameter("F1", units="Hz/s^1",
                                           value=0.0))
        # F0/F1 stay floatParameters (their dd packing differs from
        # the F2+ prefix family) but still belong to the 'F' prefix
        # family for get_prefix_mapping enumeration, as in PINT
        f0.prefix, f0.index = "F", 0
        f1.prefix, f1.index = "F", 1
        self.add_param(MJDParameter(
            "PEPOCH", description="epoch of spin parameters"))

    def setup(self):
        # F2, F3... arrive via model_builder add_prefix_param
        pass

    def validate(self):
        if self.F0.value is None:
            raise ValueError("Spindown requires F0")

    def param_dimensions(self):
        from pint_tpu.models.parameter import split_prefixed_name
        from pint_tpu.units import parse_unit

        def f_dim(name):
            if name in ("F0", "F1"):
                i = int(name[1])
            else:
                _, _, i = split_prefixed_name(name)
            return parse_unit("Hz") / parse_unit("s") ** i

        return {"F*": f_dim, "F0": f_dim, "F1": f_dim,
                "PEPOCH": parse_unit("d")}

    def f_terms(self):
        """Ordered [F0, F1, F2, ...] parameter names present."""
        out = ["F0"]
        if "F1" in self.params:
            out.append("F1")
        extras = []
        for name in self.params:
            if name.startswith("F") and name not in ("F0", "F1"):
                try:
                    _, _, idx = split_prefixed_name(name)
                    extras.append((idx, name))
                except ValueError:
                    continue
        out.extend(nm for _, nm in sorted(extras))
        return out

    def add_f_term(self, index, value=0.0, frozen=True, uncertainty=None):
        p = prefixParameter(prefix="F", index=index, value=value,
                            units=f"Hz/s^{index}", frozen=frozen,
                            uncertainty=uncertainty)
        self.add_param(p)
        return p

    def dt(self, pv, tb: DD) -> DD:
        """tb is seconds since model ref_day; shift to seconds since
        PEPOCH. (PEPOCH − ref) is ≲ tens of days → dd keeps it exact."""
        pep_days = dd_sub_f(pv["PEPOCH"], self._parent.ref_day)
        return dd_sub(tb, dd_mul_f(pep_days, SECS_PER_DAY))

    def phase(self, pv, batch, cache, ctx, tb: DD) -> DD:
        dt = self.dt(pv, tb)
        coeffs = [DD(jnp.zeros_like(dt.hi), jnp.zeros_like(dt.hi))]
        coeffs += [pv[nm] for nm in self.f_terms()]
        return dd_taylor_horner(dt, coeffs)

    def linear_design_names(self):
        """F1+ only. The spin phase is exactly linear in every F_i
        (d(phase)/d(F_i) = dt^{i+1}/(i+1)!), but F0 ALSO appears in
        other components' phases (PhaseJump/Wave/IFunc scale their
        second-offsets by F0), so claiming F0 here would require every
        consumer to contribute its share — one AD tangent is the
        safer trade. PEPOCH fitted => dt pivots => all on AD."""
        if not self.PEPOCH.frozen or self.PEPOCH.value is None:
            return []
        return [nm for nm in self.f_terms()
                if nm != "F0" and not self.params[nm].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        import math

        names = self.linear_design_names()
        if not names:
            return {}
        dt_dd = self.dt(pv, ctx["tb"])
        dts = dt_dd.hi + dt_dd.lo  # f64/f32 suffices: columns need
        # only ~1e-7 relative accuracy (they feed equilibrated normal
        # equations), unlike the phase value itself
        terms = self.f_terms()
        return {nm: ("phase",
                     dts ** (i + 1) / math.factorial(i + 1))
                for i, nm in enumerate(terms) if nm in names}
