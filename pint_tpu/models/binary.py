"""Binary pulsar models: ELL1/ELL1H, BT, DD/DDS.

Reference: src/pint/models/pulsar_binary.py (PulsarBinary wrapper) +
src/pint/models/stand_alone_psr_binaries/ (BT_model.py, DD_model.py,
ELL1_model.py, ELL1H_model.py, binary_orbits.py). The reference splits
wrapper (units, Parameters) from numpy standalone kernels; here the
"standalone kernel" is simply the pure-jnp ``binary_delay`` method —
unit handling lives in the parameter definitions, derivatives come from
jacfwd through the (fixed-iteration, jit-friendly) Kepler solve instead
of the reference's hand-coded ``prtl_der`` chains.

Formulas follow SURVEY.md Appendix A.5:
- Kepler: E - e sinE = M, Newton with a fixed 10-iteration unroll
  (converges to f64 round-off for e < 0.95; branch-free).
- DD (Damour-Deruelle 1986): alpha = x sin(omega), beta =
  x sqrt(1-etheta^2) cos(omega); Dre = alpha (cosE - er) +
  (beta + gamma) sinE with the inverse-timing expansion
  Dre (1 - nhat Drep + (nhat Drep)^2 + 1/2 nhat^2 Dre Drepp - 1/2
  e sinE/(1-e cosE) nhat^2 Dre Drep); Shapiro
  -2 r ln(1 - e cosE - s [sin(omega)(cosE - e) +
  sqrt(1-e^2) cos(omega) sinE]).
- BT (Blandford-Teukolsky 1976): same Roemer/Einstein structure with
  er = etheta = e and no Shapiro.
- ELL1 (Lange et al. 2001): Phi = mean phase from TASC; Dre =
  x [sinPhi + (eps2/2) sin2Phi - (eps1/2) cos2Phi]; Shapiro
  -2 r ln(1 - s sinPhi). ELL1H re-parameterizes Shapiro with
  orthometric H3/H4/STIG (Freire & Wex 2010).

Orbits: PB/PBDOT or the FB0..FBn orbital-frequency series (reference:
binary_orbits.py OrbitPB/OrbitFBX), selected by FB0's presence.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    prefixParameter,
)
from pint_tpu.models.timing_model import DelayComponent
from pint_tpu.ops.dd import (
    DD,
    dd_add_f,
    dd_div_f,
    dd_frac,
    dd_mul_f,
    dd_sub,
    dd_sub_f,
    dd_to_f64,
    dd_where,
)

SECS_PER_DAY = 86400.0
SECS_PER_YEAR = 365.25 * SECS_PER_DAY
DEG2RAD = np.pi / 180.0
TSUN = 4.925490947e-6  # GM_sun/c^3 [s]
TWOPI = 2.0 * np.pi


def _v(pv, name, default=0.0):
    """Traced f64 value of a (possibly absent) parameter."""
    p = pv.get(name)
    return (p.hi + p.lo) if p is not None else default


def kepler_E(M, ecc, niter: int = 10):
    """Eccentric anomaly from mean anomaly: fixed-unroll Newton
    (jit/vmap/grad friendly; reference: binary_generic.py
    compute_eccentric_anomaly's iterative solve)."""
    E = M + ecc * jnp.sin(M)
    for _ in range(niter):
        E = E - (E - ecc * jnp.sin(E) - M) / (1.0 - ecc * jnp.cos(E))
    return E


class PulsarBinary(DelayComponent):
    """Base binary component (reference: pulsar_binary.PulsarBinary).

    Subclasses define ``epoch_param`` (T0 or TASC) and
    ``binary_delay(pv, dt, nhat, M, ctx)`` where dt is seconds since the
    orbital epoch, M the mean anomaly/phase [rad], nhat = dM/dt [rad/s].
    """

    category = "pulsar_system"
    register = False
    epoch_param = "T0"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("PB", units="d",
                                      description="orbital period"))
        self.add_param(floatParameter("PBDOT", units="s/s", value=0.0))
        self.add_param(floatParameter("A1", units="ls",
                                      description="projected semi-major axis"))
        self.add_param(floatParameter("A1DOT", units="ls/s", value=0.0,
                                      aliases=["XDOT"]))
        self.add_param(floatParameter("M2", units="Msun"))
        self.add_param(floatParameter("SINI", units=""))
        self.fb_terms: List[str] = []

    def add_fb_term(self, index, value=0.0, frozen=True):
        p = prefixParameter(prefix="FB", index=index,
                            index_str=str(index), value=value,
                            frozen=frozen, units=f"1/s^{index + 1}")
        self.add_param(p)
        self.setup()
        return p

    def setup(self):
        self.fb_terms = sorted(
            (n for n in self.params
             if n.startswith("FB") and n[2:].isdigit()),
            key=lambda n: int(n[2:]))
        # TEMPO convention: *DOT values > 1e-7 are in 1e-12 units
        for name in ("PBDOT", "A1DOT", "EDOT", "EPS1DOT", "EPS2DOT"):
            if name in self.params:
                p = self.params[name]
                if p.value is not None and abs(p.value) > 1e-7:
                    p.value = p.value * 1e-12
                    if p.uncertainty is not None:
                        p.uncertainty = p.uncertainty * 1e-12

    def validate(self):
        if self.params[self.epoch_param].value is None:
            raise ValueError(
                f"{type(self).__name__} requires {self.epoch_param}")
        if self.PB.value is None and not self.fb_terms:
            raise ValueError(
                f"{type(self).__name__} requires PB or FB0")

    def param_dimensions(self):
        from pint_tpu.units import DIMENSIONLESS, parse_unit

        t = parse_unit("s")
        d = parse_unit("d")

        def fb_dim(name):
            return parse_unit("s") ** -(int(name[2:]) + 1)

        return {"PB": d, "PBDOT": DIMENSIONLESS, "A1": parse_unit("ls"),
                "A1DOT": parse_unit("ls/s"), "M2": parse_unit("Msun"),
                "SINI": DIMENSIONLESS, "T0": d, "TASC": d,
                "ECC": DIMENSIONLESS, "EDOT": t ** -1,
                "OM": parse_unit("deg"), "OMDOT": parse_unit("deg/yr"),
                "GAMMA": t, "EPS1": DIMENSIONLESS,
                "EPS2": DIMENSIONLESS, "EPS1DOT": t ** -1,
                "EPS2DOT": t ** -1, "FB*": fb_dim,
                "T0X_*": d, "A1X_*": parse_unit("ls"),
                "XR1_*": d, "XR2_*": d,
                "KIN": parse_unit("deg"), "KOM": parse_unit("deg"),
                "H3": t, "H4": t, "STIG": DIMENSIONLESS,
                "MTOT": parse_unit("Msun"), "XPBDOT": DIMENSIONLESS,
                "XOMDOT": parse_unit("deg/yr"),
                "DR": DIMENSIONLESS, "DTH": DIMENSIONLESS,
                "A0": t, "B0": t, "LNEDOT": t ** -1,
                "SHAPMAX": DIMENSIONLESS}

    # -- orbit machinery ----------------------------------------------

    def _epoch(self, pv, batch, cache):
        """Orbital epoch as DD [MJD] — scalar, or per-TOA for
        piecewise variants (BinaryBTPiecewise overrides)."""
        return pv[self.epoch_param]

    def _dt(self, pv, batch, cache, delay_so_far):
        """Barycentric seconds since the orbital epoch, as DD. Kept in
        dd through the mean-anomaly computation: collapsing to a single
        float first loses the orbit count's low bits (fatal in the f32
        Jacobian path, where a plain float holds only 24 bits of
        ~1e8 s), and dd costs nothing here."""
        ref = self._parent.ref_day
        tb = dd_mul_f(dd_add_f(batch.tdb_frac, batch.tdb_day - ref),
                      SECS_PER_DAY)
        epoch = self._epoch(pv, batch, cache)
        eref = dd_mul_f(dd_add_f(dd_sub_f(epoch, ref), 0.0), SECS_PER_DAY)
        return dd_sub_f(dd_sub(tb, eref), delay_so_far)

    def _mean_anomaly(self, dt_dd, pb_s, pbdot):
        """Reduced mean anomaly M ∈ [-π, π] and nhat = dM/dt.

        The orbit count u = dt/PB reaches ~1e4; computing it in dd and
        reducing mod 1 turn *before* the trig keeps sin/cos arguments
        O(1) — numerically better on every backend (TPU's emulated-f64
        range reduction is only ~2^-48) and required for the f32
        Jacobian path. The reduction is exact algebra: every downstream
        use of M is periodic."""
        u_dd = dd_div_f(dt_dd, pb_s)
        u = dd_to_f64(u_dd)
        orbits = dd_sub_f(u_dd, 0.5 * pbdot * u * u)
        M = TWOPI * dd_to_f64(dd_frac(orbits))
        nhat = (TWOPI / pb_s) * (1.0 - pbdot * u)
        return M, nhat

    def _orbit(self, pv, dt_dd):
        """(M, nhat): reduced mean anomaly/phase [rad] and dM/dt
        [rad/s], from DD dt."""
        if self.fb_terms:
            from pint_tpu.ops.taylor import dd_taylor_horner, \
                taylor_horner_deriv

            zero = jnp.zeros_like(dt_dd.hi)
            coeffs = [DD(zero, zero)] + [pv[n] for n in self.fb_terms]
            orbits = dd_taylor_horner(dt_dd, coeffs)
            M = TWOPI * dd_to_f64(dd_frac(orbits))
            dt = dd_to_f64(dt_dd)
            plain = [jnp.zeros((), dt.dtype)] + \
                [_v(pv, n) for n in self.fb_terms]
            nhat = TWOPI * taylor_horner_deriv(dt, plain, 1)
            return M, nhat
        pb_s = _v(pv, "PB") * SECS_PER_DAY
        return self._mean_anomaly(dt_dd, pb_s, _v(pv, "PBDOT"))

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        dt_dd = self._dt(pv, batch, cache, delay_so_far)
        M, nhat = self._orbit(pv, dt_dd)
        return self.binary_delay(pv, dd_to_f64(dt_dd), M, nhat, ctx)

    def binary_delay(self, pv, dt, M, nhat, ctx):
        raise NotImplementedError

    # -- shared pieces -------------------------------------------------

    @staticmethod
    def _shapiro_rs(pv):
        """(r, s) from M2/SINI [s, 1]."""
        return TSUN * _v(pv, "M2"), _v(pv, "SINI")

    @staticmethod
    def _inverse_timing(Dre, Drep, Drepp, anhat, ecc_sinE_term):
        """The DD inverse-orbit-timing expansion (reference:
        DD_model.py delayR; SURVEY.md A.5)."""
        nd = anhat * Drep
        return Dre * (1.0 - nd + nd * nd
                      + 0.5 * anhat * anhat * Dre * Drepp
                      - 0.5 * ecc_sinE_term * anhat * anhat * Dre * Drep)


class BinaryELL1(PulsarBinary):
    """Small-eccentricity model (reference: binary_ell1.BinaryELL1 /
    ELL1_model.ELL1model)."""

    register = True
    epoch_param = "TASC"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("TASC",
                                    description="ascending-node epoch"))
        self.add_param(floatParameter("EPS1", units="", value=0.0,
                                      description="e sin(omega)"))
        self.add_param(floatParameter("EPS2", units="", value=0.0,
                                      description="e cos(omega)"))
        self.add_param(floatParameter("EPS1DOT", units="1/s", value=0.0))
        self.add_param(floatParameter("EPS2DOT", units="1/s", value=0.0))

    def _roemer(self, pv, dt, Phi, nhat):
        x = _v(pv, "A1") + _v(pv, "A1DOT") * dt
        eps1 = _v(pv, "EPS1") + _v(pv, "EPS1DOT") * dt
        eps2 = _v(pv, "EPS2") + _v(pv, "EPS2DOT") * dt
        sP, cP = jnp.sin(Phi), jnp.cos(Phi)
        s2P, c2P = jnp.sin(2 * Phi), jnp.cos(2 * Phi)
        # the constant -(3/2) eps1 term is part of the O(e) expansion of
        # the Keplerian Roemer delay (Lange et al. 2001); without it
        # ELL1 and BT disagree by a constant 1.5 x e sin(omega)
        Dre = x * (sP + 0.5 * (eps2 * s2P - eps1 * c2P) - 1.5 * eps1)
        Drep = x * (cP + eps2 * c2P + eps1 * s2P)
        Drepp = x * (-sP - 2.0 * eps2 * s2P + 2.0 * eps1 * c2P)
        return self._inverse_timing(Dre, Drep, Drepp, nhat, 0.0)

    def _shapiro(self, pv, Phi):
        r, s = self._shapiro_rs(pv)
        return -2.0 * r * jnp.log(1.0 - s * jnp.sin(Phi))

    def binary_delay(self, pv, dt, M, nhat, ctx):
        return self._roemer(pv, dt, M, nhat) + self._shapiro(pv, M)


class BinaryELL1H(BinaryELL1):
    """ELL1 with orthometric Shapiro parameters H3/H4/STIG
    (reference: binary_ell1.BinaryELL1H / ELL1H_model; Freire & Wex
    2010). With STIG (or H4, via STIG = H4/H3): exact mapping
    r = H3/STIG^3, s = 2 STIG/(1+STIG^2); with H3 alone the
    third-harmonic approximation -(4/3) H3 sin(3 Phi)."""

    register = True

    def __init__(self):
        super().__init__()
        self.remove_param("M2")
        self.remove_param("SINI")
        self.add_param(floatParameter("H3", units="s",
                                      description="3rd Shapiro harmonic"))
        self.add_param(floatParameter("H4", units="s"))
        self.add_param(floatParameter("STIG", units="",
                                      aliases=["VARSIGMA"]))

    def validate(self):
        super().validate()
        if self.H3.value is None:
            raise ValueError("ELL1H requires H3")
        if self.H4.value is not None and self.STIG.value is not None:
            raise ValueError("give H4 or STIG, not both")

    def _shapiro(self, pv, Phi):
        h3 = _v(pv, "H3")
        if self.STIG.value is not None or self.H4.value is not None:
            stig = _v(pv, "STIG") if self.STIG.value is not None else \
                _v(pv, "H4") / h3
            r = h3 / (stig * stig * stig)
            s = 2.0 * stig / (1.0 + stig * stig)
            return -2.0 * r * jnp.log(1.0 - s * jnp.sin(Phi))
        return -(4.0 / 3.0) * h3 * jnp.sin(3.0 * Phi)


class _KeplerBinary(PulsarBinary):
    """Shared eccentric-orbit plumbing for BT/DD."""

    register = False

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("T0",
                                    description="periastron epoch"))
        self.add_param(floatParameter("ECC", units="", value=0.0,
                                      aliases=["E"]))
        self.add_param(floatParameter("EDOT", units="1/s", value=0.0))
        self.add_param(floatParameter("OM", units="deg", value=0.0))
        self.add_param(floatParameter("OMDOT", units="deg/yr", value=0.0))
        self.add_param(floatParameter("GAMMA", units="s", value=0.0))

    def _elements(self, pv, dt):
        """(x, ecc, omega [rad]) with secular drifts applied."""
        x = _v(pv, "A1") + _v(pv, "A1DOT") * dt
        ecc = _v(pv, "ECC") + _v(pv, "EDOT") * dt
        om = (_v(pv, "OM") + _v(pv, "OMDOT") * dt / SECS_PER_YEAR) \
            * DEG2RAD
        return x, ecc, om


class BinaryBT(_KeplerBinary):
    """Blandford-Teukolsky (reference: binary_bt.BinaryBT /
    BT_model.BTmodel): Keplerian Roemer + Einstein, no Shapiro."""

    register = True

    def _x_adjust(self, x, ctx):
        """Hook for per-TOA projected-semi-major-axis adjustments
        (BinaryBTPiecewise overrides)."""
        return x

    def binary_delay(self, pv, dt, M, nhat, ctx):
        x, ecc, om = self._elements(pv, dt)
        x = self._x_adjust(x, ctx)
        E = kepler_E(M, ecc)
        sE, cE = jnp.sin(E), jnp.cos(E)
        alpha = x * jnp.sin(om)
        beta = x * jnp.sqrt(1.0 - ecc * ecc) * jnp.cos(om)
        gamma = _v(pv, "GAMMA")
        Dre = alpha * (cE - ecc) + (beta + gamma) * sE
        Drep = -alpha * sE + (beta + gamma) * cE
        Drepp = -alpha * cE - (beta + gamma) * sE
        anhat = nhat / (1.0 - ecc * cE)
        return self._inverse_timing(
            Dre, Drep, Drepp, anhat, ecc * sE / (1.0 - ecc * cE))


class BinaryDD(_KeplerBinary):
    """Damour-Deruelle (reference: binary_dd.BinaryDD /
    DD_model.DDmodel)."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("DR", units="", value=0.0))
        self.add_param(floatParameter("DTH", units="", value=0.0,
                                      aliases=["DTHETA"]))
        self.add_param(floatParameter("A0", units="s", value=0.0))
        self.add_param(floatParameter("B0", units="s", value=0.0))

    def _shapiro_s(self, pv):
        return _v(pv, "SINI")

    def _dd_core(self, pv, M, nhat, x, ecc, om, gamma, r_shap, s_shap,
                 dr, dth):
        """The full DD delay for explicit orbital elements — shared by
        DD/DDS/DDH/DDGR/DDK, which differ only in how the elements and
        Shapiro (r, s) are obtained."""
        er = ecc * (1.0 + dr)
        eth = ecc * (1.0 + dth)
        E = kepler_E(M, ecc)
        sE, cE = jnp.sin(E), jnp.cos(E)
        sw, cw = jnp.sin(om), jnp.cos(om)
        alpha = x * sw
        beta = x * jnp.sqrt(1.0 - eth * eth) * cw
        # Roemer + Einstein with inverse-timing correction
        Dre = alpha * (cE - er) + (beta + gamma) * sE
        Drep = -alpha * sE + (beta + gamma) * cE
        Drepp = -alpha * cE - (beta + gamma) * sE
        anhat = nhat / (1.0 - ecc * cE)
        roemer = self._inverse_timing(
            Dre, Drep, Drepp, anhat, ecc * sE / (1.0 - ecc * cE))
        # Shapiro
        sqr = jnp.sqrt(1.0 - ecc * ecc)
        shap = -2.0 * r_shap * jnp.log(
            1.0 - ecc * cE - s_shap * (sw * (cE - ecc) + sqr * cw * sE))
        # aberration (A0/B0, usually 0)
        a0, b0 = _v(pv, "A0"), _v(pv, "B0")
        nu = 2.0 * jnp.arctan2(
            jnp.sqrt(1.0 + ecc) * jnp.sin(E / 2.0),
            jnp.sqrt(1.0 - ecc) * jnp.cos(E / 2.0))
        omnu = om + nu
        aberr = a0 * (jnp.sin(omnu) + ecc * sw) + \
            b0 * (jnp.cos(omnu) + ecc * cw)
        return roemer + shap + aberr

    def binary_delay(self, pv, dt, M, nhat, ctx):
        x, ecc, om = self._elements(pv, dt)
        return self._dd_core(pv, M, nhat, x, ecc, om, _v(pv, "GAMMA"),
                             TSUN * _v(pv, "M2"), self._shapiro_s(pv),
                             _v(pv, "DR"), _v(pv, "DTH"))


class BinaryDDS(BinaryDD):
    """DD with SHAPMAX parameterization s = 1 - exp(-SHAPMAX)
    (reference: binary_dd.BinaryDDS / DDS_model)."""

    register = True

    def __init__(self):
        super().__init__()
        self.remove_param("SINI")
        self.add_param(floatParameter("SHAPMAX", units="", value=0.0))

    def _shapiro_s(self, pv):
        return 1.0 - jnp.exp(-_v(pv, "SHAPMAX"))


class BinaryDDH(BinaryDD):
    """DD with orthometric Shapiro parameters H3/STIG (reference:
    binary_dd.BinaryDDH / DDH_model; Freire & Wex 2010): r = H3/STIG^3,
    s = 2 STIG/(1 + STIG^2)."""

    register = True

    def __init__(self):
        super().__init__()
        self.remove_param("M2")
        self.remove_param("SINI")
        self.add_param(floatParameter("H3", units="s",
                                      description="3rd Shapiro harmonic"))
        self.add_param(floatParameter("STIG", units="",
                                      aliases=["VARSIGMA"]))

    def validate(self):
        super().validate()
        if self.H3.value is None or self.STIG.value is None:
            raise ValueError("DDH requires H3 and STIG")

    def binary_delay(self, pv, dt, M, nhat, ctx):
        x, ecc, om = self._elements(pv, dt)
        h3, stig = _v(pv, "H3"), _v(pv, "STIG")
        r = h3 / (stig * stig * stig)
        s = 2.0 * stig / (1.0 + stig * stig)
        return self._dd_core(pv, M, nhat, x, ecc, om, _v(pv, "GAMMA"),
                             r, s, _v(pv, "DR"), _v(pv, "DTH"))


class BinaryDDGR(BinaryDD):
    """DD with general relativity supplying the post-Keplerian
    parameters from the component masses (reference: binary_dd.BinaryDDGR
    / DDGR_model, Damour & Deruelle 1986 paper II; Taylor & Weisberg
    1989 for the PK expressions). MTOT and M2 replace OMDOT, GAMMA,
    SINI, PBDOT(GR), DR, DTH, which become functions of the masses:

        n      = 2 pi / Pb,  m = MTOT Tsun,  m2 = M2 Tsun,  m1 = m-m2
        arr    = (m/n^2)^(1/3)   (relativistic semi-major axis, s)
        omdot  = 3 n^(5/3) m^(2/3) / (1-e^2)          [rad/s]
        gamma  = e m2 (m1 + 2 m2) n^(-1/3) m^(-4/3)   [s]
        sini   = x m^(2/3) n^(2/3) / m2
        pbdot  = -(192 pi/5) n^(5/3) m1 m2 m^(-1/3)
                 (1 + 73/24 e^2 + 37/96 e^4)(1-e^2)^(-7/2)
        dr     = (3 m1^2 + 6 m1 m2 + 2 m2^2)/(arr m)
        dth    = (3.5 m1^2 + 6 m1 m2 + 2 m2^2)/(arr m)

    XOMDOT [deg/yr] and XPBDOT add observed excesses on top of GR."""

    register = True

    def __init__(self):
        super().__init__()
        for name in ("OMDOT", "GAMMA", "SINI", "DR", "DTH"):
            self.remove_param(name)
        self.add_param(floatParameter("MTOT", units="Msun",
                                      aliases=["M"]))
        self.add_param(floatParameter("XOMDOT", units="deg/yr",
                                      value=0.0))
        self.add_param(floatParameter("XPBDOT", units="s/s", value=0.0))

    def validate(self):
        super().validate()
        if self.MTOT.value is None or self.M2.value is None:
            raise ValueError("DDGR requires MTOT and M2")
        if self.PB.value is None:
            raise ValueError(
                "DDGR requires PB (the GR post-Keplerian expressions "
                "are not implemented for the FB series)")

    def _gr_parameters(self, pv, ecc):
        pb_s = _v(pv, "PB") * SECS_PER_DAY
        n = TWOPI / pb_s
        m = TSUN * _v(pv, "MTOT")
        m2 = TSUN * _v(pv, "M2")
        m1 = m - m2
        x = _v(pv, "A1")
        arr = (m / (n * n)) ** (1.0 / 3.0)
        omdot = 3.0 * n ** (5.0 / 3.0) * m ** (2.0 / 3.0) \
            / (1.0 - ecc * ecc)
        gamma = ecc * m2 * (m1 + 2.0 * m2) * n ** (-1.0 / 3.0) \
            * m ** (-4.0 / 3.0)
        sini = x * m ** (2.0 / 3.0) * n ** (2.0 / 3.0) / m2
        fe = (1.0 + (73.0 / 24.0) * ecc ** 2
              + (37.0 / 96.0) * ecc ** 4) * (1.0 - ecc * ecc) ** -3.5
        pbdot = -(192.0 * jnp.pi / 5.0) * n ** (5.0 / 3.0) * m1 * m2 \
            * m ** (-1.0 / 3.0) * fe
        dr = (3.0 * m1 ** 2 + 6.0 * m1 * m2 + 2.0 * m2 ** 2) / (arr * m)
        dth = (3.5 * m1 ** 2 + 6.0 * m1 * m2 + 2.0 * m2 ** 2) / (arr * m)
        return omdot, gamma, sini, pbdot, dr, dth

    def _orbit(self, pv, dt_dd):
        # fold the GR + excess PBDOT into the mean-anomaly evolution
        ecc0 = _v(pv, "ECC")
        _, _, _, pbdot_gr, _, _ = self._gr_parameters(pv, ecc0)
        pb_s = _v(pv, "PB") * SECS_PER_DAY
        pbdot = _v(pv, "PBDOT") + pbdot_gr + _v(pv, "XPBDOT")
        return self._mean_anomaly(dt_dd, pb_s, pbdot)

    def binary_delay(self, pv, dt, M, nhat, ctx):
        ecc = _v(pv, "ECC") + _v(pv, "EDOT") * dt
        omdot_gr, gamma, sini, _, dr, dth = self._gr_parameters(pv, ecc)
        om = _v(pv, "OM") * DEG2RAD + omdot_gr * dt \
            + _v(pv, "XOMDOT") * DEG2RAD * dt / SECS_PER_YEAR
        x = _v(pv, "A1") + _v(pv, "A1DOT") * dt
        return self._dd_core(pv, M, nhat, x, ecc, om, gamma,
                             TSUN * _v(pv, "M2"), sini, dr, dth)


class BinaryDDK(BinaryDD):
    """DD with Kopeikin annual-orbital-parallax and proper-motion
    corrections (reference: binary_ddk.BinaryDDK / DDK_model; Kopeikin
    1995 ApJ 439 L5, Kopeikin 1996 ApJ 467 L93). KIN/KOM give the true
    orbital orientation; the observed x = a sin(i) and omega pick up

      K95 (annual-orbital parallax, needs PX and the observatory SSB
      position r):  with the sky basis I0 (east) and J0 (north) and
      d = 1/PX,
        di    = (Delta_I0 sin KOM - Delta_J0 cos KOM)/d
        domega= -(Delta_I0 cos KOM + Delta_J0 sin KOM)/(d sin KIN)
      K96 (secular proper motion):
        di    += (-mu_alpha sin KOM + mu_delta cos KOM) (t - T0)
        domega+= (mu_alpha cos KOM + mu_delta sin KOM)/sin KIN (t - T0)

    x scales exactly as sin(KIN + di)/sin(KIN); Shapiro s = sin(KIN +
    di). Sign conventions follow the published equations; they cannot
    be re-verified against the reference in this offline environment
    (SURVEY.md §0) and are pinned by the tests' symmetry/limit checks.
    Requires AstrometryEquatorial (RAJ/DECJ basis) and PX."""

    register = True

    def __init__(self):
        super().__init__()
        self.remove_param("SINI")
        self.add_param(floatParameter("KIN", units="deg",
                                      description="orbital inclination"))
        self.add_param(floatParameter("KOM", units="deg",
                                      description="pos. angle of asc. node"))
        from pint_tpu.models.parameter import boolParameter

        self.add_param(boolParameter("K96", value=True,
                                     description="include proper-motion "
                                     "corrections"))

    def validate(self):
        super().validate()
        if self.KIN.value is None or self.KOM.value is None:
            raise ValueError("DDK requires KIN and KOM")
        # the Kopeikin sky basis is built from RAJ/DECJ(+PMRA/PMDEC/PX),
        # which default to 0 in pv — silently wrong with ecliptic
        # astrometry, so refuse instead
        parent = getattr(self, "_parent", None)
        if parent is not None:
            if "AstrometryEquatorial" not in parent.components:
                raise ValueError(
                    "DDK requires equatorial astrometry (RAJ/DECJ): "
                    "the Kopeikin terms are computed in that basis")
            px = parent.components["AstrometryEquatorial"].params.get(
                "PX")
            if px is None or px.value is None:
                raise ValueError(
                    "DDK requires PX (K95 terms scale as 1/distance)")

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        ctx["ssb_obs_pos"] = batch.ssb_obs_pos  # lt-s, for K95 terms
        return super().delay(pv, batch, cache, ctx, delay_so_far)

    def binary_delay(self, pv, dt, M, nhat, ctx):
        from pint_tpu.models.astrometry import MAS_TO_RAD, PC_LS

        x0, ecc, om = self._elements(pv, dt)
        kin = _v(pv, "KIN") * DEG2RAD
        kom = _v(pv, "KOM") * DEG2RAD
        skom, ckom = jnp.sin(kom), jnp.cos(kom)
        # sky basis at the (epoch) pulsar position
        a0 = _v(pv, "RAJ")
        d0 = _v(pv, "DECJ")
        sa, ca = jnp.sin(a0), jnp.cos(a0)
        sd, cd = jnp.sin(d0), jnp.cos(d0)
        I0 = jnp.stack([-sa, ca, jnp.zeros_like(ca)])
        J0 = jnp.stack([-sd * ca, -sd * sa, cd])
        rvec = ctx.get("ssb_obs_pos")
        di = jnp.zeros_like(dt)
        domega = jnp.zeros_like(dt)
        px = _v(pv, "PX")
        if rvec is not None:
            d_ls = PC_LS * 1.0e3 / (px + 1e-30)  # PX [mas] -> d [lt-s]
            dI = rvec @ I0
            dJ = rvec @ J0
            di = di + (dI * skom - dJ * ckom) / d_ls
            domega = domega - (dI * ckom + dJ * skom) / (
                d_ls * jnp.sin(kin))
        if self.K96.value:
            mu_a = _v(pv, "PMRA") * MAS_TO_RAD / SECS_PER_YEAR
            mu_d = _v(pv, "PMDEC") * MAS_TO_RAD / SECS_PER_YEAR
            di = di + (-mu_a * skom + mu_d * ckom) * dt
            domega = domega + (mu_a * ckom + mu_d * skom) \
                / jnp.sin(kin) * dt
        kin_eff = kin + di
        x = x0 * jnp.sin(kin_eff) / jnp.sin(kin)
        om = om + domega
        sini = jnp.sin(kin_eff)
        return self._dd_core(pv, M, nhat, x, ecc, om, _v(pv, "GAMMA"),
                             TSUN * _v(pv, "M2"), sini,
                             _v(pv, "DR"), _v(pv, "DTH"))


class BinaryELL1k(BinaryELL1):
    """ELL1 variant for fast periastron advance (reference:
    binary_ell1.BinaryELL1k / ELL1k_model; Susobhanan et al. 2018):
    OMDOT rotates (EPS1, EPS2) exactly and LNEDOT scales the
    eccentricity, replacing the linear EPS1DOT/EPS2DOT drifts."""

    register = True

    def __init__(self):
        super().__init__()
        self.remove_param("EPS1DOT")
        self.remove_param("EPS2DOT")
        self.add_param(floatParameter("OMDOT", units="deg/yr",
                                      value=0.0))
        self.add_param(floatParameter("LNEDOT", units="1/s", value=0.0))

    def _roemer(self, pv, dt, Phi, nhat):
        x = _v(pv, "A1") + _v(pv, "A1DOT") * dt
        eps1_0 = _v(pv, "EPS1")
        eps2_0 = _v(pv, "EPS2")
        omdot = _v(pv, "OMDOT") * DEG2RAD / SECS_PER_YEAR
        lnedot = _v(pv, "LNEDOT")
        dom = omdot * dt
        scale = 1.0 + lnedot * dt
        cdo, sdo = jnp.cos(dom), jnp.sin(dom)
        # rotate (eps2, eps1) = e(cos w, sin w) by dom, scale by e(t)/e0
        eps1 = scale * (eps1_0 * cdo + eps2_0 * sdo)
        eps2 = scale * (eps2_0 * cdo - eps1_0 * sdo)
        sP, cP = jnp.sin(Phi), jnp.cos(Phi)
        s2P, c2P = jnp.sin(2 * Phi), jnp.cos(2 * Phi)
        Dre = x * (sP + 0.5 * (eps2 * s2P - eps1 * c2P) - 1.5 * eps1)
        Drep = x * (cP + eps2 * c2P + eps1 * s2P)
        Drepp = x * (-sP - 2.0 * eps2 * s2P + 2.0 * eps1 * c2P)
        return self._inverse_timing(Dre, Drep, Drepp, nhat, 0.0)


class BinaryBTPiecewise(BinaryBT):
    """BT with piecewise-constant T0 and/or A1 over MJD ranges
    (reference: binary_bt.BinaryBTPiecewise / BT_piecewise.py, par
    name ``BT_piecewise``): within piece i's window [XR1_i, XR2_i],
    T0X_i and A1X_i replace the global T0/A1; outside every window the
    globals hold. TPU-first layout: each piece becomes a host-built
    0/1 mask over the TOA axis, the per-TOA orbital epoch is a
    dd_where chain (so the epoch stays a dd pair per TOA — required
    for the f32 Jacobian path too), and the A1 swap rides the
    ``_x_adjust`` hook as a plain where chain. No per-piece Python
    loop survives under jit: masks are static-shape (N,) arrays."""

    register = True

    _KINDS = ("T0X_", "A1X_", "XR1_", "XR2_")

    def __init__(self):
        super().__init__()
        self.piece_ids: List[int] = []

    def add_piece_param(self, kind: str, index: int, index_str=None):
        name = f"{kind}{index_str or f'{index:04d}'}"
        if kind == "T0X_":
            # epochs keep the exact day/frac dd split a plain float
            # parse would round away (~0.3 us at MJD magnitudes)
            p = MJDParameter(name)
        else:
            units = {"A1X_": "ls", "XR1_": "MJD", "XR2_": "MJD"}[kind]
            p = prefixParameter(prefix=kind, index=index,
                                index_str=index_str or f"{index:04d}",
                                units=units)
        p.prefix, p.index = kind, index
        self.add_param(p)
        self.setup()
        return p

    def setup(self):
        super().setup()
        ids = set()
        names: dict = {}
        for n in self.params:
            for kind in self._KINDS:
                if n.startswith(kind) and n[len(kind):].isdigit():
                    i = int(n[len(kind):])
                    ids.add(i)
                    names.setdefault(i, {})[kind] = n
        self.piece_ids = sorted(ids)
        self._piece_names = names

    def validate(self):
        super().validate()
        for i in self.piece_ids:
            nm = self._piece_names[i]
            if "XR1_" not in nm or "XR2_" not in nm or \
                    self.params[nm["XR1_"]].value is None or \
                    self.params[nm["XR2_"]].value is None:
                raise ValueError(
                    f"BT_piecewise piece {i} needs XR1_/XR2_ bounds")
            if "T0X_" not in nm and "A1X_" not in nm:
                raise ValueError(
                    f"BT_piecewise piece {i} sets neither T0X nor A1X")
            if self.params[nm["XR1_"]].value >= \
                    self.params[nm["XR2_"]].value:
                raise ValueError(
                    f"BT_piecewise piece {i}: XR1 must be < XR2 "
                    f"(an inverted window would be silently inert)")
        # overlapping windows would double-apply in the where chains
        spans = sorted(
            (self.params[self._piece_names[i]["XR1_"]].value,
             self.params[self._piece_names[i]["XR2_"]].value)
            for i in self.piece_ids)
        for (a1, b1), (a2, _) in zip(spans, spans[1:]):
            if a2 < b1:
                raise ValueError("BT_piecewise windows overlap")

    def prepare(self, toas, batch, cache, prefix=""):
        import numpy as np

        mjd = np.asarray(batch.tdb_day) + np.asarray(batch.tdb_frac.hi)
        for i in self.piece_ids:
            nm = self._piece_names[i]
            r1 = self.params[nm["XR1_"]].value
            r2 = self.params[nm["XR2_"]].value
            cache[f"btx_mask_{i}"] = (
                (mjd >= r1) & (mjd < r2)).astype(np.float64)

    def _epoch(self, pv, batch, cache):
        """Per-TOA orbital epoch: global T0 with T0X_i applied inside
        each window via a dd_where chain (epochs stay dd pairs per
        TOA — required for the f32 Jacobian path too)."""
        shape = batch.tdb_day.shape
        t0 = pv["T0"]
        epoch = DD(jnp.broadcast_to(t0.hi, shape),
                   jnp.broadcast_to(t0.lo, shape))
        for i in self.piece_ids:
            t0n = self._piece_names[i].get("T0X_")
            if t0n is not None and t0n in pv:
                inside = jnp.asarray(cache[f"btx_mask_{i}"]) > 0
                px = pv[t0n]
                epoch = dd_where(
                    inside,
                    DD(jnp.broadcast_to(px.hi, shape),
                       jnp.broadcast_to(px.lo, shape)), epoch)
        return epoch

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        # the A1 swap rides ctx into the _x_adjust hook; the epoch
        # swap rides the _epoch hook inside the shared _dt
        a1_shift = jnp.zeros_like(batch.freq_mhz)
        for i in self.piece_ids:
            a1n = self._piece_names[i].get("A1X_")
            if a1n is not None and a1n in pv:
                inside = jnp.asarray(cache[f"btx_mask_{i}"]) > 0
                a1_shift = jnp.where(
                    inside, _v(pv, a1n) - _v(pv, "A1"), a1_shift)
        ctx["btx_a1_shift"] = a1_shift
        return super().delay(pv, batch, cache, ctx, delay_so_far)

    def _x_adjust(self, x, ctx):
        return x + ctx.pop("btx_a1_shift", 0.0)
