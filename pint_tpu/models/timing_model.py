"""TimingModel core: Component registry + the delay/phase/designmatrix
engine.

Reference: src/pint/models/timing_model.py (TimingModel, Component,
ModelMeta registry, DelayComponent/PhaseComponent,
TimingModel.delay/phase/designmatrix/d_phase_d_param).

TPU-first architecture (SURVEY.md §7): host Python owns parameters,
registries and orchestration; the delay/phase stack compiles to ONE pure
jitted function over

    (theta_hi, theta_lo, frozen_hi, frozen_lo, batch: ToaBatch,
     cache: dict[str, array])

where theta is the free-parameter vector (double-double as two f64
vectors so F0-class parameters keep 31 digits while staying traceable —
no retrace on value updates) and ``cache`` holds host-precomputed
per-TOA arrays (mask vectors, the TZR mini-batch...). The design matrix
is ``jax.jacfwd`` of that function over theta_hi: the dd ops carry
custom JVPs with plain-f64 tangents, so derivatives cost f64 math while
values keep dd precision (the reference instead hand-registers
d_phase_d_param functions per component).

Component delay/phase methods are pure: they read parameter values only
from the traced ``pv`` dict and per-TOA data only from batch/cache/ctx.
``ctx`` is a per-evaluation scratch dict letting earlier components pass
geometry downstream (pulsar direction, barycentric frequency) — the
moral equivalent of the reference's cross-component attribute reaches.
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.parameter import (
    MJDParameter,
    Parameter,
    boolParameter,
    intParameter,
    maskParameter,
    strParameter,
)
from pint_tpu.ops.dd import (
    DD,
    dd_add,
    dd_frac,
    dd_mul_f,
    dd_sub_f,
    dd_to_f64,
)
from pint_tpu.phase import Phase

SECS_PER_DAY = 86400.0

# Registry: class name → Component subclass (reference: ModelMeta /
# Component.component_types).
component_types: Dict[str, type] = {}

# Fixed evaluation order of delay categories (reference:
# TimingModel.DEFAULT_ORDER / SURVEY.md §3.2) then phase categories.
DELAY_CATEGORY_ORDER = [
    "astrometry",
    "solar_system_shapiro",
    "troposphere",
    "solar_wind",
    "solar_windx",
    "dispersion",
    "chromatic",
    "chromatic_cmx",
    "cmwavex",
    "frequency_dependent",
    "fdjump",
    "wavex",
    "pulsar_system",  # binary: must be LAST so delay_so_far includes
    # every ISM/geometric delay when converting to pulsar-frame time
]
PHASE_CATEGORY_ORDER = [
    "spindown",
    "glitch",
    "wave",
    "ifunc",
    "phase_jump",
    "phase_offset",
]


class Component:
    """Base model component: a bag of Parameters plus pure device fns."""

    category = "misc"
    register = True

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.__dict__.get("register", True) and not cls.__name__.startswith("_"):
            component_types[cls.__name__] = cls

    def __init__(self):
        self.params: Dict[str, Parameter] = {}
        self._parent: Optional["TimingModel"] = None

    def add_param(self, p: Parameter) -> Parameter:
        self.params[p.name] = p
        return p

    def remove_param(self, name: str):
        del self.params[name]

    def __getattr__(self, name):
        params = self.__dict__.get("params")
        if params and name in params:
            return params[name]
        raise AttributeError(
            f"{type(self).__name__} has no attribute/param {name!r}")

    # -- lifecycle hooks (host) ---------------------------------------

    def setup(self):
        """Called after par parsing: materialize prefix/mask families."""

    def validate(self):
        """Raise on missing/contradictory parameters."""

    def param_dimensions(self) -> dict:
        """{param name or 'PREFIX*': units.Unit or callable(name) ->
        Unit} — the expected DIMENSION of each parameter slot, checked
        against the declared ``units`` strings at model build time
        (pint_tpu.units.check_model_units). Empty dict = unchecked
        (incremental adoption). Keys ending in '*' match the numeric
        prefix family."""
        return {}

    def prepare(self, toas, batch, cache: dict, prefix: str = ""):
        """Host precompute into `cache` (masks etc.) for this batch.
        Keys must be namespaced `f"{prefix}{self.__class__.__name__}_*"`
        or param-specific; values must be arrays (pytree leaves)."""

    # -- hybrid-Jacobian hooks (see TimingModel.linear_design_columns) -

    def linear_design_names(self) -> List[str]:
        """FREE params of this component whose design-matrix columns
        have a closed form (no AD tangent needed). Host-side/static;
        must agree with linear_design_local's claims."""
        return []

    def linear_design_local(self, pv, batch, cache, ctx) -> dict:
        """{claimed name: (kind, g)} with kind "pre_delay" (g =
        d(own delay)/d(param) [s/unit]; the model multiplies by the
        shared pre-binary stage sensitivity d(phase)/d(delay)) or
        "phase" (g = d(phase)/d(param) [turns/unit], used directly).
        Pure and jittable; evaluated at the current pv, so g may
        depend on other parameters' values (e.g. a JUMP column uses
        the current F0)."""
        return {}

    # -- conveniences --------------------------------------------------

    @property
    def param_names(self) -> List[str]:
        return list(self.params)

    def mask_params_of(self, prefix: str) -> List[maskParameter]:
        return [p for p in self.params.values()
                if isinstance(p, maskParameter) and p.prefix == prefix]


class DelayComponent(Component):
    category = "delay"

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        """Return this component's delay [seconds] as f64 (N,). `pv` maps
        param name → DD scalar; `delay_so_far` is the accumulated f64
        delay of earlier categories (binary models need it)."""
        raise NotImplementedError


class PhaseComponent(Component):
    category = "phase"

    def phase(self, pv, batch, cache, ctx, tb: DD) -> DD:
        """Return this component's phase [turns] as DD (N,). `tb` is
        barycentric time as DD seconds since the model's ref epoch."""
        raise NotImplementedError


class MiscParams(Component):
    """Header/control parameters that drive no physics directly
    (reference: these live on TimingModel itself)."""

    category = "misc"

    def __init__(self):
        super().__init__()
        self.add_param(strParameter("PSR", description="pulsar name",
                                    aliases=["PSRJ", "PSRB"]))
        self.add_param(strParameter("EPHEM", description="ephemeris name"))
        self.add_param(strParameter("CLK", description="clock realization"))
        self.add_param(strParameter("UNITS", value="TDB"))
        self.add_param(strParameter("TIMEEPH"))
        self.add_param(strParameter("T2CMETHOD"))
        self.add_param(strParameter("DILATEFREQ"))
        self.add_param(boolParameter("PLANET_SHAPIRO", value=False))
        self.add_param(MJDParameter("START"))
        self.add_param(MJDParameter("FINISH"))
        self.add_param(intParameter("NTOA"))
        self.add_param(floatParam("CHI2", units=""))
        self.add_param(floatParam("TRES", units="us"))
        self.add_param(strParameter("INFO"))
        self.add_param(strParameter("MODE"))

    def param_dimensions(self):
        from pint_tpu.units import DIMENSIONLESS, parse_unit

        return {"START": parse_unit("d"), "FINISH": parse_unit("d"),
                "CHI2": DIMENSIONLESS, "TRES": parse_unit("us")}


def floatParam(name, **kw):
    from pint_tpu.models.parameter import floatParameter

    return floatParameter(name, **kw)


def _category_rank(comp: Component) -> int:
    cats = DELAY_CATEGORY_ORDER + PHASE_CATEGORY_ORDER
    try:
        return cats.index(comp.category)
    except ValueError:
        return len(cats)


def frozen_trace_value(param, fallback=None):
    """Trace-static parameter read for device code (graftflow G10;
    reference precedent: components_tail.chromatic_index, the
    TNCHROMIDX incident fix).

    Some parameters enter delay/phase kernels as trace constants —
    reference epochs (WXEPOCH/DMWXEPOCH/CMEPOCH/CMWXEPOCH and their
    PEPOCH fallbacks) and model-structure switches (SWM). That is
    sound ONLY while the parameter is frozen: frozen device-param
    values are part of the compile key (``_compile_key``'s
    frozen_vals), so a value change re-keys and re-traces. A FREE
    parameter read this way would go silently stale mid-fit — the
    exact bug class graftflow G10 exists for — so refuse loudly
    instead of baking it. ``fallback`` (another Parameter) is
    consulted, under the same frozen requirement, when the primary
    has no value."""
    if not param.frozen:
        raise ValueError(
            f"{param.name} is free, but device code bakes its value "
            f"as a trace constant (compile-keyed only while frozen) "
            f"— fitting it is not supported; freeze {param.name}")
    v = param.value
    if v is not None:
        return float(v)
    if fallback is not None:
        return frozen_trace_value(fallback)
    return None


class TimingModel:
    """Ordered component container + compiled evaluation engine."""

    def __init__(self, components: Optional[List[Component]] = None,
                 name: str = ""):
        self.name = name
        self.components: Dict[str, Component] = {}
        if not any(isinstance(c, MiscParams) for c in components or []):
            self.add_component(MiscParams())
        for c in components or []:
            self.add_component(c)
        for k in self._VOLATILE_CACHE_ATTRS:
            setattr(self, k, None)

    # every compiled-closure / per-TOAs cache slot, shared by
    # __init__, invalidate_cache, and __getstate__ — a new _jit_*
    # added to one site but not the pickle-drop list would make
    # pickle.dumps(model) raise only on WARM models
    _VOLATILE_CACHE_ATTRS = (
        "_cache_key", "_cache", "_jit_phase", "_cache_key_params",
        "_jit_jac", "_cache_key_jac")

    # ---------------- component / parameter plumbing -----------------

    def add_component(self, comp: Component, setup=True):
        comp._parent = self
        self.components[type(comp).__name__] = comp
        if setup:
            comp.setup()
        self.invalidate_cache()

    def remove_component(self, name: str):
        del self.components[name]
        self.invalidate_cache()

    @property
    def delay_components(self) -> List[DelayComponent]:
        out = [c for c in self.components.values()
               if isinstance(c, DelayComponent)]
        return sorted(out, key=_category_rank)

    @property
    def phase_components(self) -> List[PhaseComponent]:
        out = [c for c in self.components.values()
               if isinstance(c, PhaseComponent)]
        return sorted(out, key=_category_rank)

    @property
    def params(self) -> List[str]:
        out = []
        for c in self.components.values():
            out.extend(c.params)
        return out

    @property
    def free_params(self) -> List[str]:
        out = []
        for c in self._ordered_components():
            for p in c.params.values():
                if not p.frozen and p.value is not None:
                    out.append(p.name)
        return out

    def _ordered_components(self):
        return sorted(self.components.values(), key=_category_rank)

    # -------- introspection helpers (reference: TimingModel API) ------

    def get_params_of_type(self, param_type: str) -> List[str]:
        """Parameter names whose class (or any base class) matches
        ``param_type`` (e.g. 'maskParameter', 'floatParameter' — the
        latter includes the mask/prefix subclasses, matching
        reference: TimingModel.get_params_of_type_top)."""
        want = param_type.lower()
        out = []
        for c in self.components.values():
            for p in c.params.values():
                if any(cls.__name__.lower() == want
                       for cls in type(p).__mro__):
                    out.append(p.name)
        return out

    def get_prefix_mapping(self, prefix: str) -> Dict[int, str]:
        """{index: name} for every parameter of the given prefix
        family (reference: TimingModel.get_prefix_mapping), e.g.
        get_prefix_mapping('DMX_') -> {1: 'DMX_0001', ...}."""
        out: Dict[int, str] = {}
        for c in self.components.values():
            for p in c.params.values():
                if getattr(p, "prefix", None) == prefix:
                    out[p.index] = p.name
        return dict(sorted(out.items()))

    @property
    def components_by_category(self) -> Dict[str, List[str]]:
        """{category: [component names]} in evaluation order
        (reference: TimingModel.get_components_by_category)."""
        out: Dict[str, List[str]] = {}
        for c in self._ordered_components():
            out.setdefault(c.category, []).append(type(c).__name__)
        return out

    def get_param(self, name: str) -> Parameter:
        for c in self.components.values():
            if name in c.params:
                return c.params[name]
            for p in c.params.values():
                if name in p.aliases:
                    return p
        raise KeyError(f"model has no parameter {name!r}")

    def __getattr__(self, name):
        if name.startswith("_") or name in ("components",):
            raise AttributeError(name)
        comps = self.__dict__.get("components") or {}
        for c in comps.values():
            if name in c.params:
                return c.params[name]
        for c in comps.values():
            for p in c.params.values():
                if name in p.aliases:
                    return p
        raise AttributeError(f"model has no parameter {name!r}")

    def __contains__(self, name):
        try:
            self.get_param(name)
            return True
        except KeyError:
            return False

    def set_param_values(self, values: Dict[str, float]):
        for k, v in values.items():
            self.get_param(k).value = v
        self.invalidate_cache(params_only=True)

    def get_param_values(self, names=None) -> Dict[str, float]:
        names = names if names is not None else self.free_params
        return {n: self.get_param(n).value for n in names}

    # ---------------- device-vector packing ---------------------------

    def _device_params(self) -> List[Parameter]:
        """Numeric parameters visible to device code, in component order.
        str/bool/int params are host-only statics."""
        from pint_tpu.models.parameter import pairParameter

        out = []
        for c in self._ordered_components():
            for p in c.params.values():
                if isinstance(p, (strParameter, boolParameter,
                                  intParameter, pairParameter)):
                    continue
                if p.value is None:
                    continue
                out.append(p)
        return out

    def _pack(self):
        dev = self._device_params()
        free = [p for p in dev if not p.frozen]
        frozen = [p for p in dev if p.frozen]
        th = np.array([p.dd[0] for p in free])
        tl = np.array([p.dd[1] for p in free])
        fh = np.array([p.dd[0] for p in frozen])
        fl = np.array([p.dd[1] for p in frozen])
        return ([p.name for p in free], [p.name for p in frozen],
                th, tl, fh, fl)

    # ---------------- compiled evaluation ------------------------------

    @property
    def ref_day(self) -> float:
        """Static integer MJD all device times are relative to."""
        cached = self.__dict__.get("_ref_day")
        if cached is not None:
            return cached
        day = None
        for nm in ("PEPOCH", "POSEPOCH", "TZRMJD"):
            try:
                p = self.get_param(nm)
                if p.value is not None:
                    day = float(np.round(p.value))
                    break
            except KeyError:
                continue
        self._ref_day = day if day is not None else 55000.0
        return self._ref_day

    def _delay_tb(self, pv, batch, cache, sub: str,
                  pre_binary_shift=None):
        """The shared delay chain + delay-subtracted barycentric time
        (device, pure): the single implementation both the direct dd
        phase and the anchored delta-phase build on.

        ``pre_binary_shift``: optional scalar added to the accumulated
        delay just BEFORE the pulsar_system (binary) components run —
        the probe point for the hybrid Jacobian's stage sensitivity
        (every non-binary delay component is additive there; only the
        binary consumes delay_so_far, so d(phase)/d(shift) is the
        exact sensitivity of the phase to ANY pre-binary delay
        perturbation)."""
        ctx: dict = {}
        delay = jnp.zeros_like(batch.freq_mhz)
        shifted = pre_binary_shift is None
        for comp in self.delay_components:
            if not shifted and comp.category == "pulsar_system":
                delay = delay + pre_binary_shift
                shifted = True
            delay = delay + comp.delay(pv, batch, cache[sub], ctx, delay)
        if not shifted:
            delay = delay + pre_binary_shift
        tb = dd_mul_f(dd_addf_day(batch, self.ref_day), SECS_PER_DAY)
        tb = dd_sub_f(tb, delay)
        ctx["tb"] = tb
        return delay, tb, ctx

    def _raw_phase_fn(self, pv, batch, cache, sub: str,
                      pre_binary_shift=None):
        """The full delay→phase chain (device, pure), absolute dd.
        Components with ``apply_to_tzr = False`` (PhaseOffset) are
        excluded from the TZR row: a constant present in both would
        cancel out of the anchored difference entirely.
        ``pre_binary_shift`` threads through to _delay_tb (the hybrid
        Jacobian's stage-sensitivity probe)."""
        delay, tb, ctx = self._delay_tb(pv, batch, cache, sub,
                                        pre_binary_shift)
        phase = DD(jnp.zeros_like(delay), jnp.zeros_like(delay))
        for comp in self.phase_components:
            if sub == "tzr" and not getattr(comp, "apply_to_tzr", True):
                continue
            phase = dd_add_dd(phase, comp.phase(pv, batch, cache[sub],
                                                ctx, tb))
        return phase, delay

    def _build_phase_fn(self):
        free_names, frozen_names, *_ = self._pack()

        def phase_fn(th, tl, fh, fl, batch, cache):
            pv = {}
            for i, nm in enumerate(free_names):
                pv[nm] = DD(th[i], tl[i])
            for j, nm in enumerate(frozen_names):
                pv[nm] = DD(fh[j], fl[j])
            phase, delay = self._raw_phase_fn(pv, batch, cache, "main")
            if "tzr_batch" in cache:
                tzr_phase, _ = self._raw_phase_fn(
                    pv, cache["tzr_batch"], cache, "tzr")
                phase = dd_sub_dd(
                    phase, DD(tzr_phase.hi[0], tzr_phase.lo[0]))
            return phase, delay

        return phase_fn, (free_names, frozen_names)

    # -------- anchored delta-phase (the TPU-safe fit-step engine) -----
    #
    # The direct chain above tracks the ABSOLUTE pulse phase (~1e10
    # turns) in dd — exact on CPU (IEEE f64 EFTs), but on TPU the
    # emulated f64 is not correctly rounded (~2^-48 effective), leaving
    # a ~3e-5-turn (~100 ns) error floor through the final
    # large-cancellation. The anchored form removes every large
    # intermediate: the host computes the exact reference phase/delays
    # ONCE (CPU backend), and the device evaluates only the difference
    #   Delta = taylor(x, F - F_ref)                      [<= turns]
    #         + sum_i F_ref,i (x^{i+1} - y^{i+1})/(i+1)!  [powdiff,
    #           applied via the factored small difference d_ref - d]
    #         + (phi_other(theta) - phi_other(theta_ref)) [small]
    # so 2^-48 working precision yields <=1e-9-turn residual accuracy
    # on any backend. See ops/taylor.taylor_powdiff and
    # ARCHITECTURE.md "Anchored delta-phase".

    def _phase_pieces(self, pv, batch, cache, sub: str, skip=()):
        """(delay, tb_dd, other_phase_f64): the delay chain, the
        delay-subtracted barycentric time, and the summed phase of all
        PhaseComponents except those in ``skip`` (class names)."""
        delay, tb, ctx = self._delay_tb(pv, batch, cache, sub)
        other = jnp.zeros_like(delay)
        for comp in self.phase_components:
            if type(comp).__name__ in skip:
                continue
            if sub == "tzr" and not getattr(comp, "apply_to_tzr", True):
                continue
            p = comp.phase(pv, batch, cache[sub], ctx, tb)
            other = other + (p.hi + p.lo)
        return delay, tb, other

    # -------- hybrid Jacobian: closed-form design columns -------------
    #
    # The jacfwd design matrix pushes one tangent per free parameter
    # through the whole delay/phase chain. But many parameters are
    # LINEAR in that chain: every non-binary delay component is purely
    # additive before the binary stage (DELAY_CATEGORY_ORDER — only
    # pulsar_system consumes delay_so_far), so
    #   d(phase)/d(p) = S_pre(t) * d(delay_comp)/d(p)
    # with ONE shared stage sensitivity S_pre = d(phase)/d(shift)
    # (one JVP), and phase-linear params (JUMP, PHOFF, glitch and
    # piecewise-spindown pieces, spin F1+) have direct columns.
    # parallel.fit_step drops all such params from the jacfwd tangent
    # set — 40 -> 11 tangents at the north-star shape (12 under the
    # f32 Jacobian, where the scaled F2 stays on AD). Columns are
    # exact partials at the current
    # point (not approximations); equality with jacfwd is pinned by
    # tests/test_hybrid_jac.py.

    def _abs_phase_shift(self, pv, batch, cache, sub: str, s):
        """f64 total phase with a pre-binary delay shift ``s`` — the
        JVP probe for the hybrid Jacobian's stage sensitivity. One
        chain, not a copy: delegates to _raw_phase_fn so the probe
        always differentiates exactly what the residuals evaluate."""
        ph, _ = self._raw_phase_fn(pv, batch, cache, sub,
                                   pre_binary_shift=s)
        return ph.hi + ph.lo

    def linear_design_names(self) -> set:
        """Free-param names with closed-form design columns (the
        hybrid Jacobian's analytic set)."""
        free = set(self.free_params)
        out: set = set()
        for comp in self.components.values():
            out |= set(comp.linear_design_names()) & free
        return out

    def _ld_rows(self, pv, batch, cache, sub: str, names):
        dt = batch.freq_mhz.dtype
        delay, tb, ctx = self._delay_tb(pv, batch, cache, sub)
        local = []  # (name, kind, g) — same-name claims ADD: several
        # components may each own part of one parameter's response
        for comp in self._ordered_components():
            if sub == "tzr" and not getattr(comp, "apply_to_tzr", True):
                continue
            for nm, (kind, g) in comp.linear_design_local(
                    pv, batch, cache[sub], ctx).items():
                if nm in names:
                    local.append((nm, kind, g))
        # the stage-sensitivity JVP costs one full-chain tangent pass:
        # pay it only when some claim actually is delay-kind (a
        # JUMP/PHOFF/glitch-only model needs none of it) — the kind
        # tags are static at trace time
        if any(kind == "pre_delay" for _, kind, _ in local):
            zero = jnp.zeros((), dt)

            def f(s):
                return self._abs_phase_shift(pv, batch, cache, sub, s)

            _, s_pre = jax.jvp(f, (zero,), (jnp.ones((), dt),))
        else:
            s_pre = None
        out: dict = {}
        for nm, kind, g in local:
            contrib = s_pre * g if kind == "pre_delay" else g
            out[nm] = out[nm] + contrib if nm in out else contrib
        return out

    def linear_design_columns(self, pv, batch, cache, names) -> dict:
        """{name: exact d(phase)/d(param) column [turns/unit]} for the
        claimed ``names``: closed-form local factors x one
        stage-sensitivity JVP, including the TZR-row subtraction
        (matches what jacfwd of the TZR-referenced phase would give).
        Dtype follows ``batch`` (the f32 Jacobian path passes the f32
        batch/cache)."""
        main = self._ld_rows(pv, batch, cache, "main", names)
        if "tzr_batch" in cache:
            tzr = self._ld_rows(pv, cache["tzr_batch"], cache, "tzr",
                                names)
            # a claim can be absent from the tzr row (apply_to_tzr =
            # False components, e.g. PhaseOffset): no subtraction then
            return {nm: main[nm] - tzr[nm][0] if nm in tzr
                    else main[nm] for nm in names}
        return main

    def supports_anchored(self) -> bool:
        spin = self.components.get("Spindown")
        return spin is not None and "PEPOCH" not in self.free_params \
            and spin.PEPOCH.value is not None

    def build_anchor(self, toas) -> dict:
        """Host-side anchor constants (exact dd on the CPU backend):
        reference frac-phase, reference delays (main + TZR rows),
        reference non-spindown phase sums, reference F coefficients,
        and scaling. Arrays are numpy; rebuilt by build_fit_step
        whenever the step is rebuilt."""
        if not self.supports_anchored():
            raise ValueError("anchored step needs Spindown with a "
                             "frozen PEPOCH")
        free, frozen, th0, tl0, fh0, fl0 = self._pack()
        cache = self.get_cache(toas)
        spin = self.components["Spindown"]
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            batch = jax.device_put(cache["batch"], cpu)
            sc = jax.device_put(_strip(cache), cpu)
            phase_fn, _ = self._build_phase_fn()
            ph, _ = jax.jit(phase_fn)(
                jnp.asarray(th0), jnp.asarray(tl0), jnp.asarray(fh0),
                jnp.asarray(fl0), batch, sc)
            fr = dd_frac(ph)
            r_ref = np.asarray(fr.hi, np.float64) + \
                np.asarray(fr.lo, np.float64)
            pv0 = {nm: DD(jnp.asarray(th0[i]), jnp.asarray(tl0[i]))
                   for i, nm in enumerate(free)}
            pv0.update({nm: DD(jnp.asarray(fh0[j]), jnp.asarray(fl0[j]))
                        for j, nm in enumerate(frozen)})
            d_ref, _, oth_ref = jax.jit(
                lambda b, c: self._phase_pieces(
                    pv0, b, c, "main", skip=("Spindown",)))(batch, sc)
            anc = {"r_ref": r_ref,
                   "d_ref": np.asarray(d_ref, np.float64),
                   "oth_ref": np.asarray(oth_ref, np.float64)}
            if "tzr_batch" in sc:
                d_t, _, o_t = jax.jit(
                    lambda b, c: self._phase_pieces(
                        pv0, b, c, "tzr", skip=("Spindown",)))(
                    sc["tzr_batch"], sc)
                anc["d_ref_tzr"] = np.asarray(d_t, np.float64)
                anc["oth_ref_tzr"] = np.asarray(o_t, np.float64)
        # spindown reference coefficients and time scaling (host)
        fnames = spin.f_terms()
        name_to_val = {}
        for i, nm in enumerate(free):
            name_to_val[nm] = th0[i] + tl0[i]
        for j, nm in enumerate(frozen):
            name_to_val[nm] = fh0[j] + fl0[j]
        anc_static = {
            "fnames": fnames,
            "fref": [float(name_to_val[nm]) for nm in fnames],
            "fidx": [free.index(nm) if nm in free else None
                     for nm in fnames],
            "pepoch_shift": (float(spin.PEPOCH.value) - self.ref_day)
            * SECS_PER_DAY,
        }
        mjd = np.asarray(cache["batch"].tdb_day) + \
            np.asarray(cache["batch"].tdb_frac.hi)
        anc_static["t_scale"] = max(
            float(np.max(np.abs((mjd - self.ref_day) * SECS_PER_DAY
                                - anc_static["pepoch_shift"]))), 1.0) \
            * 1.05
        return anc, anc_static

    def _build_anchored_fn(self, anc_static):
        """fn(dth, dtl, fh, fl, batch, cache) -> (frac_resid, delay).

        (dth, dtl) is the HOST-COMPUTED exact delta theta - theta_ref
        for the FREE params (on-device subtraction of near-equal
        values is exactly what TPU's non-IEEE f64 cannot be trusted
        with); (fh, fl) are the FULL frozen-param pairs, normally the
        build-time values but honored if a caller substitutes others
        (grid_chisq varies frozen params through these slots — their
        deltas are formed on device, acceptable because grid steps
        dwarf the subtraction error). batch/cache may be f64 or the
        f32/dd32 conversions (dtype follows dth); cache["anchor"]
        holds build_anchor's array constants."""
        from pint_tpu.ops.dd import dd_add, dd_sub, dd_to_dd32
        from pint_tpu.ops.taylor import taylor_horner, taylor_powdiff

        free, frozen, th0, tl0, fh0, fl0 = self._pack()
        ref64 = (th0, tl0, fh0, fl0)
        r32 = dd_to_dd32(DD(np.asarray(th0), np.asarray(tl0)))
        f32r = dd_to_dd32(DD(np.asarray(fh0), np.asarray(fl0)))
        ref32 = (np.asarray(r32.hi), np.asarray(r32.lo),
                 np.asarray(f32r.hi), np.asarray(f32r.lo))
        fnames = anc_static["fnames"]
        fref = anc_static["fref"]
        fidx = anc_static["fidx"]
        # frozen-slot index of each F term (for grid-varied frozen Fs)
        fjdx = [frozen.index(nm) if nm in frozen else None
                for nm in fnames]
        pep = anc_static["pepoch_shift"]
        t_scale = anc_static["t_scale"]
        ref_day = self.ref_day

        def fn(dth, dtl, fh, fl, batch, cache):
            f32 = dth.dtype == jnp.float32
            rh, rl, qh, ql = [jnp.asarray(a) for a in
                              (ref32 if f32 else ref64)]
            delta = dth + dtl
            pv = {}
            for i, nm in enumerate(free):
                pv[nm] = dd_add(DD(rh[i], rl[i]), DD(dth[i], dtl[i]))
            for j, nm in enumerate(frozen):
                pv[nm] = DD(fh[j], fl[j])
            # frozen deltas vs the anchor (zero unless a caller
            # substituted grid values through fh/fl)
            fdelta = dd_to_f64(dd_sub(DD(fh, fl), DD(qh, ql)))
            anc = cache["anchor"]

            def delta_phase(batch_x, sub, d_ref, oth_ref):
                d, tb, oth = self._phase_pieces(
                    pv, batch_x, cache, sub, skip=("Spindown",))
                # x = seconds since PEPOCH at the CURRENT delay
                t_rel = (batch_x.tdb_day - ref_day) * SECS_PER_DAY \
                    + (batch_x.tdb_frac.hi + batch_x.tdb_frac.lo) \
                    * SECS_PER_DAY
                x = t_rel - d - pep
                dxy = d_ref - d      # small: cancellation of ~500 s
                a_coeffs = [jnp.zeros((), x.dtype)]
                for k, nm in enumerate(fnames):
                    if fidx[k] is not None:
                        a_coeffs.append(delta[fidx[k]])
                    elif fjdx[k] is not None:
                        a_coeffs.append(fdelta[fjdx[k]])
                    else:
                        a_coeffs.append(jnp.zeros((), x.dtype))
                A = taylor_horner(x, a_coeffs)
                B = taylor_powdiff(x, dxy, [0.0] + fref,
                                   t_scale=t_scale)
                return A + B + (oth - oth_ref), d

            dphi, d_main = delta_phase(batch, "main", anc["d_ref"],
                                       anc["oth_ref"])
            if "tzr_batch" in cache:
                dphi_t, _ = delta_phase(cache["tzr_batch"], "tzr",
                                        anc["d_ref_tzr"],
                                        anc["oth_ref_tzr"])
                dphi = dphi - dphi_t[0]
            v = anc["r_ref"] + dphi
            return v - jnp.round(v), d_main

        return fn

    def _compile_key(self):
        # The key must cover everything baked into the trace: the
        # component/parameter structure, the free set, ref_day, every
        # str/bool/int param (ECL, SIFUNC, K96, ... are read as trace
        # statics), and FROZEN device-param values (epoch params like
        # CMEPOCH are read via .value in device code). Free-param
        # VALUES are runtime arguments and deliberately absent — the
        # hot fitter loop re-uses one compile across iterations.
        statics = tuple(
            (p.name, p.value)
            for c in self._ordered_components()
            if not isinstance(c, MiscParams)  # header-only (PSR name,
            # EPHEM, ...) — never read inside a trace, and keying on
            # them would force one compile per pulsar in PTA batches
            for p in c.params.values()
            if isinstance(p, (strParameter, boolParameter,
                              intParameter)))
        # the one MiscParams entry that IS a trace static (solar-
        # system Shapiro branches on it)
        statics += (("PLANET_SHAPIRO", bool(self.PLANET_SHAPIRO.value)),)
        frozen_vals = tuple(
            p.value for p in self._device_params() if p.frozen)
        return (tuple(sorted(self.components)),
                tuple(p.name for p in self._device_params()),
                tuple(self.free_params), self.ref_day, statics,
                frozen_vals)

    def _get_compiled(self, donate_argnums=None):
        """Cached jitted phase function. ``donate_argnums`` (opt-in,
        part of the cache key) lets an ITERATED caller donate its
        argument buffers — e.g. (0, 1) for a loop advancing the
        (th, tl) pair in place (config.donation_enabled policy). The
        default stays non-donating: the host fitters re-use their
        packed arrays across calls, and a donated buffer is CONSUMED
        by the dispatch (graftlint G11 — callers opting in must
        rebuild their donated args fresh per call). One cached slot:
        callers ALTERNATING donation modes on the same model would
        recompile per switch — opt in only from a dedicated iterated
        loop, not per-call."""
        key = (self._compile_key(),
               tuple(donate_argnums) if donate_argnums else ())
        if self._jit_phase is None or self._cache_key_params != key:
            fn, names = self._build_phase_fn()
            self._jit_phase = jax.jit(
                fn, donate_argnums=donate_argnums or ())
            self._names = names
            self._cache_key_params = key
        return self._jit_phase

    def _get_compiled_jac(self):
        """Jitted hybrid design-Jacobian (th, tl, fh, fl, batch, sc)
        -> (N, p) d(phase)/d(free_j) [turns/unit]: closed-form columns
        for the linear_design_names set, AD tangents for the rest —
        cached like _get_compiled, so host fitters stop paying a full
        jacfwd RE-TRACE on every iteration (designmatrix previously
        rebuilt the jacobian trace per call)."""
        from pint_tpu.config import hybrid_jac_enabled

        lin = frozenset(self.linear_design_names()) \
            if hybrid_jac_enabled() else frozenset()
        base_key = self._compile_key()
        key = (base_key, lin)
        if self._jit_jac is None or self._cache_key_jac != key:
            phase_fn, (free_names, frozen_names) = \
                self._build_phase_fn()
            nl_idx_list = [i for i, nm in enumerate(free_names)
                           if nm not in lin]
            # host-built once here, NOT inside jac_fn: graftlint G2 —
            # np calls in a traced body are host fallbacks
            nl_idx = np.asarray(nl_idx_list, np.int32)

            def jac_fn(th, tl, fh, fl, batch, sc):
                def phase_of(thx):
                    ph, _ = phase_fn(thx, tl, fh, fl, batch, sc)
                    return ph.hi + ph.lo

                if nl_idx_list:
                    idx = jnp.asarray(nl_idx)

                    def sub(th_nl):
                        return phase_of(th.at[idx].set(th_nl))

                    jac_nl = jax.jacfwd(sub)(th[idx])
                if lin:
                    pv = {nm: DD(th[i], tl[i])
                          for i, nm in enumerate(free_names)}
                    pv.update({nm: DD(fh[j], fl[j])
                               for j, nm in enumerate(frozen_names)})
                    cols = self.linear_design_columns(pv, batch, sc,
                                                      lin)
                out, k = [], 0
                for nm in free_names:
                    if nm in lin:
                        out.append(cols[nm])
                    else:
                        out.append(jac_nl[:, k])
                        k += 1
                if not out:  # all params frozen: only the implicit
                    # Offset column exists — (N, 0), as jacfwd gave
                    return jnp.zeros((batch.freq_mhz.shape[0], 0),
                                     batch.freq_mhz.dtype)
                return jnp.stack(out, axis=1)

            self._jit_jac = jax.jit(jac_fn)
            self._cache_key_jac = key
        return self._jit_jac

    def __getstate__(self):
        """Pickle/deepcopy support (reference: models pickle for
        process-pool grids and notebook checkpoints): the compiled
        phase/Jacobian closures and per-TOAs caches are volatile
        derived state — drop them; the copy re-compiles lazily."""
        d = self.__dict__.copy()
        for k in self._VOLATILE_CACHE_ATTRS:
            d[k] = None
        d.pop("_noise_basis_cache", None)
        return d

    def invalidate_cache(self, params_only=False):
        """Drop cached compiled state. params_only=True (a parameter
        VALUE changed) keeps the jitted phase function: values enter as
        runtime arguments, so the trace is still valid — _get_compiled
        re-keys on (components, device params, free set, ref_day) and
        rebuilds exactly when the STRUCTURE changes. Clearing the jit
        here cost a full retrace per fitter iteration (the config-1
        bench regression that exposed it). ref_day is re-derived since
        epoch-valued params feed the key."""
        if not params_only:
            for k in self._VOLATILE_CACHE_ATTRS:
                setattr(self, k, None)
            self.__dict__.pop("_noise_basis_cache", None)
        # ref epoch may shift when epochs change
        self.__dict__.pop("_ref_day", None)

    def get_cache(self, toas) -> dict:
        """Host-precomputed per-batch arrays (masks, TZR mini-batch)."""
        # per-state serial, not id(): ids are reused after GC and a
        # TOAs can be mutated in place (see toa.TOAs._touch)
        key = getattr(toas, "cache_key", None) or id(toas)
        if self._cache is not None and self._cache_key == key:
            return self._cache
        batch = toas.to_batch()
        cache: dict = {"main": {}, "tzr": {}, "batch": batch}
        for comp in self._ordered_components():
            comp.prepare(toas, batch, cache["main"], prefix="")
        tzr_toas = self._make_tzr_toas(toas)
        if tzr_toas is not None:
            cache["tzr_batch"] = tzr_toas.to_batch()
            for comp in self._ordered_components():
                comp.prepare(tzr_toas, cache["tzr_batch"], cache["tzr"],
                             prefix="tzr_")
        self._cache = cache
        self._cache_key = key
        return cache

    def _host_psr_dir(self, toas) -> np.ndarray:
        """Nominal host-side SSB->pulsar unit vector (N,3), ICRS, at
        the catalog position (no proper motion): for host precomputes
        whose dependence on astrometry updates is second order (e.g.
        SWX geometry normalization)."""
        eq = self.components.get("AstrometryEquatorial")
        if eq is not None:
            a0, d0 = eq.RAJ.value, eq.DECJ.value
            n = np.array([np.cos(d0) * np.cos(a0),
                          np.cos(d0) * np.sin(a0), np.sin(d0)])
            return np.broadcast_to(n, (toas.ntoas, 3))
        ec = self.components.get("AstrometryEcliptic")
        if ec is not None:
            l0, b0 = ec.ELONG.value, ec.ELAT.value
            n_ecl = np.array([np.cos(b0) * np.cos(l0),
                              np.cos(b0) * np.sin(l0), np.sin(b0)])
            n = np.asarray(ec._ecl_matrix()) @ n_ecl
            return np.broadcast_to(n, (toas.ntoas, 3))
        raise ValueError("model has no astrometry component")

    def _make_tzr_toas(self, toas):
        """Build the one-TOA TZR set (reference:
        src/pint/models/absolute_phase.py AbsPhase.get_TZR_toa)."""
        if "AbsPhase" not in self.components:
            return None
        comp = self.components["AbsPhase"]
        if comp.TZRMJD.value is None:
            return None
        from pint_tpu.toa import get_TOAs_array

        site = comp.TZRSITE.value or "ssb"
        freq = comp.TZRFRQ.value
        freq = np.inf if freq in (None, 0.0) else float(freq)
        day, frac = comp.TZRMJD.day_frac
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return get_TOAs_array(
                (np.array([day]), (np.array([frac[0]]),
                                   np.array([frac[1]]))),
                obs=site, freqs=freq, errors=1.0,
                ephem=self.EPHEM.value,
                planets=bool(self.PLANET_SHAPIRO.value))

    # ---------------- public evaluation API ---------------------------
    #
    # These exact-dd entry points (the host-fitter surface: Residuals,
    # designmatrix, phase) are pinned to the CPU backend whenever the
    # process default is TPU: double-double error-free transforms are
    # silently broken by TPU's non-correctly-rounded emulated f64
    # (ARCHITECTURE.md), so running them there would degrade residuals
    # to ~100 ns. The TPU-native hot path is the anchored fit step
    # (parallel/fit_step), which needs no dd on device.

    @staticmethod
    def _exact_backend():
        import contextlib

        if jax.default_backend() == "tpu":
            return jax.default_device(jax.devices("cpu")[0])
        return contextlib.nullcontext()

    def phase(self, toas, abs_phase=True) -> Phase:
        """Total pulse phase at each TOA (reference: TimingModel.phase).
        With abs_phase and a TZR point, phase is anchored there."""
        cache = self.get_cache(toas)
        if not abs_phase:
            cache = {k: v for k, v in cache.items() if k != "tzr_batch"}
        _, _, th, tl, fh, fl = self._pack()
        fn = self._get_compiled()
        with self._exact_backend():
            phase, _ = fn(th, tl, fh, fl, cache["batch"], _strip(cache))
        return Phase(phase)

    def delay(self, toas) -> jnp.ndarray:
        """Total barycentering+binary delay [s] (reference:
        TimingModel.delay)."""
        cache = self.get_cache(toas)
        _, _, th, tl, fh, fl = self._pack()
        fn = self._get_compiled()
        with self._exact_backend():
            _, delay = fn(th, tl, fh, fl, cache["batch"], _strip(cache))
        return delay

    def designmatrix(self, toas, incoffset=True):
        """(M, names, units): M[i,j] = d(time-resid_i)/d(free-param_j)
        [s / param-unit], with a leading all-ones offset column when
        incoffset (reference: TimingModel.designmatrix). When a
        PhaseOffset component is present, PHOFF REPLACES the implicit
        offset column (reference semantics — both at once would be an
        exactly collinear pair)."""
        if "PhaseOffset" in self.components:
            incoffset = False
        cache = self.get_cache(toas)
        free, _, th, tl, fh, fl = self._pack()
        jac_fn = self._get_compiled_jac()
        sc = _strip(cache)
        batch = cache["batch"]

        with self._exact_backend():
            jac = jac_fn(jnp.asarray(th), jnp.asarray(tl),
                         jnp.asarray(fh), jnp.asarray(fl), batch,
                         sc)  # (N, p) turns/unit
        f0 = self.F0.value
        M = np.asarray(jac) / f0
        names = list(free)
        if incoffset:
            M = np.concatenate([np.ones((M.shape[0], 1)) / f0, M], axis=1)
            names = ["Offset"] + names
        units = ["turn"] + [self.get_param(n).units for n in free] \
            if incoffset else [self.get_param(n).units for n in free]
        return M, names, units

    def d_phase_d_toa(self, toas, sample_step_s: float = 1.0):
        """Instantaneous topocentric pulse frequency [Hz] at each TOA
        (reference: TimingModel.d_phase_d_toa): central finite
        difference of the FULL pipeline at ±sample_step_s — the
        shifted TOA sets re-run clock/ephemeris/barycentering, so the
        Doppler from Earth motion is captured (a jvp through the
        device chain alone would miss it: batch positions are
        precomputed constants there). The phase difference is taken in
        dd, so the ~1e10-turn absolute phases cancel exactly."""
        from pint_tpu.ops import dd_np
        from pint_tpu.toa import get_TOAs_array

        # the TOA cache is single-slot: preserve the caller's entry so
        # the two shifted evaluations don't force a full pipeline
        # recompute on the model's next call with the original toas
        saved = (self._cache, self._cache_key)
        step_d = sample_step_s / SECS_PER_DAY
        # the caller's mjd_frac is ALREADY clock-corrected (TOAs apply
        # corrections in place); get_TOAs_array would correct again,
        # shifting both evaluations by the full clock chain — so undo
        # the correction first and let the fresh pipeline re-apply it
        clk = np.zeros(toas.ntoas)
        if getattr(toas, "clock_applied", False):
            clk = np.array([float(f.get("clkcorr", 0.0))
                            for f in toas.flags])
        phases = []
        for sign in (+1.0, -1.0):
            frac = dd_np.add_f(
                (np.asarray(toas.mjd_frac[0]),
                 np.asarray(toas.mjd_frac[1])),
                sign * step_d - clk / SECS_PER_DAY)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                t2 = get_TOAs_array(
                    (np.asarray(toas.mjd_day), frac),
                    obs=list(toas.obs), freqs=toas.freq_mhz,
                    errors=toas.error_us, ephem=self.EPHEM.value,
                    planets=bool(self.PLANET_SHAPIRO.value),
                    flags=[{k: v for k, v in f.items()
                            if k != "clkcorr"} for f in toas.flags])
            phases.append(self.phase(t2, abs_phase=False).turns)
        self._cache, self._cache_key = saved
        diff = dd_np.sub((np.asarray(phases[0].hi),
                          np.asarray(phases[0].lo)),
                         (np.asarray(phases[1].hi),
                          np.asarray(phases[1].lo)))
        return dd_np.to_f64(diff) / (2.0 * sample_step_s)

    def d_phase_d_param(self, toas, param: str):
        """Single-parameter phase derivative [turns/unit] via the same
        jacfwd path (reference: TimingModel.d_phase_d_param)."""
        free, _, th, tl, fh, fl = self._pack()
        if param not in free:
            raise ValueError(f"{param} is not a free parameter")
        cache = self.get_cache(toas)
        fn = self._get_compiled()
        sc = _strip(cache)
        i = free.index(param)

        def phase_of(x):
            ph, _ = fn(th.at[i].set(x) if hasattr(th, "at")
                       else _np_set(th, i, x), tl, fh, fl,
                       cache["batch"], sc)
            return ph.hi + ph.lo

        with self._exact_backend():
            return jax.jacfwd(phase_of)(jnp.asarray(th[i]))

    # ---------------- wideband DM channel ------------------------------

    def dm_total_device(self, pv, batch, cache_sub):
        """Total model DM [pc/cm^3] per TOA as a pure device function,
        aggregating every component exposing ``dm_value_device`` (DM
        polynomial, DMX, DMJUMP, solar wind, DMWaveX). Astrometry's
        delay runs first to populate the ctx geometry (pulsar
        direction) the solar-wind term needs (reference: total DM
        summed over Dispersion components). Shared by build_dm_fn and
        the wideband fit step, so the two channels cannot
        desynchronize."""
        ctx: dict = {}
        zero = jnp.zeros_like(batch.freq_mhz)
        for c in self.delay_components:
            if c.category == "astrometry":
                c.delay(pv, batch, cache_sub, ctx, zero)
        dm = zero
        for c in self._ordered_components():
            if hasattr(c, "dm_value_device"):
                dm = dm + c.dm_value_device(pv, batch, cache_sub, ctx)
        return dm

    def dm_affecting_free_params(self):
        """Free-parameter names whose tangents can move
        dm_total_device: the params of every component exposing
        ``dm_value_device``, plus astrometry's params when a
        solar-wind component is present (its DM term reads the
        pulsar-direction ctx that astrometry populates). The wideband
        fit step restricts the DM-row Jacobian to these columns —
        every other column is structurally zero, and the full jacfwd
        paid ~29 wasted tangents out of 40 at the north-star shape
        for them (ADVICE r4)."""
        names: set = set()
        has_sw = False
        for c in self.components.values():
            if hasattr(c, "dm_value_device"):
                names.update(c.params)
                # only the NE_SW model's dm_value_device reads the
                # ctx geometry; SWX precomputes its geometry columns
                # on host at nominal astrometry (no coupling)
                if getattr(c, "category", "") == "solar_wind":
                    has_sw = True
        if has_sw:
            for c in self.components.values():
                if getattr(c, "category", "") == "astrometry":
                    names.update(c.params)
        return names

    def build_dm_fn(self, toas):
        """(dm_fn, free_names): dm_fn(th) -> model DM per TOA
        [pc/cm^3], pure and jacfwd-able (see dm_total_device)."""
        cache = self.get_cache(toas)
        batch = cache["batch"]
        main = cache["main"]
        free, frozen, th, tl, fh, fl = self._pack()

        def dm_fn(thx):
            pv = {nm: DD(thx[i], tl[i]) for i, nm in enumerate(free)}
            for j, nm in enumerate(frozen):
                pv[nm] = DD(fh[j], fl[j])
            return self.dm_total_device(pv, batch, main)

        return dm_fn, (free, np.asarray(th))

    def total_dm(self, toas) -> np.ndarray:
        """Model DM at each TOA [pc/cm^3] (host convenience)."""
        dm_fn, (_, th) = self.build_dm_fn(toas)
        return np.asarray(dm_fn(jnp.asarray(th)))

    def as_ECL(self, ecl: str = "IERS2010") -> "TimingModel":
        """Model with ecliptic astrometry in the ``ecl`` obliquity
        convention (reference: TimingModel.as_ECL; delegates to
        modelutils). Already ecliptic in the SAME convention returns
        self (not a copy — deepcopy if you need independence); a
        different convention converts through ICRS."""
        from pint_tpu.models.astrometry import AstrometryEcliptic
        from pint_tpu.modelutils import model_equatorial_to_ecliptic

        AstrometryEcliptic.obliquity_arcsec(ecl)  # strict, fail early
        cur = self.components.get("AstrometryEcliptic")
        if cur is not None:
            if (cur.ECL.value or "IERS2010").upper() == ecl.upper():
                return self
            # convention change: rotate out and back in (exact —
            # both matrices are pure obliquity rotations)
            return model_equatorial_to_ecliptic(self.as_ICRS(),
                                                ecl=ecl)
        return model_equatorial_to_ecliptic(self, ecl=ecl)

    def as_ICRS(self) -> "TimingModel":
        """Model with equatorial astrometry (reference:
        TimingModel.as_ICRS; delegates to modelutils). Already
        equatorial returns self (not a copy)."""
        from pint_tpu.modelutils import model_ecliptic_to_equatorial

        if "AstrometryEquatorial" in self.components:
            return self
        return model_ecliptic_to_equatorial(self)

    # ---------------- noise-model aggregation -------------------------
    # (reference: TimingModel.scaled_toa_uncertainty,
    #  .noise_model_designmatrix, .noise_model_basis_weight,
    #  .has_correlated_errors)

    @property
    def noise_components(self):
        out = [c for c in self.components.values()
               if getattr(c, "category", "") == "noise"]
        return sorted(out, key=lambda c: type(c).__name__)

    @property
    def has_correlated_errors(self) -> bool:
        return any(getattr(c, "is_basis_noise", False)
                   for c in self.noise_components)

    def scaled_toa_uncertainty(self, toas) -> np.ndarray:
        """Per-TOA white sigma [s] after EFAC/EQUAD scaling."""
        sigma2 = (toas.get_errors() * 1e-6) ** 2
        for c in self.noise_components:
            sigma2 = c.scale_toa_sigma_s2(toas, sigma2)
        return np.sqrt(sigma2)

    def scaled_dm_uncertainty(self, toas) -> np.ndarray:
        """Per-TOA wideband-DM sigma [pc/cm^3] after DMEFAC/DMEQUAD."""
        from pint_tpu.wideband import get_wideband_dm

        _, dmerr = get_wideband_dm(toas)
        sigma2 = dmerr ** 2
        for c in self.noise_components:
            sigma2 = c.scale_dm_sigma2(toas, sigma2)
        return np.sqrt(sigma2)

    def noise_model_basis_weight_pairs(self, toas, exclude=(),
                                       tspan=None, tref_day=None):
        """[(component name, F, phi), ...] for every active basis.
        Cached per (TOA set, noise hyperparameter values, exclude set):
        the bases are static during a least-squares fit (hyperparameters
        only move under MCMC), but quantization + Fourier builds are
        O(N·q) host work worth doing once, not once per downhill trial
        step. Excluded components are never densified at all (the fit
        step excludes ECORR when it rides the segment path)."""
        exclude = tuple(sorted(exclude))
        key = tuple(
            (p.name, p.value, getattr(p, "key", None),
             tuple(getattr(p, "key_value", ())))
            for c in self.noise_components for p in c.params.values()
        ) + (exclude, tspan, tref_day)
        cached = self.__dict__.get("_noise_basis_cache")
        # identity check via a held reference (not a bare id(), which
        # CPython reuses after garbage collection) PLUS the mutation
        # serial: an in-place flag edit (TOAs._touch bumps the serial)
        # changes the mask-selected bases while identity and noise
        # params stay equal — without the serial this returned a STALE
        # basis after e.g. editing -be flags on the same TOAs object
        serial = getattr(toas, "cache_key", None)
        if cached is not None and cached[0] is toas \
                and cached[1] == serial and cached[2] == key:
            return cached[3]
        out = []
        for c in self.noise_components:
            if not getattr(c, "is_basis_noise", False) or \
                    type(c).__name__ in exclude:
                continue
            pair = c.noise_basis_weight(toas, tspan=tspan,
                                         tref_day=tref_day)
            if pair is not None:
                out.append((type(c).__name__, pair[0], pair[1]))
        self._noise_basis_cache = (toas, serial, key, out)
        return out

    def noise_model_designmatrix(self, toas, exclude=(), tspan=None,
                                 tref_day=None):
        """Stacked (N, q) noise basis, or None when no basis is active.
        ``exclude`` drops named components (the fit step excludes the
        segment-handled ECORR components); ``tspan`` [s] pins the
        Fourier fundamental (the serve append path's basis-alignment
        contract — see NoiseComponent.noise_basis_weight)."""
        pairs = self.noise_model_basis_weight_pairs(
            toas, exclude=exclude, tspan=tspan, tref_day=tref_day)
        if not pairs:
            return None
        return np.concatenate([F for _, F, _ in pairs], axis=1)

    def noise_model_basis_weight(self, toas, exclude=(), tspan=None,
                                 tref_day=None):
        """Stacked (q,) prior variances matching the designmatrix."""
        pairs = self.noise_model_basis_weight_pairs(
            toas, exclude=exclude, tspan=tspan, tref_day=tref_day)
        if not pairs:
            return None
        return np.concatenate([phi for _, _, phi in pairs])

    def noise_model_dm_designmatrix(self, toas, exclude=()):
        """(N, q) DM-channel block of the noise basis, column-aligned
        with noise_model_designmatrix: components whose process IS a
        DM perturbation (PLDMNoise) expose ``noise_dm_basis`` and
        couple into the wideband DM rows; all others contribute zeros
        (reference: the wideband GLS DM-block coupling). None when no
        basis is active."""
        pairs = self.noise_model_basis_weight_pairs(toas,
                                                    exclude=exclude)
        if not pairs:
            return None
        comps = {type(c).__name__: c for c in self.noise_components}
        blocks = []
        for name, F, _ in pairs:
            comp = comps.get(name)
            if comp is not None and hasattr(comp, "noise_dm_basis"):
                blocks.append(np.asarray(
                    comp.noise_dm_basis(toas, F_time=F)))
            else:
                blocks.append(np.zeros_like(np.asarray(F)))
        return np.concatenate(blocks, axis=1)

    def noise_model_ecorr_segments(self, toas):
        """ECORR epoch-segment structure for the Sherman-Morrison GLS
        path: (epoch_ids (N,) int32 — value K means 'in no epoch' —,
        jvar (K+1,) per-epoch jitter variances [s^2] with jvar[K] = 0,
        consumed (tuple of component names to exclude from the dense
        basis)), or None when no segment-capable component is active or
        epochs overlap (then callers must fall back to the dense
        quantization basis).

        TPU-first design note: the reference treats ECORR as ~N_epoch
        dense 0/1 basis columns inside the Woodbury solve
        (src/pint/models/noise_model.py EcorrNoise.ecorr_basis_weight_
        pair); on TPU that makes the normal matrix (p+q)^2 with
        q ~ N/4. Because each epoch's covariance block is the rank-1
        matrix jvar * 1 1^T, N_eff^-1 has a closed form via one
        rank-1 downdate per epoch — O(N) segment sums instead of
        O(N q^2) matmuls. Same algebra, hardware-shaped layout.
        Extraction is sparse end-to-end (no dense U is ever built)."""
        from pint_tpu.models.noise import EcorrOverlapError

        eids, jvars, consumed = [], [], []
        for c in self.noise_components:
            fn = getattr(c, "noise_epoch_segments", None)
            if fn is None:
                continue
            try:
                seg = fn(toas)
            except EcorrOverlapError:
                return None  # fall back to the dense basis
            if seg is not None:
                eids.append(seg[0])
                jvars.append(seg[1])
                consumed.append(type(c).__name__)
        if not eids:
            return None
        eid = np.full(toas.ntoas, -1, dtype=np.int32)
        jv: list = []
        for e, v in zip(eids, jvars):
            mask = e >= 0
            if np.any(eid[mask] >= 0):
                return None  # overlap across components: dense fallback
            eid[mask] = e[mask] + len(jv)
            jv.extend(v.tolist())
        K = len(jv)
        eid[eid < 0] = K  # 'no epoch' slot with zero variance
        return eid, np.asarray(jv + [0.0]), tuple(consumed)

    def noise_model_dimensions(self, toas):
        """{component name: (start, length)} column spans in the stacked
        basis (reference: TimingModel.noise_model_dimensions)."""
        out = {}
        start = 0
        for name, F, _ in self.noise_model_basis_weight_pairs(toas):
            out[name] = (start, F.shape[1])
            start += F.shape[1]
        return out

    # ---------------- par-file round trip -----------------------------

    def as_parfile(self) -> str:
        lines = []
        # derive the BINARY name from the component actually present
        # (programmatically built models have no builder-side attribute)
        binary = next(
            (name[len("Binary"):] for name in self.components
             if name.startswith("Binary")), None)
        if binary:
            lines.append(f"{'BINARY':<15} {binary:>25}\n")
        for c in self._ordered_components():
            for p in c.params.values():
                line = p.as_parfile_line()
                if line:
                    lines.append(line)
        return "".join(lines)

    def validate(self):
        for c in self.components.values():
            c.validate()
        # build-time unit discipline: every declared parameter unit
        # must carry the dimension its component slot requires
        from pint_tpu.units import check_model_units

        check_model_units(self)

    def get_or_create_component(self, name: str):
        """components[name], constructing and attaching it from the
        registry when absent (used by jump conversion and the GUI)."""
        comp = self.components.get(name)
        if comp is None:
            comp = component_types[name]()
            self.add_component(comp)
        return comp

    def jump_flags_to_params(self, toas) -> list:
        """One free JUMP per distinct tim-file JUMP block (the
        ``-tim_jump`` flags the tim parser writes), creating the
        PhaseJump component if needed (reference:
        TimingModel/PhaseJump jump_flags_to_params)."""
        import pint_tpu.models.jump  # register PhaseJump  # noqa: F401

        if "PhaseJump" not in self.components and \
                not any("tim_jump" in f for f in toas.flags):
            return []
        return self.get_or_create_component(
            "PhaseJump").tim_jumps_to_params(toas)

    def compare(self, other: "TimingModel") -> str:
        """Parameter-by-parameter diff (reference: TimingModel.compare)."""
        rows = []
        names = dict.fromkeys(list(self.params) + list(other.params))
        for n in names:
            a = self.get_param(n).value if n in self else None
            b = other.get_param(n).value if n in other else None
            if a != b:
                rows.append(f"{n:<12} {a!r} -> {b!r}")
        return "\n".join(rows)

    def __repr__(self):
        comps = ", ".join(self.components)
        return f"<TimingModel {self.name or '?'} [{comps}]>"


# ---------------- small device helpers ----------------


def dd_addf_day(batch, ref_day: float) -> DD:
    """(tdb - ref_day) in days as DD: exact integer-day difference plus
    the dd fraction."""
    from pint_tpu.ops.dd import dd_add_f

    return dd_add_f(batch.tdb_frac, batch.tdb_day - ref_day)


def dd_add_dd(a: DD, b: DD) -> DD:
    return dd_add(a, b)


def dd_sub_dd(a: DD, b: DD) -> DD:
    from pint_tpu.ops.dd import dd_sub

    return dd_sub(a, b)


def _strip(cache: dict) -> dict:
    """Cache minus the main batch (passed separately)."""
    return {k: v for k, v in cache.items() if k != "batch"}


def _np_set(arr, i, x):
    arr = jnp.asarray(arr)
    return arr.at[i].set(x)
