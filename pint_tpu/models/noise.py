"""Noise models: white-noise scaling and correlated-noise bases.

Reference: src/pint/models/noise_model.py (NoiseComponent,
ScaleToaError, ScaleDmError, EcorrNoise, PLRedNoise, PLDMNoise,
create_quantization_matrix, create_fourier_design_matrix, powerlaw).

TPU-first design: every noise component reduces to host-precomputed
static arrays — a scaled per-TOA sigma vector, a dense (N, q) basis
matrix, and a (q,) prior-variance vector — consumed by the jitted GLS
kernel in ``pint_tpu.gls``. Noise *hyper*-parameters (EFAC, ECORR
amplitude, red-noise A/gamma) are not least-squares-fittable (exactly
as in the reference, where GLS marginalizes over basis coefficients and
the hyperparameters move only under MCMC/Bayesian sampling), so basis
and weights are rebuilt on the host whenever a value changes — no
retrace of the phase function is involved.

Conventions (SURVEY.md Appendix A.6):
  sigma_scaled^2 = EFAC^2 * (sigma^2 + EQUAD^2)      [TEMPO2/PINT]
  TNEQ is log10(EQUAD/s); EQUAD/ECORR par values are in microseconds.
  ECORR: TOAs quantized into observing epochs (default bucket gap
  0.5 day, buckets with >= 2 TOAs), basis = 0/1 membership matrix,
  weight = ECORR^2 per column.
  Red noise: Fourier pairs sin/cos(2 pi j t / T_span), j = 1..k;
  weight per pair = P(f_j) * Delta_f with the power-law PSD
  P(f) = A^2/(12 pi^2) f_yr^(gamma-3) f^(-gamma)  [s^2].
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from pint_tpu.models.parameter import (
    floatParameter,
    intParameter,
    maskParameter,
)
from pint_tpu.models.timing_model import Component

__all__ = [
    "NoiseComponent", "ScaleToaError", "ScaleDmError", "EcorrNoise",
    "PLRedNoise", "PLDMNoise", "PLChromNoise", "PLSWNoise",
    "create_quantization_matrix", "quantization_buckets",
    "create_fourier_design_matrix", "powerlaw", "EcorrOverlapError",
]

FYR = 1.0 / (86400.0 * 365.25)  # 1/yr in Hz


class EcorrOverlapError(ValueError):
    """A TOA fell into two ECORR epochs (overlapping masks)."""


def _tdb_seconds(toas, ref_day=None) -> np.ndarray:
    """TDB seconds since the first TOA's day (f64 is ample for a noise
    basis: sub-ns phase error on multi-decade spans). ``ref_day``
    pins the zero point to another dataset's first day — with the
    Tspan pin, the serve append path's basis-ALIGNMENT contract
    (a time shift rotates each Fourier sin/cos pair, so rows built
    against a different epoch describe rotated columns that cannot
    extend a cached Gram)."""
    if toas.tdb_day is None:
        raise ValueError("TOAs need compute_TDBs() before noise bases")
    day0 = toas.tdb_day.min() if ref_day is None else ref_day
    return ((toas.tdb_day - day0) + toas.tdb_frac[0]
            + toas.tdb_frac[1]) * 86400.0


def powerlaw(f: np.ndarray, A: float, gamma: float) -> np.ndarray:
    """Power-law PSD [s^2/Hz-ish per-bin convention of the reference]:
    P(f) = A^2/(12 pi^2) * f_yr^(gamma-3) * f^(-gamma)
    (reference: noise_model.powerlaw)."""
    return A ** 2 / (12.0 * np.pi ** 2) * FYR ** (gamma - 3.0) \
        * np.asarray(f, dtype=np.float64) ** (-gamma)


def quantization_buckets(t_days: np.ndarray, dt_days: float = 0.5,
                         nmin: int = 2) -> List[np.ndarray]:
    """Index lists of observing epochs: a new bucket starts whenever
    the gap to the previous (sorted) time exceeds dt_days; buckets with
    < nmin members are dropped. The sparse primitive behind both the
    dense quantization matrix and the O(N) Sherman-Morrison segment
    path."""
    t = np.asarray(t_days, dtype=np.float64)
    isort = np.argsort(t)
    buckets: List[List[int]] = []
    last = None
    for i in isort:
        if last is None or t[i] - last > dt_days:
            buckets.append([])
        buckets[-1].append(i)
        last = t[i]
    return [np.asarray(b) for b in buckets if len(b) >= nmin]


def create_quantization_matrix(t_days: np.ndarray, dt_days: float = 0.5,
                               nmin: int = 2) -> np.ndarray:
    """Group times into observing epochs; return the (N, N_epoch) 0/1
    membership matrix, keeping only epochs with >= nmin TOAs
    (reference: noise_model.create_quantization_matrix).
    """
    keep = quantization_buckets(t_days, dt_days, nmin)
    U = np.zeros((len(np.asarray(t_days)), len(keep)), dtype=np.float64)
    for j, b in enumerate(keep):
        U[b, j] = 1.0
    return U


def create_fourier_design_matrix(t_sec: np.ndarray, nmodes: int,
                                 Tspan: Optional[float] = None
                                 ) -> Tuple[np.ndarray, np.ndarray]:
    """(F, freqs): F is (N, 2*nmodes) with columns
    [sin(2pi f_1 t), cos(2pi f_1 t), sin(2pi f_2 t), ...] and freqs the
    per-column frequencies [Hz]
    (reference: noise_model.create_fourier_design_matrix)."""
    t = np.asarray(t_sec, dtype=np.float64)
    T = Tspan if Tspan is not None else (t.max() - t.min())
    f = np.arange(1, nmodes + 1, dtype=np.float64) / T
    F = np.zeros((len(t), 2 * nmodes))
    arg = 2.0 * np.pi * t[:, None] * f[None, :]
    F[:, ::2] = np.sin(arg)
    F[:, 1::2] = np.cos(arg)
    return F, np.repeat(f, 2)




def _spec(d):
    """{name-or-'PREFIX*': parse_unit(text)} from a plain dict (see
    pint_tpu.units._spec_lookup for the key rules)."""
    from pint_tpu.units import parse_unit

    return {k: parse_unit(v) for k, v in d.items()}


class NoiseComponent(Component):
    """Base: category 'noise'; contributes no delay/phase. Subclasses
    override exactly one of the three noise hooks."""

    category = "noise"
    register = False
    is_basis_noise = False  # True => contributes (basis, weights) to GLS

    def scale_toa_sigma_s2(self, toas, sigma2_s2: np.ndarray) -> np.ndarray:
        """Transform per-TOA variance [s^2] (white components only)."""
        return sigma2_s2

    def scale_dm_sigma2(self, toas, sigma2: np.ndarray) -> np.ndarray:
        """Transform per-TOA wideband-DM variance [(pc/cm^3)^2]."""
        return sigma2

    def noise_basis_weight(self, toas, tspan=None,
                           tref_day=None):
        """(F (N,q), phi (q,)) for basis components, else None.

        ``tspan`` [s] pins the Fourier fundamental 1/T instead of
        deriving it from the passed TOAs' own span — the
        basis-ALIGNMENT contract of the serve append path (ISSUE
        12): rows appended to a cached accumulated system must be
        evaluated on the ORIGINAL span's frequencies, or their
        columns describe a different GP than the cached Gram.
        Ignored by non-Fourier bases (ECORR quantization)."""
        return None


class ScaleToaError(NoiseComponent):
    """EFAC/EQUAD/TNEQ white-noise rescaling
    (reference: ScaleToaError.scale_toa_sigma)."""

    register = True


    def param_dimensions(self):
        return _spec({"EFAC*": "", "EQUAD*": "us",
                      "TNEQ*": "log10(s)"})

    def __init__(self):
        super().__init__()
        self.efacs: list = []
        self.equads: list = []
        self.tneqs: list = []

    def setup(self):
        self.efacs = sorted((n for n in self.params
                             if n.startswith("EFAC")),
                            key=lambda n: self.params[n].index)
        self.equads = sorted((n for n in self.params
                              if n.startswith("EQUAD")),
                             key=lambda n: self.params[n].index)
        self.tneqs = sorted((n for n in self.params
                             if n.startswith("TNEQ")),
                            key=lambda n: self.params[n].index)

    def add_noise_param(self, prefix, key, key_value, value, index=None):
        idx = index or (len([n for n in self.params
                             if n.startswith(prefix)]) + 1)
        p = maskParameter(prefix, index=idx, key=key,
                          key_value=key_value, value=value,
                          units={"EFAC": "", "EQUAD": "us",
                                 "TNEQ": "log10(s)"}[prefix])
        self.add_param(p)
        self.setup()
        return p

    def scale_toa_sigma_s2(self, toas, sigma2_s2):
        """sigma^2 -> EFAC^2 (sigma^2 + EQUAD^2), per mask group."""
        out = np.array(sigma2_s2, dtype=np.float64)
        for name in self.equads:
            p = self.params[name]
            if p.value is None:
                continue
            m = p.select_mask(toas)
            out[m] = out[m] + (p.value * 1e-6) ** 2
        for name in self.tneqs:
            p = self.params[name]
            if p.value is None:
                continue
            m = p.select_mask(toas)
            out[m] = out[m] + (10.0 ** p.value) ** 2
        for name in self.efacs:
            p = self.params[name]
            if p.value is None:
                continue
            m = p.select_mask(toas)
            out[m] = out[m] * p.value ** 2
        return out


class ScaleDmError(NoiseComponent):
    """DMEFAC/DMEQUAD scaling of wideband DM-channel uncertainties
    (reference: ScaleDmError.scale_dm_sigma)."""

    register = True


    def param_dimensions(self):
        return _spec({"DMEFAC*": "", "DMEQUAD*": "pc cm^-3"})

    def __init__(self):
        super().__init__()
        self.dmefacs: list = []
        self.dmequads: list = []

    def setup(self):
        self.dmefacs = sorted((n for n in self.params
                               if n.startswith("DMEFAC")),
                              key=lambda n: self.params[n].index)
        self.dmequads = sorted((n for n in self.params
                                if n.startswith("DMEQUAD")),
                               key=lambda n: self.params[n].index)

    def scale_dm_sigma2(self, toas, sigma2):
        out = np.array(sigma2, dtype=np.float64)
        for name in self.dmequads:
            p = self.params[name]
            if p.value is None:
                continue
            m = p.select_mask(toas)
            out[m] = out[m] + p.value ** 2
        for name in self.dmefacs:
            p = self.params[name]
            if p.value is None:
                continue
            m = p.select_mask(toas)
            out[m] = out[m] * p.value ** 2
        return out


class EcorrNoise(NoiseComponent):
    """Epoch-correlated jitter noise (ECORR): fully correlated within an
    observing epoch, white across epochs; enters GLS as a 0/1
    quantization basis with weight ECORR^2 per epoch
    (reference: EcorrNoise.ecorr_basis_weight_pair)."""

    register = True


    def param_dimensions(self):
        return _spec({"ECORR*": "us"})

    is_basis_noise = True

    def __init__(self):
        super().__init__()
        self.ecorrs: list = []

    def setup(self):
        self.ecorrs = sorted((n for n in self.params
                              if n.startswith("ECORR")),
                             key=lambda n: self.params[n].index)

    def add_ecorr(self, key, key_value, value, index=None):
        idx = index or (len(self.ecorrs) + 1)
        p = maskParameter("ECORR", index=idx, key=key,
                          key_value=key_value, value=value, units="us")
        self.add_param(p)
        self.setup()
        return p

    def noise_basis_weight(self, toas, tspan=None,
                           tref_day=None):
        mjd = toas.get_mjds()
        Us, ws = [], []
        for name in self.ecorrs:
            p = self.params[name]
            if p.value is None:
                continue
            mask = p.select_mask(toas)
            idx = np.flatnonzero(mask)
            if len(idx) == 0:
                continue
            Usub = create_quantization_matrix(mjd[idx])
            if Usub.shape[1] == 0:
                continue
            U = np.zeros((toas.ntoas, Usub.shape[1]))
            U[idx, :] = Usub
            Us.append(U)
            ws.append(np.full(Usub.shape[1], (p.value * 1e-6) ** 2))
        if not Us:
            return None
        return np.concatenate(Us, axis=1), np.concatenate(ws)

    def noise_epoch_segments(self, toas):
        """Sparse epoch structure without densifying the quantization
        matrix: (eid (N,) int32 — epoch index or -1 for 'no epoch' —,
        jvar (K,) per-epoch variances [s^2]), or None when inactive.
        Column order matches noise_basis_weight exactly (same mask and
        bucket enumeration), O(N) memory at any scale. Raises
        EcorrOverlapError when ECORR masks overlap (a TOA in two epochs
        has no rank-1-per-epoch representation; callers fall back to
        the dense basis)."""
        mjd = toas.get_mjds()
        eid = np.full(toas.ntoas, -1, dtype=np.int32)
        jvar: list = []
        for name in self.ecorrs:
            p = self.params[name]
            if p.value is None:
                continue
            idx = np.flatnonzero(p.select_mask(toas))
            if len(idx) == 0:
                continue
            for b in quantization_buckets(mjd[idx]):
                rows = idx[b]
                if np.any(eid[rows] >= 0):
                    raise EcorrOverlapError(
                        f"overlapping ECORR masks ({name})")
                eid[rows] = len(jvar)
                jvar.append((p.value * 1e-6) ** 2)
        if not jvar:
            return None
        return eid, np.asarray(jvar)


class PLRedNoise(NoiseComponent):
    """Power-law achromatic red noise as a Fourier-basis GP
    (reference: PLRedNoise.pl_rn_basis_weight_pair).

    Amplitude conventions: TNREDAMP is log10(A) (TempoNest); RNAMP is
    the TEMPO-style amplitude related by
    A = RNAMP * 2 pi sqrt(3) / (86400 * 365.25 * 1e6), gamma = -RNIDX.
    """

    register = True


    def param_dimensions(self):
        return _spec({"TNREDAMP": "", "TNREDGAM": "",
                      "RNAMP": "us/sqrt(yr)", "RNIDX": ""})

    is_basis_noise = True

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "TNREDAMP", units="log10(strain)", aliases=["TNRedAmp"],
            description="log10 red-noise amplitude"))
        self.add_param(floatParameter(
            "TNREDGAM", units="", aliases=["TNRedGam"],
            description="red-noise spectral index gamma"))
        self.add_param(intParameter(
            "TNREDC", value=30, aliases=["TNRedC", "TNREDFLOW"],
            description="number of Fourier modes"))
        self.add_param(floatParameter("RNAMP", units="us/sqrt(yr)"))
        self.add_param(floatParameter("RNIDX", units=""))

    def amplitude_gamma(self):
        if self.TNREDAMP.value is not None:
            return 10.0 ** self.TNREDAMP.value, self.TNREDGAM.value
        if self.RNAMP.value is not None:
            fac = (86400.0 * 365.25 * 1e6) / (2.0 * np.pi * np.sqrt(3.0))
            return self.RNAMP.value / fac, -self.RNIDX.value
        return None, None

    def validate(self):
        A, g = self.amplitude_gamma()
        if A is not None and g is None:
            raise ValueError("red-noise amplitude set without index "
                             "(TNREDGAM/RNIDX)")

    def noise_basis_weight(self, toas, tspan=None,
                           tref_day=None):
        A, gamma = self.amplitude_gamma()
        if A is None:
            return None
        nmodes = int(self.TNREDC.value or 30)
        t = _tdb_seconds(toas, ref_day=tref_day)
        F, freqs = create_fourier_design_matrix(t, nmodes, Tspan=tspan)
        df = freqs[0]
        phi = powerlaw(freqs, A, gamma) * df
        return F, phi


def _dm_rows_from_time_basis(toas, F_time):
    """Wideband DM-channel block [pc/cm^3 per coefficient] of a pure
    nu^-2 (DM-perturbation) noise process, derived from its CACHED
    time-channel block: delay rows are DMconst * DM / nu^2, so
    DM rows = F_time * nu^2 / DMconst — using the cached F_time
    guarantees the two channels can never desynchronize in mode count
    or time grid. Infinite-frequency rows (barycentered TOAs) carry
    F_time = 0 and the product would be 0*inf: those rows are set to
    zero — the GP simply does not inform the DM channel there
    (reference: the wideband GLS DM-block coupling)."""
    from pint_tpu import DMconst

    nu = np.asarray(toas.get_freqs())
    scale = np.where(np.isfinite(nu), nu * nu / DMconst, 0.0)
    return np.asarray(F_time) * scale[:, None]


class PLDMNoise(NoiseComponent):
    """Power-law DM (chromatic nu^-2) noise: the red-noise Fourier basis
    with each row scaled by (1400 MHz / nu)^2
    (reference: PLDMNoise.pl_dm_basis_weight_pair)."""

    register = True


    def param_dimensions(self):
        return _spec({"TNDMAMP": "", "TNDMGAM": ""})

    is_basis_noise = True

    REF_FREQ_MHZ = 1400.0

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "TNDMAMP", units="log10", aliases=["TNDMAmp"],
            description="log10 DM-noise amplitude"))
        self.add_param(floatParameter(
            "TNDMGAM", units="", aliases=["TNDMGam"],
            description="DM-noise spectral index"))
        self.add_param(intParameter(
            "TNDMC", value=30, aliases=["TNDMC"],
            description="number of DM Fourier modes"))

    def noise_basis_weight(self, toas, tspan=None,
                           tref_day=None):
        if self.TNDMAMP.value is None:
            return None
        A = 10.0 ** self.TNDMAMP.value
        gamma = self.TNDMGAM.value
        nmodes = int(self.TNDMC.value or 30)
        t = _tdb_seconds(toas, ref_day=tref_day)
        F, freqs = create_fourier_design_matrix(t, nmodes, Tspan=tspan)
        scale = (self.REF_FREQ_MHZ / toas.get_freqs()) ** 2
        F = F * scale[:, None]
        df = freqs[0]
        phi = powerlaw(freqs, A, gamma) * df
        return F, phi

    def noise_dm_basis(self, toas, F_time):
        """Wideband DM-channel block (see _dm_rows_from_time_basis)."""
        return _dm_rows_from_time_basis(toas, F_time)


class PLChromNoise(NoiseComponent):
    """Power-law chromatic noise with a general spectral index: the
    red-noise Fourier basis scaled per row by (1400 MHz/nu)^alpha,
    alpha = TNCHROMIDX from the ChromaticCM component (default 4)
    (reference: PLChromNoise.pl_chrom_basis_weight_pair)."""

    register = True


    def param_dimensions(self):
        return _spec({"TNCHROMAMP": "", "TNCHROMGAM": ""})

    is_basis_noise = True

    REF_FREQ_MHZ = 1400.0

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "TNCHROMAMP", units="log10", aliases=["TNChromAmp"],
            description="log10 chromatic-noise amplitude"))
        self.add_param(floatParameter(
            "TNCHROMGAM", units="", aliases=["TNChromGam"],
            description="chromatic-noise spectral index"))
        self.add_param(intParameter(
            "TNCHROMC", value=30, aliases=["TNChromC"],
            description="number of chromatic Fourier modes"))

    def _alpha(self) -> float:
        from pint_tpu.models.components_tail import chromatic_index

        return chromatic_index(getattr(self, "_parent", None))

    def validate(self):
        if self.TNCHROMAMP.value is not None and \
                self.TNCHROMGAM.value is None:
            raise ValueError("TNCHROMAMP set without TNCHROMGAM")

    def noise_basis_weight(self, toas, tspan=None,
                           tref_day=None):
        if self.TNCHROMAMP.value is None:
            return None
        A = 10.0 ** self.TNCHROMAMP.value
        gamma = self.TNCHROMGAM.value
        nmodes = int(self.TNCHROMC.value or 30)
        t = _tdb_seconds(toas, ref_day=tref_day)
        F, freqs = create_fourier_design_matrix(t, nmodes, Tspan=tspan)
        scale = (self.REF_FREQ_MHZ / toas.get_freqs()) ** self._alpha()
        F = F * np.where(np.isfinite(scale), scale, 0.0)[:, None]
        df = freqs[0]
        phi = powerlaw(freqs, A, gamma) * df
        return F, phi


class PLSWNoise(NoiseComponent):
    """Power-law stochastic solar-wind noise: the Fourier basis scaled
    per row by the solar-wind line-of-sight geometry times nu^-2
    (reference: PLSWNoise.pl_sw_basis_weight_pair). Requires a
    SolarWindDispersion component for the geometry."""

    register = True


    def param_dimensions(self):
        return _spec({"TNSWAMP": "", "TNSWGAM": ""})

    is_basis_noise = True

    REF_FREQ_MHZ = 1400.0

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "TNSWAMP", units="log10", aliases=["TNSWAmp"],
            description="log10 solar-wind-noise amplitude"))
        self.add_param(floatParameter(
            "TNSWGAM", units="", aliases=["TNSWGam"],
            description="solar-wind-noise spectral index"))
        self.add_param(intParameter(
            "TNSWC", value=10, aliases=["TNSWC"],
            description="number of solar-wind Fourier modes"))

    def validate(self):
        if self.TNSWAMP.value is not None and \
                self.TNSWGAM.value is None:
            raise ValueError("TNSWAMP set without TNSWGAM")

    def noise_basis_weight(self, toas, tspan=None,
                           tref_day=None):
        if self.TNSWAMP.value is None:
            return None
        parent = getattr(self, "_parent", None)
        if parent is None:
            return None
        A = 10.0 ** self.TNSWAMP.value
        gamma = self.TNSWGAM.value
        nmodes = int(self.TNSWC.value or 10)
        t = _tdb_seconds(toas, ref_day=tref_day)
        F, freqs = create_fourier_design_matrix(t, nmodes, Tspan=tspan)
        # geometry at nominal astrometry (second-order in updates):
        # n_e -> DM conversion normalized at 90-degree elongation, 1 AU
        from pint_tpu.models.components_extra import AU_M, PC_M
        from pint_tpu.models.components_tail import (
            solar_wind_geometry_host,
        )

        geom = solar_wind_geometry_host(toas,
                                        parent._host_psr_dir(toas))
        geom0 = (AU_M * AU_M / PC_M) * (np.pi / 2.0) / AU_M
        fscale = (self.REF_FREQ_MHZ / toas.get_freqs()) ** 2
        scale = (geom / geom0) * np.where(np.isfinite(fscale), fscale,
                                          0.0)
        F = F * scale[:, None]
        df = freqs[0]
        phi = powerlaw(freqs, A, gamma) * df
        return F, phi

    def noise_dm_basis(self, toas, F_time):
        """Solar-wind noise is also a pure nu^-2 DM perturbation (the
        geometry factor rides along in F_time): couple it into the
        wideband DM rows like PLDMNoise."""
        return _dm_rows_from_time_basis(toas, F_time)
