"""Astrometry: sky position + proper motion + parallax → Roemer delay.

Reference: src/pint/models/astrometry.py (Astrometry,
AstrometryEquatorial, AstrometryEcliptic, solar_system_geometric_delay,
ssb_to_psb_xyz_ICRS). All delays here are ≤ ~500 s needing ns accuracy →
plain f64 on device (relative 2e-12 << f64 eps headroom); only time and
phase need dd.

Internal angle unit is radians (par I/O converts sexagesimal); proper
motions are mas/yr, parallax mas — par-file units, so design-matrix
columns are per-par-unit like the reference's.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import pc_m, c_m_s
from pint_tpu.models.parameter import (
    AngleParameter,
    MJDParameter,
    floatParameter,
)
from pint_tpu.models.timing_model import DelayComponent
from pint_tpu.ops.dd import dd_to_f64
from pint_tpu.time.frames import icrs_to_ecliptic_matrix

MAS_YR_TO_RAD_S = (np.pi / 180.0 / 3600.0 / 1000.0) / (365.25 * 86400.0)
MAS_TO_RAD = np.pi / 180.0 / 3600.0 / 1000.0
PC_LS = pc_m / c_m_s  # parsec in light-seconds


class Astrometry(DelayComponent):
    category = "astrometry"
    register = False

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter(
            "POSEPOCH", description="epoch of position/proper motion"))
        self.add_param(floatParameter("PX", units="mas", value=0.0,
                                      description="parallax"))

    def _tdb_mjd_f64(self, batch):
        return batch.tdb_day + dd_to_f64(batch.tdb_frac)

    def _dt_yr(self, pv, batch):
        """Years since POSEPOCH (f64 — PM terms are tiny)."""
        pos_mjd = pv["POSEPOCH"].hi + pv["POSEPOCH"].lo \
            if "POSEPOCH" in pv else self._parent.ref_day
        return (self._tdb_mjd_f64(batch) - pos_mjd) / 365.25

    def psr_dir(self, pv, batch):
        """Unit vector SSB→pulsar, ICRS, per TOA (N,3)."""
        raise NotImplementedError

    def param_dimensions(self):
        from pint_tpu.units import parse_unit

        ang = parse_unit("rad")
        pm = parse_unit("mas/yr")
        return {"POSEPOCH": parse_unit("d"), "PX": parse_unit("mas"),
                "RAJ": ang, "DECJ": ang, "ELONG": ang, "ELAT": ang,
                "PMRA": pm, "PMDEC": pm, "PMELONG": pm, "PMELAT": pm}

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        n = self.psr_dir(pv, batch)
        ctx["psr_dir"] = n
        r = batch.ssb_obs_pos  # lt-s
        rdotn = jnp.sum(r * n, axis=-1)
        # barycentric observing frequency for downstream dispersion
        vdotn = jnp.sum(batch.ssb_obs_vel * n, axis=-1)  # v/c
        ctx["bfreq"] = batch.freq_mhz * (1.0 - vdotn)
        roemer = -rdotn
        px = pv["PX"].hi if "PX" in pv else 0.0
        pxr = jnp.where(jnp.asarray(px) != 0.0,
                        self._parallax_delay(r, rdotn, px), 0.0)
        return roemer + pxr

    def _parallax_delay(self, r, rdotn, px_mas):
        # Δ_px = (|r|² − (r·n̂)²) / (2 d)  [lt-s units] — reference:
        # Astrometry.solar_system_geometric_delay parallax term
        d_ls = PC_LS / (px_mas * 1e-3 + 1e-30)  # mas → arcsec → pc
        r2 = jnp.sum(r * r, axis=-1)
        return (r2 - rdotn ** 2) / (2.0 * d_ls)


class AstrometryEquatorial(Astrometry):
    """RAJ/DECJ/PMRA/PMDEC (reference: AstrometryEquatorial)."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(AngleParameter("RAJ", units="H:M:S",
                                      aliases=["RA"]))
        self.add_param(AngleParameter("DECJ", units="D:M:S",
                                      aliases=["DEC"]))
        self.add_param(floatParameter("PMRA", units="mas/yr", value=0.0,
                                      description="mu_alpha*cos(dec)"))
        self.add_param(floatParameter("PMDEC", units="mas/yr", value=0.0))

    def validate(self):
        if self.RAJ.value is None or self.DECJ.value is None:
            raise ValueError("AstrometryEquatorial requires RAJ and DECJ")

    def psr_dir(self, pv, batch):
        a0 = pv["RAJ"].hi + pv["RAJ"].lo
        d0 = pv["DECJ"].hi + pv["DECJ"].lo
        dt_yr = self._dt_yr(pv, batch)
        pmra = pv.get("PMRA")
        pmdec = pv.get("PMDEC")
        mu_a = (pmra.hi if pmra is not None else 0.0) * MAS_TO_RAD
        mu_d = (pmdec.hi if pmdec is not None else 0.0) * MAS_TO_RAD
        cosd, sind = jnp.cos(d0), jnp.sin(d0)
        # PMRA is mu_alpha* (includes cos dec): alpha advances by
        # mu_a dt / cos(dec)
        a = a0 + mu_a * dt_yr / cosd
        d = d0 + mu_d * dt_yr
        ca, sa = jnp.cos(a), jnp.sin(a)
        cd, sd = jnp.cos(d), jnp.sin(d)
        return jnp.stack([cd * ca, cd * sa, sd], axis=-1)


class AstrometryEcliptic(Astrometry):
    """ELONG/ELAT/PMELONG/PMELAT in the IAU-obliquity ecliptic frame
    (reference: AstrometryEcliptic + pulsar_ecliptic.py)."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(AngleParameter("ELONG", units="deg",
                                      aliases=["LAMBDA"]))
        self.add_param(AngleParameter("ELAT", units="deg",
                                      aliases=["BETA"]))
        self.add_param(floatParameter("PMELONG", units="mas/yr", value=0.0,
                                      aliases=["PMLAMBDA"]))
        self.add_param(floatParameter("PMELAT", units="mas/yr", value=0.0,
                                      aliases=["PMBETA"]))
        from pint_tpu.models.parameter import strParameter

        self.add_param(strParameter("ECL", value="IERS2010"))

    _OBLIQUITY = {  # arcsec (reference: src/pint/data/runtime/ecliptic.dat)
        "IERS2010": 84381.406,
        "IERS2003": 84381.4059,
        "IAU1976": 84381.448,
        "IAU1980": 84381.448,
    }

    @classmethod
    def obliquity_arcsec(cls, ecl) -> float:
        """Strict per-convention obliquity lookup — the ONE resolver
        (validate, _ecl_matrix, and modelutils._convert all use it, so
        a typo'd convention fails identically everywhere instead of
        silently falling back to IERS2010 on some paths)."""
        obl = cls._OBLIQUITY.get((ecl or "IERS2010").upper())
        if obl is None:
            raise ValueError(
                f"unknown ecliptic convention {ecl!r} "
                f"(know {sorted(cls._OBLIQUITY)})")
        return obl

    def validate(self):
        if self.ELONG.value is None or self.ELAT.value is None:
            raise ValueError("AstrometryEcliptic requires ELONG and ELAT")
        self.obliquity_arcsec(self.ECL.value)  # typo'd ECL fails HERE

    def _ecl_matrix(self):
        obl = self.obliquity_arcsec(self.ECL.value)
        # ecliptic ← ICRS; we need its transpose to go ecliptic → ICRS
        return icrs_to_ecliptic_matrix(obl).T

    def psr_dir(self, pv, batch):
        l0 = pv["ELONG"].hi + pv["ELONG"].lo
        b0 = pv["ELAT"].hi + pv["ELAT"].lo
        dt_yr = self._dt_yr(pv, batch)
        mu_l = pv["PMELONG"].hi * MAS_TO_RAD if "PMELONG" in pv else 0.0
        mu_b = pv["PMELAT"].hi * MAS_TO_RAD if "PMELAT" in pv else 0.0
        cosb = jnp.cos(b0)
        lam = l0 + mu_l * dt_yr / cosb
        bet = b0 + mu_b * dt_yr
        cl, sl = jnp.cos(lam), jnp.sin(lam)
        cb, sb = jnp.cos(bet), jnp.sin(bet)
        n_ecl = jnp.stack([cb * cl, cb * sl, sb], axis=-1)
        return n_ecl @ jnp.asarray(self._ecl_matrix(), n_ecl.dtype).T
