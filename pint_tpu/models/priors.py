"""Parameter prior distributions for Bayesian inference.

Reference: src/pint/models/priors.py (Prior, UniformUnboundedRV,
GaussianBoundedRV, prior_pdf hooks on Parameter). Here a prior is a
small object with jnp-traceable logpdf and a ppf (for nested-sampling
prior transforms); Parameter gains a ``prior`` attribute defaulting to
an unbounded uniform.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["Prior", "UniformPrior", "UniformUnboundedPrior",
           "GaussianPrior", "Log10TransformedPrior"]

_LN10 = float(np.log(10.0))


class Prior:
    """Base prior: improper flat over the whole real line (reference:
    Prior with UniformUnboundedRV)."""

    def logpdf(self, x):
        return jnp.zeros_like(jnp.asarray(x, dtype=jnp.float64))

    def pdf(self, x):
        return jnp.exp(self.logpdf(x))

    def ppf(self, q):
        raise ValueError(
            f"{type(self).__name__} is improper: no prior transform; "
            "give the parameter a bounded prior for nested sampling")

    def __repr__(self):
        return f"{type(self).__name__}()"


class UniformUnboundedPrior(Prior):
    """Explicit alias of the default improper flat prior."""


class UniformPrior(Prior):
    """Proper uniform on [lower, upper] (reference: UniformBoundedRV)."""

    def __init__(self, lower: float, upper: float):
        if not upper > lower:
            raise ValueError("need upper > lower")
        self.lower, self.upper = float(lower), float(upper)

    def logpdf(self, x):
        x = jnp.asarray(x, dtype=jnp.float64)
        inside = (x >= self.lower) & (x <= self.upper)
        return jnp.where(inside,
                         -jnp.log(self.upper - self.lower), -jnp.inf)

    def ppf(self, q):
        return self.lower + (self.upper - self.lower) * jnp.asarray(q)

    def __repr__(self):
        return f"UniformPrior({self.lower}, {self.upper})"


class Log10TransformedPrior(Prior):
    """Change-of-variables adapter for a dimension SAMPLED as
    eta = log10(v) whose declared prior is over the linear value v
    (the ECORR convention in ``sampling.likelihood``: the parameter's
    prior is in microseconds, the sampled dimension is log10(us)):
    p_eta(eta) = p_v(10**eta) * 10**eta * ln(10). The base prior must
    have positive support for ``ppf`` to be meaningful."""

    def __init__(self, base: Prior):
        self.base = base

    def logpdf(self, eta):
        eta = jnp.asarray(eta, dtype=jnp.float64)
        return (self.base.logpdf(10.0 ** eta) + eta * _LN10
                + np.log(_LN10))

    def ppf(self, q):
        return jnp.log10(self.base.ppf(q))

    def __repr__(self):
        return f"Log10TransformedPrior({self.base!r})"


class GaussianPrior(Prior):
    """Gaussian prior N(mu, sigma) (reference: GaussianBoundedRV without
    the truncation; add bounds by composing with UniformPrior support if
    needed)."""

    def __init__(self, mu: float, sigma: float):
        if not sigma > 0:
            raise ValueError("need sigma > 0")
        self.mu, self.sigma = float(mu), float(sigma)

    def logpdf(self, x):
        z = (jnp.asarray(x, dtype=jnp.float64) - self.mu) / self.sigma
        return -0.5 * z * z - jnp.log(
            self.sigma * jnp.sqrt(2.0 * jnp.pi))

    def ppf(self, q):
        from jax.scipy.special import ndtri

        return self.mu + self.sigma * ndtri(jnp.asarray(q))

    def __repr__(self):
        return f"GaussianPrior({self.mu}, {self.sigma})"
