"""Dispersion delays: DM Taylor series, DMX piecewise windows, DMJUMP.

Reference: src/pint/models/dispersion_model.py (Dispersion, DispersionDM,
DispersionDMX, DispersionJump). Delay = DMconst · DM(t) / ν² with ν the
Doppler-corrected barycentric frequency (ctx["bfreq"] from astrometry).

DMX windows become a host-precomputed (N,) int window-index array plus
per-window mask columns only where needed: the delay is a dense
mask·value contraction — a single (N,k)×(k,) matmul on device, MXU-
friendly, replacing the reference's per-window TOASelect loop.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from pint_tpu import DMconst
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    maskParameter,
    prefixParameter,
    split_prefixed_name,
)
from pint_tpu.models.timing_model import DelayComponent
from pint_tpu.ops.taylor import taylor_horner
from pint_tpu.ops.dd import dd_to_f64


class Dispersion(DelayComponent):
    category = "dispersion"
    register = False

    def _bfreq(self, batch, ctx):
        return ctx.get("bfreq", batch.freq_mhz)

    def dm_value_device(self, pv, batch, cache, ctx):
        """This component's DM contribution [pc/cm^3] (N,) — the hook
        the wideband DM channel aggregates over (reference:
        TimingModel.total_dm summing Dispersion dm_value)."""
        return jnp.zeros_like(batch.freq_mhz)

    def param_dimensions(self):
        from pint_tpu.models.parameter import split_prefixed_name
        from pint_tpu.units import parse_unit

        ne = parse_unit("pc cm^-3")

        def dm_dim(name):
            # only reached for DM<digits> (exact keys and the longer
            # DMX_* stems win in _spec_lookup before 'DM*')
            _, _, i = split_prefixed_name(name)
            return ne / parse_unit("yr") ** i

        return {"DM": ne, "DM*": dm_dim, "DMEPOCH": parse_unit("d"),
                "DMX": ne, "DMX_*": ne, "DMXR1_*": parse_unit("d"),
                "DMXR2_*": parse_unit("d"), "DMJUMP": ne}


class DispersionDM(Dispersion):
    """DM + DM1·dt + DM2·dt²/2... around DMEPOCH (reference:
    DispersionDM)."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("DM", units="pc cm^-3", value=0.0))
        self.add_param(floatParameter("DM1", units="pc cm^-3 / yr^1",
                                      value=None))
        self.add_param(MJDParameter("DMEPOCH"))

    def dm_terms(self):
        out = ["DM"]
        if self.DM1.value is not None:
            out.append("DM1")
        extras = []
        for name in self.params:
            if name.startswith("DM") and name not in (
                    "DM", "DM1", "DMEPOCH") and name[2:].isdigit():
                # param NAME strings are host data at trace time
                extras.append((int(name[2:]), name))  # graftlint: allow G1 -- name str
        out.extend(nm for _, nm in sorted(extras))
        return out

    def add_dm_term(self, index, value=0.0, frozen=True, uncertainty=None):
        p = prefixParameter(prefix="DM", index=index, value=value,
                            units=f"pc cm^-3 / yr^{index}", frozen=frozen,
                            uncertainty=uncertainty)
        self.add_param(p)
        return p

    def dm_value(self, pv, batch):
        """DM at each TOA [pc/cm3]. Taylor rates are per *second* in the
        reference (DM1 in pc cm^-3 / s? — upstream uses per-year par
        convention converted to sec); we keep par-file per-year units and
        convert here."""
        terms = self.dm_terms()
        dm0 = pv["DM"].hi + pv["DM"].lo
        if len(terms) == 1:
            return dm0 * jnp.ones_like(batch.freq_mhz)
        dmep = pv["DMEPOCH"].hi + pv["DMEPOCH"].lo if "DMEPOCH" in pv \
            else self._parent.ref_day
        tdb = batch.tdb_day + dd_to_f64(batch.tdb_frac)
        dt_yr = (tdb - dmep) / 365.25
        coeffs = [pv[nm].hi + pv[nm].lo for nm in terms]
        return taylor_horner(dt_yr, coeffs)

    def dm_value_device(self, pv, batch, cache, ctx):
        return self.dm_value(pv, batch)

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        bf = self._bfreq(batch, ctx)
        dm = self.dm_value(pv, batch)
        ctx["dm"] = dm
        return DMconst * dm / (bf * bf)

    def linear_design_names(self):
        free = [nm for nm in self.dm_terms()
                if not self.params[nm].frozen]
        if free and not self.DMEPOCH.frozen:
            return []  # dt_yr pivots on a fitted DMEPOCH: stay on AD
        return free

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(DMk) = DMconst * dt_yr^k/k! / nu^2 (the Taylor
        factor mirrors dm_value's taylor_horner)."""
        names = self.linear_design_names()
        if not names:
            return {}
        bf = self._bfreq(batch, ctx)
        inv2 = DMconst / (bf * bf)
        terms = self.dm_terms()
        if len(terms) > 1:
            dmep = pv["DMEPOCH"].hi + pv["DMEPOCH"].lo \
                if "DMEPOCH" in pv else self._parent.ref_day
            tdb = batch.tdb_day + dd_to_f64(batch.tdb_frac)
            dt_yr = (tdb - dmep) / 365.25
        out = {}
        for nm in names:
            k = terms.index(nm)
            if k == 0:
                out[nm] = ("pre_delay", inv2 * jnp.ones_like(bf))
            else:
                out[nm] = ("pre_delay",
                           inv2 * dt_yr ** k / math.factorial(k))
        return out


class DispersionDMX(Dispersion):
    """Piecewise-constant ΔDM over MJD windows: DMX_0001/DMXR1_/DMXR2_
    (reference: DispersionDMX + TOASelect masks)."""

    register = True

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("DMX", units="pc cm^-3", value=0.0,
                                      description="legacy header value"))
        self.dmx_ids: list = []  # index ints, sorted at setup

    def add_dmx_range(self, index, mjd_start, mjd_end, value=0.0,
                      frozen=True, index_str=None):
        istr = index_str or f"{index:04d}"
        self.add_param(prefixParameter(prefix="DMX_", index=index,
                                       index_str=istr, value=value,
                                       units="pc cm^-3", frozen=frozen))
        self.add_param(prefixParameter(prefix="DMXR1_", index=index,
                                       index_str=istr, value=mjd_start,
                                       units="MJD"))
        self.add_param(prefixParameter(prefix="DMXR2_", index=index,
                                       index_str=istr, value=mjd_end,
                                       units="MJD"))

    def setup(self):
        ids = []
        for name in self.params:
            if name.startswith("DMX_"):
                _, istr, idx = split_prefixed_name(name)
                ids.append((idx, istr))
        self.dmx_ids = sorted(ids)

    def validate(self):
        for idx, istr in self.dmx_ids:
            for pre in ("DMXR1_", "DMXR2_"):
                if f"{pre}{istr}" not in self.params:
                    raise ValueError(f"DMX_{istr} missing {pre}{istr}")

    def prepare(self, toas, batch, cache, prefix=""):
        """(N, k) window mask matrix, host-precomputed (static ranges —
        DMXR bounds are not fittable, as in the reference)."""
        if not self.dmx_ids:
            return
        mjd = toas.get_mjds()
        cols = []
        for idx, istr in self.dmx_ids:
            r1 = self.params[f"DMXR1_{istr}"].value
            r2 = self.params[f"DMXR2_{istr}"].value
            cols.append(((mjd >= r1) & (mjd <= r2)).astype(np.float64))
        cache["dmx_masks"] = np.stack(cols, axis=-1)

    def dm_value_device(self, pv, batch, cache, ctx):
        if not self.dmx_ids:
            return jnp.zeros_like(batch.freq_mhz)
        vals = jnp.stack(
            [pv[f"DMX_{istr}"].hi + pv[f"DMX_{istr}"].lo
             for _, istr in self.dmx_ids])
        return cache["dmx_masks"] @ vals  # (N,k)@(k,) one fused matmul

    def linear_design_names(self):
        return [f"DMX_{istr}" for _, istr in self.dmx_ids
                if not self.params[f"DMX_{istr}"].frozen]

    def linear_design_local(self, pv, batch, cache, ctx):
        """d(delay)/d(DMX_i) = DMconst * window_mask_i / nu^2."""
        if not self.dmx_ids:
            return {}
        bf = self._bfreq(batch, ctx)
        inv2 = DMconst / (bf * bf)
        masks = cache["dmx_masks"]
        out = {}
        for col, (_, istr) in enumerate(self.dmx_ids):
            nm = f"DMX_{istr}"
            if not self.params[nm].frozen:
                out[nm] = ("pre_delay",
                           inv2 * masks[:, col].astype(bf.dtype))
        return out

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        if not self.dmx_ids:
            return jnp.zeros_like(batch.freq_mhz)
        bf = self._bfreq(batch, ctx)
        return DMconst * self.dm_value_device(pv, batch, cache, ctx) \
            / (bf * bf)


class DispersionJump(Dispersion):
    """DMJUMP: per-system constant DM offset applied to wideband DM
    measurements only — zero TOA delay (reference: DispersionJump;
    sign/semantics: subtracted from the measured DM channel)."""

    register = True

    def __init__(self):
        super().__init__()
        self.dmjumps: list = []

    def add_dmjump(self, index, key, key_value, value=0.0, frozen=True):
        p = maskParameter("DMJUMP", index=index, key=key,
                          key_value=key_value, value=value, frozen=frozen,
                          units="pc cm^-3")
        self.add_param(p)
        self.dmjumps.append(p.name)
        return p

    def setup(self):
        self.dmjumps = [n for n in self.params if n.startswith("DMJUMP")]

    def prepare(self, toas, batch, cache, prefix=""):
        for name in self.dmjumps:
            cache[f"mask_{name}"] = self.params[name].select_mask(
                toas).astype(np.float64)

    def delay(self, pv, batch, cache, ctx, delay_so_far):
        return jnp.zeros_like(batch.freq_mhz)

    def dm_value_device(self, pv, batch, cache, ctx):
        """-Σ DMJUMPi·maski: the reference convention applies -DMJUMP
        to the model-side DM of the selected subset (src/pint/models/
        dispersion_model.py DispersionJump.jump_dm), so a positive
        published DMJUMP means the subset's measured DM reads low."""
        out = jnp.zeros_like(batch.freq_mhz)
        for name in self.dmjumps:
            if name in pv:
                out = out - (pv[name].hi + pv[name].lo) * \
                    cache[f"mask_{name}"]
        return out
