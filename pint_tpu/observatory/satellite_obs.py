"""Satellite observatories: spacecraft orbit files -> per-photon
observatory positions.

Reference: src/pint/observatory/satellite_obs.py
(get_satellite_observatory, SatelliteObs over FT2/orbit FITS) and
special_locations.py (T2SpacecraftObs). The orbit FITS carries the
spacecraft position versus mission time; photon TOAs then use the
interpolated position as their "observatory" so the barycentering
chain (Roemer/parallax/Shapiro) works exactly as for ground sites.

Conventions handled:
- position columns POS_X/POS_Y/POS_Z (NICER/RXTE/Swift/NuSTAR MKF,
  meters or km) or SC_POSITION (Fermi FT2, meters, (N,3) vector col);
- TIME in mission seconds from the header MJDREF, assumed TT;
- positions are J2000/GCRS-aligned Earth-centered inertial (the
  mission standard), so no Earth-rotation transform is applied.

T2SpacecraftObs instead takes the position per TOA from -telx/-tely/
-telz flags (light-seconds, tempo2 convention).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.observatory import Observatory, register_observatory

__all__ = ["SatelliteObs", "get_satellite_observatory",
           "T2SpacecraftObs"]

C_M_S = 299792458.0


class SatelliteObs(Observatory):
    """Orbiting observatory with positions interpolated from an orbit
    FITS file (reference: satellite_obs.SatelliteObs)."""

    timescale = "utc"  # photon TIME columns are TT; TOA loaders handle

    def __init__(self, name, orbit_file, aliases=()):
        super().__init__(name, aliases)
        from pint_tpu.io.fits import read_fits

        hdus = read_fits(orbit_file)
        tables = [h for h in hdus if h.data]
        if not tables:
            raise ValueError(f"no binary table in orbit file "
                             f"{orbit_file!r}")
        tab = next((h for h in tables
                    if h.name.upper() in ("SC_DATA", "ORBIT", "PREFILTER")),
                   tables[0])
        cols = {k.upper(): v for k, v in tab.data.items()}
        hdr = tab.header
        mjdrefi = float(hdr.get("MJDREFI", 0.0))
        mjdreff = float(hdr.get("MJDREFF", 0.0))
        if mjdrefi == 0.0 and "MJDREF" in hdr:
            v = float(hdr["MJDREF"])
            mjdrefi, mjdreff = np.floor(v), v - np.floor(v)
        t = np.asarray(cols["TIME"], dtype=np.float64)
        if "SC_POSITION" in cols:  # Fermi FT2: (N,3) meters
            pos = np.asarray(cols["SC_POSITION"], dtype=np.float64)
        else:
            try:
                pos = np.stack([np.asarray(cols[f"POS_{ax}"],
                                           dtype=np.float64)
                                for ax in "XYZ"], axis=-1)
            except KeyError:
                raise ValueError(
                    "orbit file needs SC_POSITION or POS_X/Y/Z "
                    f"columns; found {sorted(cols)}")
        # km-vs-m heuristic: LEO radius is ~6.8e6 m / ~6.8e3 km
        if np.median(np.linalg.norm(pos, axis=-1)) < 1e5:
            pos = pos * 1e3
        order = np.argsort(t)
        self._t_mjd = mjdrefi + (t[order] / 86400.0 + mjdreff)
        self._pos_m = pos[order]
        self.mjdref = (mjdrefi, mjdreff)

    def gcrs_posvel(self, utc_mjd, tt_mjd):
        """Interpolated ECI position [m] and finite-difference velocity
        [m/s] at the given epochs (orbit files are sampled at ~1-30 s:
        linear interpolation is ~m-accurate for LEO)."""
        tq = np.atleast_1d(np.asarray(tt_mjd, np.float64))
        if tq.min() < self._t_mjd[0] - 1e-6 or \
                tq.max() > self._t_mjd[-1] + 1e-6:
            raise ValueError(
                f"epochs [{tq.min():.6f}, {tq.max():.6f}] outside the "
                f"orbit file span [{self._t_mjd[0]:.6f}, "
                f"{self._t_mjd[-1]:.6f}]")
        pos = np.stack([np.interp(tq, self._t_mjd, self._pos_m[:, k])
                        for k in range(3)], axis=-1)
        dt = 1.0 / 86400.0  # 1 s
        # clamp the stencil inside the table (np.interp would silently
        # hold the endpoint value, halving the velocity near the edges)
        # and divide by the time actually spanned
        tp = np.minimum(tq + dt, self._t_mjd[-1])
        tm = np.maximum(tq - dt, self._t_mjd[0])
        pos_p = np.stack([np.interp(tp, self._t_mjd,
                                    self._pos_m[:, k])
                          for k in range(3)], axis=-1)
        pos_m_ = np.stack([np.interp(tm, self._t_mjd,
                                     self._pos_m[:, k])
                           for k in range(3)], axis=-1)
        span_s = (tp - tm) * 86400.0
        vel = (pos_p - pos_m_) / span_s[:, None]
        return pos, vel


def get_satellite_observatory(name, orbit_file, overwrite=True
                              ) -> SatelliteObs:
    """Load an orbit file and register the mission as an observatory
    (reference: satellite_obs.get_satellite_observatory)."""
    obs = SatelliteObs(name.lower(), orbit_file)
    register_observatory(obs, overwrite=overwrite)
    return obs


class T2SpacecraftObs(Observatory):
    """Spacecraft positions supplied per TOA via -telx/-tely/-telz
    flags in light-seconds (tempo2 convention; reference:
    special_locations.T2SpacecraftObs). The TOA pipeline calls
    posvel_from_flags with the TOA flag dicts."""

    def __init__(self):
        super().__init__("stl_geo", aliases=("spacecraft", "stl"))

    def posvel_from_flags(self, flags):
        """((N,3) positions [m], (N,3) velocities [m/s]) from per-TOA
        flags: -telx/-tely/-telz [lt-s] mandatory, -telvx/-telvy/-telvz
        [lt-s/s] optional (zero velocity without them — the barycentric
        Doppler frequency then omits the spacecraft motion)."""
        pos = np.zeros((len(flags), 3))
        vel = np.zeros((len(flags), 3))
        for i, f in enumerate(flags):
            try:
                pos[i] = [float(f["telx"]) * C_M_S,
                          float(f["tely"]) * C_M_S,
                          float(f["telz"]) * C_M_S]
            except KeyError as e:
                raise ValueError(
                    f"TOA {i} at spacecraft site lacks -{e.args[0]} "
                    "flag") from e
            if "telvx" in f:
                vel[i] = [float(f["telvx"]) * C_M_S,
                          float(f.get("telvy", 0.0)) * C_M_S,
                          float(f.get("telvz", 0.0)) * C_M_S]
        return pos, vel
