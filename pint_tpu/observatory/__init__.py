"""Observatory registry: name/alias → Observatory singleton.

Reference: src/pint/observatory/__init__.py (Observatory,
get_observatory), topo_obs.py (TopoObs), special_locations.py
(BarycenterObs, GeocenterObs). Ground stations carry ITRF coordinates
and a clock chain; special locations override positions/timescale.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.observatory.clock import find_clock_file
from pint_tpu.observatory.sites import load_sites
from pint_tpu.time import frames


class Observatory:
    """Base observatory. Subclasses define how to get the observatory
    position/velocity wrt the geocenter in GCRS, the clock chain, and the
    native timescale of TOAs recorded there."""

    timescale = "utc"

    def __init__(self, name, aliases=()):
        self.name = name
        self.aliases = tuple(aliases)

    def clock_corrections(self, utc_mjd, include_gps=True, include_bipm=True,
                          bipm_version="BIPM2021", limits="warn"):
        """Total clock correction [seconds] to add to raw TOA MJDs."""
        return np.zeros_like(np.asarray(utc_mjd, np.float64))

    def gcrs_posvel(self, utc_mjd, tt_mjd):
        """Observatory position [m] / velocity [m/s] wrt geocenter, GCRS."""
        z = np.zeros((np.atleast_1d(utc_mjd).shape[0], 3))
        return z, z.copy()

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


class TopoObs(Observatory):
    """Ground station with ITRF coordinates (reference: TopoObs)."""

    def __init__(self, name, itrf_xyz_m, aliases=(), tempo_code=None,
                 clock_file=None, clock_fmt="tempo2"):
        super().__init__(name, aliases)
        self.itrf_xyz_m = np.asarray(itrf_xyz_m, np.float64)
        self.tempo_code = tempo_code
        self._clock_file_name = clock_file or f"{name}2gps.clk"
        self._clock_fmt = clock_fmt
        self._clock = None

    def _get_clock(self):
        if self._clock is None:
            self._clock = find_clock_file(self._clock_file_name,
                                          fmt=self._clock_fmt)
        return self._clock

    def clock_corrections(self, utc_mjd, include_gps=True, include_bipm=True,
                          bipm_version="BIPM2021", limits="warn"):
        """site→GPS (per-site file) + GPS→UTC + optional UTC(TAI)→TT(BIPM)
        minus TT(TAI); all files zero-fallback offline."""
        utc_mjd = np.asarray(utc_mjd, np.float64)
        corr = self._get_clock().evaluate(utc_mjd, limits=limits)
        if include_gps:
            corr = corr + find_clock_file("gps2utc.clk").evaluate(
                utc_mjd, limits=limits)
        if include_bipm:
            fname = f"tai2tt_{bipm_version.lower()}.clk"
            corr = corr + find_clock_file(fname).evaluate(utc_mjd,
                                                          limits=limits)
        return corr

    def gcrs_posvel(self, utc_mjd, tt_mjd):
        return frames.itrf_to_gcrs_posvel(self.itrf_xyz_m, utc_mjd, tt_mjd)


class BarycenterObs(Observatory):
    """TOAs already at the SSB, in TDB (tempo2 'bat' style;
    reference: special_locations.py BarycenterObs)."""

    timescale = "tdb"

    def __init__(self):
        super().__init__("barycenter", aliases=("@", "ssb", "bat"))


class GeocenterObs(Observatory):
    """TOAs at the geocenter, UTC (reference: GeocenterObs)."""

    def __init__(self):
        super().__init__("geocenter", aliases=("0", "geo", "coe"))


_registry: "dict[str, Observatory]" = {}
_alias_map: "dict[str, str]" = {}
_builtins_loaded = False


def register_observatory(obs: Observatory, overwrite=False):
    key = obs.name.lower()
    if key in _registry and not overwrite:
        raise ValueError(f"observatory {obs.name!r} already registered")
    _registry[key] = obs
    _alias_map[key] = key
    for a in obs.aliases:
        _alias_map[a.lower()] = key
    if getattr(obs, "tempo_code", None):
        _alias_map[obs.tempo_code.lower()] = key


def _ensure_builtins():
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    for name, entry in load_sites().items():
        if name.lower() in _registry:  # user pre-registered an override
            continue
        register_observatory(
            TopoObs(name, entry["itrf"], aliases=entry.get("aliases", ()),
                    tempo_code=entry.get("tempo_code")))
    register_observatory(BarycenterObs())
    register_observatory(GeocenterObs())
    from pint_tpu.observatory.satellite_obs import T2SpacecraftObs

    register_observatory(T2SpacecraftObs())


def get_observatory(name: str) -> Observatory:
    """Resolve an observatory by canonical name, alias, or tempo code
    (case-insensitive) — reference: get_observatory()."""
    _ensure_builtins()
    key = _alias_map.get(str(name).lower())
    if key is None:
        raise KeyError(
            f"unknown observatory {name!r}; known: "
            f"{sorted(_registry)} (+aliases)")
    return _registry[key]


def list_observatories():
    _ensure_builtins()
    return sorted(_registry)
