"""Global clock-correction repository machinery.

Reference: src/pint/observatory/global_clock_corrections.py — there,
an Index file is downloaded from the IPTA pulsar-clock-corrections
repository, each clock file carries an update-interval policy, and
astropy's download cache stores copies. This build runs with ZERO
egress, so the TPU-native equivalent is mirror-based: point
$PINT_TPU_CLOCK_DIR (or ``set_clock_mirror``) at a local clone of
https://ipta.github.io/pulsar-clock-corrections/ and the same Index
semantics apply — per-file validity windows, staleness warnings, and
an ``update_clock_files`` that verifies mirror freshness instead of
fetching. Everything degrades loudly, never silently.
"""

from __future__ import annotations

import os
import time
import warnings
from dataclasses import dataclass
from typing import Dict, List, Optional

__all__ = ["Index", "IndexEntry", "get_index",
           "get_clock_correction_file", "update_clock_files",
           "set_clock_mirror", "clock_mirror"]

_MIRROR: Optional[str] = None
_INDEX_CACHE: Dict[str, "Index"] = {}

#: default maximum mirror age before update_clock_files warns [days]
DEFAULT_UPDATE_INTERVAL_DAYS = 64.0


def set_clock_mirror(path: Optional[str]):
    """Point the registry at a local pulsar-clock-corrections clone
    (overrides $PINT_TPU_CLOCK_DIR for the index machinery)."""
    global _MIRROR
    _MIRROR = path
    _INDEX_CACHE.clear()
    # forget per-name miss memos AND warn-once sentinels so a
    # re-pointed mirror is re-consulted for previously-missing files
    # and a broken replacement mirror still warns loudly
    from pint_tpu.observatory import clock as _clock

    _clock._refresh_missed.clear()
    _clock._warned_missing.clear()


def get_index(mirror: Optional[str] = None,
              refresh: bool = False) -> "Index":
    """Cached Index for the configured mirror (one tree walk per
    mirror per session, not per lookup); ``refresh`` forces a re-walk
    (e.g. after dropping a new file into the mirror)."""
    m = mirror or clock_mirror()
    if m is None:
        raise FileNotFoundError(
            "no clock mirror configured: set $PINT_TPU_CLOCK_DIR or "
            "call set_clock_mirror()")
    if refresh or m not in _INDEX_CACHE:
        _INDEX_CACHE[m] = Index(m)
    return _INDEX_CACHE[m]


def clock_mirror() -> Optional[str]:
    from pint_tpu import config

    d = config.clock_dir()
    return _MIRROR or (str(d) if d is not None else None)


@dataclass
class IndexEntry:
    """One row of the repository index (reference: Index entries):
    file name, advertised update interval, and last-modification
    metadata from the mirror filesystem."""

    name: str
    path: str
    update_interval_days: float
    mtime: float

    @property
    def age_days(self) -> float:
        return (time.time() - self.mtime) / 86400.0

    @property
    def stale(self) -> bool:
        iv = self.update_interval_days
        return iv > 0 and self.age_days > iv


class Index:
    """Enumerate the clock files available in the local mirror
    (reference: global_clock_corrections.Index, minus the download).

    An ``index.txt`` in the mirror root — lines of
    ``<relative path> <update interval days>`` — is honored when
    present; otherwise every ``*.clk``/``time*.dat`` under the mirror
    is indexed with the default update interval."""

    def __init__(self, mirror: Optional[str] = None):
        mirror = mirror or clock_mirror()
        if mirror is None:
            raise FileNotFoundError(
                "no clock mirror configured: set $PINT_TPU_CLOCK_DIR "
                "or call set_clock_mirror() with a local clone of the "
                "pulsar-clock-corrections repository (this build has "
                "no network access, so nothing can be downloaded)")
        if not os.path.isdir(mirror):
            raise FileNotFoundError(
                f"clock mirror {mirror!r} is not a directory")
        self.mirror = mirror
        self.files: Dict[str, IndexEntry] = {}
        index_txt = os.path.join(mirror, "index.txt")
        if os.path.exists(index_txt):
            with open(index_txt) as fh:
                for line in fh:
                    line = line.split("#", 1)[0].strip()
                    if not line:
                        continue
                    toks = line.split()
                    rel = toks[0]
                    iv = float(toks[1]) if len(toks) > 1 else \
                        DEFAULT_UPDATE_INTERVAL_DAYS
                    full = os.path.join(mirror, rel)
                    if os.path.exists(full):
                        self._add(rel, full, iv)
                    else:
                        warnings.warn(
                            f"index.txt lists {rel!r} but the mirror "
                            "lacks it")
        else:
            for root, _, names in os.walk(mirror):
                for nm in sorted(names):
                    if nm.endswith(".clk") or (
                            nm.startswith("time") and
                            nm.endswith(".dat")):
                        full = os.path.join(root, nm)
                        rel = os.path.relpath(full, mirror)
                        self._add(rel, full,
                                  DEFAULT_UPDATE_INTERVAL_DAYS)

    def _add(self, rel: str, full: str, iv: float):
        base = os.path.basename(rel)
        prev = self.files.get(base)
        if prev is not None and \
                os.path.abspath(prev.path) != os.path.abspath(full):
            warnings.warn(
                f"clock mirror has two files named {base!r} "
                f"({prev.path} and {full}); keeping the first — "
                "remove the duplicate or use an index.txt")
            return
        self.files[base] = IndexEntry(
            name=base, path=full, update_interval_days=iv,
            mtime=os.path.getmtime(full))

    def __contains__(self, name: str) -> bool:
        return os.path.basename(name) in self.files

    def __getitem__(self, name: str) -> IndexEntry:
        return self.files[os.path.basename(name)]


def get_clock_correction_file(name: str, limits: str = "warn",
                              index: Optional[Index] = None) -> str:
    """Path of ``name`` in the mirror (reference:
    get_clock_correction_file, download replaced by mirror lookup).
    Stale files warn (or raise with limits='error')."""
    idx = index or get_index()
    if name not in idx:
        raise FileNotFoundError(
            f"clock file {name!r} not in the mirror at "
            f"{idx.mirror!r} ({len(idx.files)} files indexed)")
    entry = idx[name]
    if entry.stale:
        msg = (f"clock file {name!r} is {entry.age_days:.0f} days old "
               f"(update interval {entry.update_interval_days:.0f} d);"
               " refresh the mirror clone")
        if limits == "error":
            raise RuntimeError(msg)
        warnings.warn(msg)
    return entry.path


def update_clock_files(names: Optional[List[str]] = None,
                       limits: str = "warn",
                       index: Optional[Index] = None) -> Dict[str, bool]:
    """Freshness report for every (or the named) mirror clock file
    (reference: update_clock_files — with zero egress this verifies
    instead of fetching). Returns {name: is_fresh}; stale entries warn
    or raise per ``limits``."""
    idx = index or get_index()
    wanted = names if names is not None else sorted(idx.files)
    out: Dict[str, bool] = {}
    stale = []
    for nm in wanted:
        if nm not in idx:
            raise FileNotFoundError(
                f"clock file {nm!r} not in the mirror")
        e = idx[nm]
        out[nm] = not e.stale
        if e.stale:
            stale.append(f"{nm} ({e.age_days:.0f} d old)")
    if stale:
        msg = ("stale clock files (no network in this build — refresh "
               f"the mirror clone): {', '.join(stale)}")
        if limits == "error":
            raise RuntimeError(msg)
        warnings.warn(msg)
    return out
