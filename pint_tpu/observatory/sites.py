"""Embedded ground-station table: ITRF coordinates + aliases.

Replaces the reference's ``src/pint/data/runtime/observatories.json``
(loaded by src/pint/observatory/topo_obs.py TopoObs). Coordinates are
meter-level (1 m ~ 3.3 ns) — adequate for self-simulated fixtures; for
real-data work users can override via $PINT_TPU_OBS_OVERRIDE pointing at
a JSON file of the same shape.

Each entry: canonical name → dict(itrf=[x,y,z] meters, aliases=[...],
tempo_code=single-char or None).
"""

from __future__ import annotations

import json
import os

SITES = {
    "gbt": {
        "itrf": [882589.65, -4924872.32, 3943729.35],
        "aliases": ["gb", "green_bank"],
        "tempo_code": "1",
    },
    "arecibo": {
        "itrf": [2390490.0, -5564764.0, 1994727.0],
        "aliases": ["ao", "aoutc"],
        "tempo_code": "3",
    },
    "parkes": {
        "itrf": [-4554231.5, 2816759.1, -3454036.3],
        "aliases": ["pks", "atnf"],
        "tempo_code": "7",
    },
    "jodrell": {
        "itrf": [3822626.04, -154105.65, 5086486.04],
        "aliases": ["jb", "jbo", "jboafb", "jbodfb", "jbroach"],
        "tempo_code": "8",
    },
    "vla": {
        "itrf": [-1601192.0, -5041981.4, 3554871.4],
        "aliases": ["jvla"],
        "tempo_code": "6",
    },
    "effelsberg": {
        "itrf": [4033949.5, 486989.4, 4900430.8],
        "aliases": ["eff", "eb"],
        "tempo_code": "g",
    },
    "nancay": {
        "itrf": [4324165.8, 165927.1, 4670132.8],
        "aliases": ["ncy", "nuppi"],
        "tempo_code": "f",
    },
    "wsrt": {
        "itrf": [3828445.7, 445223.9, 5064921.6],
        "aliases": ["we"],
        "tempo_code": "i",
    },
    "chime": {
        "itrf": [-2059166.3, -3621302.97, 4814304.11],
        "aliases": ["chime_telescope"],
        "tempo_code": "y",
    },
    "meerkat": {
        "itrf": [5109360.1, 2006852.6, -3238948.1],
        "aliases": ["mk"],
        "tempo_code": "m",
    },
    "fast": {
        "itrf": [-1668557.2, 5506838.5, 2744934.6],
        "aliases": [],
        "tempo_code": "k",
    },
    "gmrt": {
        "itrf": [1656342.3, 5797947.8, 2073243.2],
        "aliases": [],
        "tempo_code": "r",
    },
    "lofar": {
        "itrf": [3826577.5, 461022.9, 5064892.7],
        "aliases": ["lf"],
        "tempo_code": "t",
    },
    "srt": {
        "itrf": [4865182.8, 791922.4, 4035137.2],
        "aliases": ["sardinia"],
        "tempo_code": "z",
    },
    "hobart": {
        "itrf": [-3950077.9, 2522377.7, -4311667.4],
        "aliases": ["hb"],
        "tempo_code": "4",
    },
    "mwa": {
        "itrf": [-2559454.1, 5095372.1, -2849057.2],
        "aliases": [],
        "tempo_code": "u",
    },
}


def load_sites() -> dict:
    """The site table, honoring $PINT_TPU_OBS_OVERRIDE (a JSON file of the
    same structure, merged over the built-ins)."""
    from pint_tpu import config

    sites = {k: dict(v) for k, v in SITES.items()}
    override = config.obs_override()
    if override is not None and override.exists():
        with open(override) as f:
            for name, entry in json.load(f).items():
                sites[name.lower()] = entry
    return sites
