"""Clock-correction files: TEMPO and TEMPO2 formats, MJD interpolation.

Reference: src/pint/observatory/clock_file.py (ClockFile). This offline
build ships no correction data (the reference downloads the IPTA
pulsar-clock-corrections repo at runtime — impossible here, zero egress);
the default chain is therefore zero-correction with a single loud
warning, but the parser/evaluator machinery is complete so real files
drop in via $PINT_TPU_CLOCK_DIR.

Formats:
- TEMPO2 ``*.clk``: header line ``# <from> <to> [badness]``, then rows
  ``mjd offset_s [flags]``.
- TEMPO ``time*.dat``: rows ``mjd offset_us ...``; lines starting with
  a comment char ignored; an ``@``/``&`` in column 0 marks epoch resets
  (treated as plain rows here).
"""

from __future__ import annotations

import os
import warnings

import numpy as np


class ClockFile:
    """MJD → clock offset (seconds), linear interpolation, with
    out-of-range policy matching the reference: warn and hold the last
    value past the end of the table."""

    def __init__(self, mjd, offset_s, name="clock", valid_beyond_end=False):
        self.mjd = np.asarray(mjd, np.float64)
        self.offset_s = np.asarray(offset_s, np.float64)
        self.name = name
        self.valid_beyond_end = valid_beyond_end
        if self.mjd.size and np.any(np.diff(self.mjd) < 0):
            order = np.argsort(self.mjd)
            self.mjd = self.mjd[order]
            self.offset_s = self.offset_s[order]

    @classmethod
    def read_tempo2(cls, path):
        mjds, offs = [], []
        name = os.path.basename(path)
        with open(path) as f:
            for line in f:
                s = line.strip()
                if not s or s.startswith("#"):
                    continue
                parts = s.split()
                if len(parts) < 2:
                    continue
                try:
                    mjds.append(float(parts[0]))
                    offs.append(float(parts[1]))
                except ValueError:
                    continue
        return cls(mjds, offs, name=name)

    @classmethod
    def read_tempo(cls, path):
        """TEMPO time*.dat: offsets are in microseconds."""
        mjds, offs = [], []
        name = os.path.basename(path)
        with open(path) as f:
            for line in f:
                if line.startswith(("#", "*", "C ")):
                    continue
                s = line.strip().lstrip("@&").strip()
                parts = s.split()
                if len(parts) < 2:
                    continue
                try:
                    mjds.append(float(parts[0]))
                    offs.append(float(parts[1]) * 1e-6)
                except ValueError:
                    continue
        return cls(mjds, offs, name=name)

    @classmethod
    def read(cls, path, fmt=None):
        if fmt is None:
            fmt = "tempo2" if path.endswith(".clk") else "tempo"
        return cls.read_tempo2(path) if fmt == "tempo2" \
            else cls.read_tempo(path)

    def evaluate(self, mjd, limits="warn"):
        mjd = np.asarray(mjd, np.float64)
        if self.mjd.size == 0:
            return np.zeros_like(mjd)
        lo, hi = self.mjd[0], self.mjd[-1]
        out_of_range = (mjd < lo) | (mjd > hi)
        if np.any(out_of_range) and not self.valid_beyond_end:
            msg = (f"clock file {self.name}: {int(out_of_range.sum())} "
                   f"MJD(s) outside [{lo:.1f}, {hi:.1f}]; holding edge value")
            if limits == "error":
                raise ValueError(msg)
            warnings.warn(msg, stacklevel=2)
        return np.interp(mjd, self.mjd, self.offset_s)


class ZeroClockFile(ClockFile):
    """The zero-correction fallback used when no clock data is on disk."""

    def __init__(self, name="zero"):
        super().__init__([], [], name=name, valid_beyond_end=True)

    def evaluate(self, mjd, limits="warn"):
        return np.zeros_like(np.asarray(mjd, np.float64))


_warned_missing = set()
_refresh_missed = set()  # names already refresh-walked and not found
_clock_cache: dict = {}


def find_clock_file(name, fmt="tempo2"):
    """Locate `name` in the clock mirror (flat file under
    $PINT_TPU_CLOCK_DIR, or anywhere inside a nested
    pulsar-clock-corrections clone via the global-corrections Index);
    zero-fallback otherwise, warning once per file name (mirrors the
    reference's missing-clock warning policy in
    src/pint/observatory/topo_obs.py). Parsed files are cached per
    (path, fmt)."""
    from pint_tpu.observatory.global_clock_corrections import (
        clock_mirror, get_index)

    clock_dir = clock_mirror()
    if clock_dir:
        cand = os.path.join(clock_dir, name)
        if not os.path.exists(cand):
            # nested mirror layout (T2runtime/clock/...): consult the
            # repository index; on a miss, refresh ONCE PER NAME in
            # case the file landed after the cached walk (a hot
            # ingestion loop must not re-walk the mirror per lookup).
            # A broken mirror degrades to the zero fallback below —
            # loudly, once — never crashing ingestion
            try:
                idx = get_index()
                if name not in idx and name not in _refresh_missed:
                    _refresh_missed.add(name)
                    idx = get_index(refresh=True)
                if name in idx:
                    cand = idx[name].path
            except FileNotFoundError:
                pass
            except Exception as e:
                if "mirror-index" not in _warned_missing:
                    _warned_missing.add("mirror-index")
                    warnings.warn(
                        f"clock mirror index unusable ({e}); "
                        "falling back", stacklevel=2)
        if os.path.exists(cand):
            key = (os.path.abspath(cand), fmt)
            if key not in _clock_cache:
                _clock_cache[key] = ClockFile.read(cand, fmt=fmt)
            return _clock_cache[key]
    if name not in _warned_missing:
        _warned_missing.add(name)
        warnings.warn(
            f"no clock file {name!r} available (offline build); using "
            "zero corrections — timing vs real observatory data will be "
            "off by the site clock offset (~us). Set $PINT_TPU_CLOCK_DIR "
            "to a directory of .clk files for real-data work.",
            stacklevel=2,
        )
    return ZeroClockFile(name=name)
