"""Assign phases to Fermi-LAT FT1 photons with weights (reference:
src/pint/scripts/fermiphase.py — photonphase specialized to Fermi
with the gtsrcprob/MODEL_WEIGHT column)."""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None) -> int:
    from pint_tpu.scripts import photonphase

    argv = list(sys.argv[1:] if argv is None else argv)

    def has_opt(name):  # matches both '--opt value' and '--opt=value'
        return any(a == name or a.startswith(name + "=") for a in argv)

    if not has_opt("--weightcol"):
        argv += ["--weightcol", "MODEL_WEIGHT"]
    if not has_opt("--mission"):
        argv += ["--mission", "fermi"]
    return photonphase.main(argv)


if __name__ == "__main__":
    sys.exit(main())
