"""Assign model pulse phases to photon events and test for pulsations
(reference: src/pint/scripts/photonphase.py).

Reads a (barycentered) FITS event file, evaluates the timing model's
absolute phase at every photon, reports H-test significance, and can
write the phases back as a PULSE_PHASE column in a new FITS file, plus
an optional npz dump.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

__all__ = ["main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="photonphase",
        description="Assign pulse phases to FITS photon events")
    p.add_argument("eventfile", help="barycentered event FITS file")
    p.add_argument("parfile", help="timing model .par file")
    p.add_argument("--mission", default=None,
                   help="mission name for MJDREF fallback "
                        "(fermi/nicer/rxte/nustar/swift/xmm)")
    p.add_argument("--weightcol", default=None,
                   help="photon-weight column name (e.g. Fermi "
                        "MODEL_WEIGHT)")
    p.add_argument("--orbfile", default=None,
                   help="spacecraft orbit FITS (required for "
                        "un-barycentered TT event files)")
    p.add_argument("--minmjd", type=float, default=-np.inf)
    p.add_argument("--maxmjd", type=float, default=np.inf)
    p.add_argument("--outfile", default=None,
                   help="write a FITS copy with a PULSE_PHASE column")
    p.add_argument("--npz", default=None,
                   help="write phases (+weights) to this .npz")
    p.add_argument("--plotfile", default=None,
                   help="write a phaseogram png here")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.event_toas import get_event_weights, load_fits_TOAs
    from pint_tpu.eventstats import h_sig, hmw
    from pint_tpu.io.fits import read_events_fits, write_events_fits
    from pint_tpu.models import get_model

    model = get_model(args.parfile)
    toas = load_fits_TOAs(args.eventfile, mission=args.mission,
                          weightcolumn=args.weightcol,
                          minmjd=args.minmjd, maxmjd=args.maxmjd,
                          ephem=model.EPHEM.value,
                          planets=bool(model.PLANET_SHAPIRO.value),
                          orbit_file=args.orbfile)
    print(f"Read {toas.ntoas} photons from {args.eventfile}")

    phase = model.phase(toas)
    phases = np.mod(np.asarray(phase.frac), 1.0)
    weights = get_event_weights(toas)

    h = hmw(phases, weights)
    sig = h_sig(h)
    wtxt = " (weighted)" if weights is not None else ""
    print(f"Htest{wtxt}: {h:.2f}  ({sig:.2f} sigma)")

    if args.plotfile:
        from pint_tpu.plot_utils import phaseogram

        phaseogram(np.asarray(toas.get_mjds()), phases,
                   weights=weights,
                   title=f"{model.name or ''} H={h:.1f}",
                   plotfile=args.plotfile)
        print(f"Wrote {args.plotfile}")
    if args.npz:
        np.savez(args.npz, phases=phases,
                 weights=(weights if weights is not None
                          else np.ones_like(phases)))
        print(f"Wrote {args.npz}")
    if args.outfile:
        cols, header = read_events_fits(args.eventfile)
        cols["PULSE_PHASE"] = phases.astype(np.float64)
        keep = {k: v for k, v in header.items()
                if k in ("TIMESYS", "TIMEREF", "TELESCOP", "INSTRUME",
                         "MJDREFI", "MJDREFF", "TIMEZERO", "TIMEUNIT")}
        write_events_fits(args.outfile, cols, header_extra=keep)
        print(f"Wrote {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
