"""Quick barycentering of arbitrary times (reference:
src/pint/scripts/pintbary.py): UTC MJDs at a site -> barycentric TDB
MJDs for a given sky position (or par file)."""

from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pintbary", description="Barycenter times")
    p.add_argument("mjds", nargs="+", type=float, help="UTC MJD(s)")
    p.add_argument("--obs", default="gbt")
    p.add_argument("--freq", type=float, default=float("inf"),
                   help="MHz (dispersion removed if par has DM)")
    p.add_argument("--parfile", default=None)
    p.add_argument("--ra", default=None, help="hh:mm:ss.s")
    p.add_argument("--dec", default=None, help="dd:mm:ss.s")
    p.add_argument("--ephem", default=None)
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    import io
    import warnings

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.toa import get_TOAs_array

    if args.parfile:
        model = get_model(args.parfile)
    elif args.ra and args.dec:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model = get_model(io.StringIO(
                f"PSR BARY\nRAJ {args.ra}\nDECJ {args.dec}\n"
                f"F0 1.0\nPEPOCH 55000\nUNITS TDB\n"))
    else:
        p.error("give --parfile or --ra/--dec")

    # barycentering stops at solar-system delays: strip any binary
    # component (the reference pintbary likewise never removes the
    # orbital delay)
    binaries = [nm for nm in model.components
                if nm.startswith("Binary")]
    if binaries:
        import copy

        model = copy.deepcopy(model)
        for nm in binaries:
            model.remove_component(nm)
        model.invalidate_cache()

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        toas = get_TOAs_array(np.asarray(args.mjds, dtype=np.float64),
                              obs=args.obs, freqs=args.freq,
                              errors=1.0,
                              ephem=(args.ephem or model.EPHEM.value))
    delay = np.asarray(model.delay(toas))
    tdb = toas.tdb_day + toas.tdb_frac[0] + toas.tdb_frac[1]
    bat = tdb - delay / 86400.0
    for m_in, m_out in zip(args.mjds, bat):
        print(f"{m_in:.10f} -> {m_out:.13f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
