"""MCMC-optimize a timing model against photon phases with a template
likelihood (reference: src/pint/scripts/event_optimize.py; emcee pool
replaced by the in-repo batched ensemble sampler)."""

from __future__ import annotations

import argparse
import sys

import numpy as np

__all__ = ["main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="event_optimize",
        description="MCMC timing-model optimization on photon events")
    p.add_argument("eventfile", help="barycentered event FITS")
    p.add_argument("parfile")
    p.add_argument("--mission", default=None)
    p.add_argument("--weightcol", default=None)
    p.add_argument("--ncomp", type=int, default=1,
                   help="Gaussian components in the seed template")
    p.add_argument("--template", default=None,
                   help="profile template file (see "
                        "pint_tpu.templates.read_template); skips the "
                        "automatic template seeding")
    p.add_argument("--nwalkers", type=int, default=32)
    p.add_argument("--nsteps", type=int, default=200)
    p.add_argument("--burn", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--outfile", default=None,
                   help="write the optimized par file here")
    p.add_argument("--chains-npz", default=None,
                   help="dump the full walker chain + lnprob here")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.event_toas import get_event_weights, load_fits_TOAs
    from pint_tpu.eventstats import h_sig, hmw
    from pint_tpu.mcmc_fitter import PhotonMCMCFitter
    from pint_tpu.models import get_model
    from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate

    model = get_model(args.parfile)
    toas = load_fits_TOAs(args.eventfile, mission=args.mission,
                          weightcolumn=args.weightcol,
                          ephem=model.EPHEM.value,
                          planets=bool(model.PLANET_SHAPIRO.value))
    weights = get_event_weights(toas)
    phases = np.mod(np.asarray(model.phase(toas).frac), 1.0)
    h0 = hmw(phases, weights)
    print(f"Read {toas.ntoas} photons; initial Htest {h0:.1f} "
          f"({h_sig(h0):.1f} sigma)")

    if args.template:
        from pint_tpu.templates import read_template

        template = read_template(args.template)
        print(f"Read template from {args.template}:\n{template}")
    else:
        # seed template by ML on the initial phases; the peak location
        # comes from the first Fourier harmonic (a far-off location
        # seed collapses the ML fit into the uniform-background local
        # minimum)
        w = weights if weights is not None else np.ones_like(phases)
        c1 = np.sum(w * np.exp(2j * np.pi * phases))
        loc0 = float(np.angle(c1) / (2 * np.pi)) % 1.0
        pulsed_frac = min(0.9, max(0.1,
                                   2.0 * np.abs(c1) / np.sum(w)))
        ncomp = max(1, args.ncomp)
        prims = [LCGaussian() for _ in range(ncomp)]
        locs = [(loc0 + k / ncomp) % 1.0 for k in range(ncomp)]
        template = LCTemplate(prims,
                              norms=[pulsed_frac / ncomp] * ncomp,
                              locs=locs, widths=[0.05] * ncomp)
        tfit = LCFitter(template, phases, weights=weights)
        res = tfit.fit()
        print(f"Template ML: logL={res['loglikelihood']:.1f} "
              f"locs={np.round(template.locs, 4)} "
              f"norms={np.round(template.norms, 3)}")
        if template.norms.sum() < 0.05:
            print("WARNING: template collapsed to background — phases "
                  "may be unpulsed or the seed failed; aborting "
                  "before MCMC")
            return 1

    rng = np.random.default_rng(args.seed)
    fitter = PhotonMCMCFitter(toas, model, template, weights=weights,
                              nwalkers=args.nwalkers, rng=rng)
    lnmax = fitter.fit_toas(nsteps=args.nsteps, burn=args.burn)
    print(f"MCMC done: acc="
          f"{fitter.sampler.acceptance_fraction:.2f} "
          f"max lnL={lnmax:.1f}")
    tau = fitter.sampler.get_autocorr_time()
    conv = fitter.sampler.converged(tau=tau)
    print(f"autocorr time (steps): max {np.nanmax(tau):.1f}; "
          f"chain {'converged' if conv else 'SHORT'}"
          f" by the nsteps > 50*tau rule")
    if args.chains_npz:
        np.savez(args.chains_npz,
                 chain=fitter.sampler.chain,
                 lnprob=fitter.sampler.lnprob,
                 labels=np.array(fitter.param_labels),
                 tau=tau)
        print(f"Wrote {args.chains_npz}")
    phases2 = np.mod(np.asarray(model.phase(toas).frac), 1.0)
    h1 = hmw(phases2, weights)
    print(f"Final Htest {h1:.1f} ({h_sig(h1):.1f} sigma)")
    for name in fitter.param_labels:
        par = model.get_param(name)
        print(f"  {name} = {par.value} +- {par.uncertainty:.3g}")
    if args.outfile:
        with open(args.outfile, "w") as fh:
            fh.write(model.as_parfile())
        print(f"Wrote {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
