"""Simulate fake TOAs to a tim file (reference:
src/pint/scripts/zima.py)."""

from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="zima", description="Simulate TOAs from a timing model")
    p.add_argument("parfile")
    p.add_argument("timfile", help="output tim file")
    p.add_argument("--ntoa", type=int, default=100)
    p.add_argument("--startMJD", type=float, default=56000.0)
    p.add_argument("--duration", type=float, default=400.0,
                   help="days")
    p.add_argument("--error", type=float, default=1.0,
                   help="TOA uncertainty [us]")
    p.add_argument("--obs", default="gbt")
    p.add_argument("--freq", type=float, default=1400.0)
    p.add_argument("--addnoise", action="store_true",
                   help="add a white-noise draw at the TOA errors")
    p.add_argument("--addcorrnoise", action="store_true",
                   help="also draw the model's correlated noise")
    p.add_argument("--inputtim", default=None,
                   help="take MJDs/freqs/errors from this tim instead")
    p.add_argument("--seed", type=int, default=None)
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import (
        make_fake_toas_fromtim,
        make_fake_toas_uniform,
    )

    model = get_model(args.parfile)
    rng = np.random.default_rng(args.seed)
    if args.inputtim:
        toas = make_fake_toas_fromtim(
            args.inputtim, model, add_noise=args.addnoise,
            add_correlated_noise=args.addcorrnoise, rng=rng)
    else:
        toas = make_fake_toas_uniform(
            args.startMJD, args.startMJD + args.duration, args.ntoa,
            model, error_us=args.error, obs=args.obs,
            freq_mhz=args.freq, add_noise=args.addnoise,
            add_correlated_noise=args.addcorrnoise, rng=rng)
    toas.write_TOA_file(args.timfile)
    print(f"Wrote {toas.ntoas} simulated TOAs to {args.timfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
