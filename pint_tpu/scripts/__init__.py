"""Command-line entry points (reference: src/pint/scripts/ console
scripts pintempo, zima, photonphase, pintbary, tcb2tdb,
compare_parfiles). Each module exposes main(argv=None) so tests can
invoke them in-process."""
