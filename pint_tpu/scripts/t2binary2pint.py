"""Convert a TEMPO2 "T2"-binary par file to a native parameterization
(reference: src/pint/scripts/t2binary2pint.py).

TEMPO2's T2 model is a superset dispatcher: the actual orbit family is
implied by which parameters appear. This tool picks the matching
native model (ELL1 family for EPS1/EPS2, DDK for KIN/KOM, else DD/BT)
and rewrites the ``BINARY`` line. For DDK, the orbital-orientation
angles are converted from TEMPO2's IAU convention to the DT92
convention used by the DDK kernel (reference BinaryDDK docs):

    KIN_DT92 = 180 deg - KIN_IAU
    KOM_DT92 =  90 deg - KOM_IAU
"""

from __future__ import annotations

import argparse
import sys

__all__ = ["main", "t2_to_native_parfile"]


def _fmt(v: float) -> str:
    return repr(float(v))


def t2_to_native_parfile(text: str) -> str:
    """Rewrite the par text: BINARY T2 -> native model + angle
    conventions. Non-T2 par files pass through unchanged."""
    from pint_tpu.io.par import parse_parfile

    lines = parse_parfile(__import__("io").StringIO(text))
    keys = {ln.key.upper() for ln in lines}
    binary = next((ln.tokens[0].upper() for ln in lines
                   if ln.key.upper() == "BINARY" and ln.tokens), None)
    if binary != "T2":
        return text

    from pint_tpu.models.model_builder import guess_binary_model

    target = guess_binary_model(keys)

    out = []
    for raw in text.splitlines():
        stripped = raw.strip()
        toks = stripped.split()
        key = toks[0].upper() if toks else ""
        if key == "BINARY":
            out.append(f"BINARY {target}")
        elif key == "KIN" and target == "DDK" and len(toks) >= 2:
            rest = " ".join(toks[2:])
            out.append(f"KIN {_fmt(180.0 - float(toks[1]))} "
                       f"{rest}".rstrip())
        elif key == "KOM" and target == "DDK" and len(toks) >= 2:
            rest = " ".join(toks[2:])
            out.append(f"KOM {_fmt(90.0 - float(toks[1]))} "
                       f"{rest}".rstrip())
        else:
            out.append(raw)
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="t2binary2pint",
        description="Convert a TEMPO2 T2-binary par file to a native "
                    "binary parameterization")
    p.add_argument("input_par")
    p.add_argument("output_par")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    with open(args.input_par) as fh:
        text = fh.read()
    converted = t2_to_native_parfile(text)

    # prove the converted file builds
    import io as _io

    from pint_tpu.models import get_model

    model = get_model(_io.StringIO(converted))
    with open(args.output_par, "w") as fh:
        fh.write(converted)
    binary = next((n[len("Binary"):] for n in model.components
                   if n.startswith("Binary")), "none")
    print(f"Wrote {args.output_par} (binary model: {binary})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
