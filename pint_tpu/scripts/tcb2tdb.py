"""Convert a TCB par file to TDB (reference:
src/pint/scripts/tcb2tdb.py)."""

from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tcb2tdb", description="Convert a TCB par file to TDB")
    p.add_argument("input_par")
    p.add_argument("output_par")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.models import get_model

    # get_model converts TCB -> TDB on load
    model = get_model(args.input_par)
    if (model.UNITS.value or "").upper() != "TDB":
        raise SystemExit(f"conversion failed: UNITS={model.UNITS.value}")
    with open(args.output_par, "w") as fh:
        fh.write(model.as_parfile())
    print(f"Wrote TDB par file to {args.output_par}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
