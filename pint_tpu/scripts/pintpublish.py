"""Publication-style LaTeX table of a fitted timing solution
(reference: src/pint/scripts/pintpublish.py)."""

from __future__ import annotations

import argparse
import sys

__all__ = ["main", "publish_table"]


def publish_table(fitter, include_fixed: bool = False) -> str:
    """LaTeX table body: fitted parameters with parenthesized
    uncertainties, fit statistics, and derived quantities when the
    model is binary."""
    from pint_tpu.utils import format_uncertainty

    model = fitter.model
    res = fitter.resids
    rows = []

    def esc(s: str) -> str:
        return s.replace("_", r"\_")

    rows.append(r"\begin{tabular}{ll}")
    rows.append(r"\hline")
    rows.append(rf"Pulsar & {esc(model.name or model.PSR.value or '?')}"
                r" \\")
    rows.append(rf"TOAs & {fitter.toas.ntoas} \\")
    rows.append(rf"Weighted RMS (\,$\mu$s) & "
                rf"{res.rms_weighted() * 1e6:.3f} \\")
    rows.append(rf"$\chi^2$/dof & {float(res.chi2):.2f}/{res.dof} \\")
    rows.append(r"\hline")
    rows.append(r"\multicolumn{2}{c}{Fitted parameters} \\")
    rows.append(r"\hline")
    from pint_tpu.models.parameter import (AngleParameter,
                                           MJDParameter)

    for nm in model.free_params:
        p = model.get_param(nm)
        if isinstance(p, (AngleParameter, MJDParameter)):
            # sexagesimal / MJD values: use the parameter's own
            # par-convention formatter (raw radians would be wrong)
            val = esc(p._format_value())
            if p.uncertainty is not None:
                val += rf" $\pm$ {esc(p._format_uncertainty())}"
        else:
            val = format_uncertainty(p.value, p.uncertainty)
        unit = f" ({esc(str(p.units))})" if p.units else ""
        rows.append(rf"{esc(nm)}{unit} & {val} \\")
    if include_fixed:
        rows.append(r"\hline")
        rows.append(r"\multicolumn{2}{c}{Fixed parameters} \\")
        rows.append(r"\hline")
        for nm in model.params:  # params is a list of names
            p = model.get_param(nm)
            if p.frozen and p.value is not None and \
                    not isinstance(p.value, (str, bool)):
                try:
                    rows.append(rf"{esc(nm)} & {float(p.value)!r} \\")
                except (TypeError, ValueError):
                    continue
    binary = next((n for n in model.components
                   if n.startswith("Binary")), None)
    if binary:
        try:
            pb_days = model.get_param("PB").value
            x_lts = model.get_param("A1").value
        except KeyError:
            pb_days = x_lts = None
        if pb_days and x_lts:
            from pint_tpu.derived_quantities import mass_funct

            rows.append(r"\hline")
            rows.append(r"\multicolumn{2}{c}{Derived quantities} \\")
            rows.append(r"\hline")
            fm = mass_funct(pb_days, x_lts)
            rows.append(rf"Mass function ($M_\odot$) & {fm:.6g} \\")
    rows.append(r"\hline")
    rows.append(r"\end{tabular}")
    return "\n".join(rows) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pintpublish",
        description="Fit a timing model and print a LaTeX results "
                    "table")
    p.add_argument("parfile")
    p.add_argument("timfile")
    p.add_argument("-o", "--out", default=None,
                   help="write the table to this file (default stdout)")
    p.add_argument("--include-fixed", action="store_true",
                   help="also list fixed numeric parameters")
    p.add_argument("--no-fit", action="store_true",
                   help="tabulate the par-file solution without "
                        "refitting")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.fitter import Fitter
    from pint_tpu.models import get_model_and_toas

    model, toas = get_model_and_toas(args.parfile, args.timfile)
    f = Fitter.auto(toas, model)
    if not args.no_fit:
        f.fit_toas()
    table = publish_table(f, include_fixed=args.include_fixed)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(table)
        print(f"Wrote {args.out}")
    else:
        sys.stdout.write(table)
    return 0


if __name__ == "__main__":
    sys.exit(main())
