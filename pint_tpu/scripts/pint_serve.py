"""Offline serving daemon: JSONL requests on stdin -> coalesced
batched dispatches -> JSONL results on stdout.

The demo surface of ``pint_tpu.serve``: each input line is one
request; the threaded ServeEngine coalesces whatever arrives within
the window into padded vmapped dispatches. Request forms:

    {"kind": "fit_step",  "par": P, "tim": T, "id": ..., "deadline_ms": ...,
     "tenant": ...}
    {"kind": "residuals", "par": P, "tim": T, ...}
    {"kind": "phase", "par": P, "mjds": [...], "obs": "@",
     "seg_min": 60.0, ...}
    {"kind": "posterior", "par": P, "tim": T, "nwalkers": 32,
     "nsteps": 500, "seed": 0, "thin": 1, ...}
    {"kind": "stats", "id": ...}
    {"kind": "profile", "seconds": N, "id": ...}

(par, tim) pairs are loaded once and cached — repeated requests
against the same pulsar are the serving-state hot path, paying only
the batched solve. Phase requests generate (and cache) polycos
covering the requested MJDs, then split the MJDs per segment into
PhasePredictRequests. ``--demo N`` synthesizes an N-request
mixed-shape workload instead of reading stdin.

Lifecycle (ISSUE 8):

- **graceful shutdown**: SIGTERM/SIGINT stops the stdin read, drains
  the engine with a bounded timeout (``--drain-timeout-s`` /
  ``$PINT_TPU_SERVE_DRAIN_TIMEOUT_S``), and every request still
  queued at the bound gets an explicit
  ``{"status": "shed", "reason": "shutdown"}`` result line — queued
  work is never silently dropped on the floor;
- **crash-safe journal** (``--journal`` / ``$PINT_TPU_JOURNAL``):
  each input record is journaled at admission and acknowledged when
  its last result line is emitted (graceful sheds ack terminally as
  ``shed:shutdown`` — the client was told). On startup,
  unacknowledged records from a previous crash are REPLAYED before
  stdin is read;
- **AOT warm restart** (``--aot-dir`` / ``$PINT_TPU_AOT_DIR``): the
  engine exports each compiled shape class and a restarted daemon
  restores+primes them, serving its first request without
  recompiling the serve kernels.

Observability (ISSUE 10): a ``{"kind": "stats"}`` line answers
IMMEDIATELY on the reader thread with the latency-histogram
quantiles, flight-recorder status and dispatch counters — it is
never journaled, never queued, and never perturbs in-flight
batches. ``--trace-jsonl PATH`` (or ``$PINT_TPU_TRACE_STREAM``)
streams every completed span as a JSONL line; ``$PINT_TPU_TRACE``
arms the ring tracer; ``$PINT_TPU_FLIGHT_DIR`` arms the flight
recorder, which also dumps on the SIGTERM bounded-drain path.

Metrics plane (ISSUE 11): ``--metrics-port N`` (or
``$PINT_TPU_METRICS_PORT``; 0 = ephemeral, announced as a
``{"event": "metrics_server", "port": ...}`` line) serves Prometheus
text exposition on ``/metrics`` and breaker/pool health JSON on
``/healthz`` from a stdlib daemon thread that NEVER takes the engine
lock — the pull surface a multi-worker fleet scrapes per worker. The
``stats`` answer carries a ``registry`` summary of the same metric
plane; ``$PINT_TPU_SLO`` arms the burn-rate watchdog (fires the
flight recorder with reason ``slo_burn:<name>``).

Numerical health (ISSUE 14): with ``$PINT_TPU_HEALTH`` (and/or
``$PINT_TPU_SHADOW_RATE``) armed, the ``stats`` answer and the serve
snapshot gain a ``health`` verdict block (worst recent verdict per
(pool, kind), last incident reason + age) and ``/healthz`` a
``numerics`` block that degrades the response to 503 on an
unresolved bad verdict — all monitor-lock reads, still never an
engine lock, still never journaled.

One JSON result line per request (input order NOT guaranteed — lines
carry the request id); the final line is the engine metrics snapshot
({"metric": "serve_session", ...}) whose ``admission``/``router``/
``restart`` blocks label every shed, reroute and replay.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from pint_tpu.runtime import locks
import uuid

__all__ = ["main"]


class _Shutdown(Exception):
    """Raised into the main thread by the SIGTERM/SIGINT handler to
    break the blocking stdin read."""


def _install_signal_handlers():
    """Route SIGTERM/SIGINT into the graceful-shutdown path. Returns
    the previous handlers so an embedding process (or a test driving
    main() directly) can restore them."""
    def handler(signum, frame):
        raise _Shutdown(signal.Signals(signum).name)

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, handler)
        except (ValueError, OSError):
            pass  # not the main thread (tests drive main() directly)
    return prev


def _restore_signal_handlers(prev):
    for sig, h in (prev or {}).items():
        try:
            signal.signal(sig, h)
        except (ValueError, OSError):
            pass


def _ignore_signals():
    """Once the graceful shutdown has begun, further SIGTERM/SIGINT
    must not abort the bounded drain mid-way — the shed lines and
    the final session snapshot are the shutdown contract."""
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, signal.SIG_IGN)
        except (ValueError, OSError):
            pass


def _shed_pending_stdin(stream=None) -> int:
    """Shed input lines already written when shutdown arrives DURING
    STARTUP (no engine yet): each pending JSONL record gets the same
    explicit ``{"status": "shed", "reason": "shutdown"}`` line the
    bounded drain emits — an early signal must not silently drop a
    client's work either. Bounded by construction: only what is
    already buffered on the pipe is drained (select with a 50 ms
    grace per read, EOF stops)."""
    import select

    shed = 0

    def shed_line(line):
        nonlocal shed
        line = line.strip()
        if not line or line.startswith("#"):
            return
        try:
            rid = json.loads(line).get("id")
        except Exception:
            rid = None
        obj = {"status": "shed", "reason": "shutdown"}
        if rid is not None:
            obj["id"] = rid
        print(json.dumps(obj), flush=True)
        shed += 1

    if stream is not None:          # tests drive main(stdin=[...])
        for line in stream:
            shed_line(line)
        return shed
    try:
        while select.select([sys.stdin], [], [], 0.05)[0]:
            line = sys.stdin.readline()
            if not line:
                break
            shed_line(line)
    except (OSError, ValueError):
        pass                        # stdin closed / not selectable
    return shed


class _LineAck:
    """Journal acknowledgement for ONE input record: a record may fan
    out into several engine requests (phase segments); the terminal
    ack is written when the LAST of them has emitted its result
    line. Thread-safe — emissions arrive from the drain thread while
    the expected count is still being established on the reader
    thread."""

    def __init__(self, journal, rid):
        self.journal = journal
        self.rid = rid
        self._lock = locks.make_lock("serve.cli_state")
        self._expected = None
        self._emitted = 0
        self._acked = False
        self._worst = "served"

    def emitted(self, status: str = "served"):
        with self._lock:
            self._emitted += 1
            if status != "served":
                self._worst = status
            self._maybe_ack()

    def expect(self, n: int):
        with self._lock:
            self._expected = n
            self._maybe_ack()

    def _maybe_ack(self):
        if self._acked or self.journal is None:
            return
        if self._expected is not None and \
                self._emitted >= self._expected:
            self._acked = True
            # zero submissions = nothing was served (the error went
            # through the uncounted report path): terminal "failed",
            # never a fabricated "served"
            self.journal.ack(self.rid, self._worst
                             if self._expected > 0 else "failed")

    def fail(self):
        """Terminal "failed" ack for a record whose submission path
        raised — without this a journaled record that can never
        submit (a deleted par file, say) would be REPLAYED on every
        restart forever."""
        with self._lock:
            if self._acked or self.journal is None:
                return
            self._acked = True
            self.journal.ack(self.rid, "failed")


def _load_pair(cache, par, tim):
    key = ("pair", par, tim)
    if key not in cache:
        import warnings

        from pint_tpu.models import get_model_and_toas

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            cache[key] = get_model_and_toas(par, tim)
    return cache[key]


def _polycos_for(cache, par, obs, mjd_lo, mjd_hi, seg_min):
    key = ("polyco", par, obs, round(mjd_lo, 6), round(mjd_hi, 6),
           seg_min)
    if key not in cache:
        import warnings

        from pint_tpu.models import get_model
        from pint_tpu.polycos import Polycos

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model = get_model(par)
            cache[key] = Polycos.generate_polycos(
                model, mjd_lo, mjd_hi, obs, seg_length_min=seg_min)
    return cache[key]


def _posterior_request(cache, rec, deadline_s, tenant,
                       payload=None):
    """Build one quantized PosteriorRequest from a line record —
    shared by the submit path and the fleet replay factory (the
    quantization below must be identical in both or a re-homed chain
    lands in a different shape class than the original)."""
    from pint_tpu.parallel.pta import build_problem
    from pint_tpu.serve import PosteriorRequest
    from pint_tpu.serve.bucket import pow2_ceil

    model, toas = _load_pair(cache, rec["par"], rec["tim"])
    problem = build_problem(toas, model)
    # client-facing quantization: nwalkers/thin ride EXACTLY in
    # the posterior compile key (they are compile-time constants
    # of the scan program), so arbitrary client values would mean
    # one multi-second XLA compile per distinct request shape.
    # Pow2-quantize both (more walkers is strictly better
    # sampling; nsteps rounds up to stay a thin multiple) so
    # compiles stay bounded by class count, not traffic. The
    # walker FLOOR comes from the problem's real dimension count
    # (the 2*ndim ensemble guard), so a default request never
    # hard-fails on a wide model; nsteps is capped so one
    # request cannot monopolize a pool with an unbounded
    # sequential chunk loop.
    p = problem.M.shape[1]
    W = max(int(rec.get("nwalkers", 32)), 2 * p + 2)
    W = min(1024, max(8, pow2_ceil(W)))
    thin = min(16, max(1, pow2_ceil(int(rec.get("thin", 1)))))
    nsteps = min(int(rec.get("nsteps", 500)), 1_000_000)
    nsteps = ((nsteps + thin - 1) // thin) * thin
    return PosteriorRequest(
        problem=problem, nwalkers=W, nsteps=nsteps,
        seed=int(rec.get("seed", 0)), thin=thin,
        deadline_s=deadline_s, tenant=tenant, payload=payload)


def _line_factory(cache):
    """Fleet replay factory (ISSUE 19): rebuild a single-submission
    request from its journaled line record. Re-homing resolves the
    ORIGINAL caller's future with the rebuilt request's result, so
    the daemon's emission callback stays wired to the original."""

    def factory(payload):
        from pint_tpu.serve import FitStepRequest, ResidualsRequest

        kind = payload.get("kind", "fit_step")
        deadline_s = payload["deadline_ms"] / 1e3 \
            if payload.get("deadline_ms") is not None else None
        tenant = payload.get("tenant")
        if kind in ("fit_step", "residuals"):
            model, toas = _load_pair(cache, payload["par"],
                                     payload["tim"])
            cls = FitStepRequest if kind == "fit_step" \
                else ResidualsRequest
            return cls(toas, model, deadline_s=deadline_s,
                       tenant=tenant, payload=payload)
        if kind == "posterior":
            return _posterior_request(cache, payload, deadline_s,
                                      tenant, payload=payload)
        raise ValueError(f"kind {kind!r} is not fleet-replayable")

    return factory


def _submit_line(engine, cache, rec, emit, report, ack=None,
                 journal_payload=False):
    """Parse one request record and submit it; wire result emission
    through the future's done-callback so the daemon never blocks on
    a single request. Returns the number of requests actually
    submitted (= the number of ``emit`` calls this line will
    eventually produce — the pending-semaphore contract); failures
    that submit NOTHING go through ``report`` (uncounted).

    ``journal_payload=True`` (fleet mode) attaches the line record
    as the request payload for single-submission kinds, so the
    WORKER engine journals it with an owner and a lost worker's
    requests re-home; phase fan-outs stay unjournaled (several
    requests per line — a line-level replay covers them instead)."""
    import numpy as np

    from pint_tpu.serve import (
        FitStepRequest,
        PhasePredictRequest,
        ResidualsRequest,
        ShutdownShed,
    )

    rid = rec.get("id")
    kind = rec.get("kind", "fit_step")
    if kind == "stats":
        # introspection read: answered inline from host bookkeeping
        # (histogram snapshots + flight status + dispatch counters)
        # — zero engine submissions, zero journal lines, in-flight
        # batches untouched
        from pint_tpu.obs import metrics as om

        snap = engine.metrics.snapshot()
        out = {"ok": True, "kind": "stats",
               "latency": snap.get("latency", {}),
               "obs": snap.get("obs"),
               "dispatch": snap.get("dispatch"),
               "admission": snap.get("admission"),
               "queue_depth": snap.get("queue_depth"),
               "completed": snap.get("completed"),
               "submitted": snap.get("submitted"),
               # ISSUE 11: the same answer as a registry view — the
               # inline twin of a /metrics scrape
               "registry": om.get_registry().snapshot()}
        if snap.get("slo") is not None:
            out["slo"] = snap["slo"]
        # ISSUE 14: the numerical-health verdict block (worst recent
        # verdict per (pool, kind), last incident + age) — still
        # engine-lock-free (snapshot reads monitor-lock state only),
        # still never journaled (this whole branch is the inline
        # introspection path)
        if snap.get("health") is not None:
            out["health"] = snap["health"]
        if rid is not None:
            out["id"] = rid
        report(out)
        if ack is not None:
            # a stats record replayed out of a legacy journal must
            # ack terminally (zero submissions -> "failed"), never
            # replay forever
            ack.expect(0)
        return 0
    if kind == "profile":
        # ISSUE 15: open one bounded profiler window capturing the
        # NEXT dispatches ({"kind": "profile", "seconds": N}) —
        # answered inline like stats (zero engine submissions, never
        # journaled, in-flight batches untouched); disarmed
        # ($PINT_TPU_PROFILE_DIR unset) or rate-limited requests get
        # a labeled refusal, never an error path
        from pint_tpu.obs import perf as _perf

        res = _perf.request_window(rec.get("seconds"),
                                   reason="profile")
        out = {"kind": "profile"}
        out.update(res)
        if rid is not None:
            out["id"] = rid
        report(out)
        if ack is not None:
            ack.expect(0)
        return 0
    tenant = rec.get("tenant")
    deadline_s = rec["deadline_ms"] / 1e3 \
        if rec.get("deadline_ms") is not None else None

    def finish(kind):
        def cb(fut):
            out = {"id": rid, "kind": kind}
            try:
                res = fut.result(timeout=0)
            except ShutdownShed:
                # the graceful-shutdown contract: an explicit shed
                # line per unserved request, never a silent drop
                out.update(ok=False, status="shed",
                           reason="shutdown")
                emit(out, status="shed:shutdown")
                return
            except Exception as e:
                out.update(ok=False, error=f"{type(e).__name__}: {e}")
                emit(out, status="failed")
                return
            out["ok"] = True
            if kind == "fit_step":
                out["chi2"] = res.chi2
                out["chi2_prefit"] = res.chi2r
                out["dparams"] = {n: float(v) for n, v in
                                  zip(res.names, res.dparams)}
                out["errors"] = res.errors()
            elif kind == "residuals":
                out["chi2"] = res.chi2
                out["rms_us"] = res.rms_us
                out["n"] = len(res.time_resids)
            elif kind == "posterior":
                out["acceptance"] = res.acceptance_fraction
                out["nsteps"] = res.nsteps
                out["posterior"] = res.summary()
            else:
                out["phase_int"] = np.asarray(res.phase_int).tolist()
                out["phase_frac"] = np.asarray(res.phase_frac).tolist()
            emit(out)
        return cb

    payload = rec if journal_payload else None
    if kind in ("fit_step", "residuals"):
        model, toas = _load_pair(cache, rec["par"], rec["tim"])
        cls = FitStepRequest if kind == "fit_step" else ResidualsRequest
        fut = engine.submit(cls(toas, model, deadline_s=deadline_s,
                                tenant=tenant, payload=payload))
        fut.add_done_callback(finish(kind))
        if ack is not None:
            ack.expect(1)
        return 1
    if kind == "posterior":
        fut = engine.submit(_posterior_request(
            cache, rec, deadline_s, tenant, payload=payload))
        fut.add_done_callback(finish(kind))
        if ack is not None:
            ack.expect(1)
        return 1
    if kind == "phase":
        mjds = np.atleast_1d(np.asarray(rec["mjds"], np.float64))
        seg_min = float(rec.get("seg_min", 60.0))
        pad = seg_min / 1440.0
        pcs = _polycos_for(cache, rec["par"], rec.get("obs", "@"),
                           float(mjds.min()) - pad,
                           float(mjds.max()) + pad, seg_min)
        idx = pcs._entry_for(mjds)
        segs = np.unique(idx)
        nsub = 0
        for s in segs:
            try:
                fut = engine.submit(PhasePredictRequest(
                    pcs.entries[int(s)], mjds[idx == s],
                    deadline_s=deadline_s, tenant=tenant))
            except Exception as e:
                # PARTIAL submit (PR-3 review bug): the segments
                # already admitted WILL emit and release the pending
                # semaphore, so the count returned below must include
                # them; the shed remainder is reported through the
                # UNCOUNTED path, or the final session snapshot would
                # race the still-pending results. Catches EVERYTHING
                # (not just the ServeOverload backpressure signal):
                # any mid-fan failure after >=1 admission would
                # otherwise escape with the count lost
                report({"id": rid, "kind": "phase", "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "segments_submitted": nsub,
                        "segments_shed": int(len(segs) - nsub)})
                break
            fut.add_done_callback(finish("phase"))
            nsub += 1
        if ack is not None:
            ack.expect(nsub)
        return nsub
    raise ValueError(f"unknown request kind {kind!r}")


def _demo_requests(n: int):
    """Synthesize a mixed-shape workload: small simulated pulsars in
    three TOA-count classes + polyco phase reads. Delegates to
    ``pint_tpu.serve.workload`` — the ONE workload builder, shared
    with bench_serve.py (PR-3 review: the two copies had already
    started to drift)."""
    from pint_tpu.serve.workload import DEMO_SIZES, build_workload

    return build_workload(n, sizes=DEMO_SIZES, base=1200,
                          prebuild=False, with_kinds=True,
                          entry_name="DEMO")()


def main(argv=None, stdin=None) -> int:
    p = argparse.ArgumentParser(
        prog="pint_serve",
        description="JSONL serving daemon over the continuous-"
                    "batching scheduler (pint_tpu.serve)")
    p.add_argument("--window-ms", type=float, default=None,
                   help="coalescing window (default "
                        "$PINT_TPU_SERVE_WINDOW_MS or 5)")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--queue-cap", type=int, default=None)
    p.add_argument("--demo", type=int, default=None, metavar="N",
                   help="serve N synthesized mixed requests instead "
                        "of reading stdin")
    p.add_argument("--journal", default=None,
                   help="append-only request journal (crash replay; "
                        "default $PINT_TPU_JOURNAL)")
    p.add_argument("--aot-dir", default=None,
                   help="AOT executable dir for warm restart "
                        "(default $PINT_TPU_AOT_DIR)")
    p.add_argument("--drain-timeout-s", type=float, default=None,
                   help="graceful-shutdown drain bound (default "
                        "$PINT_TPU_SERVE_DRAIN_TIMEOUT_S or 30)")
    p.add_argument("--trace-jsonl", default=None, metavar="PATH",
                   help="stream completed tracer spans as JSONL to "
                        "PATH (default $PINT_TPU_TRACE_STREAM; "
                        "implies tracing on)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve Prometheus /metrics + /healthz on "
                        "this port (0 = ephemeral, announced as an "
                        "event line; default $PINT_TPU_METRICS_PORT "
                        "or off)")
    p.add_argument("--worker-id", default=None, metavar="ID",
                   help="fleet worker identity (ISSUE 19): admits "
                        "are owner-stamped, a lease heartbeat rides "
                        "the shared journal, and restart replay is "
                        "scoped to THIS worker's records — one "
                        "pint_serve --worker-id per process over a "
                        "shared --journal is the cross-process fleet")
    p.add_argument("--fleet", type=int, default=None, metavar="N",
                   help="run N in-process fleet workers over one "
                        "shared journal (FleetFront: lease expiry "
                        "re-homes a dead worker's requests onto "
                        "survivors); requires --journal")
    args = p.parse_args(argv)
    if args.fleet is not None and args.worker_id is not None:
        p.error("--fleet and --worker-id are mutually exclusive "
                "(the front names its own workers)")

    # handlers BEFORE the pint_tpu/jax import: startup takes seconds
    # (jax init, AOT restore), and a signal landing in that window
    # used to hit the default handler — process killed, lines already
    # written to stdin silently dropped
    prev_handlers = _install_signal_handlers()
    try:
        from pint_tpu.config import (
            enable_user_compile_cache,
            serve_drain_timeout_s,
        )

        enable_user_compile_cache()
        drain_timeout = serve_drain_timeout_s() \
            if args.drain_timeout_s is None else args.drain_timeout_s

        if args.trace_jsonl is not None:
            from pint_tpu import obs

            obs.configure(stream=args.trace_jsonl)

        from pint_tpu import config as _config
        from pint_tpu.serve import ServeEngine

        # (par, tim) cache: hoisted above engine construction because
        # the fleet replay factory closes over it — re-homed requests
        # rebuild against the same loaded pulsars as stdin ones
        cache: dict = {}
        fleet = None
        worker_lease = None
        engine_kw = dict(
            window_s=None if args.window_ms is None
            else args.window_ms / 1e3,
            max_batch=args.max_batch, queue_cap=args.queue_cap)
        if args.fleet is not None:
            from pint_tpu.serve import FleetFront

            journal_path = args.journal
            if journal_path is None:
                journal_path = _config.journal_path()
            if journal_path is None:
                p.error("--fleet requires --journal (the shared "
                        "replicated log is the fleet's ownership "
                        "protocol)")
            engine = fleet = FleetFront(
                factory=_line_factory(cache), n=args.fleet,
                journal=journal_path, aot_dir=args.aot_dir,
                engine_kwargs=engine_kw, start=False)
        else:
            engine = ServeEngine(
                aot_dir=args.aot_dir, journal=args.journal,
                worker_id=args.worker_id, **engine_kw)
            if args.worker_id is not None and \
                    engine.journal is not None:
                from pint_tpu.serve import WorkerLease

                worker_lease = WorkerLease(engine.journal,
                                           args.worker_id)
                worker_lease.start()

        # metrics plane (ISSUE 11): /metrics + /healthz on a stdlib
        # daemon thread — reads registry/breaker state only, never
        # the engine lock, so a scrape cannot perturb admission or
        # an in-flight drain
        metrics_srv = None
        mport = args.metrics_port if args.metrics_port is not None \
            else _config.metrics_port()
        if mport is not None:
            from pint_tpu.obs import metrics as _om

            def _health(engine=engine, fleet=fleet, _om=_om):
                h = _om.default_health()
                try:
                    # ISSUE 19: per-pool breaker state + learned EWMA
                    # rate + in-flight depth — router leaf-lock reads
                    # only, never an engine lock (the scrape contract
                    # tests/test_metrics.py asserts by holding
                    # eng._lock while hitting /healthz)
                    if fleet is not None:
                        h["pools"] = fleet.health_blocks()
                        h["fleet"] = {"live": fleet.live_workers()}
                    else:
                        h["pools"] = engine.router.health_block()
                except Exception:
                    pass
                return h

            metrics_srv = _om.MetricsServer(
                port=mport, health_fn=_health).start()
            print(json.dumps({"event": "metrics_server",
                              "port": metrics_srv.port}), flush=True)
    except _Shutdown as sig:
        _ignore_signals()
        shed = 0 if args.demo is not None else \
            _shed_pending_stdin(stdin)
        print(json.dumps({"event": "shutdown", "signal": str(sig),
                          "during": "startup", "shed": shed}),
              flush=True)
        _restore_signal_handlers(prev_handlers)
        return 0

    out_lock = locks.make_lock("serve.cli_stdout")
    pending = threading.Semaphore(0)
    nsub = 0

    def raw_emit(obj):
        with out_lock:
            print(json.dumps(obj), flush=True)
        pending.release()

    def report(obj):
        """Result line for a request that was never admitted — NOT
        via emit: its semaphore release is the per-SUBMITTED-request
        completion count."""
        with out_lock:
            print(json.dumps(obj), flush=True)

    shutdown_reason = None
    if args.demo is not None:
        from pint_tpu.serve import ServeOverload

        reqs = _demo_requests(args.demo)
        engine.start()
        try:
            for kind, rq in reqs:
                try:
                    fut = engine.submit(rq)
                except ServeOverload as e:
                    # PR-3 review bug: backpressure during the demo
                    # burst crashed the daemon instead of shedding
                    report({"kind": kind, "ok": False,
                            "error": repr(e)})
                    continue

                def cb(fut, kind=kind):
                    try:
                        fut.result(timeout=0)
                        raw_emit({"kind": kind, "ok": True})
                    except Exception as e:
                        raw_emit({"kind": kind, "ok": False,
                                  "error": repr(e)})
                fut.add_done_callback(cb)
                nsub += 1
        except _Shutdown as sig:
            shutdown_reason = str(sig)
            _ignore_signals()
            report({"event": "shutdown", "signal": shutdown_reason,
                    "drain_timeout_s": drain_timeout})
    else:
        engine.start()

        def fleet_emit(obj, status="served"):
            # fleet mode: the WORKER engine journals each single-
            # submission request (payload = the line record, owner =
            # the worker) so re-homing works at request granularity;
            # the line-level journal + _LineAck stay out of the way
            raw_emit(obj)

        def handle(rec):
            nonlocal nsub
            if rec.get("kind") in ("stats", "profile"):
                # introspection/window control: answered inline,
                # never journaled (a journaled stats line would
                # replay forever — it can never receive a terminal
                # ack; a profile window is a point-in-time act)
                _submit_line(engine, cache, rec, None, report)
                return
            if fleet is not None:
                nsub += _submit_line(engine, cache, rec, fleet_emit,
                                     report, journal_payload=True)
                return
            rid = rec.get("id") or uuid.uuid4().hex
            ack = _LineAck(engine.journal, rid)
            if engine.journal is not None:
                engine.journal.admit(rid, rec,
                                     tenant=rec.get("tenant"),
                                     worker=args.worker_id)

            def emit(obj, status="served", _ack=ack):
                raw_emit(obj)
                _ack.emitted(status)

            try:
                nsub += _submit_line(engine, cache, rec, emit,
                                     report, ack=ack)
            except _Shutdown:
                # NOT the record's fault: leave it UNACKED so the
                # journal replays it on restart (a terminal 'failed'
                # ack here would silently drop it — the record was
                # mid-submit when the signal landed). Without a
                # journal nothing will replay it, so the client gets
                # an explicit shed line instead.
                if engine.journal is None:
                    report({"id": rid, "status": "shed",
                            "reason": "shutdown"})
                raise
            except BaseException:
                ack.fail()  # terminal: never replay a poison record
                raise

        def replay_journal():
            """Re-admit the records a previous process died holding
            (no terminal ack in the journal). Runs BEFORE stdin so
            recovered work is first in line. Worker mode scopes the
            replay to THIS worker's owner-stamped records — a peer's
            unacked work belongs to its lease (the fleet re-home
            protocol moves it, not a restart); fleet mode replays
            everything (the front owns the whole journal)."""
            nonlocal nsub
            if engine.journal is None:
                return
            if fleet is not None:
                # engine-level records: the payload IS the line
                # record, so the stale rid acks terminally and the
                # work resubmits fresh (new rid, new owner) through
                # the same path stdin takes
                for jrec in engine.journal.unacknowledged():
                    rec = jrec.get("payload") or {}
                    engine.journal.ack(jrec["rid"], "replayed")
                    try:
                        n = _submit_line(engine, cache, rec,
                                         fleet_emit, report,
                                         journal_payload=True)
                        nsub += n
                        ri = engine.metrics.restart_info
                        ri["replayed"] = ri.get("replayed", 0) + n
                    except _Shutdown:
                        raise
                    except Exception as e:
                        report({"id": rec.get("id"), "ok": False,
                                "error": f"replay: "
                                         f"{type(e).__name__}: {e}"})
                return
            for jrec in engine.journal.unacknowledged(
                    owner=args.worker_id):
                rec = jrec.get("payload") or {}
                engine.journal.ack(jrec["rid"], "replayed")
                ack = _LineAck(engine.journal, jrec["rid"])

                def emit(obj, status="served", _ack=ack):
                    raw_emit(obj)
                    _ack.emitted(status)

                try:
                    n = _submit_line(engine, cache, rec, emit,
                                     report, ack=ack)
                    nsub += n
                    engine.metrics.restart_info["replayed"] = \
                        engine.metrics.restart_info.get(
                            "replayed", 0) + n
                except _Shutdown:
                    raise  # leave unacked: replayable next start
                except Exception as e:
                    ack.fail()  # terminal: no infinite replay loop
                    report({"id": jrec.get("rid"), "ok": False,
                            "error": f"replay: "
                                     f"{type(e).__name__}: {e}"})

        try:
            replay_journal()
            for line in (sys.stdin if stdin is None else stdin):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    rec = json.loads(line)
                    handle(rec)
                except _Shutdown:
                    raise
                except Exception as e:
                    # malformed line (or a zero-submission overload):
                    # report through the uncounted path
                    report({"ok": False,
                            "error": f"{type(e).__name__}: {e}",
                            "line": line[:200]})
        except _Shutdown as sig:
            shutdown_reason = str(sig)
            # a SECOND signal must not abort the bounded drain —
            # the shed lines + snapshot below are the contract
            _ignore_signals()
            report({"event": "shutdown", "signal": shutdown_reason,
                    "drain_timeout_s": drain_timeout})

    # graceful stop: bounded drain, then every still-queued request
    # is shed with a labeled ShutdownShed (emitted above as
    # {"status": "shed", "reason": "shutdown"}); unbounded only when
    # no signal asked us to leave
    if shutdown_reason:
        # SIGTERM-drain flight dump (ISSUE 10): capture what the
        # engine was doing when the signal landed — BEFORE the drain
        # mutates the queue, so the dump shows the pre-shutdown state
        from pint_tpu import obs

        obs.flight_dump("sigterm_drain", signal=shutdown_reason,
                        drain_timeout_s=drain_timeout)
    if worker_lease is not None:
        # stop heartbeating BEFORE the drain: a peer's sweep must be
        # free to re-home whatever this worker cannot drain in time
        worker_lease.stop()
    engine.stop(drain=True,
                timeout=drain_timeout if shutdown_reason else None)
    for _ in range(nsub):
        pending.acquire()
    snap = engine.metrics.snapshot()
    snap["metric"] = "serve_session"
    if shutdown_reason:
        snap["shutdown_signal"] = shutdown_reason
    if metrics_srv is not None:
        snap["metrics_port"] = metrics_srv.port
        metrics_srv.close()
    with out_lock:
        print(json.dumps(snap), flush=True)
    print(engine.metrics.report(), file=sys.stderr)
    _restore_signal_handlers(prev_handlers)
    return 0


if __name__ == "__main__":
    sys.exit(main())
