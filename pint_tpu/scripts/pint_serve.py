"""Offline serving daemon: JSONL requests on stdin -> coalesced
batched dispatches -> JSONL results on stdout.

The demo surface of ``pint_tpu.serve``: each input line is one
request; the threaded ServeEngine coalesces whatever arrives within
the window into padded vmapped dispatches. Request forms:

    {"kind": "fit_step",  "par": P, "tim": T, "id": ..., "deadline_ms": ...}
    {"kind": "residuals", "par": P, "tim": T, ...}
    {"kind": "phase", "par": P, "mjds": [...], "obs": "@",
     "seg_min": 60.0, ...}

(par, tim) pairs are loaded once and cached — repeated requests
against the same pulsar are the serving-state hot path, paying only
the batched solve. Phase requests generate (and cache) polycos
covering the requested MJDs, then split the MJDs per segment into
PhasePredictRequests. ``--demo N`` synthesizes an N-request
mixed-shape workload instead of reading stdin.

One JSON result line per request (input order NOT guaranteed — lines
carry the request id); the final line is the engine metrics snapshot
({"metric": "serve_session", ...}).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading

__all__ = ["main"]


def _load_pair(cache, par, tim):
    key = ("pair", par, tim)
    if key not in cache:
        import warnings

        from pint_tpu.models import get_model_and_toas

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            cache[key] = get_model_and_toas(par, tim)
    return cache[key]


def _polycos_for(cache, par, obs, mjd_lo, mjd_hi, seg_min):
    key = ("polyco", par, obs, round(mjd_lo, 6), round(mjd_hi, 6),
           seg_min)
    if key not in cache:
        import warnings

        from pint_tpu.models import get_model
        from pint_tpu.polycos import Polycos

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model = get_model(par)
            cache[key] = Polycos.generate_polycos(
                model, mjd_lo, mjd_hi, obs, seg_length_min=seg_min)
    return cache[key]


def _submit_line(engine, cache, rec, emit, report):
    """Parse one request record and submit it; wire result emission
    through the future's done-callback so the daemon never blocks on
    a single request. Returns the number of requests actually
    submitted (= the number of ``emit`` calls this line will
    eventually produce — the pending-semaphore contract); failures
    that submit NOTHING go through ``report`` (uncounted)."""
    import numpy as np

    from pint_tpu.serve import (
        FitStepRequest,
        PhasePredictRequest,
        ResidualsRequest,
    )

    rid = rec.get("id")
    kind = rec.get("kind", "fit_step")
    deadline_s = rec["deadline_ms"] / 1e3 \
        if rec.get("deadline_ms") is not None else None

    def finish(kind):
        def cb(fut):
            out = {"id": rid, "kind": kind}
            try:
                res = fut.result(timeout=0)
            except Exception as e:
                out.update(ok=False, error=f"{type(e).__name__}: {e}")
                emit(out)
                return
            out["ok"] = True
            if kind == "fit_step":
                out["chi2"] = res.chi2
                out["chi2_prefit"] = res.chi2r
                out["dparams"] = {n: float(v) for n, v in
                                  zip(res.names, res.dparams)}
                out["errors"] = res.errors()
            elif kind == "residuals":
                out["chi2"] = res.chi2
                out["rms_us"] = res.rms_us
                out["n"] = len(res.time_resids)
            else:
                out["phase_int"] = np.asarray(res.phase_int).tolist()
                out["phase_frac"] = np.asarray(res.phase_frac).tolist()
            emit(out)
        return cb

    if kind in ("fit_step", "residuals"):
        model, toas = _load_pair(cache, rec["par"], rec["tim"])
        cls = FitStepRequest if kind == "fit_step" else ResidualsRequest
        fut = engine.submit(cls(toas, model, deadline_s=deadline_s))
        fut.add_done_callback(finish(kind))
        return 1
    if kind == "phase":
        mjds = np.atleast_1d(np.asarray(rec["mjds"], np.float64))
        seg_min = float(rec.get("seg_min", 60.0))
        pad = seg_min / 1440.0
        pcs = _polycos_for(cache, rec["par"], rec.get("obs", "@"),
                           float(mjds.min()) - pad,
                           float(mjds.max()) + pad, seg_min)
        idx = pcs._entry_for(mjds)
        segs = np.unique(idx)
        nsub = 0
        for s in segs:
            try:
                fut = engine.submit(PhasePredictRequest(
                    pcs.entries[int(s)], mjds[idx == s],
                    deadline_s=deadline_s))
            except Exception as e:
                # PARTIAL submit (PR-3 review bug): the segments
                # already admitted WILL emit and release the pending
                # semaphore, so the count returned below must include
                # them; the shed remainder is reported through the
                # UNCOUNTED path, or the final session snapshot would
                # race the still-pending results. Catches EVERYTHING
                # (not just the ServeOverload backpressure signal):
                # any mid-fan failure after >=1 admission would
                # otherwise escape with the count lost
                report({"id": rid, "kind": "phase", "ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "segments_submitted": nsub,
                        "segments_shed": int(len(segs) - nsub)})
                break
            fut.add_done_callback(finish("phase"))
            nsub += 1
        return nsub
    raise ValueError(f"unknown request kind {kind!r}")


def _demo_requests(n: int):
    """Synthesize a mixed-shape workload: small simulated pulsars in
    three TOA-count classes + polyco phase reads. Delegates to
    ``pint_tpu.serve.workload`` — the ONE workload builder, shared
    with bench_serve.py (PR-3 review: the two copies had already
    started to drift)."""
    from pint_tpu.serve.workload import DEMO_SIZES, build_workload

    return build_workload(n, sizes=DEMO_SIZES, base=1200,
                          prebuild=False, with_kinds=True,
                          entry_name="DEMO")()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pint_serve",
        description="JSONL serving daemon over the coalescing "
                    "batch scheduler (pint_tpu.serve)")
    p.add_argument("--window-ms", type=float, default=None,
                   help="coalescing window (default "
                        "$PINT_TPU_SERVE_WINDOW_MS or 5)")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--queue-cap", type=int, default=None)
    p.add_argument("--demo", type=int, default=None, metavar="N",
                   help="serve N synthesized mixed requests instead "
                        "of reading stdin")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.serve import ServeEngine

    engine = ServeEngine(
        window_s=None if args.window_ms is None
        else args.window_ms / 1e3,
        max_batch=args.max_batch, queue_cap=args.queue_cap)

    out_lock = threading.Lock()
    pending = threading.Semaphore(0)
    nsub = 0

    def emit(obj):
        with out_lock:
            print(json.dumps(obj), flush=True)
        pending.release()

    def report(obj):
        """Result line for a request that was never admitted — NOT
        via emit: its semaphore release is the per-SUBMITTED-request
        completion count."""
        with out_lock:
            print(json.dumps(obj), flush=True)

    if args.demo is not None:
        from pint_tpu.serve import ServeOverload

        reqs = _demo_requests(args.demo)
        engine.start()
        for kind, rq in reqs:
            try:
                fut = engine.submit(rq)
            except ServeOverload as e:
                # PR-3 review bug: backpressure during the demo burst
                # crashed the daemon instead of shedding the request
                report({"kind": kind, "ok": False, "error": repr(e)})
                continue

            def cb(fut, kind=kind):
                try:
                    fut.result(timeout=0)
                    emit({"kind": kind, "ok": True})
                except Exception as e:
                    emit({"kind": kind, "ok": False, "error": repr(e)})
            fut.add_done_callback(cb)
            nsub += 1
    else:
        engine.start()
        cache: dict = {}
        for line in sys.stdin:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
                nsub += _submit_line(engine, cache, rec, emit,
                                     report)
            except Exception as e:
                # malformed line (or a zero-submission overload):
                # report through the uncounted path
                report({"ok": False,
                        "error": f"{type(e).__name__}: {e}",
                        "line": line[:200]})

    engine.stop(drain=True)
    for _ in range(nsub):
        pending.acquire()
    snap = engine.metrics.snapshot()
    snap["metric"] = "serve_session"
    with out_lock:
        print(json.dumps(snap), flush=True)
    print(engine.metrics.report(), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
