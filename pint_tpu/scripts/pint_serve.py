"""Offline serving daemon: JSONL requests on stdin -> coalesced
batched dispatches -> JSONL results on stdout.

The demo surface of ``pint_tpu.serve``: each input line is one
request; the threaded ServeEngine coalesces whatever arrives within
the window into padded vmapped dispatches. Request forms:

    {"kind": "fit_step",  "par": P, "tim": T, "id": ..., "deadline_ms": ...}
    {"kind": "residuals", "par": P, "tim": T, ...}
    {"kind": "phase", "par": P, "mjds": [...], "obs": "@",
     "seg_min": 60.0, ...}

(par, tim) pairs are loaded once and cached — repeated requests
against the same pulsar are the serving-state hot path, paying only
the batched solve. Phase requests generate (and cache) polycos
covering the requested MJDs, then split the MJDs per segment into
PhasePredictRequests. ``--demo N`` synthesizes an N-request
mixed-shape workload instead of reading stdin.

One JSON result line per request (input order NOT guaranteed — lines
carry the request id); the final line is the engine metrics snapshot
({"metric": "serve_session", ...}).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading

__all__ = ["main"]


def _load_pair(cache, par, tim):
    key = ("pair", par, tim)
    if key not in cache:
        import warnings

        from pint_tpu.models import get_model_and_toas

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            cache[key] = get_model_and_toas(par, tim)
    return cache[key]


def _polycos_for(cache, par, obs, mjd_lo, mjd_hi, seg_min):
    key = ("polyco", par, obs, round(mjd_lo, 6), round(mjd_hi, 6),
           seg_min)
    if key not in cache:
        import warnings

        from pint_tpu.models import get_model
        from pint_tpu.polycos import Polycos

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model = get_model(par)
            cache[key] = Polycos.generate_polycos(
                model, mjd_lo, mjd_hi, obs, seg_length_min=seg_min)
    return cache[key]


def _submit_line(engine, cache, rec, emit):
    """Parse one request record and submit it; wire result emission
    through the future's done-callback so the daemon never blocks on
    a single request."""
    import numpy as np

    from pint_tpu.serve import (
        FitStepRequest,
        PhasePredictRequest,
        ResidualsRequest,
    )

    rid = rec.get("id")
    kind = rec.get("kind", "fit_step")
    deadline_s = rec["deadline_ms"] / 1e3 \
        if rec.get("deadline_ms") is not None else None

    def finish(kind):
        def cb(fut):
            out = {"id": rid, "kind": kind}
            try:
                res = fut.result(timeout=0)
            except Exception as e:
                out.update(ok=False, error=f"{type(e).__name__}: {e}")
                emit(out)
                return
            out["ok"] = True
            if kind == "fit_step":
                out["chi2"] = res.chi2
                out["chi2_prefit"] = res.chi2r
                out["dparams"] = {n: float(v) for n, v in
                                  zip(res.names, res.dparams)}
                out["errors"] = res.errors()
            elif kind == "residuals":
                out["chi2"] = res.chi2
                out["rms_us"] = res.rms_us
                out["n"] = len(res.time_resids)
            else:
                out["phase_int"] = np.asarray(res.phase_int).tolist()
                out["phase_frac"] = np.asarray(res.phase_frac).tolist()
            emit(out)
        return cb

    if kind in ("fit_step", "residuals"):
        model, toas = _load_pair(cache, rec["par"], rec["tim"])
        cls = FitStepRequest if kind == "fit_step" else ResidualsRequest
        fut = engine.submit(cls(toas, model, deadline_s=deadline_s))
        fut.add_done_callback(finish(kind))
        return 1
    if kind == "phase":
        mjds = np.atleast_1d(np.asarray(rec["mjds"], np.float64))
        seg_min = float(rec.get("seg_min", 60.0))
        pad = seg_min / 1440.0
        pcs = _polycos_for(cache, rec["par"], rec.get("obs", "@"),
                           float(mjds.min()) - pad,
                           float(mjds.max()) + pad, seg_min)
        idx = pcs._entry_for(mjds)
        nsub = 0
        for s in np.unique(idx):
            fut = engine.submit(PhasePredictRequest(
                pcs.entries[int(s)], mjds[idx == s],
                deadline_s=deadline_s))
            fut.add_done_callback(finish("phase"))
            nsub += 1
        return nsub
    raise ValueError(f"unknown request kind {kind!r}")


def _demo_requests(n: int):
    """Synthesize a mixed-shape workload: small simulated pulsars in
    three TOA-count classes + polyco phase reads."""
    import io
    import warnings

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.polycos import PolycoEntry
    from pint_tpu.serve import (
        FitStepRequest,
        PhasePredictRequest,
        ResidualsRequest,
    )
    from pint_tpu.simulation import make_fake_toas_uniform

    sizes = (50, 100, 200)
    pairs = []
    for k, ntoa in enumerate(sizes):
        par = (f"PSR J{1200 + k}\nRAJ 12:0{k}:00.0 1\n"
               f"DECJ 30:0{k}:00.0 1\nF0 {150.0 + 31.0 * k} 1\n"
               f"F1 -1e-15 1\nPEPOCH 55000\nPOSEPOCH 55000\n"
               f"DM {10 + k} 1\nTZRMJD 55000.1\nTZRSITE @\n"
               f"TZRFRQ 1400\nUNITS TDB\n")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m = get_model(io.StringIO(par))
            t = make_fake_toas_uniform(
                54000, 56000, ntoa, m, error_us=1.0, add_noise=True,
                rng=np.random.default_rng(k))
        m.F0.add_delta(1e-10)
        m.invalidate_cache(params_only=True)
        pairs.append((m, t))
    entry = PolycoEntry(psrname="DEMO", tmid=55000.0, rphase_int=1e9,
                        rphase_frac=0.25, f0=200.0, obs="@",
                        span_min=60.0,
                        coeffs=np.array([0.02, 1e-3, -2e-5, 1e-7]))
    reqs = []
    for i in range(n):
        m, t = pairs[i % len(pairs)]
        if i % 7 == 6:
            mjds = 55000.0 + np.linspace(-0.01, 0.01, 24)
            reqs.append(("phase", PhasePredictRequest(entry, mjds)))
        elif i % 3 == 2:
            reqs.append(("residuals", ResidualsRequest(t, m)))
        else:
            reqs.append(("fit_step", FitStepRequest(t, m)))
    return reqs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pint_serve",
        description="JSONL serving daemon over the coalescing "
                    "batch scheduler (pint_tpu.serve)")
    p.add_argument("--window-ms", type=float, default=None,
                   help="coalescing window (default "
                        "$PINT_TPU_SERVE_WINDOW_MS or 5)")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--queue-cap", type=int, default=None)
    p.add_argument("--demo", type=int, default=None, metavar="N",
                   help="serve N synthesized mixed requests instead "
                        "of reading stdin")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.serve import ServeEngine

    engine = ServeEngine(
        window_s=None if args.window_ms is None
        else args.window_ms / 1e3,
        max_batch=args.max_batch, queue_cap=args.queue_cap)

    out_lock = threading.Lock()
    pending = threading.Semaphore(0)
    nsub = 0

    def emit(obj):
        with out_lock:
            print(json.dumps(obj), flush=True)
        pending.release()

    if args.demo is not None:
        reqs = _demo_requests(args.demo)
        engine.start()
        for kind, rq in reqs:
            fut = engine.submit(rq)

            def cb(fut, kind=kind):
                try:
                    fut.result(timeout=0)
                    emit({"kind": kind, "ok": True})
                except Exception as e:
                    emit({"kind": kind, "ok": False, "error": repr(e)})
            fut.add_done_callback(cb)
            nsub += 1
    else:
        engine.start()
        cache: dict = {}
        for line in sys.stdin:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
                nsub += _submit_line(engine, cache, rec, emit)
            except Exception as e:
                # malformed line: report directly (NOT via emit — its
                # semaphore release is the per-submitted-request
                # completion count)
                with out_lock:
                    print(json.dumps(
                        {"ok": False,
                         "error": f"{type(e).__name__}: {e}",
                         "line": line[:200]}), flush=True)

    engine.stop(drain=True)
    for _ in range(nsub):
        pending.acquire()
    snap = engine.metrics.snapshot()
    snap["metric"] = "serve_session"
    with out_lock:
        print(json.dumps(snap), flush=True)
    print(engine.metrics.report(), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
