"""tempo-like command-line fit driver (reference:
src/pint/scripts/pintempo.py): par + tim -> fit -> summary (+ output
par)."""

from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pintempo", description="Fit a timing model to TOAs")
    p.add_argument("parfile")
    p.add_argument("timfile")
    p.add_argument("--outfile", "-o", default=None,
                   help="write the post-fit model to this par file")
    p.add_argument("--fitter", default="auto",
                   choices=["auto", "wls", "gls", "downhill"],
                   help="solver (auto picks from model contents)")
    p.add_argument("--maxiter", type=int, default=None)
    p.add_argument("--plotfile", default=None,
                   help="write a pre/post-fit residual plot (png)")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.fitter import Fitter, WLSFitter
    from pint_tpu.gls import GLSFitter
    from pint_tpu.models import get_model_and_toas
    from pint_tpu.residuals import Residuals

    model, toas = get_model_and_toas(args.parfile, args.timfile)
    print(f"Read {toas.ntoas} TOAs; model {model.name or '?'} with "
          f"{len(model.free_params)} free parameters")
    pre = Residuals(toas, model)
    print(f"Prefit RMS: {pre.rms_weighted() * 1e6:.4f} us")

    if args.fitter == "wls":
        f = WLSFitter(toas, model)
    elif args.fitter == "gls":
        f = GLSFitter(toas, model)
    else:  # auto / downhill both go through Fitter.auto
        f = Fitter.auto(toas, model, downhill=True)
    kw = {} if args.maxiter is None else {"maxiter": args.maxiter}
    f.fit_toas(**kw)
    f.print_summary()
    if f.stats is not None:
        print(str(f.stats))

    if args.plotfile:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        mjd = toas.get_mjds()
        fig, ax = plt.subplots(2, 1, sharex=True, figsize=(8, 6))
        ax[0].errorbar(mjd, 1e6 * pre.time_resids,
                       yerr=toas.get_errors(), fmt=".")
        ax[0].set_ylabel("prefit [us]")
        ax[1].errorbar(mjd, 1e6 * f.resids.time_resids,
                       yerr=toas.get_errors(), fmt=".")
        ax[1].set_ylabel("postfit [us]")
        ax[1].set_xlabel("MJD")
        fig.savefig(args.plotfile, dpi=100)
        print(f"Wrote {args.plotfile}")
    if args.outfile:
        with open(args.outfile, "w") as fh:
            fh.write(model.as_parfile())
        print(f"Wrote {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
