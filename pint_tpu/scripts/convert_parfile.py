"""Convert a par file: binary parameterization, units, and output
format (reference: src/pint/scripts/convert_parfile.py)."""

from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="convert_parfile",
        description="Rewrite a par file, optionally converting the "
                    "binary model (DD<->ELL1, H3/STIG<->M2/SINI, ...)")
    p.add_argument("input_par")
    p.add_argument("-o", "--out", default=None,
                   help="output par file (default: stdout)")
    p.add_argument("--binary", default=None,
                   help="target binary parameterization "
                        "(e.g. ELL1, ELL1H, DD, DDS, DDK, BT)")
    p.add_argument("--allow-tcb", action="store_true",
                   help="accept a TCB par file (converted to TDB); "
                        "without this flag TCB input is refused")
    args = p.parse_args(argv)

    from pint_tpu.config import enable_user_compile_cache

    enable_user_compile_cache()

    from pint_tpu.models import get_model

    model = get_model(args.input_par, allow_tcb=args.allow_tcb)
    if args.binary:
        from pint_tpu.binaryconvert import convert_binary

        model = convert_binary(model, args.binary)
    text = model.as_parfile()
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
        print(f"Wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
