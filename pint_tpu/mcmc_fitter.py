"""MCMC fitting of timing models (and photon-template likelihoods).

Reference: src/pint/mcmc_fitter.py (MCMCFitter,
MCMCFitterAnalyticTemplate) + event_optimize's likelihood. Posterior
machinery comes from BayesianTiming (one vmapped device call per
walker batch); sampling from the in-repo EnsembleSampler.

MCMCFitter samples TOA-likelihood posteriors; PhotonMCMCFitter samples
the unbinned photon-template likelihood sum_i log(w_i f(phi_i(theta)) +
1 - w_i) over timing parameters, with the template fixed (the
event_optimize use case).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.bayesian import BayesianTiming
from pint_tpu.fitter import Fitter
from pint_tpu.sampler import EnsembleSampler

__all__ = ["MCMCFitter", "PhotonMCMCFitter", "CompositeMCMCFitter"]


class MCMCFitter(Fitter):
    """Posterior sampling over the model's free parameters (reference:
    MCMCFitter). fit_toas runs the ensemble and sets parameter values
    to posterior medians with std-dev uncertainties."""

    def __init__(self, toas, model, nwalkers: int = 32,
                 rng: Optional[np.random.Generator] = None):
        super().__init__(toas, model)
        self.bt = BayesianTiming(model, toas)
        self.nwalkers = max(nwalkers, 2 * self.bt.nparams + 2)
        if self.nwalkers % 2:
            self.nwalkers += 1
        self.rng = rng or np.random.default_rng()
        self.sampler = EnsembleSampler(
            self.nwalkers, self.bt.nparams,
            self.bt.lnposterior_batch, rng=self.rng)

    def _init_walkers(self, scatter):
        th0 = self.bt.theta0
        scales = np.empty(self.bt.nparams)
        for k, name in enumerate(self.bt.param_labels):
            p = self.model.get_param(name)
            scales[k] = p.uncertainty if p.uncertainty else \
                max(abs(th0[k]) * 1e-10, 1e-14)
        return th0[None, :] + scatter * scales[None, :] \
            * self.rng.standard_normal((self.nwalkers, self.bt.nparams))

    def fit_toas(self, nsteps: int = 300, burn: Optional[int] = None,
                 scatter: float = 0.5, progress: bool = False):
        import time as _time

        t0 = _time.perf_counter()
        p0 = self._init_walkers(scatter)
        self.sampler.run_mcmc(p0, nsteps, progress=progress)
        burn = nsteps // 3 if burn is None else burn
        flat = self.sampler.get_chain(discard=burn, flat=True)
        med = np.median(flat, axis=0)
        std = np.std(flat, axis=0)
        for k, name in enumerate(self.bt.param_labels):
            p = self.model.get_param(name)
            p.set_dd((float(med[k]), 0.0))
            p.uncertainty = float(std[k])
            self.errors[name] = float(std[k])
        self.model.invalidate_cache(params_only=True)
        from pint_tpu.residuals import Residuals

        self.resids = Residuals(self.toas, self.model)
        chi2 = self.resids.chi2
        self.converged = self.sampler.acceptance_fraction > 0.05
        self._record_stats(chi2, nsteps, t0)
        return chi2


class PhotonMCMCFitter:
    """Sample timing parameters against an unbinned photon-template
    likelihood (reference: MCMCFitterAnalyticTemplate /
    event_optimize). The phase model is re-evaluated per sample via the
    same dd low-word offset trick BayesianTiming uses; the whole walker
    batch is one vmapped device call."""

    def __init__(self, toas, model, template, weights=None,
                 nwalkers: int = 32,
                 rng: Optional[np.random.Generator] = None):
        import jax
        import jax.numpy as jnp

        self.toas = toas
        self.model = model
        self.template = template
        self.param_labels = list(model.free_params)
        self.nparams = len(self.param_labels)
        self.nwalkers = max(nwalkers, 2 * self.nparams + 2)
        if self.nwalkers % 2:
            self.nwalkers += 1
        self.rng = rng or np.random.default_rng()

        from pint_tpu.bayesian import build_batched_phase_eval

        self.theta0, self._tl0, frac_fn = build_batched_phase_eval(
            model, toas)
        w = (jnp.ones(toas.ntoas) if weights is None
             else jnp.asarray(weights, dtype=jnp.float64))
        pdf = template._pdf_fn()
        ttheta = jnp.asarray(template.theta)

        def lnlike_core(tl_eff):
            phases = jnp.mod(frac_fn(tl_eff), 1.0)
            dens = pdf(ttheta, phases)
            return jnp.sum(jnp.log(w * dens + (1.0 - w)))

        self._core_batch = jax.jit(jax.vmap(lnlike_core))
        self.sampler = EnsembleSampler(self.nwalkers, self.nparams,
                                       self._lp_batch, rng=self.rng)

    def _photon_lnlike_batch(self, thetas: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        tl_eff = self._tl0[None, :] + (
            np.asarray(thetas, dtype=np.float64)
            - self.theta0[None, :])
        return np.asarray(self._core_batch(jnp.asarray(tl_eff)))

    def _lp_batch(self, thetas: np.ndarray) -> np.ndarray:
        """Log posterior per walker; subclasses compose extra terms."""
        return self._photon_lnlike_batch(thetas)

    def fit_toas(self, nsteps: int = 300, burn: Optional[int] = None,
                 scatter: float = 1e-9, progress: bool = False):
        scales = np.maximum(np.abs(self.theta0) * scatter, 1e-16)
        p0 = self.theta0[None, :] + scales[None, :] \
            * self.rng.standard_normal((self.nwalkers, self.nparams))
        self.sampler.run_mcmc(p0, nsteps, progress=progress)
        burn = nsteps // 3 if burn is None else burn
        flat = self.sampler.get_chain(discard=burn, flat=True)
        med = np.median(flat, axis=0)
        std = np.std(flat, axis=0)
        self.errors = {}
        for k, name in enumerate(self.param_labels):
            p = self.model.get_param(name)
            p.set_dd((float(med[k]), 0.0))
            p.uncertainty = float(std[k])
            self.errors[name] = float(std[k])
        self.model.invalidate_cache(params_only=True)
        return float(np.max(self.sampler.lnprob))


class CompositeMCMCFitter(PhotonMCMCFitter):
    """Joint radio-TOA + photon-event posterior over one timing model
    (reference: mcmc_fitter.CompositeMCMCFitter): lnpost(theta) =
    lnpost_TOA(theta; radio toas, priors) + lnL_photon(theta; event
    phases, template). Both terms are batched device calls over the
    walker ensemble, so the composite costs two XLA programs per
    half-step regardless of walker count. The two TOA sets are
    independent data on the SAME free-parameter vector
    (model.free_params ordering everywhere; BayesianTiming validates
    the packed order itself)."""

    def __init__(self, toas_radio, toas_events, model, template,
                 weights=None, nwalkers: int = 32,
                 rng: Optional[np.random.Generator] = None):
        super().__init__(toas_events, model, template,
                         weights=weights, nwalkers=nwalkers, rng=rng)
        self.toas = toas_radio
        self.toas_events = toas_events
        self.bt = BayesianTiming(model, toas_radio)

    def _lp_batch(self, thetas: np.ndarray) -> np.ndarray:
        thetas = np.asarray(thetas, dtype=np.float64)
        lp = np.asarray(self.bt.lnposterior_batch(thetas),
                        dtype=np.float64)
        finite = np.isfinite(lp)
        if finite.any():
            ph = self._photon_lnlike_batch(thetas)
            lp = np.where(finite, lp + ph, lp)
        return lp
