"""MCMC fitting of timing models (and photon-template likelihoods).

Reference: src/pint/mcmc_fitter.py (MCMCFitter,
MCMCFitterAnalyticTemplate) + event_optimize's likelihood.

Since ISSUE 9 the fitters here are THIN CONSUMERS of the
``pint_tpu.sampling`` subsystem: the default ``mode="scan"`` runs the
whole ensemble chain on-device as chunked supervised ``lax.scan``
dispatches (``sampling.DeviceEnsembleSampler`` over a
``sampling.DevicePosterior``), and ``sample_noise=True`` lifts the GP
noise hyperparameters (PLRedNoise log10_A/gamma, ECORR weights) into
the sampled dimensions. ``mode="host"`` keeps the original host-loop
``EnsembleSampler`` (two vmapped dispatches per step) — the path
host-side posterior callables (CompositeMCMCFitter's mixed
radio+photon sum) still require.

MCMCFitter samples TOA-likelihood posteriors; PhotonMCMCFitter samples
the unbinned photon-template likelihood sum_i log(w_i f(phi_i(theta)) +
1 - w_i) over timing parameters, with the template fixed (the
event_optimize use case).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.bayesian import BayesianTiming
from pint_tpu.fitter import Fitter
from pint_tpu.sampler import EnsembleSampler

__all__ = ["MCMCFitter", "PhotonMCMCFitter", "CompositeMCMCFitter"]


def _run_sampler(fitter, p0, nsteps: int, progress: bool):
    """Run the fitter's sampler, host or device: the device sampler's
    positional PRNG stream is anchored by a seed drawn from the
    fitter's numpy generator, so a seeded fitter stays reproducible
    in every mode."""
    if isinstance(fitter.sampler, EnsembleSampler):
        fitter.sampler.run_mcmc(p0, nsteps, progress=progress)
    else:
        seed = int(fitter.rng.integers(0, 2 ** 31 - 1))
        fitter.sampler.run_mcmc(p0, nsteps, seed=seed,
                                mode=fitter.mode, progress=progress)


class MCMCFitter(Fitter):
    """Posterior sampling over the model's free parameters (reference:
    MCMCFitter). fit_toas runs the ensemble and sets parameter values
    to posterior medians with std-dev uncertainties.

    ``mode``: "scan" (default — whole-chain-on-device, one supervised
    dispatch per chain chunk), "host_loop" (the same device kernel
    driven one step per dispatch: the bit-equality oracle), or "host"
    (the pre-ISSUE-9 host ensemble over
    ``BayesianTiming.lnposterior_batch``). ``sample_noise=True``
    (device modes only) appends the model's GP noise hyperparameters
    to the sampled dimensions; their posterior medians land in
    ``self.noise_estimates`` rather than in the timing model."""

    def __init__(self, toas, model, nwalkers: int = 32,
                 rng: Optional[np.random.Generator] = None,
                 mode: str = "scan", sample_noise: bool = False):
        super().__init__(toas, model)
        self.mode = mode
        self.rng = rng or np.random.default_rng()
        self.noise_estimates: dict = {}
        if mode == "host":
            if sample_noise:
                raise ValueError(
                    "sample_noise requires a device mode (the host "
                    "sampler consumes the fixed-noise posterior)")
            self.post = None
            self.bt = BayesianTiming(model, toas)
            ndim = self.bt.nparams
            self.param_labels = list(self.bt.param_labels)
            self.ntiming = ndim
        else:
            from pint_tpu.sampling import DevicePosterior

            self.post = DevicePosterior(model, toas,
                                        sample_noise=sample_noise)
            self.bt = self.post.bt
            ndim = self.post.nparams
            self.param_labels = list(self.post.param_labels)
            self.ntiming = self.post.ntiming
        self.nwalkers = max(nwalkers, 2 * ndim + 2)
        if self.nwalkers % 2:
            self.nwalkers += 1
        if mode == "host":
            self.sampler = EnsembleSampler(
                self.nwalkers, ndim,
                self.bt.lnposterior_batch, rng=self.rng)
        else:
            from pint_tpu.sampling import DeviceEnsembleSampler

            self.sampler = DeviceEnsembleSampler(
                self.nwalkers, ndim, self.post.lnpost_batch)

    def _init_walkers(self, scatter):
        if self.post is not None:
            return self.post.init_walkers(self.nwalkers,
                                          rng=self.rng,
                                          scatter=scatter)
        th0 = self.bt.theta0
        scales = np.empty(self.bt.nparams)
        for k, name in enumerate(self.bt.param_labels):
            p = self.model.get_param(name)
            scales[k] = p.uncertainty if p.uncertainty else \
                max(abs(th0[k]) * 1e-10, 1e-14)
        return th0[None, :] + scatter * scales[None, :] \
            * self.rng.standard_normal((self.nwalkers, self.bt.nparams))

    def fit_toas(self, nsteps: int = 300, burn: Optional[int] = None,
                 scatter: float = 0.5, progress: bool = False):
        import time as _time

        t0 = _time.perf_counter()
        p0 = self._init_walkers(scatter)
        _run_sampler(self, p0, nsteps, progress)
        burn = nsteps // 3 if burn is None else burn
        flat = self.sampler.get_chain(discard=burn, flat=True)
        med = np.median(flat, axis=0)
        std = np.std(flat, axis=0)
        for k, name in enumerate(self.param_labels):
            if k >= self.ntiming:
                # sampled noise hyperparameters: reported, never
                # written into the timing model's parameter values
                self.noise_estimates[name] = {
                    "median": float(med[k]), "std": float(std[k])}
                continue
            p = self.model.get_param(name)
            p.set_dd((float(med[k]), 0.0))
            p.uncertainty = float(std[k])
            self.errors[name] = float(std[k])
        self.model.invalidate_cache(params_only=True)
        from pint_tpu.residuals import Residuals

        self.resids = Residuals(self.toas, self.model)
        chi2 = self.resids.chi2
        self.converged = self.sampler.acceptance_fraction > 0.05
        self._record_stats(chi2, nsteps, t0)
        return chi2


class PhotonMCMCFitter:
    """Sample timing parameters against an unbinned photon-template
    likelihood (reference: MCMCFitterAnalyticTemplate /
    event_optimize). The phase model is re-evaluated per sample via the
    same dd low-word offset trick BayesianTiming uses; the whole walker
    batch is one vmapped device call."""

    def __init__(self, toas, model, template, weights=None,
                 nwalkers: int = 32,
                 rng: Optional[np.random.Generator] = None,
                 mode: str = "scan"):
        import jax
        import jax.numpy as jnp

        self.toas = toas
        self.model = model
        self.template = template
        self.mode = mode
        self.param_labels = list(model.free_params)
        self.nparams = len(self.param_labels)
        self.nwalkers = max(nwalkers, 2 * self.nparams + 2)
        if self.nwalkers % 2:
            self.nwalkers += 1
        self.rng = rng or np.random.default_rng()

        from pint_tpu.bayesian import build_batched_phase_eval

        self.theta0, self._tl0, frac_fn = build_batched_phase_eval(
            model, toas)
        w = (jnp.ones(toas.ntoas) if weights is None
             else jnp.asarray(weights, dtype=jnp.float64))
        pdf = template._pdf_fn()
        ttheta = jnp.asarray(template.theta)

        def lnlike_core(tl_eff):
            phases = jnp.mod(frac_fn(tl_eff), 1.0)
            dens = pdf(ttheta, phases)
            return jnp.sum(jnp.log(w * dens + (1.0 - w)))

        self._core_batch = jax.jit(jax.vmap(lnlike_core))
        if mode == "host":
            self.sampler = EnsembleSampler(
                self.nwalkers, self.nparams, self._lp_batch,
                rng=self.rng)
        else:
            # whole-chain-on-device (ISSUE 9): the photon likelihood
            # is already a traced core, so it composes directly into
            # the chain kernel's lax.scan — the dd low-word offset
            # mapping rides inside the trace
            from pint_tpu.sampling import DeviceEnsembleSampler

            th0_j = jnp.asarray(self.theta0)
            tl0_j = jnp.asarray(self._tl0)

            def lnpost_one(theta):
                return lnlike_core(tl0_j + (theta - th0_j))

            self.sampler = DeviceEnsembleSampler(
                self.nwalkers, self.nparams, jax.vmap(lnpost_one))

    def _photon_lnlike_batch(self, thetas: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        tl_eff = self._tl0[None, :] + (
            np.asarray(thetas, dtype=np.float64)
            - self.theta0[None, :])
        return np.asarray(self._core_batch(jnp.asarray(tl_eff)))

    def _lp_batch(self, thetas: np.ndarray) -> np.ndarray:
        """Log posterior per walker; subclasses compose extra terms."""
        return self._photon_lnlike_batch(thetas)

    def fit_toas(self, nsteps: int = 300, burn: Optional[int] = None,
                 scatter: float = 1e-9, progress: bool = False):
        scales = np.maximum(np.abs(self.theta0) * scatter, 1e-16)
        p0 = self.theta0[None, :] + scales[None, :] \
            * self.rng.standard_normal((self.nwalkers, self.nparams))
        _run_sampler(self, p0, nsteps, progress)
        burn = nsteps // 3 if burn is None else burn
        flat = self.sampler.get_chain(discard=burn, flat=True)
        med = np.median(flat, axis=0)
        std = np.std(flat, axis=0)
        self.errors = {}
        for k, name in enumerate(self.param_labels):
            p = self.model.get_param(name)
            p.set_dd((float(med[k]), 0.0))
            p.uncertainty = float(std[k])
            self.errors[name] = float(std[k])
        self.model.invalidate_cache(params_only=True)
        return float(np.max(self.sampler.lnprob))


class CompositeMCMCFitter(PhotonMCMCFitter):
    """Joint radio-TOA + photon-event posterior over one timing model
    (reference: mcmc_fitter.CompositeMCMCFitter): lnpost(theta) =
    lnpost_TOA(theta; radio toas, priors) + lnL_photon(theta; event
    phases, template). Both terms are batched device calls over the
    walker ensemble, so the composite costs two XLA programs per
    half-step regardless of walker count. The two TOA sets are
    independent data on the SAME free-parameter vector
    (model.free_params ordering everywhere; BayesianTiming validates
    the packed order itself)."""

    def __init__(self, toas_radio, toas_events, model, template,
                 weights=None, nwalkers: int = 32,
                 rng: Optional[np.random.Generator] = None):
        # mode="host": the composite posterior mixes two device
        # evaluations with a host-side finite-mask combine, so it is
        # a host CALLABLE, not a traced core — the one fitter shape
        # the whole-chain kernel cannot absorb
        super().__init__(toas_events, model, template,
                         weights=weights, nwalkers=nwalkers, rng=rng,
                         mode="host")
        self.toas = toas_radio
        self.toas_events = toas_events
        self.bt = BayesianTiming(model, toas_radio)

    def _lp_batch(self, thetas: np.ndarray) -> np.ndarray:
        thetas = np.asarray(thetas, dtype=np.float64)
        lp = np.asarray(self.bt.lnposterior_batch(thetas),
                        dtype=np.float64)
        finite = np.isfinite(lp)
        if finite.any():
            ph = self._photon_lnlike_batch(thetas)
            lp = np.where(finite, lp + ph, lp)
        return lp
