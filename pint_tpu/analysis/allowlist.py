"""graftlint allowlist — every suppression carries its justification.

Policy (ARCHITECTURE.md "Static analysis"): an entry is a REVIEWED
decision that a finding is a false positive or a sanctioned exception,
never a convenience. Each entry must say WHY the flagged pattern is
safe. Stale entries (ones that no longer suppress anything) fail the
lint run, so this list cannot accumulate dead weight.

Entry fields:
  rule      the rule id (G1..G8)
  file      repo-relative path the finding is in
  match     substring of the flagged source line (anchors the entry to
            the code, not to a line number that churns)
  why       the written justification
  max_hits  optional, default 1: an entry suppresses at most this many
            violations — a NEW finding sharing the substring surfaces
            for its own review instead of riding an old justification
"""

ALLOWLIST = [
    # ------------------------------------------------------------ G7
    dict(rule="G7", file="tools/tpu_capture.py",
         match="jax.config.update(\"jax_enable_x64\"",
         why="tpu_capture IS an entry point: it is the on-chip "
             "benchmark driver launched as its own process by "
             "tpu_watcher.sh, and must pin x64 before any trace; no "
             "library code imports it"),
    # ------------------------------------------- G6 (dispatch layer)
    dict(rule="G6", file="pint_tpu/config.py",
         match="float(f(x))", max_hits=2,
         why="dispatch_rtt_ms's trivial probe dispatch IS the "
             "supervisor's own sizing input — routing it through the "
             "supervisor would recurse into the deadline prediction "
             "that needs it. The supervisor bounds it from outside: "
             "DispatchSupervisor._measure_rtt_guarded runs this "
             "whole function on the guarded worker under the "
             "breaker-probe timeout; remaining direct callers "
             "(auto_steps_per_dispatch on an accelerator) run after "
             "the session-start bounded-probe protocol"),
]
