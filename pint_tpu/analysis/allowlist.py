"""graftlint allowlist — every suppression carries its justification.

Policy (ARCHITECTURE.md "Static analysis"): an entry is a REVIEWED
decision that a finding is a false positive or a sanctioned exception,
never a convenience. Each entry must say WHY the flagged pattern is
safe. Stale entries (ones that no longer suppress anything) fail the
lint run, so this list cannot accumulate dead weight.

Entry fields:
  rule      the rule id (G1..G8)
  file      repo-relative path the finding is in
  match     substring of the flagged source line (anchors the entry to
            the code, not to a line number that churns)
  why       the written justification
  max_hits  optional, default 1: an entry suppresses at most this many
            violations — a NEW finding sharing the substring surfaces
            for its own review instead of riding an old justification
"""

ALLOWLIST = [
    # ------------------------------------------------------------ G7
    dict(rule="G7", file="tools/tpu_capture.py",
         match="jax.config.update(\"jax_enable_x64\"",
         why="tpu_capture IS an entry point: it is the on-chip "
             "benchmark driver launched as its own process by "
             "tpu_watcher.sh, and must pin x64 before any trace; no "
             "library code imports it"),
    # ------------------------------------------------------------ G10
    # Reviewed trace-constant captures. The common shape: a builder
    # computes a REFERENCE point from the current parameter values,
    # bakes it into the traced closure, and the runtime arguments
    # carry only deltas/substitutions against it. Staleness is
    # structurally impossible in each case because the reference and
    # the closure are (re)built together — the exact situation the
    # pv-convention's "values are runtime args" rule is relaxing for.
    dict(rule="G10", file="pint_tpu/parallel/fit_step.py",
         match="def parts_fn(th, tl, fh, fl, batch, cache",
         max_hits=2,
         why="parts_fn (the assembly half the step and the "
             "streaming accumulator share) captures `afn`/`f0_ref`: "
             "the anchored delta-"
             "phase convention — build_anchor computes the exact "
             "reference ONCE on the host and the step's (th, tl) "
             "arguments carry only theta - theta_ref; the anchor "
             "closure and reference are committed together (the "
             "commit-only-after-success block), and every "
             "build_fit_step call rebuilds both"),
    dict(rule="G10", file="pint_tpu/parallel/fit_step.py",
         match="def make_pv(thx, tlx, fhx, flx):", max_hits=3,
         why="make_pv captures `th0_c`/`tl0_c`/`ref32_c`: the "
             "anchored reference pairs the auxiliary DM channel "
             "reconstructs absolute pv values from (ref + delta). "
             "Same build-together lifetime as step_fn's anchor; the "
             "dd32 copy exists so the f32 Jacobian path reconstructs "
             "in its own dtype"),
    dict(rule="G10", file="pint_tpu/models/timing_model.py",
         match="def fn(dth, dtl, fh, fl, batch, cache):", max_hits=2,
         why="_build_anchored_fn's closure captures `ref64`/`ref32`: "
             "these ARE the anchored convention's baked statics — "
             "(dth, dtl) arguments are exact host-computed deltas "
             "against them. Rebuilt with every _build_anchored_fn "
             "call (build_fit_step rebuilds anchor + closure "
             "atomically)"),
    dict(rule="G10", file="pint_tpu/models/timing_model.py",
         match="def phase_of(x):", max_hits=4,
         why="d_phase_d_param's one-shot jacfwd probe captures the "
             "CURRENT packed values (th/tl/fh/fl) by design: the "
             "closure is built, differentiated at that point, and "
             "discarded within a single call — no later call can "
             "observe a stale capture"),
    dict(rule="G10", file="pint_tpu/bayesian.py",
         match="def frac_fn(tl_eff):", max_hits=3,
         why="the dd-low-word sampling convention: the sampled theta "
             "enters ONLY through tl_eff (a runtime arg) while "
             "th0_j and the frozen pairs are the baked reference "
             "point — deliberately, so XLA cannot constant-fold the "
             "tiny low word and every representable theta evaluates "
             "exactly (build_batched_phase_eval docstring). "
             "Reference and closure are built together per call"),
    dict(rule="G10", file="pint_tpu/bayesian.py",
         match="def lnlike_core(tl_eff):",
         why="lnlike_core bakes `f0` (reference F0) as the turns->"
             "seconds scale of the whitened residuals: the error of "
             "using F0_ref instead of the sampled F0 is second-order "
             "in the sampled delta (delta_F0/F0 ~ 1e-12 at MSP "
             "precision) — same reviewed convention as frac_fn's "
             "baked reference point, rebuilt per BayesianTiming "
             "construction"),
    dict(rule="G10", file="pint_tpu/sampling/likelihood.py",
         match="def lnlike_core(tl_eff, eta):",
         why="the noise-sampled lnlike_core bakes `f0` (reference "
             "F0) as the turns->seconds scale of the whitened "
             "residuals — the identical reviewed convention as "
             "bayesian.py's fixed-noise lnlike_core (second-order "
             "error in the sampled delta, delta_F0/F0 ~ 1e-12), "
             "rebuilt per SampledNoiseLikelihood construction"),
    dict(rule="G10", file="pint_tpu/gridutils.py",
         match="def eval_node(gvals):", max_hits=2,
         why="the grid evaluator captures the frozen baseline pairs "
             "(fh0/fl_z) and substitutes node coordinates through "
             "the runtime `gvals` argument (fh0.at[gidx].set) — the "
             "gridded params were just frozen by _build_grid_eval "
             "itself, and the closure dies with the grid call"),
    # ------------------------------------------- G6 (dispatch layer)
    dict(rule="G6", file="pint_tpu/config.py",
         match="float(f(x))", max_hits=2,
         why="dispatch_rtt_ms's trivial probe dispatch IS the "
             "supervisor's own sizing input — routing it through the "
             "supervisor would recurse into the deadline prediction "
             "that needs it. The supervisor bounds it from outside: "
             "DispatchSupervisor._measure_rtt_guarded runs this "
             "whole function on the guarded worker under the "
             "breaker-probe timeout; remaining direct callers "
             "(auto_steps_per_dispatch on an accelerator) run after "
             "the session-start bounded-probe protocol"),
]
