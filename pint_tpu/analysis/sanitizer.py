"""Runtime compile/dispatch sanitizer.

Reference invariant (CLAUDE.md "Conventions"): parameter VALUES are
runtime args, never trace constants — ``invalidate_cache(
params_only=True)`` must NOT drop the jit. Before this module the
invariant was only enforced by a comment; a regression (the config-1
bench slowdown that motivated the compile key) re-traced every fitter
iteration and no test failed. ``Sanitizer`` makes the compile count
observable:

- it wraps ``TimingModel._get_compiled`` / ``_get_compiled_jac``
  class-wide for the duration of the context and counts every time a
  FRESH jitted closure is built (object identity change), per model
  and per kind ("phase"/"jac");
- ``watch(jitted, label)`` snapshots a ``jax.jit`` wrapper's
  ``_cache_size()`` so executable-level recompiles (shape/dtype/
  static-arg churn) are attributable per call site;
- ``wrap(fn, label, expect_device=..., nan_check=...)`` returns a
  call-through proxy that records operand leaves crossing host<->
  device unexpectedly (np.ndarray operands entering a device
  dispatch mean an implicit, per-call H2D transfer) and optionally
  blocks on the outputs to assert they are finite (debug only — the
  sync defeats dispatch pipelining). The operand scan walks NESTED
  structures — dicts/tuples/lists, NamedTuple pytrees (DD), and
  plain objects that are not registered pytrees (request/entry
  dataclasses reaching the serve bucket dispatch hide their arrays
  from ``jax.tree_util.tree_leaves``, which treats an unregistered
  object as one opaque leaf);
- ``dtype_probe()`` is the runtime half of graftflow's differential
  validation (ISSUE 6): for the duration of the context it
  intercepts the registered precision-boundary functions of the
  production fit step (``parallel.fit_step._symm_mm`` /
  ``dd_to_dd32`` / ``dd_frac`` and
  ``TimingModel.linear_design_columns``) and records the dtypes of
  TRACED operands flowing through them. Tracing a built step under
  the probe (``jax.eval_shape(step_fn, *args)``) yields an observed
  profile to compare against ``graftflow.predict_profile(...)`` —
  the analyzer predicts, the trace confirms
  (tests/test_dtype_probe.py).

Usage::

    with Sanitizer() as san:
        ... sweep parameter values, re-evaluate ...
    assert san.compiles("phase") == 1   # one build, N reuses

The pytest fixture ``recompile_guard`` (tests/conftest.py) wraps the
test body in a Sanitizer; the test itself asserts on
``.compiles()``/``.builds`` (the fixture deliberately does not
auto-fail — what counts as "expected" is per-test).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["Sanitizer", "SanitizerError"]


class SanitizerError(AssertionError):
    """A sanitizer invariant (finite outputs, expected operand
    placement) failed."""


@dataclass
class _WatchEntry:
    jitted: object
    label: str
    start: Optional[int]


@dataclass
class Sanitizer:
    """Context manager counting jit builds and flagging stray host
    operands / NaN outputs. Re-entrant use is not supported (the
    class-level patch is process-global while active)."""

    nan_check: bool = False
    # (model id, kind) -> build count
    builds: Dict[Tuple[int, str], int] = field(default_factory=dict)
    host_crossings: List[Tuple[str, int]] = field(default_factory=list)
    # (probe label, dtype name) records from dtype_probe()
    dtype_records: List[Tuple[str, str]] = field(default_factory=list)
    _watches: List[_WatchEntry] = field(default_factory=list)
    _saved: Optional[tuple] = None

    # -------------------------------------------------- compile count

    def __enter__(self) -> "Sanitizer":
        from pint_tpu.models.timing_model import TimingModel

        if self._saved is not None:
            raise RuntimeError("Sanitizer is not re-entrant")
        orig_phase = TimingModel._get_compiled
        orig_jac = TimingModel._get_compiled_jac
        san = self

        def patched_phase(model, *a, **kw):
            # pass-through signature: _get_compiled grew an optional
            # donate_argnums parameter (ISSUE 7) and the wrapper must
            # not strip it from opted-in callers
            before = model._jit_phase
            fn = orig_phase(model, *a, **kw)
            if fn is not before:
                san._record(model, "phase")
            return fn

        def patched_jac(model, *a, **kw):
            before = model._jit_jac
            fn = orig_jac(model, *a, **kw)
            if fn is not before:
                san._record(model, "jac")
            return fn

        TimingModel._get_compiled = patched_phase
        TimingModel._get_compiled_jac = patched_jac
        self._saved = (TimingModel, orig_phase, orig_jac)
        return self

    def __exit__(self, *exc):
        TimingModel, orig_phase, orig_jac = self._saved
        TimingModel._get_compiled = orig_phase
        TimingModel._get_compiled_jac = orig_jac
        self._saved = None
        return False

    def _record(self, model, kind: str):
        key = (id(model), kind)
        self.builds[key] = self.builds.get(key, 0) + 1

    def compiles(self, kind: Optional[str] = None) -> int:
        """Total fresh jit builds observed (optionally one kind)."""
        return sum(n for (_, k), n in self.builds.items()
                   if kind is None or k == kind)

    def reset(self):
        """Zero the counters (e.g. after a deliberate warm-up phase
        inside the context)."""
        self.builds.clear()
        self.host_crossings.clear()

    # ------------------------------------------------ executable count

    def watch(self, jitted, label: str = "") -> None:
        """Track a jax.jit wrapper's executable cache growth."""
        self._watches.append(_WatchEntry(
            jitted, label or repr(jitted), _cache_size(jitted)))

    def executable_growth(self) -> Dict[str, Optional[int]]:
        """label -> newly compiled executables since watch() (None
        when the running jax does not expose _cache_size)."""
        out = {}
        for w in self._watches:
            now = _cache_size(w.jitted)
            out[w.label] = (None if w.start is None or now is None
                            else now - w.start)
        return out

    # ------------------------------------------------ dispatch checks

    def wrap(self, fn, label: str = "", expect_device: bool = True):
        """Call-through proxy recording host-array operands (an
        implicit H2D copy per dispatch when expect_device) and, with
        nan_check, blocking to verify finite outputs. The operand
        scan recurses through nested pytree leaves AND unregistered
        container objects (see _count_host_arrays) — serve bucket
        dispatches carry dicts/tuples of operands and request/entry
        objects that tree_leaves treats as opaque leaves."""
        import jax
        import numpy as np

        san = self
        name = label or getattr(fn, "__name__", repr(fn))

        def guarded(*args, **kw):
            if expect_device:
                nhost = _count_host_arrays((args, kw))
                if nhost:
                    san.host_crossings.append((name, nhost))
            out = fn(*args, **kw)
            if san.nan_check:
                bad = [i for i, leaf in
                       enumerate(jax.tree_util.tree_leaves(out))
                       if np.issubdtype(np.asarray(leaf).dtype,
                                        np.floating)
                       and not np.all(np.isfinite(np.asarray(leaf)))]
                if bad:
                    raise SanitizerError(
                        f"{name}: non-finite output leaves {bad}")
            return out

        return guarded

    def assert_no_host_crossings(self):
        if self.host_crossings:
            raise SanitizerError(
                f"host ndarray operands entered device dispatches: "
                f"{self.host_crossings} — convert once with "
                f"jnp.asarray at build time, not per call")

    # ------------------------------------------------- dtype probing

    def dtype_probe(self):
        """Context manager: intercept the registered precision-
        boundary functions (analysis/precision_registry.PROBES) and
        record (label, dtype) for every TRACED operand that crosses
        them. Trace a built production step inside the context —
        ``jax.eval_shape(step_fn, *args)`` is enough, no compile —
        then compare ``observed_profile()`` against
        ``graftflow.predict_profile(...)``. Records only tracers, so
        host-side build work (the anchor's numpy dd32 splits) never
        pollutes the profile."""
        import contextlib

        import jax

        import pint_tpu.parallel.fit_step as _fs
        from pint_tpu.models.timing_model import TimingModel

        _Tracer = getattr(jax.core, "Tracer", None)
        san = self

        def traced(x):
            if _Tracer is not None:
                return isinstance(x, _Tracer)
            # jax moved/removed jax.core.Tracer: duck-type — every
            # tracer class is named *Tracer and carries an aval;
            # concrete arrays are ArrayImpl and fail the name test
            return type(x).__name__.endswith("Tracer") and \
                hasattr(x, "aval")

        orig_symm = _fs._symm_mm
        orig_dd32 = _fs.dd_to_dd32
        orig_frac = _fs.dd_frac
        orig_cols = TimingModel.linear_design_columns

        def symm_mm(X, Y, f32):
            if traced(X):
                san.dtype_records.append(("symm_mm", X.dtype.name))
                if f32:
                    san.dtype_records.append(
                        ("symm_mm_f32", "float32"))
            return orig_symm(X, Y, f32)

        def dd32(a):
            out = orig_dd32(a)
            if traced(out.hi):
                san.dtype_records.append(
                    ("dd32_split", out.hi.dtype.name))
            return out

        def frac(a):
            if traced(a.hi):
                san.dtype_records.append(
                    ("phase_frac", a.hi.dtype.name))
            return orig_frac(a)

        def cols(model, pv, batch, cache, names):
            if traced(batch.freq_mhz):
                san.dtype_records.append(
                    ("linear_design_columns",
                     batch.freq_mhz.dtype.name))
            return orig_cols(model, pv, batch, cache, names)

        @contextlib.contextmanager
        def _ctx():
            _fs._symm_mm = symm_mm
            _fs.dd_to_dd32 = dd32
            _fs.dd_frac = frac
            TimingModel.linear_design_columns = cols
            try:
                yield san
            finally:
                _fs._symm_mm = orig_symm
                _fs.dd_to_dd32 = orig_dd32
                _fs.dd_frac = orig_frac
                TimingModel.linear_design_columns = orig_cols

        return _ctx()

    def observed_profile(self) -> Dict[str, dict]:
        """{probe label: {"active": True, "dtypes": set}} from the
        dtype records — absent labels mean the boundary never fired
        during the probed trace."""
        out: Dict[str, dict] = {}
        for label, dt in self.dtype_records:
            d = out.setdefault(label, {"active": True,
                                       "dtypes": set()})
            d["dtypes"].add(dt)
        return out


def _count_host_arrays(obj) -> int:
    """np.ndarray count (subclasses included) across nested pytree
    leaves AND plain container objects. jax.tree_util.tree_leaves
    descends registered pytrees only — an unregistered request/entry
    object is one opaque leaf and its member arrays would escape the
    scan (the serve bucket dispatch carries exactly such operands)."""
    import jax
    import numpy as np

    count = 0
    seen = set()
    stack = [(obj, 0)]
    while stack:
        cur, depth = stack.pop()
        if depth > 8 or id(cur) in seen:
            continue
        if isinstance(cur, (str, bytes, int, float, bool,
                            complex)) or cur is None:
            continue
        seen.add(id(cur))
        if isinstance(cur, jax.Array):
            continue
        if isinstance(cur, np.ndarray):
            count += 1
            continue
        if isinstance(cur, dict):
            stack.extend((v, depth + 1) for v in cur.values())
            continue
        if isinstance(cur, (list, tuple, set, frozenset)):
            stack.extend((v, depth + 1) for v in cur)
            continue
        d = getattr(cur, "__dict__", None)
        if isinstance(d, dict) and not isinstance(cur, type) and \
                not callable(cur):
            stack.extend((v, depth + 1) for v in d.values())
    return count


def _cache_size(jitted) -> Optional[int]:
    try:
        return int(jitted._cache_size())
    except AttributeError:
        return None
