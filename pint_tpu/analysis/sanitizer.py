"""Runtime compile/dispatch sanitizer.

Reference invariant (CLAUDE.md "Conventions"): parameter VALUES are
runtime args, never trace constants — ``invalidate_cache(
params_only=True)`` must NOT drop the jit. Before this module the
invariant was only enforced by a comment; a regression (the config-1
bench slowdown that motivated the compile key) re-traced every fitter
iteration and no test failed. ``Sanitizer`` makes the compile count
observable:

- it wraps ``TimingModel._get_compiled`` / ``_get_compiled_jac``
  class-wide for the duration of the context and counts every time a
  FRESH jitted closure is built (object identity change), per model
  and per kind ("phase"/"jac");
- ``watch(jitted, label)`` snapshots a ``jax.jit`` wrapper's
  ``_cache_size()`` so executable-level recompiles (shape/dtype/
  static-arg churn) are attributable per call site;
- ``wrap(fn, label, expect_device=..., nan_check=...)`` returns a
  call-through proxy that records operand leaves crossing host<->
  device unexpectedly (np.ndarray operands entering a device
  dispatch mean an implicit, per-call H2D transfer) and optionally
  blocks on the outputs to assert they are finite (debug only — the
  sync defeats dispatch pipelining).

Usage::

    with Sanitizer() as san:
        ... sweep parameter values, re-evaluate ...
    assert san.compiles("phase") == 1   # one build, N reuses

The pytest fixture ``recompile_guard`` (tests/conftest.py) wraps the
test body in a Sanitizer; the test itself asserts on
``.compiles()``/``.builds`` (the fixture deliberately does not
auto-fail — what counts as "expected" is per-test).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["Sanitizer", "SanitizerError"]


class SanitizerError(AssertionError):
    """A sanitizer invariant (finite outputs, expected operand
    placement) failed."""


@dataclass
class _WatchEntry:
    jitted: object
    label: str
    start: Optional[int]


@dataclass
class Sanitizer:
    """Context manager counting jit builds and flagging stray host
    operands / NaN outputs. Re-entrant use is not supported (the
    class-level patch is process-global while active)."""

    nan_check: bool = False
    # (model id, kind) -> build count
    builds: Dict[Tuple[int, str], int] = field(default_factory=dict)
    host_crossings: List[Tuple[str, int]] = field(default_factory=list)
    _watches: List[_WatchEntry] = field(default_factory=list)
    _saved: Optional[tuple] = None

    # -------------------------------------------------- compile count

    def __enter__(self) -> "Sanitizer":
        from pint_tpu.models.timing_model import TimingModel

        if self._saved is not None:
            raise RuntimeError("Sanitizer is not re-entrant")
        orig_phase = TimingModel._get_compiled
        orig_jac = TimingModel._get_compiled_jac
        san = self

        def patched_phase(model):
            before = model._jit_phase
            fn = orig_phase(model)
            if fn is not before:
                san._record(model, "phase")
            return fn

        def patched_jac(model):
            before = model._jit_jac
            fn = orig_jac(model)
            if fn is not before:
                san._record(model, "jac")
            return fn

        TimingModel._get_compiled = patched_phase
        TimingModel._get_compiled_jac = patched_jac
        self._saved = (TimingModel, orig_phase, orig_jac)
        return self

    def __exit__(self, *exc):
        TimingModel, orig_phase, orig_jac = self._saved
        TimingModel._get_compiled = orig_phase
        TimingModel._get_compiled_jac = orig_jac
        self._saved = None
        return False

    def _record(self, model, kind: str):
        key = (id(model), kind)
        self.builds[key] = self.builds.get(key, 0) + 1

    def compiles(self, kind: Optional[str] = None) -> int:
        """Total fresh jit builds observed (optionally one kind)."""
        return sum(n for (_, k), n in self.builds.items()
                   if kind is None or k == kind)

    def reset(self):
        """Zero the counters (e.g. after a deliberate warm-up phase
        inside the context)."""
        self.builds.clear()
        self.host_crossings.clear()

    # ------------------------------------------------ executable count

    def watch(self, jitted, label: str = "") -> None:
        """Track a jax.jit wrapper's executable cache growth."""
        self._watches.append(_WatchEntry(
            jitted, label or repr(jitted), _cache_size(jitted)))

    def executable_growth(self) -> Dict[str, Optional[int]]:
        """label -> newly compiled executables since watch() (None
        when the running jax does not expose _cache_size)."""
        out = {}
        for w in self._watches:
            now = _cache_size(w.jitted)
            out[w.label] = (None if w.start is None or now is None
                            else now - w.start)
        return out

    # ------------------------------------------------ dispatch checks

    def wrap(self, fn, label: str = "", expect_device: bool = True):
        """Call-through proxy recording host-array operands (an
        implicit H2D copy per dispatch when expect_device) and, with
        nan_check, blocking to verify finite outputs."""
        import jax
        import numpy as np

        san = self
        name = label or getattr(fn, "__name__", repr(fn))

        def guarded(*args, **kw):
            if expect_device:
                nhost = sum(
                    1 for leaf in jax.tree_util.tree_leaves((args, kw))
                    if type(leaf) is np.ndarray)
                if nhost:
                    san.host_crossings.append((name, nhost))
            out = fn(*args, **kw)
            if san.nan_check:
                bad = [i for i, leaf in
                       enumerate(jax.tree_util.tree_leaves(out))
                       if np.issubdtype(np.asarray(leaf).dtype,
                                        np.floating)
                       and not np.all(np.isfinite(np.asarray(leaf)))]
                if bad:
                    raise SanitizerError(
                        f"{name}: non-finite output leaves {bad}")
            return out

        return guarded

    def assert_no_host_crossings(self):
        if self.host_crossings:
            raise SanitizerError(
                f"host ndarray operands entered device dispatches: "
                f"{self.host_crossings} — convert once with "
                f"jnp.asarray at build time, not per call")


def _cache_size(jitted) -> Optional[int]:
    try:
        return int(jitted._cache_size())
    except AttributeError:
        return None
