"""graftflow — dataflow precision & trace-constant analysis (G9/G10).

Reference: the two historical bug classes the per-node graftlint
rules (G1-G8) cannot see, because both are *dataflow* properties:

- **G9 (precision demotion)**: TPU f64 is software-emulated and not
  correctly rounded (~2^-48 — PAPER.md / ARCHITECTURE.md "Precision
  strategy"), so the residual path runs dd error-free transforms and
  only *engineered* boundaries may demote to f32 (the build_fit_step
  jac_f32 / matmul_f32 sites, the Pallas Z^2 kernel). graftflow
  tracks a small dtype-provenance lattice {dd, f64, f32, unknown}
  through assignments, calls and returns (pint_tpu.analysis.cfg) over
  the jit-reachable closure graftlint already infers, and enforces:
  (a) every syntactic demotion (``.astype(float32)``, ``dd_to_dd32``
  and friends, f32-typed literals/ctors) matches a justified entry in
  ``analysis/precision_registry.py`` — stale entries fail, declared
  gate flags are statically verified against the enclosing guards;
  (b) inside the exact-precision modules (``models/timing_model.py``,
  ``residuals.py``, ``gls.py``) no ``ops/dd.py``/``ops/dd_np.py``
  consumer may receive an f32-provenance value; (c) mixed known-f32 x
  known-f64 array arithmetic is a demotion site like any other.

- **G10 (trace constants)**: parameter VALUES are runtime args, never
  trace constants (CLAUDE.md conventions; the chromatic_index
  TNCHROMIDX and silently-inert PhaseOffset incidents). Two checks:
  (a) ``.value``/``.quantity`` reads inside jit-reachable code are
  flagged unless the read is provably covered by the
  ``TimingModel._compile_key`` fields — str/bool/int parameter kinds
  (keyed statics; kinds are recovered from the Parameter-constructor
  calls in the class bodies), presence checks (``.value is not
  None``: keyed via the device-param name tuple), PLANET_SHAPIRO
  (keyed explicitly), or a frozen-guarded read (the function raises
  on a free param first — frozen values are keyed via frozen_vals);
  (b) a jit-traced closure must not CAPTURE a parameter-value-derived
  binding from its builder (th0/anchor-reference pairs etc.) — the
  pval dataflow taints ``.value`` reads, ``_pack()`` value slots and
  ``build_anchor`` results through the builder's locals and flags any
  tainted free variable of a traced inner function. Sanctioned
  anchored-reference captures ride the ordinary allowlist, each with
  its written justification.

- **G11 (use-after-donate)**: buffer donation (ISSUE 7 — the fit
  loop's (th, tl) state, the serve batch kernels' alias-exact
  inputs) means the dispatch CONSUMES the donated buffers; a read of
  the same variable after the call is a deleted-array error at best
  and, pipelined, a race against XLA reusing the buffer for
  outputs. ``check_g11_module`` resolves literal ``donate_argnums``
  on jit products (assignment targets, ``self.x =`` attributes,
  ``@partial(jax.jit, ...)`` decorations), then flags any later
  lexical read of a name passed at a donated position without an
  intervening rebinding (``x = f(x)`` is the sanctioned idiom).

The compile-key cross-check is live, not aspirational: graftflow
PARSES ``_compile_key`` and recovers which parameter kinds are keyed;
if the key ever stops covering str/bool/int statics, frozen values,
or ref_day, every sanctioning rule that leaned on that coverage
turns into a G10 violation ("compile key drifted").

Differential validation: ``predict_profile(**flags)`` exports, per
``precision_registry.PROBES`` entry, whether the probed boundary
fires and with which dtype under a production flag assignment;
``tests/test_dtype_probe.py`` traces the real ``build_fit_step``
configurations under ``Sanitizer.dtype_probe()`` and asserts the
observed dtypes match. The analyzer tests the code; the runtime
tests the analyzer.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from pint_tpu.analysis import cfg as _cfg
from pint_tpu.analysis import graftlint as _gl
from pint_tpu.analysis import precision_registry as _reg

Violation = _gl.Violation

__all__ = ["run_flow_checks", "predict_profile", "check_g9_module",
           "check_g10_module", "check_g11_module",
           "collect_donated_products", "ParamKinds", "FlowContext"]

# ---------------------------------------------------------- lattice

F32 = "f32"       # f32-provenance: demoted somewhere upstream
F64 = "f64"       # known plain float64
DDV = "dd"        # double-double pair (f64 halves)
UNKNOWN = "unknown"


def join_dtype(a: str, b: str) -> str:
    if a == b:
        return a
    if F32 in (a, b):
        return F32   # taint survives every join
    return UNKNOWN


# Parameter constructors and which kinds the compile key treats as
# sanctioned trace statics (cross-checked against _compile_key).
PARAM_CTORS = {"floatParameter", "MJDParameter", "prefixParameter",
               "maskParameter", "pairParameter", "AngleParameter",
               "strParameter", "boolParameter", "intParameter"}
DEFAULT_KEYED_KINDS = {"strParameter", "boolParameter", "intParameter"}

# dd-consumer protection zone: the exact-precision chain
PROTECTED_MODULES = {"pint_tpu/models/timing_model.py",
                     "pint_tpu/residuals.py", "pint_tpu/gls.py"}

DD32_CONVERTERS = {"dd_to_dd32", "f64_to_dd32", "_tree_to32",
                   "_split32"}

PVAL_SOURCE_CALLS = {"build_anchor"}
PACK_CALL = "_pack"

_PARAM_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")


# ------------------------------------------------------------------
# shared context: parameter kinds + compile-key cross-check
# ------------------------------------------------------------------

class ParamKinds:
    """PARAM name -> constructor kind, recovered from the
    ``xParameter("NAME", ...)`` calls in the scanned class bodies."""

    def __init__(self, modules: List["_gl.ModuleInfo"]):
        self.kinds: Dict[str, str] = {}
        for m in modules:
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                ctor = _gl._tail_name(node.func)
                if ctor not in PARAM_CTORS:
                    continue
                name = None
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                for kw in node.keywords:
                    if kw.arg == "name" and \
                            isinstance(kw.value, ast.Constant):
                        name = kw.value.value
                if name:
                    # a name built by several ctors (rare) keeps the
                    # LEAST sanctioned kind: only uniform str/bool/int
                    # construction makes a read safe
                    prev = self.kinds.get(name)
                    if prev is not None and prev != ctor:
                        self.kinds[name] = "ambiguous"
                    else:
                        self.kinds[name] = ctor

    def kind(self, name: str) -> Optional[str]:
        return self.kinds.get(name)


def parse_compile_key(modules) -> Tuple[Set[str], List[Violation]]:
    """Recover the sanctioned-static coverage from
    ``TimingModel._compile_key``'s AST. Returns (keyed ctor kinds,
    violations). A missing or drifted key is a G10 violation: every
    allowance G10 grants leans on the key covering these fields."""
    tm = None
    for m in modules:
        if m.relpath == "pint_tpu/models/timing_model.py":
            tm = m
            break
    if tm is None:
        # fixture mode (the module under test is not the real tree):
        # fall back to the documented kinds without complaint
        return set(DEFAULT_KEYED_KINDS), []
    fn = None
    for f in tm.functions:
        if f.name == "_compile_key":
            fn = f
            break
    out: List[Violation] = []
    if fn is None:
        out.append(Violation(
            "G10", tm.relpath, 0,
            "TimingModel._compile_key not found — graftflow's "
            "sanctioned-static rules assume its coverage; restore it "
            "or update graftflow.parse_compile_key", scope="repo"))
        return set(DEFAULT_KEYED_KINDS), out
    src = ast.unparse(fn)
    kinds = {k for k in DEFAULT_KEYED_KINDS if k in src}
    for feature, msg in (
            ("frozen_vals", "frozen device-param values"),
            ("ref_day", "the static reference epoch"),
            ("PLANET_SHAPIRO", "the PLANET_SHAPIRO branch static")):
        if feature not in src:
            out.append(Violation(
                "G10", tm.relpath, fn.lineno,
                f"_compile_key no longer covers {msg} ({feature!r}) "
                f"— G10's sanctioning of reads keyed through it is "
                f"now unsound; re-add the field or rework the rule",
                scope="repo"))
    if kinds != DEFAULT_KEYED_KINDS:
        missing = sorted(DEFAULT_KEYED_KINDS - kinds)
        out.append(Violation(
            "G10", tm.relpath, fn.lineno,
            f"_compile_key no longer keys {missing} parameter values "
            f"— their in-trace reads are no longer sanctioned",
            scope="repo"))
    return kinds or set(DEFAULT_KEYED_KINDS), out


class FlowContext:
    """Everything the per-module checks share."""

    def __init__(self, modules, param_kinds: Optional[ParamKinds] = None,
                 registry: Optional[List[dict]] = None):
        self.modules = modules
        self.param_kinds = param_kinds or ParamKinds(modules)
        self.registry = _reg.DEMOTIONS if registry is None else registry
        self.keyed_kinds, self.key_violations = \
            parse_compile_key(modules)
        self.registry_hits = [0] * len(self.registry)
        self.suppressed: List[Tuple[Violation, str]] = []


# ------------------------------------------------------------------
# G9 — demotion sites + dd-consumer taint
# ------------------------------------------------------------------

def _mentions_dtype(node: ast.AST, name: str) -> bool:
    """jnp.float32 / np.float32 / bare float32 AND the string
    spelling "float32" — astype("float32") is common numpy idiom and
    must not slip past the rule."""
    for n in ast.walk(node):
        if isinstance(n, (ast.Attribute, ast.Name)) and \
                _gl._tail_name(n) == name:
            return True
        if isinstance(n, ast.Constant) and n.value == name:
            return True
    return False


def demotion_kind(node: ast.Call) -> Optional[str]:
    """Classify a call as a precision demotion, or None."""
    tail = _gl._tail_name(node.func)
    if tail == "astype" and any(_mentions_dtype(a, "float32")
                                for a in node.args):
        return "astype(float32)"
    if tail in DD32_CONVERTERS:
        return f"{tail}()"
    if tail == "float32" and (node.args or node.keywords):
        return "float32(...) literal"
    # dtype-typed constructors: asarray(x, jnp.float32) /
    # zeros(n, dtype="float32") / full(..., dtype=np.float32)
    for kw in node.keywords:
        if kw.arg == "dtype" and _mentions_dtype(kw.value, "float32"):
            return "dtype=float32 construction"
    for a in node.args[1:]:
        if (isinstance(a, (ast.Attribute, ast.Name)) and
                _gl._tail_name(a) == "float32") or \
                (isinstance(a, ast.Constant) and a.value == "float32"):
            return "f32-dtype argument"
    return None


def _registry_lookup(ctx: FlowContext, relpath: str, func: str,
                     line_text: str) -> Optional[dict]:
    for i, e in enumerate(ctx.registry):
        if e["file"] != relpath or e["func"] != func:
            continue
        if e.get("match") and e["match"] not in line_text:
            continue
        if ctx.registry_hits[i] >= e.get("max_hits", 1):
            continue
        ctx.registry_hits[i] += 1
        return e
    return None


def _guard_satisfied(m: "_gl.ModuleInfo", node: ast.AST,
                     guard: str) -> bool:
    """The declared gate name must actually gate the site: the node
    sits in the TRUE branch of an enclosing ``if <guard>...`` (a
    demotion in the else-branch runs precisely when the flag is OFF
    — that is drift, not gating; a bare ``if not <guard>`` inverts
    the branches), or the enclosing function takes the gate as a
    parameter (the _symm_mm pattern: the flag selects behavior
    inside the function)."""
    prev, cur = node, m.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.If) and guard in ast.unparse(cur.test):
            negated = (isinstance(cur.test, ast.UnaryOp) and
                       isinstance(cur.test.op, ast.Not))
            in_body = any(prev is s for s in cur.body)
            in_else = any(prev is s for s in cur.orelse)
            if (in_body and not negated) or (in_else and negated):
                return True
            # wrong branch: keep walking — an outer guard may gate
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {a.arg for a in cur.args.args +
                      cur.args.kwonlyargs}
            if guard in params:
                return True
        prev, cur = cur, m.parents.get(cur)
    return False


def _dd_consumer_names(m: "_gl.ModuleInfo") -> Set[str]:
    """Names this module imports from ops.dd / ops.dd_np (plus the
    ``dd_np.x`` attribute form): the consumers G9 protects."""
    out: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                ("ops.dd" in node.module or node.module == "ops"):
            for a in node.names:
                out.add(a.asname or a.name)
    out.discard("dd_np")  # attribute calls handled separately
    return out


class _DtypeFlow:
    """Per-function dtype-provenance pass (the cfg client).
    ``protected`` switches on the dd-consumer check (the exact-
    precision modules); mixed-dtype arithmetic flags everywhere."""

    def __init__(self, m, fn, consumers: Set[str], ctx: FlowContext,
                 record: Optional[List[Violation]] = None,
                 protected: bool = True):
        self.m = m
        self.fn = fn
        self.consumers = consumers
        self.ctx = ctx
        self.record = record
        self.protected = protected

    def eval(self, node: ast.AST, env: Dict[str, str]) -> str:
        if node is None:
            return UNKNOWN
        if isinstance(node, ast.Name):
            return env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Constant):
            return UNKNOWN  # python scalars are weakly typed in jax
        if isinstance(node, ast.Attribute):
            # x.hi / x.lo keep x's provenance; everything else is a
            # fresh unknown unless the base is tainted
            base = self.eval(node.value, env)
            return base if base == F32 else (
                base if node.attr in ("hi", "lo") else UNKNOWN)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.BinOp):
            lt = self.eval(node.left, env)
            rt = self.eval(node.right, env)
            if {lt, rt} == {F32, F64} and self.record is not None:
                line = self.m.line_text(node.lineno)
                entry = _registry_lookup(
                    self.ctx, self.m.relpath, self.fn.name, line)
                v = Violation(
                    "G9", self.m.relpath, node.lineno,
                    f"mixed f32 x f64 arithmetic in jit-reachable "
                    f"`{self.fn.name}`: the f32 operand already lost "
                    f"the bits the f64 side is carrying — demote "
                    f"only at a registered boundary", line)
                if entry is not None:
                    self.ctx.suppressed.append((v, f"registry: "
                                                f"{entry['why']}"))
                else:
                    self.record.append(v)
            return join_dtype(lt, rt)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.IfExp):
            return join_dtype(self.eval(node.body, env),
                              self.eval(node.orelse, env))
        if isinstance(node, (ast.Tuple, ast.List)):
            out = UNKNOWN
            first = True
            for e in node.elts:
                t = self.eval(e, env)
                out = t if first else join_dtype(out, t)
                first = False
            return out
        if isinstance(node, ast.Subscript):
            return self.eval(node.value, env)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        return UNKNOWN

    def _eval_call(self, node: ast.Call, env) -> str:
        tail = _gl._tail_name(node.func)
        args = list(node.args) + [kw.value for kw in node.keywords]
        arg_ts = [self.eval(a, env) for a in args]
        joined = UNKNOWN
        for i, t in enumerate(arg_ts):
            joined = t if i == 0 else join_dtype(joined, t)
        if isinstance(node.func, ast.Attribute):
            # method calls on a tainted RECEIVER stay tainted —
            # x.reshape(-1)/.sum()/.ravel() of an f32 value must not
            # launder its provenance past the dd-consumer check
            joined = join_dtype(joined,
                                self.eval(node.func.value, env))
        kind = demotion_kind(node)
        if kind:
            return F32
        if tail == "astype" and any(
                _mentions_dtype(a, "float64") for a in node.args):
            # upcasts produce f64 VALUES but do not launder f32
            # provenance: the bits are already gone
            base = self.eval(node.func.value, env) \
                if isinstance(node.func, ast.Attribute) else UNKNOWN
            return F32 if base == F32 else F64
        if self.record is not None and self.protected and (
                tail in self.consumers or
                (isinstance(node.func, ast.Attribute) and
                 _gl._root_name(node.func) == "dd_np")):
            bad = [i for i, t in enumerate(arg_ts) if t == F32]
            if bad:
                line = self.m.line_text(node.lineno)
                self.record.append(Violation(
                    "G9", self.m.relpath, node.lineno,
                    f"dd consumer `{tail}` in exact-precision module "
                    f"receives f32-provenance argument(s) "
                    f"{bad} inside `{self.fn.name}`: the dd error-"
                    f"free transforms assume full-precision inputs "
                    f"(demotions belong in parallel/fit_step's "
                    f"registered boundaries)", line))
        if tail in ("dd", "DD", "dd_from_parts"):
            return F32 if joined == F32 else DDV
        # taint propagates through arbitrary calls: zeros_like(f32),
        # concatenate([f32...]), helper(f32) all stay f32-provenance
        return F32 if joined == F32 else UNKNOWN

    # ------------------------------------------------------ transfer

    def transfer(self, st: ast.stmt, env: Dict[str, str],
                 is_header: bool):
        if is_header:
            if isinstance(st, (ast.For, ast.AsyncFor)):
                t = self.eval(st.iter, env)
                for n in ast.walk(st.target):
                    if isinstance(n, ast.Name):
                        env[n.id] = t
            elif isinstance(st, ast.If):
                self.eval(st.test, env)
            elif isinstance(st, ast.While):
                self.eval(st.test, env)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    t = self.eval(item.context_expr, env)
                    if item.optional_vars is not None:
                        for n in ast.walk(item.optional_vars):
                            if isinstance(n, ast.Name):
                                env[n.id] = t
            return
        if isinstance(st, ast.Assign):
            t = self.eval(st.value, env)
            for tgt in st.targets:
                self._bind(tgt, st.value, t, env)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            t = self.eval(st.value, env)
            self._bind(st.target, st.value, t, env)
        elif isinstance(st, ast.AugAssign):
            t = join_dtype(self.eval(st.target, env),
                           self.eval(st.value, env))
            if isinstance(st.target, ast.Name):
                env[st.target.id] = t
        elif isinstance(st, ast.Expr):
            self.eval(st.value, env)
        elif isinstance(st, ast.Return) and st.value is not None:
            self.eval(st.value, env)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            env[st.name] = UNKNOWN
        elif isinstance(st, (ast.If, ast.While, ast.Try, ast.With,
                             ast.Match)):
            pass  # headers handled above
        elif isinstance(st, (ast.Raise, ast.Assert)):
            if getattr(st, "exc", None) is not None:
                self.eval(st.exc, env)

    def _bind(self, tgt, value_node, t: str, env):
        if isinstance(tgt, ast.Name):
            env[tgt.id] = t
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            # element-wise when the RHS is a literal tuple, else the
            # joined provenance lands on every target
            if isinstance(value_node, (ast.Tuple, ast.List)) and \
                    len(value_node.elts) == len(tgt.elts):
                for sub_t, sub_v in zip(tgt.elts, value_node.elts):
                    self._bind(sub_t, sub_v, self.eval(sub_v, env),
                               env)
            else:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        env[n.id] = t


def check_g9_module(m: "_gl.ModuleInfo", ctx: FlowContext
                    ) -> List[Violation]:
    """Demotion-site scan (jit regions, registry-sanctioned) plus the
    dd-consumer taint pass in the exact-precision modules."""
    out: List[Violation] = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call) or not m.in_jit_region(node):
            continue
        kind = demotion_kind(node)
        if not kind:
            continue
        fn = m.enclosing_function(node)
        fname = fn.name if fn is not None else "<module>"
        line = m.line_text(node.lineno)
        entry = _registry_lookup(ctx, m.relpath, fname, line)
        if entry is None:
            out.append(Violation(
                "G9", m.relpath, node.lineno,
                f"precision demotion [{kind}] in jit-reachable "
                f"`{fname}` outside the precision-boundary registry "
                f"— if this demotion is engineered and safe, declare "
                f"it in analysis/precision_registry.py with its "
                f"justification", line))
            continue
        guard = entry.get("guard")
        if guard and not _guard_satisfied(m, node, guard):
            out.append(Violation(
                "G9", m.relpath, node.lineno,
                f"registered boundary site declares guard "
                f"`{guard}` (flag {entry.get('flag')!r}) but the "
                f"site is not in the TRUE branch of an enclosing "
                f"`if {guard}` and no enclosing function takes "
                f"`{guard}` as a parameter — the registry's gating "
                f"claim drifted from the code", line))
        else:
            v = Violation("G9", m.relpath, node.lineno,
                          f"demotion [{kind}] in `{fname}`", line)
            ctx.suppressed.append((v, f"registry: {entry['why']}"))
    protected = m.relpath in PROTECTED_MODULES
    consumers = _dd_consumer_names(m) if protected else set()
    for fn in m.functions:
        if fn not in m.jit_funcs:
            continue
        out.extend(_run_dtype_pass(m, fn, consumers, ctx, protected))
    return out


def _run_dtype_pass(m, fn, consumers, ctx,
                    protected: bool) -> List[Violation]:
    graph = _cfg.build_cfg(fn)
    flow = _DtypeFlow(m, fn, consumers, ctx, protected=protected)
    in_envs = _cfg.run_dataflow(
        graph, {}, flow.transfer, join_dtype)
    found: List[Violation] = []
    rec = _DtypeFlow(m, fn, consumers, ctx, record=found,
                     protected=protected)
    for b in graph.blocks:
        env = dict(in_envs.get(b.bid, {}))
        for st in b.stmts:
            rec.transfer(st, env, st in b.headers)
    return found


# ------------------------------------------------------------------
# G10 — trace constants: in-trace .value reads + tainted captures
# ------------------------------------------------------------------

def _is_presence_check(m: "_gl.ModuleInfo", node: ast.AST) -> bool:
    """``X.value is (not) None``: a structural presence test —
    covered by the compile key's device-param name tuple (params
    without a value are not device params at all)."""
    cur = node
    parent = m.parents.get(cur)
    while parent is not None and isinstance(
            parent, (ast.Compare, ast.BoolOp, ast.UnaryOp)):
        if isinstance(parent, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in parent.ops) and \
                    any(isinstance(c, ast.Constant) and c.value is None
                        for c in parent.comparators):
                return True
        cur, parent = parent, m.parents.get(parent)
    return False


def _frozen_guarded_names(fn: ast.FunctionDef) -> Dict[str, int]:
    """The chromatic_index pattern, PER PARAMETER: {receiver: guard
    line} for receivers whose free-ness the function refuses with a
    raise — ``if not p.frozen: raise`` (possibly or-joined). Frozen
    values are covered by the compile key's frozen_vals, so reads of
    exactly THOSE receivers, AFTER the guard line, cannot go
    silently stale. Scoping it per-parameter and requiring the read
    to follow the guard closes two holes a blanket function-level
    exemption would leave open: a later-added read of a DIFFERENT
    free parameter, and a read on an early-return path the guard
    never dominates (lexical order approximates dominance — exact on
    the straight-line guard-first idiom this sanctions)."""
    out: Dict[str, int] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        if not any(isinstance(n, ast.Raise) for n in node.body):
            continue
        # only the refusing polarity sanctions: `not X.frozen` (or
        # any or-combination containing it)
        for n in ast.walk(node.test):
            if isinstance(n, ast.UnaryOp) and \
                    isinstance(n.op, ast.Not) and \
                    isinstance(n.operand, ast.Attribute) and \
                    n.operand.attr == "frozen":
                base = _gl._tail_name(n.operand.value)
                if base:
                    out[base] = min(out.get(base, node.lineno),
                                    node.lineno)
    return out


def check_g10_reads(m: "_gl.ModuleInfo", ctx: FlowContext
                    ) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(m.tree):
        if not (isinstance(node, ast.Attribute) and
                node.attr in ("value", "quantity")):
            continue
        if not m.in_jit_region(node):
            continue
        if _is_presence_check(m, node):
            continue
        pname = _gl._tail_name(node.value)
        if pname == "PLANET_SHAPIRO":
            continue  # the one MiscParams static, keyed explicitly
        kind = ctx.param_kinds.kind(pname) if pname and \
            _PARAM_NAME_RE.match(pname) else None
        if kind in ctx.keyed_kinds:
            continue  # str/bool/int param values are compile-keyed
        fn = m.enclosing_function(node)
        if fn is not None and pname:
            guards = _frozen_guarded_names(fn)
            if pname in guards and node.lineno > guards[pname]:
                continue  # refused free BEFORE this read; frozen
                # values are compile-keyed
        out.append(Violation(
            "G10", m.relpath, node.lineno,
            f".{node.attr} read of "
            f"{'parameter ' + pname if pname else 'a parameter'} "
            f"inside jit-reachable "
            f"`{fn.name if fn else '<module>'}` bakes a trace "
            f"constant (pv-convention: values are runtime args). "
            f"Route it through pv[...], or guard frozen-ness with a "
            f"raise (frozen values are compile-keyed)",
            m.line_text(node.lineno)))
    return out


class _PvalFlow:
    """Taints builder-function locals that derive from parameter
    values: .value/.quantity reads (non-keyed kinds), the value slots
    of ``_pack()``, and ``build_anchor`` results — propagated through
    calls, subscripts, attributes and arithmetic."""

    def __init__(self, m, ctx: FlowContext):
        self.m = m
        self.ctx = ctx

    def eval(self, node, env) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return bool(env.get(node.id, False))
        if isinstance(node, ast.Attribute):
            if node.attr in ("value", "quantity"):
                pname = _gl._tail_name(node.value)
                kind = self.ctx.param_kinds.kind(pname) if pname \
                    else None
                if kind in self.ctx.keyed_kinds:
                    return False
                return True
            return self.eval(node.value, env)
        if isinstance(node, ast.Call):
            tail = _gl._tail_name(node.func)
            if tail in PVAL_SOURCE_CALLS:
                return True
            args = list(node.args) + [k.value for k in node.keywords]
            if any(self.eval(a, env) for a in args):
                return True
            if isinstance(node.func, ast.Attribute):
                # method call on a tainted object stays tainted
                return self.eval(node.func.value, env) \
                    if node.func.attr not in ("keys", "items") \
                    else False
            return False
        if isinstance(node, (ast.BinOp,)):
            return self.eval(node.left, env) or \
                self.eval(node.right, env)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.IfExp):
            return self.eval(node.body, env) or \
                self.eval(node.orelse, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.eval(e, env) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.eval(v, env)
                       for v in list(node.keys) + list(node.values)
                       if v is not None)
        if isinstance(node, ast.Subscript):
            return self.eval(node.value, env)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            return any(self.eval(n, env) for n in ast.walk(node)
                       if isinstance(n, ast.Name))
        return False

    def transfer(self, st, env, is_header):
        if is_header:
            if isinstance(st, (ast.For, ast.AsyncFor)):
                t = self.eval(st.iter, env)
                for n in ast.walk(st.target):
                    if isinstance(n, ast.Name):
                        env[n.id] = t
            return
        if isinstance(st, ast.Assign):
            self._assign(st.targets, st.value, env)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            self._assign([st.target], st.value, env)
        elif isinstance(st, ast.AugAssign):
            if isinstance(st.target, ast.Name):
                env[st.target.id] = bool(
                    env.get(st.target.id, False)) or \
                    self.eval(st.value, env)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            env[st.name] = False  # captures are checked per-function

    def _assign(self, targets, value, env):
        # the _pack convention: (free_names, frozen_names, th, tl,
        # fh, fl) — positions >= 2 are parameter VALUES, 0-1 are
        # name lists (strings; capturing those is fine)
        is_pack = isinstance(value, ast.Call) and \
            _gl._tail_name(value.func) == PACK_CALL
        for tgt in targets:
            if is_pack and isinstance(tgt, (ast.Tuple, ast.List)):
                pos = 0
                for el in tgt.elts:
                    if isinstance(el, ast.Starred):
                        for n in ast.walk(el):
                            if isinstance(n, ast.Name):
                                env[n.id] = True
                        pos = 6
                        continue
                    if isinstance(el, ast.Name):
                        env[el.id] = pos >= 2
                    pos += 1
                continue
            t = True if is_pack else self.eval(value, env)
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    env[n.id] = t


def _free_names(fn: ast.FunctionDef) -> Set[str]:
    loaded = {n.id for n in ast.walk(fn)
              if isinstance(n, ast.Name) and
              isinstance(n.ctx, ast.Load)}
    return loaded - _gl._locally_bound_names(fn) - {fn.name}


def check_g10_captures(m: "_gl.ModuleInfo", ctx: FlowContext
                       ) -> List[Violation]:
    """A jit-traced inner function must not capture a parameter-
    value-derived binding from its builder."""
    out: List[Violation] = []
    seen: Set[Tuple[str, str, str]] = set()
    module_names = {f.name for f in m.functions} | \
        {c.name for c in m.classes}
    env_cache: Dict[ast.FunctionDef, Dict[str, bool]] = {}

    def final_env(outer: ast.FunctionDef) -> Dict[str, bool]:
        if outer not in env_cache:
            graph = _cfg.build_cfg(outer)
            flow = _PvalFlow(m, ctx)
            in_envs = _cfg.run_dataflow(
                graph, {}, flow.transfer,
                lambda a, b: bool(a) or bool(b))
            # the function's final state: join over every block's
            # OUT env (captures can be created anywhere, not only on
            # the path that reaches the exit)
            joined: Dict[str, bool] = {}
            for b in graph.blocks:
                env = dict(in_envs.get(b.bid, {}))
                for st in b.stmts:
                    flow.transfer(st, env, st in b.headers)
                for k, v in env.items():
                    joined[k] = joined.get(k, False) or v
            env_cache[outer] = joined
        return env_cache[outer]

    for fn in m.functions:
        if fn not in m.jit_funcs:
            continue
        outer = m.enclosing_function(fn)
        if outer is None:
            continue
        free = _free_names(fn) - module_names
        chain = []
        cur = outer
        while cur is not None:
            chain.append(cur)
            cur = m.enclosing_function(cur)
        for name in sorted(free):
            for binder in chain:
                if name not in _gl._locally_bound_names(binder) and \
                        not any(isinstance(s, (ast.FunctionDef,
                                               ast.AsyncFunctionDef))
                                and s.name == name
                                for s in ast.walk(binder)):
                    continue
                key = (m.relpath, binder.name, name)
                env = final_env(binder)
                if env.get(name, False) and key not in seen:
                    seen.add(key)
                    out.append(Violation(
                        "G10", m.relpath, fn.lineno,
                        f"traced closure `{fn.name}` captures "
                        f"`{name}` from `{binder.name}`, a binding "
                        f"derived from parameter VALUES (pv-"
                        f"convention: values are runtime args). "
                        f"Thread it through the step arguments, or "
                        f"allowlist it as a reviewed anchored-"
                        f"reference static",
                        m.line_text(fn.lineno)))
                break
    return out


def check_g10_module(m: "_gl.ModuleInfo", ctx: FlowContext
                     ) -> List[Violation]:
    return check_g10_reads(m, ctx) + check_g10_captures(m, ctx)


# ------------------------------------------------------------------
# G11 — use-after-donate
# ------------------------------------------------------------------

def _donate_positions(call: ast.Call):
    """(has_donation, positions): positions is a tuple of donated
    argument indices when donate_argnums is a literal int/tuple, or
    None for a non-literal / donate_argnames spelling — the caller
    then treats EVERY position as donated (conservative: an unknown
    donation set must not silently sanction reads)."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return True, (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and
                isinstance(e.value, int) for e in v.elts):
            return True, tuple(e.value for e in v.elts)
        return True, None
    return False, ()


def collect_donated_products(m: "_gl.ModuleInfo"):
    """Names bound to jit products compiled WITH buffer donation:
    assignment targets of ``jax.jit(..., donate_argnums=...)`` —
    including ``self.x = jax.jit(...)`` attributes — and functions
    decorated ``@partial(jax.jit, donate_argnums=...)``. Returns
    {name: donated positions or None (= all, see
    _donate_positions)}. Module-local by convention: every donation
    site in the tree declares and dispatches in the same module (the
    run-closure pattern); a cross-module donated import would need
    its own entry here."""
    out = {}
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _gl._tail_name(node.value.func) == "jit":
            has, pos = _donate_positions(node.value)
            if not has:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = pos
                elif isinstance(t, ast.Attribute):
                    out[t.attr] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and \
                        _gl._decorator_is_jit(dec):
                    has, pos = _donate_positions(dec)
                    if has:
                        out[node.name] = pos
    return out


def check_g11_module(m: "_gl.ModuleInfo") -> List[Violation]:
    """Use-after-donate: a variable passed in a donated argument
    position of a donated jit product is consumed by the dispatch
    (the buffer is deleted — jax raises "Array has been deleted" on
    the next read — or, pipelined, silently reused for outputs); any
    LATER lexical read of the same name in the same scope, without
    an intervening rebinding, is flagged. ``x = f(x)`` is the
    sanctioned idiom: the call's own assignment rebinds the name.
    Lexical order approximates dominance, the same approximation
    class as G10's frozen-guard check; donated args that are not
    bare names (subscripts, attribute chains, fresh ``jnp.asarray``
    temporaries — the dominant safe pattern) are outside the rule."""
    donated = collect_donated_products(m)
    if not donated:
        return []
    events: Dict[object, list] = {}    # scope -> (name, line, prod)
    rebinds: Dict[object, list] = {}   # scope -> (name, line)
    uses: Dict[object, list] = {}      # scope -> (name, line)

    def scope_of(node):
        f = m.enclosing_function(node)
        return f if f is not None else m.tree

    for node in ast.walk(m.tree):
        if isinstance(node, ast.Call):
            tail = _gl._tail_name(node.func)
            if tail in donated:
                pos = donated[tail]
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Starred):
                        break   # positions past *args are unknowable
                    if pos is not None and i not in pos:
                        continue
                    if isinstance(a, ast.Name):
                        events.setdefault(scope_of(node), []).append(
                            (a.id, node.lineno, tail))
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                               ast.For, ast.AsyncFor)):
            targets = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            targets = [i.optional_vars for i in node.items
                       if i.optional_vars is not None]
        for t in targets:
            for nn in ast.walk(t):
                if isinstance(nn, ast.Name):
                    rebinds.setdefault(scope_of(node), []).append(
                        (nn.id, node.lineno))
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            uses.setdefault(scope_of(node), []).append(
                (node.id, node.lineno))

    out: List[Violation] = []
    seen: Set[Tuple] = set()
    for scope, evs in events.items():
        rb = rebinds.get(scope, [])
        for name, dline, product in evs:
            for uname, uline in uses.get(scope, []):
                if uname != name or uline <= dline:
                    continue
                if any(bn == name and dline <= bl < uline
                       for bn, bl in rb):
                    continue   # rebound (x = f(x), or later) first
                key = (name, dline, uline)
                if key in seen:
                    continue
                seen.add(key)
                out.append(Violation(
                    "G11", m.relpath, uline,
                    f"`{name}` is read after being passed in a "
                    f"donated argument position of `{product}` "
                    f"(line {dline}): the dispatch consumed that "
                    f"buffer — rebind the name from the call's "
                    f"result, or pass a fresh temporary instead",
                    m.line_text(uline)))
    return out


# ------------------------------------------------------------------
# registry bookkeeping + probe verification
# ------------------------------------------------------------------

def registry_stale_violations(ctx: FlowContext) -> List[Violation]:
    out = []
    for i, e in enumerate(ctx.registry):
        if not ctx.registry_hits[i]:
            out.append(Violation(
                "REGISTRY", e["file"], 0,
                f"stale precision-registry entry (func "
                f"{e['func']!r}, match {e.get('match')!r}) no longer "
                f"covers any demotion site — delete it so the "
                f"registry stays honest", scope="repo"))
    return out


def verify_probes(modules, probes: Optional[List[dict]] = None
                  ) -> List[Violation]:
    """Every runtime probe must still point at a real call site in
    its declared file (the static half of the differential loop)."""
    probes = _reg.PROBES if probes is None else probes
    by_path = {m.relpath: m for m in modules}
    out = []
    for p in probes:
        m = by_path.get(p["file"])
        if m is None:
            continue  # fixture runs do not carry the real tree
        called = any(isinstance(n, ast.Call) and
                     _gl._tail_name(n.func) == p["callee"]
                     for n in ast.walk(m.tree))
        if not called:
            out.append(Violation(
                "REGISTRY", p["file"], 0,
                f"dtype-probe {p['label']!r} points at "
                f"`{p['callee']}` but nothing in the file calls it "
                f"any more — the differential validation lost a "
                f"site; update precision_registry.PROBES",
                scope="repo"))
    return out


# ------------------------------------------------------------------
# prediction (the static half of the differential validation)
# ------------------------------------------------------------------

def _eval_flag(expr: Optional[str], flags: Dict[str, object]):
    if expr is None:
        return None
    return eval(expr, {"__builtins__": {}}, dict(flags))  # noqa: S307
    # (registry-authored expressions over four booleans, not user
    # input — the restricted globals keep it a pure flag calculus)


def predict_profile(jac32: bool = False, f32mm: bool = False,
                    anchored: bool = False, hybrid: bool = False
                    ) -> Dict[str, dict]:
    """{probe label: {"active": bool, "dtype": str | None}} under a
    production flag assignment. ``hybrid`` means "hybrid Jacobian
    enabled AND the model actually claims columns" — the caller owns
    that conjunction (an empty claim set never calls the column
    assembler)."""
    flags = dict(jac32=bool(jac32), f32mm=bool(f32mm),
                 anchored=bool(anchored), hybrid=bool(hybrid),
                 True_=True)
    out: Dict[str, dict] = {}
    for p in _reg.PROBES:
        active = bool(_eval_flag(p["flag"], flags))
        out[p["label"]] = {
            "active": active,
            "dtype": _eval_flag(p.get("dtype"), flags)
            if active else None,
        }
    return out


# ------------------------------------------------------------------
# driver
# ------------------------------------------------------------------

def run_flow_checks(modules, param_kinds: Optional[ParamKinds] = None,
                    registry: Optional[List[dict]] = None,
                    verify_probe_sites: bool = True):
    """(violations, suppressed) across G9/G10 + registry hygiene.
    ``modules`` must already carry graftlint's jit marks
    (mark_jit_regions)."""
    ctx = FlowContext(modules, param_kinds=param_kinds,
                      registry=registry)
    violations: List[Violation] = list(ctx.key_violations)
    for m in modules:
        violations += check_g9_module(m, ctx)
        violations += check_g10_module(m, ctx)
        violations += check_g11_module(m)
    violations += registry_stale_violations(ctx)
    if verify_probe_sites:
        violations += verify_probes(modules)
    return violations, ctx.suppressed
