"""Concurrency lint — graftlint rules G16 (lock discipline) and G17
(validated-env enforcement). ISSUE 18's static half; the dynamic half
is ``runtime.locks`` (TracedLock + the process lock-order graph).

G16, over the dispatch layer (the G6 file set) + ``runtime/`` +
``obs/`` + the serve CLI, checks four properties against
``analysis/lock_registry.py`` (every entry justified, stale entries
fail the run — the precision_registry policy):

- **G16.0 raw primitives**: ``threading.Lock()`` / ``RLock()`` /
  ``Condition()`` construction must go through the
  ``runtime.locks`` factories (``make_lock``/``make_rlock``/
  ``make_condition``) so the $PINT_TPU_LOCK_TRACE build sees every
  lock. Sanctioned raw sites (the factory internals) carry a G16
  pragma.
- **G16.1 guarded-field writes**: a registry-GUARDED field may be
  written only in ``__init__``, a ``*_locked``-suffixed method, a
  declared holder method, or lexically under ``with self.<lock>``
  (or a declared alias such as the Condition wrapping it).
- **G16.2 scrape isolation**: registry SCRAPE_ROOTS must be
  statically unreachable from any engine-lock acquisition, over the
  resolvable call graph (same-class ``self.`` calls, same-module
  calls, imported-module attribute calls, same-module tail-name
  fallback) — the repo-wide proof of "MetricsServer never takes an
  engine lock".
- **G16.3 blocking under engine lock**: no supervised dispatch,
  journal fsync/admit/ack, or host solve lexically inside ``with``
  on a registry ENGINE_LOCKS attribute (``BLOCKING_CALLS`` names the
  banned tails). The scheduler's ``_dispatch_lock`` is deliberately
  not an engine lock — dispatch under it is the drain design.

G17 finishes the raw-env ban (CLAUDE.md "Raw env reads are BANNED in
favor of validated config parsers"): ``os.environ`` / ``os.getenv``
anywhere outside ``config.py`` (the one home of validated parsers)
and this package's sanctioned entry points is a violation. Whole-
environment passthroughs to subprocesses (``env=dict(os.environ)``)
are sanctioned per-site with a G17 pragma — they forward, they do
not parse.

Separated from graftlint.py (the graftflow pattern) so tests can
drive the per-rule halves against AST fixtures without the full
driver.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from pint_tpu.analysis import graftlint as _gl
from pint_tpu.analysis import lock_registry as _reg

Violation = _gl.Violation

# G16 scope: the dispatch layer (same file set as G6), the runtime
# supervision package, the obs plane, the serve CLI and the profiler
# scoreboard — everywhere locks guard cross-thread serving state.
_G16_EXTRA_DIRS = ("pint_tpu/runtime/", "pint_tpu/obs/",
                   "pint_tpu/scripts/")
_G16_EXTRA_FILES = {"pint_tpu/profiling.py"}

# mutation methods that count as writes to a guarded container
_MUTATORS = frozenset({
    "append", "appendleft", "add", "clear", "pop", "popleft",
    "popitem", "remove", "update", "setdefault", "extend", "insert",
    "discard",
})

# os.environ readers allowed raw (G17): the validated-parser home and
# entry points that must read env before any pint_tpu import side
# effects can run
G17_SANCTIONED = {
    "pint_tpu/config.py",
}


def g16_applies(relpath: str) -> bool:
    return (relpath in _gl.G6_DISPATCH_FILES
            or relpath in _G16_EXTRA_FILES
            or relpath.startswith(_gl.G6_DISPATCH_DIRS)
            or relpath.startswith(_G16_EXTRA_DIRS))


# --------------------------------------------------------------------
# G16.0 — raw threading primitive construction
# --------------------------------------------------------------------

def check_g16_raw_primitives(m) -> List[Violation]:
    if not g16_applies(m.relpath):
        return []
    out: List[Violation] = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = _gl._tail_name(node.func)
        if tail not in ("Lock", "RLock", "Condition"):
            continue
        root = _gl._root_name(node.func)
        if root == "threading" or (
                root == tail and _imports_name(m, tail, "threading")):
            out.append(Violation(
                "G16", m.relpath, node.lineno,
                f"raw threading.{tail}() in the dispatch/serve/"
                f"runtime/obs layer: construct through "
                f"runtime.locks.make_{'condition' if tail == 'Condition' else 'rlock' if tail == 'RLock' else 'lock'}() "
                f"so the $PINT_TPU_LOCK_TRACE build traces it "
                f"(register guarded fields in "
                f"analysis/lock_registry.py)",
                m.line_text(node.lineno)))
    return out


def _imports_name(m, name: str, frm: str) -> bool:
    for n in ast.walk(m.tree):
        if isinstance(n, ast.ImportFrom) and n.module == frm and \
                any((a.asname or a.name) == name for a in n.names):
            return True
    return False


# --------------------------------------------------------------------
# G16.1 — guarded-field writes
# --------------------------------------------------------------------

def _self_field_write(node) -> str:
    """Field name when ``node`` writes ``self.<field>`` (plain /
    subscript / augmented assignment, or a mutating method call on
    the attribute), else None."""

    def attr_of(t):
        # self.<f>  or  self.<f>[...]
        if isinstance(t, ast.Subscript):
            t = t.value
        if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and t.value.id == "self":
            return t.attr
        return None

    if isinstance(node, ast.Assign):
        for t in node.targets:
            f = attr_of(t)
            if f is not None:
                return f
    elif isinstance(node, ast.AugAssign):
        return attr_of(node.target)
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in _MUTATORS:
        return attr_of(node.func.value)
    return None


def _with_lock_attrs(m, node) -> Set[str]:
    """Attribute names of every ``with self.<attr>`` the node sits
    lexically inside."""
    out: Set[str] = set()
    cur = m.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                e = item.context_expr
                if isinstance(e, ast.Attribute) and \
                        isinstance(e.value, ast.Name) and \
                        e.value.id == "self":
                    out.add(e.attr)
        cur = m.parents.get(cur)
    return out


def _enclosing_function_names(m, node) -> List[str]:
    """Every enclosing function name, innermost first — a write in a
    closure nested inside ``_expire_locked`` still counts as inside
    it."""
    names = []
    cur = m.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.append(cur.name)
        cur = m.parents.get(cur)
    return names


def check_g16_guarded_writes(m, hits: Dict[int, int]) -> List[Violation]:
    """``hits`` maps GUARDED entry index -> write count (the caller
    aggregates across modules for the stale check)."""
    entries = [(i, e) for i, e in enumerate(_reg.GUARDED)
               if e["file"] == m.relpath]
    if not entries:
        return []
    by_cls: Dict[str, Dict[str, Tuple[int, dict]]] = {}
    for i, e in entries:
        by_cls.setdefault(e["cls"], {})[e["field"]] = (i, e)
    out: List[Violation] = []
    for cls in m.classes:
        fields = by_cls.get(cls.name)
        if not fields:
            continue
        for node in ast.walk(cls):
            f = _self_field_write(node)
            if f is None or f not in fields:
                continue
            i, e = fields[f]
            hits[i] = hits.get(i, 0) + 1
            fn_names = _enclosing_function_names(m, node)
            if any(n == "__init__" or n.endswith("_locked") or
                   n in e.get("holders", ()) for n in fn_names):
                continue
            allowed = {e["lock"], *e.get("aliases", ())}
            if _with_lock_attrs(m, node) & allowed:
                continue
            out.append(Violation(
                "G16", m.relpath, node.lineno,
                f"write to guarded field `{cls.name}.{f}` outside "
                f"`with self.{e['lock']}` (registry: owned by "
                f"{e['lock']}; allowed holders: __init__, *_locked, "
                f"{tuple(e.get('holders', ())) or '()'}) — "
                f"unsynchronized against readers under the lock",
                m.line_text(getattr(node, 'lineno', 0))))
    return out


# --------------------------------------------------------------------
# G16.2 — scrape-path isolation (call-graph reachability)
# --------------------------------------------------------------------

def _module_alias_map(m, by_relpath: Dict[str, object]) -> Dict[str, str]:
    """Local name -> relpath for imports of scanned modules
    (``from pint_tpu.obs import metrics as om`` => om -> obs/metrics).
    Also maps ``from mod import fname`` function imports as
    ``fname`` -> relpath (resolved at call time by name)."""
    out: Dict[str, str] = {}
    for n in ast.walk(m.tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                rel = a.name.replace(".", "/") + ".py"
                pkg = a.name.replace(".", "/") + "/__init__.py"
                tgt = rel if rel in by_relpath else \
                    pkg if pkg in by_relpath else None
                if tgt:
                    out[a.asname or a.name.split(".")[0]] = tgt
        elif isinstance(n, ast.ImportFrom) and n.module:
            base = n.module.replace(".", "/")
            for a in n.names:
                for cand in (f"{base}/{a.name}.py",
                             f"{base}/{a.name}/__init__.py"):
                    if cand in by_relpath:
                        out[a.asname or a.name] = cand
                        break
                else:
                    for cand in (base + ".py", base + "/__init__.py"):
                        if cand in by_relpath:
                            # from mod import fname: call `fname()`
                            # resolves into mod
                            out[a.asname or a.name] = cand
                            break
    return out


class CallGraph:
    """Name-resolved call graph over the scanned modules. Nodes are
    (relpath, ClassName.func | func). Resolution is deliberately
    conservative-but-useful: self-calls bind within the enclosing
    class, bare names within the module (or a `from`-import), module
    aliases across modules, and unresolvable receivers fall back to
    same-module tail-name matching."""

    def __init__(self, modules):
        self.by_relpath = {m.relpath: m for m in modules}
        # (relpath, qualname) -> ast node
        self.funcs: Dict[Tuple[str, str], object] = {}
        # (relpath, name) -> [qualnames]
        self.by_name: Dict[Tuple[str, str], List[str]] = {}
        for m in modules:
            for f in m.functions:
                cls = m.enclosing_class(f)
                qual = f"{cls.name}.{f.name}" if cls else f.name
                self.funcs[(m.relpath, qual)] = f
                self.by_name.setdefault(
                    (m.relpath, f.name), []).append(qual)
        self._aliases = {m.relpath: _module_alias_map(m, self.by_relpath)
                         for m in modules}
        self._edges: Dict[Tuple[str, str],
                          Set[Tuple[str, str]]] = {}

    def callees(self, key: Tuple[str, str]) -> Set[Tuple[str, str]]:
        if key in self._edges:
            return self._edges[key]
        relpath, qual = key
        m = self.by_relpath.get(relpath)
        node = self.funcs.get(key)
        out: Set[Tuple[str, str]] = set()
        if m is None or node is None:
            self._edges[key] = out
            return out
        cls_name = qual.split(".")[0] if "." in qual else None
        aliases = self._aliases.get(relpath, {})
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            fn = n.func
            if isinstance(fn, ast.Name):
                tgt = aliases.get(fn.id)
                if tgt and (tgt, fn.id) in self.by_name:
                    # from mod import fname
                    for q in self.by_name[(tgt, fn.id)]:
                        out.add((tgt, q))
                else:
                    for q in self.by_name.get(
                            (relpath, fn.id), []):
                        out.add((relpath, q))
            elif isinstance(fn, ast.Attribute):
                recv, name = fn.value, fn.attr
                if isinstance(recv, ast.Name) and recv.id == "self" \
                        and cls_name:
                    if (relpath, f"{cls_name}.{name}") in self.funcs:
                        out.add((relpath, f"{cls_name}.{name}"))
                        continue
                if isinstance(recv, ast.Name) and \
                        recv.id in aliases:
                    tgt = aliases[recv.id]
                    for q in self.by_name.get((tgt, name), []):
                        out.add((tgt, q))
                    continue
                # tail-name fallback, same module only
                for q in self.by_name.get((relpath, name), []):
                    out.add((relpath, q))
        self._edges[key] = out
        return out


def _engine_lock_acquirers(modules) -> Dict[Tuple[str, str], str]:
    """(relpath, qualname) -> lock attr, for every function that
    lexically acquires a registry engine lock (``with self.<attr>``
    or ``self.<attr>.acquire()``)."""
    by_file = {e["file"]: set(e["attrs"]) for e in _reg.ENGINE_LOCKS}
    out: Dict[Tuple[str, str], str] = {}
    for m in modules:
        attrs = by_file.get(m.relpath)
        if not attrs:
            continue
        for f in m.functions:
            cls = m.enclosing_class(f)
            qual = f"{cls.name}.{f.name}" if cls else f.name
            for n in ast.walk(f):
                hit = None
                if isinstance(n, ast.With):
                    for item in n.items:
                        e = item.context_expr
                        if isinstance(e, ast.Attribute) and \
                                isinstance(e.value, ast.Name) and \
                                e.value.id == "self" and \
                                e.attr in attrs:
                            hit = e.attr
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "acquire":
                    recv = n.func.value
                    if isinstance(recv, ast.Attribute) and \
                            isinstance(recv.value, ast.Name) and \
                            recv.value.id == "self" and \
                            recv.attr in attrs:
                        hit = recv.attr
                if hit:
                    out[(m.relpath, qual)] = hit
    return out


def check_g16_scrape_paths(modules) -> List[Violation]:
    graph = CallGraph(modules)
    acquirers = _engine_lock_acquirers(modules)
    out: List[Violation] = []
    for entry in _reg.SCRAPE_ROOTS:
        relpath, fname = entry["file"], entry["func"]
        m = graph.by_relpath.get(relpath)
        roots = [(relpath, q)
                 for q in graph.by_name.get((relpath, fname), [])]
        if m is None or not roots:
            out.append(Violation(
                "G16", relpath, 0,
                f"stale lock_registry SCRAPE_ROOTS entry: function "
                f"`{fname}` not found — delete or update the entry",
                scope="repo"))
            continue
        for root in roots:
            seen = set(roots)
            todo = list(roots)
            parent = {}
            while todo:
                cur = todo.pop()
                if cur in acquirers:
                    path = [cur]
                    while path[-1] in parent:
                        path.append(parent[path[-1]])
                    chain = " -> ".join(
                        f"{p[1]}" for p in reversed(path))
                    node = graph.funcs.get(root)
                    out.append(Violation(
                        "G16", relpath,
                        getattr(node, "lineno", 0),
                        f"scrape root `{fname}` reaches engine-lock "
                        f"acquisition `self.{acquirers[cur]}` via "
                        f"{chain} ({cur[0]}) — the scrape path must "
                        f"never block on an engine lock "
                        f"(lock_registry SCRAPE_ROOTS)"))
                    break
                for nxt in graph.callees(cur):
                    if nxt not in seen:
                        seen.add(nxt)
                        parent[nxt] = cur
                        todo.append(nxt)
            break  # one BFS covers all same-named roots
    return out


# --------------------------------------------------------------------
# G16.3 — blocking calls under an engine lock
# --------------------------------------------------------------------

def check_g16_blocking_under_lock(m) -> List[Violation]:
    attrs: Set[str] = set()
    for e in _reg.ENGINE_LOCKS:
        if e["file"] == m.relpath:
            attrs |= set(e["attrs"])
    if not attrs:
        return []
    out: List[Violation] = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.With):
            continue
        held = [item.context_expr for item in node.items
                if isinstance(item.context_expr, ast.Attribute)
                and isinstance(item.context_expr.value, ast.Name)
                and item.context_expr.value.id == "self"
                and item.context_expr.attr in attrs]
        if not held:
            continue
        for inner in ast.walk(node):
            if inner is node or not isinstance(inner, ast.Call):
                continue
            tail = _gl._tail_name(inner.func)
            if tail in _reg.BLOCKING_CALLS:
                out.append(Violation(
                    "G16", m.relpath, inner.lineno,
                    f"`{tail}(...)` inside `with self."
                    f"{held[0].attr}`: no supervised dispatch, "
                    f"journal fsync, or host solve may run under an "
                    f"engine lock — it stalls every submitter for "
                    f"the full RTT (lock_registry ENGINE_LOCKS / "
                    f"BLOCKING_CALLS)",
                    m.line_text(inner.lineno)))
    return out


# --------------------------------------------------------------------
# G16 driver + stale-entry check
# --------------------------------------------------------------------

def check_g16(m, hits: Dict[int, int]) -> List[Violation]:
    """Per-module G16: raw primitives + guarded writes + blocking
    under engine lock. ``hits`` is the run-wide GUARDED hit counter
    (pass the same dict for every module, then call
    ``g16_stale_entries``)."""
    out = check_g16_raw_primitives(m)
    out += check_g16_guarded_writes(m, hits)
    out += check_g16_blocking_under_lock(m)
    return out


def g16_stale_entries(hits: Dict[int, int]) -> List[Violation]:
    out: List[Violation] = []
    for i, e in enumerate(_reg.GUARDED):
        if not hits.get(i):
            out.append(Violation(
                "G16", e["file"], 0,
                f"stale lock_registry GUARDED entry ({e['cls']}."
                f"{e['field']}): no write to the field found — "
                f"delete or update the entry so the registry stays "
                f"honest", scope="repo"))
    return out


# --------------------------------------------------------------------
# G17 — validated-env enforcement
# --------------------------------------------------------------------

def check_g17(m) -> List[Violation]:
    if m.relpath in G17_SANCTIONED:
        return []
    bare_environ = _imports_name(m, "environ", "os")
    bare_getenv = _imports_name(m, "getenv", "os")
    out: List[Violation] = []
    for node in ast.walk(m.tree):
        hit = None
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "os" and \
                node.attr in ("environ", "getenv"):
            hit = f"os.{node.attr}"
        elif isinstance(node, ast.Name) and (
                (bare_environ and node.id == "environ") or
                (bare_getenv and node.id == "getenv")):
            hit = node.id
        if hit:
            out.append(Violation(
                "G17", m.relpath, node.lineno,
                f"raw `{hit}` read outside pint_tpu/config.py: env "
                f"knobs go through a validated config parser "
                f"(warn-and-ignore on bad values — the "
                f"dispatch_rtt_override_ms pattern); whole-env "
                f"subprocess passthroughs need a G17 pragma",
                m.line_text(node.lineno)))
    return out
