"""Declared precision-boundary sites — the ONLY places f64/dd may
legally demote to f32 in jit-reachable code (graftflow rule G9).

Policy (ARCHITECTURE.md "Static analysis"): TPU f64 is emulated and
not correctly rounded (~2^-48), which is why the dd error-free-
transform chain exists and why the production fit step demotes
precision only at *engineered* boundaries (jac_f32 / matmul_f32 /
anchored — CLAUDE.md "Production fit-step configuration"). Every
entry here is such an engineered boundary: it cites WHY the demotion
is numerically safe (what accuracy the consumer actually needs, and
which CPU equality oracle pins it). A demotion found by graftflow
anywhere else is a G9 violation — the historical failure mode is a
silent f32 creeping into the absolute-phase/dd chain, where it
costs ~100 ns-level residual corruption without failing any test.

Entry fields:
  file      repo-relative path of the boundary site
  func      enclosing function name ("<module>" for module level)
  match     optional substring of the flagged source line (anchors
            the entry when one function hosts several boundaries)
  flag      production-flag expression over {jac32, f32mm, anchored,
            hybrid} telling WHEN the site is active — this is what
            the runtime differential validation checks against the
            actually-traced dtypes (tests/test_dtype_probe.py)
  guard     optional name that must appear in an enclosing `if` test
            or the enclosing function's parameters — the static
            cross-check that the declared flag really gates the site;
            None requires the `why` to say where the gate lives
  max_hits  how many demotion findings the entry may cover
            (default 1); a NEW demotion sharing the function must
            surface for its own review, exactly like the allowlist
  why       mandatory justification

The stale rule from the allowlist applies: an entry that no longer
matches any demotion site fails the lint run, so this registry
cannot rot into a blanket waiver.
"""

DEMOTIONS = [
    # ---------------------------------------- f32 Jacobian input pack
    dict(file="pint_tpu/parallel/fit_step.py", func="conv",
         flag="jac32", guard=None, max_hits=2,
         why="_tree_to32's per-leaf converter IS the declared "
             "f64->dd32 boundary of the f32 Jacobian path: DD pairs "
             "are SPLIT via dd_to_dd32 (48 bits survive), plain f64 "
             "leaves cast to f32. Design columns need only ~1e-6 "
             "relative accuracy (they feed equilibrated normal "
             "equations); tests/test_jac32.py is the CPU equality "
             "oracle. Gate lives at the call sites: _tree_to32 is "
             "invoked only inside parts_fn's `if jac32:` block."),
    dict(file="pint_tpu/parallel/fit_step.py", func="_split32",
         flag="jac32", guard=None,
         why="device-side f64 -> (f32, f32) error-free split of the "
             "step's parameter-pair inputs for the f32 Jacobian "
             "re-trace (splitting, not truncating). Gate lives at "
             "the call sites inside parts_fn's `if jac32:` block."),
    dict(file="pint_tpu/parallel/fit_step.py", func="parts_fn",
         flag="jac32", guard="jac32", max_hits=7,
         why="the f32 Jacobian block of the production step: batch/"
             "cache/scale/f0/valid demote together so the WHOLE "
             "design-matrix re-trace runs dd32/f32 at native VPU "
             "speed while the residual path stays f64/dd. Lexically "
             "inside `if jac32:`; equality oracle test_jac32.py; "
             "the F8+ scale-window fallback clears jac32 when no "
             "safe exponent window exists (see build_fit_step)."),
    # --------------------------------------------- f32 matmul (Gram)
    dict(file="pint_tpu/parallel/fit_step.py", func="_symm_mm",
         flag="f32mm", guard="f32", max_hits=2,
         why="the normal-equation Gram matmul boundary: HIGHEST-"
             "precision f32 passes deliver the ~1e-7 relative "
             "accuracy the equilibrated normal equations need, and "
             "_gls_core retries the whole solve with f64 "
             "accumulation when the f32 Cholesky trips (in-kernel "
             "degeneracy rescue). Guarded by the f32 parameter "
             "(False upcasts to f64 and accumulates exactly)."),
    # ------------------------------------- photon-phase Pallas kernel
    dict(file="pint_tpu/ops/pallas_kernels.py",
         func="z2_harmonics_pallas", flag=None, guard=None,
         max_hits=3,
         why="the Z^2_m harmonic-sum Pallas kernel is f32 BY DESIGN: "
             "photon phases enter in [0, 1) turns (no large "
             "magnitudes to cancel) and the Z^2 statistic needs "
             "~1e-6 relative accuracy; f32 keeps the kernel on the "
             "VPU 8x128 fast path. Never feeds the dd chain — "
             "consumers are event statistics, not timing residuals."),
    dict(file="pint_tpu/ops/pallas_kernels.py",
         func="_harmonics_kernel", flag=None, guard=None, max_hits=2,
         why="f32 literal constants inside the Z^2 Pallas kernel "
             "body (2*pi and the harmonic index) — same "
             "justification as z2_harmonics_pallas: the whole "
             "kernel is a declared f32 surface."),
]


# Runtime probe table: the differential-validation contract between
# graftflow's static predictions and the dtypes actually traced on
# the production build_fit_step configurations. Each probe names a
# function the Sanitizer dtype-probe mode intercepts during ONE
# jax.eval_shape trace of the step; `flag` predicts when the probe
# fires and `dtype` (an expression over the same flags) predicts the
# recorded dtype. tests/test_dtype_probe.py asserts observed ==
# predicted for every production flag combination — the analyzer
# tests the code, the runtime tests the analyzer.
PROBES = [
    dict(label="dd32_split", file="pint_tpu/parallel/fit_step.py",
         callee="dd_to_dd32", flag="jac32", dtype="'float32'",
         why="the f64->dd32 split only runs when the f32 Jacobian "
             "path is on; its output pairs must be f32"),
    dict(label="symm_mm", file="pint_tpu/parallel/fit_step.py",
         callee="_symm_mm", flag="True",
         dtype="'float32' if jac32 else 'float64'",
         why="the Gram contraction always runs; its INPUT dtype "
             "follows the Jacobian dtype (M sets mdt)"),
    dict(label="symm_mm_f32", file="pint_tpu/parallel/fit_step.py",
         callee="_symm_mm", flag="f32mm", dtype="'float32'",
         why="an f32-accumulated Gram pass happens iff matmul_f32 "
             "(the f64 rescue branch also traces, so the probe "
             "looks for ANY f32 pass, not the only pass)"),
    dict(label="phase_frac", file="pint_tpu/parallel/fit_step.py",
         callee="dd_frac", flag="not anchored",
         dtype="'float64'",
         why="the direct chain extracts the fractional phase from "
             "the absolute dd value in f64; anchored mode never "
             "forms the absolute phase in the step at all"),
    dict(label="linear_design_columns",
         file="pint_tpu/models/timing_model.py",
         callee="linear_design_columns", flag="hybrid",
         dtype="'float32' if jac32 else 'float64'",
         why="closed-form design columns are assembled only under "
             "the hybrid Jacobian, in the dtype of the Jacobian "
             "path that consumes them"),
]


def entry_count() -> int:
    return len(DEMOTIONS)


def probe_count() -> int:
    return len(PROBES)
