"""Function-scope control-flow graphs + a forward dataflow engine.

Reference: the classic worklist algorithm (Kildall 1973 / any dragon
book) specialized to Python ASTs — the shared substrate under
``pint_tpu.analysis.graftflow``'s dtype-provenance (G9) and
trace-constant (G10) analyses. graftlint's per-node rules (G1-G8) are
purely syntactic; the two bug classes graftflow exists for — silent
f32 demotion reaching the dd error-free-transform chain, and
parameter values captured as trace constants — are *dataflow*
properties: a value acquires a provenance at one statement and does
damage at another, possibly across branches and loops. Hence: basic
blocks, edges, and a fixpoint solver, instead of more ast.walk.

Scope and honesty:

- **Intraprocedural.** One CFG per ``ast.FunctionDef``. Calls are
  summarized by the client's transfer function (typically: join of
  argument values, plus client-known summaries for names like
  ``dd_to_dd32``). This is the same approximation class as
  graftlint's jit-reachability inference and is documented in
  ARCHITECTURE.md "Static analysis".
- **Structured control flow only.** if/while/for/try/with/return/
  break/continue/raise build real edges; match statements join all
  arms; anything exotic conservatively falls through. ``try`` bodies
  edge into their handlers from the block *entry* (an exception can
  fire mid-block), which over-approximates but never loses a path.
- **Environments are per-name lattice maps.** A name missing from an
  environment is "never bound on this path"; joining keeps the bound
  side (may-analysis: a fact that holds on SOME path must survive —
  exactly what a taint/provenance client wants).

The solver iterates to a fixpoint with a generous iteration bound
(lattices here are tiny and finite; the bound is a belt against a
client writing a non-monotone transfer, in which case we stop and
keep the conservative last state rather than loop forever).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Callable, Dict, List

__all__ = ["Block", "CFG", "build_cfg", "run_dataflow", "join_envs"]


@dataclass
class Block:
    """A straight-line run of statements with edges to successors.

    ``stmts`` holds *simple* statements plus the header statements of
    compound ones (the ``If``/``While``/``For`` node itself is NOT
    re-executed — only its test/iter expressions matter to transfer
    functions, which receive the compound node tagged as a header).
    """

    bid: int
    stmts: List[ast.stmt] = field(default_factory=list)
    succs: List[int] = field(default_factory=list)
    # statements that are compound headers (their bodies live in other
    # blocks); transfer functions should only evaluate their
    # test/iter expression side effects, not their bodies
    headers: List[ast.stmt] = field(default_factory=list)

    def add_succ(self, bid: int):
        if bid not in self.succs:
            self.succs.append(bid)


class CFG:
    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.blocks: List[Block] = []
        self.entry = self.new_block()
        self.exit = self.new_block()

    def new_block(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    def preds(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {b.bid: [] for b in self.blocks}
        for b in self.blocks:
            for s in b.succs:
                out[s].append(b.bid)
        return out


class _Builder:
    """Recursive-descent CFG construction over a statement list."""

    def __init__(self, cfg: CFG):
        self.cfg = cfg
        # (loop_header_bid, loop_exit_bid) stack for break/continue
        self.loops: List[tuple] = []

    def build(self, stmts: List[ast.stmt], cur: Block) -> Block:
        """Append ``stmts`` starting in ``cur``; return the block
        control falls out of (may be a fresh empty block; a block
        with no successors and no fall-through is dead)."""
        for st in stmts:
            cur = self._stmt(st, cur)
        return cur

    def _stmt(self, st: ast.stmt, cur: Block) -> Block:
        c = self.cfg
        if isinstance(st, ast.If):
            cur.stmts.append(st)
            cur.headers.append(st)
            then_b = c.new_block()
            cur.add_succ(then_b.bid)
            then_end = self.build(st.body, then_b)
            join = c.new_block()
            then_end.add_succ(join.bid)
            if st.orelse:
                else_b = c.new_block()
                cur.add_succ(else_b.bid)
                else_end = self.build(st.orelse, else_b)
                else_end.add_succ(join.bid)
            else:
                cur.add_succ(join.bid)
            return join
        if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
            header = c.new_block()
            cur.add_succ(header.bid)
            header.stmts.append(st)
            header.headers.append(st)
            body_b = c.new_block()
            exit_b = c.new_block()
            header.add_succ(body_b.bid)
            header.add_succ(exit_b.bid)  # zero-trip / loop done
            self.loops.append((header.bid, exit_b.bid))
            body_end = self.build(st.body, body_b)
            body_end.add_succ(header.bid)  # back edge
            self.loops.pop()
            if st.orelse:
                # else runs on normal loop exit; approximate by
                # running it on the exit path
                else_end = self.build(st.orelse, exit_b)
                return else_end
            return exit_b
        if isinstance(st, ast.Try):
            cur.stmts.append(st)
            cur.headers.append(st)
            body_b = c.new_block()
            cur.add_succ(body_b.bid)
            join = c.new_block()
            body_end = self.build(st.body, body_b)
            body_end.add_succ(join.bid)
            for h in st.handlers:
                h_b = c.new_block()
                # exceptions can fire anywhere in the body: edge from
                # the body's ENTRY (pre-body env) — conservative
                cur.add_succ(h_b.bid)
                body_end.add_succ(h_b.bid)
                h_end = self.build(h.body, h_b)
                h_end.add_succ(join.bid)
            if st.orelse:
                join = self.build(st.orelse, join)
            if st.finalbody:
                join = self.build(st.finalbody, join)
            return join
        if isinstance(st, (ast.With, ast.AsyncWith)):
            cur.stmts.append(st)
            cur.headers.append(st)
            return self.build(st.body, cur)
        if isinstance(st, ast.Return):
            cur.stmts.append(st)
            cur.add_succ(self.cfg.exit.bid)
            return c.new_block()  # dead continuation
        if isinstance(st, ast.Raise):
            cur.stmts.append(st)
            cur.add_succ(self.cfg.exit.bid)
            return c.new_block()
        if isinstance(st, ast.Break):
            if self.loops:
                cur.add_succ(self.loops[-1][1])
            return c.new_block()
        if isinstance(st, ast.Continue):
            if self.loops:
                cur.add_succ(self.loops[-1][0])
            return c.new_block()
        if isinstance(st, ast.Match):
            cur.stmts.append(st)
            cur.headers.append(st)
            join = c.new_block()
            fell = False
            for case in st.cases:
                case_b = c.new_block()
                cur.add_succ(case_b.bid)
                end = self.build(case.body, case_b)
                end.add_succ(join.bid)
                if case.pattern.__class__.__name__ == "MatchAs" and \
                        getattr(case.pattern, "pattern", None) is None:
                    fell = True  # wildcard case
            if not fell:
                cur.add_succ(join.bid)  # no-match fall-through
            return join
        # simple statement (incl. nested FunctionDef/ClassDef, which
        # clients treat as a binding of the name)
        cur.stmts.append(st)
        return cur


def build_cfg(fn: ast.FunctionDef) -> CFG:
    cfg = CFG(fn)
    end = _Builder(cfg).build(fn.body, cfg.entry)
    end.add_succ(cfg.exit.bid)
    return cfg


def join_envs(a: Dict[str, object], b: Dict[str, object],
              join_val: Callable[[object, object], object]
              ) -> Dict[str, object]:
    """May-join of two environments: union of names; values joined
    where both sides bind, kept where only one does."""
    out = dict(a)
    for k, v in b.items():
        out[k] = join_val(out[k], v) if k in out else v
    return out


def run_dataflow(cfg: CFG, init_env: Dict[str, object],
                 transfer: Callable[[ast.stmt, Dict[str, object],
                                     bool], None],
                 join_val: Callable[[object, object], object],
                 max_iter: int = 64,
                 ) -> Dict[int, Dict[str, object]]:
    """Forward worklist solve. ``transfer(stmt, env, is_header)``
    mutates ``env`` in place; it must be monotone over the client
    lattice. Returns the IN-environment per block id (the exit
    block's IN env is the function's final state). A second,
    post-fixpoint pass is the client's job (re-run transfer with
    recording enabled over each block using these IN envs)."""
    preds = cfg.preds()
    in_envs: Dict[int, Dict[str, object]] = {cfg.entry.bid: dict(init_env)}
    out_envs: Dict[int, Dict[str, object]] = {}
    work = [cfg.entry.bid]
    iters = 0
    while work and iters < max_iter * max(1, len(cfg.blocks)):
        iters += 1
        bid = work.pop(0)
        block = cfg.blocks[bid]
        env = dict(in_envs.get(bid, {}))
        for st in block.stmts:
            transfer(st, env, st in block.headers)
        if out_envs.get(bid) == env and bid in out_envs:
            continue
        out_envs[bid] = env
        for s in block.succs:
            merged = env if s not in in_envs else \
                join_envs(in_envs[s], env, join_val)
            if merged != in_envs.get(s):
                in_envs[s] = merged
                if s not in work:
                    work.append(s)
    # make sure every reachable block has an IN env for replay passes
    for b in cfg.blocks:
        in_envs.setdefault(b.bid, {})
    return in_envs
